"""Certified MIP brackets for the SIPLIB sslp_15_45 instances
(VERDICT r3 next #4: close the certified gaps toward <=0.5% with the
incumbent at the published optimum).

Two models of the SAME integer problem, one per bound side:

  * OUTER bounds run on the VUB-STRENGTHENED model (y_ij <= x_j rows,
    models/sslp.py strengthen=True).  Validity: with the SIPLIB
    penalty (1000/unit) far above any revenue, an optimal solution
    never serves a client from a closed server when any server is open
    (moving the assignment to an open server pays at most the same
    overflow penalty while keeping the revenue), and all-closed first
    stages cost ~penalty * total demand >> optimum — so the VUB cuts
    remove only suboptimal points and the strengthened optimum EQUALS
    the original.  Lower bounds for the strengthened problem are
    therefore valid lower bounds for the original, and its LP
    relaxation is far tighter (-268 vs -280 on sslp_15_45_5).
  * INNER bounds run on the ORIGINAL penalty-form model: its recourse
    is feasible for every first stage (the dummy columns absorb any
    overflow), so the dive/B&B incumbent search never mistakes a good
    candidate for infeasible under a truncated budget.

Pipeline per instance (every bound CERTIFIED):
  1. LP PH on the strengthened model -> multipliers W
  2. certified LP-Lagrangian outer at W (this alone beats round-3's
     integer-Lagrangian bound)
  3. candidate pool (wait-and-see MIP first stages + rounded xbar +
     slam) -> batched evaluate_mip_many on the ORIGINAL model
  4. 1-flip local search over the server-open binaries -> incumbent
  5. Polyak-step dual ascent on the strengthened INTEGER Lagrangian
  6. if still short of target: first-stage decomposition B&B

Writes SSLP_CERT.json.  Usage:
    python sslp_cert.py [--instances 5,10] [--ascent 12] [--quick]
"""
from __future__ import annotations

import argparse
import json
import time

import numpy as np


def certify(n_scens: int, ascent_steps: int, dd_nodes: int,
            target_gap: float = 0.005, verbose: bool = True,
            seed_cands=None) -> dict:
    import jax.numpy as jnp

    from mpisppy_tpu.algos import lagrangian as lag_mod
    from mpisppy_tpu.algos import mip as mip_mod
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.algos import xhat as xhat_mod
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.models import sslp
    from mpisppy_tpu.ops import bnb, pdhg

    t_start = time.time()
    dd_dir = ("/root/reference/examples/sslp/data/"
              f"sslp_15_45_{n_scens}/scenariodata")
    names = sslp.scenario_names_creator(n_scens)
    specs = [sslp.scenario_creator(nm, data_dir=dd_dir, num_scens=n_scens,
                                   strengthen=True) for nm in names]
    batch = batch_mod.from_specs(specs)       # outer plane (tight LP)
    specs_o = [sslp.scenario_creator(nm, data_dir=dd_dir,
                                     num_scens=n_scens) for nm in names]
    batch_inner = batch_mod.from_specs(specs_o)  # inner plane (penalty)

    # -- 2. LP PH for W ----------------------------------------------------
    ph_opts = ph_mod.PHOptions(
        default_rho=50.0, max_iterations=200, conv_thresh=1e-6,
        subproblem_windows=8,
        pdhg=pdhg.PDHGOptions(tol=1e-6, restart_period=40))
    drv = ph_mod.PH(ph_opts, batch)
    _, _, trivial = drv.ph_main()
    W = drv.state.W
    if verbose:
        print(f"[cert{n_scens}] PH conv {float(drv.state.conv):.2e} "
              f"({time.time() - t_start:.0f}s)")

    # -- 3. certified LP-Lagrangian outer ----------------------------------
    lp_lag = lag_mod.lagrangian_bound(
        batch, W, pdhg.PDHGOptions(tol=1e-6, max_iters=100_000))
    outer = float(lp_lag.bound) if bool(lp_lag.certified) else -float("inf")
    if verbose:
        print(f"[cert{n_scens}] LP-lag outer {outer:.4f} "
              f"cert={bool(lp_lag.certified)}")

    # INNER-side evaluations need good integer-feasible incumbents
    # (res.inner is a valid upper bound at any truncation, but weak
    # incumbents inflate it — round 3 reached the published optima at
    # this budget); the OUTER side's bound quality scales with the
    # per-scenario B&B budget on the strengthened model.
    # pump_rounds=0: the feasibility pump's rapid small-dispatch host
    # loop reliably wedges/crashes the axon TPU worker on these
    # instances (observed repeatedly, round 5); the multistart + LNS
    # polish provides the incumbent quality instead
    # swap repair enabled explicitly: this is final-candidate
    # certification (the polish context the default-0 swap_rounds
    # reserves it for)
    eval_opts = bnb.BnBOptions(max_rounds=400, pump_rounds=0,
                               swap_rounds=bnb.POLISH_SWAP_ROUNDS)
    lag_opts = bnb.BnBOptions(max_rounds=240, pump_rounds=0)

    # -- 4. candidate pool + batched MIP evaluation ------------------------
    x_non = batch.nonants(drv.state.solver.x)
    cands = [np.asarray(xhat_mod.round_integers(batch,
                                                drv.state.xbar_nodes[0])),
             np.asarray(xhat_mod.slam_candidate(batch, x_non, True)),
             np.asarray(xhat_mod.slam_candidate(batch, x_non, False))]
    # through the dispatch scheduler (docs/dispatch.md) like every
    # other oracle call in this driver: bucket-padded shapes + the
    # bounded in-flight queue are what un-wedge these runs (round 5)
    from mpisppy_tpu import dispatch as _dispatch
    ws = _dispatch.solve_mip(batch_inner.qp, batch_inner.d_col, np.nonzero(
        np.asarray(batch_inner.integer_full))[0].astype(np.int32),
        eval_opts)
    ws_x = np.asarray(ws.x)[:, np.asarray(batch_inner.nonant_idx)]
    for s in range(batch.num_real):
        if bool(np.asarray(ws.feasible)[s]):
            cands.append(np.round(ws_x[s]))
    if seed_cands is not None:
        # externally supplied candidate first stages (e.g. the
        # LP-ranked leaders of the exhaustive 2^15 enumeration)
        for c in np.asarray(seed_cands, float):
            cands.append(c)
    # dedup on the integer signature
    seen, pool = set(), []
    for c in cands:
        key = tuple(np.round(c).astype(int))
        if key not in seen:
            seen.add(key)
            pool.append(c)
    evs = mip_mod.evaluate_mip_many(batch_inner, pool, eval_opts)
    inner, xhat_best = float("inf"), pool[0]
    for e in evs:
        if e["feasible"] and e["value"] < inner:
            inner, xhat_best = e["value"], e["xhat"]
    if verbose:
        print(f"[cert{n_scens}] pool inner {inner:.4f} "
              f"({time.time() - t_start:.0f}s)")

    # -- 5. local search ---------------------------------------------------
    ls = mip_mod.first_stage_local_search(batch_inner, xhat_best, inner,
                                          eval_opts, max_rounds=4,
                                          verbose=verbose)
    inner, xhat_best = ls["value"], ls["xhat"]
    if verbose:
        print(f"[cert{n_scens}] local-search inner {inner:.4f} "
              f"({time.time() - t_start:.0f}s)")

    # -- 5b. FINAL-candidate polish (round 5): multistart dives + LNS
    # close the per-scenario recourse assignment slack that plain B&B
    # incumbents leave on the pathological scenarios
    pol = mip_mod.evaluate_mip_polished(
        batch_inner, jnp.asarray(xhat_best), eval_opts,
        multistart=24, lns_rounds=40, verbose=verbose)
    if pol["feasible"] and pol["value"] < inner:
        inner = pol["value"]
    if verbose:
        print(f"[cert{n_scens}] polished inner {inner:.4f} "
              f"({time.time() - t_start:.0f}s)")

    def gap_of(i, o):
        return (i - o) / max(1.0, abs(i))

    # -- 6. integer-Lagrangian dual: bundle (round 5) with Polyak
    # fallback — the bundle's cutting-plane master reuses every oracle
    # evaluation instead of forgetting it, where the subgradient ascent
    # stalled ~6 units short (round 4)
    if ascent_steps > 0 and gap_of(inner, outer) > target_gap:
        target = inner - target_gap * max(1.0, abs(inner))
        asc = mip_mod.mip_dual_bundle(
            batch, W, inner, ascent_steps, lag_opts,
            target=target, verbose=verbose)
        if not np.isfinite(asc["bound"]):
            asc = mip_mod.mip_dual_ascent_polyak(
                batch, W, inner, ascent_steps, lag_opts,
                target=target, verbose=verbose)
        outer = max(outer, asc["bound"])
        W_best = asc["W"]
    else:
        W_best = W
    if verbose:
        print(f"[cert{n_scens}] after ascent: outer {outer:.4f} "
              f"gap {gap_of(inner, outer):.4f} "
              f"({time.time() - t_start:.0f}s)")

    # -- 7. decomposition B&B ----------------------------------------------
    if dd_nodes > 0 and gap_of(inner, outer) > target_gap:
        dd = mip_mod.decomposition_bnb(
            batch, W_best, lag_opts, max_nodes=dd_nodes,
            target_gap=target_gap, inner0=inner, xhat0=xhat_best,
            verbose=verbose)
        inner = min(inner, dd["inner"])
        outer = max(outer, dd["outer"])
        if dd["xhat"] is not None and dd["inner"] <= inner:
            xhat_best = dd["xhat"]

    return {
        "inner": float(inner),
        "outer": float(outer),
        "gap": float(gap_of(inner, outer)),
        "seconds": round(time.time() - t_start, 1),
        "trivial": float(trivial),
        "first_stage": np.asarray(xhat_best)[
            :len(np.asarray(batch.nonant_idx))].tolist(),
        # occupancy/recompile evidence for the artifact: how many
        # megabatches the certification actually dispatched, at what
        # occupancy, against how many compiled buckets
        "dispatch": _dispatch.scheduler_stats(),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu) — the env var "
                         "is ignored when the axon TPU plugin is on the "
                         "path, only the config API works")
    ap.add_argument("--instances", default="5,10")
    ap.add_argument("--ascent", type=int, default=12)
    ap.add_argument("--dd-nodes", type=int, default=20)
    ap.add_argument("--target-gap", type=float, default=0.005)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out", default="SSLP_CERT.json")
    ap.add_argument("--seed-cands", default=None,
                    help="npy of (K, 15) candidate first stages to "
                         "seed the incumbent pool")
    args = ap.parse_args()
    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)
    if args.quick:
        args.ascent, args.dd_nodes = 3, 0
    seeds = None if args.seed_cands is None else np.load(args.seed_cands)
    results = {}
    for inst in args.instances.split(","):
        n = int(inst)
        results[f"sslp_15_45_{n}"] = certify(
            n, args.ascent, args.dd_nodes, args.target_gap,
            seed_cands=seeds)
        print(json.dumps({f"sslp_15_45_{n}": results[f"sslp_15_45_{n}"]}))
    with open(args.out, "w") as f:
        json.dump(results, f, indent=1)


if __name__ == "__main__":
    main()
