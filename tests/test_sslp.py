# sslp: native SIPLIB generator — parse the reference .dat data when
# present, synthetic otherwise; EF oracle vs scipy; LP-relaxed PH with
# hub+spokes to a certified gap (the BASELINE.md north-star config
# "sslp LP-relaxed PH" at small scale).
import os

import numpy as np
import pytest

from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.cylinders.hub import PHHub
from mpisppy_tpu.cylinders.spoke import (
    LagrangianOuterBound, XhatXbarInnerBound,
)
from mpisppy_tpu.models import sslp
from mpisppy_tpu.ops import pdhg
from mpisppy_tpu.spin_the_wheel import WheelSpinner

from test_farmer_ef_ph import scipy_ef_solve

REF_DATA = "/root/reference/examples/sslp/data/sslp_5_25_50/scenariodata"


def sslp_specs(num_scens=3, n_servers=5, n_clients=10, seed=0,
               lp_relax=False):
    names = sslp.scenario_names_creator(num_scens)
    inst = sslp.synthetic_instance(n_servers, n_clients, seed)
    return [sslp.scenario_creator(nm, instance=inst, num_scens=num_scens,
                                  lp_relax=lp_relax)
            for nm in names]


def test_shared_A_detected():
    specs = sslp_specs(4)
    b = batch_mod.from_specs(specs)
    # RHS-only randomness -> one (m,n) constraint matrix for the batch
    assert b.qp.A.ndim == 2
    assert b.qp.bl.ndim == 2  # client rows differ per scenario
    n = 5
    assert b.num_nonants == n
    assert bool(b.integer_slot.all())


@pytest.mark.skipif(not os.path.isdir(REF_DATA),
                    reason="reference sslp data not mounted")
def test_parse_reference_dat():
    spec = sslp.scenario_creator("Scenario1", data_dir=REF_DATA)
    # sslp_5_25_50: 5 servers, 25 clients
    assert spec.nonant_idx.shape == (5,)
    assert spec.c.shape == (5 + 125 + 5,)
    assert spec.c[0] == 40.0          # FixedCost server 1
    assert spec.A.shape == (30, 135)
    # capacity row for server 1: -188 on x_1
    assert spec.A[0, 0] == pytest.approx(-188.0)
    # Scenario1 ClientPresent: client 1 present, client 2 absent
    assert spec.bu[5] == 1.0 and spec.bu[6] == 0.0


@pytest.mark.skipif(not os.path.isdir(REF_DATA),
                    reason="reference sslp data not mounted")
def test_reference_data_ef_lp():
    # LP relaxation of the first 3 SIPLIB scenarios: our PDHG EF solve
    # must match scipy/HiGHS on the identical EF.
    names = sslp.scenario_names_creator(3)
    specs = [sslp.scenario_creator(nm, data_dir=REF_DATA, num_scens=3)
             for nm in names]
    sobj, _ = scipy_ef_solve(specs)
    from mpisppy_tpu.algos import ef as ef_mod
    efobj = ef_mod.ExtensiveForm({"tol": 1e-7, "max_iters": 300_000},
                                 names, sslp.scenario_creator,
                                 {"data_dir": REF_DATA, "num_scens": 3})
    st = efobj.solve_extensive_form()
    assert bool(st.done.all())
    assert efobj.get_objective_value() == pytest.approx(
        sobj, rel=2e-3, abs=0.5)


def test_sslp_ph_hub_spoke_gap():
    # Synthetic 6-scenario LP-relaxed sslp through the full cylinder
    # stack: PH hub + Lagrangian outer + XhatXbar inner, terminating on
    # the certified relative gap.
    specs = sslp_specs(6, n_servers=5, n_clients=10, lp_relax=True)
    sobj, _ = scipy_ef_solve(specs)
    b = batch_mod.from_specs(specs)
    opts = ph_mod.PHOptions(
        default_rho=20.0, max_iterations=60, conv_thresh=1e-6,
        subproblem_windows=10,
        pdhg=pdhg.PDHGOptions(tol=1e-7, restart_period=40),
    )
    hub = {"hub_class": PHHub,
           "hub_kwargs": {"options": {"rel_gap": 0.01}},
           "opt_class": ph_mod.PH,
           "opt_kwargs": {"options": opts, "batch": b}}
    spokes = [{"spoke_class": LagrangianOuterBound, "opt_kwargs": {}},
              {"spoke_class": XhatXbarInnerBound, "opt_kwargs": {}}]
    wheel = WheelSpinner(hub, spokes).spin()
    outer, inner = wheel.BestOuterBound, wheel.BestInnerBound
    assert np.isfinite(outer) and np.isfinite(inner)
    assert outer <= sobj + abs(sobj) * 1e-3 + 0.5
    assert inner >= sobj - abs(sobj) * 1e-3 - 0.5
    rel_gap = (inner - outer) / max(1e-10, abs(inner))
    assert rel_gap <= 0.015  # hub terminates at <=1% (+ slack for f32)


def test_sslp_scaling_builds_10k():
    # 10k scenarios build as ONE pytree with a shared constraint matrix
    # (VERDICT item 2 "Done=" criterion); memory stays O(m*n + S*(m+n)).
    num = 10_000
    inst = sslp.synthetic_instance(5, 25, 0)
    names = sslp.scenario_names_creator(num)
    specs = [sslp.scenario_creator(nm, instance=inst, num_scens=num)
             for nm in names]
    b = batch_mod.from_specs(specs)
    assert b.qp.A.ndim == 2          # shared
    assert b.qp.c.shape[0] == num
    assert b.p.shape == (num,)
