# Cross-scenario cuts: augmented batch mechanics, cut validity, and the
# netdes end-to-end gap improvement that motivates the whole subsystem
# (ref:cylinders/cross_scen_spoke.py + extensions/cross_scen_extension.py).
import numpy as np
import pytest

import jax.numpy as jnp

from mpisppy_tpu.algos import cross_scen
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import farmer, netdes
from mpisppy_tpu.ops import pdhg
from mpisppy_tpu.ops.sparse import EllMatrix

from test_farmer_ef_ph import farmer_specs, scipy_ef_solve


def _farmer_batch(num=3):
    return batch_mod.from_specs(farmer_specs(num))


def test_augment_shapes_dense():
    b = _farmer_batch(3)
    S, n, m = b.num_scenarios, b.qp.n, b.qp.m
    eta_lb = np.full(S, -1e6)
    meta = cross_scen.make_meta(b, eta_lb, max_rounds=2)
    # PH view: rows only (no eta columns)
    assert meta.aug_ph.qp.n == n
    assert meta.aug_ph.qp.m == m + 2 * S
    # EF view: eta columns + rows, eta lower bounds installed
    assert meta.aug_ef.qp.n == n + S
    assert meta.aug_ef.qp.m == m + 2 * S
    assert np.allclose(np.asarray(meta.aug_ef.qp.l)[..., n:], -1e6)
    assert np.isinf(np.asarray(meta.aug_ph.qp.bu)[..., m:]).all()
    # PH still solves the row-augmented batch (rows inactive)
    st = pdhg.solve(meta.aug_ph.qp,
                    pdhg.PDHGOptions(tol=1e-6, max_iters=100_000))
    assert bool(st.done.all())


def test_cut_validity_farmer():
    """Optimality cuts must lower-bound the true scenario cost at other
    candidates (weak duality)."""
    b = _farmer_batch(3)
    opts = pdhg.PDHGOptions(tol=1e-7, max_iters=100_000,
                            detect_infeas=True)
    # candidate = scenario 0's wait-and-see solution
    st = pdhg.solve(b.qp, opts)
    x_non = b.nonants(st.x)
    raw = cross_scen.launch_cuts(b, x_non, jnp.mean(x_non, 0,
                                                    keepdims=True), opts)
    pkg = cross_scen.package_cuts(raw, opts)
    assert not pkg["infeas"].any()   # farmer recourse is always feasible
    # evaluate true f_s at a DIFFERENT x: fix nonants at xbar, solve
    xbar = np.asarray(x_non).mean(0)
    from mpisppy_tpu.algos import xhat as xhat_mod
    res = xhat_mod.evaluate(b, jnp.asarray(xbar), opts)
    true_vals = np.asarray(res.per_scenario)
    cut_vals = pkg["opt_alpha"] + pkg["opt_g"] @ xbar
    assert (cut_vals <= true_vals + 1.0).all(), (cut_vals, true_vals)


def test_write_cuts_and_ef_bound_farmer():
    b = _farmer_batch(3)
    opts = pdhg.PDHGOptions(tol=1e-7, max_iters=100_000,
                            detect_infeas=True)
    eta_lb = cross_scen.eta_lower_bounds(b, opts)
    meta = cross_scen.make_meta(b, eta_lb, max_rounds=4)
    st = pdhg.solve(b.qp, opts)
    x_non = b.nonants(st.x)
    # diverse candidates: each round cuts at the scenario farthest from
    # a different reference point (so all three scenario-x's get used)
    for r in range(3):
        raw = cross_scen.launch_cuts(b, x_non, x_non[r:r + 1], opts)
        cross_scen.write_cuts(meta, cross_scen.package_cuts(raw, opts))
    assert meta.rounds_used == 3
    bound, _ = cross_scen.ef_check_bound(meta, opts)
    sobj, _ = scipy_ef_solve(farmer_specs(3))
    assert bound is not None
    assert bound <= sobj + 1.0              # valid outer bound
    assert bound >= sobj - 1.0 * abs(sobj)  # and not vacuous


def test_netdes_wheel_with_cross_scen_cuts():
    """The netdes story: without cuts the xhatxbar candidate is
    infeasible and the gap stays wide; cross-scen cuts push x toward
    cross-scenario feasibility and the EF check provides the 'C'
    bound."""
    from mpisppy_tpu.spin_the_wheel import WheelSpinner
    from mpisppy_tpu.utils import cfg_vanilla as vanilla
    from mpisppy_tpu.utils.config import Config

    inst = netdes.synthetic_instance(n_nodes=6, num_scens=4, seed=1)
    names = netdes.scenario_names_creator(4)
    specs = [netdes.scenario_creator(nm, instance=inst, lp_relax=True)
             for nm in names]
    sobj, _ = scipy_ef_solve(specs)
    b = batch_mod.from_specs(specs)
    assert isinstance(b.qp.A, EllMatrix)

    cfg = Config()
    # 35 iterations (was 60): every assertion below — cuts installed,
    # valid outer, finite inner/gap, active Farkas rows — lands well
    # inside 35 on this deterministic CPU run, and the classic-spoke
    # wheel is the single most expensive tier-1 test (~275 s at 60
    # iters vs ~153 s at 35; the suite must fit the tier-1 budget)
    cfg.quick_assign("max_iterations", int, 35)
    cfg.quick_assign("default_rho", float, 300.0)
    cfg.quick_assign("rel_gap", float, 0.02)
    cfg.quick_assign("pdhg_tol", float, 1e-7)
    cfg.quick_assign("cross_scenario_iter_cnt", int, 3)
    hub = vanilla.ph_hub(cfg, b, scenario_names=names,
                         extensions=vanilla.cross_scenario_extension(cfg))
    spokes = [vanilla.cross_scenario_cuts_spoke(cfg),
              vanilla.xhatxbar_spoke(cfg),
              vanilla.slammax_spoke(cfg)]
    wheel = WheelSpinner(hub, spokes)
    wheel.spin()
    ext = wheel.opt.extobject
    assert ext.cuts_installed > 0
    # outer bound must be valid
    assert wheel.BestOuterBound <= sobj * (1 + 1e-3)
    # with cuts + slam the gap is finite (vs inf without them: the
    # xhatxbar candidate alone is cross-scenario infeasible on netdes)
    assert np.isfinite(wheel.BestInnerBound)
    abs_gap, rel_gap = wheel.spcomm.compute_gaps()
    assert np.isfinite(rel_gap)
    # netdes candidates are cross-scenario INFEASIBLE, so the rounds
    # must have installed active Farkas feasibility rows into the PH
    # view — the mechanism this subsystem exists for
    m_orig = ext.meta.m_orig
    bu_cut = np.asarray(ext.meta.aug_ph.qp.bu)[..., m_orig:]
    assert np.isfinite(bu_cut).any()
    # and the PH batch the driver iterates IS the row-augmented view
    assert wheel.opt.batch.qp.m == ext.meta.aug_ph.qp.m
