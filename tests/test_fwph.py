# FWPH: SDM column generation + true Lagrangian dual bounds.
# Oracle: farmer 3-scenario EF objective -108390 (scipy-verified in
# test_farmer_ef_ph.py).  For an LP the FWPH dual bound must converge to
# the EF objective from below while remaining a valid outer bound.
import jax.numpy as jnp
import numpy as np
import pytest

from mpisppy_tpu.algos import fwph as fwph_mod
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import farmer
from mpisppy_tpu.ops import pdhg, simplex_qp

FARMER_EF_OBJ = -108390.0


@pytest.fixture(scope="module")
def farmer3():
    names = farmer.scenario_names_creator(3)
    specs = [farmer.scenario_creator(nm, num_scens=3) for nm in names]
    return batch_mod.from_specs(specs)


def test_project_simplex_basic():
    v = jnp.asarray([[0.3, 0.9, -0.1, 5.0]])
    valid = jnp.asarray([[True, True, True, False]])
    lam = simplex_qp.project_simplex(v, valid)
    assert np.isclose(float(jnp.sum(lam)), 1.0, atol=1e-6)
    assert float(lam[0, 3]) == 0.0  # invalid column excluded
    assert np.all(np.asarray(lam) >= 0)
    # already-feasible point projects to itself
    v2 = jnp.asarray([[0.25, 0.75, 0.0, 0.0]])
    lam2 = simplex_qp.project_simplex(v2, jnp.asarray([[True] * 4]))
    assert np.allclose(np.asarray(lam2), np.asarray(v2), atol=1e-6)


def test_simplex_qp_known_answer():
    """min 1/2||lam - t||^2 over the simplex == projection of t."""
    K = 5
    H = jnp.eye(K)[None]
    t = jnp.asarray([[0.4, 0.4, 0.1, 0.05, 0.05]])
    g = -t
    valid = jnp.ones((1, K), bool)
    lam = simplex_qp.solve_simplex_qp(H, g, valid, iters=300)
    assert np.allclose(np.asarray(lam), np.asarray(t), atol=1e-4)
    # masked variant: restrict to first 2 columns
    valid2 = jnp.asarray([[True, True, False, False, False]])
    lam2 = simplex_qp.solve_simplex_qp(H, g, valid2, iters=300)
    assert np.allclose(np.asarray(lam2[0, 2:]), 0.0)
    assert np.allclose(np.asarray(lam2[0, :2]), 0.5, atol=1e-4)


def test_fwph_bound_converges_to_ef(farmer3):
    """FWPH dual bounds: valid (<= EF obj) and converging to it."""
    opts = fwph_mod.FWPHOptions(
        fw_iter_limit=2, max_columns=16, max_iterations=40,
        conv_thresh=1e-3, oracle_windows=12,
        pdhg=pdhg.PDHGOptions(tol=1e-7))
    algo = fwph_mod.FWPH(opts, farmer3)
    itr, weights, xbars = algo.fwph_main()

    # every certified bound is a valid outer bound
    assert algo.best_bound <= FARMER_EF_OBJ + 5.0
    # and FWPH converges the bound toward the EF objective (LP: no
    # gap).  Tolerance 5e-3, not the asymptotic 0: at this 40-iteration
    # budget the bound error is dominated by the W trajectory, not
    # oracle exactness — measured sweeps (fw_iter_limit 2->4,
    # oracle_windows 12->24) move the error NON-monotonically between
    # 2.1e-3 and 2.8e-2, so tightening the inner loop does not buy a
    # tighter assertion.  VALIDITY (bound <= EF, certified duals) is
    # the hard guarantee and is asserted above; proximity is the
    # heuristic part.
    assert algo.best_bound == pytest.approx(FARMER_EF_OBJ, rel=5e-3)
    # trivial bound (wait-and-see) is looser than the converged bound
    assert algo.trivial_bound <= algo.best_bound + 1.0

    # the QP iterate is a convex combination: weights on the simplex
    for lam in weights.values():
        assert np.isclose(lam.sum(), 1.0, atol=1e-4)
        assert (lam >= -1e-6).all()

    # primal consensus: xbar from the QP iterates near the EF solution
    assert np.isfinite(xbars).all()


def test_fwph_spoke_in_wheel(farmer3):
    """FWPH as an outer-bound spoke under the PH hub tightens the gap."""
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.cylinders.hub import PHHub
    from mpisppy_tpu.cylinders.spoke import FWPHOuterBound
    from mpisppy_tpu.spin_the_wheel import WheelSpinner

    ph_opts = ph_mod.PHOptions(default_rho=1.0, max_iterations=30,
                               conv_thresh=1e-4, subproblem_windows=10,
                               pdhg=pdhg.PDHGOptions(tol=1e-7))
    fw_opts = fwph_mod.FWPHOptions(
        fw_iter_limit=2, max_columns=16, oracle_windows=12,
        pdhg=pdhg.PDHGOptions(tol=1e-7))
    hub_dict = {
        "hub_class": PHHub,
        "hub_kwargs": {"options": {"rel_gap": 0.005}},
        "opt_class": ph_mod.PH,
        "opt_kwargs": {"options": ph_opts, "batch": farmer3},
    }
    spoke = {"spoke_class": FWPHOuterBound,
             "opt_kwargs": {"options": {"fw_opts": fw_opts}}}
    wheel = WheelSpinner(hub_dict, [spoke])
    wheel.spin()
    assert wheel.BestOuterBound is not None
    assert wheel.BestOuterBound <= FARMER_EF_OBJ + 5.0
    assert wheel.BestOuterBound >= FARMER_EF_OBJ - 0.05 * abs(FARMER_EF_OBJ)
