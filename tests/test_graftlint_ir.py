# graftlint IR layer (ISSUE 15; tools/graftlint/ir/,
# docs/static_analysis.md "IR layer"): seeded leaky fixture kernels one
# per IR pass, the clean-repo fast-subset CLI run (empty baseline,
# budget-asserted), the KERNEL_IR.json regen-vs-committed gate + the
# synthetic-regression exit-2 proof, the lowering-cache round trip, and
# compile-count regression tests proving the audited kernels really do
# run 0 recompiles across same-shape different-value inputs.
from __future__ import annotations

import copy
import json
import os
import subprocess
import sys
import time

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

from tools.graftlint.ir import manifest as ir_manifest  # noqa: E402
from tools.graftlint.ir import passes as ir_passes  # noqa: E402

IR_RULE_NAMES = ("ir-const-capture,ir-dtype-census,ir-host-boundary,"
                 "ir-collective-manifest,ir-memory-high-water")


def _sub_env(cache_dir=None):
    env = {"PATH": os.environ.get("PATH", "/usr/bin:/bin"),
           "HOME": os.path.expanduser("~"),
           "JAX_PLATFORMS": "cpu"}
    if cache_dir is not None:
        env["GRAFTLINT_IR_CACHE"] = str(cache_dir)
    return env


@pytest.fixture(scope="module")
def ir_cache(tmp_path_factory):
    """One lowering cache shared by this module's subprocess runs —
    the second drive costs traces, not compiles (the jaxpr-hash cache
    CI and local runs share via --ir-cache / GRAFTLINT_IR_CACHE)."""
    return tmp_path_factory.mktemp("ir_cache")


# ---------------------------------------------------------------------------
# tier-1 wiring: the repo lints CLEAN on the fast manifest subset,
# with an EMPTY baseline, inside the time budget
# ---------------------------------------------------------------------------
def test_ir_fast_subset_repo_lints_clean_within_budget(ir_cache):
    t0 = time.monotonic()
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--json",
         "--rules", IR_RULE_NAMES, "--ir-subset", "fast"],
        capture_output=True, text=True, cwd=REPO,
        env=_sub_env(ir_cache), timeout=300)
    elapsed = time.monotonic() - t0
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    rep = json.loads(out.stdout)
    assert rep["ok"] and rep["errors"] == []
    active = [f for f in rep["findings"] if not f["baselined"]]
    assert active == [], active
    # EMPTY baseline: nothing grandfathered on any IR rule
    assert rep["baselined"] == 0
    # the tier-1 budget the ISSUE sets — cached lowerings hold it
    assert elapsed < 60.0, f"fast IR subset took {elapsed:.1f}s"


def test_kernel_ir_fast_regen_matches_committed(ir_cache):
    """Regenerate the fast-subset facts and gate them against the
    committed KERNEL_IR.json — const bytes may never grow, temp bytes
    ratchet at +10% (telemetry/regress.py GATES)."""
    from mpisppy_tpu.telemetry import regress
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftlint.ir", "--subset", "fast"],
        capture_output=True, text=True, cwd=REPO,
        env=_sub_env(ir_cache), timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    fresh = json.loads(out.stdout)
    committed = regress.load_artifact(os.path.join(REPO, "KERNEL_IR.json"))
    rep = regress.gate(committed, fresh)
    assert rep["common"] > 0
    assert rep["ok"], regress.render_compare(rep, only_gated=True)


@pytest.mark.slow
def test_kernel_ir_full_sweep_matches_committed(tmp_path):
    """The full manifest sweep (every kernel, sharded collective facts)
    gates against the committed artifact and covers every kernel the
    artifact carries."""
    from mpisppy_tpu.telemetry import regress
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftlint.ir", "--subset", "full"],
        capture_output=True, text=True, cwd=REPO,
        env=_sub_env(tmp_path), timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    fresh = json.loads(out.stdout)
    committed = regress.load_artifact(os.path.join(REPO, "KERNEL_IR.json"))
    assert set(fresh["kernels"]) == set(committed["kernels"])
    rep = regress.gate(committed, fresh)
    assert rep["ok"], regress.render_compare(rep, only_gated=True)


# ---------------------------------------------------------------------------
# regress wiring: synthetic regression exits 2; committed artifact
# witnesses the gate keys (the schema-drift coupling)
# ---------------------------------------------------------------------------
def test_kernel_ir_synthetic_regression_exits_2(tmp_path):
    with open(os.path.join(REPO, "KERNEL_IR.json")) as f:
        good = json.load(f)
    bad = copy.deepcopy(good)
    some = sorted(bad["kernels"])[0]
    bad["kernels"][some]["const_bytes"] += 4096      # any increase fails
    other = sorted(bad["kernels"])[-1]
    bad["kernels"][other]["temp_bytes"] = int(
        bad["kernels"][other]["temp_bytes"] * 1.2 + 64)  # past +10%
    bad_path = tmp_path / "KERNEL_IR_bad.json"
    bad_path.write_text(json.dumps(bad))
    out = subprocess.run(
        [sys.executable, "-m", "mpisppy_tpu.telemetry", "gate",
         "KERNEL_IR.json", str(bad_path), "--json"],
        capture_output=True, text=True, cwd=REPO, env=_sub_env(),
        timeout=120)
    assert out.returncode == 2, out.stdout[-1500:] + out.stderr[-500:]
    rep = json.loads(out.stdout)
    failed = {r["metric"] for r in rep["regressions"]}
    assert f"kernels.{some}.const_bytes" in failed
    assert f"kernels.{other}.temp_bytes" in failed


def test_committed_artifact_witnesses_gate_keys():
    """Schema-drift check 4 coupling: the kernels.*.const_bytes /
    temp_bytes GATES patterns must resolve against the committed
    KERNEL_IR.json — a gate nothing produces gates nothing."""
    import re
    from mpisppy_tpu.telemetry import regress
    keys = set(regress.extract_metrics(
        regress.load_artifact(os.path.join(REPO, "KERNEL_IR.json"))))
    for pat in (r"kernels\..*\.const_bytes$", r"kernels\..*\.temp_bytes$"):
        assert any(re.search(pat, k) for k in keys), pat
    # and the artifact covers the full manifest
    with open(os.path.join(REPO, "KERNEL_IR.json")) as f:
        art = json.load(f)
    assert set(art["kernels"]) == set(ir_manifest.names("full"))


# ---------------------------------------------------------------------------
# seeded leaky fixture kernels — one per IR pass, each asserted caught
# ---------------------------------------------------------------------------
def _fixture_audit(spec, **kw):
    from tools.graftlint.ir import audit
    return audit.audit_kernel(spec, ir_manifest.Fixtures(), REPO, **kw)


def test_const_capture_catches_closed_over_ndarray():
    import jax
    import jax.numpy as jnp
    import numpy as np
    baked = jnp.asarray(np.arange(1024, dtype=np.float32))  # 4 KiB

    def build(fx):
        return jax.jit(lambda x: x + baked), (jnp.zeros(1024),)

    spec = ir_manifest.KernelSpec("fixture_const", build)
    facts = _fixture_audit(spec)
    found = ir_passes.const_capture_findings(spec, facts)
    assert len(found) == 1 and "4096 bytes" in found[0].message
    assert found[0].key == "ir::fixture_const::const::float32[1024]#0"
    assert facts.const_bytes >= 4096


def test_const_capture_threshold_exempts_small_helpers():
    import jax
    import jax.numpy as jnp
    small = jnp.arange(8, dtype=jnp.float32)     # 32 bytes: idiomatic

    def build(fx):
        return jax.jit(lambda x: x + small), (jnp.zeros(8),)

    spec = ir_manifest.KernelSpec("fixture_small_const", build)
    facts = _fixture_audit(spec)
    assert ir_passes.const_capture_findings(spec, facts) == []
    assert facts.const_bytes == 32               # still in the ratchet


def test_dtype_census_catches_f64_promotion():
    import jax
    import jax.numpy as jnp

    def build(fx):
        return jax.jit(
            lambda x: (x.astype(jnp.float64) * 2.0).sum()), \
            (jnp.zeros(16, jnp.float32),)

    spec = ir_manifest.KernelSpec("fixture_f64", build)
    with jax.experimental.enable_x64():
        facts = _fixture_audit(spec)
    found = ir_passes.dtype_census_findings(spec, facts)
    assert len(found) == 1 and "float64" in found[0].message
    assert found[0].key == "ir::fixture_f64::f64"


def test_host_boundary_catches_io_callback():
    import jax
    import jax.numpy as jnp
    from jax.experimental import io_callback

    def kernel(x):
        io_callback(lambda v: None, None, x)
        return x * 2.0

    def build(fx):
        return jax.jit(kernel), (jnp.zeros(8),)

    spec = ir_manifest.KernelSpec("fixture_cb", build)
    facts = _fixture_audit(spec)
    found = ir_passes.host_boundary_findings(spec, facts)
    assert [f.key for f in found] == ["ir::fixture_cb::callback::io_callback"]


def test_memory_high_water_catches_s_major_temp():
    import jax
    import jax.numpy as jnp

    def kernel(key):
        big = jax.random.normal(key, (256, 256))     # 256 KiB S-major
        return (big @ big.T).sum()

    def build(fx):
        return jax.jit(kernel), (jax.random.PRNGKey(0),)

    spec = ir_manifest.KernelSpec("fixture_smear", build, virtual=True,
                                  temp_budget_bytes=4096)
    facts = _fixture_audit(spec)
    found = ir_passes.memory_high_water_findings(spec, facts)
    assert len(found) == 1 and "transients budget" in found[0].message
    # same kernel under an honest budget: clean
    ok_spec = ir_manifest.KernelSpec(
        "fixture_smear_ok", build, virtual=True,
        temp_budget_bytes=facts.temp_bytes)
    assert ir_passes.memory_high_water_findings(ok_spec, facts) == []


_COLLECTIVE_FIXTURE = r"""
import json, sys
sys.path.insert(0, {repo!r})
from tools.graftlint.ir import audit, manifest, passes
audit.ensure_devices(2)
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from mpisppy_tpu.parallel import mesh as mesh_mod


def _sharded(fx, x):
    if fx.mesh is not None:
        return jax.device_put(
            x, NamedSharding(fx.mesh, P(mesh_mod.SCEN_AXIS)))
    return x


def build_silent(fx):
    return jax.jit(lambda v: v + 1.0), (_sharded(fx, jnp.arange(
        8, dtype=jnp.float32)),)


def build_chatty(fx):
    return jax.jit(lambda v: v - v.mean()), (_sharded(fx, jnp.arange(
        8, dtype=jnp.float32)),)


silent = manifest.KernelSpec(
    "fixture_silent", build_silent, sharded=True,
    collectives=frozenset({{"all-reduce"}}))        # declared, absent
chatty = manifest.KernelSpec(
    "fixture_chatty", build_chatty, sharded=True,
    collectives=frozenset())                        # present, undeclared
fx = manifest.Fixtures()
sfx = manifest.Fixtures(mesh=mesh_mod.make_mesh(2))
keys = []
for spec in (silent, chatty):
    facts = audit.audit_kernel(spec, fx, {repo!r}, sharded_fx=sfx)
    keys += [f.key for f in passes.collective_manifest_findings(
        spec, facts)]
print(json.dumps(keys))
"""


def test_collective_manifest_catches_both_directions(tmp_path):
    """Declared-but-missing AND present-but-undeclared collectives are
    findings.  Runs in a subprocess: collective facts need >= 2 virtual
    devices forced before jax initializes."""
    out = subprocess.run(
        [sys.executable, "-c", _COLLECTIVE_FIXTURE.format(repo=REPO)],
        capture_output=True, text=True, cwd=REPO,
        env=_sub_env(tmp_path), timeout=180)
    assert out.returncode == 0, out.stderr[-2000:]
    keys = json.loads(out.stdout.strip().splitlines()[-1])
    assert "ir::fixture_silent::collective-missing::all-reduce" in keys
    assert "ir::fixture_chatty::collective-extra::all-reduce" in keys


# ---------------------------------------------------------------------------
# rule plumbing: scoped scans skip the audit; a broken audit is a
# finding on whichever selected IR rule runs first, never a clean exit
# ---------------------------------------------------------------------------
def test_ir_rules_skip_path_scoped_scans():
    from tools.graftlint.core import Context
    ctx = Context(REPO, paths=["mpisppy_tpu/telemetry"])
    assert ctx.scoped
    assert ir_passes._audit_for(ctx) is None
    for rule in ir_passes.IR_RULES:
        assert rule.run(ctx) == []


def test_ir_audit_failure_reported_on_first_selected_rule(monkeypatch):
    """A crashed audit must never read as a clean repo — even when the
    rule subset excludes ir-const-capture; and it reports exactly
    once."""
    from tools import graftlint
    from tools.graftlint.ir import audit as ir_audit_mod

    def boom(*a, **k):
        raise RuntimeError("synthetic audit failure")
    monkeypatch.setattr(ir_audit_mod, "run_manifest", boom)
    rep = graftlint.lint(
        REPO, rules=["ir-dtype-census", "ir-memory-high-water"])
    assert not rep["ok"]
    failed = [f for f in rep["findings"] if f["key"] == "ir-audit-failed"]
    assert len(failed) == 1
    assert failed[0]["rule"] == "ir-dtype-census"
    assert "synthetic audit failure" in failed[0]["message"]


# ---------------------------------------------------------------------------
# the jaxpr-hash lowering cache
# ---------------------------------------------------------------------------
def test_lowering_cache_round_trip(tmp_path):
    import jax
    import jax.numpy as jnp

    def build(fx):
        return jax.jit(lambda x: (x * 2.0).sum()), (jnp.zeros(32),)

    spec = ir_manifest.KernelSpec("fixture_cached", build)
    first = _fixture_audit(spec, cdir=str(tmp_path))
    assert not first.cached
    second = _fixture_audit(spec, cdir=str(tmp_path))
    assert second.cached
    assert (second.temp_bytes, second.arg_bytes, second.flops) == \
        (first.temp_bytes, first.arg_bytes, first.flops)


# ---------------------------------------------------------------------------
# CLI satellite: bare --rules lists IR rules with kernel counts
# ---------------------------------------------------------------------------
def test_cli_rules_listing_shows_ir_kernel_counts():
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--rules"],
        capture_output=True, text=True, cwd=REPO, env=_sub_env(),
        timeout=60)
    assert out.returncode == 0, out.stderr
    text = out.stdout
    counts = ir_passes.kernel_counts()
    for rule, n in counts.items():
        line = next(ln for ln in text.splitlines() if ln.startswith(rule))
        assert f"[{n} kernels]" in line, line
    # AST rules list too, without counts
    assert any(ln.startswith("trace-purity") for ln in text.splitlines())


# ---------------------------------------------------------------------------
# compile-count regression tests: the audited (const-free) kernels run
# 0 recompiles across same-shape different-VALUE inputs — the dynamic
# counterpart of the ir-const-capture pass (and the missing coverage
# for the PR-4 leaks: estimate_norm and the bnb round kernels)
# ---------------------------------------------------------------------------
def _jitter(tree):
    """Same shapes/dtypes, fresh float values."""
    import jax
    import jax.numpy as jnp

    def bump(a):
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating):
            # dtype-typed scalars: a bare python float would promote a
            # numpy f32 leaf to f64 and change the aval (a recompile
            # for the WRONG reason — shapes, not values)
            one = a.dtype.type(1.001)
            eps = a.dtype.type(0.0009)
            return a * one + eps
        return a
    return jax.tree_util.tree_map(bump, tree)


def test_ph_iterk_zero_recompiles_across_values():
    import jax.numpy as jnp
    import __graft_entry__ as ge
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.dispatch import compilewatch
    batch = ge._flagship_batch(num_scens=6, crops_multiplier=1)
    opts = ph_mod.PHOptions(subproblem_windows=2, iter0_windows=4)
    rho = jnp.ones(batch.num_nonants, batch.qp.c.dtype)
    st, _, _ = ph_mod.ph_iter0(batch, rho, opts)
    jbatch = _jitter(batch)   # built BEFORE the watch: the eager bump
    #                           ops compile their own tiny executables
    st = ph_mod.ph_iterk(batch, st, opts)        # warm the shape key
    watch = compilewatch.CompileWatch()
    warm = watch.total()
    ph_mod.ph_iterk(jbatch, st, opts).conv.block_until_ready()
    assert watch.total() == warm, \
        "ph_iterk recompiled for same-shape different-value batch"


def test_xhat_evaluate_zero_recompiles_across_values():
    import __graft_entry__ as ge
    from mpisppy_tpu.algos import xhat as xhat_mod
    from mpisppy_tpu.dispatch import compilewatch
    from mpisppy_tpu.ops import pdhg
    batch = ge._flagship_batch(num_scens=6, crops_multiplier=1)
    opts = pdhg.PDHGOptions(tol=1e-4, max_iters=40, restart_period=10)
    lb, ub = batch.nonant_box()
    import jax.numpy as jnp
    xhat = jnp.asarray((lb + ub) / 2.0, jnp.float32)
    jbatch = _jitter(batch)
    xhat_mod._evaluate_core(batch, xhat, opts, 1e-3)      # warm
    watch = compilewatch.CompileWatch()
    warm = watch.total()
    res = xhat_mod._evaluate_core(jbatch, xhat, opts, 1e-3)
    res.value.block_until_ready()
    assert watch.total() == warm, \
        "_evaluate_core recompiled for same-shape different-value batch"


def test_estimate_norm_zero_recompiles_across_values():
    """The original PR-4 leak site: eager power iteration baked QP
    values into its fori_loop jaxpr — one backend compile per distinct
    QP.  Now jitted; prove the fix holds dynamically."""
    import __graft_entry__ as ge
    from mpisppy_tpu.dispatch import compilewatch
    from mpisppy_tpu.ops import pdhg
    qp = ge._sslp_batch(num_scens=4).qp
    jqp = _jitter(qp)
    pdhg.estimate_norm(qp).block_until_ready()            # warm
    watch = compilewatch.CompileWatch()
    warm = watch.total()
    pdhg.estimate_norm(jqp).block_until_ready()
    assert watch.total() == warm, \
        "estimate_norm recompiled for same-shape different-value QP"


def test_bnb_round_zero_recompiles_across_values():
    import __graft_entry__ as ge
    from mpisppy_tpu.dispatch import compilewatch
    from mpisppy_tpu.ops import bnb as bnb_mod
    from mpisppy_tpu.ops import pdhg
    sbatch = ge._sslp_batch(num_scens=4)
    bnb_opts = bnb_mod.BnBOptions(
        max_rounds=1, pump_rounds=0,
        lp=pdhg.PDHGOptions(tol=1e-3, max_iters=200))
    int_cols, bst = ge._bnb_probe_state(sbatch, bnb_opts)
    jqp = _jitter(sbatch.qp)
    out = bnb_mod.bnb_round(sbatch.qp, sbatch.d_col, int_cols, bst,
                            bnb_opts)                     # warm
    watch = compilewatch.CompileWatch()
    warm = watch.total()
    out = bnb_mod.bnb_round(jqp, sbatch.d_col, int_cols,
                            bst, bnb_opts)
    out.outer.block_until_ready()
    assert watch.total() == warm, \
        "bnb_round recompiled for same-shape different-value QP"
