# Model-zoo tail (round 4): apl1p / gbd / stoch_distr — scipy EF
# oracles + PH/ADMM end-to-end (the TPU analogs of
# ref:mpisppy/tests/examples/{apl1p,gbd}.py and
# ref:examples/stoch_distr/).
import numpy as np
import pytest

from mpisppy_tpu.algos import ef as ef_mod
from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import apl1p, distr, gbd, stoch_distr
from mpisppy_tpu.ops import pdhg
from mpisppy_tpu.utils.stoch_admmWrapper import Stoch_AdmmWrapper

from test_farmer_ef_ph import scipy_ef_solve


def _ph(b, rho=1.0, iters=150, conv=1e-3, windows=8, tol=1e-7):
    opts = ph_mod.PHOptions(
        default_rho=rho, max_iterations=iters, conv_thresh=conv,
        subproblem_windows=windows,
        pdhg=pdhg.PDHGOptions(tol=tol, restart_period=40))
    algo = ph_mod.PH(opts, b)
    return algo, algo.ph_main()


# ---------------- apl1p ----------------

def _apl1p_specs(num=6):
    return [apl1p.scenario_creator(nm, num_scens=num)
            for nm in apl1p.scenario_names_creator(num)]


def test_apl1p_sampling_matches_reference_stream():
    # the reference draws rand(6) from RandomState(scennum): indices
    # 1-2 pick availability, 3-5 demand — spot-check determinism + range
    a0, d0 = apl1p.sample(0)
    a0b, d0b = apl1p.sample(0)
    assert np.array_equal(a0, a0b) and np.array_equal(d0, d0b)
    assert all(v in (1.0, 0.9, 0.5, 0.1) for v in [a0[0]])
    assert all(v in (1.0, 0.9, 0.7, 0.1, 0.0) for v in [a0[1]])
    assert all(v in (900.0, 1000.0, 1100.0, 1200.0) for v in d0)
    # different scenarios differ somewhere
    draws = [apl1p.sample(i) for i in range(8)]
    assert len({tuple(np.concatenate(dr)) for dr in draws}) > 1


def test_apl1p_ef_matches_scipy():
    specs = _apl1p_specs(6)
    sobj, sx = scipy_ef_solve(specs)
    ef = ef_mod.ExtensiveForm(
        {"tol": 1e-7, "max_iters": 300_000},
        apl1p.scenario_names_creator(6), apl1p.scenario_creator,
        {"num_scens": 6})
    st = ef.solve_extensive_form()
    assert bool(st.done.all())
    assert ef.get_objective_value() == pytest.approx(sobj, rel=2e-3)


def test_apl1p_ph_brackets_ef():
    specs = _apl1p_specs(6)
    sobj, _ = scipy_ef_solve(specs)
    b = batch_mod.from_specs(specs)
    algo, (conv, eobj, tb) = _ph(b, rho=2.0, iters=200, conv=1e-2)
    assert tb <= sobj + abs(sobj) * 1e-3   # wait-and-see lower bound
    assert conv <= 1e-2


# ---------------- gbd ----------------

def _gbd_specs(num=5):
    return [gbd.scenario_creator(nm, num_scens=num)
            for nm in gbd.scenario_names_creator(num)]


def test_gbd_demand_distributions():
    dmds, prbs = gbd._distributions(None)
    for d, p in zip(dmds, prbs):
        assert len(d) == len(p)
        assert np.isclose(np.sum(p), 1.0, atol=1e-6)
    d0 = gbd.sample(0)
    assert all(any(np.isclose(v, dm).any() for dm in [dmds[i]])
               for i, v in enumerate(d0))


def test_gbd_ef_matches_scipy():
    specs = _gbd_specs(5)
    sobj, _ = scipy_ef_solve(specs)
    ef = ef_mod.ExtensiveForm(
        {"tol": 1e-7, "max_iters": 300_000},
        gbd.scenario_names_creator(5), gbd.scenario_creator,
        {"num_scens": 5})
    st = ef.solve_extensive_form()
    assert bool(st.done.all())
    assert ef.get_objective_value() == pytest.approx(sobj, rel=2e-3)


def test_gbd_ph_converges():
    specs = _gbd_specs(5)
    sobj, _ = scipy_ef_solve(specs)
    b = batch_mod.from_specs(specs)
    algo, (conv, eobj, tb) = _ph(b, rho=5.0, iters=250, conv=1e-2)
    assert tb <= sobj + abs(sobj) * 1e-3
    assert conv <= 1e-2
    # first stage is a genuine allocation: inventory rows hold at xbar
    x1 = algo.first_stage_solution()
    x = x1.reshape(4, 5)
    slackless_use = x.sum(axis=1)
    assert np.all(slackless_use <= np.array([10, 19, 25, 15]) + 1e-2)


# ---------------- stoch_distr ----------------

def test_stoch_distr_admm_matches_global_lp():
    R, S = 3, 3
    data = distr.region_data(R, seed=2)
    stoch_names = stoch_distr.stoch_scenario_names_creator(S)
    cons = stoch_distr.consensus_vars_creator(R, data)
    wrapper = Stoch_AdmmWrapper(
        {}, stoch_distr.admm_subproblem_names_creator(R), stoch_names,
        lambda snm, rnm, **kw: stoch_distr.scenario_creator(
            snm, rnm, data=data), cons)
    b = wrapper.make_batch()
    assert b.tree.num_stages == 3
    assert b.num_scenarios == R * S
    algo, (conv, eobj, tb) = _ph(b, rho=2.0, iters=400, conv=2e-4,
                                 windows=10)
    ref = stoch_distr.global_lp_oracle(data, stoch_names)
    assert conv <= 2e-4
    # consensus expectation within 1% of the merged two-stage LP
    assert eobj == pytest.approx(ref, rel=1e-2)
    # z is a ROOT (stage-1) quantity: one value across all nodes
    xb = np.asarray(algo.state.xbar_nodes)
    assert xb.shape[1] == b.num_nonants
