# Model-zoo tail (round 4): apl1p / gbd / stoch_distr — scipy EF
# oracles + PH/ADMM end-to-end (the TPU analogs of
# ref:mpisppy/tests/examples/{apl1p,gbd}.py and
# ref:examples/stoch_distr/).
import numpy as np
import pytest

from mpisppy_tpu.algos import ef as ef_mod
from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import apl1p, distr, gbd, stoch_distr
from mpisppy_tpu.ops import pdhg
from mpisppy_tpu.utils.stoch_admmWrapper import Stoch_AdmmWrapper

from test_farmer_ef_ph import scipy_ef_solve


def _ph(b, rho=1.0, iters=150, conv=1e-3, windows=8, tol=1e-7):
    opts = ph_mod.PHOptions(
        default_rho=rho, max_iterations=iters, conv_thresh=conv,
        subproblem_windows=windows,
        pdhg=pdhg.PDHGOptions(tol=tol, restart_period=40))
    algo = ph_mod.PH(opts, b)
    return algo, algo.ph_main()


# ---------------- apl1p ----------------

def _apl1p_specs(num=6):
    return [apl1p.scenario_creator(nm, num_scens=num)
            for nm in apl1p.scenario_names_creator(num)]


def test_apl1p_sampling_matches_reference_stream():
    # the reference draws rand(6) from RandomState(scennum): indices
    # 1-2 pick availability, 3-5 demand — spot-check determinism + range
    a0, d0 = apl1p.sample(0)
    a0b, d0b = apl1p.sample(0)
    assert np.array_equal(a0, a0b) and np.array_equal(d0, d0b)
    assert all(v in (1.0, 0.9, 0.5, 0.1) for v in [a0[0]])
    assert all(v in (1.0, 0.9, 0.7, 0.1, 0.0) for v in [a0[1]])
    assert all(v in (900.0, 1000.0, 1100.0, 1200.0) for v in d0)
    # different scenarios differ somewhere
    draws = [apl1p.sample(i) for i in range(8)]
    assert len({tuple(np.concatenate(dr)) for dr in draws}) > 1


def test_apl1p_ef_matches_scipy():
    specs = _apl1p_specs(6)
    sobj, sx = scipy_ef_solve(specs)
    ef = ef_mod.ExtensiveForm(
        {"tol": 1e-7, "max_iters": 300_000},
        apl1p.scenario_names_creator(6), apl1p.scenario_creator,
        {"num_scens": 6})
    st = ef.solve_extensive_form()
    assert bool(st.done.all())
    assert ef.get_objective_value() == pytest.approx(sobj, rel=2e-3)


def test_apl1p_ph_brackets_ef():
    specs = _apl1p_specs(6)
    sobj, _ = scipy_ef_solve(specs)
    b = batch_mod.from_specs(specs)
    algo, (conv, eobj, tb) = _ph(b, rho=2.0, iters=200, conv=1e-2)
    assert tb <= sobj + abs(sobj) * 1e-3   # wait-and-see lower bound
    assert conv <= 1e-2


# ---------------- gbd ----------------

def _gbd_specs(num=5):
    return [gbd.scenario_creator(nm, num_scens=num)
            for nm in gbd.scenario_names_creator(num)]


def test_gbd_demand_distributions():
    dmds, prbs = gbd._distributions(None)
    for d, p in zip(dmds, prbs):
        assert len(d) == len(p)
        assert np.isclose(np.sum(p), 1.0, atol=1e-6)
    d0 = gbd.sample(0)
    assert all(any(np.isclose(v, dm).any() for dm in [dmds[i]])
               for i, v in enumerate(d0))


def test_gbd_ef_matches_scipy():
    specs = _gbd_specs(5)
    sobj, _ = scipy_ef_solve(specs)
    ef = ef_mod.ExtensiveForm(
        {"tol": 1e-7, "max_iters": 300_000},
        gbd.scenario_names_creator(5), gbd.scenario_creator,
        {"num_scens": 5})
    st = ef.solve_extensive_form()
    assert bool(st.done.all())
    assert ef.get_objective_value() == pytest.approx(sobj, rel=2e-3)


def test_gbd_ph_converges():
    specs = _gbd_specs(5)
    sobj, _ = scipy_ef_solve(specs)
    b = batch_mod.from_specs(specs)
    algo, (conv, eobj, tb) = _ph(b, rho=5.0, iters=250, conv=1e-2)
    assert tb <= sobj + abs(sobj) * 1e-3
    assert conv <= 1e-2
    # first stage is a genuine allocation: inventory rows hold at xbar
    x1 = algo.first_stage_solution()
    x = x1.reshape(4, 5)
    slackless_use = x.sum(axis=1)
    assert np.all(slackless_use <= np.array([10, 19, 25, 15]) + 1e-2)


# ---------------- stoch_distr ----------------

def test_stoch_distr_admm_matches_global_lp():
    R, S = 3, 3
    data = distr.region_data(R, seed=2)
    stoch_names = stoch_distr.stoch_scenario_names_creator(S)
    cons = stoch_distr.consensus_vars_creator(R, data)
    wrapper = Stoch_AdmmWrapper(
        {}, stoch_distr.admm_subproblem_names_creator(R), stoch_names,
        lambda snm, rnm, **kw: stoch_distr.scenario_creator(
            snm, rnm, data=data), cons)
    b = wrapper.make_batch()
    assert b.tree.num_stages == 3
    assert b.num_scenarios == R * S
    algo, (conv, eobj, tb) = _ph(b, rho=2.0, iters=400, conv=2e-4,
                                 windows=10)
    ref = stoch_distr.global_lp_oracle(data, stoch_names)
    assert conv <= 2e-4
    # consensus expectation within 1% of the merged two-stage LP
    assert eobj == pytest.approx(ref, rel=1e-2)
    # z is a ROOT (stage-1) quantity: one value across all nodes
    xb = np.asarray(algo.state.xbar_nodes)
    assert xb.shape[1] == b.num_nonants


# ---------------- usar ----------------

def test_usar_lp_relax_ef_and_ph():
    from mpisppy_tpu.models import usar
    inst = usar.generate_instance(num_depots=3, num_sites=6,
                                  time_horizon=5, num_active_depots=2,
                                  seed=1)
    N = 4
    specs = [usar.scenario_creator(nm, instance=inst, num_scens=N,
                                   lp_relax=True)
             for nm in usar.scenario_names_creator(N)]
    sobj, _ = scipy_ef_solve(specs)
    b = batch_mod.from_specs(specs)
    algo, (conv, eobj, tb) = _ph(b, rho=5.0, iters=250, conv=1e-3)
    assert tb <= sobj + abs(sobj) * 1e-3 + 1e-6
    assert conv <= 1e-3
    # saving lives pays: the optimum is strictly negative
    assert sobj < -1.0


def test_usar_integer_first_stage():
    from mpisppy_tpu.algos import mip as mip_mod
    from mpisppy_tpu.models import usar
    from mpisppy_tpu.ops import bnb
    inst = usar.generate_instance(num_depots=3, num_sites=5,
                                  time_horizon=4, num_active_depots=1,
                                  seed=2)
    N = 3
    specs = [usar.scenario_creator(nm, instance=inst, num_scens=N)
             for nm in usar.scenario_names_creator(N)]
    b = batch_mod.from_specs(specs)
    res = mip_mod.certified_mip_gap(
        b, ph_options=ph_mod.PHOptions(
            default_rho=5.0, max_iterations=60, conv_thresh=1e-3,
            pdhg=pdhg.PDHGOptions(tol=1e-6)),
        opts=bnb.BnBOptions(max_rounds=120), dd_nodes=4)
    assert np.isfinite(res.inner) and np.isfinite(res.outer)
    assert res.outer <= res.inner + 1e-6
    # exactly one active depot in the incumbent
    depots = np.round(res.xhat[:3])
    assert depots.sum() == pytest.approx(1.0)


# ---------------- ccopf (acopf3 DC stand-in) ----------------

def test_ccopf_lp_ef_matches_scipy_tree():
    from mpisppy_tpu.models import ccopf
    from test_hydro import scipy_ef_solve_tree
    inst = ccopf.grid_instance(4, seed=3)
    inst["c2"] = np.zeros_like(inst["c2"])   # LP variant for the oracle
    specs = [ccopf.scenario_creator(nm, instance=inst)
             for nm in ccopf.scenario_names_creator(9)]
    tree = ccopf.make_tree((3, 3), inst)
    sobj, _ = scipy_ef_solve_tree(specs, tree)
    from mpisppy_tpu.algos import ef as ef_mod2
    # the B-theta EF is more ill-conditioned than the flow LPs (angle
    # columns couple through stiff susceptances); 1e-5 relative KKT is
    # ample for a 3e-3 objective comparison
    ef = ef_mod2.ExtensiveForm(
        {"tol": 1e-5, "max_iters": 400_000},
        ccopf.scenario_names_creator(9), ccopf.scenario_creator,
        {"instance": inst}, tree=tree)
    st = ef.solve_extensive_form()
    assert float(st.score.max()) <= 2e-5
    assert ef.get_objective_value() == pytest.approx(sobj, rel=3e-3)


def test_ccopf_quadratic_ph_converges():
    from mpisppy_tpu.models import ccopf
    inst = ccopf.grid_instance(4, seed=3)
    specs = [ccopf.scenario_creator(nm, instance=inst)
             for nm in ccopf.scenario_names_creator(9)]
    tree = ccopf.make_tree((3, 3), inst)
    b = batch_mod.from_specs(specs, tree=tree)
    assert float(np.abs(np.asarray(b.qp.q)).max()) > 0.0  # true QP
    algo, (conv, eobj, tb) = _ph(b, rho=50.0, iters=300, conv=1e-3)
    assert conv <= 1e-3
    assert tb <= eobj + abs(eobj) * 1e-3  # wait-and-see brackets
    # nonant layout: stage-1 + stage-2 generation
    ng = len(inst["gens"])
    assert b.num_nonants == 2 * ng
