# End-to-end farmer: EF known answer + PH convergence to the EF objective.
# The TPU analog of ref:mpisppy/tests/test_ef_ph.py — but our solver is
# in-repo, so we can also oracle against scipy.linprog.
import numpy as np
import pytest
from scipy.optimize import linprog

from mpisppy_tpu.algos import ef as ef_mod
from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import farmer
from mpisppy_tpu.ops import pdhg

FARMER_EF_OBJ = -108390.0  # classic Birge & Louveaux value


def farmer_specs(num_scens=3, **kw):
    names = farmer.scenario_names_creator(num_scens)
    return [farmer.scenario_creator(nm, num_scens=num_scens, **kw)
            for nm in names]


def scipy_ef_solve(specs):
    """Independent EF oracle via scipy.linprog on the assembled EF."""
    efp = ef_mod.build_ef(specs, scale=False)
    qp = efp.qp
    c = np.asarray(qp.c, np.float64)
    A = np.asarray(qp.A.toarray() if hasattr(qp.A, "toarray") else qp.A,
                   np.float64)
    bl, bu = np.asarray(qp.bl, np.float64), np.asarray(qp.bu, np.float64)
    l, u = np.asarray(qp.l, np.float64), np.asarray(qp.u, np.float64)
    A_ub, b_ub, A_eq, b_eq = [], [], [], []
    for i in range(A.shape[0]):
        if bl[i] == bu[i]:
            A_eq.append(A[i]); b_eq.append(bu[i])
        else:
            if np.isfinite(bu[i]):
                A_ub.append(A[i]); b_ub.append(bu[i])
            if np.isfinite(bl[i]):
                A_ub.append(-A[i]); b_ub.append(-bl[i])
    res = linprog(c, A_ub=np.array(A_ub) if A_ub else None,
                  b_ub=np.array(b_ub) if b_ub else None,
                  A_eq=np.array(A_eq) if A_eq else None,
                  b_eq=np.array(b_eq) if b_eq else None,
                  bounds=list(zip(l, u)), method="highs")
    assert res.status == 0
    return res.fun, res.x


def test_farmer_ef_known_answer():
    specs = farmer_specs(3)
    obj, _ = scipy_ef_solve(specs)
    assert obj == pytest.approx(FARMER_EF_OBJ, abs=1.0)


def test_farmer_ef_pdhg_matches_scipy():
    specs = farmer_specs(3)
    sobj, _ = scipy_ef_solve(specs)
    efobj = ef_mod.ExtensiveForm({"tol": 1e-7, "max_iters": 200_000},
                                 farmer.scenario_names_creator(3),
                                 farmer.scenario_creator,
                                 {"num_scens": 3})
    st = efobj.solve_extensive_form()
    assert bool(st.done.all())
    assert efobj.get_objective_value() == pytest.approx(sobj, rel=2e-3)
    # first-stage solution: WHEAT 170, CORN 80, BEETS 250 (textbook)
    x1 = [efobj.get_root_solution()[f"x{i}"] for i in range(3)]
    np.testing.assert_allclose(x1, [170.0, 80.0, 250.0], atol=2.0)


def test_farmer_ph_converges_to_ef():
    specs = farmer_specs(3)
    sobj, _ = scipy_ef_solve(specs)
    b = batch_mod.from_specs(specs)
    opts = ph_mod.PHOptions(
        default_rho=1.0, max_iterations=150, conv_thresh=5e-2,
        subproblem_windows=10,
        pdhg=pdhg.PDHGOptions(tol=1e-7, restart_period=40),
    )
    algo = ph_mod.PH(opts, b)
    conv, eobj, tbound = algo.ph_main()
    # trivial bound = wait-and-see expectation, a valid lower bound
    assert tbound <= sobj + 1.0
    assert conv <= opts.conv_thresh
    # converged nonants agree across scenarios and with the EF solution
    x1 = algo.first_stage_solution()
    np.testing.assert_allclose(x1, [170.0, 80.0, 250.0], atol=5.0)


def test_farmer_ph_larger_scenarios():
    # 12 scenarios (groups > 0 use the seeded RNG noise path)
    specs = farmer_specs(12)
    b = batch_mod.from_specs(specs)
    opts = ph_mod.PHOptions(default_rho=1.0, max_iterations=120,
                            conv_thresh=1e-1, subproblem_windows=8)
    algo = ph_mod.PH(opts, b)
    conv, eobj, tbound = algo.ph_main()
    sobj, _ = scipy_ef_solve(specs)
    assert tbound <= sobj + 1.0
    assert eobj == pytest.approx(sobj, rel=5e-3)
