# W/xbar I/O + PHState checkpointing (ref:utils/wxbar*) and proper
# bundles (ref:utils/proper_bundler.py, pickle_bundle.py).
import numpy as np
import pytest

from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import farmer
from mpisppy_tpu.ops import pdhg
from mpisppy_tpu.utils import pickle_bundle, wxbarutils
from mpisppy_tpu.utils.proper_bundler import ProperBundler, form_bundle_spec

from test_farmer_ef_ph import farmer_specs, scipy_ef_solve


def _ph(b, iters=30, conv=0.0):
    opts = ph_mod.PHOptions(
        default_rho=1.0, max_iterations=iters, conv_thresh=conv,
        subproblem_windows=8,
        pdhg=pdhg.PDHGOptions(tol=1e-7, restart_period=40))
    return ph_mod.PH(opts, b)


def test_w_xbar_roundtrip(tmp_path):
    b = batch_mod.from_specs(farmer_specs(3))
    algo = _ph(b, iters=10)
    algo.Iter0()
    algo.iterk_loop()
    wf, xf = str(tmp_path / "w.csv"), str(tmp_path / "xbar.csv")
    wxbarutils.write_W_to_file(algo, wf)
    wxbarutils.write_xbar_to_file(algo, xf)

    algo2 = _ph(b, iters=10)
    algo2.Iter0()
    wxbarutils.set_W_from_file(wf, algo2)
    wxbarutils.set_xbar_from_file(xf, algo2)
    np.testing.assert_allclose(np.asarray(algo2.state.W),
                               np.asarray(algo.state.W), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(algo2.state.xbar_nodes),
                               np.asarray(algo.state.xbar_nodes),
                               rtol=1e-6)


def test_w_check_rejects_invalid_duals(tmp_path):
    b = batch_mod.from_specs(farmer_specs(3))
    algo = _ph(b, iters=3)
    algo.Iter0()
    wf = str(tmp_path / "w.csv")
    # all-ones W has nonzero node mean: not a valid PH dual vector
    with open(wf, "w") as f:
        for nm in algo.scenario_names:
            for i in range(b.num_nonants):
                f.write(f"{nm},{i},1.0\n")
    with pytest.raises(ValueError, match="node mean"):
        wxbarutils.set_W_from_file(wf, algo)
    wxbarutils.set_W_from_file(wf, algo, disable_check=True)  # forced


def test_warm_start_from_saved_w_converges_faster(tmp_path):
    b = batch_mod.from_specs(farmer_specs(3))
    ref = _ph(b, iters=60, conv=5e-2)
    ref.ph_main()
    wf = str(tmp_path / "w.csv")
    wxbarutils.write_W_to_file(ref, wf)

    from mpisppy_tpu.extensions.wxbar_io import WXBarReader
    import functools
    warm = ph_mod.PH(
        ph_mod.PHOptions(default_rho=1.0, max_iterations=60,
                         conv_thresh=5e-2, subproblem_windows=8,
                         pdhg=pdhg.PDHGOptions(tol=1e-7,
                                               restart_period=40)),
        b, extensions=functools.partial(WXBarReader, init_W_fname=wf))
    warm.ph_main()
    # warm duals help, but not necessarily strictly: the saved W was
    # taken at a loose 5e-2 stop, and the warm run's slightly different
    # iterate path can cross the threshold a step or two later (observed
    # 28 vs 26 under f32 rounding) — allow that jitter, still assert the
    # warm start is in the same ballpark rather than restarting cold
    assert warm._iter <= ref._iter + 2


def test_checkpoint_resume_exact(tmp_path):
    b = batch_mod.from_specs(farmer_specs(3))
    algo = _ph(b, iters=8)
    algo.Iter0()
    algo.iterk_loop()
    ck = str(tmp_path / "state.npz")
    wxbarutils.save_ph_state(ck, algo)

    algo2 = _ph(b, iters=8)
    algo2.Iter0()
    wxbarutils.load_ph_state(ck, algo2)
    assert algo2._iter == algo._iter
    # one more identical step from the restored state matches exactly
    s1 = ph_mod.ph_iterk(b, algo.state, algo.options)
    s2 = ph_mod.ph_iterk(b, algo2.state, algo2.options)
    np.testing.assert_array_equal(np.asarray(s1.W), np.asarray(s2.W))
    np.testing.assert_array_equal(np.asarray(s1.solver.x),
                                  np.asarray(s2.solver.x))


def test_bundle_spec_ef_equivalence():
    """PH over 3 bundles of 2 must reach the same EF objective as the
    6-scenario EF (the bundle EF identity p_bun f_bun = sum p_i f_i)."""
    specs = farmer_specs(6)
    sobj, _ = scipy_ef_solve(specs)
    bundles = [form_bundle_spec(specs[2 * i:2 * i + 2], f"Bundle_{i}")
               for i in range(3)]
    # the bundle batch EF equals the scenario EF
    bobj, _ = scipy_ef_solve(bundles)
    assert bobj == pytest.approx(sobj, rel=1e-6)
    bb = batch_mod.from_specs(bundles)
    algo = _ph(bb, iters=120, conv=5e-2)
    conv, eobj, tb = algo.ph_main()
    assert conv <= 5e-2
    assert eobj == pytest.approx(sobj, rel=5e-3)
    np.testing.assert_allclose(algo.first_stage_solution(),
                               [170.0, 80.0, 250.0], atol=5.0)


def test_proper_bundler_api(tmp_path):
    from mpisppy_tpu.utils.config import Config
    pb = ProperBundler(farmer)
    cfg = Config()
    cfg.quick_assign("num_scens", int, 6)
    cfg.quick_assign("scenarios_per_bundle", int, 3)
    names = pb.bundle_names_creator(2, cfg=cfg)
    assert names == ["Bundle_0_2", "Bundle_3_5"]
    kw = pb.kw_creator(cfg)
    b0 = pb.scenario_creator(names[0], **kw)
    assert b0.name == "Bundle_0_2"
    assert len(b0.nonant_idx) == 3          # farmer: 3 crops shared
    # pickle roundtrip
    pickle_bundle.write_spec(b0, str(tmp_path))
    b0r = pickle_bundle.read_spec(str(tmp_path), "Bundle_0_2")
    np.testing.assert_array_equal(b0r.c, b0.c)
    # plain scenario passthrough
    s0 = pb.scenario_creator("scen0", **kw)
    assert s0.name == "scen0"
