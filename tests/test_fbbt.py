# Presolve / FBBT plane (ops/fbbt.py) — semantics parity with the
# reference's SPPresolve + cross-rank nonant bound reduction
# (ref:mpisppy/opt/presolve.py:61-260).
import numpy as np
import jax.numpy as jnp

from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import sslp
from mpisppy_tpu.ops import boxqp, fbbt, pdhg, sparse as sparse_mod


def _qp(c, A, bl, bu, l, u):  # noqa: E741
    z = np.zeros_like(np.asarray(c, float))
    return boxqp.BoxQP(
        c=jnp.asarray(c, jnp.float32), q=jnp.asarray(z, jnp.float32),
        A=jnp.asarray(A, jnp.float32), bl=jnp.asarray(bl, jnp.float32),
        bu=jnp.asarray(bu, jnp.float32), l=jnp.asarray(l, jnp.float32),
        u=jnp.asarray(u, jnp.float32))


def test_fbbt_hand_example():
    # 2x + 3y <= 6, x,y >= 0  =>  x <= 3, y <= 2
    qp = _qp([1.0, 1.0], [[2.0, 3.0]], [-np.inf], [6.0],
             [0.0, 0.0], [np.inf, np.inf])
    l, u = fbbt.fbbt(qp, n_sweeps=2)  # noqa: E741
    assert np.allclose(np.asarray(u), [3.0, 2.0], atol=1e-5)
    assert np.allclose(np.asarray(l), [0.0, 0.0], atol=1e-5)


def test_fbbt_integer_rounding():
    # 2x + 2y <= 5 with x,y integer => x,y <= floor(2.5) = 2
    qp = _qp([1.0, 1.0], [[2.0, 2.0]], [-np.inf], [5.0],
             [0.0, 0.0], [10.0, 10.0])
    l, u = fbbt.fbbt(qp, n_sweeps=2, d_col=jnp.ones(2),  # noqa: E741
                     integer=jnp.ones(2, bool))
    assert np.allclose(np.asarray(u), [2.0, 2.0], atol=1e-5)


def test_fbbt_equality_propagation():
    # x + y == 4, 0<=x<=1  =>  3 <= y <= 4
    qp = _qp([0.0, 0.0], [[1.0, 1.0]], [4.0], [4.0],
             [0.0, 0.0], [1.0, 10.0])
    l, u = fbbt.fbbt(qp, n_sweeps=2)  # noqa: E741
    assert np.allclose(np.asarray(l), [0.0, 3.0], atol=1e-5)
    assert np.allclose(np.asarray(u), [1.0, 4.0], atol=1e-5)


def test_fbbt_ell_matches_dense():
    rng = np.random.RandomState(5)
    m, n = 6, 9
    A = rng.randn(m, n) * (rng.rand(m, n) < 0.5)
    bu = rng.rand(m) * 4 + 1
    bl = np.full(m, -np.inf)
    l = np.zeros(n)  # noqa: E741
    u = np.full(n, 5.0)
    qp_d = _qp(rng.randn(n), A, bl, bu, l, u)
    import scipy.sparse as sps
    ell = sparse_mod.ell_from_scipy(sps.csr_matrix(A), jnp.float32)
    import dataclasses
    qp_s = dataclasses.replace(qp_d, A=ell)
    ld, ud = fbbt.fbbt(qp_d, n_sweeps=3)
    ls, us = fbbt.fbbt(qp_s, n_sweeps=3)
    assert np.allclose(np.asarray(ld), np.asarray(ls), atol=1e-4)
    assert np.allclose(np.asarray(ud), np.asarray(us), atol=1e-4)


def test_fbbt_never_cuts_optimum():
    """Tightened boxes must preserve the LP optimum (validity)."""
    rng = np.random.RandomState(7)
    S, m, n = 3, 5, 7
    c = rng.randn(S, n)
    A = rng.randn(S, m, n) * (rng.rand(S, m, n) < 0.6)
    x0 = rng.rand(S, n)
    bu = np.einsum("smn,sn->sm", A, x0) + 0.3
    qp = _qp(c, A, np.full((S, m), -np.inf), bu,
             np.zeros((S, n)), np.full((S, n), 3.0))
    st0 = pdhg.solve(qp, pdhg.PDHGOptions(tol=1e-7))
    obj0 = np.asarray(jnp.sum(qp.c * st0.x, axis=-1))
    l2, u2 = fbbt.fbbt(qp, n_sweeps=3)
    import dataclasses
    qp2 = dataclasses.replace(qp, l=l2, u=u2)
    st1 = pdhg.solve(qp2, pdhg.PDHGOptions(tol=1e-7))
    obj1 = np.asarray(jnp.sum(qp2.c * st1.x, axis=-1))
    assert np.allclose(obj0, obj1, atol=1e-3 * (1 + np.abs(obj0).max()))


def test_presolve_batch_sslp():
    """Presolving the sslp batch tightens bounds (the dummy overflow
    columns get demand-sum-implied boxes; binaries stay [0,1]) and
    preserves every scenario's LP optimum + the PH trivial bound."""
    inst = sslp.synthetic_instance(5, 10, seed=1)
    names = sslp.scenario_names_creator(6)
    specs = [sslp.scenario_creator(nm, instance=inst, num_scens=6)
             for nm in names]
    batch = batch_mod.from_specs(specs)
    st0 = pdhg.solve(batch.qp, pdhg.PDHGOptions(tol=1e-6))
    obj0 = np.asarray(batch.objective(st0.x))

    pre, info = fbbt.presolve_batch(batch, n_sweeps=3)
    assert info["tightened_bounds"] > 0
    assert not info["infeasible"].any()
    st1 = pdhg.solve(pre.qp, pdhg.PDHGOptions(tol=1e-6))
    obj1 = np.asarray(pre.objective(st1.x))
    assert np.allclose(obj0, obj1, rtol=1e-3, atol=1e-2), (obj0, obj1)


def test_presolve_cross_scenario_nonant_intersection():
    """A bound implied in ONE scenario must propagate to all scenarios'
    nonant boxes (ref:mpisppy/opt/presolve.py:183-260 Allreduce
    semantics)."""
    # two scenarios; scenario 1's row x0 <= 2 must tighten scenario 0 too
    import dataclasses
    from mpisppy_tpu.core.batch import ScenarioSpec
    mk = lambda name, bu0: ScenarioSpec(  # noqa: E731
        name=name,
        c=np.array([1.0, 1.0]),
        A=np.array([[1.0, 0.0]]),
        bl=np.array([-np.inf]),
        bu=np.array([bu0]),
        l=np.zeros(2),
        u=np.array([10.0, 10.0]),
        nonant_idx=np.array([0], np.int32),
    )
    specs = [mk("s0", 9.0), mk("s1", 2.0)]
    batch = batch_mod.from_specs(specs, scale=False)
    pre, info = fbbt.presolve_batch(batch, n_sweeps=2)
    u_non = np.asarray(pre.qp.u)[:, 0] * np.broadcast_to(
        np.asarray(pre.d_col), np.asarray(pre.qp.u).shape)[:, 0]
    assert np.all(u_non <= 2.0 + 1e-5), u_non


def test_presolve_detects_infeasible_scenario():
    from mpisppy_tpu.core.batch import ScenarioSpec
    # x0 + x1 >= 5 with boxes [0,1] is infeasible
    sp_bad = ScenarioSpec(
        name="bad", c=np.zeros(2), A=np.array([[1.0, 1.0]]),
        bl=np.array([5.0]), bu=np.array([np.inf]),
        l=np.zeros(2), u=np.ones(2), nonant_idx=np.array([0], np.int32))
    sp_ok = ScenarioSpec(
        name="ok", c=np.zeros(2), A=np.array([[1.0, 1.0]]),
        bl=np.array([1.0]), bu=np.array([np.inf]),
        l=np.zeros(2), u=np.ones(2), nonant_idx=np.array([0], np.int32))
    import pytest
    batch = batch_mod.from_specs([sp_ok, sp_bad], scale=False)
    with pytest.raises(ValueError, match="infeasible"):
        fbbt.presolve_batch(batch, n_sweeps=3)
    _, info = fbbt.presolve_batch(batch, n_sweeps=3,
                                  raise_on_infeasible=False)
    assert bool(info["infeasible"][1])
    # the cross-scenario MAX/MIN reduction propagates the empty nonant
    # box to every member scenario — correct: one infeasible scenario
    # makes the whole stochastic program infeasible (same effect as the
    # reference's bound Allreduce, ref:mpisppy/opt/presolve.py:183-260)
    assert info["infeasible"].all()


def test_fbbt_infinite_terms_do_not_fabricate_bounds():
    # ADVICE r3 (medium): with an unbounded column in the row, clipped
    # 1e30 activity sums absorbed the finite terms and the derived bound
    # for the unbounded column ignored the other columns' real activity.
    # Row: x0 + x1 <= 10, x0 in [-5, 5], x1 in (-inf, inf).
    # True implication: x1 <= 10 - min(x0) = 15 (NOT 10).
    qp = _qp([0.0, 0.0], [[1.0, 1.0]], [-np.inf], [10.0],
             [-5.0, -np.inf], [5.0, np.inf])
    l, u = fbbt.fbbt(qp, n_sweeps=1)  # noqa: E741
    u = np.asarray(u)
    l = np.asarray(l)  # noqa: E741
    assert u[1] >= 15.0 - 1e-4, f"invalid tightening: u1={u[1]}"
    assert u[1] <= 15.0 + 1e-4, f"missed valid tightening: u1={u[1]}"
    # x0's own bound must be untouched by the side carrying x1's
    # infinity (two infinite terms would remain after excluding x0)
    assert u[0] == 5.0 and l[0] == -5.0


def test_fbbt_two_infinite_terms_skip_tightening():
    # Row: x0 + x1 + x2 <= 10 with x1, x2 both unbounded below: no
    # column may be tightened from this row's upper side (excluding any
    # single j still leaves an infinite min-term).
    qp = _qp([0.0] * 3, [[1.0, 1.0, 1.0]], [-np.inf], [10.0],
             [0.0, -np.inf, -np.inf], [np.inf, np.inf, np.inf])
    l, u = fbbt.fbbt(qp, n_sweeps=2)  # noqa: E741
    assert np.all(np.isinf(np.asarray(u)))


def test_fbbt_single_infinite_term_tightens_only_owner():
    # Row: 2 x0 - x1 <= 8, x0 in [0, inf), x1 in [0, 4]:
    #   x0 <= (8 + max(x1)) / 2 = 6   (x0's min-term is the infinite one
    #   -> excluded exactly; x1's finite activity must count)
    qp = _qp([0.0, 0.0], [[2.0, -1.0]], [-np.inf], [8.0],
             [0.0, 0.0], [np.inf, 4.0])
    l, u = fbbt.fbbt(qp, n_sweeps=1)  # noqa: E741
    u = np.asarray(u)
    assert abs(u[0] - 6.0) < 1e-4, f"u0={u[0]}"
    # x1 cannot be tightened from this row (x0's term is infinite after
    # excluding x1), and no other row exists
    assert np.isinf(u[1]) or u[1] == 4.0
