# Serve-layer chaos storm (ISSUE 12 acceptance): a seeded randomized
# mix of ServeFaults (hang / poison / disconnect / flood) + a
# kill-dispatcher storm on the shared dispatch scheduler + a
# preemption mid-traffic, against a running WheelServer.  The serving
# invariant under all of it: every submitted session observes a
# terminal outcome — result, typed failure, or typed rejection — NEVER
# a hang; tenant quotas are fully restored; the server survives.  Fast
# 2-seed subset in tier-1, 12-seed soak under `slow`.
import dataclasses
import socket
import threading
import time

import numpy as np
import pytest
import jax.numpy as jnp

from mpisppy_tpu import dispatch
from mpisppy_tpu.dispatch import (
    DispatchOptions, SolveFailed, SolveScheduler,
)
from mpisppy_tpu.resilience import DispatchFault, FaultPlan, ServeFault
from mpisppy_tpu.serve import ServeOptions, SubmitRequest, WheelServer
from mpisppy_tpu.serve import loadgen
from mpisppy_tpu.serve.engine import SyntheticEngine

from test_mip_bnb import random_mips

pytestmark = pytest.mark.chaos


def _fake_solve(qp, d_col, int_cols, opts, **kw):
    from mpisppy_tpu.ops.bnb import BnBResult
    time.sleep(0.002)
    S = qp.c.shape[0]
    return BnBResult(
        x=jnp.zeros_like(qp.c),
        inner=jnp.sum(qp.c, axis=-1),
        outer=jnp.sum(qp.c, axis=-1) - 1.0,
        gap=jnp.zeros((S,), qp.c.dtype),
        feasible=jnp.ones((S,), bool),
        nodes_solved=jnp.ones((S,), jnp.int32))


def run_serve_storm(seed: int, tmp_path) -> dict:
    """One seeded storm round.  Healthy tenants acme/zeta run mixed
    sessions; mallory hangs+poisons+floods; ghost gets its connection
    dropped mid-run; a preemption fires mid-traffic; and a concurrent
    dispatch storm (with an injected dispatcher-thread death) hammers
    the process-default scheduler the whole time."""
    rng = np.random.default_rng(seed)
    hang_ord = int(rng.integers(0, 2))
    plan = FaultPlan(seed=seed, serves=(
        ServeFault("hang", tenant="mallory", at_sessions=(hang_ord,),
                   hang_s=20.0),
        ServeFault("poison", tenant="mallory",
                   at_sessions=(1 - hang_ord,)),
        ServeFault("disconnect", tenant="ghost", at_sessions=(0,)),
        ServeFault("flood", tenant="mallory", flood_factor=2),
    ), dispatches=(
        DispatchFault("kill_dispatcher"),
        DispatchFault("slow", jitter_s=0.004),
    ))
    engine = SyntheticEngine(
        iters=5, step_s=0.004,
        preempt_at={("acme", int(rng.integers(0, 2))): 2})
    srv = WheelServer(ServeOptions(
        unix_path=str(tmp_path / f"storm{seed}.sock"),
        trace_dir=str(tmp_path / f"traces{seed}"),
        max_running=2, max_queued=8, max_queued_per_tenant=4,
        default_deadline_s=3.0, engine=engine, fault_plan=plan,
        multiplex=False)).start()

    # the concurrent dispatch storm: its own scheduler armed with the
    # SAME plan (kill_dispatcher fires in its daemon); tickets must
    # resolve typed while serve traffic flows
    sched = SolveScheduler(
        DispatchOptions(max_wait_ms=2.0, dispatch_timeout_s=0.25,
                        retry_max=1, retry_backoff_s=0.005,
                        deadline_s=3.0),
        solve_fn=_fake_solve, fault_plan=plan)
    base, _, _ = random_mips(S=2, n=6, m=4)
    d = jnp.ones(6, jnp.float32)
    ic = np.arange(2, dtype=np.int32)
    storm_out: dict = {}

    def dispatch_storm():
        tickets = [sched.submit(dataclasses.replace(
            base, c=base.c * (k + 1)), d, ic) for k in range(6)]
        for k, t in enumerate(tickets):
            try:
                storm_out[k] = np.asarray(t.result(timeout=8.0).inner)
            except SolveFailed as e:
                storm_out[k] = e

    records: list = []
    rec_lock = threading.Lock()

    def healthy(tenant, ci):
        cl = loadgen.ServeClient(srv.address, timeout=30.0)
        try:
            for k in range(2):
                rec = loadgen.run_session(cl, SubmitRequest(
                    tenant=tenant, model="farmer", num_scens=3,
                    sla="latency" if k == 0 else "throughput",
                    deadline_s=10.0))
                with rec_lock:
                    records.append(rec)
        finally:
            cl.close()

    def mallory():
        cl = loadgen.ServeClient(srv.address, timeout=30.0)
        try:
            n = 2 * plan.serve_flood_factor("mallory")
            for k in range(n):
                rec = loadgen.run_session(
                    cl, SubmitRequest(tenant="mallory",
                                      model="farmer", num_scens=3,
                                      deadline_s=4.0))
                with rec_lock:
                    records.append(rec)
        finally:
            cl.close()

    ghost_server_done = threading.Event()

    def ghost():
        cl = loadgen.ServeClient(srv.address, timeout=6.0)
        try:
            rec = loadgen.run_session(cl, SubmitRequest(
                tenant="ghost", model="farmer", num_scens=3,
                deadline_s=10.0))
            with rec_lock:
                records.append(rec)
        except (socket.timeout, ConnectionError, OSError):
            # the dropped connection: the CLIENT may never see the
            # terminal line — the server-side invariant (terminal
            # state + freed quota) is asserted below
            ghost_server_done.set()
        finally:
            cl.close()

    threads = [threading.Thread(target=healthy, args=("acme", 0)),
               threading.Thread(target=healthy, args=("zeta", 1)),
               threading.Thread(target=mallory),
               threading.Thread(target=ghost),
               threading.Thread(target=dispatch_storm)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    wall = time.perf_counter() - t0
    alive = [t.name for t in threads if t.is_alive()]
    # settle server-side terminal accounting before the asserts
    deadline = time.perf_counter() + 15.0
    while time.perf_counter() < deadline:
        states = srv.stats()["states"]
        nonterminal = sum(v for k, v in states.items()
                          if k not in ("DONE", "FAILED", "REJECTED"))
        if nonterminal == 0:
            break
        time.sleep(0.05)
    stats = srv.stats()
    sessions = dict(srv._sessions)
    srv.stop()
    sched.close()
    return {"seed": seed, "plan": plan, "records": records,
            "storm_out": storm_out, "stats": stats, "wall": wall,
            "alive": alive, "sessions": sessions}


def assert_storm_invariants(r: dict) -> None:
    assert not r["alive"], \
        f"DEADLOCK: {r['alive']} still alive (seed {r['seed']})"
    # every client-side record reached a terminal outcome
    for rec in r["records"]:
        assert rec["outcome"] in ("done", "failed", "rejected"), rec
    # the healthy tenants' non-rejected sessions all finished; a
    # preempted one resumed to done (no client-visible loss)
    healthy = [rec for rec in r["records"]
               if rec["tenant"] in ("acme", "zeta")]
    assert healthy
    for rec in healthy:
        assert rec["outcome"] in ("done", "rejected"), rec
    assert any(rec.get("preempted") for rec in healthy), \
        "the mid-traffic preemption never exercised"
    # mallory's hang resolved at its deadline, typed; the poison is a
    # typed failure; floods are typed rejects or served — never a hang
    mall = [rec for rec in r["records"] if rec["tenant"] == "mallory"]
    reasons = {rec.get("reason") for rec in mall
               if rec["outcome"] == "failed"}
    assert "deadline" in reasons or "RuntimeError" in reasons, mall
    # EVERY server-side session is terminal and quotas fully restored
    for s in r["sessions"].values():
        assert s.state in ("DONE", "FAILED", "REJECTED"), \
            (s.sid, s.tenant, s.state)
    for name, t in r["stats"]["admission"]["tenants"].items():
        assert t["inflight"] == 0, (name, t)
    # the dispatch storm's tickets all resolved (result or typed)
    assert set(r["storm_out"]) == set(range(6))
    for k, out in r["storm_out"].items():
        if isinstance(out, SolveFailed):
            assert out.reason in ("timeout", "exception", "deadline",
                                  "dispatcher-died")
    # the seams actually fired
    seams = {s for s, _ in r["plan"].fired}
    assert "serve" in seams and "dispatch" in seams
    assert r["wall"] < 60.0


def test_serve_chaos_storm_fast_seeded(tmp_path):
    """Tier-1 subset: two seeded storms."""
    for seed in (11, 23):
        assert_storm_invariants(run_serve_storm(seed, tmp_path))


@pytest.mark.slow
def test_serve_chaos_storm_soak(tmp_path):
    """The long soak across the fault-mix space."""
    for seed in range(400, 412):
        assert_storm_invariants(run_serve_storm(seed, tmp_path))
