# Rolling-horizon MPC streams (mpisppy_tpu/mpc; ISSUE 19, docs/mpc.md):
# shift-plan/kernel invariants, zero warm recompiles, the serve
# stream's preempt-resume bit-identity on a real uc horizon, the
# streaming reaper's per-step miss budget, per-step WFQ charging, and
# the BENCH_r11 -> r12 gate.
import json
import os
import time

import numpy as np
import pytest

from mpisppy_tpu.mpc.horizon import (
    HorizonSpec, ccopf_horizon, horizon_for, uc_horizon,
)
from mpisppy_tpu.mpc.shift import (
    ShiftPlan, ccopf_plan, shift_warm_plane, uc_plan,
)
from mpisppy_tpu.serve import FairQueue, ServeOptions, SubmitRequest, \
    WheelServer
from mpisppy_tpu.serve import loadgen
from mpisppy_tpu.serve.engine import SyntheticEngine, WheelEngine
from mpisppy_tpu.serve.session import Session

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spec(**kw):
    kw.setdefault("tenant", "acme")
    kw.setdefault("sla", "latency")
    kw.setdefault("model", "uc")
    kw.setdefault("num_scens", 3)
    return SubmitRequest(**kw)


# ---------------------------------------------------------------------------
# shift plans: the gather indices against a hand-rolled host shift
# ---------------------------------------------------------------------------
def test_uc_plan_rolls_hours_and_freshens_tails():
    """uc slot (g, t) of the new window reads old (g, t + stride);
    the last `stride` hours of each generator are fresh, persistence-
    filled from the generator's final in-window hour."""
    for stride in (1, 2):
        G, T = 2, 4
        plan = uc_plan(G, T, stride)
        assert plan.num_nonants == G * T
        for g in range(G):
            for t in range(T):
                i = g * T + t
                if t + stride < T:
                    assert plan.src_idx[i] == g * T + t + stride
                    assert plan.fresh_mask[i] == 0.0
                else:
                    assert plan.src_idx[i] == g * T + (T - 1)
                    assert plan.fresh_mask[i] == 1.0


def test_ccopf_plan_promotes_stage2_to_stage1():
    """Stage-major (N = 2*ng): old stage 2 becomes new stage 1, new
    stage 2 is fresh (persistence-filled from old stage 2)."""
    ng = 3
    plan = ccopf_plan(ng)
    assert plan.num_nonants == 2 * ng
    np.testing.assert_array_equal(
        plan.src_idx, np.concatenate([np.arange(ng, 2 * ng)] * 2))
    np.testing.assert_array_equal(
        plan.fresh_mask, np.r_[np.zeros(ng), np.ones(ng)])


def test_shift_plan_and_horizon_validation():
    with pytest.raises(ValueError, match="same"):
        ShiftPlan(src_idx=np.zeros(3, np.int32),
                  fresh_mask=np.zeros(4, np.float32))
    with pytest.raises(ValueError, match="index the same window"):
        ShiftPlan(src_idx=np.array([0, 5], np.int32),
                  fresh_mask=np.zeros(2, np.float32))
    with pytest.raises(ValueError, match="stride"):
        uc_plan(2, 4, stride=5)
    with pytest.raises(ValueError, match="bad horizon"):
        HorizonSpec(name="x", model="uc", window=4, stride=5,
                    plan=uc_plan(1, 4), base_argv=(),
                    step_flag="--uc-mpc-step")
    with pytest.raises(ValueError, match="step"):
        uc_horizon(n_gens=1, n_hours=4).step_argv(-1)


# ---------------------------------------------------------------------------
# the shift kernel: splice semantics, PH invariant, compile stability
# ---------------------------------------------------------------------------
def _rand_plane(rng, S, nodes, N):
    W = rng.normal(size=(S, N)).astype(np.float32)
    W -= W.mean(axis=0)     # uniform-p node-mean-zero PH invariant
    return {"W": W,
            "xbar_nodes": rng.normal(size=(nodes, N)).astype(np.float32),
            "x": rng.normal(size=(S, N)).astype(np.float32)}


def test_shift_warm_plane_matches_host_gather():
    """The jitted kernel equals the numpy roll: W gathered then zeroed
    on fresh tails (rolled columns keep the mean-zero invariant,
    fresh columns are exactly zero), x̄/x persistence-gathered."""
    rng = np.random.default_rng(7)
    plan = uc_plan(2, 4, stride=2)
    plane = _rand_plane(rng, S=3, nodes=1, N=plan.num_nonants)
    out = shift_warm_plane(plane, plan)
    keep = 1.0 - plan.fresh_mask
    np.testing.assert_array_equal(
        out["W"], plane["W"][..., plan.src_idx] * keep)
    np.testing.assert_array_equal(
        out["xbar_nodes"], plane["xbar_nodes"][..., plan.src_idx])
    np.testing.assert_array_equal(out["x"], plane["x"][..., plan.src_idx])
    # invariant: every column of the shifted W still node-mean-zero
    np.testing.assert_allclose(out["W"].mean(axis=0),
                               np.zeros(plan.num_nonants), atol=1e-6)
    # fresh tail duals carry no stale pricing
    assert np.all(out["W"][:, plan.fresh_mask == 1.0] == 0.0)


def test_shift_kernel_zero_recompiles_across_ten_steps():
    """shift_state is one process-wide jit with every input traced:
    ten same-shape dispatches with DIFFERENT data (indices included)
    share one executable — 0 compiles after the first call."""
    from mpisppy_tpu.dispatch.compilewatch import CompileWatch

    rng = np.random.default_rng(3)
    plan = uc_plan(2, 6)
    plane = _rand_plane(rng, S=4, nodes=1, N=plan.num_nonants)
    watch = CompileWatch()
    shift_warm_plane(plane, plan)        # pays any first-call compile
    watch.mark()
    for k in range(10):
        plan_k = uc_plan(2, 6, stride=1 + k % 3)
        plane = shift_warm_plane(plane, plan_k)
        assert watch.delta() == 0, f"recompile at warm step {k}"


# ---------------------------------------------------------------------------
# the horizon bridge (serve spec -> HorizonSpec)
# ---------------------------------------------------------------------------
def test_horizon_for_reads_geometry_and_strips_step_flags():
    spec = _spec(gap_target=0.02, max_iterations=77,
                 args=("--uc-n-gens", "2", "--uc-n-hours", "4",
                       "--uc-mpc-step", "9"), mpc_steps=3)
    hz = horizon_for(spec)
    assert hz.model == "uc" and hz.window == 4
    assert hz.plan.num_nonants == 2 * 4
    assert hz.gap_target == 0.02 and hz.max_step_iterations == 77
    # the driver owns the step counter: the stray client copy is gone
    # and step_argv(k) appends exactly one step flag
    argv = hz.step_argv(2)
    assert argv.count("--uc-mpc-step") == 1
    assert argv[argv.index("--uc-mpc-step") + 1] == "2"
    # ccopf: --soc routes to the soc horizon, not duplicated in args
    hz2 = horizon_for(_spec(model="ccopf", num_scens=9,
                            args=("--soc",), mpc_steps=2))
    assert hz2.name == "ccopf-soc"
    assert hz2.base_argv.count("--soc") == 1
    with pytest.raises(ValueError, match="rolling-horizon"):
        horizon_for(_spec(model="farmer", mpc_steps=2))


# ---------------------------------------------------------------------------
# real uc streams: one compile warm-up shared by the e2e assertions
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def uc_streams(tmp_path_factory):
    """Three real 4-step uc streams through the serve engine: the
    fault-free ground truth (with per-step compile deltas), a stream
    preempted entering step 2, and its resume from the stream
    checkpoint."""
    from mpisppy_tpu.dispatch.compilewatch import CompileWatch

    tmp = tmp_path_factory.mktemp("mpc")
    eng = WheelEngine(multiplexed=False)
    steps = 4

    def stream_spec():
        return _spec(gap_target=0.05, max_iterations=400,
                     args=("--uc-n-gens", "2", "--uc-n-hours", "4"),
                     mpc_steps=steps, step_deadline_s=600.0)

    trace_dir = str(tmp / "traces")
    os.makedirs(trace_dir, exist_ok=True)
    base_lines = []
    s0 = Session(stream_spec(), outbox=base_lines.append,
                 trace_dir=trace_dir)
    watch = CompileWatch()
    deltas = {}

    def _count(sess):
        deltas[sess.mpc_step - 1] = watch.delta()
        watch.mark()

    s0.on_step = _count
    watch.mark()
    v0 = eng.run(s0)

    chaos_lines = []
    s1 = Session(stream_spec(), outbox=chaos_lines.append,
                 trace_dir=trace_dir)
    s1.checkpoint_path = str(tmp / "stream.npz")
    preempt_at = 2
    s1.on_step = (lambda sess: sess.preempt_event.set()
                  if sess.mpc_step == preempt_at else None)
    v1 = eng.run(s1)
    ckpt_existed = os.path.exists(s1.checkpoint_path)
    s1.preempt_event.clear()
    s1.on_step = None
    s1.restore = True
    s1.preemptions += 1
    v2 = eng.run(s1)
    # the server's settle latch: exactly one terminal delivery even if
    # two exit paths race to it (worker + reaper)
    s1.transition("ADMITTED")
    s1.transition("RUNNING")
    settled_first = s1.settle("done", **v2[1])
    settled_again = s1.settle("done", **v2[1])
    return {"steps": steps, "verdict0": v0, "deltas": deltas,
            "settled": (settled_first, settled_again),
            "base_lines": base_lines, "verdict1": v1, "verdict2": v2,
            "chaos_lines": chaos_lines, "ckpt_existed": ckpt_existed,
            "ckpt_path": s1.checkpoint_path, "preempt_at": preempt_at,
            "trace_path1": s1.trace_path}


def _step_lines(lines):
    return {m["step"]: m for m in lines if m.get("event") == "step"}


def test_stream_runs_warm_to_done(uc_streams):
    """The fault-free stream: every window certifies, steps after the
    cold start ride the shifted plane (no cold fallbacks, no degrades),
    and the payload carries the latency-class stats."""
    verdict, payload = uc_streams["verdict0"]
    assert verdict == "done"
    assert payload["steps"] == uc_streams["steps"]
    assert payload["warm_steps"] == uc_streams["steps"] - 1
    assert payload["cold_fallbacks"] == 0
    assert payload["degraded_steps"] == 0
    assert payload["rel_gap"] <= 0.05 + 1e-9
    assert payload["step_latency_p50_s"] > 0
    assert payload["step_latency_p99_s"] >= payload["step_latency_p50_s"]
    steps = _step_lines(uc_streams["base_lines"])
    assert sorted(steps) == list(range(uc_streams["steps"]))
    assert not steps[0]["warm"]
    assert all(steps[k]["warm"] for k in range(1, uc_streams["steps"]))
    assert all(len(m["x_root"]) > 0 for m in steps.values())


def test_stream_zero_warm_recompiles(uc_streams):
    """Steps 2+ of a healthy stream re-dispatch the step-1 executables:
    0 backend compiles per window (step 0 pays the wheel compiles, step
    1 may compile the one warm-plane application kernel)."""
    deltas = uc_streams["deltas"]
    assert sorted(deltas) == list(range(uc_streams["steps"]))
    for k in range(2, uc_streams["steps"]):
        assert deltas[k] == 0, f"step {k} recompiled {deltas[k]} kernels"


def test_preempted_stream_resumes_bit_identically(uc_streams):
    """The acceptance chaos round (docs/mpc.md): a stream preempted
    entering step 2 resumes from the stream checkpoint and reproduces
    the fault-free per-step bounds exactly, with exactly one terminal
    outcome and the checkpoint removed on completion."""
    v1, p1 = uc_streams["verdict1"]
    assert v1 == "preempted" and p1["step"] == uc_streams["preempt_at"]
    assert uc_streams["ckpt_existed"]
    v2, p2 = uc_streams["verdict2"]
    assert v2 == "done"
    base = _step_lines(uc_streams["base_lines"])
    chaos = _step_lines(uc_streams["chaos_lines"])
    assert sorted(chaos) == sorted(base)
    for k, b in base.items():
        c = chaos[k]
        for f in ("outer", "inner", "rel_gap"):
            tol = 1e-9 * max(1.0, abs(b[f]))
            assert abs(b[f] - c[f]) <= tol, (k, f, b[f], c[f])
    terminals = [m for m in uc_streams["chaos_lines"]
                 if m.get("event") in ("done", "failed", "rejected")]
    assert len(terminals) == 1 and terminals[0]["event"] == "done"
    assert uc_streams["settled"] == (True, False)
    assert not os.path.exists(uc_streams["ckpt_path"])


# ---------------------------------------------------------------------------
# streaming reaper: per-step miss budget, not session wall clock
# ---------------------------------------------------------------------------
def test_steps_overdue_counts_whole_windows():
    s = Session(_spec(mpc_steps=3, step_deadline_s=0.2))
    assert s.streaming
    s.reset_step_anchor()
    now = time.perf_counter()
    assert s.steps_overdue(now + 0.19) == 0
    assert s.steps_overdue(now + 0.41) == 2
    s.note_step(0)      # a completed window re-arms the clock
    assert s.mpc_step == 1
    assert s.steps_overdue(time.perf_counter()) == 0
    # no per-step deadline -> the reaper never counts misses
    s2 = Session(_spec(mpc_steps=3))
    assert s2.steps_overdue(time.perf_counter() + 999.0) == 0


def _serve(tmp_path, engine, **kw):
    kw.setdefault("unix_path", str(tmp_path / "wheel.sock"))
    kw.setdefault("spool_dir", str(tmp_path / "spool"))
    kw.setdefault("multiplex", False)
    kw["engine"] = engine
    return WheelServer(ServeOptions(**kw)).start()


def test_stalled_stream_reaped_on_step_miss_budget(tmp_path):
    """A RUNNING stream that stops producing steps settles `failed`
    reason=step-deadline after step_miss_budget consecutive per-step
    deadlines — typed, never a hang."""
    eng = SyntheticEngine(iters=400, step_s=0.02)   # never note_steps
    srv = _serve(tmp_path, eng, step_miss_budget=2)
    try:
        cl = loadgen.ServeClient(srv.address, timeout=30.0)
        rec = loadgen.run_session(cl, _spec(
            mpc_steps=3, step_deadline_s=0.1))
        cl.close()
    finally:
        srv.stop()
    assert rec["outcome"] == "failed"
    assert rec["reason"] == "step-deadline"


def test_healthy_stream_outlives_session_wall_deadline(tmp_path):
    """A live stream's liveness unit is the STEP: deadline_s bounds its
    QUEUED wait only, so a stream running past the whole-session wall
    clock with a healthy step cadence is never wall-reaped."""
    eng = SyntheticEngine(iters=30, step_s=0.02)    # ~0.6 s run
    srv = _serve(tmp_path, eng)
    try:
        cl = loadgen.ServeClient(srv.address, timeout=30.0)
        rec = loadgen.run_session(cl, _spec(
            mpc_steps=2, step_deadline_s=60.0, deadline_s=0.2))
        cl.close()
    finally:
        srv.stop()
    assert rec["outcome"] == "done", rec


# ---------------------------------------------------------------------------
# per-step WFQ charge
# ---------------------------------------------------------------------------
def test_charge_step_bills_wfq_without_touching_quota():
    """Each completed window advances the tenant's virtual finish time
    like a fresh admission (so a long-lived stream keeps paying) but
    holds exactly its one quota slot."""
    q = FairQueue(max_queued=8, default_quota=2)
    a = Session(_spec(tenant="A", mpc_steps=4))
    q.submit(a)
    assert q.pop() is a
    st0 = q.stats()["tenants"]["A"]
    assert st0["inflight"] == 1 and st0["steps_charged"] == 0
    for _ in range(3):
        q.charge_step(a)
    st = q.stats()["tenants"]["A"]
    assert st["steps_charged"] == 3
    assert st["vfinish"] > st0["vfinish"]
    assert st["inflight"] == 1          # quota untouched
    # fairness effect: the charged tenant is now BEHIND a fresh one
    q.submit(Session(_spec(tenant="A")))
    q.submit(Session(_spec(tenant="B")))
    assert q.pop().tenant == "B"


# ---------------------------------------------------------------------------
# the committed r11 -> r12 gate
# ---------------------------------------------------------------------------
def test_bench_r11_r12_gate_and_milestones(tmp_path):
    """The committed pair gates green with both mpc_stream milestones
    met; a synthetic p99 regression and a resume-match slip both
    fail."""
    from mpisppy_tpu.telemetry import regress

    r11 = os.path.join(REPO, "BENCH_r11.json")
    r12 = os.path.join(REPO, "BENCH_r12.json")
    rep = regress.gate_paths(r11, r12)
    assert rep["ok"], rep["regressions"]
    ms = {r["metric"]: r for r in rep["milestones"]}
    ratio = ms["mpc_stream.warm_over_cold_ratio"]
    assert ratio["status"] == "met" and ratio["milestone"] == 0.6
    match = ms["mpc_stream.chaos.resumed_matched_frac"]
    assert match["status"] == "met" and match["milestone"] == 1.0

    # per-step latency is a gated serving metric: p99 +50% fails
    slow = json.load(open(r12))
    slow["parsed"]["mpc_stream"]["uc"]["step_latency_p99_s"] *= 1.5
    slow_path = tmp_path / "slow.json"
    slow_path.write_text(json.dumps(slow))
    rep2 = regress.gate_paths(r12, str(slow_path))
    assert not rep2["ok"]
    assert any(r["metric"].endswith("uc.step_latency_p99_s")
               for r in rep2["regressions"])

    # the resume story ratchets at 1.0 once landed
    slip = json.load(open(r12))
    slip["parsed"]["mpc_stream"]["chaos"]["resumed_matched_frac"] = 0.5
    slip_path = tmp_path / "slip.json"
    slip_path.write_text(json.dumps(slip))
    rep3 = regress.gate_paths(r12, str(slip_path))
    assert not rep3["ok"]


# ---------------------------------------------------------------------------
# the analyzer's mpc row (telemetry/analyze.py)
# ---------------------------------------------------------------------------
def test_analyze_summarizes_mpc_stream_rows():
    """The analyzer joins mpc-step/mpc-degraded events into an "mpc"
    report section (and leaves it None for non-stream runs)."""
    from mpisppy_tpu.telemetry import analyze as an

    def _row(kind, step, **data):
        return {"kind": kind, "run": "r1", "cyl": "mpc",
                "t_wall": 1.0 + step, "t_mono": 1.0 + step,
                "data": {"step": step, **data}}

    rows = [
        {"kind": "run-start", "run": "r1", "t_wall": 1.0, "t_mono": 1.0,
         "data": {"hub_class": "PHHub", "num_spokes": 2}},
        _row("mpc-step", 0, warm=False, cold_fallback=False,
             degraded=False, rel_gap=0.03, latency_s=9.0),
        _row("mpc-step", 1, warm=True, cold_fallback=False,
             degraded=False, rel_gap=0.02, latency_s=0.8),
        _row("mpc-step", 2, warm=False, cold_fallback=True,
             degraded=True, rel_gap=0.09, latency_s=1.2),
        _row("mpc-degraded", 2, rel_gap=0.09, gap_target=0.05),
        {"kind": "run-end", "run": "r1", "t_wall": 5.0, "t_mono": 5.0,
         "data": {"reason": "converged", "rel_gap": 0.09}},
    ]
    rep = an.analyze(an.build_run_model(rows))
    mpc = rep["mpc"]
    assert mpc["steps"] == 3 and mpc["last_step"] == 2
    assert mpc["warm"] == 1 and mpc["cold_fallbacks"] == 1
    assert mpc["degraded"] == 1 and mpc["degraded_at_steps"] == [2]
    assert mpc["step_latency_p50_s"] == pytest.approx(1.2)
    assert mpc["step_latency_max_s"] == pytest.approx(9.0)
    assert mpc["last_rel_gap"] == pytest.approx(0.09)
    assert "mpc stream: steps 3" in an.render_report(rep)

    # a plain wheel run carries no mpc section
    plain = an.analyze(an.build_run_model(rows[:1] + rows[-1:]))
    assert plain["mpc"] is None
    assert "mpc stream" not in an.render_report(plain)


def test_stream_trace_continuity_across_preempt_resume(uc_streams):
    """ISSUE 20 (satellite c): the preempted stream and its resume are
    ONE causal trace — every window's mpc-step span (including the
    twice-started window at the preemption point) parents under the
    same root, with zero orphan spans after the checkpoint restore."""
    from mpisppy_tpu.telemetry import spans

    rows = spans.load_rows(uc_streams["trace_path1"])
    tids = spans.trace_ids(rows)
    assert len(tids) == 1, tids
    rep = spans.assemble(rows, tids[0])
    assert rep["orphans"] == [], rep["orphans"]
    names = [sp["name"] for sp in rep["spans"]]
    assert names[0] == "request", names
    step_spans = [sp for sp in rep["spans"] if sp["name"] == "mpc-step"]
    # 4 windows + the re-solved preemption window start a 5th span
    assert len(step_spans) >= uc_streams["steps"], names
    root = rep["spans"][0]["span_id"]
    assert all(sp["parent_span_id"] == root for sp in step_spans)
    # both attempts' mpc-step rows carry the one trace id
    steps_seen = {r["data"].get("step") for r in rows
                  if r.get("kind") == "mpc-step"}
    assert steps_seen == set(range(uc_streams["steps"]))
