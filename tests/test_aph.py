# APH (async projective hedging) on farmer: convergence to the EF
# objective, partial dispatch, dynamic gamma, hub integration.
# The TPU analog of ref:mpisppy/tests/test_aph.py.
import numpy as np
import pytest

from mpisppy_tpu.algos import aph as aph_mod
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import farmer
from mpisppy_tpu.ops import pdhg

from test_farmer_ef_ph import farmer_specs, scipy_ef_solve


def _aph_opts(**kw):
    base = dict(
        default_rho=1.0, max_iterations=200, conv_thresh=2e-3,
        subproblem_windows=10,
        pdhg=pdhg.PDHGOptions(tol=1e-7, restart_period=40),
    )
    base.update(kw)
    return aph_mod.APHOptions(**base)


def test_aph_farmer_converges_to_ef():
    specs = farmer_specs(3)
    sobj, _ = scipy_ef_solve(specs)
    b = batch_mod.from_specs(specs)
    algo = aph_mod.APH(_aph_opts(), b)
    conv, eobj, tbound = algo.APH_main()
    # trivial bound is the wait-and-see expectation, a valid lower bound
    assert tbound <= sobj + 1.0
    assert conv <= 2e-3
    x1 = algo.first_stage_solution()
    np.testing.assert_allclose(x1, [170.0, 80.0, 250.0], atol=5.0)
    assert eobj == pytest.approx(sobj, rel=5e-3)


def test_aph_partial_dispatch_converges():
    # dispatch_frac=0.5: each iteration solves only the stalest half of
    # the scenario batch (ref:opt/aph.py APH_solve_loop dispatch_frac)
    specs = farmer_specs(6)
    sobj, _ = scipy_ef_solve(specs)
    b = batch_mod.from_specs(specs)
    algo = aph_mod.APH(_aph_opts(dispatch_frac=0.5, max_iterations=400), b)
    conv, eobj, tbound = algo.APH_main()
    assert conv <= 2e-3
    assert eobj == pytest.approx(sobj, rel=1e-2)
    # every real scenario must have been dispatched at some point
    last = np.asarray(algo.state.last_solved)[:b.num_real]
    assert (last > 0).all()


def test_aph_dispatch_mask_round_robins():
    specs = farmer_specs(8)
    b = batch_mod.from_specs(specs)
    algo = aph_mod.APH(_aph_opts(dispatch_frac=0.25, max_iterations=8,
                                 conv_thresh=0.0), b)
    algo.Iter0()
    algo.iterk_loop()
    last = np.asarray(algo.state.last_solved)
    # 2 of 8 scenarios per iteration for 8 iterations (iter 1 full):
    # everyone has been solved within the last 8/2 = 4 rounds
    assert (algo.state.it - last <= 4).all()


def test_aph_theta_positive_and_conv_decreases():
    specs = farmer_specs(3)
    b = batch_mod.from_specs(specs)
    algo = aph_mod.APH(_aph_opts(max_iterations=30, conv_thresh=0.0), b)
    algo.Iter0()
    convs, thetas = [], []
    for _ in range(30):
        algo.state = aph_mod.aph_iterk(b, algo.state, algo.options)
        convs.append(float(algo.state.conv))
        thetas.append(float(algo.state.theta))
    # theta fires (the projective step is actually taken)
    assert max(thetas) > 0.0
    finite = [c for c in convs if np.isfinite(c)]
    assert finite, "conv never became finite"
    assert finite[-1] < finite[0]


def test_aph_dynamic_gamma_runs():
    specs = farmer_specs(3)
    b = batch_mod.from_specs(specs)
    algo = aph_mod.APH(_aph_opts(use_dynamic_gamma=True,
                                 max_iterations=60), b)
    conv, eobj, _ = algo.APH_main()
    assert np.isfinite(float(algo.state.gamma))
    assert float(algo.state.gamma) > 0.0
    sobj, _ = scipy_ef_solve(specs)
    assert eobj == pytest.approx(sobj, rel=2e-2)


def test_aph_hub_with_spokes():
    # APH as hub with a Lagrangian outer + xhatxbar inner spoke
    from mpisppy_tpu.spin_the_wheel import WheelSpinner
    from mpisppy_tpu.utils import cfg_vanilla as vanilla
    from mpisppy_tpu.utils.config import Config

    specs = farmer_specs(3)
    sobj, _ = scipy_ef_solve(specs)
    b = batch_mod.from_specs(specs)
    cfg = Config()
    cfg.quick_assign("max_iterations", int, 60)
    cfg.quick_assign("rel_gap", float, 0.005)
    cfg.quick_assign("pdhg_tol", float, 1e-7)
    hub = vanilla.aph_hub(cfg, b)
    spokes = [vanilla.lagrangian_spoke(cfg), vanilla.xhatxbar_spoke(cfg)]
    wheel = WheelSpinner(hub, spokes)
    wheel.spin()
    assert wheel.BestOuterBound <= sobj + 1.0
    assert wheel.BestInnerBound >= sobj - 1.0
    abs_gap, rel_gap = wheel.spcomm.compute_gaps()
    assert rel_gap <= 0.005 + 1e-6
