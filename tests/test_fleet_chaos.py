# Fleet chaos storm (ISSUE 16 acceptance): seeded replica-fault mixes
# — kill one replica mid-traffic, slow another's heartbeat — against a
# running 3-replica FleetRouter with live traffic.  The fleet
# invariant under all of it: every submitted session observes EXACTLY
# ONE terminal outcome (the settle latch holds across the migration
# hand-off), killed-replica sessions live-migrate and finish (zero
# migrations lost), the dead replica is fenced, and global quotas are
# fully restored.  Fast 2-seed subset in tier-1, 12-seed soak under
# `slow`.
import json
import threading
import time

import numpy as np
import pytest

from mpisppy_tpu.fleet import DEAD, FleetOptions, FleetRouter
from mpisppy_tpu.resilience.faults import FaultPlan, ReplicaFault
from mpisppy_tpu.serve import SubmitRequest
from mpisppy_tpu.serve import loadgen
from mpisppy_tpu.serve.engine import SyntheticEngine

pytestmark = pytest.mark.chaos


def run_fleet_storm(seed: int, tmp_path) -> dict:
    """One seeded storm round: 3 replicas, 6 concurrent slots, all
    busy when a seed-chosen replica dies (its beat loop stops a few
    beats in) and a second replica turns slow-but-alive.  Healthy
    tenants acme/zeta stream their sessions to terminal through it
    all."""
    rng = np.random.default_rng(seed)
    kill_rid = f"r{int(rng.integers(0, 3))}"
    slow_rid = f"r{(int(kill_rid[1:]) + 1) % 3}"
    plan = FaultPlan(seed=seed, replicas=(
        ReplicaFault("kill", replica=kill_rid,
                     at_beats=(int(rng.integers(3, 6)),)),
        ReplicaFault("slow_heartbeat", replica=slow_rid,
                     delay_s=0.15),
    ))
    router = FleetRouter(FleetOptions(
        unix_path=str(tmp_path / f"fleet{seed}.sock"),
        n_replicas=3, max_running_per_replica=2,
        max_queued=32, max_queued_per_tenant=16, tenant_quota=4,
        trace_dir=str(tmp_path / f"traces{seed}"),
        spool_dir=str(tmp_path / f"spool{seed}"),
        heartbeat_s=0.05, drain_grace_s=10.0,
        default_deadline_s=30.0,
        engine_factory=lambda rid: SyntheticEngine(iters=40,
                                                   step_s=0.02),
        fault_plan=plan)).start()

    records: list = []
    rec_lock = threading.Lock()

    def client(tenant):
        cl = loadgen.ServeClient(router.address, timeout=45.0)
        try:
            for k in range(2):
                rec = loadgen.run_session(cl, SubmitRequest(
                    tenant=tenant, model="farmer", num_scens=3,
                    sla="latency" if k == 0 else "throughput"))
                with rec_lock:
                    records.append(rec)
        finally:
            cl.close()

    threads = [threading.Thread(target=client, args=(t,))
               for t in ("acme", "acme", "acme", "zeta", "zeta",
                         "zeta")]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    wall = time.perf_counter() - t0
    alive = [t.name for t in threads if t.is_alive()]
    # settle server-side terminal accounting before the asserts
    deadline = time.perf_counter() + 15.0
    while time.perf_counter() < deadline:
        states = router.stats()["states"]
        nonterminal = sum(v for k, v in states.items()
                          if k not in ("DONE", "FAILED", "REJECTED"))
        if nonterminal == 0:
            break
        time.sleep(0.05)
    stats = router.stats()
    sessions = dict(router._sessions)
    router.stop()
    fleet_log = tmp_path / f"traces{seed}" / "fleet.jsonl"
    rows = [json.loads(ln)
            for ln in fleet_log.read_text().splitlines()]
    return {"seed": seed, "plan": plan, "kill_rid": kill_rid,
            "records": records, "stats": stats, "wall": wall,
            "alive": alive, "sessions": sessions, "rows": rows,
            "trace_dir": str(tmp_path / f"traces{seed}")}


def assert_fleet_storm_invariants(r: dict) -> None:
    seed = r["seed"]
    assert not r["alive"], \
        f"DEADLOCK: {r['alive']} still alive (seed {seed})"
    # every client record terminal; healthy traffic all DONE (caps are
    # wide, the only disruption is the replica fault mix)
    assert len(r["records"]) == 12
    for rec in r["records"]:
        assert rec["outcome"] == "done", (seed, rec)
    # the kill fired and the replica is fenced
    assert any(s == "replica" and
               d.startswith(f"kill {r['kill_rid']}@")
               for s, d in r["plan"].fired), r["plan"].fired
    assert r["stats"]["health"][r["kill_rid"]] == DEAD
    # live migration exercised, nothing lost
    mig = r["stats"]["migration"]
    assert mig["started"] >= 1, \
        f"seed {seed}: kill landed after traffic, nothing migrated"
    assert mig["completed"] == mig["started"]
    assert mig["lost"] == 0
    # EXACTLY ONE terminal session-state row per session fleet-wide —
    # the exactly-once delivery contract across the hand-off races
    terminals: dict = {}
    for row in r["rows"]:
        d = row.get("data", {})
        if row["kind"] == "session-state" and \
                d.get("state") in ("DONE", "FAILED", "REJECTED"):
            terminals[d["session"]] = terminals.get(d["session"], 0) + 1
    assert len(terminals) == 12
    assert all(n == 1 for n in terminals.values()), \
        (seed, {k: v for k, v in terminals.items() if v > 1})
    # every server-side session terminal; global quota fully restored
    for s in r["sessions"].values():
        assert s.state in ("DONE", "FAILED", "REJECTED"), \
            (seed, s.sid, s.tenant, s.state)
    for name, t in r["stats"]["admission"]["tenants"].items():
        assert t["inflight"] == 0, (seed, name, t)
    assert r["wall"] < 60.0


def assert_trace_continuity(r: dict) -> None:
    """ISSUE 20 (satellite c): a live-migrated session's trace is ONE
    causal tree across the replica hand-off — zero orphan spans, the
    migration span on the critical path, and the bucket partition
    covering the client-observed latency within the 5% line."""
    from mpisppy_tpu.telemetry import spans

    seed = r["seed"]
    # twelve clients, twelve distinct traces, minted at submit
    trace_by_sid = {rec["session"]: rec["trace_id"]
                    for rec in r["records"]}
    assert len(set(trace_by_sid.values())) == 12
    # sessions that LIVE-migrated (queued re-dispatches never started,
    # so there is no segment to stitch)
    migrated = {row["data"]["session"] for row in r["rows"]
                if row["kind"] == "session-migrated"
                and not row["data"].get("queued")}
    assert migrated, f"seed {seed}: no live migration in the storm"
    rows = spans.load_rows(r["trace_dir"])
    for sid in sorted(migrated):
        rep = spans.assemble(rows, trace_by_sid[sid])
        assert rep["orphans"] == [], (seed, sid, rep["orphans"])
        names = [sp["name"] for sp in rep["spans"]]
        assert names[0] == "request", (seed, sid, names)
        assert "migration" in names, (seed, sid, names)
        assert rep["migrated_segments"] >= 1, (seed, sid)
        cp = rep["critical_path"]
        assert cp["buckets"].get("migration-gap", 0) > 0, \
            (seed, sid, cp["buckets"])
        assert cp["client_total_s"] is not None, (seed, sid)
        assert abs(cp["coverage"] - 1.0) <= 0.05, (seed, sid, cp)


def test_fleet_chaos_kill_replica_fast_seeded(tmp_path):
    """Tier-1 subset: two seeded storms (~15s wall together)."""
    for seed in (7, 31):
        r = run_fleet_storm(seed, tmp_path)
        assert_fleet_storm_invariants(r)
        assert_trace_continuity(r)


@pytest.mark.slow
def test_fleet_chaos_soak(tmp_path):
    """The long soak across the replica-fault mix space."""
    for seed in range(500, 512):
        assert_fleet_storm_invariants(run_fleet_storm(seed, tmp_path))
