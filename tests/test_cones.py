# Second-order-cone subsystem (ops/cones.py): Moreau projections
# against closed forms and scipy references, the conic PDHG kernel and
# its certificates, FBBT's conservative norm-ball relaxation of SOC
# blocks, metadata threading through batch/EF assembly, and the ccopf
# --soc (branch-flow SOCP relaxation) workload end to end on the
# virtual 8-device CPU mesh.
import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import ccopf
from mpisppy_tpu.ops import boxqp, cones, pdhg
from mpisppy_tpu.ops.fbbt import fbbt


# ---------------------------------------------------------------------------
# projection unit tests
# ---------------------------------------------------------------------------
def np_soc_project(v):
    """Closed-form numpy reference: Euclidean projection of (t; z) onto
    the second-order cone {(t, z): ||z|| <= t}."""
    t, z = float(v[0]), np.asarray(v[1:], np.float64)
    nz = float(np.linalg.norm(z))
    if nz <= t:
        return np.asarray(v, np.float64).copy()
    if nz <= -t:
        return np.zeros_like(np.asarray(v, np.float64))
    a = 0.5 * (t + nz)
    return np.concatenate([[a], a * z / max(nz, 1e-30)])


def one_block_spec(dim, m_extra=0):
    """ConeSpec with a single SOC block on rows [0, dim) and m_extra
    trailing box rows."""
    return cones.cone_spec(dim + m_extra, [np.arange(dim)])


def test_project_closed_form_cases():
    spec = one_block_spec(3, m_extra=2)
    cases = [
        (np.array([2.0, 1.0, 1.0]), None),            # interior: identity
        (np.array([np.sqrt(2.0), 1.0, 1.0]), None),   # boundary: identity
        (np.array([-2.0, 1.0, 1.0]), np.zeros(3)),    # polar: zero
        # reflection: ||z|| = 5 > |t|, alpha = (0 + 5)/2 = 2.5
        (np.array([0.0, 3.0, 4.0]), np.array([2.5, 1.5, 2.0])),
        (np.array([-1.0, 0.0, 3.0]), np.array([1.0, 0.0, 1.0])),
    ]
    for v_blk, want in cases:
        if want is None:
            want = v_blk
        v = jnp.asarray(np.concatenate([v_blk, [7.0, -3.0]]), jnp.float32)
        out = np.asarray(cones.project_soc_rows(spec, v))
        np.testing.assert_allclose(out[:3], want, atol=1e-6)
        # box rows pass through untouched
        np.testing.assert_allclose(out[3:], [7.0, -3.0], atol=0.0)


def test_project_matches_scipy_reference():
    from scipy.optimize import minimize

    rng = np.random.default_rng(0)
    spec = one_block_spec(5)
    for _ in range(6):
        v = rng.normal(scale=2.0, size=5)
        ours = np.asarray(
            cones.project_soc_rows(spec, jnp.asarray(v, jnp.float64)))

        def dist(p, v=v):
            return np.sum((p - v) ** 2)

        ref = minimize(
            dist, np_soc_project(v) + 1e-3,
            constraints=[{"type": "ineq",
                          "fun": lambda p: p[0] ** 2
                          - np.sum(p[1:] ** 2)},
                         {"type": "ineq", "fun": lambda p: p[0]}],
            method="SLSQP", tol=1e-12)
        np.testing.assert_allclose(ours, ref.x, atol=1e-4)
        np.testing.assert_allclose(ours, np_soc_project(v), atol=1e-5)


def test_moreau_identity_and_orthogonality_batched():
    """v = Proj_K(v) + Proj_{-K}(v) with the parts orthogonal, on a
    batched ragged multi-block layout (box rows interleaved)."""
    rng = np.random.default_rng(1)
    # rows 0-2 block A, row 3 box, rows 4-8 block B, row 9 box
    spec = cones.cone_spec(10, [np.arange(3), np.arange(4, 9)])
    v = jnp.asarray(rng.normal(scale=3.0, size=(4, 10)), jnp.float64)
    pk = np.asarray(cones.project_soc_rows(spec, v))
    pp = np.asarray(cones.project_polar_rows(spec, v))
    soc = np.asarray(spec.is_soc)
    np.testing.assert_allclose((pk + pp)[:, soc], np.asarray(v)[:, soc],
                               atol=1e-5)
    for blk in (slice(0, 3), slice(4, 9)):
        dots = np.sum(pk[:, blk] * pp[:, blk], axis=-1)
        np.testing.assert_allclose(dots, 0.0, atol=1e-4)
        # projections land in their cones
        assert np.all(np.linalg.norm(pk[:, blk][:, 1:], axis=-1)
                      <= pk[:, blk][:, 0] + 1e-5)
        assert np.all(np.linalg.norm(pp[:, blk][:, 1:], axis=-1)
                      <= -pp[:, blk][:, 0] + 1e-5)


def test_dual_prox_equals_division_form():
    """dual_prox's division-free form == w - sigma*Proj_set(w/sigma)
    computed naively per row set (box interval / shifted cone)."""
    rng = np.random.default_rng(2)
    spec = cones.cone_spec(7, [np.arange(2, 6)])
    w = rng.normal(scale=2.0, size=(3, 7))
    sigma = rng.uniform(0.2, 3.0, size=(3, 1))
    b = rng.normal(size=7)
    bl = np.where(np.asarray(spec.is_soc), b, -1.0)
    bu = np.where(np.asarray(spec.is_soc), b, 2.0)
    got = np.asarray(cones.dual_prox(
        spec, jnp.asarray(w), jnp.asarray(sigma), jnp.asarray(bl),
        jnp.asarray(bu)))
    for i in range(3):
        ws = w[i] / sigma[i]
        proj = np.clip(ws, bl, bu)
        proj[2:6] = b[2:6] + np_soc_project(ws[2:6] - b[2:6])
        np.testing.assert_allclose(got[i], w[i] - sigma[i] * proj,
                                   atol=1e-5)


def test_cone_spec_validation():
    with pytest.raises(ValueError, match="overlaps"):
        cones.cone_spec(6, [np.arange(3), np.arange(2, 6)])
    with pytest.raises(ValueError, match="head"):
        cones.cone_spec(6, [np.array([4])])
    # duplicate rows WITHIN a block collapse in the fancy assignments
    # and would silently build a looser cone — rejected at build time
    with pytest.raises(ValueError, match="duplicate"):
        cones.cone_spec(8, [np.array([5, 7, 7])])
    spec = cones.cone_spec(4, [np.arange(3)])
    with pytest.raises(ValueError, match="shift"):
        cones.validate_against_bounds(
            spec, np.zeros(4), np.array([0.0, 1.0, 0.0, 5.0]))
    # bl == bu on SOC rows is fine; box rows may differ freely
    cones.validate_against_bounds(
        spec, np.zeros(4), np.array([0.0, 0.0, 0.0, 5.0]))


# ---------------------------------------------------------------------------
# conic PDHG + certificates
# ---------------------------------------------------------------------------
def conic_lp_batch(caps=(1.5, 0.9)):
    """max x1 + x2 - 0.1 x0  s.t.  ||(x1, x2)|| <= x0 <= cap_s, as a
    min problem — optimum at x0 = cap, x1 = x2 = cap/sqrt(2).  Rows:
    one inactive box row then the 3-row SOC block (head first)."""
    S = len(caps)
    n = 3
    c = np.tile([0.1, -1.0, -1.0], (S, 1))
    A = np.array([[0.0, 1.0, 1.0],     # box: x1 + x2 <= 10
                  [1.0, 0.0, 0.0],     # head: t = x0
                  [0.0, 1.0, 0.0],     # tail z1 = x1
                  [0.0, 0.0, 1.0]])    # tail z2 = x2
    bl = np.tile([-np.inf, 0.0, 0.0, 0.0], (S, 1))
    bu = np.tile([10.0, 0.0, 0.0, 0.0], (S, 1))
    l = np.tile([0.0, -5.0, -5.0], (S, 1))  # noqa: E741
    u = np.stack([[cap, 5.0, 5.0] for cap in caps])
    spec = cones.cone_spec(4, [np.arange(1, 4)])
    qp = boxqp.BoxQP(
        c=jnp.asarray(c, jnp.float32), q=jnp.zeros((S, n), jnp.float32),
        A=jnp.asarray(A, jnp.float32),
        bl=jnp.asarray(bl, jnp.float32), bu=jnp.asarray(bu, jnp.float32),
        l=jnp.asarray(l, jnp.float32), u=jnp.asarray(u, jnp.float32),
        cones=spec)
    x_star = np.stack([[cap, cap / np.sqrt(2.0), cap / np.sqrt(2.0)]
                       for cap in caps])
    obj_star = np.sum(c * x_star, axis=-1)
    return qp, x_star, obj_star


def test_conic_pdhg_solves_and_certifies():
    qp, x_star, obj_star = conic_lp_batch()
    opts = pdhg.PDHGOptions(tol=1e-7, max_iters=40_000)
    st = pdhg.solve(qp, opts, pdhg.init_state(qp, opts))
    assert bool(np.all(np.asarray(st.done)))
    x = np.asarray(st.x)
    np.testing.assert_allclose(x, x_star, atol=2e-4)
    rp, rd, gap = (np.asarray(r)
                   for r in boxqp.kkt_residuals(qp, st.x, st.y))
    assert rp.max() <= 1e-5 and rd.max() <= 1e-5 and gap.max() <= 1e-5
    # dual iterates lie in the polar cone by construction (dual_prox)
    dcr = np.asarray(cones.dual_cone_residual_rows(qp.cones, st.y))
    np.testing.assert_allclose(dcr, 0.0, atol=1e-6)   # 0 up to f32 ulps
    # weak duality: the certified Fenchel bound sits just under the
    # primal objective at the optimum
    obj = np.asarray(jnp.sum(qp.c * st.x, axis=-1))
    dual = np.asarray(boxqp.certified_dual_bound(qp, st.x, st.y))
    assert np.all(dual <= obj + 1e-4)
    np.testing.assert_allclose(dual, obj_star, atol=2e-3)


def test_conic_matches_scipy_reference():
    from scipy.optimize import minimize

    qp, _, _ = conic_lp_batch(caps=(1.3,))
    opts = pdhg.PDHGOptions(tol=1e-7, max_iters=40_000)
    st = pdhg.solve(qp, opts, pdhg.init_state(qp, opts))
    c = np.asarray(qp.c)[0]

    ref = minimize(
        lambda x: float(c @ x), np.array([1.0, 0.5, 0.5]),
        constraints=[{"type": "ineq",
                      "fun": lambda x: x[0] - np.linalg.norm(x[1:])}],
        bounds=[(0.0, 1.3), (-5.0, 5.0), (-5.0, 5.0)],
        method="SLSQP", tol=1e-12)
    assert float(jnp.sum(qp.c[0] * st.x[0])) == pytest.approx(
        float(ref.fun), abs=5e-4)


def test_conic_dual_residual_gates_certificates():
    """A hand-built y OFF the polar cone must show up in rel_dual (the
    conic dual-feasibility residual is folded into kkt_residuals), so
    every downstream bound-publication gate inherits the check."""
    qp, x_star, _ = conic_lp_batch()
    opts = pdhg.PDHGOptions(tol=1e-7, max_iters=40_000)
    st = pdhg.solve(qp, opts, pdhg.init_state(qp, opts))
    _, rd_good, _ = boxqp.kkt_residuals(qp, st.x, st.y)
    # push the SOC block's dual INTO the cone interior (not the polar):
    y_bad = st.y.at[:, 1].set(3.0)
    _, rd_bad, _ = boxqp.kkt_residuals(qp, st.x, y_bad)
    assert float(np.max(np.asarray(rd_good))) <= 1e-5
    assert float(np.min(np.asarray(rd_bad))) >= 0.1
    # certified_dual_bound projects such a y back to the polar cone
    # first, so it stays a VALID (if weaker) bound rather than garbage
    obj = np.asarray(jnp.sum(qp.c * st.x, axis=-1))
    dual_bad = np.asarray(boxqp.certified_dual_bound(qp, st.x, y_bad))
    assert np.all(dual_bad <= obj + 1e-4)


def test_unboundedness_recession_accepts_conic_ray():
    """The recession cone of b + K is K: a direction whose block lies
    IN the cone is a legitimate ray (the box bl==bu test would demand
    Ad == 0 and miss it)."""
    # min -x0 with x0 free above, SOC block (x0; x1) i.e. x0 >= |x1|
    spec = cones.cone_spec(2, [np.arange(2)])
    qp = boxqp.BoxQP(
        c=jnp.asarray([[-1.0, 0.0]], jnp.float32),
        q=jnp.zeros((1, 2), jnp.float32),
        A=jnp.eye(2, dtype=jnp.float32),
        bl=jnp.zeros((1, 2), jnp.float32),
        bu=jnp.zeros((1, 2), jnp.float32),
        l=jnp.asarray([[0.0, -50.0]], jnp.float32),
        u=jnp.asarray([[jnp.inf, 50.0]], jnp.float32),
        cones=spec)
    d = jnp.asarray([[1.0, 0.0]], jnp.float32)   # ray: grow the head
    ok = boxqp.unboundedness_certificate(qp, d)
    assert bool(np.asarray(ok)[0])


# ---------------------------------------------------------------------------
# FBBT on SOC blocks
# ---------------------------------------------------------------------------
def test_fbbt_soc_norm_ball_bounds():
    """head t = x0 in [0, 5], tail z = x1 unbounded: FBBT must derive
    |x1| <= 5 (norm-ball) — and must NOT treat the bl==bu==0 storage as
    an equality (which would pin x1 = 0, an invalid tightening)."""
    spec = cones.cone_spec(2, [np.arange(2)])
    qp = boxqp.BoxQP(
        c=jnp.zeros((1, 2), jnp.float32), q=jnp.zeros((1, 2), jnp.float32),
        A=jnp.eye(2, dtype=jnp.float32),
        bl=jnp.zeros((1, 2), jnp.float32),
        bu=jnp.zeros((1, 2), jnp.float32),
        l=jnp.asarray([[0.0, -jnp.inf]], jnp.float32),
        u=jnp.asarray([[5.0, jnp.inf]], jnp.float32),
        cones=spec)
    l1, u1 = fbbt(qp, n_sweeps=3)
    l1, u1 = np.asarray(l1)[0], np.asarray(u1)[0]
    assert l1[1] == pytest.approx(-5.0, abs=1e-5)
    assert u1[1] == pytest.approx(5.0, abs=1e-5)
    # every point of the cone's slice (t, z) with |z| <= t <= 5 remains
    # inside the tightened box — validity, not just non-collapse
    assert l1[0] <= 0.0 + 1e-6 and u1[0] >= 5.0 - 1e-5


def test_fbbt_soc_unbounded_head_leaves_tails_alone():
    spec = cones.cone_spec(2, [np.arange(2)])
    qp = boxqp.BoxQP(
        c=jnp.zeros((1, 2), jnp.float32), q=jnp.zeros((1, 2), jnp.float32),
        A=jnp.eye(2, dtype=jnp.float32),
        bl=jnp.zeros((1, 2), jnp.float32),
        bu=jnp.zeros((1, 2), jnp.float32),
        l=jnp.asarray([[0.0, -jnp.inf]], jnp.float32),
        u=jnp.asarray([[jnp.inf, jnp.inf]], jnp.float32),
        cones=spec)
    l1, u1 = fbbt(qp, n_sweeps=2)
    assert not np.isfinite(np.asarray(l1)[0, 1])
    assert not np.isfinite(np.asarray(u1)[0, 1])


def test_fbbt_soc_bounds_stay_valid_on_ccopf():
    """FBBT-tightened boxes on the ccopf SOC workload must contain the
    conic optimum (the sweeps' norm-ball relaxation is conservative)."""
    specs = [ccopf.scenario_creator(nm, soc=True)
             for nm in ccopf.scenario_names_creator(3)]
    b = batch_mod.from_specs(specs, tree=ccopf.make_tree((3, 1)))
    opts = pdhg.PDHGOptions(tol=1e-6, max_iters=30_000)
    st = pdhg.solve(b.qp, opts, pdhg.init_state(b.qp, opts))
    assert bool(np.all(np.asarray(st.done)))
    l1, u1 = fbbt(b.qp, n_sweeps=3, d_col=b.d_col)
    x = np.asarray(st.x)
    slack = 1e-3
    assert np.all(x >= np.asarray(l1) - slack)
    assert np.all(x <= np.asarray(u1) + slack)
    assert np.all(np.asarray(l1) <= np.asarray(u1) + 1e-6)


# ---------------------------------------------------------------------------
# metadata threading: batch / EF assembly, scaling invariance
# ---------------------------------------------------------------------------
def test_batch_carries_cone_spec_and_ruiz_respects_blocks():
    specs = [ccopf.scenario_creator(nm, soc=True)
             for nm in ccopf.scenario_names_creator(3)]
    b = batch_mod.from_specs(specs, tree=ccopf.make_tree((3, 1)))
    spec = b.qp.cones
    assert spec is not None
    assert spec.num_cones == 9 and spec.max_dim == 4    # 3 lines x 3 stages
    # Ruiz equilibration kept the bl == bu == b storage exact on SOC
    # rows (block-uniform row scales scale the shift consistently)
    soc = np.asarray(spec.is_soc)
    np.testing.assert_allclose(np.asarray(b.qp.bl)[:, soc],
                               np.asarray(b.qp.bu)[:, soc], atol=0.0)
    # the derived QPs (fixed nonants / W-shifts) inherit the spec
    xhat = jnp.zeros((b.tree.num_nodes, b.num_nonants), b.qp.c.dtype)
    assert b.with_fixed_nonants(xhat).cones is spec


def test_batch_rejects_mismatched_cone_patterns():
    specs = [ccopf.scenario_creator(nm, soc=True)
             for nm in ccopf.scenario_names_creator(3)]
    broken = dataclasses.replace(
        specs[1], soc_blocks=[blk + 1 for blk in specs[1].soc_blocks])
    with pytest.raises(ValueError, match="pattern"):
        batch_mod.from_specs([specs[0], broken, specs[2]],
                             tree=ccopf.make_tree((3, 1)))


def test_ef_assembly_offsets_cone_blocks():
    from mpisppy_tpu.algos.ef import build_ef

    specs = [ccopf.scenario_creator(nm, soc=True)
             for nm in ccopf.scenario_names_creator(3)]
    efp = build_ef(specs, tree=ccopf.make_tree((3, 1)))
    spec = efp.qp.cones
    assert spec is not None and spec.num_cones == 3 * 9
    m_per = specs[0].A.shape[0]
    seg = np.asarray(spec.seg)
    soc = np.asarray(spec.is_soc)
    # scenario s's blocks live in rows [s*m_per, (s+1)*m_per) and the
    # trailing nonant link rows carry no cones
    for s in range(3):
        blk_ids = np.unique(seg[s * m_per:(s + 1) * m_per][
            soc[s * m_per:(s + 1) * m_per]])
        assert blk_ids.min() >= s * 9 and blk_ids.max() < (s + 1) * 9
    assert not soc[3 * m_per:].any()
    np.testing.assert_allclose(np.asarray(efp.qp.bl)[soc],
                               np.asarray(efp.qp.bu)[soc], atol=0.0)


# ---------------------------------------------------------------------------
# Pallas window kernel: conic dual prox via membership-matrix dots
# ---------------------------------------------------------------------------
def test_pallas_conic_window_matches_xla():
    from mpisppy_tpu.ops import pdhg_pallas

    specs = [ccopf.scenario_creator(nm, soc=True)
             for nm in ccopf.scenario_names_creator(9)]
    b = batch_mod.from_specs(specs, tree=ccopf.make_tree((3, 3)))
    qp = b.qp
    assert pdhg_pallas.supported(qp)
    opts = pdhg.PDHGOptions(tol=1e-6)
    st = pdhg.init_state(qp, opts)
    tau = st.omega / st.Lnorm
    sigma = 1.0 / (st.omega * st.Lnorm)
    stt = st
    xs = jnp.zeros_like(st.x)
    ys = jnp.zeros_like(st.y)
    for _ in range(8):
        stt = pdhg._pdhg_iter(qp, stt, tau, sigma)
        xs = xs + stt.x
        ys = ys + stt.y
    xo, yo, xso, yso = pdhg_pallas.run_window(
        qp, st.x, st.y, jnp.zeros_like(st.x), jnp.zeros_like(st.y),
        tau, sigma, jnp.zeros(st.x.shape[0], bool), 8, interpret=True)
    np.testing.assert_allclose(np.asarray(xo), np.asarray(stt.x),
                               atol=2e-6)
    np.testing.assert_allclose(np.asarray(yo), np.asarray(stt.y),
                               atol=2e-6)
    np.testing.assert_allclose(np.asarray(xso), np.asarray(xs), atol=5e-6)
    np.testing.assert_allclose(np.asarray(yso), np.asarray(ys), atol=5e-6)


# ---------------------------------------------------------------------------
# ccopf --soc: the cylinder wheel on the conic workload
# ---------------------------------------------------------------------------
def test_ccopf_soc_wheel_end_to_end():
    """The full hub + Lagrangian + xhat wheel on the branch-flow SOCP
    relaxation: a certified gap closes, and the published bounds'
    conic dual-feasibility residual is zero (the certificate the conic
    Fenchel accounting rests on)."""
    from mpisppy_tpu.cylinders.spoke import (
        LagrangianOuterBound, XhatXbarInnerBound,
    )
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.cylinders import PHHub
    from mpisppy_tpu.spin_the_wheel import WheelSpinner

    specs = [ccopf.scenario_creator(nm, soc=True)
             for nm in ccopf.scenario_names_creator(9)]
    b = batch_mod.from_specs(specs, tree=ccopf.make_tree((3, 3)))
    opts = ph_mod.PHOptions(default_rho=10.0, max_iterations=40,
                            conv_thresh=0.0,
                            pdhg=pdhg.PDHGOptions(tol=1e-6))
    hub = {"hub_class": PHHub, "hub_kwargs": {"options": {"rel_gap": 5e-3}},
           "opt_class": ph_mod.PH,
           "opt_kwargs": {"options": opts, "batch": b}}
    spokes = [{"spoke_class": LagrangianOuterBound,
               "opt_kwargs": {"options": {}}},
              {"spoke_class": XhatXbarInnerBound,
               "opt_kwargs": {"options": {}}}]
    wheel = WheelSpinner(hub, spokes).spin()
    outer = wheel.BestOuterBound
    inner = wheel.BestInnerBound
    assert np.isfinite(outer) and np.isfinite(inner)
    assert outer <= inner + 1e-6
    _, rel_gap = wheel.spcomm.compute_gaps()
    assert rel_gap <= 5e-3
    # conic dual feasibility of the hub's final subproblem duals: PDHG
    # iterates never leave the polar cone, so the residual the
    # certificates fold into rel_dual must be exactly zero here
    st = wheel.spcomm.opt.state
    dcr = np.asarray(cones.dual_cone_residual_rows(b.qp.cones,
                                                   st.solver.y))
    np.testing.assert_allclose(dcr, 0.0, atol=1e-6)   # 0 up to f32 ulps


def test_ccopf_soc_relaxation_is_meaningful():
    """The SOC blocks actually bind: dropping them (same rows treated
    as free box rows) must strictly lower the optimum — i.e. the conic
    constraint is doing work, not decoration."""
    specs = [ccopf.scenario_creator(nm, soc=True)
             for nm in ccopf.scenario_names_creator(3)]
    b = batch_mod.from_specs(specs, tree=ccopf.make_tree((3, 1)))
    opts = pdhg.PDHGOptions(tol=1e-7, max_iters=40_000)
    st = pdhg.solve(b.qp, opts, pdhg.init_state(b.qp, opts))
    obj_soc = float(b.expectation(
        jnp.sum(b.qp.c * st.x + 0.5 * b.qp.q * st.x * st.x, axis=-1)))
    # free the SOC rows entirely (bounds to +-inf, no cones)
    soc = np.asarray(b.qp.cones.is_soc)
    bl = np.asarray(b.qp.bl).copy()
    bu = np.asarray(b.qp.bu).copy()
    bl[:, soc] = -np.inf
    bu[:, soc] = np.inf
    qp_free = dataclasses.replace(
        b.qp, bl=jnp.asarray(bl, b.qp.bl.dtype),
        bu=jnp.asarray(bu, b.qp.bu.dtype), cones=None)
    st2 = pdhg.solve(qp_free, opts, pdhg.init_state(qp_free, opts))
    obj_free = float(b.expectation(
        jnp.sum(qp_free.c * st2.x + 0.5 * qp_free.q * st2.x * st2.x,
                axis=-1)))
    assert obj_free < obj_soc - 1e-3 * max(1.0, abs(obj_soc))
