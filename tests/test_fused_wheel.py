# Fused wheel (algos.fused_wheel): the round-4 answer to the one-queue
# serialization of classic spokes — Lagrangian/xhat/slam/shuffle bound
# planes ride INSIDE the hub's jitted step with fixed warm budgets.
# Validity contract tested here: every bound the fused planes publish is
# gated by the same certificates as the standalone spokes, so the
# certified gap brackets the EF objective exactly like the classic wheel
# (ref:mpisppy/tests/test_with_cylinders.py analog).
import os

import numpy as np
import pytest

from mpisppy_tpu.algos import fused_wheel as fw
from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.cylinders.spoke import (
    FusedLagrangianOuterBound, FusedSlamHeuristic, FusedXhatShuffleInnerBound,
    FusedXhatXbarInnerBound,
)
from mpisppy_tpu.cylinders import PHHub
from mpisppy_tpu.models import farmer, sslp
from mpisppy_tpu.ops import pdhg
from mpisppy_tpu.spin_the_wheel import WheelSpinner

FARMER_EF_OBJ = -108390.0


def farmer_batch(num_scens=3):
    specs = [farmer.scenario_creator(nm, num_scens=num_scens)
             for nm in farmer.scenario_names_creator(num_scens)]
    return batch_mod.from_specs(specs)


def sslp_batch(num_scens=16):
    inst = sslp.synthetic_instance(5, 15, seed=0)
    specs = [sslp.scenario_creator(nm, instance=inst, num_scens=num_scens,
                                   lp_relax=True)
             for nm in sslp.scenario_names_creator(num_scens)]
    return batch_mod.from_specs(specs)


def fused_hub_dict(batch, rel_gap=5e-3, max_iterations=150,
                   wheel_options=None, hub_extra=None, rho=1.0):
    opts = ph_mod.PHOptions(default_rho=rho, max_iterations=max_iterations,
                            conv_thresh=0.0, subproblem_windows=10,
                            pdhg=pdhg.PDHGOptions(tol=1e-7))
    hub_opts = {"rel_gap": rel_gap}
    hub_opts.update(hub_extra or {})
    return {
        "hub_class": PHHub,
        "hub_kwargs": {"options": hub_opts},
        "opt_class": fw.FusedPH,
        "opt_kwargs": {"options": opts, "batch": batch,
                       "wheel_options": wheel_options
                       or fw.FusedWheelOptions()},
    }


ALL_FUSED_SPOKES = [
    {"spoke_class": FusedLagrangianOuterBound, "opt_kwargs": {"options": {}}},
    {"spoke_class": FusedXhatXbarInnerBound, "opt_kwargs": {"options": {}}},
    {"spoke_class": FusedXhatShuffleInnerBound,
     "opt_kwargs": {"options": {}}},
    {"spoke_class": FusedSlamHeuristic, "opt_kwargs": {"options": {}}},
]


def test_fused_wheel_farmer_certified_gap():
    batch = farmer_batch(3)
    wopts = fw.FusedWheelOptions(slam_windows=2, shuffle_windows=4,
                                 slam_sense_max=False,  # farmer: acreage min
                                 lag_pdhg=pdhg.PDHGOptions(tol=1e-7),
                                 xhat_pdhg=pdhg.PDHGOptions(
                                     tol=1e-7, omega0=0.1,
                                     restart_period=80))
    ws = WheelSpinner(fused_hub_dict(batch, wheel_options=wopts),
                      ALL_FUSED_SPOKES).spin()
    inner, outer = ws.BestInnerBound, ws.BestOuterBound
    assert np.isfinite(inner) and np.isfinite(outer)
    assert outer <= inner + 2e-3 * abs(inner)
    slack = 2e-3 * abs(FARMER_EF_OBJ)
    assert outer <= FARMER_EF_OBJ + slack
    assert inner >= FARMER_EF_OBJ - slack
    rel_gap = (inner - outer) / abs(inner)
    assert rel_gap <= 5e-3 + 1e-6
    assert ws.spcomm._iter < 150
    # the incumbent winner's solution is retrievable
    nodes = ws.spcomm.best_nonants()
    assert nodes.shape[1] == batch.num_nonants


def test_fused_wheel_sslp_matches_classic_bracket():
    batch = sslp_batch(16)
    ws = WheelSpinner(fused_hub_dict(batch, rel_gap=1e-2,
                                     max_iterations=200, rho=20.0),
                      ALL_FUSED_SPOKES[:2]).spin()
    inner, outer = ws.BestInnerBound, ws.BestOuterBound
    assert np.isfinite(inner) and np.isfinite(outer)
    # certified gap reached and bracket is consistent
    assert (inner - outer) / abs(inner) <= 1e-2 + 1e-6
    assert outer <= inner


def test_split_dispatch_matches_monolithic():
    """The split-dispatch pipeline (default) and the monolithic fused
    program run the same plane math — the Lagrangian trajectory is
    identical (tight tolerance), while the inner bound may differ
    slightly because split mode freezes the evaluated candidate across
    exchanges (see FusedWheelOptions.xhat_give_up).  Both must produce
    a consistent certified bracket."""
    batch = sslp_batch(16)
    results = {}
    for split in (True, False):
        wopts = fw.FusedWheelOptions(split_dispatch=split,
                                     adapt_budgets=False,
                                     slam_windows=2, shuffle_windows=2)
        ws = WheelSpinner(
            fused_hub_dict(batch, rel_gap=1e-2, max_iterations=60,
                           rho=20.0, wheel_options=wopts),
            ALL_FUSED_SPOKES).spin()
        results[split] = (ws.BestOuterBound, ws.BestInnerBound)
    (o1, i1), (o2, i2) = results[True], results[False]
    assert np.isfinite(o1) and np.isfinite(i1)
    assert abs(o1 - o2) <= 1e-3 * max(1.0, abs(o2))
    assert abs(i1 - i2) <= 5e-3 * max(1.0, abs(i2))
    for outer, inner in results.values():
        assert outer <= inner + 1e-6 * max(1.0, abs(inner))


def test_plane_budget_controller():
    b = fw._PlaneBudget(full=8, lean=2, stall_after=3)
    assert b.windows() == 8
    b.observe(True)
    b.observe(True)
    assert b.windows() == 8   # streak below threshold
    b.observe(True)
    assert b.windows() == 2   # lean after stall_after certified exchanges
    b.observe(True)
    assert b.windows() == 2   # stays lean while certified
    b.observe(False)          # certification lost -> full immediately
    assert b.windows() == 8
    # uncertified exchanges keep full budget (still chasing the gate)
    b2 = fw._PlaneBudget(full=4, lean=1, stall_after=2)
    b2.observe(False)
    b2.observe(False)
    assert b2.windows() == 4
    # disabled plane stays disabled
    b3 = fw._PlaneBudget(full=0, lean=1, stall_after=2)
    assert b3.windows() == 0


def test_adaptive_budgets_engage_on_stalled_wheel():
    """Once the planes certify streak-long, the controllers must drop
    every enabled plane to its lean budget."""
    batch = farmer_batch(3)
    wopts = fw.FusedWheelOptions(
        adapt_stall=2, slam_windows=2, shuffle_windows=2,
        slam_sense_max=False,
        lag_pdhg=pdhg.PDHGOptions(tol=1e-7),
        xhat_pdhg=pdhg.PDHGOptions(tol=1e-7, omega0=0.1,
                                   restart_period=80))
    # an unreachable gap target forces the wheel to run out its
    # iterations well past bound convergence
    ws = WheelSpinner(fused_hub_dict(batch, rel_gap=-1.0,
                                     max_iterations=40,
                                     wheel_options=wopts),
                      ALL_FUSED_SPOKES).spin()
    budgets = ws.opt._budgets
    # the outer-bound plane does NOT lean by default (bound quality
    # gates termination — see FusedWheelOptions.adapt_lag_budget)
    assert budgets["lag"].windows() == wopts.lag_windows
    assert budgets["xhat"].windows() == wopts.lean_xhat_windows
    # bounds are still a certified bracket after running lean
    assert ws.BestOuterBound <= ws.BestInnerBound + 1e-6


def test_fused_wheel_checkpoint_resume(tmp_path):
    batch = sslp_batch(16)
    ckpt = str(tmp_path / "wheel.ckpt.npz")
    hub_extra = {"checkpoint_path": ckpt, "checkpoint_every_s": 0.0}
    # phase 1: a short run that cannot certify yet
    ws1 = WheelSpinner(fused_hub_dict(batch, rel_gap=1e-4,
                                      max_iterations=12,
                                      hub_extra=hub_extra, rho=20.0),
                       ALL_FUSED_SPOKES[:2]).spin()
    assert os.path.exists(ckpt)
    it1, ob1 = ws1.spcomm._iter, ws1.BestOuterBound

    # phase 2: fresh objects, restore, continue — the resumed wheel
    # must pick up the counters/bounds and keep improving
    ws2 = WheelSpinner(fused_hub_dict(batch, rel_gap=1e-4,
                                      max_iterations=40,
                                      hub_extra=hub_extra, rho=20.0),
                       ALL_FUSED_SPOKES[:2]).build()
    ws2.spcomm.load_checkpoint(ckpt)
    # checkpoints write from a background thread, so the saved iteration
    # may lag the final counter — it must be a valid earlier sync point
    assert 0 < ws2.spcomm._iter <= it1
    # the final flush after the last checkpoint may have improved the
    # bound by up to one pipelined iteration — restored must be a valid,
    # no-better snapshot of the final bookkeeping
    assert np.isfinite(ws2.spcomm.BestOuterBound)
    assert ws2.spcomm.BestOuterBound <= ob1 + 1e-6
    ws2.spin()
    assert ws2.spcomm._iter > it1
    assert ws2.BestOuterBound >= ob1 - 1e-6
    # trivial bound was not re-folded (Iter0 skipped on resume)
    assert ws2.opt._iter > 12


# ---------------------------------------------------------------------------
# ADVICE r5 regressions
# ---------------------------------------------------------------------------
def test_gather_qp_ell_by_field_layout():
    """_gather_qp must never scenario-gather an EllMatrix's shared cols
    index array, even when m == S (the tree_map-over-leading-dim
    heuristic silently corrupted the tail-rescue sub-batch)."""
    import dataclasses

    import jax.numpy as jnp

    from mpisppy_tpu.ops import boxqp, sparse

    S = m = 4   # the trap: row count equals scenario count
    n, k = 3, 2
    rng = np.random.default_rng(0)
    cols = jnp.asarray(rng.integers(0, n, size=(m, k)), jnp.int32)
    vals_b = jnp.asarray(rng.normal(size=(S, m, k)), jnp.float32)
    qp = boxqp.BoxQP(
        c=jnp.zeros((S, n), jnp.float32), q=jnp.zeros((S, n), jnp.float32),
        A=sparse.EllMatrix(vals=vals_b, cols=cols, n=n),
        bl=jnp.zeros((S, m), jnp.float32), bu=jnp.ones((S, m), jnp.float32),
        l=jnp.zeros((S, n), jnp.float32), u=jnp.ones((S, n), jnp.float32))
    idx = jnp.asarray([2, 0])
    sub = fw._gather_qp(qp, idx, S)
    np.testing.assert_array_equal(np.asarray(sub.A.cols), np.asarray(cols))
    np.testing.assert_array_equal(np.asarray(sub.A.vals),
                                  np.asarray(vals_b)[np.asarray(idx)])
    # a SHARED vals (m, k) — leading dim S-sized — must stay shared too
    qp2 = dataclasses.replace(
        qp, A=sparse.EllMatrix(vals=vals_b[0], cols=cols, n=n))
    sub2 = fw._gather_qp(qp2, idx, S)
    assert sub2.A.vals.ndim == 2
    np.testing.assert_array_equal(np.asarray(sub2.A.vals),
                                  np.asarray(vals_b)[0])


def test_scalar_pipeline_depth_shared_constant():
    """The scalar-cache pipeline depth is a single named constant and
    the split-dispatch freshness check reads it (hard-coding the depth
    in two places misattributes landed/dead flags when one changes)."""
    import inspect

    assert fw.SCALAR_PIPELINE_DEPTH == 2
    # the freshness check lives in the shared candidate policy, and
    # both the split pipeline and the async wheel route through the
    # one spoke-plane dispatch helper that applies it
    assert "SCALAR_PIPELINE_DEPTH" in inspect.getsource(
        fw.FusedPH._next_xhat_cand)
    assert "_next_xhat_cand" in inspect.getsource(
        fw.FusedPH._dispatch_spoke_planes)
    assert "_dispatch_spoke_planes" in inspect.getsource(
        fw.FusedPH._iterk_split)


def test_eval_step_comp_is_safety_scaled():
    """The fused planes' published inner values carry the SAFETY-SCALED
    first-order compensation (approximately-certified semantics — see
    xhat.COMP_SAFETY)."""
    import inspect

    from mpisppy_tpu.algos import xhat as xhat_mod

    assert xhat_mod.COMP_SAFETY >= 2.0
    assert "COMP_SAFETY" in inspect.getsource(fw._eval_step)
