# admmWrapper / stoch_admmWrapper: consensus ADMM as (multistage) PH
# with variable probabilities (ref:utils/admmWrapper.py,
# utils/stoch_admmWrapper.py; tests ref:test_admmWrapper.py).
import numpy as np
import pytest

from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.core.batch import ScenarioSpec
from mpisppy_tpu.ops import pdhg
from mpisppy_tpu.utils.admmWrapper import AdmmWrapper
from mpisppy_tpu.utils.stoch_admmWrapper import Stoch_AdmmWrapper


def _region_creator(name):
    """Two regions sharing consensus variable 'f':
      A: min 1/2 f^2 - 2 f + yA      , 0 <= yA <= 1,  f - yA <= 3
      B: min 1/2 f^2 - 6 f + 2 yB    , yB >= f - 3  (as f - yB <= 3)
    merged optimum: f* = 4 (d/df of f^2 - 8f), yA* = 1, yB* = 1.
    """
    if name == "A":
        spec = ScenarioSpec(
            name="A",
            c=np.array([-2.0, 1.0]),
            q=np.array([1.0, 0.0]),
            A=np.array([[1.0, -1.0]]),
            bl=np.array([-np.inf]), bu=np.array([3.0]),
            l=np.array([0.0, 0.0]), u=np.array([10.0, 1.0]),
            nonant_idx=np.array([0], np.int32),
        )
        return spec, ["f", "yA"]
    spec = ScenarioSpec(
        name="B",
        c=np.array([-6.0, 2.0]),
        q=np.array([1.0, 0.0]),
        A=np.array([[1.0, -1.0]]),
        bl=np.array([-np.inf]), bu=np.array([3.0]),
        l=np.array([0.0, 0.0]), u=np.array([10.0, 10.0]),
        nonant_idx=np.array([0], np.int32),
    )
    return spec, ["f", "yB"]


def _merged_optimum():
    # min over (f, yA, yB): f^2 - 8f + yA + 2 yB
    #   s.t. f - yA <= 3, f - yB <= 3, boxes
    from scipy.optimize import minimize
    res = minimize(
        lambda v: v[0] ** 2 - 8 * v[0] + v[1] + 2 * v[2],
        x0=np.array([1.0, 0.5, 0.5]),
        bounds=[(0, 10), (0, 1), (0, 10)],
        constraints=[{"type": "ineq",
                      "fun": lambda v: 3 - v[0] + v[1]},
                     {"type": "ineq",
                      "fun": lambda v: 3 - v[0] + v[2]}])
    assert res.success
    return res.fun, res.x


def test_admm_wrapper_consensus():
    wrapper = AdmmWrapper({}, ["A", "B"], _region_creator,
                          {"A": ["f"], "B": ["f"]})
    b = wrapper.make_batch()
    assert b.var_prob is not None
    # weight 1/2 for the shared consensus var in both regions
    np.testing.assert_allclose(np.asarray(b.var_prob)[:, 0], [0.5, 0.5])

    opts = ph_mod.PHOptions(default_rho=2.0, max_iterations=200,
                            conv_thresh=1e-4, subproblem_windows=10,
                            pdhg=pdhg.PDHGOptions(tol=1e-7,
                                                  restart_period=40))
    algo = ph_mod.PH(opts, b)
    conv, eobj, tb = algo.ph_main()
    assert conv <= 1e-4
    fstar_obj, xstar = _merged_optimum()
    # PH expectation = (1/2) * sum_r (2 * f_r) = the admm sum
    assert eobj == pytest.approx(fstar_obj, abs=5e-2)
    f_consensus = float(algo.state.xbar_nodes[0, 0])
    assert f_consensus == pytest.approx(xstar[0], abs=1e-2)


def test_admm_wrapper_missing_var_raises():
    with pytest.raises(RuntimeError, match="not in the model"):
        AdmmWrapper({}, ["A", "B"], _region_creator,
                    {"A": ["f", "ghost"], "B": ["f"]})


def _stoch_region_creator(snm, rnm, d=None):
    """Two scenarios scaling region B's linear consensus reward:
    first-stage z (cost 1, z >= f - 2 as f - z <= 2), consensus f."""
    dval = {"S0": -6.0, "S1": -10.0}[snm]
    if rnm == "A":
        spec = ScenarioSpec(
            name=f"{snm}_A",
            c=np.array([0.25, -2.0]),   # cols: [z, f] (cheap z: the
            #                             optimum is strict, z* = 4)
            q=np.array([0.0, 1.0]),
            A=np.array([[-1.0, 1.0]]),  # f - z <= 2
            bl=np.array([-np.inf]), bu=np.array([2.0]),
            l=np.zeros(2), u=np.array([10.0, 10.0]),
            nonant_idx=np.array([0], np.int32),
        )
        return spec, ["z", "f"]
    spec = ScenarioSpec(
        name=f"{snm}_B",
        c=np.array([0.25, dval]),
        q=np.array([0.0, 1.0]),
        A=np.array([[-1.0, 1.0]]),
        bl=np.array([-np.inf]), bu=np.array([2.0]),
        l=np.zeros(2), u=np.array([10.0, 10.0]),
        nonant_idx=np.array([0], np.int32),
    )
    return spec, ["z", "f"]


def test_stoch_admm_wrapper_tree_and_consensus():
    wrapper = Stoch_AdmmWrapper(
        {}, ["A", "B"], ["S0", "S1"], _stoch_region_creator,
        {"A": ["f"], "B": ["f"]})
    assert wrapper.split_admm_stoch_subproblem_scenario_name(
        "ADMM_STOCH_S0_B") == ("S0", "B")
    b = wrapper.make_batch()
    assert b.tree.num_stages == 3
    assert b.num_scenarios == 4          # 2 scenarios x 2 regions
    assert b.num_nonants == 2            # [z (stage-1), f (stage-2)]

    opts = ph_mod.PHOptions(default_rho=2.0, max_iterations=300,
                            conv_thresh=2e-4, subproblem_windows=10,
                            pdhg=pdhg.PDHGOptions(tol=1e-7,
                                                  restart_period=40))
    algo = ph_mod.PH(opts, b)
    conv, eobj, tb = algo.ph_main()
    assert conv <= 2e-4
    # per-scenario consensus f*: minimizes f^2 + (-2 + dval) f with
    # f <= z + 2; z shared across scenarios (cost 2 total across
    # regions after the R-scaling cancels in expectation)
    xb = np.asarray(algo.state.xbar_nodes)
    f_s0 = xb[1, 1]
    f_s1 = xb[2, 1]
    # S1's reward is steeper, so its consensus flow must be larger
    assert f_s1 > f_s0 + 0.2
    # z is a ROOT quantity: equal view everywhere, and binding for S1
    z = xb[0, 0]
    assert f_s1 <= z + 2.0 + 1e-3
