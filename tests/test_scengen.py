# Seeded scenario synthesis (mpisppy_tpu/scengen; ISSUE 14,
# docs/scengen.md): the bit-identity contract between host
# materialization and device synthesis, the VirtualBatch wheel path,
# sharded synthesis, in-kernel Pallas tile synthesis, the
# confidence-interval provenance plumbing, and the BENCH_r09 gate.
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpisppy_tpu import scengen
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import aircond, farmer, sslp, uc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _assert_bit_identical(prog):
    """from_specs over the program's host specs (template scaling) must
    equal device synthesis bit-for-bit in every leaf."""
    bh = batch_mod.from_specs(prog.to_specs(), tree=prog.tree,
                              scaling=prog.scaling)
    bd = scengen.materialize(prog)
    lh, th = jax.tree_util.tree_flatten(bh)
    ld, td = jax.tree_util.tree_flatten(bd)
    assert th == td
    for a, b in zip(lh, ld):
        a, b = np.asarray(a), np.asarray(b)
        assert a.shape == b.shape
        assert np.array_equal(a, b, equal_nan=True)


def test_bit_identity_farmer():
    # farmer is the per-scenario-A case (yields enter the matrix)
    _assert_bit_identical(farmer.scenario_program(6, seed=3))


def test_bit_identity_sslp():
    _assert_bit_identical(sslp.scenario_program(
        5, seed=1, n_servers=3, n_clients=8))


def test_bit_identity_uc():
    # shared sparse (ELL) A, RHS-only randomness
    _assert_bit_identical(uc.scenario_program(
        3, seed=2, n_gens=2, n_hours=4))


def test_bit_identity_aircond_multistage():
    # node-keyed draws: scenarios through a node share its demand
    prog = aircond.scenario_program(4, seed=5, branching_factors=(2, 2))
    _assert_bit_identical(prog)
    b = scengen.materialize(prog)
    # nonanticipativity of the DATA: scenarios 0,1 share the stage-2
    # node, so their stage-2 balance RHS (row 1) must coincide
    bl = np.asarray(b.qp.bl)
    assert bl[0, 1] == bl[1, 1]
    assert bl[2, 1] == bl[3, 1]
    assert bl[0, 1] != bl[2, 1]  # different nodes draw differently


def test_start_window_shifts_draws():
    """Draw s depends only on (base_seed, start + s) — the replication
    windows of two programs overlap exactly where their index windows
    do (compare raw draws: the template Scaling anchors at `start`, so
    the scaled batches legitimately differ)."""
    p0 = farmer.scenario_program(4, seed=3, start=0)
    p2 = farmer.scenario_program(4, seed=3, start=2)
    assert np.array_equal(p0.spec_at(2).A, p2.spec_at(2).A)
    assert np.array_equal(p0.spec_at(3).A, p2.spec_at(3).A)
    assert not np.array_equal(p0.spec_at(2).A, p0.spec_at(3).A)


def test_virtual_batch_surface_and_bytes():
    prog = farmer.scenario_program(64, seed=0)
    vb = scengen.virtual_batch(prog)
    assert vb.num_scenarios == 64 and vb.num_real == 64
    assert vb.qp.c.shape == (64, 12) and vb.qp.c.dtype == jnp.float32
    lb, ub = vb.nonant_box()
    assert lb.shape == (3,) and np.all(ub > lb)
    # the decoupling witness: the resident pytree is far smaller than
    # what host materialization would keep resident
    assert vb.persistent_bytes() < vb.materialized_bytes() / 4
    # pad rows carry probability zero and clone the last real scenario
    vbp = scengen.virtual_batch(prog, pad_to=48)
    assert vbp.num_scenarios == 96 and vbp.num_real == 64
    b = scengen.virtual._realize_jit(vbp)
    assert float(jnp.sum(vbp.p)) == pytest.approx(1.0, abs=1e-6)
    assert np.asarray(vbp.p)[64:].sum() == 0.0
    assert np.array_equal(np.asarray(b.qp.A)[64:],
                          np.broadcast_to(np.asarray(b.qp.A)[63],
                                          (32, 7, 12)))


def test_virtual_wheel_bounds_bit_match_materialized():
    """The acceptance contract's wheel half: the fused wheel on a
    VirtualBatch publishes the same certified bounds as on the
    materialized batch (same bits in, same program structure)."""
    from mpisppy_tpu.algos import fused_wheel as fw
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.ops import pdhg

    prog = farmer.scenario_program(12, seed=7)
    vb = scengen.virtual_batch(prog)
    bm = scengen.materialize(prog)
    opts = ph_mod.PHOptions(
        subproblem_windows=2, iter0_windows=30,
        pdhg=pdhg.PDHGOptions(tol=1e-6, restart_period=40))
    ko = ph_mod.kernel_opts(opts)
    wopts = fw.FusedWheelOptions(lag_windows=2, xhat_windows=2,
                                 slam_windows=0, shuffle_windows=0,
                                 split_dispatch=False)
    rho = jnp.ones(vb.num_nonants, jnp.float32)
    sv, tbv, cv = fw.fused_iter0(vb, rho, ko, wopts)
    sm, tbm, cm = fw.fused_iter0(bm, rho, ko, wopts)
    assert float(tbv) == float(tbm) and bool(cv) == bool(cm)
    for _ in range(3):
        sv = fw.fused_iterk(vb, sv, ko, wopts)
        sm = fw.fused_iterk(bm, sm, ko, wopts)
    assert np.array_equal(np.asarray(sv.scalars), np.asarray(sm.scalars))


def test_sharded_synthesis_collectives_and_values():
    """Each device folds in only its shard's indices; the compiled step
    communicates, and the reductions match the unsharded wheel."""
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.ops import pdhg
    from mpisppy_tpu.parallel import mesh as mesh_mod

    prog = farmer.scenario_program(16, seed=0)
    opts = ph_mod.PHOptions(
        subproblem_windows=2, iter0_windows=20,
        pdhg=pdhg.PDHGOptions(tol=1e-6, restart_period=40))
    rho = jnp.ones(3, jnp.float32)

    vb = scengen.virtual_batch(prog)
    st, tb, _ = ph_mod.ph_iter0(vb, rho, opts)

    mesh = mesh_mod.make_mesh(8)
    vbs = mesh_mod.shard_batch(scengen.virtual_batch(prog, pad_to=8),
                               mesh)
    sts, tbs, _ = ph_mod.ph_iter0(vbs, rho, opts)
    assert float(tbs) == pytest.approx(float(tb), rel=1e-5)
    hlo = ph_mod.ph_iterk.lower(vbs, sts, opts).compile().as_text()
    assert "all-reduce" in hlo or "all-gather" in hlo


def test_pallas_tile_synth_bit_matches_dma_window():
    """The synth/compute pipeline engine: data operands generated in
    the kernel equal the DMA-streamed materialized window bit-for-bit
    (interpret mode)."""
    from mpisppy_tpu.ops import pdhg_pallas

    prog = sslp.scenario_program(200, seed=4, n_servers=3, n_clients=8,
                                 lp_relax=True)
    vb = scengen.virtual_batch(prog)
    bm = scengen.materialize(prog)
    S, n = bm.qp.c.shape
    m = bm.qp.bl.shape[-1]
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(S, n)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(S, m)), jnp.float32)
    zx, zy = jnp.zeros_like(x), jnp.zeros_like(y)
    tau = jnp.full((S,), 0.05, jnp.float32)
    sig = jnp.full((S,), 0.05, jnp.float32)
    done = jnp.zeros((S,), bool)
    ref = pdhg_pallas.run_window(bm.qp, x, y, zx, zy, tau, sig, done,
                                 n_iters=4, pipeline=True,
                                 interpret=True)
    qp_proxy, ts = scengen.window_inputs(vb)
    out = pdhg_pallas.run_window(qp_proxy, x, y, zx, zy, tau, sig,
                                 done, n_iters=4, pipeline=True,
                                 interpret=True, synth=ts)
    for a, b in zip(ref, out):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_tile_synth_rejects_unsupported():
    from mpisppy_tpu.ops import pdhg_pallas

    prog = uc.scenario_program(3, seed=0, n_gens=2, n_hours=4)
    with pytest.raises(ValueError, match="shared dense"):
        scengen.window_inputs(scengen.virtual_batch(prog))
    fprog = sslp.scenario_program(8, seed=0, n_servers=3, n_clients=4)
    qp_proxy, ts = scengen.window_inputs(scengen.virtual_batch(fprog))
    x = jnp.zeros((8, qp_proxy.n), jnp.float32)
    y = jnp.zeros((8, qp_proxy.A.shape[0]), jnp.float32)
    sv = jnp.ones((8,), jnp.float32)
    with pytest.raises(ValueError, match="pipelined"):
        pdhg_pallas.run_window(qp_proxy, x, y, x, y, sv, sv,
                               jnp.zeros((8,), bool), n_iters=2,
                               pipeline=False, interpret=True, synth=ts)


def test_gap_estimators_scengen_provenance():
    """CI replications draw through scengen keys when the cfg opts in,
    and record the seed-provenance window; the legacy stream stays the
    default for raw configs."""
    from mpisppy_tpu.confidence_intervals import ciutils
    from mpisppy_tpu.utils.config import Config

    xhat = np.array([170.0, 80.0, 250.0])
    cfg = Config()
    cfg.quick_assign("num_scens", int, 8)
    names = farmer.scenario_names_creator(8, start=40)
    est_legacy = ciutils.gap_estimators(xhat, farmer, names, cfg)
    assert "seed_provenance" not in est_legacy

    cfg.quick_assign("use_scengen", bool, True)
    est = ciutils.gap_estimators(xhat, farmer, names, cfg)
    prov = est["seed_provenance"]
    assert prov["scheme"] == "threefry2x32/fold_in"
    assert prov["program"] == "farmer"
    assert prov["start"] == 40 and prov["num_scenarios"] == 8
    assert est["seed"] == 48  # seed bookkeeping unchanged
    # (exact reproducibility of the draws from the provenance window is
    # covered by the bit-identity + start-window tests above)

    # the cfg's MODEL kwargs reach the program: a crops_multiplier=2
    # candidate (C=6 nonants) must be evaluated on a crops_multiplier=2
    # sample, not a silently-default one
    cfg2 = Config()
    cfg2.quick_assign("num_scens", int, 6)
    cfg2.quick_assign("use_scengen", bool, True)
    cfg2.quick_assign("crops_multiplier", int, 2)
    est_k2 = ciutils.gap_estimators(
        np.tile(xhat, 2), farmer,
        farmer.scenario_names_creator(6, start=10), cfg2)
    assert est_k2["seed_provenance"]["program"] == "farmer"
    assert est_k2["xstar"].shape == (6,)


def test_mpc_advance_rekey_bit_identity():
    """ScenarioProgram.advance(k) (ISSUE 19): the MPC step re-key is
    bit-identical to folding the base key to k directly, absolute (not
    cumulative), carried in provenance, and the advanced program keeps
    the host/device bit-identity contract."""
    prog = uc.scenario_program(3, seed=2, n_gens=2, n_hours=4)
    p2 = prog.advance(2)
    # absolute semantics + identity short-circuits (jit-static hygiene:
    # the same step must not key a fresh compile)
    assert prog.advance(0) is prog and p2.advance(2) is p2
    assert p2.advance(5).step == 5
    assert np.array_equal(
        np.asarray(p2.base_key()),
        np.asarray(jax.random.fold_in(jax.random.PRNGKey(2), 2)))
    # step k resamples: the uc RHS draws differ across steps...
    b0, b2 = scengen.materialize(prog), scengen.materialize(p2)
    assert not np.array_equal(np.asarray(b0.qp.bl), np.asarray(b2.qp.bl))
    # ...but the advanced program still materializes bit-identically on
    # host and device (the resharding-invariance witness: synthesis
    # folds per scenario from the SAME advanced base key either way)
    _assert_bit_identical(p2)
    assert p2.provenance()["step"] == 2
    assert "step" not in prog.provenance()


def test_aircond_program_rejects_start_window():
    # node keys derive from the within-tree path, so an index window
    # would replay the same tree — replications must vary `seed`
    with pytest.raises(ValueError, match="vary `seed`"):
        aircond.scenario_program(4, seed=1, start=4,
                                 branching_factors=(2, 2))


def test_scengen_event_and_metrics():
    from mpisppy_tpu.telemetry import metrics as metrics_mod
    from mpisppy_tpu.telemetry.bus import EventBus

    events = []

    class Sink:
        def handle(self, e):
            events.append(e)

    bus = EventBus()
    bus.subscribe(Sink())
    before = metrics_mod.REGISTRY.get("scengen_virtual_batches_total")
    vb = scengen.virtual_batch(farmer.scenario_program(32, seed=0),
                               bus=bus)
    assert metrics_mod.REGISTRY.get(
        "scengen_virtual_batches_total") == before + 1
    (ev,) = [e for e in events if e.kind == "scengen"]
    assert ev.data["program"] == "farmer"
    assert ev.data["num_scenarios"] == 32
    assert ev.data["persistent_bytes"] == vb.persistent_bytes()


def test_bench_r08_r09_gate_and_milestones(tmp_path):
    """The committed r08->r09 pair gates green; the scengen MILESTONES
    bind on the committed artifact (ratio >= 0.9 met, S=1M presence),
    and a synthetic ratio regression / dropped S=1M phase fails."""
    from mpisppy_tpu.telemetry import regress

    r08 = os.path.join(REPO, "BENCH_r08.json")
    r09 = os.path.join(REPO, "BENCH_r09.json")
    rep = regress.gate_paths(r08, r09)
    assert rep["ok"], rep["regressions"]
    ms = {r["metric"]: r for r in rep["milestones"]}
    ratio_row = ms["wheel_scengen.synth_vs_materialized_ratio"]
    assert ratio_row["status"] == "met"
    assert ms["wheel_scengen.sweep.S1000000.iters_per_sec"][
        "status"] == "met"
    # the certified S>=1M witness is present in the committed artifact
    art = regress.load_artifact(r09)
    cert = art["wheel_scengen"]["certified_run"]
    assert cert["scenarios"] >= 1_000_000 and cert["certified"]

    # ratchet: a later artifact slipping the ratio below 0.9 fails
    slip = json.load(open(r09))
    slip["parsed"]["wheel_scengen"]["synth_vs_materialized_ratio"] = 0.5
    slip_path = tmp_path / "slip.json"
    slip_path.write_text(json.dumps(slip))
    rep2 = regress.gate_paths(r09, str(slip_path))
    assert not rep2["ok"]
    assert any(r["metric"].endswith("synth_vs_materialized_ratio")
               for r in rep2["regressions"])

    # dropping the S=1M sweep entry is MISSING, not a quiet un-gate
    gone = json.load(open(r09))
    gone["parsed"]["wheel_scengen"]["sweep"] = \
        gone["parsed"]["wheel_scengen"]["sweep"][:1]
    gone_path = tmp_path / "gone.json"
    gone_path.write_text(json.dumps(gone))
    rep3 = regress.gate_paths(r09, str(gone_path))
    assert not rep3["ok"]
    assert any(r.get("status") == "MISSING"
               and "S1000000" in r["metric"]
               for r in rep3["regressions"])


@pytest.mark.slow
def test_bit_identity_sslp_wheel_bounds():
    """The sslp half of the acceptance contract (slow: extra fused
    compiles at an sslp shape)."""
    from mpisppy_tpu.algos import fused_wheel as fw
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.ops import pdhg

    prog = sslp.scenario_program(12, seed=2, n_servers=3, n_clients=8,
                                 lp_relax=True)
    vb = scengen.virtual_batch(prog)
    bm = scengen.materialize(prog)
    opts = ph_mod.PHOptions(
        default_rho=20.0, subproblem_windows=2, iter0_windows=30,
        pdhg=pdhg.PDHGOptions(tol=1e-6, restart_period=40))
    ko = ph_mod.kernel_opts(opts)
    wopts = fw.FusedWheelOptions(lag_windows=2, xhat_windows=2,
                                 slam_windows=0, shuffle_windows=0,
                                 split_dispatch=False)
    rho = jnp.full((vb.num_nonants,), 20.0, jnp.float32)
    sv, tbv, _ = fw.fused_iter0(vb, rho, ko, wopts)
    sm, tbm, _ = fw.fused_iter0(bm, rho, ko, wopts)
    assert float(tbv) == float(tbm)
    for _ in range(4):
        sv = fw.fused_iterk(vb, sv, ko, wopts)
        sm = fw.fused_iterk(bm, sm, ko, wopts)
    assert np.array_equal(np.asarray(sv.scalars), np.asarray(sm.scalars))


@pytest.mark.slow
def test_large_s_synthesis_smoke():
    """S = 200k synthesized PH step on CPU: resident bytes stay at the
    program-pytree scale while the step runs (the 1M acceptance run
    lives in bench.py wheel_scengen / BENCH_r09.json)."""
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.ops import pdhg

    prog = farmer.scenario_program(200_000, seed=0)
    vb = scengen.virtual_batch(prog)
    assert vb.persistent_bytes() < 2_000_000  # ~MBs, not ~100s of MB
    opts = ph_mod.PHOptions(
        subproblem_windows=1, iter0_windows=4,
        pdhg=pdhg.PDHGOptions(tol=1e-6, restart_period=40))
    st, tb, _ = ph_mod.ph_iter0(vb, jnp.ones(3, jnp.float32), opts)
    st = ph_mod.ph_iterk(vb, st, opts)
    assert np.isfinite(float(st.conv)) and np.isfinite(float(tb))
