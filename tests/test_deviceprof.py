# Device-time observability (ISSUE 7; mpisppy_tpu/telemetry/
# {deviceprof,roofline,watch}.py): the chrome-trace + xplane-sidecar
# parsers over the COMMITTED jax.profiler captures, the roofline
# report's acceptance metrics (trace-derived measured_stream_gbps
# anchored to BENCH_DETAIL.json, overlap_frac in [0,1]), the device
# gates in `telemetry gate` (overlap/bandwidth regressions exit 2),
# the `telemetry watch --once` smoke against the golden farmer trace,
# and the ProfilerSession hardening contract.
import gzip
import json
import os
import subprocess
import sys

import pytest

from mpisppy_tpu.telemetry import deviceprof as dp
from mpisppy_tpu.telemetry import regress, roofline

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
GOLDEN_DEVICE = os.path.join(HERE, "fixtures",
                             "golden_device_trace.json.gz")
GOLDEN_FARMER = os.path.join(HERE, "fixtures",
                             "golden_farmer_trace.jsonl")
PROFILE_S100K = os.path.join(REPO, "profile_trace_S100000")
PROFILE_S10K = os.path.join(REPO, "profile_trace_S10000")
CLI = [sys.executable, "-m", "mpisppy_tpu.telemetry"]
ENV = {"PATH": "/usr/bin:/bin:/usr/local/bin", "JAX_PLATFORMS": "cpu",
       "HOME": os.path.expanduser("~")}


def _run(args, **kw):
    return subprocess.run(CLI + args, capture_output=True, text=True,
                          cwd=REPO, env=ENV, timeout=120, **kw)


# ---------------------------------------------------------------------------
# parser: committed real captures (trace.json.gz + xplane.pb sidecar)
# ---------------------------------------------------------------------------
def test_parse_committed_capture_with_xplane_sidecar():
    caps = dp.discover_captures(PROFILE_S100K)
    assert caps, "committed S=100k capture missing"
    cap = caps[-1]
    assert cap["trace"].endswith(".trace.json.gz")
    assert cap["xplane"] and cap["xplane"].endswith(".xplane.pb")
    tl = dp.build_timeline(cap)
    assert tl.device_name.startswith("/device:")
    assert len(tl.ops) > 1000
    assert len(tl.modules) == 1
    # the sidecar delivered the per-memory-space split and the
    # device's own peaks — no tensorflow/protobuf import involved
    assert tl.has_memory_spaces
    assert tl.peak_hbm_gbps == pytest.approx(819.16, abs=0.1)
    assert tl.peak_tflops == pytest.approx(202.7, abs=0.1)
    assert "tensorflow" not in sys.modules
    # DMA spans were recovered and carry bytes
    assert tl.dma and sum(d.bytes for d in tl.dma) > 1e9


def test_hbm_split_consistent_with_bytes_accessed():
    tl = dp.build_timeline(PROFILE_S10K)
    checked = 0
    for op in tl.ops:
        if op.hbm_bytes is None or not op.bytes_accessed:
            continue
        # per-space bytes can never exceed the all-space total
        assert op.hbm_bytes <= op.bytes_accessed + 1024
        checked += 1
    assert checked > 500


# ---------------------------------------------------------------------------
# roofline: the ISSUE 7 acceptance criteria
# ---------------------------------------------------------------------------
def test_roofline_s100k_stream_matches_committed_bench_detail():
    """`analyze --profile-dir profile_trace_S100000` must report a
    trace-derived measured_stream_gbps within 10% of the committed
    BENCH_DETAIL.json value (485.1) and an overlap_frac in [0, 1]."""
    with open(os.path.join(REPO, "BENCH_DETAIL.json")) as f:
        committed = json.load(f)["measured_mfu"]["S100000"]
    rep = roofline.roofline_path(PROFILE_S100K)
    got = rep["measured_stream_gbps"]
    want = committed["measured_stream_gbps"]
    assert abs(got - want) / want <= 0.10, (got, want)
    assert 0.0 <= rep["overlap_frac"] <= 1.0
    # device time per iteration is bounded by the committed host
    # sec/iter (host adds dispatch + python overhead on top)
    assert 0.0 < rep["device_sec_per_iter"] <= committed["sec_per_iter"]
    # the S=100k step is Pallas-dominated: the report must disclose the
    # byte-opaque fraction instead of presenting a false roofline
    assert rep["opaque_frac"] > 0.5
    assert any("byte-opaque" in n for n in rep["notes"])


def test_roofline_s10k_sane():
    rep = roofline.roofline_path(PROFILE_S10K)
    assert rep["byte_source"] == "xplane-memory-spaces"
    # achieved HBM flux can never exceed the device's physical peak
    assert 0 < rep["achieved_hbm_gbps"] <= rep["peak_hbm_gbps"]
    assert 0.0 <= rep["overlap_frac"] <= 1.0
    assert rep["mfu"] is None or 0.0 <= rep["mfu"] <= 1.0


def test_roofline_golden_fixture_json_only_fallback():
    rep = roofline.roofline(dp.build_timeline(GOLDEN_DEVICE))
    assert rep["byte_source"] == "bytes-accessed-all-spaces"
    assert rep["measured_stream_gbps"] > 0
    assert 0.0 <= rep["overlap_frac"] <= 1.0
    assert rep["dma"]["spans"] > 0
    # the fallback must announce its VMEM-reuse caveat
    assert any("bytes_accessed" in n for n in rep["notes"])


def test_xplane_walker_rejects_garbage(tmp_path):
    bad = tmp_path / "vm.xplane.pb"
    bad.write_bytes(os.urandom(4096))
    assert dp._read_xplane_sidecar(str(bad)) is None
    # a corrupt sidecar degrades to the json-only path, not a crash
    with gzip.open(GOLDEN_DEVICE, "rt") as f:
        raw = f.read()
    trace = tmp_path / "vm.trace.json.gz"
    with gzip.open(trace, "wt") as f:
        f.write(raw)
    tl = dp.build_timeline({"dir": str(tmp_path), "trace": str(trace),
                            "xplane": str(bad)})
    assert tl.ops and not tl.has_memory_spaces


# ---------------------------------------------------------------------------
# CI gate: device-metric regressions must exit 2 (ISSUE 7 satellite)
# ---------------------------------------------------------------------------
def _golden_report(tmp_path):
    rep = roofline.roofline(dp.build_timeline(GOLDEN_DEVICE))
    p = tmp_path / "device_golden.json"
    p.write_text(json.dumps(rep))
    return rep, p


@pytest.mark.parametrize("key,factor", [("overlap_frac", 0.5),
                                        ("measured_stream_gbps", 0.8)])
def test_gate_fails_synthetic_device_regression(tmp_path, key, factor):
    rep, p = _golden_report(tmp_path)
    bad = dict(rep)
    bad[key] = rep[key] * factor
    pb = tmp_path / f"device_bad_{key}.json"
    pb.write_text(json.dumps(bad))
    out = _run(["gate", str(p), str(pb)])
    assert out.returncode == 2, out.stdout + out.stderr
    assert key in out.stdout and "REGRESSED" in out.stdout


def test_gate_passes_identical_device_report(tmp_path):
    _, p = _golden_report(tmp_path)
    out = _run(["gate", str(p), str(p)])
    assert out.returncode == 0, out.stdout + out.stderr
    assert "PASS" in out.stdout


def test_device_metrics_direction_aware():
    mets = regress.extract_metrics(
        roofline.roofline(dp.build_timeline(GOLDEN_DEVICE)))
    assert "device.measured_stream_gbps" in mets
    assert "device.overlap_frac" in mets
    # bandwidth falling regresses, rising does not
    d, _ = regress._gate_for("device.measured_stream_gbps")
    assert d == "down"
    d, _ = regress._gate_for("device.device_sec_per_iter")
    assert d == "up"


# ---------------------------------------------------------------------------
# CLI: analyze --profile-dir (device-only + joined) and watch --once
# ---------------------------------------------------------------------------
def test_cli_analyze_profile_dir_device_only():
    out = _run(["analyze", "--profile-dir", "profile_trace_S100000",
                "--json"])
    assert out.returncode == 0, out.stderr
    rep = json.loads(out.stdout)
    assert rep["schema"].startswith("mpisppy-tpu-deviceprof/")
    assert rep["measured_stream_gbps"] == pytest.approx(485.1, rel=0.10)
    # the human rendering names the acceptance metrics verbatim
    out2 = _run(["analyze", "--profile-dir", "profile_trace_S100000"])
    assert "measured_stream_gbps" in out2.stdout
    assert "overlap_frac" in out2.stdout


def test_cli_analyze_joins_device_section_onto_trace():
    out = _run(["analyze", "--trace-jsonl", GOLDEN_FARMER,
                "--profile-dir", "profile_trace_S10000", "--json"])
    assert out.returncode == 0, out.stderr
    rep = json.loads(out.stdout)
    assert rep["schema"].startswith("mpisppy-tpu-analyze/")
    dev = rep["device"]
    assert dev["schema"].startswith("mpisppy-tpu-deviceprof/")
    assert 0.0 <= dev["overlap_frac"] <= 1.0
    # device metrics ride the analyzer report into the gate
    mets = regress.extract_metrics(rep)
    assert "device.achieved_hbm_gbps" in mets


def test_cli_analyze_needs_an_input():
    out = _run(["analyze"])
    assert out.returncode == 1
    assert "--profile-dir" in out.stderr


def test_cli_watch_once_golden_farmer():
    out = _run(["watch", "--trace-jsonl", GOLDEN_FARMER, "--once"])
    assert out.returncode == 0, out.stderr
    assert "rel_gap" in out.stdout
    assert "36c89caf6cf7" in out.stdout       # the fixture's run id
    assert "RUN ENDED" in out.stdout          # fixture ends with run-end
    assert "quarantine" in out.stdout


def test_cli_watch_once_with_metrics_snapshot(tmp_path):
    prom = tmp_path / "metrics.prom"
    prom.write_text('# HELP dispatch_batches_total x\n'
                    'dispatch_batches_total 7\n'
                    'wheel_iterations_total 12\n'
                    'not a sample line\n')
    out = _run(["watch", "--trace-jsonl", GOLDEN_FARMER,
                "--metrics-snapshot", str(prom), "--once"])
    assert out.returncode == 0, out.stderr
    assert "dispatch_batches_total=7" in out.stdout


def test_cli_watch_missing_trace_exits_1(tmp_path):
    out = _run(["watch", "--trace-jsonl", str(tmp_path / "nope.jsonl"),
                "--once"])
    assert out.returncode == 1


# ---------------------------------------------------------------------------
# ProfilerSession hardening (ISSUE 7 satellite)
# ---------------------------------------------------------------------------
class _RecBus:
    def __init__(self):
        self.events = []

    def emit(self, kind, **kw):
        self.events.append((kind, kw))


def test_profiler_unwritable_dir_degrades_to_warning(tmp_path):
    from mpisppy_tpu.telemetry.profiler import ProfilerSession
    # a FILE where the profile dir should go: makedirs cannot succeed
    # (works under root too, where chmod-based read-only is bypassed)
    blocker = tmp_path / "blocker"
    blocker.write_text("")
    bus = _RecBus()
    ps = ProfilerSession(str(blocker / "prof"), num_iters=1,
                         start_iter=0, bus=bus)
    ps.on_sync(0)      # must not raise
    ps.on_sync(1)
    ps.close()
    assert ps.failed and not ps.active
    # no profile event may claim a capture that never happened
    assert not any(kw.get("action") == "captured"
                   for _, kw in bus.events)


def test_profiler_emits_captured_only_after_files_land(tmp_path):
    from mpisppy_tpu.telemetry import events as ev
    from mpisppy_tpu.telemetry.profiler import ProfilerSession
    prof = tmp_path / "prof"
    bus = _RecBus()
    ps = ProfilerSession(str(prof), num_iters=1, start_iter=0, bus=bus)
    ps.on_sync(0)
    if ps.failed:      # no profiler backend in this env: contract held
        return
    import jax
    import jax.numpy as jnp
    jax.block_until_ready(jnp.arange(8) * 2)
    ps.on_sync(1)
    ps.close()
    actions = [kw.get("action") for k, kw in bus.events
               if k == ev.PROFILE]
    assert actions[0] == "start"
    if "captured" in actions:
        cap = next(kw for _, kw in bus.events
                   if kw.get("action") == "captured")
        assert os.path.isdir(cap["trace_dir"])
        assert dp.discover_captures(str(prof))


def test_profiler_never_rearms_after_window(tmp_path):
    """One capture window per session: after stop, later syncs must
    NOT restart tracing (a re-arming session writes a junk capture
    every ~2 iterations for the rest of the run)."""
    from mpisppy_tpu.telemetry.profiler import ProfilerSession
    starts = []
    ps = ProfilerSession(str(tmp_path / "prof"), num_iters=2,
                         start_iter=3, bus=_RecBus())
    real_stop = ps._stop

    def fake_stop(hub_iter):
        ps.done = True
        ps.active = False
    ps._stop = fake_stop
    import unittest.mock as mock
    with mock.patch("jax.profiler.start_trace",
                    side_effect=lambda d: starts.append(d)):
        for it in range(30):
            ps.on_sync(it)
    ps._stop = real_stop
    assert len(starts) == 1, f"session re-armed {len(starts)} times"
    assert ps.done and not ps.active


def test_dma_pairing_is_fifo():
    ops = [
        dp.DeviceOp("copy-start.1", "copy-start", 0.0, 0.001),
        dp.DeviceOp("copy-start.1", "copy-start", 2.0, 0.001),
        dp.DeviceOp("copy-done.1", "copy-done", 3.0, 0.001),
        dp.DeviceOp("copy-done.1", "copy-done", 5.0, 0.001),
    ]
    spans = sorted(dp._pair_dma(ops), key=lambda s: s.start_us)
    # transfers complete in issue order: (0 -> 3), (2 -> 5) — never
    # the crossed (2 -> 3), (0 -> 5)
    assert [(s.start_us, round(s.end_us, 3)) for s in spans] == \
        [(0.0, 3.001), (2.0, 5.001)]


def test_golden_fixture_stays_small():
    # the committed fixture is a trimmed capture, not a full trace
    assert os.path.getsize(GOLDEN_DEVICE) < 200_000
    with gzip.open(GOLDEN_DEVICE, "rt") as f:
        n = len(json.load(f)["traceEvents"])
    assert 100 <= n <= 1000
