# Model-zoo tail: battery (ref:examples/battery/battery.py) and distr
# (ref:examples/distr/) — both oracle-tested against scipy.
import numpy as np
import jax.numpy as jnp

from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import battery, distr
from mpisppy_tpu.ops import pdhg


def _spec_lp_oracle(sp, fix=None):
    from scipy.optimize import linprog
    A = sp.A.toarray() if hasattr(sp.A, "toarray") else np.asarray(sp.A)
    l, u = sp.l.copy(), sp.u.copy()  # noqa: E741
    if fix is not None:
        l[sp.nonant_idx] = fix
        u[sp.nonant_idx] = fix
    A_ub, b_ub, A_eq, b_eq = [], [], [], []
    for i in range(A.shape[0]):
        if sp.bl[i] == sp.bu[i]:
            A_eq.append(A[i]); b_eq.append(sp.bu[i])
            continue
        if np.isfinite(sp.bu[i]):
            A_ub.append(A[i]); b_ub.append(sp.bu[i])
        if np.isfinite(sp.bl[i]):
            A_ub.append(-A[i]); b_ub.append(-sp.bl[i])
    res = linprog(sp.c, A_ub=np.array(A_ub) if A_ub else None,
                  b_ub=np.array(b_ub) if b_ub else None,
                  A_eq=np.array(A_eq) if A_eq else None,
                  b_eq=np.array(b_eq) if b_eq else None,
                  bounds=list(zip(l, u)), method="highs")
    assert res.success, res.message
    return res.fun


def test_battery_scenarios_match_scipy():
    data = battery.getData(num_scens=6, seed=3)
    names = battery.scenario_names_creator(6)
    specs = [battery.scenario_creator(nm, data=data, use_LP=True, lam=50.0)
             for nm in names]
    b = batch_mod.from_specs(specs)
    st = pdhg.solve(b.qp, pdhg.PDHGOptions(tol=1e-7, max_iters=200_000))
    ours = np.asarray(b.objective(st.x))
    ref = np.array([_spec_lp_oracle(sp) for sp in specs])
    assert np.allclose(ours, ref, rtol=2e-3, atol=1e-3), (ours, ref)


def test_battery_ph_runs_and_bounds():
    from mpisppy_tpu.algos import ph as ph_mod
    data = battery.getData(num_scens=6, seed=3)
    names = battery.scenario_names_creator(6)
    specs = [battery.scenario_creator(nm, data=data, use_LP=True, lam=50.0)
             for nm in names]
    b = batch_mod.from_specs(specs)
    drv = ph_mod.PH(ph_mod.PHOptions(max_iterations=40, default_rho=0.05),
                    b)
    conv, eobj, tb = drv.ph_main()
    # wait-and-see <= optimal; converged PH objective above it
    assert tb <= eobj + 1e-2 * (1 + abs(eobj))
    assert conv < 10.0


def test_battery_z_binary_flagged():
    sp = battery.scenario_creator("scen0", num_scens=4, use_LP=False)
    assert sp.integer.sum() == 1  # exactly z
    sp_lp = battery.scenario_creator("scen0", num_scens=4, use_LP=True)
    assert sp_lp.integer.sum() == 0


def test_distr_admm_matches_global_lp():
    """Consensus ADMM over regions reproduces the merged-network LP
    (ref:examples/distr/globalmodel.py comparison)."""
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.utils.admmWrapper import AdmmWrapper

    R = 3
    data = distr.region_data(R, seed=1)
    names = distr.scenario_names_creator(R)
    cons = distr.consensus_vars_creator(R, data)
    wrapper = AdmmWrapper({}, names,
                          lambda nm, **kw: distr.scenario_creator(
                              nm, data=data),
                          cons)
    b = wrapper.make_batch()
    # admm rho tuning matters: rho>=5 freezes the inter-region flows at
    # a consensus point ~1-7% off optimal (measured); rho~2 is exact
    drv = ph_mod.PH(ph_mod.PHOptions(max_iterations=600, default_rho=2.0,
                                     conv_thresh=1e-7,
                                     subproblem_windows=10), b)
    conv, eobj, tb = drv.ph_main()
    ref = distr.global_lp_oracle(data)
    assert conv <= 1e-3, conv
    assert abs(eobj - ref) <= 5e-3 * (1 + abs(ref)), (eobj, ref)
