# Gradient costs, Find_Rho, rho csv, prox_approx cuts, sensitivities,
# and the dynamic-rho extensions (ref:utils/gradient.py, find_rho.py,
# prox_approx.py, nonant_sensitivities.py; tests
# ref:test_gradient_rho.py).
import numpy as np
import pytest

from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.ops import pdhg
from mpisppy_tpu.utils import gradient, rho_utils
from mpisppy_tpu.utils.nonant_sensitivities import nonant_sensitivities
from mpisppy_tpu.utils.prox_approx import ProxApproxManager, tangent_cut

from test_farmer_ef_ph import farmer_specs


def _ph(b, iters=20):
    opts = ph_mod.PHOptions(
        default_rho=1.0, max_iterations=iters, conv_thresh=0.0,
        subproblem_windows=8,
        pdhg=pdhg.PDHGOptions(tol=1e-7, restart_period=40))
    algo = ph_mod.PH(opts, b)
    algo.Iter0()
    algo.iterk_loop()
    return algo


def test_grad_cost_is_negated_objective_gradient():
    b = batch_mod.from_specs(farmer_specs(3))
    xhat = np.array([170.0, 80.0, 250.0])
    c = gradient.find_grad_cost(b, xhat)
    assert c.shape == (3, 3)
    # farmer first-stage cost: 150, 230, 260 $/acre (pure linear), so
    # the negated gradient is -cost for every scenario
    np.testing.assert_allclose(c, -np.array([[150.0, 230.0, 260.0]] * 3),
                               rtol=1e-4)


def test_order_stat_aggregate_limits():
    rho = np.array([[1.0, 4.0], [3.0, 8.0]])
    p = np.array([0.5, 0.5])
    np.testing.assert_allclose(
        gradient.order_stat_aggregate(rho, p, 0.0), [1.0, 4.0])
    np.testing.assert_allclose(
        gradient.order_stat_aggregate(rho, p, 1.0), [3.0, 8.0])
    np.testing.assert_allclose(
        gradient.order_stat_aggregate(rho, p, 0.5), [2.0, 6.0])
    # triangular interpolation stays within [min, max]
    mid = gradient.order_stat_aggregate(rho, p, 0.25)
    assert ((mid >= [1.0, 4.0]) & (mid <= [3.0, 8.0])).all()
    with pytest.raises(ValueError):
        gradient.order_stat_aggregate(rho, p, 1.5)


def test_find_rho_positive_and_finite():
    b = batch_mod.from_specs(farmer_specs(3))
    algo = _ph(b, iters=5)
    finder = gradient.Find_Rho(algo, {"grad_order_stat": 0.5})
    rho = finder.compute_rho()
    assert rho.shape == (3,)
    assert np.isfinite(rho).all() and (rho >= 0).all()
    rho_i = finder.compute_rho(indep_denom=True)
    assert np.isfinite(rho_i).all() and (rho_i >= 0).all()


def test_rho_csv_roundtrip(tmp_path):
    rho = np.array([1.5, 2.0, 0.25])
    f = str(tmp_path / "rho.csv")
    rho_utils.rhos_to_csv(rho, f)
    back = rho_utils.rhos_from_csv(f, 3)
    np.testing.assert_allclose(back, rho)
    from mpisppy_tpu.utils.gradient import Set_Rho
    setter = Set_Rho({"rho_file_in": f})
    b = batch_mod.from_specs(farmer_specs(3))
    np.testing.assert_allclose(setter.rho_setter(b), rho)


def test_prox_approx_cuts_tighten():
    mgr = ProxApproxManager(1, tol=1e-3)
    # tangent cut math: underestimates x^2 everywhere, exact at x_pt
    s, b = tangent_cut(np.array(2.0))
    xs = np.linspace(-5, 5, 101)
    assert (s * xs + b <= xs * xs + 1e-12).all()
    assert s * 2.0 + b == pytest.approx(4.0)
    # iterating add_cut at a point drives the epigraph gap under tol
    x = 3.7
    for _ in range(30):
        if mgr.add_cut(0, x) == 0:
            break
    assert x * x - mgr.evaluate(0, x) <= 1e-3
    # and the approximation is still a global underestimator
    for xx in np.linspace(-6, 6, 25):
        assert mgr.evaluate(0, float(xx)) <= xx * xx + 1e-9


def test_sensitivities_shape_and_magnitude():
    b = batch_mod.from_specs(farmer_specs(3))
    opts = pdhg.PDHGOptions(tol=1e-7, max_iters=100_000)
    st = pdhg.solve(b.qp, opts)
    sens = nonant_sensitivities(b, st)
    assert sens.shape == (3, 3)
    assert np.isfinite(sens).all()


def test_dynamic_rho_extensions_run():
    import functools
    from mpisppy_tpu.extensions.rho_setters import (
        Gradient_extension, MultRhoUpdater, SensiRho,
    )
    b = batch_mod.from_specs(farmer_specs(3))
    opts = ph_mod.PHOptions(default_rho=1.0, max_iterations=8,
                            conv_thresh=0.0, subproblem_windows=8,
                            pdhg=pdhg.PDHGOptions(tol=1e-7))
    # MultRhoUpdater doubles rho on schedule
    algo = ph_mod.PH(opts, b, extensions=functools.partial(
        MultRhoUpdater, mult_rho_update_factor=2.0,
        mult_rho_update_interval=2))
    algo.ph_main()
    assert float(np.asarray(algo.state.rho)[0]) > 1.0
    # SensiRho sets rho from iter0 sensitivities
    algo2 = ph_mod.PH(opts, b, extensions=SensiRho)
    algo2.ph_main()
    assert not np.allclose(np.asarray(algo2.state.rho), 1.0)
    # Gradient_extension updates rho mid-run without breaking PH
    algo3 = ph_mod.PH(opts, b, extensions=functools.partial(
        Gradient_extension, grad_rho_update_interval=3))
    conv, eobj, tb = algo3.ph_main()
    assert np.isfinite(eobj)
    assert (np.asarray(algo3.state.rho) > 0).all()
