# Confidence-interval subsystem: gap estimators, MMW, sequential
# sampling, zhat4xhat, sample trees (ref:confidence_intervals/*;
# tests ref:test_conf_int_farmer.py, test_conf_int_aircond.py).
import numpy as np
import pytest

from mpisppy_tpu.confidence_intervals import ciutils, mmw_ci, zhat4xhat
from mpisppy_tpu.confidence_intervals.seqsampling import SeqSampling
from mpisppy_tpu.models import aircond, farmer
from mpisppy_tpu.ops import pdhg
from mpisppy_tpu.utils.config import Config

XHAT_STAR = np.array([170.0, 80.0, 250.0])   # farmer EF optimum


def _cfg(num_scens=20, **kw):
    cfg = Config()
    cfg.quick_assign("num_scens", int, num_scens)
    for k, v in kw.items():
        cfg.quick_assign(k, type(v), v)
    return cfg


def test_gap_estimator_near_zero_at_optimum():
    cfg = _cfg(24)
    names = farmer.scenario_names_creator(24, start=100)
    est = ciutils.gap_estimators(XHAT_STAR, farmer, names, cfg)
    # at (essentially) the optimal xhat the gap estimate is small
    assert est["G"] >= 0.0
    assert est["G"] <= 0.02 * 108390.0
    assert est["s"] >= 0.0
    assert est["seed"] == 124


def test_gap_estimator_positive_for_bad_xhat():
    cfg = _cfg(24)
    names = farmer.scenario_names_creator(24, start=200)
    bad = np.array([500.0, 0.0, 0.0])      # all wheat: clearly bad
    est_bad = ciutils.gap_estimators(bad, farmer, names, cfg)
    est_good = ciutils.gap_estimators(XHAT_STAR, farmer, names, cfg)
    assert est_bad["G"] > est_good["G"] + 1000.0


def test_gap_estimator_arrp_pooling():
    cfg = _cfg(24)
    names = farmer.scenario_names_creator(24, start=300)
    est = ciutils.gap_estimators(XHAT_STAR, farmer, names, cfg, ArRP=2)
    assert np.isfinite(est["G"]) and np.isfinite(est["s"])


def test_mmw_ci_runs_and_brackets_gap():
    cfg = _cfg(12)
    mmw = mmw_ci.MMWConfidenceIntervals(farmer, cfg, XHAT_STAR,
                                        num_batches=4, batch_size=12,
                                        start=400, verbose=False)
    res = mmw.run(confidence_level=0.95)
    assert res["gap_outer_bound"] == 0.0
    assert res["gap_inner_bound"] >= res["Gbar"]
    # near-optimal xhat: the gap CI stays tiny relative to the objective
    assert res["gap_inner_bound"] <= 0.05 * 108390.0
    assert len(res["Glist"]) == 4


def _xhat_gen(scenario_names, **kw):
    """EF solve on the sample -> root solution (the reference's
    xhat_generator shape, ref:seqsampling.py docstring)."""
    from mpisppy_tpu.algos.ef import ExtensiveForm
    ef = ExtensiveForm({"tol": 1e-6, "max_iters": 200_000},
                       scenario_names, farmer.scenario_creator,
                       {"num_scens": len(scenario_names)})
    ef.solve_extensive_form()
    sol = ef.get_root_solution()
    return np.array([sol[f"x{i}"] for i in range(3)])


def test_seq_sampling_bm_terminates():
    cfg = _cfg(10, BM_h=3.0, BM_hprime=0.1, BM_eps=50.0,
               BM_eps_prime=40.0, confidence_level=0.9)
    seq = SeqSampling(farmer, _xhat_gen, cfg, stopping_criterion="BM")
    res = seq.run(maxit=8)
    assert res["T"] <= 8
    assert res["CI"][0] == 0.0 and res["CI"][1] > 0.0
    assert len(res["Candidate_solution"]) == 3


def test_seq_sampling_bpl_terminates():
    cfg = _cfg(10, BPL_eps=2000.0, BPL_c0=10, confidence_level=0.9)
    seq = SeqSampling(farmer, _xhat_gen, cfg, stopping_criterion="BPL")
    res = seq.run(maxit=8)
    assert res["T"] <= 8
    assert np.isfinite(res["CI"][1])


def test_zhat4xhat_two_stage(tmp_path):
    cfg = _cfg(12)
    zhats, seed = zhat4xhat.evaluate_sample_trees(
        XHAT_STAR, 4, cfg, farmer, InitSeed=500)
    assert zhats.shape == (4,)
    # sampled-scenario yields differ from the base-3 distribution, so
    # anchor on internal consistency: finite, negative (profit), and
    # batch means within a few percent of each other
    assert np.isfinite(zhats).all() and (zhats < 0).all()
    assert np.abs(zhats - zhats.mean()).max() \
        <= 0.1 * np.abs(zhats.mean())
    # the t-interval driver
    p = str(tmp_path / "xhat.npy")
    ciutils.write_xhat(XHAT_STAR, p)
    cfg.quick_assign("xhatpath", str, p)
    zbar, eps = zhat4xhat.run_samples(cfg, farmer, num_samples=4)
    assert np.isfinite(zbar) and eps >= 0.0


def test_sample_tree_multistage_aircond():
    from mpisppy_tpu.confidence_intervals.sample_tree import (
        SampleSubtree, walking_tree_xhats,
    )
    cfg = Config()
    bfs = (2, 2)
    cfg.quick_assign("branching_factors", list, list(bfs))
    st = SampleSubtree(aircond, None, bfs, seed=7, cfg=cfg)
    obj = st.run()
    assert np.isfinite(obj)
    # pinned-root subtree costs at least as much as the free one
    xhat_root = np.array([250.0, 0.0])   # (Reg_1, OT_1) forced high
    st2 = SampleSubtree(aircond, xhat_root[:2], bfs, seed=7, cfg=cfg)
    # root stage has 2 slots; force an overproduction policy
    obj2 = st2.run()
    assert obj2 >= obj - 1e-3
    # walking_tree_xhats: a value for every non-leaf node
    xhats, seed2 = walking_tree_xhats(aircond, xhat_root[:2], bfs, 7,
                                      cfg)
    assert xhats.shape[0] == 3           # ROOT + 2 stage-2 nodes
    # row 0 = ROOT: its own (stage-1) slots are pinned at xhat_root
    np.testing.assert_allclose(xhats[0, :2], xhat_root[:2], atol=1e-5)
    # stage-2 nodes carry their own slots (2,3); values are finite
    assert np.isfinite(xhats).all()
    assert seed2 > 7


def test_zhat4xhat_multistage():
    cfg = Config()
    cfg.quick_assign("branching_factors", list, [2, 2])
    xhat_root = np.array([200.0, 0.0])
    zhats, _ = zhat4xhat.evaluate_sample_trees(
        xhat_root, 3, cfg, aircond, InitSeed=11)
    assert zhats.shape == (3,)
    assert np.isfinite(zhats).all()


def test_sample_tree_seed_varies_samples():
    # regression: aircond takes start_seed via **kw; the seed must
    # reach the creator or every sampled subtree is identical
    from mpisppy_tpu.confidence_intervals.sample_tree import SampleSubtree
    cfg = Config()
    objs = [SampleSubtree(aircond, None, (2, 2), seed, cfg).run()
            for seed in (100, 5000)]
    assert objs[0] != objs[1]


def test_zhat4xhat_multistage_nonzero_variance():
    # regression: the t-interval is only valid if samples vary
    cfg = Config()
    cfg.quick_assign("branching_factors", list, [2, 2])
    zhats, _ = zhat4xhat.evaluate_sample_trees(
        np.array([200.0, 0.0]), 3, cfg, aircond, InitSeed=11)
    assert np.std(zhats) > 0.0


def test_seq_sampling_converged_flag():
    # unmet stopping criterion at maxit must be flagged
    cfg = _cfg(10, BM_h=1.75, BM_hprime=0.0, BM_eps=0.01,
               BM_eps_prime=1e-8, confidence_level=0.9)
    bad_gen = lambda names, **kw: np.array([0.0, 0.0, 0.0])
    seq = SeqSampling(farmer, bad_gen, cfg, stopping_criterion="BM")
    res = seq.run(maxit=2)
    assert res["converged"] is False


def test_multistage_gap_estimators():
    """gap_estimators_mstage: small aircond trees; G >= 0 near a
    reasonable candidate, seed advances by the trees' node counts."""
    from mpisppy_tpu.confidence_intervals.sample_tree import (
        SampleSubtree, _number_of_nodes,
    )
    cfg = _cfg(4)
    cfg.quick_assign("branching_factors", list, [2, 2])
    # candidate: root solution of one sampled tree
    st = SampleSubtree(aircond, None, (2, 2), seed=3, cfg=cfg)
    st.run()
    sol = st.ef.x
    nonant_idx = np.asarray(st.ef.ef.nonant_idx)
    tree = st.ef.ef.tree
    root_slots = np.nonzero(tree.slot_stage == 1)[0]
    xhat_root = sol[:, nonant_idx].mean(axis=0)[root_slots]

    est = ciutils.gap_estimators_mstage(
        xhat_root, aircond, 3, cfg, start_seed=50,
        branching_factors=[2, 2])
    assert est["G"] >= 0.0
    assert est["s"] >= 0.0
    assert est["seed"] == 50 + 3 * _number_of_nodes([2, 2])


def test_multistage_seq_sampling_aircond():
    """IndepScens_SeqSampling on 3-stage aircond (the round-2 review's
    missing #4; ref:test_conf_int_aircond.py style)."""
    from mpisppy_tpu.confidence_intervals.seqsampling import (
        IndepScens_SeqSampling,
    )
    cfg = _cfg(4, BM_h=5.0, BM_hprime=0.2, BM_eps=150.0,
               BM_eps_prime=120.0, confidence_level=0.9)
    cfg.quick_assign("branching_factors", list, [2, 2])
    seq = IndepScens_SeqSampling(aircond, None, cfg,
                                 stopping_criterion="BM")
    res = seq.run(maxit=5)
    assert res["T"] <= 5
    assert res["CI"][0] == 0.0 and np.isfinite(res["CI"][1])
    assert len(res["Candidate_solution"]) == 2  # aircond root nonants


def test_mmw_conf_cli(tmp_path):
    """The mmw_conf CLI end-to-end on farmer (ref:mmw_conf.py)."""
    import json
    import contextlib
    import io

    from mpisppy_tpu.confidence_intervals import mmw_conf

    xhat_path = str(tmp_path / "xhat.npy")
    ciutils.write_xhat(XHAT_STAR, xhat_path)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        res = mmw_conf.main([
            "--module-name", "mpisppy_tpu.models.farmer",
            "--xhatpath", xhat_path,
            "--num-scens", "10",
            "--MMW-num-batches", "2",
            "--MMW-batch-size", "8",
        ])
    assert res["Gbar"] >= 0.0
    line = buf.getvalue().strip().splitlines()[-1]
    out = json.loads(line)
    assert "gap_inner_bound" in out
