# Unit tests for the batched PDHG kernel against scipy.linprog oracles.
# Mirrors the role of solver-adaptive smoke tests in the reference
# (ref:mpisppy/tests/utils.py:14-34) — but our "solver" is in-repo, so we
# can test tight tolerances against an independent implementation.
import numpy as np
import pytest
from scipy.optimize import linprog

import jax
import jax.numpy as jnp

from mpisppy_tpu.ops import boxqp, pdhg


def random_lp(rng, n=20, m=12, two_sided=False):
    """A feasible, bounded random LP in BoxQP form + its scipy solution."""
    A = rng.normal(size=(m, n))
    x0 = rng.uniform(0.5, 2.0, size=n)
    slack = rng.uniform(0.1, 1.0, size=m)
    bu = A @ x0 + slack
    bl = A @ x0 - rng.uniform(3.0, 6.0, size=m) if two_sided else np.full(m, -np.inf)
    c = rng.normal(size=n)
    l, u = np.zeros(n), np.full(n, 5.0)

    A_ub = [A]
    b_ub = [bu]
    if two_sided:
        A_ub.append(-A)
        b_ub.append(-bl)
    res = linprog(c, A_ub=np.vstack(A_ub), b_ub=np.concatenate(b_ub),
                  bounds=list(zip(l, u)), method="highs")
    assert res.status == 0
    prob = boxqp.make_boxqp(c, A, bl, bu, l, u)
    return prob, res


@pytest.mark.parametrize("two_sided", [False, True])
def test_lp_matches_scipy(two_sided):
    rng = np.random.default_rng(0)
    prob, res = random_lp(rng, two_sided=two_sided)
    scaled, sc = boxqp.ruiz_scale(prob)
    opts = pdhg.PDHGOptions(tol=1e-6, max_iters=40_000)
    st = pdhg.solve(scaled, opts)
    x = np.asarray(st.x) * sc.d_col
    obj = float(np.asarray(prob.c) @ x)
    assert st.done.item()
    assert obj == pytest.approx(res.fun, abs=2e-3, rel=2e-4)
    # primal feasibility in original space
    viol = np.asarray(boxqp.primal_residual(prob, jnp.asarray(x, prob.c.dtype)))
    assert viol.max() < 5e-3


def test_equality_rows():
    # min -x1 - 2 x2  s.t. x1 + x2 == 1, 0 <= x <= 1  -> x = (0, 1), obj -2
    prob = boxqp.make_boxqp(
        c=[-1.0, -2.0], A=[[1.0, 1.0]], bl=[1.0], bu=[1.0], l=[0.0, 0.0], u=[1.0, 1.0]
    )
    st = pdhg.solve(prob, pdhg.PDHGOptions(tol=1e-7))
    np.testing.assert_allclose(np.asarray(st.x), [0.0, 1.0], atol=1e-4)


def test_qp_simplex_projection():
    # min 1/2||x - z||^2 s.t. sum x = 1, x >= 0 : Euclidean projection.
    rng = np.random.default_rng(3)
    z = rng.normal(size=8)
    # reference projection via sorting (Held et al.)
    zs = np.sort(z)[::-1]
    css = np.cumsum(zs) - 1.0
    rho = np.nonzero(zs - css / (np.arange(8) + 1) > 0)[0][-1]
    expected = np.maximum(z - css[rho] / (rho + 1), 0.0)

    prob = boxqp.make_boxqp(
        c=-z, q=np.ones(8), A=np.ones((1, 8)), bl=[1.0], bu=[1.0],
        l=np.zeros(8), u=np.full(8, np.inf),
    )
    st = pdhg.solve(prob, pdhg.PDHGOptions(tol=1e-7))
    np.testing.assert_allclose(np.asarray(st.x), expected, atol=1e-4)


def test_batched_solve_matches_individual():
    rng = np.random.default_rng(7)
    probs, refs = zip(*[random_lp(rng, n=10, m=6) for _ in range(5)])
    batch = jax.tree.map(lambda *xs: jnp.stack(xs), *probs)
    scaled, sc = boxqp.ruiz_scale(batch)
    # instance 0 of this seed has a slow f32 tail (~60k iters on CPU)
    st = pdhg.solve(scaled, pdhg.PDHGOptions(tol=1e-6, max_iters=120_000))
    assert bool(st.done.all())
    xs = np.asarray(st.x) * sc.d_col
    for i, (prob, res) in enumerate(zip(probs, refs)):
        obj = float(np.asarray(prob.c) @ xs[i])
        assert obj == pytest.approx(res.fun, abs=2e-3, rel=2e-4)


def test_warm_start_converges_faster():
    rng = np.random.default_rng(11)
    prob, _ = random_lp(rng)
    scaled, _ = boxqp.ruiz_scale(prob)
    opts = pdhg.PDHGOptions(tol=1e-6, max_iters=40_000)
    st = pdhg.solve(scaled, opts)
    cold_iters = int(st.k)
    # perturb the objective slightly and re-solve warm.  Warm starting
    # carries no guarantee of strictly fewer iterations, so assert
    # convergence plus a loose 2x bound (ADVICE r1).
    p2 = scaled.__class__(**{**scaled.__dict__, "c": scaled.c * 1.01})
    st2 = pdhg.solve(p2, opts, state=st)
    assert st2.done.item()
    assert int(st2.k) <= 2 * cold_iters


def test_difference_rows_norm_not_degenerate():
    # Rows that sum to zero (x_i - x_j form, the exact shape of
    # nonanticipativity constraints) put the all-ones vector in null(A'A);
    # regression for the ADVICE r1 finding that the power iteration then
    # collapsed and the solve diverged.  The 2-row difference matrix has
    # sigma_max = sqrt(3) STRICTLY greater than the max row norm sqrt(2),
    # so this assertion requires the power iteration itself to work (the
    # row-norm floor alone would return sqrt(2)).
    prob = boxqp.make_boxqp(
        c=[-1.0, 0.0, 0.0], A=[[1.0, -1.0, 0.0], [0.0, 1.0, -1.0]],
        bl=[-np.inf, -np.inf], bu=[0.0, 0.0],
        l=[0.0, 0.0, 0.0], u=[1.0, 1.0, 1.0],
    )
    est = float(pdhg.estimate_norm(prob))
    assert est == pytest.approx(np.sqrt(3.0), rel=1e-3)
    st = pdhg.solve(prob, pdhg.PDHGOptions(tol=1e-6))
    assert st.done.item()
    # min -x1 s.t. x1 <= x2 <= x3, x in [0,1]: optimum all ones
    np.testing.assert_allclose(np.asarray(st.x), [1.0, 1.0, 1.0], atol=1e-4)


def test_solve_fixed_budget_runs():
    rng = np.random.default_rng(13)
    prob, res = random_lp(rng)
    scaled, sc = boxqp.ruiz_scale(prob)
    opts = pdhg.PDHGOptions(tol=0.0)  # tol floors at 5*eps; fixed budget
    st = pdhg.init_state(scaled, opts)
    st = pdhg.solve_fixed(scaled, 200, opts, st)
    x = np.asarray(st.x) * sc.d_col
    obj = float(np.asarray(prob.c) @ x)
    assert obj == pytest.approx(res.fun, rel=1e-2, abs=1e-2)


def test_auto_chunked_dispatch(monkeypatch):
    """A host-level solve whose budget exceeds dispatch_cap must split
    into multiple capped dispatches (the TPU-worker crash guard that
    round 4 hand-rolled in the bench harness, now in the kernel)."""
    # constraint-infeasible LP (x >= 2 inside [0,1]) with infeasibility
    # detection off: the solve can never set done, so it must burn the
    # whole budget — deterministically exercising the chunk loop
    f = lambda v: jnp.asarray(v, jnp.float32)  # noqa: E731
    scaled = boxqp.BoxQP(c=f([1.0]), q=f([0.0]), A=f([[1.0]]),
                         bl=f([2.0]), bu=f([np.inf]),
                         l=f([0.0]), u=f([1.0]))

    calls = []
    real = pdhg._dispatch_capped

    def spy(p, opts, st):
        out = real(p, opts, st)
        calls.append(int(out.k))
        return out

    monkeypatch.setattr(pdhg, "_dispatch_capped", spy)
    opts = pdhg.PDHGOptions(tol=1e-30, max_iters=2_000,
                            dispatch_cap=400, restart_period=40,
                            detect_infeas=False)
    st = pdhg.solve(scaled, opts)
    # every dispatch advanced at most cap (+one window of slack)
    assert len(calls) >= 2, calls
    prev = 0
    for k in calls:
        assert k - prev <= opts.dispatch_cap + opts.restart_period
        prev = k
    assert int(st.k) <= opts.max_iters

    # traced calls keep the single while_loop: jit of solve with a
    # huge budget must not host-chunk (the caller owns the budget)
    calls.clear()
    jitted = jax.jit(pdhg.solve, static_argnames=("opts",))
    jitted(scaled, opts).k.block_until_ready()
    assert calls == []


def test_lagrangian_big_budget_chunks(monkeypatch):
    """lagrangian_bound with a certification-scale budget goes through
    the capped host seam (sslp_cert's 100k-iteration calls)."""
    from mpisppy_tpu.algos import lagrangian as lag_mod
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.models import farmer

    specs = [farmer.scenario_creator(nm, num_scens=3)
             for nm in farmer.scenario_names_creator(3)]
    batch = batch_mod.from_specs(specs)
    W = jnp.zeros((batch.num_scenarios, batch.num_nonants),
                  batch.qp.c.dtype)

    calls = []
    real = pdhg._dispatch_capped

    def spy(p, opts, st):
        out = real(p, opts, st)
        calls.append(int(out.k))
        return out

    monkeypatch.setattr(pdhg, "_dispatch_capped", spy)
    # 600-iteration budget with a 200 cap: >=2 chunks prove the routing
    # without burning a certification-scale budget in CI
    res = lag_mod.lagrangian_bound(
        batch, W, pdhg.PDHGOptions(tol=1e-30, max_iters=600,
                                   dispatch_cap=200))
    assert len(calls) >= 2, calls
    assert np.isfinite(float(res.bound))
