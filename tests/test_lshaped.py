# L-shaped (Benders): farmer convergence to the EF optimum (single- and
# multi-cut), and feasibility cuts on a problem without complete
# recourse.  TPU analog of the reference's lshaped tests
# (ref:mpisppy/tests/test_lshaped.py-style known answers).
import numpy as np
import pytest

from mpisppy_tpu.algos import lshaped as ls_mod
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.core.batch import ScenarioSpec
from mpisppy_tpu.models import farmer
from mpisppy_tpu.ops import pdhg

FARMER_EF_OBJ = -108390.0


def farmer_batch(num_scens=3):
    names = farmer.scenario_names_creator(num_scens)
    specs = [farmer.scenario_creator(nm, num_scens=num_scens)
             for nm in names]
    return batch_mod.from_specs(specs)


def test_lshaped_farmer_singlecut():
    b = farmer_batch(3)
    opts = ls_mod.LShapedOptions(max_iter=60, tol=2e-3)
    ls = ls_mod.LShapedMethod(opts, b)
    res = ls.lshaped_algorithm()
    # certified bracket around the known optimum
    assert res["bound"] <= FARMER_EF_OBJ + 40.0
    assert res["ub"] >= FARMER_EF_OBJ - 40.0
    assert res["ub"] - res["bound"] <= 2e-3 * abs(res["ub"]) + 1.0
    np.testing.assert_allclose(res["xhat"], [170.0, 80.0, 250.0], atol=8.0)


def test_lshaped_farmer_multicut():
    b = farmer_batch(3)
    opts = ls_mod.LShapedOptions(max_iter=60, tol=2e-3, multicut=True)
    ls = ls_mod.LShapedMethod(opts, b)
    res = ls.lshaped_algorithm()
    assert res["ub"] == pytest.approx(FARMER_EF_OBJ, rel=2e-3)
    # multicut should not need more iterations than the aggregate mode
    single = ls_mod.LShapedMethod(
        ls_mod.LShapedOptions(max_iter=60, tol=2e-3), farmer_batch(3))
    rs = single.lshaped_algorithm()
    assert res["iterations"] <= rs["iterations"] + 2


def _no_recourse_specs():
    """max x (min -x), x in [0,3] nonant; recourse y in [0, 0.5] with
    x - y <= 1  =>  feasible iff x <= 1.5.  Optimum: x*=1.5, obj -1.5.
    A scenario batch of two copies (slightly different y cost) so the
    batched path is exercised."""
    specs = []
    for k, ycost in enumerate([0.0, 0.01]):
        specs.append(ScenarioSpec(
            name=f"scen{k}",
            c=np.array([-1.0, ycost]),
            A=np.array([[1.0, -1.0]]),
            bl=np.array([-np.inf]),
            bu=np.array([1.0]),
            l=np.array([0.0, 0.0]),
            u=np.array([3.0, 0.5]),
            nonant_idx=np.array([0], np.int32),
        ))
    return specs


def test_lshaped_feasibility_cuts():
    b = batch_mod.from_specs(_no_recourse_specs())
    opts = ls_mod.LShapedOptions(
        max_iter=40, tol=1e-3,
        sub_pdhg=pdhg.PDHGOptions(tol=1e-7, max_iters=50_000,
                                  detect_infeas=True))
    ls = ls_mod.LShapedMethod(opts, b)
    res = ls.lshaped_algorithm()
    assert res["xhat"][0] == pytest.approx(1.5, abs=0.02)
    assert res["ub"] == pytest.approx(-1.5 + 0.005 * 0.5, abs=0.05)
    # at least one feasibility cut must have fired (x̂ starts > 1.5 is
    # not guaranteed, so check via trace: some iteration had no ub yet)
    assert res["iterations"] >= 2


def test_lshaped_hub_with_xhat_spoke():
    """LShapedHub wheel: Benders hub + xhat-lshaped inner spoke reach a
    certified gap on farmer (ref:cylinders/hub.py:618-710 +
    lshaped_bounder.py:14)."""
    from mpisppy_tpu.spin_the_wheel import WheelSpinner
    from mpisppy_tpu.utils import cfg_vanilla as vanilla
    from mpisppy_tpu.utils.config import Config

    cfg = Config()
    cfg.popular_args()
    cfg.lshaped_args()
    cfg.rel_gap = 5e-3
    cfg.lshaped_max_iter = 60
    b = farmer_batch(3)
    hub = vanilla.lshaped_hub(cfg, b)
    wheel = WheelSpinner(hub, [vanilla.xhatlshaped_spoke(cfg)])
    wheel.spin()
    assert wheel.BestOuterBound <= FARMER_EF_OBJ + 40.0
    assert wheel.BestInnerBound >= FARMER_EF_OBJ - 40.0
    gap = wheel.BestInnerBound - wheel.BestOuterBound
    assert gap <= 5e-3 * abs(wheel.BestInnerBound) + 1.0
    # W-getter spokes must be rejected (nonants-only hub)
    import pytest as _pytest
    bad = WheelSpinner(vanilla.lshaped_hub(cfg, farmer_batch(3)),
                       [vanilla.lagrangian_spoke(cfg)])
    with _pytest.raises(RuntimeError, match="W-getter"):
        bad.spin()


def test_lshaped_rejects_multistage_and_quadratic():
    from mpisppy_tpu.models import hydro
    names = hydro.scenario_names_creator(4)
    specs = [hydro.scenario_creator(nm, branching_factors=[2, 2])
             for nm in names]
    tree = hydro.make_tree([2, 2])
    b3 = batch_mod.from_specs(specs, tree=tree)
    with pytest.raises(ValueError, match="two-stage"):
        ls_mod.LShapedMethod(ls_mod.LShapedOptions(), b3)

    # quadratic cost ON A NONANT column breaks cut affinity -> rejected
    sp = _no_recourse_specs()
    for s in sp:
        s.q = np.array([1.0, 0.0])  # q on the nonant (col 0)
    bq = batch_mod.from_specs(sp)
    with pytest.raises(ValueError, match="quadratic"):
        ls_mod.LShapedMethod(ls_mod.LShapedOptions(), bq)

    # quadratic cost on a RECOURSE column is fine
    sp2 = _no_recourse_specs()
    for s in sp2:
        s.q = np.array([0.0, 1.0])
    ls_mod.LShapedMethod(ls_mod.LShapedOptions(),
                         batch_mod.from_specs(sp2))
