# Schur-complement interior point (ref:mpisppy/opt/sc.py; tests
# ref:mpisppy/tests/test_sc.py — serial and mpirun there, one batched
# program here).
import dataclasses

import numpy as np
import pytest

from mpisppy_tpu.algos.sc import SchurComplement, SCOptions
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import farmer, sslp

from test_farmer_ef_ph import farmer_specs, scipy_ef_solve


def test_sc_farmer_matches_ef():
    specs = farmer_specs(3)
    sobj, _ = scipy_ef_solve(specs)
    b = batch_mod.from_specs(specs)
    sc = SchurComplement(SCOptions(max_iter=60, tol=1e-8), b)
    res = sc.solve()
    assert res["converged"]
    assert res["objective"] == pytest.approx(sobj, rel=1e-5)
    np.testing.assert_allclose(res["x"], [170.0, 80.0, 250.0], atol=0.1)


def test_sc_farmer_quadratic():
    # add a diagonal quadratic cost on the first-stage acres: SC handles
    # QPs natively (a strict superset of the reference's LP-only MA27
    # usage on these problems)
    specs = farmer_specs(3)
    specs = [dataclasses.replace(
        sp, q=np.concatenate([np.full(3, 0.1),
                              np.zeros(sp.c.shape[0] - 3)]))
        for sp in specs]
    sobj, sx = scipy_qp_oracle(specs)
    b = batch_mod.from_specs(specs)
    sc = SchurComplement(SCOptions(max_iter=60, tol=1e-8), b)
    res = sc.solve()
    assert res["converged"]
    assert res["objective"] == pytest.approx(sobj, rel=1e-4)


def scipy_qp_oracle(specs):
    """EF QP via scipy.optimize.minimize (SLSQP is fine at this size)."""
    from mpisppy_tpu.algos.ef import build_ef
    efp = build_ef(specs, scale=False)
    qp = efp.qp
    c = np.asarray(qp.c, np.float64)
    q = np.asarray(qp.q, np.float64)
    A = np.asarray(qp.A, np.float64)
    bl = np.asarray(qp.bl, np.float64)
    bu = np.asarray(qp.bu, np.float64)
    l = np.asarray(qp.l, np.float64)
    u = np.asarray(qp.u, np.float64)
    from scipy.optimize import Bounds, LinearConstraint, minimize
    n = len(c)
    x0 = np.clip(np.zeros(n), l, np.minimum(u, 1e3))
    res = minimize(
        lambda v: c @ v + 0.5 * v @ (q * v),
        x0, jac=lambda v: c + q * v,
        hess=lambda v: np.diag(q),
        bounds=Bounds(l, u),
        constraints=[LinearConstraint(A, bl, bu)],
        method="trust-constr",
        options={"maxiter": 3000, "gtol": 1e-10, "xtol": 1e-12})
    assert res.status in (1, 2), res.message
    return res.fun, res.x


def test_sc_sslp_lp_relaxation():
    inst = sslp.synthetic_instance(3, 9, seed=2)
    names = sslp.scenario_names_creator(4)
    specs = [sslp.scenario_creator(nm, instance=inst, num_scens=4,
                                   lp_relax=True) for nm in names]
    sobj, _ = scipy_ef_solve(specs)
    b = batch_mod.from_specs(specs)
    # degenerate set-cover vertices need a deep central path: tol 1e-9
    sc = SchurComplement(SCOptions(max_iter=250, tol=1e-10), b)
    res = sc.solve()
    assert res["converged"]
    assert res["objective"] == pytest.approx(sobj, rel=1e-4)


def test_sc_rejects_integer_and_multistage():
    inst = sslp.synthetic_instance(3, 9, seed=2)
    specs = [sslp.scenario_creator("Scenario0", instance=inst,
                                   num_scens=1, lp_relax=False)]
    b = batch_mod.from_specs(specs)
    with pytest.raises(ValueError, match="continuous"):
        SchurComplement(SCOptions(), b)

    from mpisppy_tpu.models import hydro
    hspecs = [hydro.scenario_creator(nm)
              for nm in hydro.scenario_names_creator(9)]
    hb = batch_mod.from_specs(hspecs, tree=hydro.make_tree())
    with pytest.raises(ValueError, match="two-stage"):
        SchurComplement(SCOptions(), hb)


def test_sc_backend_and_timing_recorded():
    """The CPU-offload boundary is explicit (round-2 review, weak #4):
    the result records which backend the f64 loop ran on and how long
    it took; under the test harness (cpu default) no offload happens."""
    import jax
    specs = [farmer.scenario_creator(nm, num_scens=3)
             for nm in farmer.scenario_names_creator(3)]
    batch = batch_mod.from_specs(specs)
    sc = SchurComplement({}, batch)
    res = sc.solve()
    assert res["backend_used"] == jax.default_backend() == "cpu"
    assert res["solve_seconds"] > 0.0
    assert res["converged"]
