# Reduced-costs spoke + fixer and the ph_ob outer-bound spoke
# (ref:cylinders/reduced_costs_spoke.py, extensions/reduced_costs_fixer.py,
# cylinders/ph_ob.py).
import numpy as np
import pytest

from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import sslp
from mpisppy_tpu.spin_the_wheel import WheelSpinner
from mpisppy_tpu.utils import cfg_vanilla as vanilla
from mpisppy_tpu.utils.config import Config

from test_farmer_ef_ph import farmer_specs, scipy_ef_solve


def _sslp_batch(num=6):
    """sslp where server 0 is absurdly expensive: the LP-LR pins its
    build variable at 0 in EVERY scenario, which is exactly the at-bound
    + consensus situation reduced costs exist to exploit (interior
    fractional slots correctly yield NaN and no signal)."""
    inst = sslp.synthetic_instance(5, 15, seed=0)
    inst["FixedCost"] = inst["FixedCost"].copy()
    inst["FixedCost"][0] = 1e5
    names = sslp.scenario_names_creator(num)
    specs = [sslp.scenario_creator(nm, instance=inst, num_scens=num,
                                   lp_relax=True) for nm in names]
    return batch_mod.from_specs(specs), names, specs


def _cfg(**kw):
    cfg = Config()
    cfg.quick_assign("max_iterations", int, kw.pop("iters", 40))
    cfg.quick_assign("rel_gap", float, kw.pop("rel_gap", 0.01))
    cfg.quick_assign("pdhg_tol", float, 1e-7)
    for k, v in kw.items():
        cfg.quick_assign(k, type(v), v)
    return cfg


def test_rc_spoke_extracts_reduced_costs():
    b, names, specs = _sslp_batch(6)
    cfg = _cfg(iters=25, default_rho=20.0, rc_bound_tol=1e-3)
    hub = vanilla.ph_hub(cfg, b, scenario_names=names)
    rc_spoke = vanilla.reduced_costs_spoke(cfg)
    wheel = WheelSpinner(hub, [rc_spoke, vanilla.xhatxbar_spoke(cfg)])
    wheel.spin()
    sp = wheel.spcomm.spokes[0]
    assert sp.rc_global is not None
    assert sp.rc_scenario.shape == (b.num_scenarios, b.num_nonants)
    # at least one slot must have a usable (non-NaN) expected rc after
    # PH converges the LP relaxation
    assert np.isfinite(sp.rc_global).any()
    # the spoke's Lagrangian bound must be a valid outer bound
    sobj, _ = scipy_ef_solve(specs)
    assert sp.bound is not None and sp.bound <= sobj + 1e-3 * abs(sobj)


def test_rc_fixer_fixes_and_preserves_objective():
    b, names, specs = _sslp_batch(6)
    sobj, _ = scipy_ef_solve(specs)
    cfg = _cfg(iters=50, default_rho=20.0,
               rc_fix_fraction_iterk=0.3)
    hub = vanilla.ph_hub(cfg, b, scenario_names=names,
                         extensions=vanilla.reduced_costs_fixer(cfg))
    wheel = WheelSpinner(hub, [vanilla.reduced_costs_spoke(cfg),
                               vanilla.xhatxbar_spoke(cfg)])
    wheel.spin()
    fixer = wheel.opt.extobject
    assert fixer.nfixed() > 0          # something got fixed
    # fixing at the LP-LR bound values must not cut off the optimum:
    # the xhatxbar incumbent (a certified feasible evaluation) still
    # reaches the LP-relaxed EF optimum
    assert wheel.BestInnerBound >= sobj - 1e-3 * abs(sobj)  # validity
    assert wheel.BestInnerBound == pytest.approx(sobj, rel=2e-2)


def test_rc_bound_tightening():
    b, names, specs = _sslp_batch(4)
    cfg = _cfg(iters=40, default_rho=20.0,
               rc_bound_tightening=True, rc_fix_fraction_iterk=0.0)
    hub = vanilla.ph_hub(cfg, b, scenario_names=names,
                         extensions=vanilla.reduced_costs_fixer(cfg))
    wheel = WheelSpinner(hub, [vanilla.reduced_costs_spoke(cfg),
                               vanilla.xhatxbar_spoke(cfg)])
    wheel.spin()
    fixer = wheel.opt.extobject
    # with a finite gap and clean rcs, some bound should tighten on a
    # binary-server model; at minimum the machinery must not corrupt
    # the solve
    sobj, _ = scipy_ef_solve(specs)
    assert wheel.BestOuterBound <= sobj + 1e-3 * abs(sobj)
    assert wheel.BestInnerBound >= sobj - 1e-3 * abs(sobj)
    assert fixer.n_tightened >= 0


def test_ph_ob_spoke_farmer():
    specs = farmer_specs(3)
    sobj, _ = scipy_ef_solve(specs)
    b = batch_mod.from_specs(specs)
    cfg = _cfg(iters=40, default_rho=1.0, rel_gap=0.005)
    hub = vanilla.ph_hub(cfg, b)
    wheel = WheelSpinner(hub, [vanilla.ph_ob_spoke(cfg),
                               vanilla.xhatxbar_spoke(cfg)])
    wheel.spin()
    sp = wheel.spcomm.spokes[0]
    # the ph_ob Lagrangian bound is valid and eventually certified
    assert sp.bound is not None
    assert sp.bound <= sobj + 1.0
    # and it actually improves on the trivial wait-and-see bound
    assert sp.bound > wheel.opt.trivial_bound - 1.0
