# Bounds plane: Lagrangian outer bound, xhat inner bounds, subgradient.
# Oracle: farmer 3-scenario EF objective -108390 (scipy-verified in
# test_farmer_ef_ph.py).  For an LP, outer <= EF obj <= inner, and both
# tighten to the EF value at the PH fixed point.
import jax.numpy as jnp
import numpy as np
import pytest

from mpisppy_tpu.algos import lagrangian as lag_mod
from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.algos import xhat as xhat_mod
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import farmer
from mpisppy_tpu.ops import pdhg

FARMER_EF_OBJ = -108390.0


@pytest.fixture(scope="module")
def farmer3():
    names = farmer.scenario_names_creator(3)
    specs = [farmer.scenario_creator(nm, num_scens=3) for nm in names]
    return batch_mod.from_specs(specs)


@pytest.fixture(scope="module")
def ph_solved(farmer3):
    opts = ph_mod.PHOptions(default_rho=1.0, max_iterations=150,
                            conv_thresh=5e-2, subproblem_windows=10,
                            pdhg=pdhg.PDHGOptions(tol=1e-7))
    algo = ph_mod.PH(opts, farmer3)
    algo.ph_main()
    return algo


def test_lagrangian_zero_w_is_wait_and_see(farmer3):
    """L(0) = E[min f_s] (the trivial/wait-and-see bound), below EF obj."""
    W0 = jnp.zeros((farmer3.num_scenarios, farmer3.num_nonants),
                   farmer3.qp.c.dtype)
    res = lag_mod.lagrangian_bound(farmer3, W0,
                                   pdhg.PDHGOptions(tol=1e-7))
    assert bool(res.certified)
    assert float(res.bound) <= FARMER_EF_OBJ + 1.0
    # wait-and-see for farmer3 is about -115406 (known value)
    assert float(res.bound) == pytest.approx(-115405.6, rel=1e-3)


def test_lagrangian_with_ph_w_tightens(farmer3, ph_solved):
    """L(W*) with converged PH duals should be close to the EF objective
    and never above it (valid outer bound)."""
    res = lag_mod.lagrangian_bound(farmer3, ph_solved.state.W,
                                   pdhg.PDHGOptions(tol=1e-7))
    b = float(res.bound)
    assert b <= FARMER_EF_OBJ + 5.0
    assert b >= FARMER_EF_OBJ - 0.02 * abs(FARMER_EF_OBJ)


def test_xhat_xbar_inner_bound(farmer3, ph_solved):
    """E[f(xbar)] is a valid upper bound and ~EF obj at the optimum."""
    _, nodes = farmer3.node_average(
        farmer3.nonants(ph_solved.state.solver.x))
    res = xhat_mod.xhat_xbar(farmer3, nodes[0],
                             pdhg.PDHGOptions(tol=1e-7))
    assert bool(res.feasible)
    v = float(res.value)
    # valid upper bound modulo f32 solve accuracy (~1e-4 relative)
    assert v >= FARMER_EF_OBJ - 2e-3 * abs(FARMER_EF_OBJ)
    assert v <= FARMER_EF_OBJ + 0.02 * abs(FARMER_EF_OBJ)


def test_gap_closes(farmer3, ph_solved):
    lag = lag_mod.lagrangian_bound(farmer3, ph_solved.state.W,
                                   pdhg.PDHGOptions(tol=1e-7))
    _, nodes = farmer3.node_average(
        farmer3.nonants(ph_solved.state.solver.x))
    inner = xhat_mod.xhat_xbar(farmer3, nodes[0],
                               pdhg.PDHGOptions(tol=1e-7))
    outer_v, inner_v = float(lag.bound), float(inner.value)
    assert outer_v <= inner_v + 2e-3 * abs(inner_v)
    gap = (inner_v - outer_v) / max(1.0, abs(inner_v))
    assert gap < 0.02


def test_xhat_infeasible_candidate(farmer3):
    """A nonsense candidate (negative acreage impossible: l=0 clamps —
    use an over-acreage candidate violating the total-land row)."""
    bad = jnp.full((farmer3.num_nonants,), 400.0)  # sums to 1200 > 500
    res = xhat_mod.evaluate(farmer3, bad, pdhg.PDHGOptions(tol=1e-6))
    assert not bool(res.feasible)
    assert np.isinf(float(res.value))


def test_xhat_shuffle(farmer3, ph_solved):
    x_non = farmer3.nonants(ph_solved.state.solver.x)
    ids = jnp.asarray([0, 1, 2])
    vals, feas, _, comps = xhat_mod.xhat_shuffle(farmer3, x_non, ids, 3,
                                                 pdhg.PDHGOptions(tol=1e-6))
    assert bool(feas.all())
    # every candidate evaluation is a valid upper bound (f32 slack)
    assert float(jnp.min(vals)) >= FARMER_EF_OBJ - 2e-3 * abs(FARMER_EF_OBJ)
    # converged evaluations carry (near) zero first-order compensation
    assert float(jnp.max(comps)) <= 1e-3 * abs(FARMER_EF_OBJ)


def test_slam_heuristic(farmer3, ph_solved):
    x_non = farmer3.nonants(ph_solved.state.solver.x)
    res = xhat_mod.slam_heuristic(farmer3, x_non, sense_max=False,
                                  opts=pdhg.PDHGOptions(tol=1e-6))
    # slam-min of acreage is feasible (land constraint is <=)
    assert bool(res.feasible)
    assert float(res.value) >= FARMER_EF_OBJ - 2e-3 * abs(FARMER_EF_OBJ)


def test_subgradient_improves(farmer3):
    opts = pdhg.PDHGOptions(tol=1e-6)
    st = lag_mod.subgradient_init(farmer3, opts)
    rho = jnp.asarray(1.0, farmer3.qp.c.dtype)
    for _ in range(20):
        st = lag_mod.subgradient_step(farmer3, st, rho, opts, n_windows=40)
    assert float(st.best_bound) <= FARMER_EF_OBJ + 2e-3 * abs(FARMER_EF_OBJ)
    # best bound beats L(0) (wait-and-see)
    assert float(st.best_bound) > -115405.0


# ---------------------------------------------------------------------------
# comp-tightness publication gate (ADVICE r5: the evaluators' first-
# order infeasibility compensation must be gated like every other
# publication path — fused _eval_step, EFXhatInnerBound)
# ---------------------------------------------------------------------------
def _mk_result(batch, comp, value):
    S = batch.num_scenarios
    return xhat_mod.XhatResult(
        value=jnp.asarray(value, jnp.float32),
        per_scenario=jnp.zeros(S, jnp.float32),
        feasible=jnp.asarray(np.isfinite(value)),
        primal_resid=jnp.zeros(S, jnp.float32),
        status=jnp.zeros(S, jnp.int32),
        comp=jnp.full((S,), comp, jnp.float32))


def test_comp_tight_gate(farmer3):
    assert xhat_mod.comp_tight(farmer3, _mk_result(farmer3, 0.0, -100.0))
    # loose compensation (50% of the value) must NOT publish
    assert not xhat_mod.comp_tight(farmer3, _mk_result(farmer3, 50.0,
                                                       -100.0))
    assert not xhat_mod.comp_tight(farmer3, _mk_result(farmer3, 0.0,
                                                       np.inf))
    # the gate is RELATIVE: the same absolute comp passes at large |value|
    assert xhat_mod.comp_tight(farmer3, _mk_result(farmer3, 0.15,
                                                   -1000.0))
    assert not xhat_mod.comp_tight(farmer3, _mk_result(farmer3, 0.15,
                                                       -10.0))


def test_inner_spoke_harvest_gates_on_comp(farmer3):
    """InnerBoundSpoke.harvest withholds a feasible-but-loose value
    (regression: the blocking warm-rescue path used to publish through
    this gate-free, the hydro +37% case)."""
    from mpisppy_tpu.cylinders.spoke import InnerBoundSpoke

    class _Opt:
        batch = farmer3

    xhat = np.zeros(farmer3.num_nonants)
    spoke = InnerBoundSpoke(_Opt())
    spoke._pending = (_mk_result(farmer3, 50.0, -100.0), xhat)
    assert spoke.harvest() is None          # loose: withheld
    spoke._pending = (_mk_result(farmer3, 0.0, -100.0), xhat)
    assert spoke.harvest() == pytest.approx(-100.0)   # tight: published


def test_evaluators_return_safety_scaled_comp(farmer3, ph_solved):
    """The evaluators expose the (safety-scaled, xhat.COMP_SAFETY)
    compensation their published values already include; converged
    solves carry ~zero."""
    from mpisppy_tpu.ops import boxqp

    assert xhat_mod.COMP_SAFETY >= 2.0
    _, nodes = farmer3.node_average(
        farmer3.nonants(ph_solved.state.solver.x))
    res = xhat_mod.evaluate(farmer3, nodes[0],
                            pdhg.PDHGOptions(tol=1e-7))
    assert bool(res.feasible)
    assert float(jnp.max(res.comp)) >= 0.0
    assert xhat_mod.comp_tight(farmer3, res)
    # behavioral contract: comp IS the safety-scaled exact-penalty term
    # COMP_SAFETY * sum(|y| * viol) of the returned solver state.  A
    # deliberately truncated warm solve (loose tol, tiny budget, and a
    # generous feas_tol so the stalled-tail rescue stays out of the
    # way) leaves nonzero violation to scale.
    qp = farmer3.with_fixed_nonants(nodes[0])
    lo_opts = pdhg.PDHGOptions(tol=1e-2, max_iters=100)
    res_w, st = xhat_mod.evaluate_warm(
        farmer3, nodes[0], pdhg.init_state(qp, lo_opts), lo_opts,
        feas_tol=1e6)
    expect = xhat_mod.COMP_SAFETY * np.sum(
        np.abs(np.asarray(st.y))
        * np.asarray(boxqp.primal_residual(qp, st.x)), axis=-1)
    assert np.allclose(np.asarray(res_w.comp), expect,
                       rtol=1e-5, atol=1e-7)
