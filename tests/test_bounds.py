# Bounds plane: Lagrangian outer bound, xhat inner bounds, subgradient.
# Oracle: farmer 3-scenario EF objective -108390 (scipy-verified in
# test_farmer_ef_ph.py).  For an LP, outer <= EF obj <= inner, and both
# tighten to the EF value at the PH fixed point.
import jax.numpy as jnp
import numpy as np
import pytest

from mpisppy_tpu.algos import lagrangian as lag_mod
from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.algos import xhat as xhat_mod
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import farmer
from mpisppy_tpu.ops import pdhg

FARMER_EF_OBJ = -108390.0


@pytest.fixture(scope="module")
def farmer3():
    names = farmer.scenario_names_creator(3)
    specs = [farmer.scenario_creator(nm, num_scens=3) for nm in names]
    return batch_mod.from_specs(specs)


@pytest.fixture(scope="module")
def ph_solved(farmer3):
    opts = ph_mod.PHOptions(default_rho=1.0, max_iterations=150,
                            conv_thresh=5e-2, subproblem_windows=10,
                            pdhg=pdhg.PDHGOptions(tol=1e-7))
    algo = ph_mod.PH(opts, farmer3)
    algo.ph_main()
    return algo


def test_lagrangian_zero_w_is_wait_and_see(farmer3):
    """L(0) = E[min f_s] (the trivial/wait-and-see bound), below EF obj."""
    W0 = jnp.zeros((farmer3.num_scenarios, farmer3.num_nonants),
                   farmer3.qp.c.dtype)
    res = lag_mod.lagrangian_bound(farmer3, W0,
                                   pdhg.PDHGOptions(tol=1e-7))
    assert bool(res.certified)
    assert float(res.bound) <= FARMER_EF_OBJ + 1.0
    # wait-and-see for farmer3 is about -115406 (known value)
    assert float(res.bound) == pytest.approx(-115405.6, rel=1e-3)


def test_lagrangian_with_ph_w_tightens(farmer3, ph_solved):
    """L(W*) with converged PH duals should be close to the EF objective
    and never above it (valid outer bound)."""
    res = lag_mod.lagrangian_bound(farmer3, ph_solved.state.W,
                                   pdhg.PDHGOptions(tol=1e-7))
    b = float(res.bound)
    assert b <= FARMER_EF_OBJ + 5.0
    assert b >= FARMER_EF_OBJ - 0.02 * abs(FARMER_EF_OBJ)


def test_xhat_xbar_inner_bound(farmer3, ph_solved):
    """E[f(xbar)] is a valid upper bound and ~EF obj at the optimum."""
    _, nodes = farmer3.node_average(
        farmer3.nonants(ph_solved.state.solver.x))
    res = xhat_mod.xhat_xbar(farmer3, nodes[0],
                             pdhg.PDHGOptions(tol=1e-7))
    assert bool(res.feasible)
    v = float(res.value)
    # valid upper bound modulo f32 solve accuracy (~1e-4 relative)
    assert v >= FARMER_EF_OBJ - 2e-3 * abs(FARMER_EF_OBJ)
    assert v <= FARMER_EF_OBJ + 0.02 * abs(FARMER_EF_OBJ)


def test_gap_closes(farmer3, ph_solved):
    lag = lag_mod.lagrangian_bound(farmer3, ph_solved.state.W,
                                   pdhg.PDHGOptions(tol=1e-7))
    _, nodes = farmer3.node_average(
        farmer3.nonants(ph_solved.state.solver.x))
    inner = xhat_mod.xhat_xbar(farmer3, nodes[0],
                               pdhg.PDHGOptions(tol=1e-7))
    outer_v, inner_v = float(lag.bound), float(inner.value)
    assert outer_v <= inner_v + 2e-3 * abs(inner_v)
    gap = (inner_v - outer_v) / max(1.0, abs(inner_v))
    assert gap < 0.02


def test_xhat_infeasible_candidate(farmer3):
    """A nonsense candidate (negative acreage impossible: l=0 clamps —
    use an over-acreage candidate violating the total-land row)."""
    bad = jnp.full((farmer3.num_nonants,), 400.0)  # sums to 1200 > 500
    res = xhat_mod.evaluate(farmer3, bad, pdhg.PDHGOptions(tol=1e-6))
    assert not bool(res.feasible)
    assert np.isinf(float(res.value))


def test_xhat_shuffle(farmer3, ph_solved):
    x_non = farmer3.nonants(ph_solved.state.solver.x)
    ids = jnp.asarray([0, 1, 2])
    vals, feas, _ = xhat_mod.xhat_shuffle(farmer3, x_non, ids, 3,
                                          pdhg.PDHGOptions(tol=1e-6))
    assert bool(feas.all())
    # every candidate evaluation is a valid upper bound (f32 slack)
    assert float(jnp.min(vals)) >= FARMER_EF_OBJ - 2e-3 * abs(FARMER_EF_OBJ)


def test_slam_heuristic(farmer3, ph_solved):
    x_non = farmer3.nonants(ph_solved.state.solver.x)
    res = xhat_mod.slam_heuristic(farmer3, x_non, sense_max=False,
                                  opts=pdhg.PDHGOptions(tol=1e-6))
    # slam-min of acreage is feasible (land constraint is <=)
    assert bool(res.feasible)
    assert float(res.value) >= FARMER_EF_OBJ - 2e-3 * abs(FARMER_EF_OBJ)


def test_subgradient_improves(farmer3):
    opts = pdhg.PDHGOptions(tol=1e-6)
    st = lag_mod.subgradient_init(farmer3, opts)
    rho = jnp.asarray(1.0, farmer3.qp.c.dtype)
    for _ in range(20):
        st = lag_mod.subgradient_step(farmer3, st, rho, opts, n_windows=40)
    assert float(st.best_bound) <= FARMER_EF_OBJ + 2e-3 * abs(FARMER_EF_OBJ)
    # best bound beats L(0) (wait-and-see)
    assert float(st.best_bound) > -115405.0
