# Config system + vanilla factories + generic_cylinders CLI
# (the TPU analogs of ref:mpisppy/utils/config.py, cfg_vanilla.py,
# generic_cylinders.py).
import json
import subprocess
import sys

import numpy as np
import pytest

from mpisppy_tpu.utils.config import Config


def test_config_declare_parse():
    cfg = Config()
    cfg.popular_args()
    cfg.ph_args()
    cfg.lagrangian_args()
    cfg.parse_command_line("t", ["--default-rho", "2.5", "--lagrangian",
                                 "--max-iterations", "7"])
    assert cfg.default_rho == 2.5
    assert cfg.lagrangian is True
    assert cfg.max_iterations == 7
    assert cfg.get("abs_gap") is None
    # dict-style access and membership
    assert "default_rho" in cfg
    assert cfg["default_rho"] == 2.5


def test_config_quick_assign_and_model_api():
    from mpisppy_tpu.models import farmer
    cfg = Config()
    farmer.inparser_adder(cfg)
    cfg.num_scens = 3
    cfg.crops_multiplier = 2
    kw = farmer.kw_creator(cfg)
    assert kw["crops_multiplier"] == 2
    assert kw["num_scens"] == 3


def test_vanilla_factories_run_wheel():
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.spin_the_wheel import WheelSpinner
    from mpisppy_tpu.utils import cfg_vanilla as vanilla

    cfg = Config()
    cfg.popular_args()
    cfg.ph_args()
    cfg.num_scens_optional()
    cfg.parse_command_line("t", ["--num-scens", "3", "--max-iterations",
                                 "40", "--rel-gap", "0.01",
                                 "--convthresh", "0"])
    names = farmer.scenario_names_creator(3)
    specs = [farmer.scenario_creator(nm, num_scens=3) for nm in names]
    b = batch_mod.from_specs(specs)
    hub = vanilla.ph_hub(cfg, b, scenario_names=names)
    spokes = [vanilla.lagrangian_spoke(cfg), vanilla.xhatxbar_spoke(cfg)]
    wheel = WheelSpinner(hub, spokes).spin()
    _, rel_gap = wheel.spcomm.compute_gaps()
    assert rel_gap <= 0.01
    assert wheel.BestInnerBound == pytest.approx(-108390.0, rel=5e-3)


@pytest.mark.parametrize("extra", [[], ["--EF"],
                                   ["--fused-wheel", "--slammin"],
                                   ["--fused-wheel",
                                    "--async-staleness", "1"]])
def test_cli_end_to_end(tmp_path, extra):
    """`python -m mpisppy_tpu --module-name ...farmer` runs PH (or EF)
    end-to-end (VERDICT r1 item 10 'Done=' criterion)."""
    cmd = [sys.executable, "-m", "mpisppy_tpu",
           "--module-name", "mpisppy_tpu.models.farmer",
           "--num-scens", "3", "--max-iterations", "40",
           "--rel-gap", "0.01", "--convthresh", "0",
           "--lagrangian", "--xhatxbar"] + extra
    out = subprocess.run(cmd, capture_output=True, text=True,
                         cwd="/root/repo", timeout=600,
                         env={"PATH": "/usr/bin:/bin:/usr/local/bin",
                              "JAX_PLATFORMS": "cpu",
                              "HOME": "/root"})
    assert out.returncode == 0, out.stderr[-2000:]
    payload = json.loads(out.stdout.strip().splitlines()[-1])
    if "--EF" in extra:
        assert payload["EF_objective"] == pytest.approx(-108390.0,
                                                        rel=5e-3)
    else:
        assert payload["rel_gap"] <= 0.01
        # the async wheel terminates the moment the CERTIFIED 1% gap
        # lands, so its inner incumbent is only guaranteed to that
        # tolerance; the synchronous runs land tighter in practice
        tol = 1.1e-2 if "--async-staleness" in extra else 5e-3
        assert payload["inner_bound"] == pytest.approx(-108390.0, rel=tol)
