# Multi-host (multi-process) mesh path: 2 processes x 4 virtual CPU
# devices with gloo collectives — the DCN analog of the conftest's
# 8-device virtual mesh (round-2 review, missing #10; reference analog:
# `mpiexec -np 2` smoke tests, ref:mpisppy/tests/straight_tests.py).
import os
import re
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_env(xla_devices=None):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = repo
    if xla_devices is not None:
        env["XLA_FLAGS"] = \
            f"--xla_force_host_platform_device_count={xla_devices}"
        env["JAX_PLATFORMS"] = "cpu"
    return env


def test_two_process_four_device_dryrun():
    coord = f"127.0.0.1:{_free_port()}"
    env = _worker_env()
    cmd = [sys.executable, "-m",
           "mpisppy_tpu.parallel._multihost_dryrun", coord, "2"]
    procs = [subprocess.Popen(cmd + [str(pid), "4"], env=env,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
             for pid in (0, 1)]
    outs = []
    for p in procs:
        # stderr is CAPTURED and surfaced: a crashing worker previously
        # reported only "exit 1" with its traceback piped to DEVNULL
        out, err = p.communicate(timeout=550)
        assert p.returncode == 0, f"stdout:\n{out}\nstderr:\n{err}"
        outs.append(out)
    convs = []
    for out in outs:
        m = re.search(r"CONV ([\d.e+-]+) TB ([\d.e+-]+) procs (\d+) "
                      r"devices (\d+)", out)
        assert m, out
        assert m.group(3) == "2" and m.group(4) == "8", out
        convs.append(float(m.group(1)))
    # global reductions: both processes must compute the SAME conv
    assert convs[0] == pytest.approx(convs[1], rel=1e-6), convs


@pytest.mark.slow
def test_elastic_kill_one_host_round_trip(tmp_path):
    """ISSUE 17 multi-process fault domain: a host dies mid-wheel; the
    survivor detects it (beacon staleness + bounded harvest), cannot
    complete the emergency gather without the dead peer, exits 75
    (restartable) holding the iter-4 SYNCHRONIZED snapshot; a relaunch
    at the shrunk 6-device topology resumes from that snapshot and
    reaches the same certified gap as a fault-free baseline — gloo
    meshes cannot shrink live, so the elastic loop here is a
    driver-orchestrated restart."""
    coord = f"127.0.0.1:{_free_port()}"
    wd = str(tmp_path)
    cmd = [sys.executable, "-m",
           "mpisppy_tpu.parallel._elastic_dryrun"]
    procs = [subprocess.Popen(
        cmd + ["kill", coord, "2", str(pid), "4", wd],
        env=_worker_env(), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True) for pid in (0, 1)]
    outs = []
    for pid, p in enumerate(procs):
        out, err = p.communicate(timeout=550)
        outs.append(out)
        want = 75 if pid == 0 else 0   # survivor EX_TEMPFAIL, victim dies
        assert p.returncode == want, \
            f"pid {pid} rc {p.returncode}\nstdout:\n{out}\nstderr:\n{err}"
    m = re.search(r"HOSTLOST reason=([\w-]+) iter=(\d+) "
                  r"dead=\[1\] ckpt=1", outs[0])
    assert m, outs[0]

    # relaunch at the survivor topology (6 devices): re-shard 16 -> 18
    # and spin to the certified gap from the synchronized snapshot
    res = subprocess.run(cmd + ["resume", wd], env=_worker_env(6),
                         capture_output=True, text=True, timeout=550)
    assert res.returncode == 0, res.stderr
    base = subprocess.run(cmd + ["baseline", wd], env=_worker_env(8),
                          capture_output=True, text=True, timeout=550)
    assert base.returncode == 0, base.stderr
    pat = (r"inner=([\d.e+-]+) outer=([\d.e+-]+) gap=([\d.e+-]+) "
           r"start=(\d+) iter=(\d+) devices=(\d+)")
    mr = re.search(r"RESUME " + pat, res.stdout)
    mb = re.search(r"BASE " + pat, base.stdout)
    assert mr and mb, (res.stdout, base.stdout)
    assert mr.group(6) == "6" and mb.group(6) == "8"
    assert int(mr.group(4)) >= 4          # resumed, not restarted
    ir, orr, gr = (float(mr.group(i)) for i in (1, 2, 3))
    ib, ob, gb = (float(mb.group(i)) for i in (1, 2, 3))
    assert gr <= 5e-3 + 1e-6 and gb <= 5e-3 + 1e-6
    # both sides bracket the same EF objective
    slack = 5e-3 * max(abs(ir), abs(ib))
    assert orr <= ib + slack and ob <= ir + slack


@pytest.mark.slow
def test_elastic_partition_heals_without_reshard(tmp_path):
    """A partition (suppressed beacon delivery, beats 1-2) only drives
    the victim to SUSPECT under dead_after=3; the first post-partition
    beat heals it and the wheel completes with NO reshard — both
    processes certify the same bracket at the full topology."""
    coord = f"127.0.0.1:{_free_port()}"
    cmd = [sys.executable, "-m",
           "mpisppy_tpu.parallel._elastic_dryrun"]
    procs = [subprocess.Popen(
        cmd + ["partition", coord, "2", str(pid), "4", str(tmp_path)],
        env=_worker_env(), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True) for pid in (0, 1)]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=550)
        assert p.returncode == 0, f"stdout:\n{out}\nstderr:\n{err}"
        outs.append(out)
    vals = []
    for out in outs:
        m = re.search(r"PARTITION_OK inner=([\d.e+-]+) "
                      r"outer=([\d.e+-]+) gap=([\d.e+-]+) iter=(\d+) "
                      r"moves=([\w:,-]+) dead=\[\] epoch=(\d+)", out)
        assert m, out
        assert "DEAD" not in m.group(5)   # suspicion never killed anyone
        vals.append((float(m.group(1)), float(m.group(2))))
    # SPMD: both processes hold the identical bracket
    assert vals[0] == pytest.approx(vals[1], rel=1e-6)
    # the poller watched the partitioned host go SUSPECT then heal back
    # to UP, in that order, with no reshard in between
    moves0 = re.search(r"moves=([\w:,-]+)", outs[0]).group(1).split(",")
    assert "1:SUSPECT" in moves0, outs[0]
    assert "1:UP" in moves0[moves0.index("1:SUSPECT"):], outs[0]
