# Multi-host (multi-process) mesh path: 2 processes x 4 virtual CPU
# devices with gloo collectives — the DCN analog of the conftest's
# 8-device virtual mesh (round-2 review, missing #10; reference analog:
# `mpiexec -np 2` smoke tests, ref:mpisppy/tests/straight_tests.py).
import os
import re
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_four_device_dryrun():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = repo
    cmd = [sys.executable, "-m",
           "mpisppy_tpu.parallel._multihost_dryrun", coord, "2"]
    procs = [subprocess.Popen(cmd + [str(pid), "4"], env=env,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
             for pid in (0, 1)]
    outs = []
    for p in procs:
        # stderr is CAPTURED and surfaced: a crashing worker previously
        # reported only "exit 1" with its traceback piped to DEVNULL
        out, err = p.communicate(timeout=550)
        assert p.returncode == 0, f"stdout:\n{out}\nstderr:\n{err}"
        outs.append(out)
    convs = []
    for out in outs:
        m = re.search(r"CONV ([\d.e+-]+) TB ([\d.e+-]+) procs (\d+) "
                      r"devices (\d+)", out)
        assert m, out
        assert m.group(3) == "2" and m.group(4) == "8", out
        convs.append(float(m.group(1)))
    # global reductions: both processes must compute the SAME conv
    assert convs[0] == pytest.approx(convs[1], rel=1e-6), convs
