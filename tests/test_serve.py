# Multi-tenant wheel server (ISSUE 12; docs/serving.md): admission
# fairness + SLA ordering, typed backpressure, cross-session megabatch
# coalescing == per-session results, the per-session dispatch context
# token, the server end-to-end over a unix socket (real farmer wheel),
# preempt-mid-traffic resume, and the `telemetry watch --trace-dir`
# satellite.
import dataclasses
import json
import os
import threading
import time

import numpy as np
import pytest
import jax.numpy as jnp

from mpisppy_tpu import dispatch
from mpisppy_tpu.dispatch import DispatchOptions, SolveScheduler
from mpisppy_tpu.resilience import FaultPlan, ServeFault
from mpisppy_tpu.serve import (
    AdmissionRejected, FairQueue, SubmitRequest, ServeOptions,
    WheelServer,
)
from mpisppy_tpu.serve import loadgen, multiplex
from mpisppy_tpu.serve.engine import SyntheticEngine, WheelEngine
from mpisppy_tpu.serve.session import Session

from test_mip_bnb import random_mips


def _spec(tenant="acme", sla="throughput", **kw):
    kw.setdefault("model", "farmer")
    kw.setdefault("num_scens", 3)
    return SubmitRequest(tenant=tenant, sla=sla, **kw)


def _sess(tenant="acme", sla="throughput", **kw):
    return Session(_spec(tenant, sla, **kw))


# ---------------------------------------------------------------------------
# admission: fairness, SLA ordering, quotas, typed backpressure
# ---------------------------------------------------------------------------
def test_wfq_interleaves_a_flooding_tenant():
    """Tenant A floods 12 sessions, B submits 4: WFQ must interleave —
    every admitted B session appears within the first 2 pops of its
    'fair share' position, never starved behind A's backlog."""
    q = FairQueue(max_queued=64, default_quota=99)
    for _ in range(12):
        q.submit(_sess("A"))
    for _ in range(4):
        q.submit(_sess("B"))
    order = []
    while True:
        s = q.pop()
        if s is None:
            break
        order.append(s.tenant)
    # equal weights: the first 8 pops must alternate A/B until B dries
    assert order.count("B") == 4
    first8 = order[:8]
    assert first8.count("B") == 4, first8
    assert order[8:] == ["A"] * 8


def test_wfq_weights_bias_service():
    q = FairQueue(max_queued=64, default_quota=99,
                  weights={"big": 3.0, "small": 1.0})
    for _ in range(9):
        q.submit(_sess("big"))
        q.submit(_sess("small"))
    first8 = [q.pop().tenant for _ in range(8)]
    # 3:1 weights -> ~6 of the first 8 go to the heavy tenant
    assert first8.count("big") >= 5, first8


def test_sla_latency_class_jumps_queue_with_starvation_guard():
    q = FairQueue(max_queued=64, default_quota=99, latency_burst=2)
    for _ in range(4):
        q.submit(_sess("A", "throughput"))
    for _ in range(4):
        q.submit(_sess("B", "latency"))
    order = [(q.pop().sla) for _ in range(8)]
    # latency first, but the guard forces a throughput session through
    # after every `latency_burst` consecutive latency pops
    assert order[0] == "latency" and order[1] == "latency"
    assert "throughput" in order[:3 + 1], order
    assert order.count("latency") == 4


def test_quota_defers_and_release_resumes():
    q = FairQueue(default_quota=1)
    s1, s2 = _sess("A"), _sess("A")
    q.submit(s1)
    q.submit(s2)
    assert q.pop() is s1
    assert q.pop() is None          # A at quota; s2 must wait
    q.release(s1)
    assert q.pop() is s2


def test_pop_discards_reaped_sessions_without_charging_wfq():
    """A session settled terminal while queued (deadline-reaped) is
    discarded by pop() without burning the tenant's quota/virtual
    clock — a dead session must never cost a worker slot (review
    fix)."""
    q = FairQueue(default_quota=1)
    dead, live = _sess("A"), _sess("A")
    q.submit(dead)
    q.submit(live)
    dead.settle("failed", reason="deadline")
    got = q.pop()
    assert got is live
    t = q.stats()["tenants"]["A"]
    assert t["admitted"] == 1 and t["inflight"] == 1


def test_interner_pool_is_bounded():
    """FIFO eviction keeps the content-addressed pool bounded — an
    evicted entry only costs coalescence, never correctness (review
    fix)."""
    it = multiplex.StructureInterner(max_entries=4)
    arrays = [np.full((3, 3), float(i)) for i in range(10)]
    for a in arrays:
        it.intern(a)
    st = it.stats()
    assert st["entries"] <= 4 and st["evictions"] >= 6
    # a still-pooled digest keeps interning to the canonical object
    fresh = it.intern(np.full((3, 3), 9.0))
    assert fresh is arrays[9]


def test_backpressure_is_typed_never_a_hang():
    q = FairQueue(max_queued=2, max_queued_per_tenant=2)
    q.submit(_sess("A"))
    q.submit(_sess("A"))
    with pytest.raises(AdmissionRejected) as ei:
        q.submit(_sess("A"))
    assert ei.value.reason in ("queue-full", "tenant-queue-full")
    qt = FairQueue(max_queued=50, max_queued_per_tenant=1)
    qt.submit(_sess("A"))
    with pytest.raises(AdmissionRejected) as ei2:
        qt.submit(_sess("A"))
    assert ei2.value.reason == "tenant-queue-full"
    qt.drain()
    with pytest.raises(AdmissionRejected) as ei3:
        qt.submit(_sess("B"))
    assert ei3.value.reason == "draining"


# ---------------------------------------------------------------------------
# cross-session coalescing == per-session results (multiplex interning)
# ---------------------------------------------------------------------------
def _fake_solve(qp, d_col, int_cols, opts, **kw):
    from mpisppy_tpu.ops.bnb import BnBResult
    time.sleep(0.002)
    S = qp.c.shape[0]
    return BnBResult(
        x=jnp.zeros_like(qp.c),
        inner=jnp.sum(qp.c, axis=-1),
        outer=jnp.sum(qp.c, axis=-1) - 1.0,
        gap=jnp.zeros((S,), qp.c.dtype),
        feasible=jnp.ones((S,), bool),
        nodes_solved=jnp.ones((S,), jnp.int32))


def test_cross_session_coalescing_matches_per_session_results():
    """Two 'sessions' build equal-but-distinct shared structure; after
    interning, their concurrent submits coalesce into ONE megabatch
    (same mergeable identity) and each session's lanes come back
    exactly as its solo solve — coalescing is a perf transform, not a
    semantic one."""
    base, _, _ = random_mips(S=2, n=6, m=4)
    # SHARED structure: one (m, n) A broadcast across lanes — the
    # identity-keyed case the interner exists for (a batched 3-D A
    # carries no identity and coalesces by shape alone)
    A_shared = np.asarray(base.A)[0]
    interner = multiplex.StructureInterner()

    def session_qp(seed):
        # each session rebuilds its own equal A (distinct object)
        rng = np.random.default_rng(seed)
        qp = dataclasses.replace(
            base, A=jnp.asarray(A_shared.copy()),
            c=jnp.asarray(rng.standard_normal((2, 6)).astype(np.float32)))
        return multiplex.intern_qp(qp, interner=interner)

    qp1, qp2 = session_qp(1), session_qp(2)
    assert qp1.A is qp2.A, "interning must canonicalize equal A"
    st = interner.stats()
    assert st["hits"] >= 1

    sched = SolveScheduler(
        DispatchOptions(max_wait_ms=30.0, coalesce=True),
        solve_fn=_fake_solve)
    d = jnp.ones(6, jnp.float32)
    ic = np.arange(2, dtype=np.int32)
    t1 = sched.submit(qp1, d, ic)
    t2 = sched.submit(qp2, d, ic)
    r1, r2 = t1.result(), t2.result()
    stats = sched.stats()
    sched.close()
    assert stats["batches"] == 1, "equal structure must coalesce"
    assert stats["coalesced_lanes"] == 4
    # by_key (ISSUE 12 satellite): the one shared key carries the lanes
    assert len(stats["by_key"]) == 1
    row = next(iter(stats["by_key"].values()))
    assert row["lanes"] == 4 and row["coalesced_lanes"] == 4
    np.testing.assert_allclose(np.asarray(r1.inner),
                               np.asarray(qp1.c).sum(-1), atol=1e-5)
    np.testing.assert_allclose(np.asarray(r2.inner),
                               np.asarray(qp2.c).sum(-1), atol=1e-5)

    # WITHOUT interning the same submits do NOT coalesce (distinct A
    # identity) — the control proving the interner is the mechanism
    sched2 = SolveScheduler(
        DispatchOptions(max_wait_ms=30.0, coalesce=True),
        solve_fn=_fake_solve)
    qa = dataclasses.replace(base, A=jnp.asarray(A_shared.copy()))
    qb = dataclasses.replace(base, A=jnp.asarray(A_shared.copy()))
    ta, tb = sched2.submit(qa, d, ic), sched2.submit(qb, d, ic)
    ta.result(), tb.result()
    assert sched2.stats()["batches"] == 2
    sched2.close()


def test_session_context_token_attributes_concurrent_sessions():
    """Two threads with different session tokens submit concurrently:
    the megabatch event carries the per-session breakdown, and the
    analyzer joins each dispatch to the RIGHT session's run — no seq
    heuristics (ISSUE 12 satellite)."""
    from mpisppy_tpu import telemetry as tel
    from mpisppy_tpu.telemetry import analyze as an

    base, _, _ = random_mips(S=2, n=6, m=4)
    d = jnp.ones(6, jnp.float32)
    ic = np.arange(2, dtype=np.int32)
    rows = []

    class _Capture:
        def handle(self, event):
            rows.append(json.loads(event.to_json()))

    bus = tel.EventBus()
    bus.subscribe(_Capture())
    sched = SolveScheduler(
        DispatchOptions(max_wait_ms=20.0, coalesce=True),
        solve_fn=_fake_solve, bus=bus, run="scheduler-run")
    barrier = threading.Barrier(2)

    def worker(run_id, it):
        dispatch.set_session_context(run_id, it)
        barrier.wait()
        t = sched.submit(base, d, ic)
        t.result()
        dispatch.clear_session_context()

    th = [threading.Thread(target=worker, args=(f"run{i}", 3 + i))
          for i in range(2)]
    for t in th:
        t.start()
    for t in th:
        t.join()
    sched.close()
    mbs = [r for r in rows if r["kind"] == "dispatch"]
    assert mbs
    # every lane is attributed to a session token, whichever way the
    # two submits landed (one coalesced batch or two)
    seen = {}
    for r in mbs:
        sess = r["data"].get("sessions")
        if sess is None:
            # single-session batch: the event's own run IS the token
            assert r["run"] in ("run0", "run1")
            seen[r["run"]] = r["iter"]
        else:
            for s in sess:
                seen[s["run"]] = s["iter"]
    assert seen == {"run0": 3, "run1": 4}

    # analyzer join: a trace holding only these dispatch rows resolves
    # per-session megabatches for each run
    for i, run_id in enumerate(("run0", "run1")):
        trace = [dict(r, kind="run-start", data={}) for r in mbs[:1]]
        trace[0]["run"] = run_id
        model = an.build_run_model(trace + mbs, run=run_id)
        assert len(model.megabatches) >= 1
        assert all(b["iter"] == 3 + i for b in model.megabatches
                   if b.get("sessions") is None or True)


# ---------------------------------------------------------------------------
# server end-to-end
# ---------------------------------------------------------------------------
def _start_server(tmp_path, engine=None, **opt_kw):
    opt_kw.setdefault("unix_path", str(tmp_path / "wheel.sock"))
    opt_kw.setdefault("trace_dir", str(tmp_path / "traces"))
    opt_kw.setdefault("spool_dir", str(tmp_path / "spool"))
    opt_kw.setdefault("max_running", 2)
    if engine is not None:
        opt_kw.setdefault("multiplex", False)
        opt_kw["engine"] = engine
    return WheelServer(ServeOptions(**opt_kw)).start()


def test_server_farmer_session_end_to_end(tmp_path):
    """A real farmer wheel served over the unix socket: progress
    events stream, the terminal outcome matches a direct wheel run,
    and the per-session JSONL trace analyzes as that one run."""
    from mpisppy_tpu.telemetry import analyze as an

    srv = _start_server(tmp_path, multiplex=True)
    try:
        cl = loadgen.ServeClient(srv.address, timeout=240.0)
        rec = loadgen.run_session(cl, _spec(
            tenant="acme", gap_target=0.01, max_iterations=150))
        cl.close()
    finally:
        srv.stop()
    assert rec["outcome"] == "done", rec
    assert rec["time_to_gap_s"] is not None
    trace = tmp_path / "traces" / f"session-{rec['session']}.jsonl"
    assert trace.exists()
    rep = an.analyze_path(str(trace))
    assert rep["run"]["exit"]["reason"] == "converged"
    assert rep["bounds"]["final_rel_gap"] <= 0.01 + 1e-9
    # the session lifecycle rode the same trace
    kinds = {json.loads(ln)["kind"]
             for ln in trace.read_text().splitlines()}
    assert "session-state" in kinds and "hub-iteration" in kinds

    # direct (serverless) run of the same spec for the ground truth
    eng = WheelEngine(multiplexed=True)
    s = Session(_spec(tenant="direct", gap_target=0.01,
                      max_iterations=150))
    verdict, payload = eng.run(s)
    assert verdict == "done"
    assert payload["rel_gap"] <= 0.01 + 1e-9


def test_typed_rejection_and_disconnect_paths(tmp_path):
    """Backpressure answers a flood with typed rejects in the ack (the
    client can never mistake one for a hang), and a client vanishing
    mid-run leaves the session to its terminal state with the quota
    restored."""
    eng = SyntheticEngine(iters=40, step_s=0.01)
    srv = _start_server(tmp_path, engine=eng, max_running=1,
                        max_queued=2, max_queued_per_tenant=2)
    try:
        cl = loadgen.ServeClient(srv.address)
        acks = []
        for _ in range(6):
            acks.append(cl.submit(_spec(tenant="flood")))
        rejected = [a for a in acks if not a.get("ok")]
        accepted = [a for a in acks if a.get("ok")]
        assert rejected, "queue caps must reject typed"
        assert all(a.get("error") == "rejected"
                   and a.get("reason") in ("queue-full",
                                           "tenant-queue-full")
                   for a in rejected)
        # disconnect mid-run: close without reading the stream
        cl.close()
        deadline = time.time() + 30.0
        while time.time() < deadline:
            states = srv.stats()["states"]
            if states.get("DONE", 0) + states.get("FAILED", 0) \
                    >= len(accepted):
                break
            time.sleep(0.05)
        states = srv.stats()["states"]
        assert states.get("DONE", 0) >= 1
        # quota fully restored: nothing left running or stuck
        adm_stats = srv.stats()["admission"]["tenants"]["flood"]
        assert adm_stats["inflight"] == 0
    finally:
        srv.stop()


def test_status_op_over_the_socket(tmp_path):
    """The `status` request (ISSUE 16 satellite): a plain client gets
    the replica's health summary over the wire — session counts by
    state, queue depth, free slots, and the engine's interner digests
    (the fleet router's placement/health probe rides this op)."""
    eng = SyntheticEngine(iters=30, step_s=0.01)
    srv = _start_server(tmp_path, engine=eng, max_running=1,
                        max_queued=8, replica_id="r7")
    try:
        cl = loadgen.ServeClient(srv.address)
        for _ in range(3):
            assert cl.submit(_spec(tenant="acme")).get("ok")
        cl.send({"op": "status"})
        msg = cl.recv()
        while msg.get("event") is not None or "status" not in msg:
            msg = cl.recv()     # skip interleaved session events
        assert msg["ok"] and msg["op"] == "status"
        st = msg["status"]
        assert st["replica"] == "r7"
        assert st["running"] + st["queued"] == 3
        assert st["free_slots"] == 0
        assert st["draining"] is False
        assert sum(st["states"].values()) == 3
        # the WheelEngine variant carries interner digests (the
        # structure-affinity routing signal); the synthetic one has no
        # interner and reports the empty tuple
        assert st["interner_digests"] == []
        cl.close()
    finally:
        srv.stop()
    # an engine WITH an interner reports its digests through the same
    # status surface (the structure-affinity routing signal)
    intern = multiplex.StructureInterner()
    intern.intern(np.arange(3.0))
    assert len(intern.digests()) == 1
    eng2 = WheelEngine(multiplexed=True, interner=intern)
    srv2 = _start_server(tmp_path / "m", engine=eng2, multiplex=True)
    try:
        assert srv2.status()["interner_digests"] == \
            list(intern.digests())
    finally:
        srv2.stop()


def test_bad_session_args_fail_typed_not_hang(tmp_path):
    """Client-supplied session args that argparse rejects (SystemExit,
    a BaseException) must surface as a typed terminal `failed` — not a
    dead worker and a silent hang."""
    srv = _start_server(tmp_path, multiplex=False, max_running=1)
    try:
        cl = loadgen.ServeClient(srv.address, timeout=60.0)
        rec = loadgen.run_session(cl, _spec(
            tenant="acme", args=("--no-such-flag",)))
        cl.close()
    finally:
        srv.stop()
    assert rec["outcome"] == "failed"
    assert rec["reason"] == "ValueError"


def test_session_deadline_is_a_typed_failure(tmp_path):
    """A hanging session (ServeFault hang) resolves at its deadline
    with a typed `failed` reason=deadline — the no-hang contract."""
    plan = FaultPlan(seed=3, serves=(
        ServeFault("hang", tenant="acme", at_sessions=(0,),
                   hang_s=60.0),))
    eng = SyntheticEngine(iters=3, step_s=0.005)
    srv = _start_server(tmp_path, engine=eng, fault_plan=plan)
    try:
        cl = loadgen.ServeClient(srv.address, timeout=30.0)
        rec = loadgen.run_session(cl, _spec(tenant="acme",
                                            deadline_s=1.0))
        cl.close()
    finally:
        srv.stop()
    assert rec["outcome"] == "failed"
    assert rec["reason"] == "deadline"
    assert ("serve", "hang acme#0") in plan.fired


def test_preempt_mid_traffic_resume_round_trip(tmp_path):
    """The acceptance round trip: a SimulatedPreemption mid-run
    emergency-saves, the session re-enters the queue DEGRADED,
    restores from its checkpoint, and finishes with the fault-free
    bounds — the client stream shows preempted -> restored -> done
    with no terminal failure (no client-visible state loss)."""
    # fault-free ground truth
    eng = WheelEngine(multiplexed=False)
    s0 = Session(_spec(tenant="truth", gap_target=0.01,
                       max_iterations=150))
    v0, base = eng.run(s0)
    assert v0 == "done"

    plan = FaultPlan(seed=5, preempt_at_iter=4)
    srv = _start_server(tmp_path, multiplex=False, fault_plan=plan)
    try:
        cl = loadgen.ServeClient(srv.address, timeout=240.0)
        rec = loadgen.run_session(cl, _spec(
            tenant="acme", gap_target=0.01, max_iterations=150))
        cl.close()
    finally:
        srv.stop()
    assert ("preemption", "iter4") in plan.fired
    assert rec["outcome"] == "done", rec
    assert rec["preempted"] == 1
    # resumed run reproduces the fault-free certified bounds
    stats = srv.stats()
    assert stats["preemptions"] == 1
    sess = list(srv._sessions.values())[0]
    assert sess.outcome["event"] == "done"
    assert sess.outcome["rel_gap"] <= 0.01 + 1e-9
    assert sess.outcome["inner"] == pytest.approx(base["inner"],
                                                  rel=1e-2)
    assert sess.outcome["outer"] == pytest.approx(base["outer"],
                                                  rel=1e-2)
    # the trace records the preemption checkpoint round trip
    trace = tmp_path / "traces" / f"session-{rec['session']}.jsonl"
    kinds = [json.loads(ln)["kind"]
             for ln in trace.read_text().splitlines()]
    assert "checkpoint-write" in kinds
    assert "checkpoint-restore" in kinds


# ---------------------------------------------------------------------------
# watch --trace-dir (satellite)
# ---------------------------------------------------------------------------
def test_watch_trace_dir_renders_tenant_table(tmp_path):
    eng = SyntheticEngine(iters=5, step_s=0.002)
    srv = _start_server(tmp_path, engine=eng)
    try:
        recs = loadgen.run_load(srv.address, n_clients=4,
                                sessions_each=1,
                                tenants=("acme", "zeta"),
                                deadline_s=30.0)
    finally:
        srv.stop()
    assert all(r["outcome"] == "done" for r in recs)
    from mpisppy_tpu.telemetry import watch as w
    import io
    out = io.StringIO()
    rc = w.watch_dir(str(tmp_path / "traces"), once=True, out=out)
    assert rc == 0
    text = out.getvalue()
    assert "tenant acme" in text and "tenant zeta" in text
    assert "DONE" in text
    # the CLI surface
    from mpisppy_tpu.telemetry.__main__ import main as tel_main
    assert tel_main(["watch", "--trace-dir",
                     str(tmp_path / "traces"), "--once"]) == 0
    # exactly one of --trace-jsonl/--trace-dir
    assert tel_main(["watch", "--once"]) == 1
