# Telemetry subsystem (mpisppy_tpu/telemetry/, docs/telemetry.md):
# event bus + typed events + sinks, the back-compat Hub.trace/sp.trace
# views, on-device PDHG kernel counters with the telemetry=off HLO
# byte-identity contract (mirroring test_chaos.py's disarmed-plan
# check), profiler hooks, the metrics exporter's shared snapshot
# schema, the no-bare-print lint, and the phtracker atomic-flush fix.
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from mpisppy_tpu import telemetry
from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.cylinders import (
    LagrangianOuterBound, PHHub, XhatXbarInnerBound,
)
from mpisppy_tpu.models import farmer
from mpisppy_tpu.ops import pdhg
from mpisppy_tpu.spin_the_wheel import WheelSpinner
from mpisppy_tpu.telemetry import console, counters as kcounters, metrics


def farmer_batch(num_scens=3):
    names = farmer.scenario_names_creator(num_scens)
    specs = [farmer.scenario_creator(nm, num_scens=num_scens)
             for nm in names]
    return batch_mod.from_specs(specs)


def hub_dict(batch, hub_extra=None, max_iterations=6, telemetry_on=False):
    opts = ph_mod.PHOptions(
        default_rho=1.0, max_iterations=max_iterations, conv_thresh=0.0,
        subproblem_windows=10,
        pdhg=pdhg.PDHGOptions(tol=1e-7, telemetry=telemetry_on))
    hub_opts = {"rel_gap": 5e-3}
    hub_opts.update(hub_extra or {})
    return {
        "hub_class": PHHub,
        "hub_kwargs": {"options": hub_opts},
        "opt_class": ph_mod.PH,
        "opt_kwargs": {"options": opts, "batch": batch},
    }


BOTH_SPOKES = [
    {"spoke_class": LagrangianOuterBound, "opt_kwargs": {"options": {}}},
    {"spoke_class": XhatXbarInnerBound, "opt_kwargs": {"options": {}}},
]


# ---------------------------------------------------------------------------
# Event schema round-trip + ordering (ISSUE 3 satellite)
# ---------------------------------------------------------------------------
def test_jsonl_event_schema_roundtrip_and_ordering(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    bus = telemetry.EventBus()
    bus.subscribe(telemetry.JsonlSink(path))
    run = telemetry.new_run_id()
    bus.emit(telemetry.HUB_ITERATION, run=run, cyl="hub", hub_iter=1,
             outer=-110.0, inner=float("inf"), rel_gap=float("nan"))
    bus.emit(telemetry.SPOKE_HARVEST, run=run, cyl="hub", hub_iter=1,
             spoke=0, sense="outer", bound=np.float32(-109.5))
    bus.emit(telemetry.CHECKPOINT_WRITE, run=run, cyl="hub", hub_iter=2,
             path="/x/y.npz", bytes=123)
    bus.close()

    rows = [json.loads(line) for line in open(path)]
    assert [r["kind"] for r in rows] == [
        "hub-iteration", "spoke-harvest", "checkpoint-write"]
    # every row carries the full envelope
    for r in rows:
        assert set(r) >= {"kind", "seq", "t_wall", "t_mono", "run",
                          "cyl", "data"}
        assert r["run"] == run
    # total order: seq strictly increasing, monotonic clock nondecreasing
    seqs = [r["seq"] for r in rows]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    monos = [r["t_mono"] for r in rows]
    assert monos == sorted(monos)
    # strict JSON: non-finite floats serialize as null, numpy scalars
    # as plain numbers
    assert rows[0]["data"]["inner"] is None
    assert rows[0]["data"]["rel_gap"] is None
    assert rows[1]["data"]["bound"] == pytest.approx(-109.5)
    assert rows[0]["iter"] == 1 and rows[2]["iter"] == 2
    # the file ends cleanly (closed sink) and every line re-serializes
    for r in rows:
        json.dumps(r)


def test_bus_isolates_failing_sink():
    class Bomb(telemetry.Sink):
        def handle(self, event):
            raise RuntimeError("boom")

    seen = []

    class Ok(telemetry.Sink):
        def handle(self, event):
            seen.append(event.kind)

    bus = telemetry.EventBus()
    bus.subscribe(Bomb())
    bus.subscribe(Ok())
    for _ in range(5):
        bus.emit(telemetry.CONSOLE, msg="x")
    assert len(seen) == 5          # healthy sink saw everything
    assert len(bus.sinks) == 1     # bomb detached after repeated fails


# ---------------------------------------------------------------------------
# On-device kernel counters
# ---------------------------------------------------------------------------
def test_kernel_counters_accumulate_and_harvest():
    batch = farmer_batch(3)
    opts = pdhg.PDHGOptions(tol=1e-6, max_iters=8_000, telemetry=True)
    st = pdhg.solve(batch.qp, opts)
    h = kcounters.harvest_state(st)
    assert h["pdhg_iterations_total"] > 0
    assert h["pdhg_restarts_total"] >= 1
    assert h["pdhg_windows_total"] >= 1
    ring = h["residual_ring"]
    assert ring.shape == (3, opts.telemetry_ring)
    assert np.isfinite(ring).any()
    # converged lanes' last scores sit at/below tolerance scale
    assert h["pdhg_last_score_median"] <= 1e-4

    # counters persist across a warm-started re-solve (PH's pattern)
    st2 = pdhg.solve(batch.qp, opts, st)
    h2 = kcounters.harvest_state(st2)
    assert h2["pdhg_iterations_total"] >= h["pdhg_iterations_total"]

    # off by default: zero-leaf None, and harvest says so
    st_off = pdhg.solve(batch.qp, pdhg.PDHGOptions(tol=1e-6,
                                                   max_iters=4_000))
    assert st_off.counters is None
    assert kcounters.harvest_state(st_off) is None


def test_kernel_counters_off_hlo_identical(tmp_path):
    """Overhead contract (mirrors test_chaos.py's disarmed-plan check):
    with telemetry off, the PH wheel step lowered from a fully
    telemetry-wired wheel is byte-identical to one lowered from a
    driver that never touched the telemetry layer; flipping the kernel
    counters ON must change the program (proof the flag gates real
    instrumentation)."""
    batch = farmer_batch(3)
    opts = ph_mod.kernel_opts(ph_mod.PHOptions(
        default_rho=1.0, conv_thresh=0.0, subproblem_windows=10,
        pdhg=pdhg.PDHGOptions(tol=1e-7)))
    rho = jnp.ones((batch.num_nonants,), batch.qp.c.dtype)
    st, _, _ = ph_mod.ph_iter0(batch, rho, opts)
    text_base = ph_mod.ph_iterk.lower(batch, st, opts).as_text()

    # the same step lowered from a wheel with a live bus + sinks
    # attached but counters off
    bus = telemetry.EventBus()
    bus.subscribe(telemetry.MetricsSnapshotSink(
        str(tmp_path / "m.prom"), registry=metrics.MetricsRegistry(),
        every_s=1e9))
    ws = WheelSpinner(
        hub_dict(batch, {"telemetry_bus": bus}, max_iterations=3),
        [dict(d) for d in BOTH_SPOKES]).spin()
    text_wired = ph_mod.ph_iterk.lower(
        batch, ws.opt.state, ph_mod.kernel_opts(ws.opt.options)).as_text()
    assert text_wired == text_base

    # counters ON: state gains leaves and the lowered program differs
    opts_on = ph_mod.kernel_opts(ph_mod.PHOptions(
        default_rho=1.0, conv_thresh=0.0, subproblem_windows=10,
        pdhg=pdhg.PDHGOptions(tol=1e-7, telemetry=True)))
    st_on, _, _ = ph_mod.ph_iter0(batch, rho, opts_on)
    assert st_on.solver.counters is not None
    text_on = ph_mod.ph_iterk.lower(batch, st_on, opts_on).as_text()
    assert text_on != text_base


# ---------------------------------------------------------------------------
# One spine: hub emits, legacy lists are subscriber views
# ---------------------------------------------------------------------------
def test_hub_trace_lists_are_bus_views(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    bus = telemetry.EventBus()
    bus.subscribe(telemetry.JsonlSink(path))
    batch = farmer_batch(3)
    ws = WheelSpinner(
        hub_dict(batch, {"telemetry_bus": bus}, max_iterations=5,
                 telemetry_on=True),
        [dict(d) for d in BOTH_SPOKES]).spin()
    bus.close()
    hub = ws.spcomm

    rows = [json.loads(line) for line in open(path)]
    kinds = {r["kind"] for r in rows}
    assert {"run-start", "hub-iteration", "spoke-harvest",
            "bound-accept", "kernel-counters", "run-end"} <= kinds

    # the legacy Hub.trace list is exactly the hub-iteration stream
    hub_rows = [r for r in rows if r["kind"] == "hub-iteration"]
    assert len(hub.trace) == len(hub_rows) == hub._iter
    for view_row, ev_row in zip(hub.trace, hub_rows):
        assert view_row["iter"] == ev_row["data"]["iter"]
        assert view_row["t"] == ev_row["t_mono"]   # bench reads row["t"]
        assert (view_row["rel_gap"] == ev_row["data"]["rel_gap"]
                or ev_row["data"]["rel_gap"] is None)

    # spoke traces are exactly the bound-accept stream, per spoke
    for j, sp in enumerate(hub.spokes):
        accepts = [(r["iter"], r["data"]["bound"]) for r in rows
                   if r["kind"] == "bound-accept"
                   and r["data"]["spoke"] == j]
        assert sp.trace == accepts
        assert len(sp.trace) >= 1

    # kernel counters made it into the global registry with nonzero
    # totals
    assert metrics.REGISTRY.get("pdhg_iterations_total", cyl="hub") > 0


def test_fused_plane_counters_harvested():
    """--kernel-counters must cover the fused bound planes, not only
    the hub's subproblems: plane solvers are harvested under their own
    cyl labels (the silent-undercount regression)."""
    import dataclasses
    from mpisppy_tpu.algos import fused_wheel as fw
    from mpisppy_tpu.cylinders import spoke as spoke_mod
    batch = farmer_batch(3)
    opts = ph_mod.PHOptions(
        default_rho=1.0, max_iterations=4, conv_thresh=0.0,
        subproblem_windows=10,
        pdhg=pdhg.PDHGOptions(tol=1e-7, telemetry=True))
    wd = fw.FusedWheelOptions()
    wopts = dataclasses.replace(
        wd,
        lag_pdhg=dataclasses.replace(wd.lag_pdhg, telemetry=True),
        xhat_pdhg=dataclasses.replace(wd.xhat_pdhg, telemetry=True))
    hub = {"hub_class": PHHub,
           "hub_kwargs": {"options": {"rel_gap": 5e-3}},
           "opt_class": fw.FusedPH,
           "opt_kwargs": {"options": opts, "batch": batch,
                          "wheel_options": wopts}}
    spokes = [
        {"spoke_class": spoke_mod.FusedLagrangianOuterBound,
         "opt_kwargs": {"options": {}}},
        {"spoke_class": spoke_mod.FusedXhatXbarInnerBound,
         "opt_kwargs": {"options": {}}},
    ]
    WheelSpinner(hub, spokes).spin()
    for cyl in ("hub", "lag", "xhat"):
        assert metrics.REGISTRY.get("pdhg_iterations_total",
                                    cyl=cyl) > 0, cyl


def test_fault_injections_reach_the_trace(tmp_path):
    from mpisppy_tpu.resilience import FaultPlan, SpokeBoundFault
    path = str(tmp_path / "trace.jsonl")
    bus = telemetry.EventBus()
    bus.subscribe(telemetry.JsonlSink(path))
    plan = FaultPlan(seed=1, spoke_bounds=(
        SpokeBoundFault("nan", spoke_index=0, at_iters=(3,)),))
    batch = farmer_batch(3)
    WheelSpinner(
        hub_dict(batch, {"telemetry_bus": bus, "fault_plan": plan,
                         "spoke_max_strikes": 10}, max_iterations=5),
        [dict(d) for d in BOTH_SPOKES]).spin()
    bus.close()
    rows = [json.loads(line) for line in open(path)]
    faults = [r for r in rows if r["kind"] == "fault-injected"]
    strikes = [r for r in rows if r["kind"] == "spoke-strike"]
    assert faults and faults[0]["data"]["seam"] == "spoke_bound"
    assert strikes and strikes[0]["data"]["spoke"] == 0
    # cause precedes response in the total order
    assert faults[0]["seq"] < strikes[0]["seq"]


# ---------------------------------------------------------------------------
# Console verbosity + global_toc routing
# ---------------------------------------------------------------------------
def test_console_levels_and_global_toc_capture(tmp_path, capsys):
    from mpisppy_tpu import global_toc
    path = str(tmp_path / "trace.jsonl")
    bus = telemetry.EventBus()
    bus.subscribe(telemetry.JsonlSink(path))
    bus.subscribe(telemetry.ConsoleSink(verbosity=console.INFO))
    console.attach(bus)
    try:
        global_toc("visible info line")
        console.log("debug line", level=console.DEBUG)
        console.log("suppressed", cond=False)
    finally:
        console.detach(bus)
        bus.close()
    out = capsys.readouterr().out
    assert "visible info line" in out
    assert "debug line" not in out       # below the verbosity bar
    assert "suppressed" not in out
    rows = [json.loads(line) for line in open(path)]
    msgs = [r["data"]["msg"] for r in rows]
    # the machine trace records BOTH levels (filtering is render-side)
    assert msgs == ["visible info line", "debug line"]
    assert rows[1]["level"] == console.DEBUG
    # detached: back to the classic direct print
    global_toc("after detach")
    assert "after detach" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# Metrics exporter: prom rendering + the schema bench.py embeds
# ---------------------------------------------------------------------------
def test_metrics_snapshot_schema_and_prom_render(tmp_path):
    reg = metrics.MetricsRegistry()
    reg.inc("events_total", kind="hub-iteration")
    reg.inc("events_total", kind="hub-iteration")
    reg.set_counter("pdhg_iterations_total", 1600, cyl="hub")
    reg.set_gauge("pdhg_last_score_median", 3e-7, cyl="hub")
    snap = reg.to_snapshot()
    assert snap["schema"] == metrics.SNAPSHOT_SCHEMA
    assert set(snap) == {"schema", "t_wall", "counters", "gauges"}
    assert snap["counters"]['events_total{kind="hub-iteration"}'] == 2.0
    json.dumps(snap)  # BENCH_*.json embeddability

    path = str(tmp_path / "m.prom")
    sink = telemetry.MetricsSnapshotSink(path, registry=reg, every_s=1e9)
    sink.close()  # close always writes a final snapshot
    text = open(path).read()
    assert "# TYPE pdhg_iterations_total counter" in text
    assert 'pdhg_iterations_total{cyl="hub"} 1600.0' in text
    assert "# TYPE pdhg_last_score_median gauge" in text

    # bench.py embeds the SAME schema object (shared code path)
    import bench
    assert bench.metrics_schema_probe() == metrics.SNAPSHOT_SCHEMA


# ---------------------------------------------------------------------------
# phtracker: atomic writes + flush on post_everything at any cadence
# ---------------------------------------------------------------------------
def test_phtracker_flushes_off_cadence_rows(tmp_path):
    """Regression (ISSUE 3 satellite): rows buffered past the last
    save_every*write_every boundary must land via post_everything, and
    the csv is written atomically (no partial/torn content)."""
    batch = farmer_batch(3)
    import functools
    from mpisppy_tpu.extensions.phtracker import PHTracker
    folder = str(tmp_path / "tr")
    opts = ph_mod.PHOptions(
        default_rho=1.0, max_iterations=5, conv_thresh=0.0,
        subproblem_windows=4, pdhg=pdhg.PDHGOptions(tol=1e-6))
    drv = ph_mod.PH(opts, batch,
                    extensions=functools.partial(
                        PHTracker, folder=folder, save_every=1,
                        write_every=4, track_nonants=True))
    drv.ph_main()
    # 5 iterations with write_every=4: iter 5's row is PAST the last
    # write boundary and only post_everything can flush it
    conv = open(os.path.join(folder, "hub", "convergence.csv")
                ).read().strip().splitlines()
    assert conv[0] == "iteration,conv"
    assert len(conv) == 1 + 5
    assert [int(line.split(",")[0]) for line in conv[1:]] == [1, 2, 3, 4, 5]
    non = open(os.path.join(folder, "hub", "nonants.csv")
               ).read().strip().splitlines()
    assert len(non) == 1 + 5
    # no stale tmp files left behind by the atomic writer
    leftovers = [f for f in os.listdir(os.path.join(folder, "hub"))
                 if ".tmp." in f]
    assert leftovers == []


# ---------------------------------------------------------------------------
# The no-bare-print lint (run in tier-1, as the satellite requires)
# ---------------------------------------------------------------------------
def test_no_bare_prints_in_library_code():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import lint_no_print
    finally:
        sys.path.pop(0)
    assert lint_no_print.find_violations() == []


def test_lint_catches_a_new_print(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import lint_no_print
    finally:
        sys.path.pop(0)
    bad = tmp_path / "lib"
    os.makedirs(bad / "sub")
    (bad / "sub" / "mod.py").write_text(
        'x = 1\nprint("dbg")\n'
        'print(json.dumps({}))  # telemetry: allow-print\n'
        '# a comment mentioning print( is fine\n')
    vio = lint_no_print.find_violations(str(bad))
    assert len(vio) == 1 and "sub/mod.py:2" in vio[0]


# ---------------------------------------------------------------------------
# CLI smoke: the acceptance-criteria run (farmer wheel, telemetry on)
# ---------------------------------------------------------------------------
def test_cli_trace_jsonl_metrics_and_profile(tmp_path):
    trace = str(tmp_path / "trace.jsonl")
    prom = str(tmp_path / "metrics.prom")
    prof = str(tmp_path / "profile")
    ckpt = str(tmp_path / "wheel.npz")
    cmd = [sys.executable, "-m", "mpisppy_tpu",
           "--module-name", "mpisppy_tpu.models.farmer",
           "--num-scens", "3", "--max-iterations", "40",
           "--rel-gap", "0.01", "--convthresh", "0",
           "--lagrangian", "--xhatxbar",
           "--kernel-counters",
           "--trace-jsonl", trace,
           "--metrics-snapshot", prom, "--metrics-every-s", "0",
           "--profile-dir", prof, "--profile-iters", "2",
           "--checkpoint-path", ckpt, "--checkpoint-every-s", "0.1"]
    out = subprocess.run(cmd, capture_output=True, text=True,
                         cwd="/root/repo", timeout=600,
                         env={"PATH": "/usr/bin:/bin:/usr/local/bin",
                              "JAX_PLATFORMS": "cpu",
                              "HOME": "/root"})
    assert out.returncode == 0, out.stderr[-2000:]
    payload = json.loads(out.stdout.strip().splitlines()[-1])
    assert payload["rel_gap"] <= 0.01

    # parseable JSONL trace with the acceptance event kinds
    rows = [json.loads(line) for line in open(trace)]
    kinds = {r["kind"] for r in rows}
    assert "hub-iteration" in kinds
    assert "spoke-harvest" in kinds
    assert "checkpoint-write" in kinds
    assert "kernel-counters" in kinds
    assert "profile" in kinds
    # one run id correlates every hub-scoped event (console lines are
    # process-level and carry no run id)
    runs = {r["run"] for r in rows if r["kind"] != "console"}
    assert len(runs) == 1 and "" not in runs

    # metrics snapshot with NONZERO pdhg iteration/restart counters
    text = open(prom).read()
    vals = {}
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        key, v = line.rsplit(" ", 1)
        vals[key] = float(v)
    assert vals['pdhg_iterations_total{cyl="hub"}'] > 0
    assert vals['pdhg_restarts_total{cyl="hub"}'] > 0

    # the profiler session produced an actual device trace artifact
    prof_files = []
    for dirpath, _, filenames in os.walk(prof):
        prof_files += [os.path.join(dirpath, f) for f in filenames]
    assert prof_files, "profiler session wrote no trace"
