# Scenario tree + batch compiler unit tests
# (ref:mpisppy/utils/sputils.py:691-856 tree semantics; spbase.py nonant maps).
import numpy as np
import jax.numpy as jnp

from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.core.tree import ScenarioTree, two_stage_tree
from mpisppy_tpu.models import farmer


def test_two_stage_tree():
    t = two_stage_tree(5, 3)
    assert t.num_stages == 2
    assert t.num_scenarios == 5
    assert t.num_nodes == 1
    assert t.all_nodenames() == ["ROOT"]
    nos = t.node_of_slot()
    assert nos.shape == (5, 3)
    assert (nos == 0).all()


def test_three_stage_tree():
    # branching 2 then 3: 6 scenarios; nodes: ROOT + ROOT_0, ROOT_1
    t = ScenarioTree(branching_factors=(2, 3), nonants_per_stage=(2, 1))
    assert t.num_scenarios == 6
    assert t.nodes_per_stage == (1, 2)
    assert t.num_nodes == 3
    assert t.all_nodenames() == ["ROOT", "ROOT_0", "ROOT_1"]
    nos = t.node_of_slot()
    assert nos.shape == (6, 3)
    # stage-1 slots (first two) always ROOT
    assert (nos[:, :2] == 0).all()
    # stage-2 slot: scenarios 0-2 -> ROOT_0 (id 1), 3-5 -> ROOT_1 (id 2)
    np.testing.assert_array_equal(nos[:, 2], [1, 1, 1, 2, 2, 2])
    assert (t.slot_stage == [1, 1, 2]).all()


def test_farmer_batch_build():
    names = farmer.scenario_names_creator(3)
    specs = [farmer.scenario_creator(nm, num_scens=3) for nm in names]
    b = batch_mod.from_specs(specs)
    assert b.num_scenarios == 3
    assert b.num_nonants == 3
    np.testing.assert_allclose(np.asarray(b.p), np.full(3, 1 / 3), rtol=1e-6)
    # yields differ by scenario -> A batched
    assert b.qp.A.ndim == 3


def test_node_average_two_stage():
    names = farmer.scenario_names_creator(3)
    specs = [farmer.scenario_creator(nm, num_scens=3) for nm in names]
    b = batch_mod.from_specs(specs)
    vals = jnp.asarray(np.arange(9, dtype=np.float32).reshape(3, 3))
    avg_s, avg_n = b.node_average(vals)
    np.testing.assert_allclose(np.asarray(avg_n[0]), [3.0, 4.0, 5.0],
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(avg_s), np.tile([3, 4, 5], (3, 1)),
                               rtol=1e-5)


def test_node_average_multistage_segments():
    # 3-stage, branching (2, 2): 4 scenarios, stage-1 slot + stage-2 slot.
    t = ScenarioTree(branching_factors=(2, 2), nonants_per_stage=(1, 1))
    nos = t.node_of_slot()
    np.testing.assert_array_equal(nos[:, 0], [0, 0, 0, 0])
    np.testing.assert_array_equal(nos[:, 1], [1, 1, 2, 2])
    # fabricate a tiny batch just to exercise node_average
    rng = np.random.default_rng(0)
    specs = []
    for s in range(4):
        specs.append(batch_mod.ScenarioSpec(
            name=f"s{s}", c=rng.normal(size=3), A=np.eye(3),
            bl=np.full(3, -np.inf), bu=np.ones(3) * 10,
            l=np.zeros(3), u=np.ones(3) * 5,
            nonant_idx=np.array([0, 1], np.int32)))
    b = batch_mod.from_specs(specs, tree=t)
    vals = jnp.asarray(np.array([[1., 10.], [3., 20.], [5., 30.], [7., 40.]],
                                np.float32))
    avg_s, avg_n = b.node_average(vals)
    # ROOT slot 0: mean of all four = 4; ROOT_0 slot 1: mean(10,20)=15;
    # ROOT_1 slot 1: mean(30,40)=35
    assert np.asarray(avg_n)[0, 0] == 4.0
    assert np.asarray(avg_n)[1, 1] == 15.0
    assert np.asarray(avg_n)[2, 1] == 35.0
    np.testing.assert_allclose(np.asarray(avg_s)[:, 1], [15, 15, 35, 35])


def test_pad_to_multiple():
    names = farmer.scenario_names_creator(3)
    specs = [farmer.scenario_creator(nm, num_scens=3) for nm in names]
    b = batch_mod.from_specs(specs)
    pb = batch_mod.pad_to_multiple(b, 8)
    assert pb.num_scenarios == 8
    assert pb.num_real == 3
    np.testing.assert_allclose(float(jnp.sum(pb.p)), 1.0, rtol=1e-6)
    # padded rows duplicate the last scenario's data
    np.testing.assert_array_equal(np.asarray(pb.qp.c[-1]),
                                  np.asarray(b.qp.c[-1]))
    # p-weighted reductions unchanged
    vals = pb.nonants(jnp.zeros_like(pb.qp.c) + 1.0)
    avg_s, _ = pb.node_average(vals)
    assert np.isfinite(np.asarray(avg_s)).all()
