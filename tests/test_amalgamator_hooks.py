# Amalgamator one-call driver (utils/amalgamator.py, ref
# utils/amalgamator.py:143-257), the extension callout sequence
# (ref:mpisppy/phbase.py:829-1061), and the xhat looper/specific spoke
# variants (ref:cylinders/xhatlooper_bounder.py:23,
# xhatspecific_bounder.py:25).
import numpy as np

from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import farmer
from mpisppy_tpu.utils import amalgamator
from mpisppy_tpu.utils.config import Config


def _farmer_cfg(**kw):
    cfg = Config()
    cfg.popular_args()
    cfg.ph_args()
    cfg.two_sided_args()
    cfg.quick_assign("num_scens", int, 3)
    for k, v in kw.items():
        cfg.quick_assign(k, type(v), v)
    return cfg


def test_amalgamator_ef_farmer():
    cfg = _farmer_cfg(EF=True)
    ama = amalgamator.from_module("mpisppy_tpu.models.farmer", cfg)
    ama.run()
    # farmer 3-scenario EF objective is the textbook -108390
    # (ref:examples/farmer/farmer.py + test_ef_ph.py known values)
    assert abs(ama.EF_Obj - (-108390.0)) / 108390.0 < 1e-3, ama.EF_Obj
    assert ama.best_inner_bound == ama.best_outer_bound == ama.EF_Obj
    assert ama.first_stage_solution is not None


def test_amalgamator_decomp_farmer():
    cfg = _farmer_cfg(max_iterations=20, default_rho=1.0,
                      lagrangian=True, xhatxbar=True, rel_gap=0.01,
                      display_progress=False)
    ama = amalgamator.from_module("mpisppy_tpu.models.farmer", cfg)
    ama.run()
    assert ama.wheel is not None
    # bounds bracket the EF optimum
    assert ama.best_outer_bound <= -108390.0 + 200
    assert ama.best_inner_bound >= -108390.0 - 200
    assert ama.first_stage_solution is not None and \
        len(ama.first_stage_solution) == 3


def test_extension_hook_sequence():
    """Every PH-driven hook fires, in the reference's order
    (ref:mpisppy/phbase.py:829-1061 callouts)."""
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.extensions.test_extension import TestExtension

    specs = [farmer.scenario_creator(nm, num_scens=3)
             for nm in farmer.scenario_names_creator(3)]
    batch = batch_mod.from_specs(specs)
    driver = ph_mod.PH(ph_mod.PHOptions(max_iterations=2),
                       batch, extensions=TestExtension)
    driver.ph_main()
    calls = driver._TestExtension_who_is_called
    # iter0 sequence
    assert calls[:4] == ["pre_iter0", "iter0_post_solver_creation",
                         "post_iter0", "post_iter0_after_sync"], calls
    # one iterk block
    k_block = ["miditer", "pre_solve_loop", "post_solve_loop", "enditer",
               "enditer_after_sync"]
    assert calls[4:9] == k_block, calls
    assert calls[-1] == "post_everything", calls


def test_xhat_looper_and_specific_spokes():
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.cylinders import hub as hub_mod
    from mpisppy_tpu.cylinders.spoke import (
        XhatLooperInnerBound, XhatSpecificInnerBound,
    )
    from mpisppy_tpu.spin_the_wheel import WheelSpinner

    specs = [farmer.scenario_creator(nm, num_scens=3)
             for nm in farmer.scenario_names_creator(3)]
    batch = batch_mod.from_specs(specs)
    hub = {
        "hub_class": hub_mod.PHHub,
        "opt_class": ph_mod.PH,
        "opt_kwargs": {"options": ph_mod.PHOptions(max_iterations=10),
                       "batch": batch,
                       "scenario_names": ["scen0", "scen1", "scen2"]},
        "hub_kwargs": {"options": {"rel_gap": 0.01}},
    }
    spokes = [
        {"spoke_class": XhatLooperInnerBound,
         "opt_kwargs": {"options": {"scen_limit": 2}}},
        {"spoke_class": XhatSpecificInnerBound,
         "opt_kwargs": {"options": {"scenario_names": ["scen1"]}}},
    ]
    wheel = WheelSpinner(hub, spokes)
    wheel.spin()
    # farmer inner bounds must be >= EF optimum (min problem)
    assert wheel.BestInnerBound >= -108390.0 - 200.0
    assert np.isfinite(wheel.BestInnerBound)


def test_wheel_drives_hub_side_extension_hooks():
    """The FULL hook plane in a wheel run: the hub drives setup_hub /
    initialize_spoke_indices at wheel setup and sync_with_spokes every
    sync (ref:mpisppy/cylinders/hub.py:476-532), on top of PH's own
    iteration callouts — round-3 review weak #8."""
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.cylinders import hub as hub_mod
    from mpisppy_tpu.cylinders.spoke import LagrangianOuterBound
    from mpisppy_tpu.extensions.test_extension import TestExtension
    from mpisppy_tpu.spin_the_wheel import WheelSpinner

    specs = [farmer.scenario_creator(nm, num_scens=3)
             for nm in farmer.scenario_names_creator(3)]
    batch = batch_mod.from_specs(specs)
    hub = {
        "hub_class": hub_mod.PHHub,
        "hub_kwargs": {"options": {"rel_gap": 1e-9}},
        "opt_class": ph_mod.PH,
        "opt_kwargs": {"options": ph_mod.PHOptions(max_iterations=3),
                       "batch": batch,
                       "extensions": TestExtension},
    }
    spokes = [{"spoke_class": LagrangianOuterBound,
               "opt_kwargs": {"options": {}}}]
    ws = WheelSpinner(hub, spokes).spin()
    calls = ws.opt._TestExtension_who_is_called
    # hub setup fires the two wiring hooks BEFORE any PH hook
    assert calls[:2] == ["setup_hub", "initialize_spoke_indices"], calls
    # iter0 block, with the hub's sync_with_spokes inside the Iter0 sync
    assert calls[2:6] == ["pre_iter0", "iter0_post_solver_creation",
                          "post_iter0", "sync_with_spokes"], calls
    assert calls[6] == "post_iter0_after_sync", calls
    # every iterk sync drives sync_with_spokes between enditer and
    # enditer_after_sync (the spcomm.sync callout point)
    k_block = ["miditer", "pre_solve_loop", "post_solve_loop", "enditer",
               "sync_with_spokes", "enditer_after_sync"]
    assert calls[7:13] == k_block, calls
    assert calls[-1] == "post_everything", calls
    # all 13 batched-design callout points fired (pre_solve/post_solve
    # have no per-subproblem callout in the one-program design)
    assert set(calls) == {
        "setup_hub", "initialize_spoke_indices", "sync_with_spokes",
        "pre_iter0", "iter0_post_solver_creation", "post_iter0",
        "post_iter0_after_sync", "miditer", "pre_solve_loop",
        "post_solve_loop", "enditer", "enditer_after_sync",
        "post_everything"}, calls
