# Hub-and-spoke ("cylinders") system: PH hub + bound spokes through
# WheelSpinner, terminating on a certified gap — the TPU analog of
# ref:mpisppy/tests/test_with_cylinders.py.
import numpy as np
import pytest

from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.cylinders import (
    PHHub, LagrangianOuterBound, XhatXbarInnerBound, XhatShuffleInnerBound,
    SlamMinHeuristic, SubgradientOuterBound,
)
from mpisppy_tpu.models import farmer
from mpisppy_tpu.ops import pdhg
from mpisppy_tpu.spin_the_wheel import WheelSpinner

FARMER_EF_OBJ = -108390.0


def farmer_batch(num_scens=3):
    names = farmer.scenario_names_creator(num_scens)
    specs = [farmer.scenario_creator(nm, num_scens=num_scens)
             for nm in names]
    return batch_mod.from_specs(specs)


def hub_dict(batch, rel_gap=5e-3, max_iterations=150):
    opts = ph_mod.PHOptions(default_rho=1.0, max_iterations=max_iterations,
                            conv_thresh=0.0,  # let the gap terminate
                            subproblem_windows=10,
                            pdhg=pdhg.PDHGOptions(tol=1e-7))
    return {
        "hub_class": PHHub,
        "hub_kwargs": {"options": {"rel_gap": rel_gap}},
        "opt_class": ph_mod.PH,
        "opt_kwargs": {"options": opts, "batch": batch},
    }


def test_wheel_ph_lagrangian_xhatxbar():
    batch = farmer_batch(3)
    spokes = [
        {"spoke_class": LagrangianOuterBound, "opt_kwargs": {"options": {}}},
        {"spoke_class": XhatXbarInnerBound, "opt_kwargs": {"options": {}}},
    ]
    ws = WheelSpinner(hub_dict(batch), spokes).spin()
    inner, outer = ws.BestInnerBound, ws.BestOuterBound
    assert np.isfinite(inner) and np.isfinite(outer)
    assert outer <= inner + 2e-3 * abs(inner)
    # both bounds bracket the EF objective (modulo f32 slack)
    slack = 2e-3 * abs(FARMER_EF_OBJ)
    assert outer <= FARMER_EF_OBJ + slack
    assert inner >= FARMER_EF_OBJ - slack
    # gap actually certified
    rel_gap = (inner - outer) / abs(inner)
    assert rel_gap <= 5e-3 + 1e-6
    # terminated early thanks to the gap, not the iteration cap
    assert ws.spcomm._iter < 150


def test_wheel_more_spokes():
    batch = farmer_batch(6)
    spokes = [
        {"spoke_class": LagrangianOuterBound, "opt_kwargs": {"options": {}}},
        {"spoke_class": SubgradientOuterBound,
         "opt_kwargs": {"options": {"rho": 1.0, "n_windows": 10}}},
        {"spoke_class": XhatShuffleInnerBound,
         "opt_kwargs": {"options": {"k": 2}}},
        {"spoke_class": SlamMinHeuristic, "opt_kwargs": {"options": {}}},
    ]
    ws = WheelSpinner(hub_dict(batch, rel_gap=1e-2, max_iterations=80),
                      spokes).spin()
    inner, outer = ws.BestInnerBound, ws.BestOuterBound
    assert np.isfinite(inner) and np.isfinite(outer)
    assert outer <= inner + 2e-3 * abs(inner)
    # trace recorded per sync
    assert len(ws.spcomm.trace) == ws.spcomm._iter
    assert ws.spcomm.trace[-1]["rel_gap"] <= 1e-2 + 1e-6


def test_stall_termination():
    batch = farmer_batch(3)
    hd = hub_dict(batch, rel_gap=0.0, max_iterations=100)
    hd["hub_kwargs"]["options"] = {"rel_gap": 0.0,
                                   "max_stalled_iters": 5}
    spokes = [
        {"spoke_class": XhatXbarInnerBound, "opt_kwargs": {"options": {}}},
    ]
    ws = WheelSpinner(hd, spokes).spin()
    # stalls quickly: inner bound stops improving near the optimum
    assert ws.spcomm._iter < 100
    assert np.isfinite(ws.BestInnerBound)


def test_solution_writers(tmp_path):
    batch = farmer_batch(3)
    spokes = [
        {"spoke_class": LagrangianOuterBound, "opt_kwargs": {"options": {}}},
        {"spoke_class": XhatXbarInnerBound, "opt_kwargs": {"options": {}}},
    ]
    ws = WheelSpinner(hub_dict(batch), spokes).spin()
    f = tmp_path / "sol.npy"
    ws.write_first_stage_solution(str(f))
    x1 = np.load(f)
    # the written solution is the incumbent that achieved BestInnerBound:
    # re-evaluating it must reproduce the reported bound
    from mpisppy_tpu.algos import xhat as xhat_mod
    from mpisppy_tpu.ops import pdhg as pdhg_mod
    res = xhat_mod.evaluate(batch, np.asarray(x1),
                            pdhg_mod.PDHGOptions(tol=1e-7))
    assert bool(res.feasible)
    assert float(res.value) == pytest.approx(ws.BestInnerBound, rel=1e-4)
    d = tmp_path / "tree"
    ws.write_tree_solution(str(d))
    assert (d / "ROOT.csv").exists()


def test_spoke_sync_period():
    """spoke_sync_period=k exchanges with spokes every k-th sync; bounds
    still land and the gap still closes (the async-cylinder overlap
    analog, ref:mpisppy/cylinders/hub.py write-id freshness)."""
    import numpy as np
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.cylinders import hub as hub_mod
    from mpisppy_tpu.cylinders.spoke import (
        LagrangianOuterBound, XhatXbarInnerBound,
    )
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.spin_the_wheel import WheelSpinner

    specs = [farmer.scenario_creator(nm, num_scens=3)
             for nm in farmer.scenario_names_creator(3)]
    batch = batch_mod.from_specs(specs)
    hub = {
        "hub_class": hub_mod.PHHub,
        "opt_class": ph_mod.PH,
        "opt_kwargs": {"options": ph_mod.PHOptions(max_iterations=30,
                                                   default_rho=1.0,
                                                   conv_thresh=0.0),
                       "batch": batch},
        "hub_kwargs": {"options": {"rel_gap": 0.01,
                                   "spoke_sync_period": 3}},
    }
    spokes = [
        {"spoke_class": LagrangianOuterBound,
         "opt_kwargs": {"options": {}}},
        {"spoke_class": XhatXbarInnerBound,
         "opt_kwargs": {"options": {}}},
    ]
    wheel = WheelSpinner(hub, spokes)
    wheel.spin()
    assert np.isfinite(wheel.BestOuterBound)
    assert np.isfinite(wheel.BestInnerBound)
    _, rel_gap = wheel.spcomm.compute_gaps()
    assert rel_gap <= 0.05, rel_gap


def test_compute_gaps_near_zero_inner():
    # A shifted model can legitimately have an optimal objective near 0;
    # the rel_gap denominator must scale by the larger bound magnitude so
    # termination can still fire (ref divides by |inner| alone).
    from mpisppy_tpu.cylinders import hub as hub_mod

    h = hub_mod.Hub(opt=None, options={"rel_gap": 0.01})
    h.BestInnerBound = 1e-12   # ~zero incumbent
    h.BestOuterBound = -5.0
    abs_gap, rel_gap = h.compute_gaps()
    assert abs_gap == pytest.approx(5.0)
    assert rel_gap == pytest.approx(1.0)  # 5 / max(|1e-12|, |-5|)
    assert np.isfinite(rel_gap)

    # tight bounds around zero: rel_gap stays finite and of the bounds'
    # own scale (2x here), not 1e10 as with the |inner|-only denominator
    h.BestInnerBound = 1e-9
    h.BestOuterBound = -1e-9
    _, rel_gap = h.compute_gaps()
    assert rel_gap == pytest.approx(2.0)

    # EXACT reference semantics whenever |inner| is not degenerate —
    # the certification convention all BENCH numbers use
    h.BestInnerBound = -100.0
    h.BestOuterBound = -101.0
    abs_gap, rel_gap = h.compute_gaps()
    assert rel_gap == pytest.approx(1.0 / 100.0)
