# graftlint static-analysis suite (ISSUE 10; tools/graftlint/,
# docs/static_analysis.md): per-rule seeded-violation fixtures, the
# clean-repo tier-1 run, suppression + baseline round trips, --json
# schema stability, and the trace-purity satellite's compile-count
# regression test on ops/pdhg.solve.
from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

from tools import graftlint  # noqa: E402
from tools.graftlint.core import Context, load_baseline  # noqa: E402
from tools.graftlint import (  # noqa: E402
    rules_config_knob, rules_host_sync, rules_lock_discipline,
    rules_no_print, rules_readme_claims, rules_schema_drift,
    rules_trace_purity,
)


def mini_repo(tmp_path, files: dict[str, str]):
    """A throwaway repo tree with an mpisppy_tpu/ lib dir."""
    for rel, body in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(body))
    return Context(str(tmp_path))


# ---------------------------------------------------------------------------
# the tier-1 wiring: the repo itself lints clean on every AST pass.
# The IR passes (tools/graftlint/ir/) need a fresh process for their
# multi-device collective facts, so their clean-repo run lives in
# tests/test_graftlint_ir.py as a subprocess CLI drive.
# ---------------------------------------------------------------------------
def test_repo_lints_clean():
    rep = graftlint.lint(REPO, rules=[r.name for r in graftlint.AST_RULES])
    msgs = [f"{f['path']}:{f['line']} [{f['rule']}] {f['message']}"
            for f in rep["findings"] if not f["baselined"]]
    assert rep["errors"] == [] and msgs == [], "\n".join(msgs)


def test_required_empty_baseline_rules():
    """ISSUE 10 acceptance: lock-discipline / schema-drift /
    config-knob carry NO baseline entries (trace-purity and host-sync
    may, with justification — currently none do).  ISSUE 15 extends
    the ban to every IR pass: IR violations get fixed, not
    grandfathered."""
    entries, errors = load_baseline(graftlint.DEFAULT_BASELINE)
    assert errors == []
    banned = {"lock-discipline", "schema-drift", "config-knob",
              "no-print", "readme-claims",
              "ir-const-capture", "ir-dtype-census", "ir-host-boundary",
              "ir-collective-manifest", "ir-memory-high-water"}
    assert not [k for k in entries if k[0] in banned]


# ---------------------------------------------------------------------------
# rule 1: trace-purity
# ---------------------------------------------------------------------------
def test_trace_purity_catches_eager_control_flow(tmp_path):
    ctx = mini_repo(tmp_path, {"mpisppy_tpu/mod.py": """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=())
        def fine(x):
            return jax.lax.fori_loop(0, 3, lambda i, s: s + x, x)

        def _helper(x):  # private, only called from the jitted entry
            return jax.lax.scan(lambda c, _: (c, c), x, None)

        def fine_caller_jit(x):
            return _helper(x)

        def leaky(x):
            return jax.lax.while_loop(lambda s: s.any(),
                                      lambda s: s - x, x)
    """})
    found = {(f.key.split("::")[1], f.line)
             for f in rules_trace_purity.run(ctx)}
    assert ("leaky", 16) in {(k, ln) for k, ln in found}
    assert all(k == "leaky" for k, _ in found), found


def test_trace_purity_private_method_inherits_via_jitted_sibling(tmp_path):
    # self._body is only reachable through the jitted step() — the
    # class-qualified call edge must feed the protection fixed point
    ctx = mini_repo(tmp_path, {"mpisppy_tpu/mod.py": """
        import jax
        from functools import partial

        class K:
            @partial(jax.jit, static_argnums=0)
            def step(self, x):
                return self._body(x)

            def _body(self, x):
                return jax.lax.scan(lambda c, _: (c, c), x, None)

            def _orphan(self, x):   # no caller: stays unprotected
                return jax.lax.cond(x.any(), lambda v: v,
                                    lambda v: -v, x)
    """})
    names = {f.key.split("::")[1] for f in rules_trace_purity.run(ctx)}
    assert names == {"K._orphan"}, names


def test_trace_purity_partial_wrapped_protection(tmp_path):
    """`g = partial(jax.jit, ...)(f)` / `g = jax.jit(f, ...)` at module
    level protect f exactly like a decorator — the ops/pdhg
    `solve = jax.jit(_solve_impl, ...)` idiom must not be flagged
    (ISSUE 15 satellite: the detector used to miss both forms)."""
    ctx = mini_repo(tmp_path, {"mpisppy_tpu/mod.py": """
        import jax
        from functools import partial

        def _impl(x):
            return jax.lax.fori_loop(0, 3, lambda i, s: s + x, x)

        solve = jax.jit(_impl, static_argnames=())

        def _impl2(x):
            return jax.lax.scan(lambda c, _: (c, c), x, None)

        solve2 = partial(jax.jit, static_argnames=())(_impl2)

        def leaky(x):
            return jax.lax.while_loop(lambda s: s.any(),
                                      lambda s: s - x, x)
    """})
    names = {f.key.split("::")[1] for f in rules_trace_purity.run(ctx)}
    assert names == {"leaky"}, names


def test_trace_purity_decorator_alias_protection(tmp_path):
    """A module-level jit alias (`_jit = partial(jax.jit, ...)`) used
    as a decorator protects the function it decorates (the second
    missed form)."""
    ctx = mini_repo(tmp_path, {"mpisppy_tpu/mod.py": """
        import jax
        from functools import partial

        _jitted = partial(jax.jit, static_argnames=("n",))

        @_jitted
        def fine(x, n):
            return jax.lax.fori_loop(0, n, lambda i, s: s + x, x)

        def _helper(x):   # only reachable through the alias-wrapped g
            return jax.lax.scan(lambda c, _: (c, c), x, None)

        g = _jitted(_helper)

        def leaky(x):
            return jax.lax.cond(x.any(), lambda v: v, lambda v: -v, x)
    """})
    names = {f.key.split("::")[1] for f in rules_trace_purity.run(ctx)}
    assert names == {"leaky"}, names


def test_trace_purity_wrapped_fn_with_eager_caller_stays_flagged(tmp_path):
    """The wrapping assignment counts as ONE protected caller, not a
    blanket grant: a second, eager call path to the wrapped function
    still bakes values into per-call jaxprs and must stay a finding."""
    ctx = mini_repo(tmp_path, {"mpisppy_tpu/mod.py": """
        import jax

        def _impl(x):
            return jax.lax.fori_loop(0, 3, lambda i, s: s + x, x)

        solve = jax.jit(_impl)

        def eager(x):          # reaches _impl OUTSIDE any jit
            return _impl(x)
    """})
    names = {f.key.split("::")[1] for f in rules_trace_purity.run(ctx)}
    assert names == {"_impl"}, names


def test_trace_purity_catches_per_call_jit_wrapper(tmp_path):
    ctx = mini_repo(tmp_path, {"mpisppy_tpu/mod.py": """
        import jax

        def hot(x):
            f = jax.jit(lambda v: v + 1)   # fresh wrapper per call
            return f(x)
    """})
    msgs = [f.message for f in rules_trace_purity.run(ctx)]
    assert any("jit(lambda)" in m for m in msgs), msgs


# ---------------------------------------------------------------------------
# rule 2: lock-discipline
# ---------------------------------------------------------------------------
LOCK_MOD = """
    import threading

    class S:
        def __init__(self):
            self._lock = threading.Lock()
            self._wake = threading.Condition(self._lock)
            self._n = 0            # guarded-by: _lock

        def good(self):
            with self._lock:
                self._n += 1

        def good_via_condition(self):
            with self._wake:
                self._n += 1

        def good_caller_holds(self):   # holds-lock: _lock
            self._n += 1

        def bad(self):
            self._n += 1
"""


def test_lock_discipline_catches_unguarded_access(tmp_path):
    ctx = mini_repo(tmp_path, {"mpisppy_tpu/mod.py": LOCK_MOD})
    found = rules_lock_discipline.run(ctx)
    assert len(found) == 1 and "bad()" in found[0].message


def test_lock_discipline_nested_def_does_not_inherit(tmp_path):
    # a closure handed to a thread must not inherit the lexical lock
    ctx = mini_repo(tmp_path, {"mpisppy_tpu/mod.py": """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0        # guarded-by: _lock

            def spawn(self):
                with self._lock:
                    def worker():
                        self._n += 1   # runs on another thread
                    return worker
    """})
    found = rules_lock_discipline.run(ctx)
    assert len(found) == 1 and "spawn()" in found[0].message


# ---------------------------------------------------------------------------
# rule 3: host-sync
# ---------------------------------------------------------------------------
def test_host_sync_catches_syncs_in_hot_kernels(tmp_path):
    ctx = mini_repo(tmp_path, {"mpisppy_tpu/ops/pdhg.py": """
        import numpy as np

        def step(st):
            v = st.x.item()
            w = np.asarray(st.y)
            st.x.block_until_ready()
            k = int(st.k)
            return v, w, k

        def fine(st):
            n = int(3)             # literal: never a sync
            ok = int(st.k)         # graftlint: allow-host-sync
            return n, ok
    """, "mpisppy_tpu/ops/bnb.py": """
        import numpy as np

        def harvest(res):
            return np.asarray(res)   # host orchestrator: exempt
    """})
    found = [f for f in rules_host_sync.run(ctx)
             if not ctx.suppressed(f.path, f.line, f.rule)]
    kinds = sorted(f.message.split(" in a hot")[0] for f in found)
    assert len(found) == 4, kinds
    assert all("pdhg.py" in f.path for f in found)


def test_host_sync_keys_are_per_occurrence(tmp_path):
    """Two same-kind syncs in one function must get DISTINCT baseline
    keys — a shared key would let one grandfathered entry silently
    cover a future violation landing nearby."""
    ctx = mini_repo(tmp_path, {"mpisppy_tpu/ops/pdhg.py": """
        def f(st):
            a = st.x.item()
            b = st.y.item()
            return a, b
    """})
    keys = [f.key for f in rules_host_sync.run(ctx)]
    assert len(keys) == 2 and len(set(keys)) == 2, keys
    assert all("::f::" in k for k in keys)


# ---------------------------------------------------------------------------
# rule 4: schema-drift
# ---------------------------------------------------------------------------
SD_EVENTS = """
    FOO = "foo-kind"
    BAR = "bar-kind"
    ALL_KINDS = frozenset(v for k, v in list(globals().items())
                          if k.isupper() and isinstance(v, str))
"""
SD_METRICS = """
    ALL_METRICS = frozenset({"good_total"})
    class R: pass
    REGISTRY = R()
"""
SD_DOC = """
    # doc
    | kind | when |
    |------|------|
    | `foo-kind` | x |
"""


def test_schema_drift_catches_unknown_kind_and_metric(tmp_path):
    ctx = mini_repo(tmp_path, {
        "mpisppy_tpu/telemetry/events.py": SD_EVENTS,
        "mpisppy_tpu/telemetry/metrics.py": SD_METRICS,
        "docs/telemetry.md": SD_DOC,
        "mpisppy_tpu/emitter.py": """
            from mpisppy_tpu.telemetry.metrics import REGISTRY

            def go(bus):
                bus.emit("foo-kind", x=1)      # declared: fine
                bus.emit("tyop-kind", x=1)     # NOT declared
                REGISTRY.inc("good_total")     # registered: fine
                REGISTRY.inc("typo_total")     # NOT registered
        """})
    keys = {f.key for f in rules_schema_drift.run(ctx)}
    assert "mpisppy_tpu/emitter.py::emit::tyop-kind" in keys
    assert "mpisppy_tpu/emitter.py::metric::typo_total" in keys
    # bar-kind is declared but has no doc row
    assert "doc-missing::bar-kind" in keys
    assert not any("foo-kind" in k or "good_total" in k for k in keys)


# ---------------------------------------------------------------------------
# rule 5: config-knob
# ---------------------------------------------------------------------------
def test_config_knob_catches_undeclared_and_dead(tmp_path):
    ctx = mini_repo(tmp_path, {
        "mpisppy_tpu/utils/config.py": """
            class Config:
                def add_to_config(self, name, description, domain=str,
                                  default=None, argparse=True):
                    pass
                def get(self, name, default=None):
                    pass
                def my_args(self):
                    self.add_to_config("live_knob", "used", int, 1)
                    self.add_to_config("dead_knob", "unused", int, 1)
                    # graftlint: allow-config-knob
                    self.add_to_config("legacy_knob", "alias", int, 1)
        """,
        "mpisppy_tpu/consumer.py": """
            def use(cfg):
                a = cfg.get("live_knob", 1)
                b = cfg.get("ghost_knob")     # never declared
                return a, b
        """})
    found = [f for f in rules_config_knob.run(ctx)
             if not ctx.suppressed(f.path, f.line, f.rule)]
    keys = {f.key for f in found}
    assert "mpisppy_tpu/consumer.py::undeclared::ghost_knob" in keys
    assert "dead::dead_knob" in keys
    assert "dead::legacy_knob" not in keys      # suppressed alias
    assert "dead::live_knob" not in keys


# ---------------------------------------------------------------------------
# rules 6+7: the folded-in legacy passes (shims covered by the
# pre-existing tests in test_telemetry / test_observability)
# ---------------------------------------------------------------------------
def test_no_print_rule_fixture(tmp_path):
    ctx = mini_repo(tmp_path, {"mpisppy_tpu/mod.py": """
        print("dbg")
        print("{}")  # telemetry: allow-print
        # print( in a comment is fine
    """})
    found = rules_no_print.run(ctx)
    assert len(found) == 1 and found[0].line == 2


def test_readme_claims_rule_fixture(tmp_path):
    (tmp_path / "README.md").write_text(
        "Measured on one chip:\n\n"
        "- hits the gap in 999 s (bf16x3)\n\n"
        "Out of scope: nothing.\n")
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"phase": {"seconds_to_gap": 42.0}}))
    ctx = Context(str(tmp_path))
    found = rules_readme_claims.run(ctx)
    assert len(found) == 1 and "999s" in found[0].message


# ---------------------------------------------------------------------------
# framework: suppression, baseline round trip, CLI + --json schema
# ---------------------------------------------------------------------------
def test_inline_suppression_same_and_preceding_line(tmp_path):
    ctx = mini_repo(tmp_path, {"mpisppy_tpu/mod.py": """
        print("a")  # graftlint: allow-no-print
        # graftlint: allow-no-print
        print("b")
        print("c")
    """})
    rep = graftlint.lint(str(tmp_path), rules=["no-print"])
    # line 2 suppressed same-line, line 4 by the preceding comment;
    # only the bare line-5 print survives
    lines = [f["line"] for f in rep["findings"]]
    assert lines == [5]


def test_baseline_round_trip(tmp_path):
    mini_repo(tmp_path, {"mpisppy_tpu/mod.py": 'print("x")\n'})
    base = tmp_path / "baseline.json"
    rep = graftlint.lint(str(tmp_path), rules=["no-print"],
                         baseline_path=str(base))
    assert rep["active"] == 1 and not rep["ok"]
    key = rep["findings"][0]["key"]
    # grandfather it WITH a justification -> ok
    base.write_text(json.dumps({
        "schema": "graftlint-baseline/1",
        "entries": [{"rule": "no-print", "key": key,
                     "why": "legacy CLI output, migrating in PR N+1"}]}))
    rep2 = graftlint.lint(str(tmp_path), rules=["no-print"],
                          baseline_path=str(base))
    assert rep2["ok"] and rep2["baselined"] == 1 and rep2["active"] == 0
    # an entry without `why` is itself a failure
    base.write_text(json.dumps({
        "schema": "graftlint-baseline/1",
        "entries": [{"rule": "no-print", "key": key}]}))
    rep3 = graftlint.lint(str(tmp_path), rules=["no-print"],
                          baseline_path=str(base))
    assert not rep3["ok"] and any("why" in e for e in rep3["errors"])
    # a stale entry (finding fixed, entry left behind) is a failure
    (tmp_path / "mpisppy_tpu" / "mod.py").write_text("x = 1\n")
    base.write_text(json.dumps({
        "schema": "graftlint-baseline/1",
        "entries": [{"rule": "no-print", "key": key, "why": "gone"}]}))
    rep4 = graftlint.lint(str(tmp_path), rules=["no-print"],
                          baseline_path=str(base))
    assert not rep4["ok"] and any("stale" in e for e in rep4["errors"])


def test_cli_json_schema_stability(tmp_path):
    mini_repo(tmp_path, {"mpisppy_tpu/mod.py": 'print("x")\n'})
    env = {"PATH": os.environ.get("PATH", "/usr/bin:/bin"),
           "HOME": os.path.expanduser("~")}
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--json",
         "--root", str(tmp_path), "--rules", "no-print"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert out.returncode == 1, out.stderr
    rep = json.loads(out.stdout)
    assert rep["schema"] == "graftlint-report/1"
    f = rep["findings"][0]
    assert set(f) == {"rule", "path", "line", "message", "key",
                      "baselined"}
    assert rep["active"] == 1 and rep["rules"] == ["no-print"]
    # clean tree -> exit 0
    (tmp_path / "mpisppy_tpu" / "mod.py").write_text("x = 1\n")
    out2 = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--root",
         str(tmp_path), "--rules", "no-print"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert out2.returncode == 0, out2.stdout + out2.stderr


def test_unknown_rule_name_rejected():
    with pytest.raises(ValueError, match="unknown rule"):
        graftlint.lint(REPO, rules=["no-such-rule"])


# ---------------------------------------------------------------------------
# the golden dispatch trace fixture backs the GATES witness check
# ---------------------------------------------------------------------------
def test_golden_dispatch_trace_carries_gate_keys():
    """The committed fixture exists so regress.GATES' backend_compiles
    / unexpected_recompiles patterns resolve against a committed
    artifact (schema-drift check 4) — guard the coupling."""
    import re
    from mpisppy_tpu.telemetry import analyze, regress
    rep = analyze.analyze_path(os.path.join(
        HERE, "fixtures", "golden_dispatch_trace.jsonl"))
    keys = set(regress.extract_metrics(rep))
    for pat in ("backend_compiles", "unexpected_recompiles"):
        assert any(re.search(pat, k) for k in keys), (pat, sorted(keys))


# ---------------------------------------------------------------------------
# trace-purity satellite: the pdhg host-level solve recompile leak is
# FIXED (not baselined) — compile-count regression test
# ---------------------------------------------------------------------------
def _toy_qp(seed: int):
    import numpy as np
    import jax.numpy as jnp
    from mpisppy_tpu.ops.boxqp import BoxQP
    r = np.random.default_rng(seed)
    n, m, S = 6, 4, 3
    A = jnp.asarray(r.normal(size=(m, n)).astype(np.float32))
    c = jnp.asarray(r.normal(size=(S, n)).astype(np.float32))
    return BoxQP(c=c, q=jnp.zeros_like(c), A=A,
                 bl=jnp.full((m,), -1.0, jnp.float32),
                 bu=jnp.full((m,), 1.0, jnp.float32),
                 l=jnp.full((n,), -2.0, jnp.float32),
                 u=jnp.full((n,), 2.0, jnp.float32))


def test_pdhg_host_solve_does_not_recompile_per_qp():
    """Pre-fix, host-level pdhg.solve() below the dispatch_cap ran an
    EAGER while_loop closing over the QP values as jaxpr constants —
    one silent backend compile per distinct QP (the exact leak class
    the PR-4 runtime guard caught in estimate_norm, now lint-flagged
    by graftlint trace-purity and fixed via _solve_loop_jit)."""
    from mpisppy_tpu.dispatch import compilewatch
    from mpisppy_tpu.ops import pdhg
    opts = pdhg.PDHGOptions(tol=1e-5, max_iters=2000)
    assert not pdhg.will_chunk(opts)     # the leaky (non-chunked) path
    watch = compilewatch.CompileWatch()
    st = pdhg.solve(_toy_qp(0), opts)    # warm the shape+opts key
    assert bool(st.done.all())
    warm = watch.total()
    for seed in (1, 2, 3):               # same shapes, fresh values
        st = pdhg.solve(_toy_qp(seed), opts)
        assert bool(st.done.all())
    assert watch.total() == warm, \
        "host-level solve recompiled for same-shape QPs"


def test_pdhg_solve_fixed_does_not_recompile_per_qp():
    from mpisppy_tpu.dispatch import compilewatch
    from mpisppy_tpu.ops import pdhg
    opts = pdhg.PDHGOptions(tol=1e-5)
    watch = compilewatch.CompileWatch()
    qp = _toy_qp(7)
    pdhg.solve_fixed(qp, 4, opts, pdhg.init_state(qp, opts))
    warm = watch.total()
    qp2 = _toy_qp(8)
    pdhg.solve_fixed(qp2, 4, opts, pdhg.init_state(qp2, opts))
    assert watch.total() == warm, \
        "host-level solve_fixed recompiled for same-shape QPs"
