# ELL sparse constraint matrices: oracle parity with dense on matvec,
# norms, Ruiz, full PDHG solves, batch compilation, and sharding.
import numpy as np
import pytest
import scipy.sparse as sps

import jax
import jax.numpy as jnp

from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import farmer
from mpisppy_tpu.ops import boxqp, pdhg
from mpisppy_tpu.ops.sparse import (
    EllMatrix, ell_from_scipy, ell_from_scipy_batch, ruiz_scale_ell,
)


def _rand_sparse(m, n, density=0.15, seed=0):
    rng = np.random.default_rng(seed)
    M = sps.random(m, n, density=density, random_state=rng,
                   data_rvs=lambda k: rng.normal(size=k))
    # guarantee no empty rows (constraint rows always touch something)
    M = sps.lil_matrix(M)
    for i in range(m):
        if M.rows[i] == []:
            M[i, rng.integers(n)] = rng.normal()
    return sps.csr_matrix(M)


def test_ell_matvec_rmatvec_oracle():
    M = _rand_sparse(17, 29)
    E = ell_from_scipy(M, jnp.float32)
    x = np.random.default_rng(1).normal(size=29).astype(np.float32)
    y = np.random.default_rng(2).normal(size=17).astype(np.float32)
    np.testing.assert_allclose(E.matvec(jnp.asarray(x)), M @ x, rtol=1e-5)
    np.testing.assert_allclose(E.rmatvec(jnp.asarray(y)), M.T @ y,
                               rtol=1e-5, atol=1e-6)
    # batched x against per-row dense oracle
    X = np.random.default_rng(3).normal(size=(5, 29)).astype(np.float32)
    np.testing.assert_allclose(E.matvec(jnp.asarray(X)),
                               (M @ X.T).T, rtol=1e-5, atol=1e-6)


def test_ell_batched_vals():
    mats = []
    base = _rand_sparse(11, 13, seed=4)
    for s in range(4):
        M = base.copy()
        M.data = M.data * (1.0 + 0.1 * s)
        mats.append(M)
    E = ell_from_scipy_batch(mats, jnp.float32)
    assert E.vals.shape[0] == 4
    X = np.random.default_rng(5).normal(size=(4, 13)).astype(np.float32)
    want = np.stack([mats[s] @ X[s] for s in range(4)])
    np.testing.assert_allclose(E.matvec(jnp.asarray(X)), want, rtol=1e-5,
                               atol=1e-6)
    Y = np.random.default_rng(6).normal(size=(4, 11)).astype(np.float32)
    want = np.stack([mats[s].T @ Y[s] for s in range(4)])
    np.testing.assert_allclose(E.rmatvec(jnp.asarray(Y)), want, rtol=1e-5,
                               atol=1e-6)


def test_ell_pattern_mismatch_unions():
    """Differing sparsity patterns are padded onto the pattern union
    (heterogeneous admm regions); values match the dense stack."""
    a = sps.csr_matrix(np.array([[1.0, 0.0], [0.0, 2.0]]))
    b = sps.csr_matrix(np.array([[0.0, 1.0], [0.0, 2.0]]))
    ell = ell_from_scipy_batch([a, b])
    dense = np.asarray(ell.toarray())
    assert dense.shape == (2, 2, 2)
    assert np.allclose(dense[0], a.toarray())
    assert np.allclose(dense[1], b.toarray())


def test_ell_norms_match_dense():
    M = _rand_sparse(9, 14, seed=7)
    E = ell_from_scipy(M, jnp.float32)
    D = M.toarray()
    np.testing.assert_allclose(E.row_sqnorms(), (D * D).sum(1), rtol=1e-5)
    np.testing.assert_allclose(E.col_sqnorms(), (D * D).sum(0), rtol=1e-5,
                               atol=1e-6)


def test_ruiz_ell_matches_dense_when_no_empty_cols():
    M = _rand_sparse(10, 8, density=0.5, seed=8)
    D = M.toarray()
    # ensure every column is touched so the dense floor path never fires
    for j in range(8):
        if not D[:, j].any():
            D[0, j] = 1.0
    M = sps.csr_matrix(D)
    vals, cols = __import__(
        "mpisppy_tpu.ops.sparse", fromlist=["from_scipy"]).from_scipy(M)
    svals, dr, dc = ruiz_scale_ell(vals, cols, 8)
    qp = boxqp.make_boxqp(np.zeros(8), D, -np.ones(10), np.ones(10),
                          -np.ones(8), np.ones(8))
    _, scal = boxqp.ruiz_scale(qp)
    np.testing.assert_allclose(dr, scal.d_row, rtol=1e-6)
    np.testing.assert_allclose(dc, scal.d_col, rtol=1e-6)


def _farmer_sparse_specs(num=3):
    """Farmer specs with A converted to scipy-sparse (shared object)."""
    names = farmer.scenario_names_creator(num)
    specs = [farmer.scenario_creator(nm, num_scens=num) for nm in names]
    # A varies per scenario (yields): shared-pattern batched ELL
    import dataclasses as dc
    return [dc.replace(sp, A=sps.csr_matrix(np.where(
        np.abs(sp.A) > 0, sp.A, 0.0))) for sp in specs]


def test_pdhg_sparse_matches_dense_farmer():
    names = farmer.scenario_names_creator(3)
    dense_specs = [farmer.scenario_creator(nm, num_scens=3) for nm in names]
    sparse_specs = _farmer_sparse_specs(3)
    bd = batch_mod.from_specs(dense_specs)
    bs = batch_mod.from_specs(sparse_specs)
    assert isinstance(bs.qp.A, EllMatrix)
    opts = pdhg.PDHGOptions(tol=1e-6, restart_period=40,
                            max_iters=100_000)
    std = pdhg.solve(bd.qp, opts)
    sts = pdhg.solve(bs.qp, opts)
    assert bool(std.done.all()) and bool(sts.done.all())
    np.testing.assert_allclose(bd.objective(std.x), bs.objective(sts.x),
                               rtol=2e-4)


def test_sparse_ph_end_to_end():
    from mpisppy_tpu.algos import ph as ph_mod
    specs = _farmer_sparse_specs(3)
    b = batch_mod.from_specs(specs)
    opts = ph_mod.PHOptions(default_rho=1.0, max_iterations=150,
                            conv_thresh=5e-2, subproblem_windows=10,
                            pdhg=pdhg.PDHGOptions(tol=1e-7,
                                                  restart_period=40))
    algo = ph_mod.PH(opts, b)
    conv, eobj, tb = algo.ph_main()
    assert conv <= opts.conv_thresh
    np.testing.assert_allclose(algo.first_stage_solution(),
                               [170.0, 80.0, 250.0], atol=5.0)


def test_sparse_batch_shards_and_pads():
    from mpisppy_tpu.parallel import mesh as mesh_mod
    specs = _farmer_sparse_specs(3)
    b = batch_mod.from_specs(specs)
    b = batch_mod.pad_to_multiple(b, 8)
    assert b.num_scenarios == 8
    assert b.qp.A.vals.shape[0] == 8      # batched ELL padded too
    mesh = mesh_mod.make_mesh(8)
    bsh = mesh_mod.shard_batch(b, mesh)
    st = pdhg.solve(bsh.qp, pdhg.PDHGOptions(tol=1e-6))
    obj = float(bsh.expectation(bsh.objective(st.x)))
    b1 = batch_mod.from_specs(specs)
    st1 = pdhg.solve(b1.qp, pdhg.PDHGOptions(tol=1e-6))
    obj1 = float(b1.expectation(b1.objective(st1.x)))
    assert obj == pytest.approx(obj1, rel=1e-3)
