# Dispatch subsystem (mpisppy_tpu/dispatch, docs/dispatch.md): the
# shape-bucket ladder, padding round trips, coalesced megabatches vs
# per-item solves, backpressure under a synthetic dispatch storm, and
# the compile-cache discipline — the acceptance microbenchmark for the
# sslp_15_45 dispatch-storm fix (round-5 verdict).
import threading
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from mpisppy_tpu import dispatch
from mpisppy_tpu.dispatch import (
    BucketLadder, CompileWatch, DispatchOptions, SolveFailed,
    SolveScheduler, pad_qp_batch, slice_result,
)
from mpisppy_tpu.dispatch.buckets import balanced_split
from mpisppy_tpu.resilience import DispatchFault, FaultPlan
from mpisppy_tpu.ops import bnb
from mpisppy_tpu.ops.bnb import BnBOptions, BnBResult

from test_mip_bnb import random_mips


@pytest.fixture(autouse=True, scope="module")
def _fresh_caches():
    """Compile-count assertions need a known-cold jit cache (mirrors
    test_mip_bnb's fixture)."""
    jax.clear_caches()
    yield
    jax.clear_caches()


# lean budgets: the storm/equivalence tests measure DISPATCH behavior,
# not bound quality — tiny pools and no pump keep each lane cheap
LEAN = BnBOptions(pool_size=8, max_rounds=20, dive_rounds=4,
                  dive_tail=8, pump_rounds=0)


def _d(qp):
    return jnp.ones(qp.c.shape[-1], jnp.float32)


def _fake_result(qp):
    S = qp.c.shape[0]
    return BnBResult(
        x=jnp.zeros_like(qp.c),
        inner=jnp.sum(qp.c, axis=-1),        # request-identifying value
        outer=jnp.sum(qp.c, axis=-1) - 1.0,
        gap=jnp.zeros((S,), qp.c.dtype),
        feasible=jnp.ones((S,), bool),
        nodes_solved=jnp.ones((S,), jnp.int32))


# -- bucket ladder ----------------------------------------------------------
def test_bucket_ladder_properties():
    lad = BucketLadder()
    assert lad.rungs(64) == [1, 2, 4, 8, 16, 32, 64]
    assert lad.bucket(1) == 1 and lad.bucket(5) == 8
    assert lad.bucket(8) == 8          # exact rung: no padding
    assert lad.bucket_floor(12) == 8   # gathers never exceed the source
    assert lad.bucket_floor(1) == 1
    # sub-2 growth still strictly increases (no infinite ladders)
    g = BucketLadder(1.5)
    r = g.rungs(30)
    assert all(b > a for a, b in zip(r, r[1:]))
    assert g.bucket(5) == 5 and g.bucket(6) == 8
    with pytest.raises(ValueError):
        lad.bucket(0)
    with pytest.raises(ValueError):
        BucketLadder(1.0)


def test_pad_round_trip_shapes():
    qp, _, _ = random_mips(S=3)
    qp8, d8 = pad_qp_batch(qp, _d(qp), 8)
    assert qp8.c.shape[0] == 8 and qp8.A.shape[0] == 8
    # pad lanes are copies of lane 0 — THE padding contract
    assert np.array_equal(np.asarray(qp8.c[3:]),
                          np.tile(np.asarray(qp.c[:1]), (5, 1)))
    assert d8 is not None
    res = slice_result(_fake_result(qp8), 3)
    assert res.inner.shape == (3,)
    with pytest.raises(ValueError):
        pad_qp_batch(qp, _d(qp), 2)


# -- padded solve == direct solve ------------------------------------------
def test_padded_solve_mip_equals_direct():
    """Bucket padding must be invisible: pad lanes mirror lane 0 and
    every per-lane computation is independent, so the sliced-back
    result equals the unpadded solve up to XLA's shape-dependent
    instruction scheduling (ulp-level per op, which the B&B's
    value-driven host heuristics can amplify into small — still
    certified — value differences; see the padding contract in
    dispatch/buckets.py)."""
    qp, integer, ref = random_mips(S=5, seed=7)
    ic = np.nonzero(integer)[0].astype(np.int32)
    direct = bnb.solve_mip(qp, _d(qp), ic, LEAN)
    sched = SolveScheduler()       # pads 5 -> 8
    via = sched.solve_mip(qp, _d(qp), ic, LEAN)
    assert np.array_equal(np.asarray(direct.feasible),
                          np.asarray(via.feasible))
    tol = LEAN.gap_tol * (1.0 + np.abs(ref))
    assert np.allclose(np.asarray(direct.outer), np.asarray(via.outer),
                       atol=tol.max(), rtol=1e-4)
    feas = np.asarray(direct.feasible)
    assert np.allclose(np.asarray(direct.inner)[feas],
                       np.asarray(via.inner)[feas],
                       atol=tol.max(), rtol=1e-4)
    st = sched.stats()
    assert st["batches"] == 1
    assert st["lanes"] == 5 and st["pad_lanes"] == 3
    assert st["occupancy"] == pytest.approx(5 / 8)
    # the certified bracket survives the trip
    scale = 1.0 + np.abs(ref)
    assert np.all(np.asarray(via.outer) <= ref + 1e-3 * scale)


def test_exact_rung_pays_no_padding():
    qp, integer, _ = random_mips(S=4)
    ic = np.nonzero(integer)[0].astype(np.int32)
    sched = SolveScheduler()
    sched.solve_mip(qp, _d(qp), ic, LEAN)
    assert sched.stats()["pad_lanes"] == 0


# -- coalescing -------------------------------------------------------------
def test_coalesced_megabatch_matches_per_item():
    """Three submits coalesce into ONE megabatch whose per-request
    results match the per-item direct solves.  Values agree within the
    certified-bound tolerance (gap_tol): lanes are independent, but the
    merged solve's host loop runs until EVERY lane closes, so a lane
    can receive extra (never fewer) dive/B&B rounds than its solo run —
    both runs' brackets are certified, and both must contain the
    oracle optimum."""
    reqs = [random_mips(S=3, seed=s) for s in (1, 2, 3)]
    ic = np.nonzero(reqs[0][1])[0].astype(np.int32)
    sched = SolveScheduler(DispatchOptions(max_wait_ms=500.0))
    # ONE d_col object: shared (non-batched) fields merge by identity
    d = _d(reqs[0][0])
    tickets = [sched.submit(qp, d, ic, LEAN) for qp, _, _ in reqs]
    results = [t.result() for t in tickets]
    st = sched.stats()
    assert st["batches"] == 1, st
    assert st["coalesced_lanes"] == 9
    assert st["lanes"] == 9 and st["pad_lanes"] == 7   # 9 -> 16
    for (qp, integer, ref), res in zip(reqs, results):
        assert res.inner.shape == (3,)
        direct = bnb.solve_mip(qp, _d(qp), ic, LEAN)
        scale = 1.0 + np.abs(ref)
        # both brackets certified around the oracle optimum
        assert np.all(np.asarray(res.outer) <= ref + 1e-3 * scale)
        assert np.all(np.where(np.asarray(res.feasible),
                               np.asarray(res.inner) >= ref - 1e-3 * scale,
                               True))
        # lanes where BOTH runs closed their certified gap pin the
        # optimum to gap_tol on each side: the values must agree there
        tol = LEAN.gap_tol * scale
        closed = (np.asarray(res.gap) <= LEAN.gap_tol) \
            & (np.asarray(direct.gap) <= LEAN.gap_tol)
        with np.errstate(invalid="ignore"):  # open lanes: inf-inf=nan
            diff = np.abs(np.where(closed,
                                   np.asarray(res.inner)
                                   - np.asarray(direct.inner), 0.0))
        assert np.all(np.where(closed, diff <= 2 * tol + 1e-6, True))


def test_coalesce_respects_max_batch():
    sched = SolveScheduler(DispatchOptions(max_batch=4, max_wait_ms=500.0),
                           solve_fn=lambda qp, d, ic, o, **kw:
                           _fake_result(qp))
    qps = [random_mips(S=3, seed=s)[0] for s in range(3)]
    ic = np.arange(2, dtype=np.int32)
    d = _d(qps[0])
    tickets = [sched.submit(qp, d, ic, LEAN) for qp in qps]
    for t, qp in zip(tickets, qps):
        got = np.asarray(t.result().inner)
        assert np.allclose(got, np.asarray(qp.c).sum(-1)), \
            "megabatch result split returned the wrong lanes"
    # 3 lanes per request, cap 4: no two requests fit one window
    assert sched.stats()["batches"] == 3


# -- backpressure -----------------------------------------------------------
def test_backpressure_bounds_inflight_under_storm():
    """Synthetic dispatch storm: 12 threads hammer the scheduler while
    the (instrumented) solve is deliberately slow.  The in-flight
    semaphore must cap concurrent dispatches at max_inflight, the
    stalled submitters must coalesce into larger megabatches instead of
    queueing 1-lane dispatches, and every request must get ITS OWN
    lanes back."""
    state = {"now": 0, "max": 0}
    lock = threading.Lock()

    def slow_solve(qp, d_col, int_cols, opts, **kw):
        with lock:
            state["now"] += 1
            state["max"] = max(state["max"], state["now"])
        time.sleep(0.05)
        with lock:
            state["now"] -= 1
        return _fake_result(qp)

    sched = SolveScheduler(
        DispatchOptions(max_inflight=2, max_wait_ms=5.0),
        solve_fn=slow_solve)
    rng = np.random.RandomState(0)
    cs = [rng.randn(2, 6).astype(np.float32) for _ in range(12)]
    base, _, _ = random_mips(S=2, n=6, m=4)
    d = _d(base)
    ic = np.arange(2, dtype=np.int32)
    import dataclasses
    errs = []

    def one(c):
        try:
            qp = dataclasses.replace(base, c=jnp.asarray(c))
            res = sched.solve_mip(qp, d, ic, LEAN)
            assert np.allclose(np.asarray(res.inner), c.sum(-1)), \
                "lane routing under the storm returned foreign lanes"
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=one, args=(c,)) for c in cs]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errs, errs
    st = sched.stats()
    assert state["max"] <= 2, f"in-flight exceeded the cap: {state}"
    assert st["inflight_max"] <= 2
    # the storm coalesced: strictly fewer dispatches than requests
    assert st["batches"] < 12, st
    assert st["lanes"] == 24
    # telemetry mirrored into the process registry
    from mpisppy_tpu.telemetry import metrics as metrics_mod
    assert metrics_mod.REGISTRY.get("dispatch_batches_total") > 0
    assert 0.0 < metrics_mod.REGISTRY.get("dispatch_batch_occupancy",
                                          0.0) <= 1.0


# -- compile-cache discipline ----------------------------------------------
def test_compile_count_bounded_by_buckets():
    """The acceptance guard: a storm of VARIABLY-sized solves through
    the scheduler compiles executables only on first touch of a bucket
    — per jitted kernel, lowered executables <= buckets exercised, and
    re-dispatching warm-bucket sizes compiles NOTHING new."""
    jax.clear_caches()
    ic_all = np.arange(8, dtype=np.int32)
    sched = SolveScheduler(DispatchOptions(coalesce=False))
    watch = CompileWatch()
    # first wave: sizes {3, 4} -> bucket 4, {5, 6} -> bucket 8
    for s, size in [(0, 3), (1, 4), (2, 5), (3, 6)]:
        qp, integer, _ = random_mips(S=size, seed=s)
        sched.solve_mip(qp, _d(qp), ic_all, LEAN)
    assert sched.stats()["buckets"] == 2
    # per-kernel form of "executables <= buckets exercised": the B&B
    # round kernel lowered at most one executable per bucket
    assert bnb.bnb_round._cache_size() <= 2
    # second wave: NEW sizes into the SAME buckets -> zero compiles
    watch.mark()
    for s, size in [(7, 3), (8, 6), (9, 4), (10, 5)]:
        qp, integer, _ = random_mips(S=size, seed=s)
        sched.solve_mip(qp, _d(qp), ic_all, LEAN)
    assert watch.delta() == 0, \
        "warm-bucket dispatches recompiled: shape discipline is broken"
    assert sched.stats()["unexpected_recompiles"] == 0
    assert sched.stats()["buckets"] == 2
    assert bnb.bnb_round._cache_size() <= 2


def test_compile_guard_raises_on_warm_bucket_recompile():
    """--dispatch-compile-guard turns a warm-bucket recompile into an
    error instead of a silent storm."""
    compiled = []

    def leaky_solve(qp, d_col, int_cols, opts, **kw):
        # a fresh jit per CALL: every dispatch compiles — the exact
        # pathology the guard exists to catch
        f = jax.jit(lambda c: c * 2.0 + float(len(compiled)))
        jax.block_until_ready(f(qp.c))
        compiled.append(1)
        return _fake_result(qp)

    sched = SolveScheduler(DispatchOptions(compile_guard=True,
                                           coalesce=False),
                           solve_fn=leaky_solve)
    qp, _, _ = random_mips(S=4)
    ic = np.arange(2, dtype=np.int32)
    sched.solve_mip(qp, _d(qp), ic, LEAN)      # first touch: allowed
    with pytest.raises(AssertionError, match="compile-cache discipline"):
        sched.solve_mip(qp, _d(qp), ic, LEAN)  # warm bucket: caught


# -- oracle equivalence through the default scheduler -----------------------
def test_lagrangian_oracle_matches_direct_path():
    """mip.lagrangian_mip_bound (routed through the process-default
    scheduler) returns the same certified bound as assembling the same
    oracle by hand on the direct ops.bnb path."""
    from mpisppy_tpu.algos import mip
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.models import sslp

    inst = sslp.synthetic_instance(3, 6, seed=4)
    names = sslp.scenario_names_creator(3)
    specs = [sslp.scenario_creator(nm, instance=inst, num_scens=3)
             for nm in names]
    batch = batch_mod.from_specs(specs)
    W = jnp.zeros((batch.num_scenarios, batch.num_nonants),
                  batch.qp.c.dtype)
    lag = mip.lagrangian_mip_bound(batch, W, LEAN)
    # direct path: identical oracle, no scheduler
    qp = batch.with_nonant_linear_quad(W, jnp.zeros_like(W))
    res = bnb.solve_mip(qp, batch.d_col, mip._int_cols(batch), LEAN)
    p = np.asarray(batch.p)
    direct = float(np.sum(np.where(p > 0.0, p * np.asarray(res.outer),
                                   0.0)))
    # within certified-bound tolerance: the 3 -> 4 padding changes XLA's
    # instruction schedule at the ulp level and the B&B's value-driven
    # host heuristics can amplify that into a small value shift — both
    # bounds remain certified Lagrangian outer bounds
    assert lag["bound"] == pytest.approx(direct, rel=1e-3, abs=1e-3)


def test_decomposition_bnb_fanout_keeps_bracket():
    """The coalesced node fanout changes only the search order: the
    certified bracket must still close on a problem the serial search
    handles, and the fanout path must coalesce node solves."""
    from mpisppy_tpu.algos import mip
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.models import sslp

    inst = sslp.synthetic_instance(3, 6, seed=5)
    names = sslp.scenario_names_creator(3)
    specs = [sslp.scenario_creator(nm, instance=inst, num_scens=3)
             for nm in names]
    batch = batch_mod.from_specs(specs)
    W = jnp.zeros((batch.num_scenarios, batch.num_nonants),
                  batch.qp.c.dtype)
    before = dispatch.get_scheduler().stats()["coalesced_lanes"]
    dd = mip.decomposition_bnb(batch, W, LEAN, max_nodes=6,
                               node_fanout=3)
    assert dd["outer"] <= dd["inner"] + 1e-6
    assert dd["nodes"] <= 6
    after = dispatch.get_scheduler().stats()["coalesced_lanes"]
    assert after > before, "node fanout produced no coalesced dispatch"


# -- telemetry + CLI --------------------------------------------------------
def test_dispatch_events_and_gauges():
    from mpisppy_tpu import telemetry as tel

    seen = []

    class _Probe:
        def handle(self, ev):
            seen.append(ev)

    bus = tel.EventBus()
    bus.subscribe(_Probe())
    sched = SolveScheduler(
        DispatchOptions(max_wait_ms=200.0),
        solve_fn=lambda qp, d, ic, o, **kw: _fake_result(qp),
        bus=bus, run="testrun")
    qp, _, _ = random_mips(S=3)
    ic = np.arange(2, dtype=np.int32)
    d = _d(qp)
    t1 = sched.submit(qp, d, ic, LEAN)
    t2 = sched.submit(qp, d, ic, LEAN)
    t1.result(), t2.result()
    ev = [e for e in seen if e.kind == tel.DISPATCH]
    assert len(ev) == 1
    d = ev[0].data
    assert d["requests"] == 2 and d["lanes"] == 6
    assert d["padded_to"] == 8
    assert d["occupancy"] == pytest.approx(6 / 8)
    assert "queue_depth" in d and "wait_ms" in d
    assert ev[0].run == "testrun" and ev[0].cyl == "dispatch"


def test_overflow_rotation_dispatches_displaced_window():
    """A submit that would overflow max_batch must DISPATCH the
    displaced open window, not orphan it (its fire-and-forget tickets
    would otherwise never complete — review finding)."""
    sched = SolveScheduler(
        DispatchOptions(max_batch=8, max_wait_ms=60_000.0),
        solve_fn=lambda qp, d, ic, o, **kw: _fake_result(qp))
    qps = [random_mips(S=3, seed=s)[0] for s in range(3)]
    ic = np.arange(2, dtype=np.int32)
    d = _d(qps[0])
    t1 = sched.submit(qps[0], d, ic, LEAN)   # window A: 3 lanes
    t2 = sched.submit(qps[1], d, ic, LEAN)   # window A: 6 lanes
    # 6 + 3 > 8: rotation — window A must dispatch NOW, not sit behind
    # the (here: effectively infinite) admission timer
    t3 = sched.submit(qps[2], d, ic, LEAN)
    assert t1.done() and t2.done()
    assert np.allclose(np.asarray(t1.result().inner),
                       np.asarray(qps[0].c).sum(-1))
    assert np.allclose(np.asarray(t2.result().inner),
                       np.asarray(qps[1].c).sum(-1))
    t3.result()


def test_coalesce_off_fire_and_forget_still_dispatches():
    """--dispatch-coalesce false must not orphan submits whose caller
    never blocks on result(): the admission-timer daemon covers them
    (review finding)."""
    sched = SolveScheduler(
        DispatchOptions(coalesce=False, max_wait_ms=20.0),
        solve_fn=lambda qp, d, ic, o, **kw: _fake_result(qp))
    qp, _, _ = random_mips(S=3)
    t = sched.submit(qp, _d(qp), np.arange(2, dtype=np.int32), LEAN)
    deadline = time.perf_counter() + 5.0
    while not t.done() and time.perf_counter() < deadline:
        time.sleep(0.01)
    assert t.done(), "fire-and-forget submit never dispatched"


def test_warm_start_kwargs_ride_the_padding():
    """Per-lane kwargs (x_warm/y_warm) must pad with the qp: the
    drop-in contract with ops.bnb.solve_mip includes its warm-start
    arguments (review finding)."""
    qp, integer, _ = random_mips(S=5, seed=11)
    ic = np.nonzero(integer)[0].astype(np.int32)
    S, n = qp.c.shape
    x_warm = jnp.zeros((S, n), qp.c.dtype)
    y_warm = jnp.zeros((S, qp.m), qp.c.dtype)
    sched = SolveScheduler()                   # pads 5 -> 8
    res = sched.solve_mip(qp, _d(qp), ic, LEAN,
                          x_warm=x_warm, y_warm=y_warm)
    assert res.inner.shape == (5,)


# -- fault domain: deadlines, retry, bisection quarantine, supervisor ------
# (ISSUE 9; docs/dispatch.md failure semantics — a solve_mip caller
# observes a result or a typed SolveFailed, never a hang)
def test_balanced_split_halves_lanes():
    assert balanced_split([3, 3, 3]) == 1            # 3 | 6 vs 6 | 3: tie -> first
    assert balanced_split([1, 1, 8]) == 2            # big request isolated
    assert balanced_split([8, 1, 1]) == 1
    with pytest.raises(ValueError):
        balanced_split([4])


def test_ticket_result_timeout_kwarg_never_hangs():
    """Satellite: result(timeout=) bounds the wait — expiry raises a
    typed SolveFailed('deadline'); a later call returns the result once
    the (slow) dispatch eventually lands."""
    def slow(qp, d, ic, o, **kw):
        time.sleep(0.3)
        return _fake_result(qp)

    sched = SolveScheduler(DispatchOptions(max_wait_ms=1.0),
                           solve_fn=slow)
    qp, _, _ = random_mips(S=3)
    t = sched.submit(qp, _d(qp), np.arange(2, dtype=np.int32), LEAN)
    t0 = time.perf_counter()
    with pytest.raises(SolveFailed) as ei:
        t.result(timeout=0.05)
    assert ei.value.reason == "deadline"
    assert time.perf_counter() - t0 < 0.25, "blocked past the timeout"
    res = t.result()                       # the solve still lands
    assert np.allclose(np.asarray(res.inner),
                       np.asarray(qp.c).sum(-1))


def test_submit_deadline_bounds_every_result_call():
    """Tentpole: a per-ticket deadline (submit deadline_s / the
    options default) bounds result() even with NO timeout argument."""
    def hang(qp, d, ic, o, **kw):
        time.sleep(5.0)
        return _fake_result(qp)

    sched = SolveScheduler(DispatchOptions(max_wait_ms=1.0,
                                           deadline_s=0.08),
                           solve_fn=hang)
    qp, _, _ = random_mips(S=3)
    t = sched.submit(qp, _d(qp), np.arange(2, dtype=np.int32), LEAN)
    t0 = time.perf_counter()
    with pytest.raises(SolveFailed) as ei:
        t.result()
    assert ei.value.reason == "deadline"
    assert time.perf_counter() - t0 < 1.0


def test_hung_dispatch_times_out_and_retry_succeeds():
    """A hung dispatch is abandoned after dispatch_timeout_s and
    retried with backoff; the retry lands and the caller sees a normal
    result plus a retries_total count."""
    calls = []

    def flaky(qp, d, ic, o, **kw):
        calls.append(1)
        if len(calls) == 1:
            time.sleep(5.0)           # first attempt hangs
        return _fake_result(qp)

    sched = SolveScheduler(
        DispatchOptions(dispatch_timeout_s=0.1, retry_max=2,
                        retry_backoff_s=0.01),
        solve_fn=flaky)
    qp, _, _ = random_mips(S=3)
    res = sched.solve_mip(qp, _d(qp), np.arange(2, dtype=np.int32), LEAN)
    assert np.allclose(np.asarray(res.inner),
                       np.asarray(qp.c).sum(-1))
    st = sched.stats()
    assert st["retries_total"] == 1
    assert st["quarantined_lanes"] == 0


def test_poison_request_bisected_and_quarantined():
    """The acceptance path: one poisoned request in a coalesced
    megabatch fails every retry, bisection isolates it, ITS ticket
    resolves SolveFailed and the healthy requests get correct
    results — with the quarantined lanes accounted."""
    from mpisppy_tpu import telemetry as tel
    seen = []

    class _Probe:
        def handle(self, ev):
            seen.append(ev)

    bus = tel.EventBus()
    bus.subscribe(_Probe())
    plan = FaultPlan(seed=0, dispatches=(
        DispatchFault("poison", submits=(1,)),))
    sched = SolveScheduler(
        DispatchOptions(max_wait_ms=500.0, retry_max=1,
                        retry_backoff_s=0.001),
        solve_fn=lambda qp, d, ic, o, **kw: _fake_result(qp),
        fault_plan=plan, bus=bus)
    qps = [random_mips(S=3, seed=s)[0] for s in range(3)]
    ic = np.arange(2, dtype=np.int32)
    d = _d(qps[0])
    tickets = [sched.submit(qp, d, ic, LEAN) for qp in qps]
    for k in (0, 2):
        got = np.asarray(tickets[k].result().inner)
        assert np.allclose(got, np.asarray(qps[k].c).sum(-1)), \
            "healthy request got foreign lanes after bisection"
    with pytest.raises(SolveFailed) as ei:
        tickets[1].result()
    assert ei.value.reason == "exception"
    assert ei.value.lanes == 3
    assert "DispatchPoison" in ei.value.detail
    st = sched.stats()
    assert st["quarantined_lanes"] == 3
    assert st["quarantined_requests"] == 1
    assert st["retries_total"] >= 1
    q = [e for e in seen if e.kind == tel.DISPATCH_QUARANTINE]
    assert len(q) == 1 and q[0].data["submit"] == 1 \
        and q[0].data["bisected"]
    assert [e for e in seen if e.kind == tel.DISPATCH_RETRY]
    from mpisppy_tpu.telemetry import metrics as metrics_mod
    assert metrics_mod.REGISTRY.get(
        "dispatch_quarantined_lanes_total") >= 3


def test_dispatcher_death_fails_queued_tickets_fast():
    """Satellite + tentpole: the dispatcher daemon dying must fail
    every queued ticket with SolveFailed('dispatcher-died') promptly —
    not leave them hanging — and the next submit restarts the daemon."""
    plan = FaultPlan(seed=0, dispatches=(
        DispatchFault("kill_dispatcher"),))
    sched = SolveScheduler(DispatchOptions(max_wait_ms=20.0),
                           solve_fn=lambda qp, d, ic, o, **kw:
                           _fake_result(qp),
                           fault_plan=plan)
    qp, _, _ = random_mips(S=3)
    t = sched.submit(qp, _d(qp), np.arange(2, dtype=np.int32), LEAN)
    deadline = time.perf_counter() + 5.0
    while not t.done() and time.perf_counter() < deadline:
        time.sleep(0.01)
    assert t.done(), "queued ticket hung on a dead dispatcher"
    with pytest.raises(SolveFailed) as ei:
        t.result()
    assert ei.value.reason == "dispatcher-died"
    assert sched.stats()["dispatcher_deaths"] == 1
    # the kill fired once; a fresh submit restarts the daemon and works
    t2 = sched.submit(qp, _d(qp), np.arange(2, dtype=np.int32), LEAN)
    assert np.asarray(t2.result().inner).shape == (3,)


def test_exception_raising_dispatch_propagates_to_all_window_tickets():
    """Satellite: a dispatch raising on ANOTHER thread must propagate
    to every ticket in the window (here: retries exhausted on a window
    driven by the admission-timer daemon)."""
    def bad(qp, d, ic, o, **kw):
        raise RuntimeError("synthetic device failure")

    sched = SolveScheduler(
        DispatchOptions(max_wait_ms=10.0, retry_max=1,
                        retry_backoff_s=0.001),
        solve_fn=bad)
    qp, _, _ = random_mips(S=3)
    d = _d(qp)
    ic = np.arange(2, dtype=np.int32)
    t1 = sched.submit(qp, d, ic, LEAN)
    t2 = sched.submit(qp, d, ic, LEAN)
    deadline = time.perf_counter() + 5.0
    while not (t1.done() and t2.done()) \
            and time.perf_counter() < deadline:
        time.sleep(0.01)
    for t in (t1, t2):
        with pytest.raises(SolveFailed) as ei:
            t.result(timeout=1.0)
        assert ei.value.reason == "exception"
        assert "synthetic device failure" in ei.value.detail


def test_stats_split_dispatch_cause():
    """Satellite: stats() attributes every dispatch to why it fired —
    admission-timer expiry vs size overflow vs a blocking caller — so
    the analyzer can attribute occupancy loss to timeouts."""
    sched = SolveScheduler(
        DispatchOptions(max_batch=6, max_wait_ms=30.0),
        solve_fn=lambda qp, d, ic, o, **kw: _fake_result(qp))
    ic = np.arange(2, dtype=np.int32)
    # size: two 3-lane submits fill max_batch exactly
    qa, qb = (random_mips(S=3, seed=s)[0] for s in (0, 1))
    d = _d(qa)
    ta = sched.submit(qa, d, ic, LEAN)
    tb = sched.submit(qb, d, ic, LEAN)
    ta.result(), tb.result()
    # inline: a lone blocking caller drives its own window
    qc = random_mips(S=2, seed=2)[0]
    sched.solve_mip(qc, _d(qc), ic, LEAN)
    # timer: a fire-and-forget submit waits out the admission window
    qd = random_mips(S=2, seed=3)[0]
    td = sched.submit(qd, _d(qd), ic, LEAN)
    deadline = time.perf_counter() + 5.0
    while not td.done() and time.perf_counter() < deadline:
        time.sleep(0.01)
    by = sched.stats()["by_cause"]
    assert by.get("size") == 1, by
    assert by.get("inline") == 1, by
    assert by.get("timer") == 1, by
    assert sched.stats()["batches"] == sum(by.values())


def test_degrade_switches_to_uncoalesced_direct_dispatch():
    sched = SolveScheduler(
        DispatchOptions(max_wait_ms=500.0),
        solve_fn=lambda qp, d, ic, o, **kw: _fake_result(qp))
    assert sched.options.coalesce
    sched.degrade()
    assert not sched.options.coalesce
    assert sched.stats()["degraded"]
    # still solves, one window per submit
    qp, _, _ = random_mips(S=3)
    ic = np.arange(2, dtype=np.int32)
    d = _d(qp)
    t1 = sched.submit(qp, d, ic, LEAN)
    t2 = sched.submit(qp, d, ic, LEAN)
    t1.result(), t2.result()
    assert sched.stats()["batches"] == 2


def test_dispatch_cli_knobs_and_from_cfg():
    from mpisppy_tpu.utils.config import Config

    cfg = Config()
    cfg.dispatch_args()
    cfg.parse_command_line("t", [
        "--dispatch-max-inflight", "3", "--dispatch-max-batch", "64",
        "--dispatch-coalesce", "false", "--dispatch-bucket-growth",
        "1.5", "--dispatch-compile-guard",
        "--dispatch-timeout-s", "30", "--dispatch-retry-max", "4",
        "--dispatch-retry-backoff-s", "0.2",
        "--dispatch-deadline-s", "120"])
    try:
        sched = dispatch.from_cfg(cfg)
        assert sched is dispatch.get_scheduler()
        o = sched.options
        assert o.max_inflight == 3 and o.max_batch == 64
        assert o.coalesce is False and o.compile_guard is True
        assert sched.ladder.growth == 1.5
        assert o.dispatch_timeout_s == 30.0 and o.retry_max == 4
        assert o.retry_backoff_s == 0.2 and o.deadline_s == 120.0
    finally:
        # restore the process default for whatever test runs next
        dispatch.configure()
