# Mesh chaos storm (ISSUE 17 tentpole, end to end): kill a host in the
# middle of a sharded fused wheel and prove the elastic loop
# (parallel.elastic.run_elastic) re-shards the scenario batch across
# the survivors, recompiles at the shrunk topology, resumes from the
# emergency checkpoint, and still certifies the SAME gap as a
# fault-free baseline — the paper's bound-validity contract is
# topology-invariant.  The A/B here is the test-sized twin of
# bench.py's mesh_chaos phase (BENCH_r11.json).
import numpy as np
import pytest

from mpisppy_tpu import scengen
from mpisppy_tpu import telemetry as tel
from mpisppy_tpu.algos import fused_wheel as fw
from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.cylinders import PHHub
from mpisppy_tpu.cylinders.spoke import (
    FusedLagrangianOuterBound, FusedXhatXbarInnerBound,
)
from mpisppy_tpu.models import farmer
from mpisppy_tpu.ops import pdhg
from mpisppy_tpu.parallel import mesh as mesh_mod
from mpisppy_tpu.parallel.elastic import run_elastic
from mpisppy_tpu.resilience import FaultPlan, MeshFault
from mpisppy_tpu.spin_the_wheel import WheelSpinner
from mpisppy_tpu.telemetry import EventBus
from mpisppy_tpu.telemetry import metrics as _metrics

pytestmark = pytest.mark.chaos

NUM_HOSTS = 4   # 8 virtual devices -> 2 per host
S = 13          # prime: pads to 16 on 8 devices and to 18 on 6
REL_GAP = 5e-3

# minimal certified plane set: one outer (Lagrangian) + one inner
# (xhat-xbar) window so every seed shares the same two compiled shapes
_WOPTS = fw.FusedWheelOptions(lag_windows=4, xhat_windows=2,
                              slam_windows=0, shuffle_windows=0,
                              split_dispatch=False,
                              lag_pdhg=pdhg.PDHGOptions(tol=1e-7),
                              xhat_pdhg=pdhg.PDHGOptions(
                                  tol=1e-7, omega0=0.1,
                                  restart_period=80))
_SPOKES = [
    {"spoke_class": FusedLagrangianOuterBound,
     "opt_kwargs": {"options": {}}},
    {"spoke_class": FusedXhatXbarInnerBound,
     "opt_kwargs": {"options": {}}},
]


class _Cap:
    def __init__(self):
        self.events = []

    def handle(self, event):
        self.events.append(event)

    def kinds(self):
        return [e.kind for e in self.events]


def _build_fn(prog, ckpt, max_iterations=80):
    def build(mesh):
        b = mesh_mod.shard_batch(scengen.virtual_batch(prog), mesh,
                                 pad=True)
        opts = ph_mod.PHOptions(default_rho=1.0,
                                max_iterations=max_iterations,
                                conv_thresh=0.0, subproblem_windows=10,
                                pdhg=pdhg.PDHGOptions(tol=1e-7))
        hub = {"hub_class": PHHub,
               "hub_kwargs": {"options": {
                   "rel_gap": REL_GAP, "checkpoint_path": ckpt,
                   "checkpoint_every_s": 1e9}},  # emergency save only
               "opt_class": fw.FusedPH,
               "opt_kwargs": {"options": opts, "batch": b,
                              "wheel_options": _WOPTS}}
        return WheelSpinner(hub, _SPOKES)
    return build


def _bracket(ws):
    inner, outer = float(ws.BestInnerBound), float(ws.BestOuterBound)
    assert np.isfinite(inner) and np.isfinite(outer)
    gap = (inner - outer) / max(abs(inner), abs(outer), 1e-12)
    return inner, outer, gap


def _storm(tmp_path, seed, kill_iter=3, host=1):
    prog = farmer.scenario_program(S, seed=seed)

    # A side: fault-free wheel on the full 8-device mesh
    base = _build_fn(prog, str(tmp_path / f"base{seed}.npz"))(
        mesh_mod.make_mesh())
    base.spin()
    ib, ob, gb = _bracket(base)
    assert gb <= REL_GAP + 1e-6
    kill_iter = min(kill_iter, max(1, base.spcomm._iter - 1))

    # B side: same program, but a host dies mid-wheel
    cap = _Cap()
    bus = EventBus()
    bus.subscribe(cap)
    trace_file = str(tmp_path / f"mesh_trace{seed}.jsonl")
    bus.subscribe(tel.JsonlSink(trace_file))
    ckpt = str(tmp_path / f"storm{seed}.npz")
    before = _metrics.REGISTRY.get("mesh_reshards_total")
    before_lost = _metrics.REGISTRY.get("mesh_reshards_lost_total")
    plan = FaultPlan(seed=seed, meshes=(
        MeshFault("host_lost", host=host, at_iters=(kill_iter,)),))
    ws, info = run_elastic(_build_fn(prog, ckpt),
                           num_hosts=NUM_HOSTS, checkpoint_path=ckpt,
                           plan=plan, bus=bus, run_id=f"storm{seed}")

    assert info["resumed"] and len(info["reshards"]) == 1
    r = info["reshards"][0]
    assert r["reason"] == "host-lost"
    assert (r["old_devices"], r["new_devices"]) == (8, 6)
    assert info["final_devices"] == 6 and info["epoch"] >= 1
    assert _metrics.REGISTRY.get("mesh_reshards_total") == before + 1
    assert _metrics.REGISTRY.get("mesh_reshards_lost_total") \
        == before_lost
    assert tel.MESH_HOST_LOST in cap.kinds()
    assert tel.MESH_RESHARD in cap.kinds()
    resh = [e for e in cap.events if e.kind == tel.MESH_RESHARD][0]
    assert resh.data["new_devices"] == 6
    assert resh.data["scenarios"] == S

    # the resumed run holds the SAME certified bracket: both sides'
    # outer bounds stay below both sides' inner bounds (they bracket
    # one EF objective), and the chaos side certifies the gap target
    ic, oc, gc = _bracket(ws)
    assert gc <= REL_GAP + 1e-6
    slack = REL_GAP * max(abs(ib), abs(ic))
    assert ob <= ic + slack and oc <= ib + slack

    # trace continuity (ISSUE 20 satellite c): the kill, the reshard
    # and the resumed attempt are ONE causal tree — the pre-kill and
    # post-reshard segments share the trace, the reshard span sits on
    # the critical path, and no span is orphaned by the host loss
    from mpisppy_tpu.telemetry import spans
    trep = spans.assemble_path(trace_file)
    assert trep["orphans"] == [], trep["orphans"]
    names = [sp["name"] for sp in trep["spans"]]
    assert names[0] == "mesh-run", names
    assert names.count("mesh-segment") == 2, names
    assert "reshard" in names, names
    assert trep["migrated_segments"] == 1
    assert trep["critical_path"]["buckets"].get(
        "migration-gap", 0) > 0, trep["critical_path"]
    return info


@pytest.mark.parametrize("seed", [0, 1])
def test_mesh_chaos_storm(tmp_path, seed):
    _storm(tmp_path, seed)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(12))
def test_mesh_chaos_soak(tmp_path, seed):
    """12-seed soak: vary the kill iteration and the victim host; the
    reshard must never lose a run (mesh_reshards_lost_total flat) and
    every resumed run must reach the certified gap."""
    _storm(tmp_path, seed, kill_iter=2 + seed % 4,
           host=1 + seed % (NUM_HOSTS - 1))
