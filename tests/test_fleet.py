# Wheel fleet (ISSUE 16; docs/serving.md fleet section): placement-
# aware global admission (FleetAdmission.pop_placed), structure-affine
# placement, the router end-to-end over its socket, live session
# migration off a killed replica, the health plane's UP/SUSPECT/DEAD
# ladder under the three ReplicaFault seams, and the corrupted-
# destination checkpoint-restore fallback.
import json
import os
import time

import numpy as np
import pytest

from mpisppy_tpu.fleet import (
    DEAD, SUSPECT, UP, FleetOptions, FleetRouter, HealthBoard,
    Replica, choose, routing_key,
)
from mpisppy_tpu.resilience.faults import FaultPlan, ReplicaFault
from mpisppy_tpu.serve import FleetAdmission, SubmitRequest
from mpisppy_tpu.serve import loadgen
from mpisppy_tpu.serve.engine import SyntheticEngine, WheelEngine
from mpisppy_tpu.serve.session import Session


def _spec(tenant="acme", **kw):
    kw.setdefault("model", "farmer")
    kw.setdefault("num_scens", 3)
    return SubmitRequest(tenant=tenant, **kw)


def _sess(tenant="acme", **kw):
    s = Session(_spec(tenant, **kw))
    s.structure_key = routing_key(s.spec)
    return s


class _FakeReplica:
    """Placement test double: id + free slots + held keys."""

    def __init__(self, rid, free=1, keys=()):
        self.id = rid
        self._free = free
        self._keys = set(keys)

    def free_slots(self):
        return self._free

    def holds(self, key):
        return key in self._keys


# ---------------------------------------------------------------------------
# placement
# ---------------------------------------------------------------------------
def test_routing_key_is_structure_content_addressed():
    """Equal (model, scale, args) specs share a key — they intern to
    the same canonical structure; different scale means a different
    key."""
    a = routing_key(_spec("acme", num_scens=4))
    b = routing_key(_spec("zeta", num_scens=4))     # tenant-agnostic
    c = routing_key(_spec("acme", num_scens=5))
    d = routing_key(_spec("acme", num_scens=4, args=("--x", "1")))
    assert a == b
    assert a != c and a != d


def test_placement_prefers_affinity_then_least_loaded():
    s = _sess("acme", num_scens=4)
    key = s.structure_key
    busy_with_key = _FakeReplica("r0", free=1, keys=(key,))
    idle_without = _FakeReplica("r1", free=3)
    rep, policy = choose(s, [busy_with_key, idle_without])
    assert rep is busy_with_key and policy == "affinity"
    # no key held anywhere: most free slots wins, id breaks ties
    rep, policy = choose(s, [_FakeReplica("r0", 1),
                             _FakeReplica("r1", 3)])
    assert rep.id == "r1" and policy == "least-loaded"
    rep, _ = choose(s, [_FakeReplica("r0", 2), _FakeReplica("r1", 2)])
    assert rep.id == "r1"          # deterministic tie-break
    assert choose(s, []) == (None, "none")


# ---------------------------------------------------------------------------
# fused WFQ pop + placement
# ---------------------------------------------------------------------------
def test_pop_placed_declined_placement_leaves_session_uncharged():
    """No live replica with a free slot: the session must stay at its
    queue front UNCHARGED (quota and virtual clock untouched), and the
    next pop with capacity gets it."""
    q = FleetAdmission(max_queued=8, default_quota=2)
    s = _sess("acme")
    q.submit(s)
    got, rep = q.pop_placed(lambda _s: None)
    assert got is None and rep is None
    st = q.stats()["tenants"]["acme"]
    assert st["queued"] == 1 and st["inflight"] == 0
    target = _FakeReplica("r0", free=1)
    got, rep = q.pop_placed(lambda _s: target)
    assert got is s and rep is target
    st = q.stats()["tenants"]["acme"]
    assert st["queued"] == 0 and st["inflight"] == 1


def test_pop_placed_aborts_when_drain_races_the_candidate():
    """A drain emptying the queue between placement and commit must
    void the pop — no charge, no ghost session."""
    q = FleetAdmission(max_queued=8, default_quota=2)
    s = _sess("acme")
    q.submit(s)

    def place(sess):
        drained = q.drain()            # the race, deterministically
        assert drained == [s]
        return _FakeReplica("r0", free=1)

    got, rep = q.pop_placed(place)
    assert got is None and rep is None
    assert q.stats()["tenants"]["acme"]["inflight"] == 0


# ---------------------------------------------------------------------------
# health board
# ---------------------------------------------------------------------------
def test_health_ladder_and_sticky_death():
    hb = HealthBoard()
    assert hb.state("r0") == UP
    # stale beats but the probe answers: degraded, not dead
    assert hb.observe("r0", fresh=False, probe_ok=True) == SUSPECT
    # beats resume: recovered
    assert hb.observe("r0", fresh=True) == UP
    # stale AND probe fails: dead, and DEAD is sticky (fencing) —
    # a partitioned replica reappearing is never readmitted
    assert hb.observe("r0", fresh=False, probe_ok=False) == DEAD
    assert hb.observe("r0", fresh=True) is None
    assert hb.state("r0") == DEAD
    assert hb.snapshot() == {"r0": DEAD}


# ---------------------------------------------------------------------------
# router end-to-end (SyntheticEngine replicas over real sockets)
# ---------------------------------------------------------------------------
def _start_fleet(tmp_path, n=2, iters=8, step_s=0.01, fault_plan=None,
                 **opts_kw):
    opts_kw.setdefault("trace_dir", str(tmp_path / "traces"))
    opts_kw.setdefault("spool_dir", str(tmp_path / "spool"))
    opts_kw.setdefault("heartbeat_s", 0.05)
    return FleetRouter(FleetOptions(
        n_replicas=n, max_running_per_replica=2,
        engine_factory=lambda rid: SyntheticEngine(iters=iters,
                                                   step_s=step_s),
        fault_plan=fault_plan, **opts_kw)).start()


def _drive(router, n_sessions, timeout=30.0, tenants=("t0", "t1")):
    """Submit n sessions and stream to terminal; returns {sid:
    [events...]} keyed in arrival order."""
    cl = loadgen.ServeClient(router.address, timeout=timeout)
    acks = [cl.submit(_spec(tenants[i % len(tenants)]))
            for i in range(n_sessions)]
    assert all(a.get("ok") for a in acks), acks
    terminal = {}
    for msg in cl.stream():
        if msg.get("event") in ("done", "failed", "rejected"):
            terminal.setdefault(msg["session"], []).append(msg)
            if len(terminal) == n_sessions:
                break
    cl.close()
    return terminal


def test_fleet_router_serves_and_reports(tmp_path):
    """Plain traffic through the router: every session lands DONE on
    some replica, the status/stats ops answer over the socket, and
    each session got exactly one fleet-placement event."""
    router = _start_fleet(tmp_path)
    try:
        terminal = _drive(router, 6)
        assert all(v[0]["event"] == "done" for v in terminal.values())
        cl = loadgen.ServeClient(router.address)
        cl.send({"op": "status"})
        st = cl.recv()["status"]
        assert set(st["replicas"]) == {"r0", "r1"}
        assert all(r["alive"] for r in st["replicas"].values())
        cl.send({"op": "stats"})
        stats = cl.recv()["stats"]
        assert stats["states"].get("DONE", 0) == 6
        assert stats["migration"]["lost"] == 0
        cl.close()
    finally:
        router.stop()
    fleet_log = tmp_path / "traces" / "fleet.jsonl"
    placements = [json.loads(ln) for ln in
                  fleet_log.read_text().splitlines()
                  if json.loads(ln)["kind"] == "fleet-placement"]
    assert len(placements) == 6
    # per-replica trace subdirectories carry the session traces
    placed_reps = {p["data"]["replica"] for p in placements}
    for rid in placed_reps:
        assert list((tmp_path / "traces" / rid).glob("session-*.jsonl"))


def test_fleet_kill_replica_live_migrates_running_sessions(tmp_path):
    """The tentpole acceptance in miniature: r0 dies mid-traffic, its
    running sessions drain through the emergency-checkpoint hand-off
    and finish on r1 — every session exactly one terminal outcome,
    zero migrations lost, and the migrated sessions' resume cursors
    carried (SyntheticEngine resumes from session.resume_iter on a
    DIFFERENT engine instance)."""
    plan = FaultPlan(replicas=(
        ReplicaFault("kill", replica="r0", at_beats=(4,)),))
    router = _start_fleet(tmp_path, iters=40, step_s=0.02,
                          fault_plan=plan)
    try:
        terminal = _drive(router, 6, timeout=60.0)
        assert all(len(v) == 1 for v in terminal.values()), terminal
        assert all(v[0]["event"] == "done" for v in terminal.values())
        stats = router.stats()
        mig = stats["migration"]
        assert mig["started"] >= 1, "kill landed after traffic: " \
            "no migration exercised"
        assert mig["completed"] == mig["started"]
        assert mig["lost"] == 0
        assert stats["health"]["r0"] == DEAD
    finally:
        router.stop()
    rows = [json.loads(ln) for ln in
            (tmp_path / "traces" / "fleet.jsonl")
            .read_text().splitlines()]
    migrated = {r["data"]["session"] for r in rows
                if r["kind"] == "session-migrated"}
    assert migrated
    # exactly one terminal session-state row per session fleet-wide
    terminals = {}
    for r in rows:
        if r["kind"] == "session-state" and \
                r["data"].get("state") in ("DONE", "FAILED",
                                           "REJECTED"):
            sid = r["data"]["session"]
            terminals[sid] = terminals.get(sid, 0) + 1
    assert all(n == 1 for n in terminals.values()), terminals
    # a migrated session's trace is split across BOTH replicas'
    # subdirectories (source segment + destination segment)
    sid = sorted(migrated)[0]
    assert (tmp_path / "traces" / "r0" / f"session-{sid}.jsonl").exists()
    assert (tmp_path / "traces" / "r1" / f"session-{sid}.jsonl").exists()


def test_fleet_partition_fences_and_drains(tmp_path):
    """A partitioned replica (beats AND probes suppressed) goes DEAD
    after the miss budget, its sessions migrate, and it stays fenced
    even after the partition window ends."""
    plan = FaultPlan(replicas=(
        ReplicaFault("partition", replica="r0",
                     at_beats=tuple(range(3, 100))),))
    router = _start_fleet(tmp_path, iters=40, step_s=0.02,
                          fault_plan=plan)
    try:
        terminal = _drive(router, 4, timeout=60.0)
        assert all(v[0]["event"] == "done" for v in terminal.values())
        stats = router.stats()
        assert stats["health"]["r0"] == DEAD
        assert stats["migration"]["lost"] == 0
        assert not router.replicas[0].alive()     # fenced for good
    finally:
        router.stop()


def test_fleet_slow_heartbeat_is_suspect_not_dead(tmp_path):
    """A slow-but-alive replica (delayed beats, answering probes) is
    at worst SUSPECT: no fencing, no migration, traffic completes."""
    plan = FaultPlan(replicas=(
        ReplicaFault("slow_heartbeat", replica="r0", delay_s=0.4),))
    router = _start_fleet(tmp_path, iters=10, step_s=0.01,
                          fault_plan=plan)
    try:
        terminal = _drive(router, 4, timeout=60.0)
        assert all(v[0]["event"] == "done" for v in terminal.values())
        time.sleep(0.5)                 # a few monitor cycles
        stats = router.stats()
        assert stats["health"].get("r0") in (None, UP, SUSPECT)
        assert stats["migration"]["started"] == 0
        assert router.replicas[0].alive()
    finally:
        router.stop()


def test_fleet_typed_backpressure_and_drain(tmp_path):
    """Global queue caps reject typed at the ROUTER (replica queues
    are non-binding), and stop() settles queued sessions typed."""
    router = _start_fleet(tmp_path, n=1, iters=200, step_s=0.02,
                          max_queued=2, max_queued_per_tenant=2,
                          tenant_quota=1)
    try:
        cl = loadgen.ServeClient(router.address, timeout=30.0)
        acks = [cl.submit(_spec("flood")) for _ in range(6)]
        rejected = [a for a in acks if not a.get("ok")]
        assert rejected
        assert all(a["error"] == "rejected" and a["reason"] in
                   ("queue-full", "tenant-queue-full")
                   for a in rejected)
        cl.close()
    finally:
        router.stop()
    # nothing non-terminal survives stop()
    assert all(s.is_terminal()
               for s in router._sessions.values())


# ---------------------------------------------------------------------------
# corrupted-destination restore (satellite): the migration target must
# survive a corrupt newest snapshot via the rotation fallback
# ---------------------------------------------------------------------------
class _Cap:
    def __init__(self):
        self.events = []

    def handle(self, event):
        self.events.append(event)

    def close(self):
        pass


def test_migration_restore_falls_back_past_corrupt_newest(tmp_path):
    """Two preemptions on the source engine leave a rotated snapshot
    pair (ckpt @ iter_b, ckpt.1 @ iter_a) in the shared spool.  The
    newest is then corrupted in place (payload flipped, stale CRC) —
    exactly the torn-migration hazard.  The DESTINATION replica's
    engine must reject it on CRC, fall back to the older rotation
    slot, emit checkpoint-restore with fallback=True, and still finish
    the session."""
    spool = tmp_path / "spool"
    spool.mkdir()
    path = str(spool / "ckpt-mig.npz")
    sess = Session(_spec(tenant="acme", gap_target=0.01,
                         max_iterations=150))
    sess.checkpoint_path = path

    src = WheelEngine(multiplexed=False)
    v, _ = src.run(sess, fault_plan=FaultPlan(seed=5,
                                              preempt_at_iter=3))
    assert v == "preempted"
    sess.restore = True
    v, _ = src.run(sess, fault_plan=FaultPlan(seed=5,
                                              preempt_at_iter=7))
    assert v == "preempted"
    assert os.path.exists(path) and os.path.exists(path + ".1")

    # corrupt the NEWEST snapshot: perturb a state leaf but keep the
    # stored CRC — np.load succeeds, the integrity check must not
    with np.load(path) as d:
        arrays = {k: np.array(d[k]) for k in d.files}
    arrays["leaf0"] = arrays["leaf0"] + 1.0
    with open(path, "wb") as f:
        np.savez(f, **arrays)

    cap = _Cap()
    sess.bus.subscribe(cap)
    dst = WheelEngine(multiplexed=False)    # a DIFFERENT engine
    v, payload = dst.run(sess)
    assert v == "done"
    assert payload["rel_gap"] <= 0.01 + 1e-9
    restores = [e for e in cap.events
                if e.kind == "checkpoint-restore"]
    assert restores, "no restore event: the destination never loaded"
    assert restores[0].data.get("fallback") is True
    assert restores[0].data.get("path", "").endswith(".1")
