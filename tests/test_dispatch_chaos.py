# Dispatch/device fault domain (ISSUE 9): the seeded randomized chaos
# soak over the solve scheduler's serving invariants — no deadlock, no
# caller ever blocks past its deadline, quarantined work is exactly
# accounted, healthy requests always get THEIR lanes back — plus the
# wheel-level contract: dispatch-layer chaos (hung dispatches, poison
# requests, dispatcher death) cannot corrupt the wheel's certified
# bounds, and checkpoint->restore mid-fault reproduces the fault-free
# run.  The fast seeded subset runs in tier-1 (<=20 s); the long soak
# is `slow`.  docs/dispatch.md (failure semantics) + docs/resilience.md
# (fault domain) document the contracts pinned here.
import dataclasses
import threading
import time

import numpy as np
import pytest
import jax.numpy as jnp

from mpisppy_tpu import dispatch
from mpisppy_tpu.dispatch import (
    DispatchOptions, SolveFailed, SolveScheduler,
)
from mpisppy_tpu.ops.bnb import BnBResult
from mpisppy_tpu.resilience import DispatchFault, FaultPlan

from test_mip_bnb import random_mips

pytestmark = pytest.mark.chaos


def _fake_result(qp):
    S = qp.c.shape[0]
    return BnBResult(
        x=jnp.zeros_like(qp.c),
        inner=jnp.sum(qp.c, axis=-1),        # request-identifying value
        outer=jnp.sum(qp.c, axis=-1) - 1.0,
        gap=jnp.zeros((S,), qp.c.dtype),
        feasible=jnp.ones((S,), bool),
        nodes_solved=jnp.ones((S,), jnp.int32))


def _fake_solve(qp, d_col, int_cols, opts, **kw):
    time.sleep(0.002)                        # a tiny "device" latency
    return _fake_result(qp)


# ---------------------------------------------------------------------------
# the seeded soak harness
# ---------------------------------------------------------------------------
def run_soak_round(seed: int, n_submitters: int = 8,
                   submits_each: int = 2) -> dict:
    """One seeded chaos round: a threaded storm of submits against a
    scheduler armed with a randomized dispatch FaultPlan.  Returns the
    bookkeeping the invariant asserts below consume."""
    rng = np.random.default_rng(seed)
    total = n_submitters * submits_each
    # randomized fault mix, all seeded: a few poisoned submits, a
    # dropped ticket, an exception or hang on an early attempt, and
    # slow-device jitter on everything
    poison = tuple(int(s) for s in rng.choice(
        total, size=rng.integers(1, 3), replace=False))
    droppable = sorted(set(range(total)) - set(poison))
    drop = (int(rng.choice(droppable)),)
    burst_kind = "hang" if rng.random() < 0.5 else "exception"
    plan = FaultPlan(seed=seed, dispatches=(
        DispatchFault("poison", submits=poison),
        DispatchFault("drop_ticket", submits=drop),
        DispatchFault(burst_kind, at_dispatches=(int(rng.integers(0, 3)),),
                      hang_s=30.0),
        DispatchFault("slow", jitter_s=0.005),
    ))
    sched = SolveScheduler(
        DispatchOptions(max_wait_ms=2.0, max_inflight=2,
                        dispatch_timeout_s=0.25, retry_max=1,
                        retry_backoff_s=0.005, deadline_s=2.0),
        solve_fn=_fake_solve, fault_plan=plan)
    base, _, _ = random_mips(S=2, n=6, m=4)
    d = jnp.ones(base.c.shape[-1], jnp.float32)
    ic = np.arange(2, dtype=np.int32)
    cs = [rng.standard_normal((2, 6)).astype(np.float32)
          for _ in range(total)]

    # keyed by the SCHEDULER-assigned submit id (ticket.sid): threaded
    # submits race, so the fault plan's submit indices can land on any
    # submitter — exactly like production traffic
    outcomes: dict[int, object] = {}
    expected: dict[int, np.ndarray] = {}
    lock = threading.Lock()

    def submitter(tid):
        for j in range(submits_each):
            k = tid * submits_each + j
            qp = dataclasses.replace(base, c=jnp.asarray(cs[k]))
            t = sched.submit(qp, d, ic)
            with lock:
                expected[t.sid] = cs[k]
            try:
                res = t.result(timeout=10.0)
                with lock:
                    outcomes[t.sid] = np.asarray(res.inner)
            except SolveFailed as e:
                with lock:
                    outcomes[t.sid] = e

    threads = [threading.Thread(target=submitter, args=(i,))
               for i in range(n_submitters)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    wall = time.perf_counter() - t0
    assert not any(t.is_alive() for t in threads), \
        f"DEADLOCK: submitters still alive after 60s (seed {seed})"
    sched.close()
    return {"seed": seed, "plan": plan, "sched": sched,
            "expected": expected, "poison": set(poison),
            "drop": set(drop), "outcomes": outcomes, "total": total,
            "wall": wall}


def assert_soak_invariants(r: dict) -> None:
    """The serving invariants (ISSUE 9 acceptance)."""
    outcomes, expected = r["outcomes"], r["expected"]
    # every ticket RESOLVED (result or typed failure) — never a hang
    assert set(outcomes) == set(range(r["total"]))
    st = r["sched"].stats()
    for sid, out in outcomes.items():
        if sid in r["poison"]:
            assert isinstance(out, SolveFailed), \
                f"poisoned submit {sid} returned a result (seed {r['seed']})"
            assert out.reason in ("exception", "timeout", "deadline")
        elif sid in r["drop"]:
            # a dropped delivery resolves by deadline, never a hang
            assert isinstance(out, SolveFailed) \
                and out.reason == "deadline", out
        elif isinstance(out, SolveFailed):
            # collateral of a killed/faulted window is allowed but must
            # be TYPED — silent hangs and foreign lanes are not
            assert out.reason in ("timeout", "exception", "deadline",
                                  "dispatcher-died")
        else:
            # healthy submits got exactly THEIR lanes back
            # atol covers f32 reduction-order noise on a coalesced/
            # padded batch; a foreign lane would differ at O(1)
            assert np.allclose(out, expected[sid].sum(-1), atol=1e-4), \
                f"submit {sid} got foreign lanes (seed {r['seed']})"
    # quarantine accounting: every poisoned lane the scheduler resolved
    # as SolveFailed('exception'/'timeout') is counted; deadline-
    # resolved tickets (caller gave up first) don't reach quarantine
    resolved_q = sum(
        2 for sid in r["poison"]
        if isinstance(outcomes[sid], SolveFailed)
        and outcomes[sid].reason in ("exception", "timeout"))
    assert st["quarantined_lanes"] >= resolved_q
    # the fault plan actually fired its dispatch seams
    seams = {s for s, _ in r["plan"].fired}
    assert "dispatch" in seams
    # bounded wall: nothing waited out the full 15 s deadline budget
    # unless a drop/hang forced it — the round itself stays snappy
    assert r["wall"] < 45.0


def test_chaos_soak_fast_seeded():
    """Tier-1 subset: two seeded rounds, <=20 s total."""
    for seed in (101, 202):
        assert_soak_invariants(run_soak_round(seed))


@pytest.mark.slow
def test_chaos_soak_long():
    """The long soak: many seeded rounds across the fault mix space."""
    for seed in range(300, 312):
        assert_soak_invariants(run_soak_round(seed))


# ---------------------------------------------------------------------------
# wheel-level serving invariants: dispatch chaos + preemption mid-storm
# cannot corrupt certified bounds; restore reproduces the fault-free run
# ---------------------------------------------------------------------------
def _farmer_wheel_parts(num_scens=3):
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.cylinders import (
        LagrangianOuterBound, PHHub, XhatXbarInnerBound,
    )
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.ops import pdhg

    names = farmer.scenario_names_creator(num_scens)
    specs = [farmer.scenario_creator(nm, num_scens=num_scens)
             for nm in names]
    batch = batch_mod.from_specs(specs)

    def hub_dict(hub_extra=None, max_iterations=150):
        hub_opts = {"rel_gap": 5e-3}
        hub_opts.update(hub_extra or {})
        return {
            "hub_class": PHHub,
            "hub_kwargs": {"options": hub_opts},
            "opt_class": ph_mod.PH,
            "opt_kwargs": {"options": ph_mod.PHOptions(
                default_rho=1.0, max_iterations=max_iterations,
                conv_thresh=0.0, subproblem_windows=10,
                pdhg=pdhg.PDHGOptions(tol=1e-7)), "batch": batch},
        }

    spokes = [
        {"spoke_class": LagrangianOuterBound,
         "opt_kwargs": {"options": {}}},
        {"spoke_class": XhatXbarInnerBound,
         "opt_kwargs": {"options": {}}},
    ]
    return hub_dict, spokes


def test_wheel_bounds_survive_dispatch_chaos_and_preemption(tmp_path):
    """The acceptance round trip: spin the farmer wheel while a
    concurrent storm hammers the process-default scheduler under a
    hung-dispatch + poison FaultPlan, preempt mid-storm, restore, and
    the resumed wheel's certified bounds must equal the fault-free
    run's (the quarantined storm work is excluded by construction —
    its tickets resolved SolveFailed, not into anyone's bounds)."""
    from mpisppy_tpu.resilience import SimulatedPreemption
    from mpisppy_tpu.spin_the_wheel import WheelSpinner

    hub_dict, spokes = _farmer_wheel_parts(3)
    ws0 = WheelSpinner(hub_dict(), [dict(d) for d in spokes]).spin()
    inner0, outer0 = ws0.BestInnerBound, ws0.BestOuterBound
    assert np.isfinite(inner0) and np.isfinite(outer0)

    plan = FaultPlan(seed=7, dispatches=(
        DispatchFault("poison", submits=(1,)),
        DispatchFault("hang", at_dispatches=(0,), hang_s=30.0),
    ), preempt_at_iter=6)
    sched = dispatch.configure(DispatchOptions(
        max_wait_ms=2.0, dispatch_timeout_s=0.2, retry_max=1,
        retry_backoff_s=0.005, deadline_s=10.0))
    sched.solve_fn = _fake_solve
    sched.fault_plan = plan
    base, _, _ = random_mips(S=2, n=6, m=4)
    d = jnp.ones(base.c.shape[-1], jnp.float32)
    ic = np.arange(2, dtype=np.int32)
    storm_out = {}

    def storm():
        tickets = [sched.submit(dataclasses.replace(
            base, c=base.c * (k + 1)), d, ic) for k in range(4)]
        for k, t in enumerate(tickets):
            try:
                storm_out[k] = np.asarray(t.result(timeout=10.0).inner)
            except SolveFailed as e:
                storm_out[k] = e

    ckpt = str(tmp_path / "wheel.npz")
    ws1 = WheelSpinner(
        hub_dict({"fault_plan": plan, "checkpoint_path": ckpt,
                  "checkpoint_every_s": 1e9}),
        [dict(d) for d in spokes])
    st_thread = threading.Thread(target=storm)
    st_thread.start()
    try:
        with pytest.raises(SimulatedPreemption):
            ws1.spin()
        st_thread.join(timeout=30.0)
        assert not st_thread.is_alive(), "storm deadlocked the wheel run"
    finally:
        dispatch.configure()  # restore the real process default
    # the chaos seams fired, the storm resolved every ticket, and the
    # poisoned one is a typed failure
    assert {"dispatch", "preemption"} <= {s for s, _ in plan.fired}
    assert set(storm_out) == {0, 1, 2, 3}
    assert isinstance(storm_out[1], SolveFailed)
    healthy = [k for k in (0, 2, 3)
               if not isinstance(storm_out[k], SolveFailed)]
    for k in healthy:
        assert np.allclose(storm_out[k],
                           np.asarray(base.c * (k + 1)).sum(-1),
                           atol=1e-4)

    # restore and resume to termination: bounds match the fault-free run
    ws2 = WheelSpinner(hub_dict({"checkpoint_path": ckpt}),
                       [dict(d) for d in spokes]).build()
    ws2.spcomm.load_checkpoint(ckpt)
    ws2.spin()
    _, rel_gap = ws2.spcomm.compute_gaps()
    assert rel_gap <= 5e-3 + 1e-6
    assert ws2.BestInnerBound == pytest.approx(inner0, rel=1e-2)
    assert ws2.BestOuterBound == pytest.approx(outer0, rel=1e-2)


def test_emergency_save_with_dispatch_in_flight(tmp_path):
    """Satellite regression: SIGTERM/preemption at a hub iteration with
    a megabatch still IN FLIGHT must not deadlock the emergency save —
    the save path is independent of the dispatch layer, the preempted
    run exits promptly, and the in-flight ticket still resolves (late
    result or typed failure), never a hang."""
    from mpisppy_tpu.resilience import SimulatedPreemption
    from mpisppy_tpu.spin_the_wheel import WheelSpinner

    hub_dict, spokes = _farmer_wheel_parts(3)
    plan = FaultPlan(seed=9, preempt_at_iter=3)
    sched = dispatch.configure(DispatchOptions(
        max_wait_ms=2.0, deadline_s=20.0))

    def slow_solve(qp, d_col, int_cols, opts, **kw):
        time.sleep(1.5)               # still running at preempt time
        return _fake_result(qp)

    sched.solve_fn = slow_solve
    base, _, _ = random_mips(S=2, n=6, m=4)
    d = jnp.ones(base.c.shape[-1], jnp.float32)
    ticket = sched.submit(base, d, np.arange(2, dtype=np.int32))
    ckpt = str(tmp_path / "wheel.npz")
    ws = WheelSpinner(
        hub_dict({"fault_plan": plan, "checkpoint_path": ckpt,
                  "checkpoint_every_s": 1e9}),
        [dict(d) for d in spokes])
    t0 = time.perf_counter()
    try:
        with pytest.raises(SimulatedPreemption):
            ws.spin()
        saved_in = time.perf_counter() - t0
        import os
        assert os.path.exists(ckpt), "emergency save never landed"
        # the save must not have waited out the in-flight dispatch
        # plus margin — a deadlock here used to mean 'forever'
        assert saved_in < 60.0
        res = ticket.result(timeout=20.0)
        assert np.asarray(res.inner).shape == (2,)
    finally:
        dispatch.configure()
