# Interpret-mode coverage for the Pallas VMEM window kernel
# (ops/pdhg_pallas.py) — the TPU engine behind PDHGOptions.use_pallas.
# The real-chip path differs only in lowering; interpret mode runs the
# same kernel trace on CPU, so the math (hoisted invariants, folded
# done-masking, the manual bf16x3 three-pass matvec) is exercised in CI.
# Role model: the reference tests its solver plumbing on tiny instances
# without real solvers (ref:mpisppy/tests/test_ef_ph.py builds 3-scenario
# farmer models); here the "solver" is ours, so we check it directly.
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpisppy_tpu.ops import pdhg
from mpisppy_tpu.ops.boxqp import make_boxqp


def _random_batch_lp(S=5, m=7, n=11, seed=0):
    """Small feasible batched LP with a SHARED dense A (the Pallas
    kernel's supported shape) and per-scenario c/rhs."""
    rng = np.random.default_rng(seed)
    A = rng.normal(size=(m, n))
    x_feas = rng.uniform(0.2, 0.8, size=(S, n))
    slack = rng.uniform(0.5, 1.5, size=(S, m))
    b = np.einsum("mn,sn->sm", A, x_feas)
    return make_boxqp(
        c=rng.normal(size=(S, n)),
        A=A,
        bl=b - slack,
        bu=b + slack,
        l=np.zeros((S, n)),
        u=np.ones((S, n)),
    )


def _run(p, opts, n_windows=2):
    st0 = pdhg.init_state(p, opts)
    return pdhg.solve_fixed(p, n_windows, opts, st0)


@pytest.mark.parametrize("iter_precision", [None, "high", "bf16x3"])
def test_window_kernel_matches_xla_path(iter_precision):
    p = _random_batch_lp()
    xla = _run(p, pdhg.PDHGOptions(use_pallas=False,
                                   iter_precision=iter_precision))
    pal = _run(p, pdhg.PDHGOptions(use_pallas=True,
                                   iter_precision=iter_precision))
    # same math up to float reassociation (None) or the bf16x3 manual
    # decomposition standing in for Precision.HIGH ("high"/"bf16x3" —
    # the bench-engaged alias, ops/boxqp.py PRECISION_ALIASES)
    tol = 1e-4 if iter_precision is None else 5e-2
    np.testing.assert_allclose(pal.x, xla.x, atol=tol, rtol=tol)
    np.testing.assert_allclose(pal.y, xla.y, atol=tol, rtol=tol)
    np.testing.assert_allclose(pal.x_sum, xla.x_sum, atol=80 * tol,
                               rtol=tol)


@pytest.mark.parametrize("iter_precision", [None, "bf16x3"])
def test_pipelined_kernel_bit_matches_single_buffer(iter_precision):
    """The double-buffered engine (ISSUE 8 tentpole) is a pure data-
    movement restructure: both engines run the same _tile_math trace
    per tile, so their outputs must BIT-match on CPU interpret — any
    drift means the pipeline touched math, not just DMA.  Covers
    multiple tiles, a tile count that doesn't divide the batch, and
    the bf16x3 three-pass mode."""
    for S, tile, seed in ((13, 4, 0), (8, 8, 1), (6, 2, 2)):
        p = _random_batch_lp(S=S, seed=seed)
        opts = pdhg.PDHGOptions(use_pallas=True, restart_period=9,
                                pallas_tile_s=tile)
        st = pdhg.init_state(p, opts)
        tau = opts.step_margin * st.omega / st.Lnorm
        sigma = opts.step_margin / (st.omega * st.Lnorm)

        from mpisppy_tpu.ops import pdhg_pallas
        args = (p, st.x, st.y, st.x_sum, st.y_sum, tau, sigma, st.done,
                opts.restart_period)
        single = pdhg_pallas.run_window(
            *args, tile_s=tile, precision=iter_precision,
            pipeline=False, interpret=True)
        piped = pdhg_pallas.run_window(
            *args, tile_s=tile, precision=iter_precision,
            pipeline=True, interpret=True)
        for a, b in zip(single, piped):
            assert np.array_equal(np.asarray(a), np.asarray(b))


def test_unknown_iter_precision_rejected_with_alias_list():
    """ISSUE 8 satellite: a typo'd precision string must fail with the
    valid aliases in the message, never silently trace at the module
    default."""
    from mpisppy_tpu.ops import boxqp
    with pytest.raises(ValueError, match="bf16x3"):
        boxqp.as_precision("bf16x4")
    with pytest.raises(ValueError, match="valid aliases"):
        p = _random_batch_lp(S=2)
        opts = pdhg.PDHGOptions(use_pallas=False, iter_precision="hihg")
        pdhg.solve_fixed(p, 1, opts, pdhg.init_state(p, opts))
    # the engaged aliases resolve (and agree with their Precision twins)
    import jax
    assert boxqp.as_precision("bf16x3") == jax.lax.Precision.HIGH
    assert boxqp.as_precision("bf16x3") == boxqp.as_precision("high")
    assert boxqp.as_precision("bf16x6") == jax.lax.Precision.HIGHEST


def test_done_scenarios_are_frozen():
    """The folded done-masking (tau=sigma=0) must be an exact no-op on
    frozen scenarios while window sums keep accumulating the frozen
    iterate — the same contract as the XLA path's where-blend."""
    p = _random_batch_lp(S=4)
    opts = pdhg.PDHGOptions(use_pallas=True, restart_period=6)
    st0 = pdhg.init_state(p, opts)
    # mark scenarios 1 and 3 done with distinctive iterates
    x_mark = jnp.clip(st0.x + 0.25, p.l, p.u)
    done = jnp.array([False, True, False, True])
    st0 = dataclasses.replace(st0, x=x_mark, done=done)

    from mpisppy_tpu.ops import pdhg_pallas
    tau = opts.step_margin * st0.omega / st0.Lnorm
    sigma = opts.step_margin / (st0.omega * st0.Lnorm)
    x, y, xs, ys = pdhg_pallas.run_window(
        p, st0.x, st0.y, st0.x_sum, st0.y_sum, tau, sigma, st0.done,
        opts.restart_period, interpret=True)
    np.testing.assert_allclose(x[1], x_mark[1], atol=1e-6)
    np.testing.assert_allclose(x[3], x_mark[3], atol=1e-6)
    np.testing.assert_allclose(y[1], st0.y[1], atol=1e-6)
    # frozen scenarios accumulate their frozen iterate every iteration
    np.testing.assert_allclose(
        xs[1], opts.restart_period * x_mark[1], atol=1e-5)
    # live scenarios actually moved
    assert float(jnp.max(jnp.abs(x[0] - x_mark[0]))) > 1e-6


def test_padding_is_exact_noop():
    """Scenario counts and row/col dims that don't divide the hardware
    tiles must give the same answer as an aligned problem (pad scenarios
    frozen, pad columns pinned at 0, pad rows dual-pinned at 0)."""
    p = _random_batch_lp(S=3, m=5, n=9, seed=1)
    xla = _run(p, pdhg.PDHGOptions(use_pallas=False))
    pal = _run(p, pdhg.PDHGOptions(use_pallas=True, pallas_tile_s=8))
    np.testing.assert_allclose(pal.x, xla.x, atol=1e-4, rtol=1e-4)


def test_bf16x3_wheel_publishes_same_certified_bounds():
    """ISSUE 8 satellite: the certificate-unaffected contract.  A wheel
    run with bf16x3 ITERATION matvecs (through the real Pallas kernel,
    interpret mode) must publish the same certified outer/inner bounds
    as the full-precision wheel within the restart-recheck tolerance —
    restart candidate scoring, convergence tests, and every published
    bound always re-evaluate at the boxqp module default (bf16x6), so
    a cheaper iteration path can shift the ITERATES it proposes but
    never what gets certified."""
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.cylinders import (
        LagrangianOuterBound, PHHub, XhatXbarInnerBound,
    )
    from mpisppy_tpu.models import sslp
    from mpisppy_tpu.spin_the_wheel import WheelSpinner
    from mpisppy_tpu.algos import ph as ph_mod

    inst = sslp.synthetic_instance(5, 10, seed=0)
    specs = [sslp.scenario_creator(nm, instance=inst, num_scens=6,
                                   lp_relax=True)
             for nm in sslp.scenario_names_creator(6)]

    def run(iter_precision, use_pallas):
        batch = batch_mod.from_specs(specs)
        opts = ph_mod.PHOptions(
            default_rho=20.0, max_iterations=100, conv_thresh=0.0,
            subproblem_windows=8,
            pdhg=pdhg.PDHGOptions(tol=1e-6, use_pallas=use_pallas,
                                  pallas_tile_s=8,
                                  iter_precision=iter_precision))
        spokes = [
            {"spoke_class": LagrangianOuterBound,
             "opt_kwargs": {"options": {}}},
            {"spoke_class": XhatXbarInnerBound,
             "opt_kwargs": {"options": {}}},
        ]
        hub = {"hub_class": PHHub,
               "hub_kwargs": {"options": {"rel_gap": 0.01}},
               "opt_class": ph_mod.PH,
               "opt_kwargs": {"options": opts, "batch": batch}}
        ws = WheelSpinner(hub, spokes).spin()
        assert np.isfinite(ws.BestOuterBound)
        assert np.isfinite(ws.BestInnerBound)
        rel_gap = (ws.BestInnerBound - ws.BestOuterBound) \
            / abs(ws.BestInnerBound)
        assert rel_gap <= 0.01 + 1e-6   # both runs actually certify
        return ws.BestOuterBound, ws.BestInnerBound

    out_full, in_full = run(None, use_pallas=False)
    out_b3, in_b3 = run("bf16x3", use_pallas=True)
    # restart-recheck tolerance: candidates are scored at full
    # precision against tol=1e-6 relative KKT, so published bounds of
    # the two runs may differ only at that order, not at bf16 order
    tol = 2e-3 * max(1.0, abs(in_full))
    assert abs(out_b3 - out_full) <= tol
    assert abs(in_b3 - in_full) <= tol


def test_three_pass_dot_accuracy():
    """The manual bf16x3 decomposition must be far more accurate than a
    single bf16 pass (it mirrors Precision.HIGH, which Mosaic rejects)."""
    rng = np.random.default_rng(3)
    v32 = rng.normal(size=(16, 64)).astype(np.float32)
    M32 = rng.normal(size=(64, 32)).astype(np.float32)
    # f64 numpy reference: jnp matmul is NOT a trustworthy reference
    # here (some backends run DEFAULT-precision f32 matmuls as bf16
    # passes — measured on both the axon CPU backend and v5e)
    exact = (v32.astype(np.float64) @ M32.astype(np.float64)).astype(
        np.float32)
    v, M = jnp.asarray(v32), jnp.asarray(M32)
    from mpisppy_tpu.ops.pdhg_pallas import _dot3, _split_bf16
    hi, lo = _split_bf16(M)
    got = jax.jit(lambda v, hi, lo: _dot3(_split_bf16(v), hi, lo))(
        v, hi, lo)
    one_pass = jax.jit(lambda a, b: jax.lax.dot_general(
        a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32))(v, M)
    err3 = float(jnp.max(jnp.abs(got - exact)))
    err1 = float(jnp.max(jnp.abs(one_pass - exact)))
    assert err3 < err1 / 50
    assert err3 < 5e-4
