# Interpret-mode coverage for the Pallas VMEM window kernel
# (ops/pdhg_pallas.py) — the TPU engine behind PDHGOptions.use_pallas.
# The real-chip path differs only in lowering; interpret mode runs the
# same kernel trace on CPU, so the math (hoisted invariants, folded
# done-masking, the manual bf16x3 three-pass matvec) is exercised in CI.
# Role model: the reference tests its solver plumbing on tiny instances
# without real solvers (ref:mpisppy/tests/test_ef_ph.py builds 3-scenario
# farmer models); here the "solver" is ours, so we check it directly.
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpisppy_tpu.ops import pdhg
from mpisppy_tpu.ops.boxqp import make_boxqp


def _random_batch_lp(S=5, m=7, n=11, seed=0):
    """Small feasible batched LP with a SHARED dense A (the Pallas
    kernel's supported shape) and per-scenario c/rhs."""
    rng = np.random.default_rng(seed)
    A = rng.normal(size=(m, n))
    x_feas = rng.uniform(0.2, 0.8, size=(S, n))
    slack = rng.uniform(0.5, 1.5, size=(S, m))
    b = np.einsum("mn,sn->sm", A, x_feas)
    return make_boxqp(
        c=rng.normal(size=(S, n)),
        A=A,
        bl=b - slack,
        bu=b + slack,
        l=np.zeros((S, n)),
        u=np.ones((S, n)),
    )


def _run(p, opts, n_windows=2):
    st0 = pdhg.init_state(p, opts)
    return pdhg.solve_fixed(p, n_windows, opts, st0)


@pytest.mark.parametrize("iter_precision", [None, "high"])
def test_window_kernel_matches_xla_path(iter_precision):
    p = _random_batch_lp()
    xla = _run(p, pdhg.PDHGOptions(use_pallas=False,
                                   iter_precision=iter_precision))
    pal = _run(p, pdhg.PDHGOptions(use_pallas=True,
                                   iter_precision=iter_precision))
    # same math up to float reassociation (None) or the bf16x3 manual
    # decomposition standing in for Precision.HIGH ("high")
    tol = 1e-4 if iter_precision is None else 5e-2
    np.testing.assert_allclose(pal.x, xla.x, atol=tol, rtol=tol)
    np.testing.assert_allclose(pal.y, xla.y, atol=tol, rtol=tol)
    np.testing.assert_allclose(pal.x_sum, xla.x_sum, atol=80 * tol,
                               rtol=tol)


def test_done_scenarios_are_frozen():
    """The folded done-masking (tau=sigma=0) must be an exact no-op on
    frozen scenarios while window sums keep accumulating the frozen
    iterate — the same contract as the XLA path's where-blend."""
    p = _random_batch_lp(S=4)
    opts = pdhg.PDHGOptions(use_pallas=True, restart_period=6)
    st0 = pdhg.init_state(p, opts)
    # mark scenarios 1 and 3 done with distinctive iterates
    x_mark = jnp.clip(st0.x + 0.25, p.l, p.u)
    done = jnp.array([False, True, False, True])
    st0 = dataclasses.replace(st0, x=x_mark, done=done)

    from mpisppy_tpu.ops import pdhg_pallas
    tau = opts.step_margin * st0.omega / st0.Lnorm
    sigma = opts.step_margin / (st0.omega * st0.Lnorm)
    x, y, xs, ys = pdhg_pallas.run_window(
        p, st0.x, st0.y, st0.x_sum, st0.y_sum, tau, sigma, st0.done,
        opts.restart_period, interpret=True)
    np.testing.assert_allclose(x[1], x_mark[1], atol=1e-6)
    np.testing.assert_allclose(x[3], x_mark[3], atol=1e-6)
    np.testing.assert_allclose(y[1], st0.y[1], atol=1e-6)
    # frozen scenarios accumulate their frozen iterate every iteration
    np.testing.assert_allclose(
        xs[1], opts.restart_period * x_mark[1], atol=1e-5)
    # live scenarios actually moved
    assert float(jnp.max(jnp.abs(x[0] - x_mark[0]))) > 1e-6


def test_padding_is_exact_noop():
    """Scenario counts and row/col dims that don't divide the hardware
    tiles must give the same answer as an aligned problem (pad scenarios
    frozen, pad columns pinned at 0, pad rows dual-pinned at 0)."""
    p = _random_batch_lp(S=3, m=5, n=9, seed=1)
    xla = _run(p, pdhg.PDHGOptions(use_pallas=False))
    pal = _run(p, pdhg.PDHGOptions(use_pallas=True, pallas_tile_s=8))
    np.testing.assert_allclose(pal.x, xla.x, atol=1e-4, rtol=1e-4)


def test_three_pass_dot_accuracy():
    """The manual bf16x3 decomposition must be far more accurate than a
    single bf16 pass (it mirrors Precision.HIGH, which Mosaic rejects)."""
    rng = np.random.default_rng(3)
    v32 = rng.normal(size=(16, 64)).astype(np.float32)
    M32 = rng.normal(size=(64, 32)).astype(np.float32)
    # f64 numpy reference: jnp matmul is NOT a trustworthy reference
    # here (some backends run DEFAULT-precision f32 matmuls as bf16
    # passes — measured on both the axon CPU backend and v5e)
    exact = (v32.astype(np.float64) @ M32.astype(np.float64)).astype(
        np.float32)
    v, M = jnp.asarray(v32), jnp.asarray(M32)
    from mpisppy_tpu.ops.pdhg_pallas import _dot3, _split_bf16
    hi, lo = _split_bf16(M)
    got = jax.jit(lambda v, hi, lo: _dot3(_split_bf16(v), hi, lo))(
        v, hi, lo)
    one_pass = jax.jit(lambda a, b: jax.lax.dot_general(
        a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32))(v, M)
    err3 = float(jnp.max(jnp.abs(got - exact)))
    err1 = float(jnp.max(jnp.abs(one_pass - exact)))
    assert err3 < err1 / 50
    assert err3 < 5e-4
