# Per-scenario status handling: infeasible/unbounded certificates
# (the batched analog of ref:mpisppy/spopt.py:76-96,194-231).
import numpy as np
import pytest

import jax.numpy as jnp

from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import farmer
from mpisppy_tpu.ops import boxqp, pdhg


def test_infeasibility_certificate_direct():
    # x <= 0 and x >= 1, box [-10, 10]: infeasible; y = (1, -1) is a ray
    p = boxqp.make_boxqp(c=[0.0], A=[[1.0], [1.0]], bl=[-np.inf, 1.0],
                         bu=[0.0, np.inf], l=[-10.0], u=[10.0])
    y = jnp.asarray([1.0, -1.0])
    assert bool(boxqp.infeasibility_certificate(p, y))
    # feasible twin: x <= 2, x >= 1 — same ray is NOT a certificate
    p2 = boxqp.make_boxqp(c=[0.0], A=[[1.0], [1.0]], bl=[-np.inf, 1.0],
                          bu=[2.0, np.inf], l=[-10.0], u=[10.0])
    assert not bool(boxqp.infeasibility_certificate(p2, y))


def test_unboundedness_certificate_direct():
    # min -x, x >= 0 unbounded above; d = 1 certifies
    p = boxqp.make_boxqp(c=[-1.0], A=[[0.0]], bl=[-np.inf], bu=[1.0],
                         l=[0.0], u=[np.inf])
    assert bool(boxqp.unboundedness_certificate(p, jnp.asarray([1.0])))
    # bounded twin (u = 5): not a certificate
    p2 = boxqp.make_boxqp(c=[-1.0], A=[[0.0]], bl=[-np.inf], bu=[1.0],
                          l=[0.0], u=[5.0])
    assert not bool(boxqp.unboundedness_certificate(p2, jnp.asarray([1.0])))


def test_solver_detects_infeasible_in_batch():
    # batch of 3: [feasible, INFEASIBLE, feasible] — the infeasible one
    # is flagged without poisoning the others (VERDICT r1 item 8).
    A = np.array([[[1.0, 0.0], [0.0, 1.0]]] * 3)
    bl = np.array([[-np.inf, -np.inf],
                   [2.0, -np.inf],       # x0 >= 2 but u0 = 1: infeasible
                   [-np.inf, -np.inf]])
    bu = np.array([[1.0, 1.0], [np.inf, 1.0], [1.5, 1.0]])
    p = boxqp.make_boxqp(c=np.array([[1.0, 1.0]] * 3), A=A, bl=bl, bu=bu,
                         l=np.zeros((3, 2)), u=np.ones((3, 2)))
    opts = pdhg.PDHGOptions(tol=1e-6, max_iters=20_000, detect_infeas=True)
    st = pdhg.solve(p, opts)
    status = np.asarray(st.status)
    assert status[1] == pdhg.INFEASIBLE
    assert status[0] == pdhg.OPTIMAL and status[2] == pdhg.OPTIMAL
    # the feasible problems' solutions are untouched
    x = np.asarray(st.x)
    np.testing.assert_allclose(x[0], [0.0, 0.0], atol=1e-4)


def test_solver_detects_unbounded():
    p = boxqp.make_boxqp(c=[-1.0, 0.0], A=[[0.0, 1.0]], bl=[-np.inf],
                         bu=[1.0], l=[0.0, 0.0], u=[np.inf, 1.0])
    opts = pdhg.PDHGOptions(tol=1e-6, max_iters=20_000, detect_infeas=True)
    st = pdhg.solve(p, opts)
    assert int(st.status) == pdhg.UNBOUNDED


def test_xhat_infeasible_candidate_not_poisoning():
    # Farmer: acreage xhat exceeding total land is infeasible in every
    # scenario; a sane xhat is not.  The infeasible candidate reports
    # value=inf + feasible=False; per-scenario objectives stay finite
    # for the sane one.
    from mpisppy_tpu.algos import xhat as xhat_mod
    specs = [farmer.scenario_creator(nm, num_scens=3)
             for nm in farmer.scenario_names_creator(3)]
    b = batch_mod.from_specs(specs)
    bad = jnp.asarray([400.0, 400.0, 400.0])   # sum 1200 > 500 acres
    r = xhat_mod.evaluate(b, bad, pdhg.PDHGOptions(tol=1e-6))
    assert not bool(r.feasible)
    assert np.isinf(float(r.value))
    good = jnp.asarray([170.0, 80.0, 250.0])
    r2 = xhat_mod.evaluate(b, good, pdhg.PDHGOptions(tol=1e-6))
    assert bool(r2.feasible)
    assert np.isfinite(float(r2.value))
    assert float(r2.value) == pytest.approx(-108390.0, rel=2e-3)
