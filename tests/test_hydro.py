# Hydro: 3-stage multistage path — node-segmented reductions, EF with
# per-node nonant links, PH on the (3,3) tree (the TPU analog of
# ref:mpisppy/tests/test_ef_ph.py Test_hydro).
import numpy as np
import pytest

from mpisppy_tpu.algos import ef as ef_mod
from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import hydro
from mpisppy_tpu.ops import pdhg

from test_farmer_ef_ph import scipy_ef_solve


def hydro_specs(bfs=(3, 3)):
    num = bfs[0] * bfs[1]
    names = hydro.scenario_names_creator(num)
    return ([hydro.scenario_creator(nm, branching_factors=bfs)
             for nm in names], hydro.make_tree(bfs))


def test_tree_structure():
    specs, tree = hydro_specs()
    assert tree.num_nodes == 4          # ROOT + 3 stage-2 nodes
    assert tree.num_scenarios == 9
    node_of_slot = tree.node_of_slot()
    # stage-1 slots owned by ROOT for everyone
    assert (node_of_slot[:, :4] == 0).all()
    # scenarios 0-2 share stage-2 node 1, 3-5 node 2, 6-8 node 3
    assert (node_of_slot[0:3, 4:] == 1).all()
    assert (node_of_slot[3:6, 4:] == 2).all()
    assert (node_of_slot[6:9, 4:] == 3).all()
    assert tree.all_nodenames() == ["ROOT", "ROOT_0", "ROOT_1", "ROOT_2"]


def test_hydro_ef_matches_scipy():
    specs, tree = hydro_specs()
    sobj, sx = scipy_ef_solve_tree(specs, tree)
    efobj = ef_mod.ExtensiveForm({"tol": 1e-7, "max_iters": 300_000},
                                 hydro.scenario_names_creator(9),
                                 hydro.scenario_creator,
                                 {"branching_factors": (3, 3)}, tree=tree)
    st = efobj.solve_extensive_form()
    assert bool(st.done.all())
    assert efobj.get_objective_value() == pytest.approx(sobj, rel=2e-3)
    # reference known answer: Scen7 Pgt[2] == 60
    # (ref:mpisppy/tests/test_ef_ph.py:608-611)
    x = efobj.x  # (9, 13); Scen7 is index 6; Pgt[2] is column 1
    assert x[6, 1] == pytest.approx(60.0, abs=1.0)


def scipy_ef_solve_tree(specs, tree):
    from mpisppy_tpu.algos import ef as ef_mod_
    import numpy as np
    from scipy.optimize import linprog
    efp = ef_mod_.build_ef(specs, tree=tree, scale=False)
    qp = efp.qp
    c = np.asarray(qp.c, np.float64)
    A = np.asarray(qp.A, np.float64)
    bl, bu = np.asarray(qp.bl, np.float64), np.asarray(qp.bu, np.float64)
    l, u = np.asarray(qp.l, np.float64), np.asarray(qp.u, np.float64)
    A_ub, b_ub, A_eq, b_eq = [], [], [], []
    for i in range(A.shape[0]):
        if bl[i] == bu[i]:
            A_eq.append(A[i]); b_eq.append(bu[i])
        else:
            if np.isfinite(bu[i]):
                A_ub.append(A[i]); b_ub.append(bu[i])
            if np.isfinite(bl[i]):
                A_ub.append(-A[i]); b_ub.append(-bl[i])
    res = linprog(c, A_ub=np.array(A_ub) if A_ub else None,
                  b_ub=np.array(b_ub) if b_ub else None,
                  A_eq=np.array(A_eq) if A_eq else None,
                  b_eq=np.array(b_eq) if b_eq else None,
                  bounds=list(zip(l, u)), method="highs")
    assert res.status == 0
    return res.fun, res.x


def test_hydro_ph_three_stage():
    # 3-stage PH: node-segmented xbar (segment_sum path), convergence,
    # objective parity with the EF (VERDICT r1 item 9 "Done=" criterion).
    specs, tree = hydro_specs()
    sobj, _ = scipy_ef_solve_tree(specs, tree)
    b = batch_mod.from_specs(specs, tree=tree)
    opts = ph_mod.PHOptions(
        default_rho=1.0, max_iterations=400, conv_thresh=1e-3,
        subproblem_windows=10,
        pdhg=pdhg.PDHGOptions(tol=1e-7, restart_period=40),
    )
    algo = ph_mod.PH(opts, b)
    conv, eobj, tbound = algo.ph_main()
    assert tbound <= sobj + 1.0
    assert conv <= opts.conv_thresh
    assert eobj == pytest.approx(sobj, rel=2e-2)
    # nonanticipativity really holds per node: scenarios of the same
    # stage-2 node agree on stage-2 slots
    x_non = np.asarray(b.nonants(algo.state.solver.x))
    for grp in (slice(0, 3), slice(3, 6), slice(6, 9)):
        span = x_non[grp, 4:].max(axis=0) - x_non[grp, 4:].min(axis=0)
        assert span.max() < 2.0
    # ... but DIFFERENT stage-2 nodes genuinely differ (inflows 10/50/90)
    assert abs(x_non[0, 4:].mean() - x_non[6, 4:].mean()) > 1e-2


def test_hydro_larger_tree_builds():
    specs, tree = hydro_specs((4, 3))   # synthetic extra branch
    b = batch_mod.from_specs(specs, tree=tree)
    assert b.num_scenarios == 12
    assert tree.num_nodes == 5


def test_ef_xhat_inner_bound_multistage():
    """EFXhatInnerBound (root-fixed EF with intra-tree nonanticipativity)
    must publish a value that upper-bounds the EF optimum; fixing ALL
    stages' nonants at xbar is structurally infeasible on hydro (the
    stage-2 reservoir balance couples fixed nonants with stochastic
    inflow), which is exactly why this spoke exists."""
    from mpisppy_tpu.algos import fused_wheel as fw
    from mpisppy_tpu.cylinders import PHHub
    from mpisppy_tpu.cylinders.spoke import EFOuterBound, EFXhatInnerBound
    from mpisppy_tpu.spin_the_wheel import WheelSpinner

    specs, tree = hydro_specs((3, 3))
    batch = batch_mod.from_specs(specs, tree=tree)
    efp = ef_mod.build_ef(specs, tree=tree)
    # oracle: EF optimum via a tight direct solve
    st = pdhg.solve(efp.qp, pdhg.PDHGOptions(tol=1e-7, max_iters=60_000,
                                             dispatch_cap=0))
    x = np.asarray(st.x) * np.asarray(efp.scaling.d_col)
    S, n = len(efp.probs), efp.n_per_scen
    xs = x.reshape(S, n)
    opt = sum(float(efp.probs[s] * specs[s].c @ xs[s]) for s in range(S))

    opts = ph_mod.PHOptions(default_rho=2.0, max_iterations=60,
                            conv_thresh=0.0, subproblem_windows=8,
                            pdhg=pdhg.PDHGOptions(tol=1e-6))
    hub = {"hub_class": PHHub, "opt_class": fw.FusedPH,
           "opt_kwargs": {"options": opts, "batch": batch},
           "hub_kwargs": {"options": {"rel_gap": 1e-2}}}
    spokes = [
        {"spoke_class": EFOuterBound,
         "opt_kwargs": {"options": {"ef_problem": efp, "n_windows": 30}}},
        {"spoke_class": EFXhatInnerBound,
         "opt_kwargs": {"options": {"ef_problem": efp, "n_windows": 30}}},
    ]
    ws = WheelSpinner(hub, spokes).spin()
    inner, outer = ws.BestInnerBound, ws.BestOuterBound
    assert np.isfinite(inner) and np.isfinite(outer)
    # inner is a valid (first-order-compensated) upper bound on the
    # optimum, outer a valid lower bound
    slack = 5e-3 * max(1.0, abs(opt))
    assert inner >= opt - slack
    assert outer <= opt + slack
    # and the pair certifies a tight bracket around the oracle
    assert (inner - outer) / abs(inner) <= 1e-2 + 1e-6
