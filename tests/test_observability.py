# Derived observability (ISSUE 5; mpisppy_tpu/telemetry/{analyze,
# flightrec,regress}.py, tools/check_readme_claims.py): the trace
# analyzer's typed run model + report, the crash flight recorder's
# ring/dump semantics and overhead contract, the perf-regression gate
# over BENCH fixtures and analyzer reports, and the README perf-claim
# lint — all wired to the `python -m mpisppy_tpu.telemetry` CLI.
import json
import os
import subprocess
import sys
import time

import pytest

from mpisppy_tpu import telemetry
from mpisppy_tpu.telemetry import analyze as an
from mpisppy_tpu.telemetry import regress

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
GOLDEN = os.path.join(HERE, "fixtures", "golden_farmer_trace.jsonl")
CLI = [sys.executable, "-m", "mpisppy_tpu.telemetry"]
ENV = {"PATH": "/usr/bin:/bin:/usr/local/bin", "JAX_PLATFORMS": "cpu",
       "HOME": os.path.expanduser("~")}


def farmer_wheel(bus, max_iterations=8, hub_extra=None):
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.cylinders import (
        LagrangianOuterBound, PHHub, XhatXbarInnerBound,
    )
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.ops import pdhg
    from mpisppy_tpu.spin_the_wheel import WheelSpinner
    names = farmer.scenario_names_creator(3)
    specs = [farmer.scenario_creator(nm, num_scens=3) for nm in names]
    batch = batch_mod.from_specs(specs)
    opts = ph_mod.PHOptions(
        default_rho=1.0, max_iterations=max_iterations, conv_thresh=0.0,
        subproblem_windows=10, pdhg=pdhg.PDHGOptions(tol=1e-7))
    hub_opts = {"rel_gap": 5e-3, "telemetry_bus": bus}
    hub_opts.update(hub_extra or {})
    hub = {"hub_class": PHHub, "hub_kwargs": {"options": hub_opts},
           "opt_class": ph_mod.PH,
           "opt_kwargs": {"options": opts, "batch": batch}}
    spokes = [
        {"spoke_class": LagrangianOuterBound,
         "opt_kwargs": {"options": {}}},
        {"spoke_class": XhatXbarInnerBound, "opt_kwargs": {"options": {}}},
    ]
    return WheelSpinner(hub, spokes).spin()


# ---------------------------------------------------------------------------
# Analyzer: golden-trace round trip (committed fixture of a real
# farmer wheel with a NaN fault injection + checkpointing)
# ---------------------------------------------------------------------------
def test_analyze_golden_trace():
    rep = an.analyze_path(GOLDEN)
    assert rep["schema"] == an.ANALYZE_SCHEMA
    assert rep["run"]["hub_class"] == "PHHub"
    assert rep["run"]["num_spokes"] == 2
    # explicit exit verdict (ISSUE 5 satellite: run-end event)
    assert rep["run"]["exit"]["reason"] == "max-iter"
    assert rep["run"]["exit"]["rel_gap"] == pytest.approx(7.787e-3,
                                                          rel=1e-3)
    # per-phase wall-time breakdown from the span events
    phases = rep["phases"]
    assert {"harvest", "hub_sync", "spoke_update", "checkpoint",
            "subproblem_solve", "iter0_solve"} <= set(phases)
    assert phases["subproblem_solve"]["calls"] == 10
    assert all(a["total_s"] >= 0 for a in phases.values())
    assert abs(sum(a["share"] for a in phases.values()) - 1.0) < 1e-6
    # iteration timing
    it = rep["iteration"]
    assert it["count"] == 11
    assert it["sec_per_iter_median"] > 0
    # bound progress + stall diagnostics
    b = rep["bounds"]
    assert b["final_outer"] == pytest.approx(-108931.95, rel=1e-4)
    assert b["final_inner"] == pytest.approx(-108090.27, rel=1e-4)
    assert b["time_to_gap"]["0.01"]["iter"] == 10
    assert b["iters_since_outer_moved"] == 4
    # per-spoke attribution: who produced the binding bounds
    at = rep["attribution"]
    assert at["final_bound_producer"]["outer"]["spoke"] == 0
    assert at["final_bound_producer"]["outer"]["class"] \
        == "LagrangianOuterBound"
    assert at["final_bound_producer"]["inner"]["spoke"] == 1
    s0 = at["spokes"]["0"]
    assert s0["harvests"] == 11 and s0["rejects"] == 1 \
        and s0["strikes"] == 1
    # the injected NaN shows up as cause (fault) AND response (strike)
    res = rep["resilience"]
    assert res["faults_injected"] == {"spoke_bound": 1}
    assert res["spoke_strikes"] == 1 and res["checkpoint_writes"] >= 1
    # kernel counters folded per cylinder
    assert rep["kernel"]["hub"]["pdhg_iterations_total"] > 0
    # the human rendering carries the load-bearing lines
    text = an.render_report(rep)
    assert "binding outer: spoke 0 (LagrangianOuterBound)" in text
    assert "exit: max-iter" in text
    json.dumps(rep)  # machine report is strict-JSON-able


def test_analyze_handles_torn_tail_and_run_selection(tmp_path):
    rows = open(GOLDEN).read().splitlines()
    torn = tmp_path / "torn.jsonl"
    torn.write_text("\n".join(rows) + "\n" + rows[-1][: len(rows[-1]) // 2])
    rep = an.analyze_path(str(torn))
    assert rep["run"]["events"] == len(rows)
    # unknown run id is a clear error, not a silent empty report
    with pytest.raises(ValueError):
        an.analyze_path(GOLDEN, run="nonexistent")


def test_analyze_cli_json(tmp_path):
    out = subprocess.run(CLI + ["analyze", "--trace-jsonl", GOLDEN,
                                "--json"],
                         capture_output=True, text=True, cwd=REPO,
                         timeout=120, env=ENV)
    assert out.returncode == 0, out.stderr[-2000:]
    rep = json.loads(out.stdout)
    assert rep["schema"] == an.ANALYZE_SCHEMA
    assert rep["run"]["exit"]["reason"] == "max-iter"
    # human mode renders the report (not JSON)
    out2 = subprocess.run(CLI + ["analyze", "--trace-jsonl", GOLDEN],
                          capture_output=True, text=True, cwd=REPO,
                          timeout=120, env=ENV)
    assert out2.returncode == 0 and "phases (host wall):" in out2.stdout


# ---------------------------------------------------------------------------
# Analyzer on a live tier-1 wheel run (the acceptance criterion)
# ---------------------------------------------------------------------------
def test_analyze_live_wheel_trace(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    bus = telemetry.EventBus()
    bus.subscribe(telemetry.JsonlSink(path))
    farmer_wheel(bus, max_iterations=6)
    bus.close()
    rep = an.analyze_path(path)
    assert rep["run"]["exit"]["reason"] in ("converged", "max-iter",
                                            "conv-thresh", "stalled")
    assert {"harvest", "subproblem_solve"} <= set(rep["phases"])
    assert rep["iteration"]["sec_per_iter_median"] > 0
    producers = rep["attribution"]["final_bound_producer"]
    assert {"outer", "inner"} <= set(producers)
    assert rep["flags"] == [] or all(isinstance(f, str)
                                     for f in rep["flags"])


# ---------------------------------------------------------------------------
# Flight recorder: ring semantics, dump format, overhead contract
# ---------------------------------------------------------------------------
def test_flight_recorder_ring_wraps_and_dumps(tmp_path):
    bus = telemetry.EventBus()
    rec = telemetry.FlightRecorder(capacity=8, dump_dir=str(tmp_path))
    bus.subscribe(rec)
    run = telemetry.new_run_id()
    for i in range(20):
        bus.emit(telemetry.HUB_ITERATION, run=run, cyl="hub",
                 hub_iter=i, iter=i)
    assert rec.dropped == 12
    evs = rec.events()
    assert len(evs) == 8
    assert [e.hub_iter for e in evs] == list(range(12, 20))  # oldest first
    path = rec.dump(reason="unit test")
    assert path == str(tmp_path / f"flight-{run}.jsonl")
    rows = [json.loads(line) for line in open(path)]
    hdr = rows[0]
    assert hdr["kind"] == "flight-recorder" and hdr["reason"] == "unit test"
    assert hdr["dumped_events"] == 8 and hdr["dropped"] == 12
    assert [r["iter"] for r in rows[1:]] == list(range(12, 20))
    # a dump is an analyzer input; without run-end it reads as truncated
    rep = an.analyze(an.build_run_model(rows))
    assert rep["run"]["exit"]["reason"] == "truncated"
    assert rep["run"]["exit"]["flight_reason"] == "unit test"
    assert any("truncated" in f for f in rep["flags"])


def test_flight_recorder_zero_graph_impact_and_throughput(tmp_path):
    """Overhead contract: the ring sink is host-side bookkeeping only —
    the lowered wheel step is byte-identical with a recorder-bearing
    bus attached (the kernel-counters HLO test's contract extended to
    the black box), and bus throughput with a recorder stays in the
    microseconds-per-event regime."""
    import jax.numpy as jnp
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.ops import pdhg
    names = farmer.scenario_names_creator(3)
    specs = [farmer.scenario_creator(nm, num_scens=3) for nm in names]
    batch = batch_mod.from_specs(specs)
    opts = ph_mod.kernel_opts(ph_mod.PHOptions(
        default_rho=1.0, conv_thresh=0.0, subproblem_windows=10,
        pdhg=pdhg.PDHGOptions(tol=1e-7)))
    rho = jnp.ones((batch.num_nonants,), batch.qp.c.dtype)
    st, _, _ = ph_mod.ph_iter0(batch, rho, opts)
    text_base = ph_mod.ph_iterk.lower(batch, st, opts).as_text()

    bus = telemetry.EventBus()
    bus.subscribe(telemetry.FlightRecorder(dump_dir=str(tmp_path)))
    ws = farmer_wheel(bus, max_iterations=3)
    text_wired = ph_mod.ph_iterk.lower(
        batch, ws.opt.state, ph_mod.kernel_opts(ws.opt.options)).as_text()
    assert text_wired == text_base

    # throughput: the ring is a preallocated slot store — no growth,
    # no per-event allocation of anything but the Event the bus built
    rec = telemetry.FlightRecorder(capacity=512)
    bus2 = telemetry.EventBus()
    bus2.subscribe(rec)
    n = 20_000
    t0 = time.perf_counter()
    for i in range(n):
        bus2.emit(telemetry.HUB_ITERATION, run="r", cyl="hub", hub_iter=i)
    per_event = (time.perf_counter() - t0) / n
    assert per_event < 250e-6, f"{per_event * 1e6:.1f} us/event"
    assert len(rec._ring) == 512 and rec.dropped == n - 512


def test_generic_cylinders_crash_leaves_black_box(tmp_path, monkeypatch):
    """A wheel dying under the CLI driver with tracing OFF still leaves
    flight-<runid>.jsonl (the always-on registration in
    generic_cylinders + the dump in WheelSpinner.spin's unwind)."""
    from mpisppy_tpu import generic_cylinders
    from mpisppy_tpu.cylinders import hub as hub_mod

    calls = {"n": 0}
    orig = hub_mod.PHHub._harvest_kernel_counters

    def boom(self):
        calls["n"] += 1
        if calls["n"] >= 3:
            raise RuntimeError("synthetic mid-wheel crash")
        return orig(self)

    monkeypatch.setattr(hub_mod.PHHub, "_harvest_kernel_counters", boom)
    args = ["--module-name", "mpisppy_tpu.models.farmer",
            "--num-scens", "3", "--max-iterations", "6",
            "--rel-gap", "0.005", "--lagrangian", "--xhatxbar",
            "--flight-dir", str(tmp_path)]
    with pytest.raises(RuntimeError, match="synthetic mid-wheel crash"):
        generic_cylinders.main(args)
    dumps = [f for f in os.listdir(tmp_path)
             if f.startswith("flight-") and f.endswith(".jsonl")]
    assert len(dumps) == 1, dumps
    rep = an.analyze_path(str(tmp_path / dumps[0]))
    assert rep["run"]["exit"]["reason"] == "exception"
    assert "synthetic mid-wheel crash" in rep["run"]["exit"]["error"]


# ---------------------------------------------------------------------------
# Regression gate: BENCH fixtures + analyzer reports
# ---------------------------------------------------------------------------
def test_gate_passes_r05_vs_r04_and_fails_on_regression(tmp_path):
    r04 = os.path.join(REPO, "BENCH_r04.json")
    r05 = os.path.join(REPO, "BENCH_r05.json")
    rep = regress.gate_paths(r04, r05)
    assert rep["ok"], rep["regressions"]
    assert rep["common"] > 10
    # the salvage recovered gateable keys from the truncated tails
    gated = {r["metric"] for r in rep["rows"] if r["gated"]}
    assert any("sec_per_iter" in k for k in gated)
    assert any("iters_per_sec" in k for k in gated)

    # synthetically regress sec_per_iter by 33% -> gate must fail
    bad = json.load(open(r05))
    bad["tail"] = bad["tail"].replace('"sec_per_iter": 0.0601',
                                     '"sec_per_iter": 0.0801')
    bad_path = tmp_path / "BENCH_regressed.json"
    bad_path.write_text(json.dumps(bad))
    rep2 = regress.gate_paths(r04, str(bad_path))
    assert not rep2["ok"]
    assert any("sec_per_iter" in r["metric"] for r in rep2["regressions"])
    # the CLI maps the verdicts to exit codes (0 pass / 2 regression)
    from mpisppy_tpu.telemetry.__main__ import main as tel_main
    assert tel_main(["gate", r04, r05]) == 0
    assert tel_main(["gate", r04, str(bad_path)]) == 2
    # direction matters: a 33% FASTER sec_per_iter is not a regression
    good = json.load(open(r05))
    good["tail"] = good["tail"].replace('"sec_per_iter": 0.0601',
                                       '"sec_per_iter": 0.0401')
    good_path = tmp_path / "BENCH_improved.json"
    good_path.write_text(json.dumps(good))
    assert regress.gate_paths(r04, str(good_path))["ok"]


def test_gate_r06_fixture_and_milestones(tmp_path):
    """ISSUE 8 gate-fixture refresh: the committed r05->r06 pair must
    gate green; the new absolute MILESTONE thresholds (S=10k
    sec_per_iter <= 0.045, S=100k iters_per_sec >= 2) follow ratchet
    semantics — pending on pre-win artifacts, strict-bindable via
    --milestones, and permanently binding once an artifact has landed
    the win; the committed overlap_frac keys fail the gate on a
    synthetic drop."""
    r05 = os.path.join(REPO, "BENCH_r05.json")
    r06 = os.path.join(REPO, "BENCH_r06.json")
    rep = regress.gate_paths(r05, r06)
    assert rep["ok"], rep["regressions"]
    # both milestone keys are present and reported pending (r06 carries
    # the pre-win on-TPU measurements: 0.0601 s/iter, 1.46 iters/s)
    ms = {r["metric"]: r for r in rep["milestones"]}
    assert ms["measured_mfu.S10000.sec_per_iter"]["status"] == "pending"
    assert ms["sweep_iters_per_sec.S100000.iters_per_sec"]["status"] \
        == "pending"
    assert not any(r["regressed"] for r in rep["milestones"])

    # strict mode: the same pair FAILS until the wins land on hardware
    from mpisppy_tpu.telemetry.__main__ import main as tel_main
    assert tel_main(["gate", r05, r06]) == 0
    assert tel_main(["gate", r05, r06, "--milestones"]) == 2

    # a post-win artifact meets the floors in strict mode... (strict
    # requires EVERY milestone phase present, so the synthetic post-win
    # artifact also carries the ISSUE-11 async-overhead phase, the
    # ISSUE-12 serve isolation phase, the ISSUE-14 scengen phase, the
    # ISSUE-16 fleet migration phase, the ISSUE-17 mesh reshard phase,
    # and the ISSUE-19 mpc stream phase)
    won = json.load(open(r06))
    won["parsed"]["measured_mfu"]["S10000"]["sec_per_iter"] = 0.044
    won["parsed"]["sweep_iters_per_sec"][2]["iters_per_sec"] = 2.2
    won["parsed"]["wheel_overhead_async"] = {"overhead_factor": 1.25}
    won["parsed"]["serve_load"] = {
        "isolation": {"isolation_ratio": 1.0}}
    won["parsed"]["fleet_serve_load"] = {
        "isolation": {"isolation_ratio": 1.0},
        "migration": {"migrated_reached_gap_frac": 1.0}}
    won["parsed"]["wheel_scengen"] = {
        "synth_vs_materialized_ratio": 0.97,
        "sweep": [{"scenarios": 1_000_000, "iters_per_sec": 0.07}]}
    won["parsed"]["mesh_chaos"] = {
        "reshard": {"reshard_reached_gap_frac": 1.0}}
    won["parsed"]["mpc_stream"] = {
        "warm_over_cold_ratio": 0.5,
        "chaos": {"resumed_matched_frac": 1.0}}
    won_path = tmp_path / "BENCH_won.json"
    won_path.write_text(json.dumps(won))
    rep2 = regress.gate_paths(r06, str(won_path), milestones=True)
    assert rep2["ok"], rep2["regressions"]
    assert all(r["status"] == "met" for r in rep2["milestones"])

    # ...and then RATCHETS: a later artifact slipping past the floor
    # fails WITHOUT --milestones even when the relative move is inside
    # the +-10% band (0.044 -> 0.0462 is +5%; 2.2 -> 1.98 is -10%)
    slip = json.load(open(r06))
    slip["parsed"]["measured_mfu"]["S10000"]["sec_per_iter"] = 0.0462
    slip["parsed"]["sweep_iters_per_sec"][2]["iters_per_sec"] = 1.98
    slip_path = tmp_path / "BENCH_slipped.json"
    slip_path.write_text(json.dumps(slip))
    rep3 = regress.gate_paths(str(won_path), str(slip_path))
    assert not rep3["ok"]
    failed = {r["metric"] for r in rep3["regressions"]}
    assert "measured_mfu.S10000.sec_per_iter" in failed
    assert "sweep_iters_per_sec.S100000.iters_per_sec" in failed

    # a LANDED milestone key that disappears from the next artifact is
    # a failure, not a silently-un-bound gate (dropping the bench phase
    # must not become the regression escape hatch)
    gone = json.load(open(r06))
    del gone["parsed"]["measured_mfu"]
    gone_path = tmp_path / "BENCH_phase_dropped.json"
    gone_path.write_text(json.dumps(gone))
    rep_gone = regress.gate_paths(str(won_path), str(gone_path))
    assert not rep_gone["ok"]
    assert any(r.get("status") == "MISSING"
               and "measured_mfu" in r["metric"]
               for r in rep_gone["regressions"])
    # strict mode fails the absent key even when it never landed
    rep_gone2 = regress.gate_paths(r06, str(gone_path), milestones=True)
    assert any(r.get("status") == "MISSING" for r in rep_gone2["milestones"])
    assert not rep_gone2["ok"]
    # ...but ratchet mode lets a never-landed phase disappear quietly
    rep_gone3 = regress.gate_paths(r06, str(gone_path))
    assert not any(r.get("status") == "MISSING"
                   for r in rep_gone3["milestones"])

    # overlap_frac keys gate direction-aware on the committed fixture:
    # a 35% drop in DMA/compute overlap at S=100k is a regression
    drop = json.load(open(r06))
    drop["parsed"]["device_profile"]["S100000"]["overlap_frac"] = 0.64
    drop_path = tmp_path / "BENCH_overlap_drop.json"
    drop_path.write_text(json.dumps(drop))
    rep4 = regress.gate_paths(r06, str(drop_path))
    assert not rep4["ok"]
    assert any("overlap_frac" in r["metric"] for r in rep4["regressions"])
    # while a RISING overlap (the double-buffer win direction) passes
    rise = json.load(open(r06))
    rise["parsed"]["device_profile"]["S100000"]["overlap_frac"] = 0.999
    rise_path = tmp_path / "BENCH_overlap_rise.json"
    rise_path.write_text(json.dumps(rise))
    assert regress.gate_paths(r06, str(rise_path))["ok"]


def test_gate_r08_serve_load_keys_and_isolation_milestone(tmp_path):
    """ISSUE 12 gate fixture: the committed r07->r08 pair gates green;
    the serve_load latency keys gate direction-aware; and the
    tenant-isolation ratio carries a <= 1.25 ratchet MILESTONE that
    the committed (meeting) artifact binds."""
    r07 = os.path.join(REPO, "BENCH_r07.json")
    r08 = os.path.join(REPO, "BENCH_r08.json")
    rep = regress.gate_paths(r07, r08)
    assert rep["ok"], rep["regressions"]
    ms = {r["metric"]: r for r in rep["milestones"]}
    iso = ms["serve_load.isolation.isolation_ratio"]
    assert iso["status"] == "met" and iso["milestone"] == 1.25

    # the committed artifact meets the bound, so the ratchet BINDS: a
    # later artifact slipping past 1.25 fails even within +-25%
    slip = json.load(open(r08))
    slip["parsed"]["serve_load"]["isolation"]["isolation_ratio"] = 1.3
    slip_path = tmp_path / "BENCH_iso_slip.json"
    slip_path.write_text(json.dumps(slip))
    rep2 = regress.gate_paths(r08, str(slip_path))
    assert not rep2["ok"]
    assert any(r["metric"] == "serve_load.isolation.isolation_ratio"
               for r in rep2["regressions"])

    # client-observed latency keys gate at +-25%
    slow = json.load(open(r08))
    slow["parsed"]["serve_load"]["time_to_gap_p99_s"] *= 1.5
    slow_path = tmp_path / "BENCH_p99_slow.json"
    slow_path.write_text(json.dumps(slow))
    rep3 = regress.gate_paths(r08, str(slow_path))
    assert not rep3["ok"]
    assert any("time_to_gap_p99_s" in r["metric"]
               for r in rep3["regressions"])
    # ...and a FASTER p99 passes (direction-aware)
    fast = json.load(open(r08))
    fast["parsed"]["serve_load"]["time_to_gap_p99_s"] *= 0.6
    fast_path = tmp_path / "BENCH_p99_fast.json"
    fast_path.write_text(json.dumps(fast))
    assert regress.gate_paths(r08, str(fast_path))["ok"]


def test_gate_analyzer_reports_and_thresholds(tmp_path):
    rep = an.analyze_path(GOLDEN)
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(rep))
    # identical reports: everything common, nothing regressed
    out = regress.gate_paths(str(a), str(a))
    assert out["ok"] and out["common"] > 3

    worse = json.loads(json.dumps(rep))
    worse["iteration"]["sec_per_iter_median"] *= 2.0
    worse["iteration"]["sec_per_iter_p90"] *= 2.0
    b.write_text(json.dumps(worse))
    out2 = regress.gate_paths(str(a), str(b))
    assert not out2["ok"]
    assert any("sec_per_iter_median" in r["metric"]
               for r in out2["regressions"])
    # per-call threshold override loosens the verdict
    out3 = regress.gate_paths(str(a), str(b),
                              overrides={"sec_per_iter": 3.0})
    assert out3["ok"]
    # metric extraction keyed the gateable fields
    m = regress.extract_metrics(rep)
    assert "iteration.sec_per_iter_median" in m
    assert "time_to_gap.0.01" in m
    assert "kernel.hub.guard_resets" in m


def test_gate_refuses_vacuous_diff():
    out = regress.gate({"x": {"a": 1.0}}, {"y": {"b": 2.0}})
    assert not out["ok"] and "no common metrics" in out["error"]


def test_bench_tail_salvage_recovers_sections():
    art = regress.load_artifact(os.path.join(REPO, "BENCH_r04.json"))
    # the r04 tail is front-truncated; the complete trailing sections
    # must still be recovered with their nested fields intact
    assert art["hydro_to_1pct_gap"]["seconds_to_gap"] == \
        pytest.approx(176.072)
    assert art["measured_mfu"]["S10000"]["sec_per_iter"] == \
        pytest.approx(0.0597)
    assert isinstance(art["sweep_iters_per_sec"], list)
    # nested sections are not duplicated at top level
    assert "S10000" not in art


# ---------------------------------------------------------------------------
# Dispatch events join the iteration timeline exactly (ISSUE 5
# satellite: hub_iter stamps)
# ---------------------------------------------------------------------------
def test_dispatch_events_carry_hub_iter_stamp():
    from mpisppy_tpu import dispatch
    from mpisppy_tpu.dispatch import DispatchOptions, SolveScheduler

    events = []

    class Grab(telemetry.Sink):
        def handle(self, event):
            events.append(event)

    bus = telemetry.EventBus()
    bus.subscribe(Grab())

    def fake_solve(qp, d_col, int_cols, opts, **kw):
        return qp.c  # any array with a leading batch axis

    import jax.numpy as jnp
    import dataclasses as dc
    from mpisppy_tpu.ops.boxqp import BoxQP
    S, n, m = 3, 4, 2
    qp = BoxQP(c=jnp.zeros((S, n)), q=jnp.ones((S, n)),
               A=jnp.zeros((m, n)), bl=jnp.zeros((S, m)),
               bu=jnp.ones((S, m)), l=jnp.zeros((S, n)),
               u=jnp.ones((S, n)))
    sched = SolveScheduler(DispatchOptions(max_wait_ms=0.1),
                           solve_fn=fake_solve, bus=bus, run="testrun")
    try:
        dispatch.set_hub_iter(-1)   # pre-wheel
        sched.solve_mip(qp, jnp.ones((n,)), jnp.array([], jnp.int32))
        dispatch.set_hub_iter(7)    # mid-wheel
        sched.solve_mip(qp, jnp.ones((n,)), jnp.array([], jnp.int32))
    finally:
        sched.close()
        dispatch.set_hub_iter(-1)
    disp = [e for e in events if e.kind == telemetry.DISPATCH]
    assert [e.hub_iter for e in disp] == [-1, 7]
    # and the stamp survives serialization for the analyzer's join
    rows = [json.loads(e.to_json()) for e in disp]
    assert rows[0]["iter"] == -1 and rows[1]["iter"] == 7


# ---------------------------------------------------------------------------
# README perf-claim lint (tier-1, next to lint_no_print)
# ---------------------------------------------------------------------------
def _claims_tool():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import check_readme_claims
    finally:
        sys.path.pop(0)
    return check_readme_claims


def test_readme_claims_trace_to_artifacts():
    tool = _claims_tool()
    assert tool.find_violations() == []


def test_readme_claims_lint_catches_drift(tmp_path):
    tool = _claims_tool()
    fake = tmp_path / "README.md"
    fake.write_text(
        "intro prose\n\n"
        "Measured on one TPU v5 lite chip:\n\n"
        "- reaches the gap in 999 s (12 iterations, bf16x6) at ~3.1x "
        "speedup\n"
        "- config noise: 900 scenarios, 3-stage tree\n\n"
        "Out of scope: nothing.\n")
    pool = {12.0, 3.05}
    vio = tool.find_violations(readme=str(fake), pool=pool)
    # 999 s has no witness; 12 iterations does; ~3.1x matches 3.05
    # within the approximation slack; config numbers are not claims
    assert len(vio) == 1 and "'999s'" in vio[0]
    assert tool.find_violations(readme=str(fake),
                                pool=pool | {998.9}) == []
    # ISSUE 8: a throughput bullet WITHOUT a precision-mode token is a
    # violation even when every number is witnessed — wrapped bullet
    # lines share the first line's disclosure
    fake.write_text(
        "Measured on one TPU v5 lite chip:\n\n"
        "- reaches the gap in 999 s\n"
        "- wrapped bullet at bf16x3 reaches\n"
        "  the gap in 999 s too\n\n"
        "Out of scope: nothing.\n")
    vio2 = tool.find_violations(readme=str(fake), pool={999.0})
    assert len(vio2) == 1 and "precision" in vio2[0]
    # trailing section prose must NOT donate its token to the last
    # bullet (a paragraph is not a bullet continuation)
    fake.write_text(
        "Measured on one TPU v5 lite chip:\n\n"
        "- reaches the gap in 999 s\n\n"
        "See docs/precision.md for the bf16x6 contract.\n\n"
        "Out of scope: nothing.\n")
    vio3 = tool.find_violations(readme=str(fake), pool={999.0})
    assert len(vio3) == 1 and "precision" in vio3[0]


# ---------------------------------------------------------------------------
# Dispatch fault domain observability (ISSUE 9): analyzer resilience
# rows + dispatch-cause audit, regress-gate rows, and the `telemetry
# watch` torn-tail contract.
# ---------------------------------------------------------------------------
def _fault_domain_trace(path: str) -> None:
    """Synthesize a serving-shaped trace through the real bus+sink so
    the row schema can never drift from the emitters'."""
    bus = telemetry.EventBus()
    bus.subscribe(telemetry.JsonlSink(path))
    run = "fdrun"
    bus.emit(telemetry.RUN_START, run=run, cyl="hub",
             hub_class="PHHub", num_spokes=2)
    for it in (1, 2, 3):
        bus.emit(telemetry.HUB_ITERATION, run=run, cyl="hub",
                 hub_iter=it, iter=it, outer=-110.0 - it, inner=-100.0,
                 abs_gap=10.0, rel_gap=0.1)
    bus.emit(telemetry.DISPATCH, run=run, cyl="dispatch", hub_iter=1,
             requests=2, lanes=6, padded_to=8, occupancy=0.75,
             bucket=[8, 4, 4], wait_ms=1.0, queue_depth=0,
             cause="timer", inflight_max=1)
    bus.emit(telemetry.DISPATCH, run=run, cyl="dispatch", hub_iter=2,
             requests=1, lanes=8, padded_to=8, occupancy=1.0,
             bucket=[8, 4, 4], wait_ms=0.1, queue_depth=0,
             cause="size", inflight_max=1)
    bus.emit(telemetry.DISPATCH_RETRY, run=run, cyl="dispatch",
             hub_iter=2, attempt=1, requests=2, lanes=6,
             backoff_s=0.05, error="RuntimeError: injected")
    bus.emit(telemetry.DISPATCH_QUARANTINE, run=run, cyl="dispatch",
             hub_iter=2, submit=3, lanes=3, attempts=4,
             reason="exception", bisected=True,
             error="DispatchPoison: injected")
    bus.emit(telemetry.WATCHDOG, run=run, cyl="watchdog",
             component="hub", action="degrade", stalled_s=12.5,
             budget_s=10.0, trips=1)
    bus.emit(telemetry.WATCHDOG, run=run, cyl="dispatch",
             component="dispatcher", action="fail-fast",
             failed_tickets=2, error="RuntimeError: killed")
    bus.emit(telemetry.DISPATCH, run=run, cyl="hub", hub_iter=3,
             batches=3, buckets=2, backend_compiles=2,
             unexpected_recompiles=0, inflight_max=1,
             retries_total=1, quarantined_lanes=3, degraded=True)
    bus.emit(telemetry.RUN_END, run=run, cyl="hub", hub_iter=3,
             reason="max-iter", outer=-113.0, inner=-100.0,
             abs_gap=13.0, rel_gap=0.13, iterations=3)
    bus.close()


def test_analyzer_reports_dispatch_fault_domain(tmp_path):
    path = str(tmp_path / "fd.jsonl")
    _fault_domain_trace(path)
    rep = an.analyze_path(path)
    res = rep["resilience"]
    assert res["dispatch_retries"] == 1
    assert res["dispatch_quarantined_lanes"] == 3
    assert res["dispatch_quarantined_requests"] == 1
    assert res["watchdog_trips"] == 1          # degrade counts, fail-
    assert res["dispatcher_deaths"] == 1       # fast is its own row
    d = rep["dispatch"]
    # the cause split attributes occupancy loss to admission timeouts
    assert d["by_cause"]["timer"]["batches"] == 1
    assert d["by_cause"]["timer"]["occupancy"] == 0.75
    assert d["by_cause"]["size"]["occupancy"] == 1.0
    assert d["retries_total"] == 1 and d["quarantined_lanes"] == 3
    flags = "\n".join(rep["flags"])
    assert "quarantined" in flags and "watchdog" in flags \
        and "dispatcher-thread death" in flags
    text = an.render_report(rep)
    assert "dispatch fault domain: retries 1" in text
    json.dumps(rep)


def test_gate_fails_on_quarantine_or_retry_increase(tmp_path):
    """ISSUE 9 regress rows: on bench-style artifacts ANY increase in
    dispatch retries or quarantined lanes is a regression."""
    old = {"phase": {"seconds_to_gap": 100.0,
                     "dispatch": {"batches": 5, "retries_total": 0,
                                  "quarantined_lanes": 0}}}
    good = {"phase": {"seconds_to_gap": 101.0,
                      "dispatch": {"batches": 5, "retries_total": 0,
                                   "quarantined_lanes": 0}}}
    bad_q = {"phase": {"seconds_to_gap": 101.0,
                       "dispatch": {"batches": 5, "retries_total": 0,
                                    "quarantined_lanes": 3}}}
    bad_r = {"phase": {"seconds_to_gap": 101.0,
                       "dispatch": {"batches": 5, "retries_total": 2,
                                    "quarantined_lanes": 0}}}
    assert regress.gate(old, good)["ok"]
    repq = regress.gate(old, bad_q)
    assert not repq["ok"]
    assert any("quarantined_lanes" in r["metric"]
               for r in repq["regressions"])
    repr_ = regress.gate(old, bad_r)
    assert not repr_["ok"]
    assert any("retries_total" in r["metric"]
               for r in repr_["regressions"])


def test_gate_analyzer_resilience_rows(tmp_path):
    """Analyzer reports carry the fault-domain counters into the gate:
    a run that started quarantining lanes fails against a clean one."""
    clean = str(tmp_path / "clean.jsonl")
    bus = telemetry.EventBus()
    bus.subscribe(telemetry.JsonlSink(clean))
    farmer_wheel(bus, max_iterations=4)
    bus.close()
    rep_old = an.analyze_path(clean)
    faulty = str(tmp_path / "faulty.jsonl")
    _fault_domain_trace(faulty)
    rep_new = an.analyze_path(faulty)
    verdict = regress.gate(rep_old, rep_new)
    assert not verdict["ok"]
    failing = {r["metric"] for r in verdict["regressions"]}
    assert "resilience.dispatch_quarantined_lanes" in failing
    assert "resilience.dispatch_retries" in failing
    assert "resilience.watchdog_trips" in failing


def test_watch_survives_torn_and_concurrently_appended_trace(tmp_path):
    """Satellite: `telemetry watch` tails a trace a live wheel is
    appending to — a torn final line (no newline / half a JSON object)
    must not crash the tailer, must not be double-counted, and must be
    picked up once completed."""
    from mpisppy_tpu.telemetry import watch as w

    path = str(tmp_path / "t.jsonl")
    _fault_domain_trace(path)
    rows = open(path).read().splitlines()
    # rewrite with the final line torn mid-object, no newline
    keep, last = rows[:-1], rows[-1]
    with open(path, "w") as f:
        f.write("\n".join(keep) + "\n" + last[: len(last) // 2])
    state = w.WatchState()
    pos = w._follow(path, state, 0)
    assert state.events == len(keep)          # torn line NOT consumed
    assert state.end is None
    # the writer finishes the line (plus one more event) — the tailer
    # resumes from its offset and sees both exactly once
    with open(path, "a") as f:
        f.write(last[len(last) // 2:] + "\n")
    pos = w._follow(path, state, pos)
    assert state.events == len(rows)
    assert state.end is not None              # run-end landed
    assert state.dispatch_retries == 1
    assert state.dispatch_quarantined == 3
    assert state.watchdog_trips == 1   # fail-fast is not a trip
    # a torn line that never completes (writer died) parses as garbage
    # once newline-terminated and is skipped, not fatal
    with open(path, "a") as f:
        f.write('{"kind": "hub-iter')
    pos2 = w._follow(path, state, pos)
    assert pos2 == pos and state.events == len(rows)
    with open(path, "a") as f:
        f.write("\n")
    pos3 = w._follow(path, state, pos2)
    assert pos3 > pos2 and state.events == len(rows)   # skipped
    # the CLI smoke mode renders the resilience line from this state
    rendered = w.render_status(state)
    assert "retries 1" in rendered and "quarantined lanes 3" in rendered


def test_watch_once_cli_on_fault_domain_trace(tmp_path):
    path = str(tmp_path / "fd.jsonl")
    _fault_domain_trace(path)
    out = subprocess.run(CLI + ["watch", "--trace-jsonl", path,
                                "--once"],
                         capture_output=True, text=True, cwd=REPO,
                         timeout=120, env=ENV)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "RUN ENDED: max-iter" in out.stdout
    assert "quarantined lanes 3" in out.stdout


# ---------------------------------------------------------------------------
# fleet layouts in `watch --trace-dir` (ISSUE 16 satellite): a migrated
# session's trace is split across two replicas' subdirectories, with
# the destination tail torn mid-migration
# ---------------------------------------------------------------------------
def _jl(path, rows, torn_last=False):
    with open(path, "w") as f:
        for i, row in enumerate(rows):
            line = json.dumps(row)
            if torn_last and i == len(rows) - 1:
                f.write(line[: len(line) // 2])   # torn, no newline
            else:
                f.write(line + "\n")


def test_watch_merges_migrated_session_across_replicas(tmp_path):
    """A fleet trace dir: session s01 started on r0, migrated to r1
    (the r1 segment's terminal line is TORN mid-write), s02 lives only
    on r1.  The watcher must join the s01 segments on (run, sid) into
    ONE row — never double-counting the session — render the replica
    chain `r0>r1`, and pick up the torn terminal once completed."""
    from mpisppy_tpu.telemetry import watch as w

    run = "run-fleet-1"
    td = tmp_path / "traces"
    (td / "r0").mkdir(parents=True)
    (td / "r1").mkdir()
    # aggregate router stream: must be SKIPPED by the dir walker
    _jl(td / "fleet.jsonl",
        [{"kind": "fleet-placement", "run": run, "t_wall": 99.0,
          "data": {"session": "s01", "replica": "r0"}}])
    _jl(td / "r0" / "session-s01.jsonl", [
        {"kind": "session-state", "run": run, "t_wall": 100.0,
         "data": {"session": "s01", "tenant": "acme", "sla": "latency",
                  "state": "RUNNING", "replica": "r0"}},
        {"kind": "hub-iteration", "run": run, "t_wall": 100.5,
         "t_mono": 1.0, "data": {"iter": 3, "rel_gap": 0.5}},
        {"kind": "session-migrated", "run": run, "t_wall": 101.0,
         "data": {"session": "s01", "tenant": "acme", "migrations": 1,
                  "from_replica": "r0", "iter": 3}},
    ])
    s01_r1 = [
        {"kind": "session-state", "run": run, "t_wall": 102.0,
         "data": {"session": "s01", "tenant": "acme", "sla": "latency",
                  "state": "RUNNING", "replica": "r1"}},
        {"kind": "hub-iteration", "run": run, "t_wall": 102.5,
         "t_mono": 2.0, "data": {"iter": 7, "rel_gap": 0.008}},
        {"kind": "session-state", "run": run, "t_wall": 103.0,
         "data": {"session": "s01", "tenant": "acme",
                  "state": "DONE", "replica": "r1"}},
    ]
    _jl(td / "r1" / "session-s01.jsonl", s01_r1, torn_last=True)
    _jl(td / "r1" / "session-s02.jsonl", [
        {"kind": "session-state", "run": run, "t_wall": 102.2,
         "data": {"session": "s02", "tenant": "zeta", "sla": "batch",
                  "state": "DONE", "replica": "r1"}},
    ])

    states: dict = {}
    offsets: dict = {}
    for name in ("r0/session-s01.jsonl", "r1/session-s01.jsonl",
                 "r1/session-s02.jsonl"):
        st = states.setdefault(name, w.WatchState())
        offsets[name] = w._follow(str(td / name), st, 0)

    rows = {r["session"]: r for r in w.merge_session_rows(states)}
    assert set(rows) == {"s01", "s02"}        # s01 joined, counted ONCE
    s01 = rows["s01"]
    assert s01["chain"] == ["r0", "r1"]
    assert s01["replica"] == "r1"             # newest segment wins
    assert s01["state"] == "RUNNING"          # torn DONE not consumed
    assert s01["iter"] == 7                   # max across segments
    assert s01["migrations"] == 1
    assert s01["events"] == 5                 # 3 (r0) + 2 complete (r1)
    assert rows["s02"]["chain"] == ["r1"]

    table = w.render_tenant_table(states)
    assert table.count("s01") == 1            # one row, no double-count
    assert "r0>r1" in table
    assert "replica r0: 0 session(s) resident, 0 terminal, 1 migrated" \
        in table
    assert "replica r1: 2 session(s) resident, 1 terminal, 1 migrated" \
        in table

    # the writer finishes the torn terminal line: the tailer resumes
    # from its offset and the session lands DONE, seen exactly once
    full = json.dumps(s01_r1[-1])
    with open(td / "r1" / "session-s01.jsonl", "a") as f:
        f.write(full[len(full) // 2:] + "\n")
    name = "r1/session-s01.jsonl"
    w._follow(str(td / name), states[name], offsets[name])
    rows = {r["session"]: r for r in w.merge_session_rows(states)}
    assert rows["s01"]["state"] == "DONE"
    assert rows["s01"]["events"] == 6

    # the CLI dir mode walks one level deep and skips fleet.jsonl
    import io
    buf = io.StringIO()
    assert w.watch_dir(str(td), once=True, out=buf) == 0
    out = buf.getvalue()
    assert "r0>r1" in out and out.count("s01") == 1
    assert "fleet" not in out                 # aggregate stream skipped


def test_gate_r09_r10_fleet_keys_and_migration_milestone(tmp_path):
    """ISSUE 16 gate fixture: the committed r09->r10 pair gates green
    with the fleet phase's latency/isolation keys riding the existing
    serve_load patterns; fleet_migrations_lost_total carries an
    any-increase gate (must stay 0) and migrated_reached_gap_frac a
    1.0 ratchet MILESTONE the committed artifact binds."""
    r09 = os.path.join(REPO, "BENCH_r09.json")
    r10 = os.path.join(REPO, "BENCH_r10.json")
    rep = regress.gate_paths(r09, r10)
    assert rep["ok"], rep["regressions"]
    ms = {r["metric"]: r for r in rep["milestones"]}
    mig = ms["fleet_serve_load.migration.migrated_reached_gap_frac"]
    assert mig["status"] == "met" and mig["milestone"] == 1.0

    # a later round LOSING a migrated session fails on the
    # any-increase gate even though the baseline value is 0
    lost = json.load(open(r10))
    lost["parsed"]["fleet_serve_load"]["migration"][
        "fleet_migrations_lost_total"] = 1
    lost_path = tmp_path / "BENCH_lost.json"
    lost_path.write_text(json.dumps(lost))
    rep2 = regress.gate_paths(r10, str(lost_path))
    assert not rep2["ok"]
    assert any("migrations_lost" in r["metric"]
               for r in rep2["regressions"])

    # fleet p99 regressing past +-25% fails via the serve_load
    # latency pattern (unanchored search covers fleet_serve_load)
    slow = json.load(open(r10))
    slow["parsed"]["fleet_serve_load"]["time_to_gap_p99_s"] *= 1.5
    slow_path = tmp_path / "BENCH_fleet_slow.json"
    slow_path.write_text(json.dumps(slow))
    rep3 = regress.gate_paths(r10, str(slow_path))
    assert not rep3["ok"]
    assert any(r["metric"] ==
               "fleet_serve_load.time_to_gap_p99_s"
               for r in rep3["regressions"])

    # ...and the bound migration milestone RATCHETS: a fleet round
    # where a migrated session misses its gap fails from then on
    miss = json.load(open(r10))
    miss["parsed"]["fleet_serve_load"]["migration"][
        "migrated_reached_gap_frac"] = 0.5
    miss_path = tmp_path / "BENCH_mig_miss.json"
    miss_path.write_text(json.dumps(miss))
    rep4 = regress.gate_paths(r10, str(miss_path))
    assert not rep4["ok"]
    assert any("migrated_reached_gap_frac" in r["metric"]
               for r in rep4["regressions"])


def test_gate_r10_r11_mesh_chaos_keys_and_reshard_milestone(tmp_path):
    """ISSUE 17 gate fixture: the committed r10->r11 pair gates green
    with the mesh_chaos phase's keys; mesh_reshards_lost_total carries
    an any-increase gate (a resharded run must never be lost) and
    reshard_reached_gap_frac a 1.0 ratchet MILESTONE — the resumed
    post-reshard wheel certifies the same gap as the fault-free run."""
    r10 = os.path.join(REPO, "BENCH_r10.json")
    r11 = os.path.join(REPO, "BENCH_r11.json")
    rep = regress.gate_paths(r10, r11)
    assert rep["ok"], rep["regressions"]
    ms = {r["metric"]: r for r in rep["milestones"]}
    resh = ms["mesh_chaos.reshard.reshard_reached_gap_frac"]
    assert resh["status"] == "met" and resh["milestone"] == 1.0

    # a later round LOSING a resharded run fails on the any-increase
    # gate even though the baseline value is 0
    lost = json.load(open(r11))
    lost["parsed"]["mesh_chaos"]["reshard"][
        "mesh_reshards_lost_total"] = 1
    lost_path = tmp_path / "BENCH_mesh_lost.json"
    lost_path.write_text(json.dumps(lost))
    rep2 = regress.gate_paths(r11, str(lost_path))
    assert not rep2["ok"]
    assert any("mesh_reshards_lost" in r["metric"]
               for r in rep2["regressions"])

    # ...and the bound reshard milestone RATCHETS: a chaos round where
    # the resumed wheel misses its gap target fails from then on
    miss = json.load(open(r11))
    miss["parsed"]["mesh_chaos"]["reshard"][
        "reshard_reached_gap_frac"] = 0.5
    miss_path = tmp_path / "BENCH_reshard_miss.json"
    miss_path.write_text(json.dumps(miss))
    rep3 = regress.gate_paths(r11, str(miss_path))
    assert not rep3["ok"]
    assert any("reshard_reached_gap_frac" in r["metric"]
               for r in rep3["regressions"])
