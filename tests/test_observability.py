# Derived observability (ISSUE 5; mpisppy_tpu/telemetry/{analyze,
# flightrec,regress}.py, tools/check_readme_claims.py): the trace
# analyzer's typed run model + report, the crash flight recorder's
# ring/dump semantics and overhead contract, the perf-regression gate
# over BENCH fixtures and analyzer reports, and the README perf-claim
# lint — all wired to the `python -m mpisppy_tpu.telemetry` CLI.
import json
import os
import subprocess
import sys
import time

import pytest

from mpisppy_tpu import telemetry
from mpisppy_tpu.telemetry import analyze as an
from mpisppy_tpu.telemetry import regress

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
GOLDEN = os.path.join(HERE, "fixtures", "golden_farmer_trace.jsonl")
CLI = [sys.executable, "-m", "mpisppy_tpu.telemetry"]
ENV = {"PATH": "/usr/bin:/bin:/usr/local/bin", "JAX_PLATFORMS": "cpu",
       "HOME": os.path.expanduser("~")}


def farmer_wheel(bus, max_iterations=8, hub_extra=None):
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.cylinders import (
        LagrangianOuterBound, PHHub, XhatXbarInnerBound,
    )
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.ops import pdhg
    from mpisppy_tpu.spin_the_wheel import WheelSpinner
    names = farmer.scenario_names_creator(3)
    specs = [farmer.scenario_creator(nm, num_scens=3) for nm in names]
    batch = batch_mod.from_specs(specs)
    opts = ph_mod.PHOptions(
        default_rho=1.0, max_iterations=max_iterations, conv_thresh=0.0,
        subproblem_windows=10, pdhg=pdhg.PDHGOptions(tol=1e-7))
    hub_opts = {"rel_gap": 5e-3, "telemetry_bus": bus}
    hub_opts.update(hub_extra or {})
    hub = {"hub_class": PHHub, "hub_kwargs": {"options": hub_opts},
           "opt_class": ph_mod.PH,
           "opt_kwargs": {"options": opts, "batch": batch}}
    spokes = [
        {"spoke_class": LagrangianOuterBound,
         "opt_kwargs": {"options": {}}},
        {"spoke_class": XhatXbarInnerBound, "opt_kwargs": {"options": {}}},
    ]
    return WheelSpinner(hub, spokes).spin()


# ---------------------------------------------------------------------------
# Analyzer: golden-trace round trip (committed fixture of a real
# farmer wheel with a NaN fault injection + checkpointing)
# ---------------------------------------------------------------------------
def test_analyze_golden_trace():
    rep = an.analyze_path(GOLDEN)
    assert rep["schema"] == an.ANALYZE_SCHEMA
    assert rep["run"]["hub_class"] == "PHHub"
    assert rep["run"]["num_spokes"] == 2
    # explicit exit verdict (ISSUE 5 satellite: run-end event)
    assert rep["run"]["exit"]["reason"] == "max-iter"
    assert rep["run"]["exit"]["rel_gap"] == pytest.approx(7.787e-3,
                                                          rel=1e-3)
    # per-phase wall-time breakdown from the span events
    phases = rep["phases"]
    assert {"harvest", "hub_sync", "spoke_update", "checkpoint",
            "subproblem_solve", "iter0_solve"} <= set(phases)
    assert phases["subproblem_solve"]["calls"] == 10
    assert all(a["total_s"] >= 0 for a in phases.values())
    assert abs(sum(a["share"] for a in phases.values()) - 1.0) < 1e-6
    # iteration timing
    it = rep["iteration"]
    assert it["count"] == 11
    assert it["sec_per_iter_median"] > 0
    # bound progress + stall diagnostics
    b = rep["bounds"]
    assert b["final_outer"] == pytest.approx(-108931.95, rel=1e-4)
    assert b["final_inner"] == pytest.approx(-108090.27, rel=1e-4)
    assert b["time_to_gap"]["0.01"]["iter"] == 10
    assert b["iters_since_outer_moved"] == 4
    # per-spoke attribution: who produced the binding bounds
    at = rep["attribution"]
    assert at["final_bound_producer"]["outer"]["spoke"] == 0
    assert at["final_bound_producer"]["outer"]["class"] \
        == "LagrangianOuterBound"
    assert at["final_bound_producer"]["inner"]["spoke"] == 1
    s0 = at["spokes"]["0"]
    assert s0["harvests"] == 11 and s0["rejects"] == 1 \
        and s0["strikes"] == 1
    # the injected NaN shows up as cause (fault) AND response (strike)
    res = rep["resilience"]
    assert res["faults_injected"] == {"spoke_bound": 1}
    assert res["spoke_strikes"] == 1 and res["checkpoint_writes"] >= 1
    # kernel counters folded per cylinder
    assert rep["kernel"]["hub"]["pdhg_iterations_total"] > 0
    # the human rendering carries the load-bearing lines
    text = an.render_report(rep)
    assert "binding outer: spoke 0 (LagrangianOuterBound)" in text
    assert "exit: max-iter" in text
    json.dumps(rep)  # machine report is strict-JSON-able


def test_analyze_handles_torn_tail_and_run_selection(tmp_path):
    rows = open(GOLDEN).read().splitlines()
    torn = tmp_path / "torn.jsonl"
    torn.write_text("\n".join(rows) + "\n" + rows[-1][: len(rows[-1]) // 2])
    rep = an.analyze_path(str(torn))
    assert rep["run"]["events"] == len(rows)
    # unknown run id is a clear error, not a silent empty report
    with pytest.raises(ValueError):
        an.analyze_path(GOLDEN, run="nonexistent")


def test_analyze_cli_json(tmp_path):
    out = subprocess.run(CLI + ["analyze", "--trace-jsonl", GOLDEN,
                                "--json"],
                         capture_output=True, text=True, cwd=REPO,
                         timeout=120, env=ENV)
    assert out.returncode == 0, out.stderr[-2000:]
    rep = json.loads(out.stdout)
    assert rep["schema"] == an.ANALYZE_SCHEMA
    assert rep["run"]["exit"]["reason"] == "max-iter"
    # human mode renders the report (not JSON)
    out2 = subprocess.run(CLI + ["analyze", "--trace-jsonl", GOLDEN],
                          capture_output=True, text=True, cwd=REPO,
                          timeout=120, env=ENV)
    assert out2.returncode == 0 and "phases (host wall):" in out2.stdout


# ---------------------------------------------------------------------------
# Analyzer on a live tier-1 wheel run (the acceptance criterion)
# ---------------------------------------------------------------------------
def test_analyze_live_wheel_trace(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    bus = telemetry.EventBus()
    bus.subscribe(telemetry.JsonlSink(path))
    farmer_wheel(bus, max_iterations=6)
    bus.close()
    rep = an.analyze_path(path)
    assert rep["run"]["exit"]["reason"] in ("converged", "max-iter",
                                            "conv-thresh", "stalled")
    assert {"harvest", "subproblem_solve"} <= set(rep["phases"])
    assert rep["iteration"]["sec_per_iter_median"] > 0
    producers = rep["attribution"]["final_bound_producer"]
    assert {"outer", "inner"} <= set(producers)
    assert rep["flags"] == [] or all(isinstance(f, str)
                                     for f in rep["flags"])


# ---------------------------------------------------------------------------
# Flight recorder: ring semantics, dump format, overhead contract
# ---------------------------------------------------------------------------
def test_flight_recorder_ring_wraps_and_dumps(tmp_path):
    bus = telemetry.EventBus()
    rec = telemetry.FlightRecorder(capacity=8, dump_dir=str(tmp_path))
    bus.subscribe(rec)
    run = telemetry.new_run_id()
    for i in range(20):
        bus.emit(telemetry.HUB_ITERATION, run=run, cyl="hub",
                 hub_iter=i, iter=i)
    assert rec.dropped == 12
    evs = rec.events()
    assert len(evs) == 8
    assert [e.hub_iter for e in evs] == list(range(12, 20))  # oldest first
    path = rec.dump(reason="unit test")
    assert path == str(tmp_path / f"flight-{run}.jsonl")
    rows = [json.loads(line) for line in open(path)]
    hdr = rows[0]
    assert hdr["kind"] == "flight-recorder" and hdr["reason"] == "unit test"
    assert hdr["dumped_events"] == 8 and hdr["dropped"] == 12
    assert [r["iter"] for r in rows[1:]] == list(range(12, 20))
    # a dump is an analyzer input; without run-end it reads as truncated
    rep = an.analyze(an.build_run_model(rows))
    assert rep["run"]["exit"]["reason"] == "truncated"
    assert rep["run"]["exit"]["flight_reason"] == "unit test"
    assert any("truncated" in f for f in rep["flags"])


def test_flight_recorder_zero_graph_impact_and_throughput(tmp_path):
    """Overhead contract: the ring sink is host-side bookkeeping only —
    the lowered wheel step is byte-identical with a recorder-bearing
    bus attached (the kernel-counters HLO test's contract extended to
    the black box), and bus throughput with a recorder stays in the
    microseconds-per-event regime."""
    import jax.numpy as jnp
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.ops import pdhg
    names = farmer.scenario_names_creator(3)
    specs = [farmer.scenario_creator(nm, num_scens=3) for nm in names]
    batch = batch_mod.from_specs(specs)
    opts = ph_mod.kernel_opts(ph_mod.PHOptions(
        default_rho=1.0, conv_thresh=0.0, subproblem_windows=10,
        pdhg=pdhg.PDHGOptions(tol=1e-7)))
    rho = jnp.ones((batch.num_nonants,), batch.qp.c.dtype)
    st, _, _ = ph_mod.ph_iter0(batch, rho, opts)
    text_base = ph_mod.ph_iterk.lower(batch, st, opts).as_text()

    bus = telemetry.EventBus()
    bus.subscribe(telemetry.FlightRecorder(dump_dir=str(tmp_path)))
    ws = farmer_wheel(bus, max_iterations=3)
    text_wired = ph_mod.ph_iterk.lower(
        batch, ws.opt.state, ph_mod.kernel_opts(ws.opt.options)).as_text()
    assert text_wired == text_base

    # throughput: the ring is a preallocated slot store — no growth,
    # no per-event allocation of anything but the Event the bus built
    rec = telemetry.FlightRecorder(capacity=512)
    bus2 = telemetry.EventBus()
    bus2.subscribe(rec)
    n = 20_000
    t0 = time.perf_counter()
    for i in range(n):
        bus2.emit(telemetry.HUB_ITERATION, run="r", cyl="hub", hub_iter=i)
    per_event = (time.perf_counter() - t0) / n
    assert per_event < 250e-6, f"{per_event * 1e6:.1f} us/event"
    assert len(rec._ring) == 512 and rec.dropped == n - 512


def test_generic_cylinders_crash_leaves_black_box(tmp_path, monkeypatch):
    """A wheel dying under the CLI driver with tracing OFF still leaves
    flight-<runid>.jsonl (the always-on registration in
    generic_cylinders + the dump in WheelSpinner.spin's unwind)."""
    from mpisppy_tpu import generic_cylinders
    from mpisppy_tpu.cylinders import hub as hub_mod

    calls = {"n": 0}
    orig = hub_mod.PHHub._harvest_kernel_counters

    def boom(self):
        calls["n"] += 1
        if calls["n"] >= 3:
            raise RuntimeError("synthetic mid-wheel crash")
        return orig(self)

    monkeypatch.setattr(hub_mod.PHHub, "_harvest_kernel_counters", boom)
    args = ["--module-name", "mpisppy_tpu.models.farmer",
            "--num-scens", "3", "--max-iterations", "6",
            "--rel-gap", "0.005", "--lagrangian", "--xhatxbar",
            "--flight-dir", str(tmp_path)]
    with pytest.raises(RuntimeError, match="synthetic mid-wheel crash"):
        generic_cylinders.main(args)
    dumps = [f for f in os.listdir(tmp_path)
             if f.startswith("flight-") and f.endswith(".jsonl")]
    assert len(dumps) == 1, dumps
    rep = an.analyze_path(str(tmp_path / dumps[0]))
    assert rep["run"]["exit"]["reason"] == "exception"
    assert "synthetic mid-wheel crash" in rep["run"]["exit"]["error"]


# ---------------------------------------------------------------------------
# Regression gate: BENCH fixtures + analyzer reports
# ---------------------------------------------------------------------------
def test_gate_passes_r05_vs_r04_and_fails_on_regression(tmp_path):
    r04 = os.path.join(REPO, "BENCH_r04.json")
    r05 = os.path.join(REPO, "BENCH_r05.json")
    rep = regress.gate_paths(r04, r05)
    assert rep["ok"], rep["regressions"]
    assert rep["common"] > 10
    # the salvage recovered gateable keys from the truncated tails
    gated = {r["metric"] for r in rep["rows"] if r["gated"]}
    assert any("sec_per_iter" in k for k in gated)
    assert any("iters_per_sec" in k for k in gated)

    # synthetically regress sec_per_iter by 33% -> gate must fail
    bad = json.load(open(r05))
    bad["tail"] = bad["tail"].replace('"sec_per_iter": 0.0601',
                                     '"sec_per_iter": 0.0801')
    bad_path = tmp_path / "BENCH_regressed.json"
    bad_path.write_text(json.dumps(bad))
    rep2 = regress.gate_paths(r04, str(bad_path))
    assert not rep2["ok"]
    assert any("sec_per_iter" in r["metric"] for r in rep2["regressions"])
    # the CLI maps the verdicts to exit codes (0 pass / 2 regression)
    from mpisppy_tpu.telemetry.__main__ import main as tel_main
    assert tel_main(["gate", r04, r05]) == 0
    assert tel_main(["gate", r04, str(bad_path)]) == 2
    # direction matters: a 33% FASTER sec_per_iter is not a regression
    good = json.load(open(r05))
    good["tail"] = good["tail"].replace('"sec_per_iter": 0.0601',
                                       '"sec_per_iter": 0.0401')
    good_path = tmp_path / "BENCH_improved.json"
    good_path.write_text(json.dumps(good))
    assert regress.gate_paths(r04, str(good_path))["ok"]


def test_gate_r06_fixture_and_milestones(tmp_path):
    """ISSUE 8 gate-fixture refresh: the committed r05->r06 pair must
    gate green; the new absolute MILESTONE thresholds (S=10k
    sec_per_iter <= 0.045, S=100k iters_per_sec >= 2) follow ratchet
    semantics — pending on pre-win artifacts, strict-bindable via
    --milestones, and permanently binding once an artifact has landed
    the win; the committed overlap_frac keys fail the gate on a
    synthetic drop."""
    r05 = os.path.join(REPO, "BENCH_r05.json")
    r06 = os.path.join(REPO, "BENCH_r06.json")
    rep = regress.gate_paths(r05, r06)
    assert rep["ok"], rep["regressions"]
    # both milestone keys are present and reported pending (r06 carries
    # the pre-win on-TPU measurements: 0.0601 s/iter, 1.46 iters/s)
    ms = {r["metric"]: r for r in rep["milestones"]}
    assert ms["measured_mfu.S10000.sec_per_iter"]["status"] == "pending"
    assert ms["sweep_iters_per_sec.S100000.iters_per_sec"]["status"] \
        == "pending"
    assert not any(r["regressed"] for r in rep["milestones"])

    # strict mode: the same pair FAILS until the wins land on hardware
    from mpisppy_tpu.telemetry.__main__ import main as tel_main
    assert tel_main(["gate", r05, r06]) == 0
    assert tel_main(["gate", r05, r06, "--milestones"]) == 2

    # a post-win artifact meets the floors in strict mode... (strict
    # requires EVERY milestone phase present, so the synthetic post-win
    # artifact also carries the ISSUE-11 async-overhead phase, the
    # ISSUE-12 serve isolation phase, the ISSUE-14 scengen phase, the
    # ISSUE-16 fleet migration phase, the ISSUE-17 mesh reshard phase,
    # the ISSUE-19 mpc stream phase, and the ISSUE-20 slo rollup)
    won = json.load(open(r06))
    won["parsed"]["measured_mfu"]["S10000"]["sec_per_iter"] = 0.044
    won["parsed"]["sweep_iters_per_sec"][2]["iters_per_sec"] = 2.2
    won["parsed"]["wheel_overhead_async"] = {"overhead_factor": 1.25}
    won["parsed"]["serve_load"] = {
        "isolation": {"isolation_ratio": 1.0}}
    won["parsed"]["fleet_serve_load"] = {
        "isolation": {"isolation_ratio": 1.0},
        "migration": {"migrated_reached_gap_frac": 1.0}}
    won["parsed"]["wheel_scengen"] = {
        "synth_vs_materialized_ratio": 0.97,
        "sweep": [{"scenarios": 1_000_000, "iters_per_sec": 0.07}]}
    won["parsed"]["mesh_chaos"] = {
        "reshard": {"reshard_reached_gap_frac": 1.0}}
    won["parsed"]["mpc_stream"] = {
        "warm_over_cold_ratio": 0.5,
        "chaos": {"resumed_matched_frac": 1.0}}
    won["parsed"]["slo"] = {
        "latency": {"burn_rate": 0.0, "budget_remaining": 1.0}}
    won_path = tmp_path / "BENCH_won.json"
    won_path.write_text(json.dumps(won))
    rep2 = regress.gate_paths(r06, str(won_path), milestones=True)
    assert rep2["ok"], rep2["regressions"]
    assert all(r["status"] == "met" for r in rep2["milestones"])

    # ...and then RATCHETS: a later artifact slipping past the floor
    # fails WITHOUT --milestones even when the relative move is inside
    # the +-10% band (0.044 -> 0.0462 is +5%; 2.2 -> 1.98 is -10%)
    slip = json.load(open(r06))
    slip["parsed"]["measured_mfu"]["S10000"]["sec_per_iter"] = 0.0462
    slip["parsed"]["sweep_iters_per_sec"][2]["iters_per_sec"] = 1.98
    slip_path = tmp_path / "BENCH_slipped.json"
    slip_path.write_text(json.dumps(slip))
    rep3 = regress.gate_paths(str(won_path), str(slip_path))
    assert not rep3["ok"]
    failed = {r["metric"] for r in rep3["regressions"]}
    assert "measured_mfu.S10000.sec_per_iter" in failed
    assert "sweep_iters_per_sec.S100000.iters_per_sec" in failed

    # a LANDED milestone key that disappears from the next artifact is
    # a failure, not a silently-un-bound gate (dropping the bench phase
    # must not become the regression escape hatch)
    gone = json.load(open(r06))
    del gone["parsed"]["measured_mfu"]
    gone_path = tmp_path / "BENCH_phase_dropped.json"
    gone_path.write_text(json.dumps(gone))
    rep_gone = regress.gate_paths(str(won_path), str(gone_path))
    assert not rep_gone["ok"]
    assert any(r.get("status") == "MISSING"
               and "measured_mfu" in r["metric"]
               for r in rep_gone["regressions"])
    # strict mode fails the absent key even when it never landed
    rep_gone2 = regress.gate_paths(r06, str(gone_path), milestones=True)
    assert any(r.get("status") == "MISSING" for r in rep_gone2["milestones"])
    assert not rep_gone2["ok"]
    # ...but ratchet mode lets a never-landed phase disappear quietly
    rep_gone3 = regress.gate_paths(r06, str(gone_path))
    assert not any(r.get("status") == "MISSING"
                   for r in rep_gone3["milestones"])

    # overlap_frac keys gate direction-aware on the committed fixture:
    # a 35% drop in DMA/compute overlap at S=100k is a regression
    drop = json.load(open(r06))
    drop["parsed"]["device_profile"]["S100000"]["overlap_frac"] = 0.64
    drop_path = tmp_path / "BENCH_overlap_drop.json"
    drop_path.write_text(json.dumps(drop))
    rep4 = regress.gate_paths(r06, str(drop_path))
    assert not rep4["ok"]
    assert any("overlap_frac" in r["metric"] for r in rep4["regressions"])
    # while a RISING overlap (the double-buffer win direction) passes
    rise = json.load(open(r06))
    rise["parsed"]["device_profile"]["S100000"]["overlap_frac"] = 0.999
    rise_path = tmp_path / "BENCH_overlap_rise.json"
    rise_path.write_text(json.dumps(rise))
    assert regress.gate_paths(r06, str(rise_path))["ok"]


def test_gate_r08_serve_load_keys_and_isolation_milestone(tmp_path):
    """ISSUE 12 gate fixture: the committed r07->r08 pair gates green;
    the serve_load latency keys gate direction-aware; and the
    tenant-isolation ratio carries a <= 1.25 ratchet MILESTONE that
    the committed (meeting) artifact binds."""
    r07 = os.path.join(REPO, "BENCH_r07.json")
    r08 = os.path.join(REPO, "BENCH_r08.json")
    rep = regress.gate_paths(r07, r08)
    assert rep["ok"], rep["regressions"]
    ms = {r["metric"]: r for r in rep["milestones"]}
    iso = ms["serve_load.isolation.isolation_ratio"]
    assert iso["status"] == "met" and iso["milestone"] == 1.25

    # the committed artifact meets the bound, so the ratchet BINDS: a
    # later artifact slipping past 1.25 fails even within +-25%
    slip = json.load(open(r08))
    slip["parsed"]["serve_load"]["isolation"]["isolation_ratio"] = 1.3
    slip_path = tmp_path / "BENCH_iso_slip.json"
    slip_path.write_text(json.dumps(slip))
    rep2 = regress.gate_paths(r08, str(slip_path))
    assert not rep2["ok"]
    assert any(r["metric"] == "serve_load.isolation.isolation_ratio"
               for r in rep2["regressions"])

    # client-observed latency keys gate at +-25%
    slow = json.load(open(r08))
    slow["parsed"]["serve_load"]["time_to_gap_p99_s"] *= 1.5
    slow_path = tmp_path / "BENCH_p99_slow.json"
    slow_path.write_text(json.dumps(slow))
    rep3 = regress.gate_paths(r08, str(slow_path))
    assert not rep3["ok"]
    assert any("time_to_gap_p99_s" in r["metric"]
               for r in rep3["regressions"])
    # ...and a FASTER p99 passes (direction-aware)
    fast = json.load(open(r08))
    fast["parsed"]["serve_load"]["time_to_gap_p99_s"] *= 0.6
    fast_path = tmp_path / "BENCH_p99_fast.json"
    fast_path.write_text(json.dumps(fast))
    assert regress.gate_paths(r08, str(fast_path))["ok"]


def test_gate_analyzer_reports_and_thresholds(tmp_path):
    rep = an.analyze_path(GOLDEN)
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(rep))
    # identical reports: everything common, nothing regressed
    out = regress.gate_paths(str(a), str(a))
    assert out["ok"] and out["common"] > 3

    worse = json.loads(json.dumps(rep))
    worse["iteration"]["sec_per_iter_median"] *= 2.0
    worse["iteration"]["sec_per_iter_p90"] *= 2.0
    b.write_text(json.dumps(worse))
    out2 = regress.gate_paths(str(a), str(b))
    assert not out2["ok"]
    assert any("sec_per_iter_median" in r["metric"]
               for r in out2["regressions"])
    # per-call threshold override loosens the verdict
    out3 = regress.gate_paths(str(a), str(b),
                              overrides={"sec_per_iter": 3.0})
    assert out3["ok"]
    # metric extraction keyed the gateable fields
    m = regress.extract_metrics(rep)
    assert "iteration.sec_per_iter_median" in m
    assert "time_to_gap.0.01" in m
    assert "kernel.hub.guard_resets" in m


def test_gate_refuses_vacuous_diff():
    out = regress.gate({"x": {"a": 1.0}}, {"y": {"b": 2.0}})
    assert not out["ok"] and "no common metrics" in out["error"]


def test_bench_tail_salvage_recovers_sections():
    art = regress.load_artifact(os.path.join(REPO, "BENCH_r04.json"))
    # the r04 tail is front-truncated; the complete trailing sections
    # must still be recovered with their nested fields intact
    assert art["hydro_to_1pct_gap"]["seconds_to_gap"] == \
        pytest.approx(176.072)
    assert art["measured_mfu"]["S10000"]["sec_per_iter"] == \
        pytest.approx(0.0597)
    assert isinstance(art["sweep_iters_per_sec"], list)
    # nested sections are not duplicated at top level
    assert "S10000" not in art


# ---------------------------------------------------------------------------
# Dispatch events join the iteration timeline exactly (ISSUE 5
# satellite: hub_iter stamps)
# ---------------------------------------------------------------------------
def test_dispatch_events_carry_hub_iter_stamp():
    from mpisppy_tpu import dispatch
    from mpisppy_tpu.dispatch import DispatchOptions, SolveScheduler

    events = []

    class Grab(telemetry.Sink):
        def handle(self, event):
            events.append(event)

    bus = telemetry.EventBus()
    bus.subscribe(Grab())

    def fake_solve(qp, d_col, int_cols, opts, **kw):
        return qp.c  # any array with a leading batch axis

    import jax.numpy as jnp
    import dataclasses as dc
    from mpisppy_tpu.ops.boxqp import BoxQP
    S, n, m = 3, 4, 2
    qp = BoxQP(c=jnp.zeros((S, n)), q=jnp.ones((S, n)),
               A=jnp.zeros((m, n)), bl=jnp.zeros((S, m)),
               bu=jnp.ones((S, m)), l=jnp.zeros((S, n)),
               u=jnp.ones((S, n)))
    sched = SolveScheduler(DispatchOptions(max_wait_ms=0.1),
                           solve_fn=fake_solve, bus=bus, run="testrun")
    try:
        dispatch.set_hub_iter(-1)   # pre-wheel
        sched.solve_mip(qp, jnp.ones((n,)), jnp.array([], jnp.int32))
        dispatch.set_hub_iter(7)    # mid-wheel
        sched.solve_mip(qp, jnp.ones((n,)), jnp.array([], jnp.int32))
    finally:
        sched.close()
        dispatch.set_hub_iter(-1)
    disp = [e for e in events if e.kind == telemetry.DISPATCH]
    assert [e.hub_iter for e in disp] == [-1, 7]
    # and the stamp survives serialization for the analyzer's join
    rows = [json.loads(e.to_json()) for e in disp]
    assert rows[0]["iter"] == -1 and rows[1]["iter"] == 7


# ---------------------------------------------------------------------------
# README perf-claim lint (tier-1, next to lint_no_print)
# ---------------------------------------------------------------------------
def _claims_tool():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import check_readme_claims
    finally:
        sys.path.pop(0)
    return check_readme_claims


def test_readme_claims_trace_to_artifacts():
    tool = _claims_tool()
    assert tool.find_violations() == []


def test_readme_claims_lint_catches_drift(tmp_path):
    tool = _claims_tool()
    fake = tmp_path / "README.md"
    fake.write_text(
        "intro prose\n\n"
        "Measured on one TPU v5 lite chip:\n\n"
        "- reaches the gap in 999 s (12 iterations, bf16x6) at ~3.1x "
        "speedup\n"
        "- config noise: 900 scenarios, 3-stage tree\n\n"
        "Out of scope: nothing.\n")
    pool = {12.0, 3.05}
    vio = tool.find_violations(readme=str(fake), pool=pool)
    # 999 s has no witness; 12 iterations does; ~3.1x matches 3.05
    # within the approximation slack; config numbers are not claims
    assert len(vio) == 1 and "'999s'" in vio[0]
    assert tool.find_violations(readme=str(fake),
                                pool=pool | {998.9}) == []
    # ISSUE 8: a throughput bullet WITHOUT a precision-mode token is a
    # violation even when every number is witnessed — wrapped bullet
    # lines share the first line's disclosure
    fake.write_text(
        "Measured on one TPU v5 lite chip:\n\n"
        "- reaches the gap in 999 s\n"
        "- wrapped bullet at bf16x3 reaches\n"
        "  the gap in 999 s too\n\n"
        "Out of scope: nothing.\n")
    vio2 = tool.find_violations(readme=str(fake), pool={999.0})
    assert len(vio2) == 1 and "precision" in vio2[0]
    # trailing section prose must NOT donate its token to the last
    # bullet (a paragraph is not a bullet continuation)
    fake.write_text(
        "Measured on one TPU v5 lite chip:\n\n"
        "- reaches the gap in 999 s\n\n"
        "See docs/precision.md for the bf16x6 contract.\n\n"
        "Out of scope: nothing.\n")
    vio3 = tool.find_violations(readme=str(fake), pool={999.0})
    assert len(vio3) == 1 and "precision" in vio3[0]


# ---------------------------------------------------------------------------
# Dispatch fault domain observability (ISSUE 9): analyzer resilience
# rows + dispatch-cause audit, regress-gate rows, and the `telemetry
# watch` torn-tail contract.
# ---------------------------------------------------------------------------
def _fault_domain_trace(path: str) -> None:
    """Synthesize a serving-shaped trace through the real bus+sink so
    the row schema can never drift from the emitters'."""
    bus = telemetry.EventBus()
    bus.subscribe(telemetry.JsonlSink(path))
    run = "fdrun"
    bus.emit(telemetry.RUN_START, run=run, cyl="hub",
             hub_class="PHHub", num_spokes=2)
    for it in (1, 2, 3):
        bus.emit(telemetry.HUB_ITERATION, run=run, cyl="hub",
                 hub_iter=it, iter=it, outer=-110.0 - it, inner=-100.0,
                 abs_gap=10.0, rel_gap=0.1)
    bus.emit(telemetry.DISPATCH, run=run, cyl="dispatch", hub_iter=1,
             requests=2, lanes=6, padded_to=8, occupancy=0.75,
             bucket=[8, 4, 4], wait_ms=1.0, queue_depth=0,
             cause="timer", inflight_max=1)
    bus.emit(telemetry.DISPATCH, run=run, cyl="dispatch", hub_iter=2,
             requests=1, lanes=8, padded_to=8, occupancy=1.0,
             bucket=[8, 4, 4], wait_ms=0.1, queue_depth=0,
             cause="size", inflight_max=1)
    bus.emit(telemetry.DISPATCH_RETRY, run=run, cyl="dispatch",
             hub_iter=2, attempt=1, requests=2, lanes=6,
             backoff_s=0.05, error="RuntimeError: injected")
    bus.emit(telemetry.DISPATCH_QUARANTINE, run=run, cyl="dispatch",
             hub_iter=2, submit=3, lanes=3, attempts=4,
             reason="exception", bisected=True,
             error="DispatchPoison: injected")
    bus.emit(telemetry.WATCHDOG, run=run, cyl="watchdog",
             component="hub", action="degrade", stalled_s=12.5,
             budget_s=10.0, trips=1)
    bus.emit(telemetry.WATCHDOG, run=run, cyl="dispatch",
             component="dispatcher", action="fail-fast",
             failed_tickets=2, error="RuntimeError: killed")
    bus.emit(telemetry.DISPATCH, run=run, cyl="hub", hub_iter=3,
             batches=3, buckets=2, backend_compiles=2,
             unexpected_recompiles=0, inflight_max=1,
             retries_total=1, quarantined_lanes=3, degraded=True)
    bus.emit(telemetry.RUN_END, run=run, cyl="hub", hub_iter=3,
             reason="max-iter", outer=-113.0, inner=-100.0,
             abs_gap=13.0, rel_gap=0.13, iterations=3)
    bus.close()


def test_analyzer_reports_dispatch_fault_domain(tmp_path):
    path = str(tmp_path / "fd.jsonl")
    _fault_domain_trace(path)
    rep = an.analyze_path(path)
    res = rep["resilience"]
    assert res["dispatch_retries"] == 1
    assert res["dispatch_quarantined_lanes"] == 3
    assert res["dispatch_quarantined_requests"] == 1
    assert res["watchdog_trips"] == 1          # degrade counts, fail-
    assert res["dispatcher_deaths"] == 1       # fast is its own row
    d = rep["dispatch"]
    # the cause split attributes occupancy loss to admission timeouts
    assert d["by_cause"]["timer"]["batches"] == 1
    assert d["by_cause"]["timer"]["occupancy"] == 0.75
    assert d["by_cause"]["size"]["occupancy"] == 1.0
    assert d["retries_total"] == 1 and d["quarantined_lanes"] == 3
    flags = "\n".join(rep["flags"])
    assert "quarantined" in flags and "watchdog" in flags \
        and "dispatcher-thread death" in flags
    text = an.render_report(rep)
    assert "dispatch fault domain: retries 1" in text
    json.dumps(rep)


def test_gate_fails_on_quarantine_or_retry_increase(tmp_path):
    """ISSUE 9 regress rows: on bench-style artifacts ANY increase in
    dispatch retries or quarantined lanes is a regression."""
    old = {"phase": {"seconds_to_gap": 100.0,
                     "dispatch": {"batches": 5, "retries_total": 0,
                                  "quarantined_lanes": 0}}}
    good = {"phase": {"seconds_to_gap": 101.0,
                      "dispatch": {"batches": 5, "retries_total": 0,
                                   "quarantined_lanes": 0}}}
    bad_q = {"phase": {"seconds_to_gap": 101.0,
                       "dispatch": {"batches": 5, "retries_total": 0,
                                    "quarantined_lanes": 3}}}
    bad_r = {"phase": {"seconds_to_gap": 101.0,
                       "dispatch": {"batches": 5, "retries_total": 2,
                                    "quarantined_lanes": 0}}}
    assert regress.gate(old, good)["ok"]
    repq = regress.gate(old, bad_q)
    assert not repq["ok"]
    assert any("quarantined_lanes" in r["metric"]
               for r in repq["regressions"])
    repr_ = regress.gate(old, bad_r)
    assert not repr_["ok"]
    assert any("retries_total" in r["metric"]
               for r in repr_["regressions"])


def test_gate_analyzer_resilience_rows(tmp_path):
    """Analyzer reports carry the fault-domain counters into the gate:
    a run that started quarantining lanes fails against a clean one."""
    clean = str(tmp_path / "clean.jsonl")
    bus = telemetry.EventBus()
    bus.subscribe(telemetry.JsonlSink(clean))
    farmer_wheel(bus, max_iterations=4)
    bus.close()
    rep_old = an.analyze_path(clean)
    faulty = str(tmp_path / "faulty.jsonl")
    _fault_domain_trace(faulty)
    rep_new = an.analyze_path(faulty)
    verdict = regress.gate(rep_old, rep_new)
    assert not verdict["ok"]
    failing = {r["metric"] for r in verdict["regressions"]}
    assert "resilience.dispatch_quarantined_lanes" in failing
    assert "resilience.dispatch_retries" in failing
    assert "resilience.watchdog_trips" in failing


def test_watch_survives_torn_and_concurrently_appended_trace(tmp_path):
    """Satellite: `telemetry watch` tails a trace a live wheel is
    appending to — a torn final line (no newline / half a JSON object)
    must not crash the tailer, must not be double-counted, and must be
    picked up once completed."""
    from mpisppy_tpu.telemetry import watch as w

    path = str(tmp_path / "t.jsonl")
    _fault_domain_trace(path)
    rows = open(path).read().splitlines()
    # rewrite with the final line torn mid-object, no newline
    keep, last = rows[:-1], rows[-1]
    with open(path, "w") as f:
        f.write("\n".join(keep) + "\n" + last[: len(last) // 2])
    state = w.WatchState()
    pos = w._follow(path, state, 0)
    assert state.events == len(keep)          # torn line NOT consumed
    assert state.end is None
    # the writer finishes the line (plus one more event) — the tailer
    # resumes from its offset and sees both exactly once
    with open(path, "a") as f:
        f.write(last[len(last) // 2:] + "\n")
    pos = w._follow(path, state, pos)
    assert state.events == len(rows)
    assert state.end is not None              # run-end landed
    assert state.dispatch_retries == 1
    assert state.dispatch_quarantined == 3
    assert state.watchdog_trips == 1   # fail-fast is not a trip
    # a torn line that never completes (writer died) parses as garbage
    # once newline-terminated and is skipped, not fatal
    with open(path, "a") as f:
        f.write('{"kind": "hub-iter')
    pos2 = w._follow(path, state, pos)
    assert pos2 == pos and state.events == len(rows)
    with open(path, "a") as f:
        f.write("\n")
    pos3 = w._follow(path, state, pos2)
    assert pos3 > pos2 and state.events == len(rows)   # skipped
    # the CLI smoke mode renders the resilience line from this state
    rendered = w.render_status(state)
    assert "retries 1" in rendered and "quarantined lanes 3" in rendered


def test_watch_once_cli_on_fault_domain_trace(tmp_path):
    path = str(tmp_path / "fd.jsonl")
    _fault_domain_trace(path)
    out = subprocess.run(CLI + ["watch", "--trace-jsonl", path,
                                "--once"],
                         capture_output=True, text=True, cwd=REPO,
                         timeout=120, env=ENV)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "RUN ENDED: max-iter" in out.stdout
    assert "quarantined lanes 3" in out.stdout


# ---------------------------------------------------------------------------
# fleet layouts in `watch --trace-dir` (ISSUE 16 satellite): a migrated
# session's trace is split across two replicas' subdirectories, with
# the destination tail torn mid-migration
# ---------------------------------------------------------------------------
def _jl(path, rows, torn_last=False):
    with open(path, "w") as f:
        for i, row in enumerate(rows):
            line = json.dumps(row)
            if torn_last and i == len(rows) - 1:
                f.write(line[: len(line) // 2])   # torn, no newline
            else:
                f.write(line + "\n")


def test_watch_merges_migrated_session_across_replicas(tmp_path):
    """A fleet trace dir: session s01 started on r0, migrated to r1
    (the r1 segment's terminal line is TORN mid-write), s02 lives only
    on r1.  The watcher must join the s01 segments on (run, sid) into
    ONE row — never double-counting the session — render the replica
    chain `r0>r1`, and pick up the torn terminal once completed."""
    from mpisppy_tpu.telemetry import watch as w

    run = "run-fleet-1"
    td = tmp_path / "traces"
    (td / "r0").mkdir(parents=True)
    (td / "r1").mkdir()
    # aggregate router stream: must be SKIPPED by the dir walker
    _jl(td / "fleet.jsonl",
        [{"kind": "fleet-placement", "run": run, "t_wall": 99.0,
          "data": {"session": "s01", "replica": "r0"}}])
    _jl(td / "r0" / "session-s01.jsonl", [
        {"kind": "session-state", "run": run, "t_wall": 100.0,
         "data": {"session": "s01", "tenant": "acme", "sla": "latency",
                  "state": "RUNNING", "replica": "r0"}},
        {"kind": "hub-iteration", "run": run, "t_wall": 100.5,
         "t_mono": 1.0, "data": {"iter": 3, "rel_gap": 0.5}},
        {"kind": "session-migrated", "run": run, "t_wall": 101.0,
         "data": {"session": "s01", "tenant": "acme", "migrations": 1,
                  "from_replica": "r0", "iter": 3}},
    ])
    s01_r1 = [
        {"kind": "session-state", "run": run, "t_wall": 102.0,
         "data": {"session": "s01", "tenant": "acme", "sla": "latency",
                  "state": "RUNNING", "replica": "r1"}},
        {"kind": "hub-iteration", "run": run, "t_wall": 102.5,
         "t_mono": 2.0, "data": {"iter": 7, "rel_gap": 0.008}},
        {"kind": "session-state", "run": run, "t_wall": 103.0,
         "data": {"session": "s01", "tenant": "acme",
                  "state": "DONE", "replica": "r1"}},
    ]
    _jl(td / "r1" / "session-s01.jsonl", s01_r1, torn_last=True)
    _jl(td / "r1" / "session-s02.jsonl", [
        {"kind": "session-state", "run": run, "t_wall": 102.2,
         "data": {"session": "s02", "tenant": "zeta", "sla": "batch",
                  "state": "DONE", "replica": "r1"}},
    ])

    states: dict = {}
    offsets: dict = {}
    for name in ("r0/session-s01.jsonl", "r1/session-s01.jsonl",
                 "r1/session-s02.jsonl"):
        st = states.setdefault(name, w.WatchState())
        offsets[name] = w._follow(str(td / name), st, 0)

    rows = {r["session"]: r for r in w.merge_session_rows(states)}
    assert set(rows) == {"s01", "s02"}        # s01 joined, counted ONCE
    s01 = rows["s01"]
    assert s01["chain"] == ["r0", "r1"]
    assert s01["replica"] == "r1"             # newest segment wins
    assert s01["state"] == "RUNNING"          # torn DONE not consumed
    assert s01["iter"] == 7                   # max across segments
    assert s01["migrations"] == 1
    assert s01["events"] == 5                 # 3 (r0) + 2 complete (r1)
    assert rows["s02"]["chain"] == ["r1"]

    table = w.render_tenant_table(states)
    assert table.count("s01") == 1            # one row, no double-count
    assert "r0>r1" in table
    assert "replica r0: 0 session(s) resident, 0 terminal, 1 migrated" \
        in table
    assert "replica r1: 2 session(s) resident, 1 terminal, 1 migrated" \
        in table

    # the writer finishes the torn terminal line: the tailer resumes
    # from its offset and the session lands DONE, seen exactly once
    full = json.dumps(s01_r1[-1])
    with open(td / "r1" / "session-s01.jsonl", "a") as f:
        f.write(full[len(full) // 2:] + "\n")
    name = "r1/session-s01.jsonl"
    w._follow(str(td / name), states[name], offsets[name])
    rows = {r["session"]: r for r in w.merge_session_rows(states)}
    assert rows["s01"]["state"] == "DONE"
    assert rows["s01"]["events"] == 6

    # the CLI dir mode walks one level deep and skips fleet.jsonl
    import io
    buf = io.StringIO()
    assert w.watch_dir(str(td), once=True, out=buf) == 0
    out = buf.getvalue()
    assert "r0>r1" in out and out.count("s01") == 1
    assert "fleet" not in out                 # aggregate stream skipped


def test_gate_r09_r10_fleet_keys_and_migration_milestone(tmp_path):
    """ISSUE 16 gate fixture: the committed r09->r10 pair gates green
    with the fleet phase's latency/isolation keys riding the existing
    serve_load patterns; fleet_migrations_lost_total carries an
    any-increase gate (must stay 0) and migrated_reached_gap_frac a
    1.0 ratchet MILESTONE the committed artifact binds."""
    r09 = os.path.join(REPO, "BENCH_r09.json")
    r10 = os.path.join(REPO, "BENCH_r10.json")
    rep = regress.gate_paths(r09, r10)
    assert rep["ok"], rep["regressions"]
    ms = {r["metric"]: r for r in rep["milestones"]}
    mig = ms["fleet_serve_load.migration.migrated_reached_gap_frac"]
    assert mig["status"] == "met" and mig["milestone"] == 1.0

    # a later round LOSING a migrated session fails on the
    # any-increase gate even though the baseline value is 0
    lost = json.load(open(r10))
    lost["parsed"]["fleet_serve_load"]["migration"][
        "fleet_migrations_lost_total"] = 1
    lost_path = tmp_path / "BENCH_lost.json"
    lost_path.write_text(json.dumps(lost))
    rep2 = regress.gate_paths(r10, str(lost_path))
    assert not rep2["ok"]
    assert any("migrations_lost" in r["metric"]
               for r in rep2["regressions"])

    # fleet p99 regressing past +-25% fails via the serve_load
    # latency pattern (unanchored search covers fleet_serve_load)
    slow = json.load(open(r10))
    slow["parsed"]["fleet_serve_load"]["time_to_gap_p99_s"] *= 1.5
    slow_path = tmp_path / "BENCH_fleet_slow.json"
    slow_path.write_text(json.dumps(slow))
    rep3 = regress.gate_paths(r10, str(slow_path))
    assert not rep3["ok"]
    assert any(r["metric"] ==
               "fleet_serve_load.time_to_gap_p99_s"
               for r in rep3["regressions"])

    # ...and the bound migration milestone RATCHETS: a fleet round
    # where a migrated session misses its gap fails from then on
    miss = json.load(open(r10))
    miss["parsed"]["fleet_serve_load"]["migration"][
        "migrated_reached_gap_frac"] = 0.5
    miss_path = tmp_path / "BENCH_mig_miss.json"
    miss_path.write_text(json.dumps(miss))
    rep4 = regress.gate_paths(r10, str(miss_path))
    assert not rep4["ok"]
    assert any("migrated_reached_gap_frac" in r["metric"]
               for r in rep4["regressions"])


def test_gate_r10_r11_mesh_chaos_keys_and_reshard_milestone(tmp_path):
    """ISSUE 17 gate fixture: the committed r10->r11 pair gates green
    with the mesh_chaos phase's keys; mesh_reshards_lost_total carries
    an any-increase gate (a resharded run must never be lost) and
    reshard_reached_gap_frac a 1.0 ratchet MILESTONE — the resumed
    post-reshard wheel certifies the same gap as the fault-free run."""
    r10 = os.path.join(REPO, "BENCH_r10.json")
    r11 = os.path.join(REPO, "BENCH_r11.json")
    rep = regress.gate_paths(r10, r11)
    assert rep["ok"], rep["regressions"]
    ms = {r["metric"]: r for r in rep["milestones"]}
    resh = ms["mesh_chaos.reshard.reshard_reached_gap_frac"]
    assert resh["status"] == "met" and resh["milestone"] == 1.0

    # a later round LOSING a resharded run fails on the any-increase
    # gate even though the baseline value is 0
    lost = json.load(open(r11))
    lost["parsed"]["mesh_chaos"]["reshard"][
        "mesh_reshards_lost_total"] = 1
    lost_path = tmp_path / "BENCH_mesh_lost.json"
    lost_path.write_text(json.dumps(lost))
    rep2 = regress.gate_paths(r11, str(lost_path))
    assert not rep2["ok"]
    assert any("mesh_reshards_lost" in r["metric"]
               for r in rep2["regressions"])

    # ...and the bound reshard milestone RATCHETS: a chaos round where
    # the resumed wheel misses its gap target fails from then on
    miss = json.load(open(r11))
    miss["parsed"]["mesh_chaos"]["reshard"][
        "reshard_reached_gap_frac"] = 0.5
    miss_path = tmp_path / "BENCH_reshard_miss.json"
    miss_path.write_text(json.dumps(miss))
    rep3 = regress.gate_paths(r11, str(miss_path))
    assert not rep3["ok"]
    assert any("reshard_reached_gap_frac" in r["metric"]
               for r in rep3["regressions"])


# ---------------------------------------------------------------------------
# ISSUE 20: causal tracing + the SLO plane — tracecontext/spans/slo
# units, the committed golden fleet trace, the `trace`/`slo` CLI exit
# codes, first-class histogram metrics, the trace-id joins in
# analyze/watch, and the r12->r13 SLO gate fixture.
# ---------------------------------------------------------------------------
GOLDEN_FLEET = os.path.join(HERE, "fixtures",
                            "golden_fleet_trace.jsonl")


def test_tracecontext_mint_child_and_wire_roundtrip():
    from mpisppy_tpu.telemetry.tracecontext import TraceContext

    root = TraceContext.mint()
    assert len(root.trace_id) == 32 and len(root.span_id) == 16
    int(root.trace_id, 16), int(root.span_id, 16)
    assert root.parent_span_id == ""
    kid = root.child()
    assert kid.trace_id == root.trace_id
    assert kid.span_id != root.span_id
    assert kid.parent_span_id == root.span_id
    # wire round trip drops the parent edge (W3C traceparent carries
    # only the current position) but keeps trace + span
    back = TraceContext.from_traceparent(kid.to_traceparent())
    assert (back.trace_id, back.span_id) == (kid.trace_id, kid.span_id)
    # garbage never raises — the server mints instead
    for junk in (None, 42, "", "00-short-1234-01",
                 "00-" + "g" * 32 + "-" + "1" * 16 + "-01",
                 "00-" + "0" * 32 + "-" + "1" * 16 + "-01",
                 "00-" + "a" * 32 + "-" + "0" * 16 + "-01",
                 "a" * 32):
        assert TraceContext.from_traceparent(junk) is None


def test_bus_stamps_scoped_trace_and_per_emit_override():
    from mpisppy_tpu.telemetry.tracecontext import TraceContext

    got = []

    class Grab(telemetry.Sink):
        def handle(self, event):
            got.append(json.loads(event.to_json()))

    bus = telemetry.EventBus()
    bus.subscribe(Grab())
    bus.emit(telemetry.HUB_ITERATION, run="r", cyl="hub", hub_iter=0)
    root = TraceContext.mint()
    bus.set_trace(root)
    bus.emit(telemetry.HUB_ITERATION, run="r", cyl="hub", hub_iter=1)
    other = root.child()
    bus.emit(telemetry.HUB_ITERATION, run="r", cyl="hub", hub_iter=2,
             trace=other)
    # pre-trace rows carry NO trace keys (same schema, old rows valid)
    assert "trace_id" not in got[0] and "span_id" not in got[0]
    assert got[1]["trace_id"] == root.trace_id
    assert got[1]["span_id"] == root.span_id
    assert "parent_span_id" not in got[1]
    # per-emit override wins over the bus scope (shared-bus attribution)
    assert got[2]["span_id"] == other.span_id
    assert got[2]["parent_span_id"] == root.span_id


def _traced_serve_rows():
    """A hand-timed migrated-session trace: the bucket partition is
    checked against exact wall-clock arithmetic."""
    from mpisppy_tpu.telemetry.tracecontext import TraceContext

    root = TraceContext.mint()
    s1, mig, s2 = root.child(), root.child(), root.child()

    def row(t, kind, ctx, seq, **data):
        r = {"kind": kind, "seq": seq, "t_wall": t, "t_mono": t,
             "run": "run-t", "cyl": "serve",
             "trace_id": ctx.trace_id, "span_id": ctx.span_id,
             "data": data}
        if ctx.parent_span_id:
            r["parent_span_id"] = ctx.parent_span_id
        return r

    rows = [
        row(100.0, "span-start", root, 1, name="request",
            session="s01", tenant="acme", sla="latency"),
        row(100.2, "session-state", root, 2, state="ADMITTED",
            session="s01"),
        row(100.3, "session-state", root, 3, state="RUNNING",
            session="s01"),
        row(100.35, "span-start", s1, 4, name="segment",
            replica="r0"),
        row(100.9, "hub-iteration", s1, 5, iter=0),
        row(101.0, "hub-iteration", s1, 6, iter=1),
        row(101.1, "session-migrated", s1, 7, session="s01",
            from_replica="r0"),
        row(101.6, "span-start", mig, 8, name="migration",
            from_replica="r0"),
        row(101.8, "span-start", s2, 9, name="segment",
            replica="r1", restore=True),
        row(102.0, "hub-iteration", s2, 10, iter=2),
        row(102.3, "hub-iteration", s2, 11, iter=3),
        row(102.4, "session-state", root, 12, state="DONE",
            session="s01"),
        row(102.5, "slo-observation", root, 13, outcome="done",
            sla="latency", total_s=2.5),
    ]
    return root, rows


def test_spans_assemble_tree_and_critical_path_partition(tmp_path):
    from mpisppy_tpu.telemetry import spans

    root, rows = _traced_serve_rows()
    rep = spans.assemble(rows, root.trace_id)
    assert rep["schema"] == spans.TRACE_SCHEMA
    assert rep["orphans"] == []
    assert [sp["name"] for sp in rep["spans"]] \
        == ["request", "segment", "migration", "segment"]
    assert [sp["depth"] for sp in rep["spans"]] == [0, 1, 1, 1]
    assert rep["migrated_segments"] == 1
    cp = rep["critical_path"]
    # the buckets PARTITION the [first, last] wall timeline exactly
    assert cp["total_s"] == pytest.approx(2.5)
    assert sum(cp["buckets"].values()) == pytest.approx(2.5)
    assert cp["buckets"]["queue-wait"] == pytest.approx(0.2)
    assert cp["buckets"]["admission"] == pytest.approx(0.15)
    assert cp["buckets"]["iter0"] == pytest.approx(0.75)
    assert cp["buckets"]["hub-sync"] == pytest.approx(0.4)
    assert cp["buckets"]["migration-gap"] == pytest.approx(0.8)
    assert cp["buckets"]["solve"] == pytest.approx(0.2)
    # ...and the sum equals the client-observed latency (coverage 1.0)
    assert cp["client_total_s"] == pytest.approx(2.5)
    assert cp["coverage"] == pytest.approx(1.0)
    text = spans.render_trace(rep)
    assert "migration" in text and "replica=r1" in text
    assert "ORPHAN" not in text
    # a dropped propagation hop (the root's rows vanish) is an orphan
    torn = [r for r in rows if r["span_id"] != root.span_id]
    rep2 = spans.assemble(torn, root.trace_id)
    assert len(rep2["orphans"]) == 3
    assert "ORPHAN SPANS: 3" in spans.render_trace(rep2)
    # torn-tail safety: a half-written final line is skipped
    path = tmp_path / "t.jsonl"
    _jl(path, rows, torn_last=True)
    rep3 = spans.assemble_path(str(path))
    assert rep3["events"] == len(rows) - 1
    assert rep3["orphans"] == []


def test_spans_resolve_trace_id_prefixes_and_errors(tmp_path):
    from mpisppy_tpu.telemetry import spans

    _, rows_a = _traced_serve_rows()
    _, rows_b = _traced_serve_rows()
    for r in rows_b:
        r["t_wall"] += 10.0
    both = rows_a + rows_b
    ta = rows_a[0]["trace_id"]
    tb = rows_b[0]["trace_id"]
    assert spans.trace_ids(both) == [ta, tb]
    assert spans.resolve_trace_id(rows_a, None) == ta
    assert spans.resolve_trace_id(both, "last") == tb
    # a unique prefix resolves; ambiguity and no-rows are typed errors
    n = next(i for i in range(1, 33) if ta[:i] != tb[:i])
    assert spans.resolve_trace_id(both, ta[:n]) == ta
    with pytest.raises(ValueError, match="multiple traces"):
        spans.resolve_trace_id(both, None)
    with pytest.raises(ValueError, match="matches 0"):
        spans.resolve_trace_id(both, "zz")
    with pytest.raises(ValueError, match="no trace-stamped"):
        spans.resolve_trace_id([{"kind": "run-start"}], None)


def test_golden_fleet_trace_assembles_zero_orphan(tmp_path):
    """The committed fixture — one live-migrated session recorded from
    the fleet chaos storm — assembles into ONE zero-orphan causal tree
    whose critical path covers the client-observed latency within the
    5% acceptance line, with the migration span on the path."""
    from mpisppy_tpu.telemetry import spans

    rep = spans.assemble_path(GOLDEN_FLEET)
    assert rep["schema"] == spans.TRACE_SCHEMA
    assert rep["orphans"] == []
    names = [sp["name"] for sp in rep["spans"]]
    assert names[0] == "request"
    assert "migration" in names
    assert names.count("segment") == 2
    assert rep["migrated_segments"] == 1
    cp = rep["critical_path"]
    assert cp["buckets"]["migration-gap"] > 0
    assert sum(cp["buckets"].values()) == pytest.approx(cp["total_s"])
    assert cp["client_total_s"] is not None
    assert abs(cp["coverage"] - 1.0) <= 0.05
    # CLI: a clean tree exits 0, an orphaned one exits 2
    out = subprocess.run(CLI + ["trace", "--trace-jsonl", GOLDEN_FLEET,
                                "--json"],
                         capture_output=True, text=True, cwd=REPO,
                         timeout=120, env=ENV)
    assert out.returncode == 0, out.stderr[-2000:]
    assert json.loads(out.stdout)["trace_id"] == rep["trace_id"]
    rows = spans.load_rows(GOLDEN_FLEET)
    root = next(sp["span_id"] for sp in rep["spans"]
                if sp["name"] == "request")
    torn = tmp_path / "orphaned.jsonl"
    _jl(torn, [r for r in rows if r.get("span_id") != root])
    out2 = subprocess.run(CLI + ["trace", "--trace-jsonl", str(torn)],
                          capture_output=True, text=True, cwd=REPO,
                          timeout=120, env=ENV)
    assert out2.returncode == 2
    assert "ORPHAN" in out2.stdout


def test_session_trace_adoption_and_slo_observation(tmp_path):
    """Session adopts the client's traceparent, stamps every row of its
    per-session trace with it, and settles exactly one slo-observation
    sample carrying the client-joinable total."""
    from mpisppy_tpu.serve.protocol import SubmitRequest
    from mpisppy_tpu.serve.session import Session
    from mpisppy_tpu.telemetry import spans
    from mpisppy_tpu.telemetry.tracecontext import TraceContext

    minted = TraceContext.mint()
    spec = SubmitRequest(tenant="acme", sla="latency", model="farmer",
                         num_scens=3,
                         traceparent=minted.to_traceparent())
    s = Session(spec, outbox=lambda m: None,
                trace_dir=str(tmp_path))
    assert s.trace.trace_id == minted.trace_id
    s.transition("ADMITTED")
    s.transition("RUNNING")
    s.begin_segment()
    assert s.segment.parent_span_id == s.trace.span_id
    s.end_segment()
    assert s.settle("done", rel_gap=0.004)
    rows = spans.load_rows(s.trace_path)
    assert rows and all(r.get("trace_id") == minted.trace_id
                        for r in rows)
    obs = [r for r in rows if r["kind"] == "slo-observation"]
    assert len(obs) == 1
    d = obs[0]["data"]
    assert d["outcome"] == "done" and d["sla"] == "latency"
    assert d["total_s"] > 0
    # the sample lands on the request ROOT span (not the segment)
    assert obs[0]["span_id"] == minted.span_id
    rep = spans.assemble(rows, minted.trace_id)
    assert rep["orphans"] == []
    assert [sp["name"] for sp in rep["spans"]][:2] \
        == ["request", "segment"]
    # a garbage traceparent never errors: the session mints instead
    s2 = Session(SubmitRequest(tenant="acme", sla="latency",
                               model="farmer", num_scens=3,
                               traceparent="garbage"),
                 outbox=lambda m: None)
    assert len(s2.trace.trace_id) == 32


def test_slo_evaluate_observations_classes_and_budgets():
    from mpisppy_tpu.telemetry import slo

    def ob(**d):
        return {"kind": "slo-observation", "data": d}

    rows = [
        ob(outcome="done", sla="latency", total_s=10.0),
        ob(outcome="done", sla="latency", total_s=20.0),   # over 15s
        ob(outcome="failed", sla="latency", total_s=3.0),
        ob(outcome="done", sla="throughput", total_s=50.0),
        # streams evaluate per WINDOW, not per session
        ob(outcome="done", sla="latency", total_s=4.0,
           steps_expected=4, steps=4),
        ob(outcome="failed", sla="latency", total_s=2.0,
           steps_expected=4, steps=2),
    ]
    rep = slo.evaluate_observations(rows)
    assert rep["schema"] == slo.SLO_SCHEMA
    lat = rep["slo"]["latency"]
    assert (lat["samples"], lat["bad"]) == (3, 2)
    assert lat["burn_rate"] == pytest.approx((2 / 3) / 0.05, rel=1e-3)
    assert not lat["ok"] and lat["budget_remaining"] == 0.0
    thr = rep["slo"]["throughput"]
    assert (thr["samples"], thr["bad"]) == (1, 0)
    assert thr["ok"] and thr["burn_rate"] == 0.0
    mpc = rep["slo"]["mpc"]
    assert (mpc["samples"], mpc["bad"]) == (8, 2)
    assert mpc["burn_rate"] == pytest.approx(0.25 / 0.10, rel=1e-3)
    assert not mpc["ok"]
    # absence of traffic is not a violation: zero samples burn nothing
    empty = slo.evaluate_observations([])
    assert all(r["samples"] == 0 and r["ok"] and r["burn_rate"] == 0.0
               for r in empty["slo"].values())
    text = slo.render_slo(rep)
    assert "VIOLATED" in text and "latency" in text


def test_slo_bench_evaluation_and_cli_exit_codes(tmp_path):
    """`telemetry slo --bench` on the committed r13 artifact is green;
    a synthetic budget-exhausting artifact exits 2."""
    from mpisppy_tpu.telemetry import regress, slo

    parsed = regress.load_artifact(os.path.join(REPO, "BENCH_r13.json"))
    rep = slo.evaluate_bench(parsed)
    assert set(rep["slo"]) == {"latency", "throughput", "mpc"}
    for row in rep["slo"].values():
        assert row["ok"] and row["burn_rate"] == 0.0
        assert row["samples"] > 0
    # the committed artifact's own slo sections match a re-evaluation
    assert parsed["slo"]["latency"]["burn_rate"] \
        == rep["slo"]["latency"]["burn_rate"]
    out = subprocess.run(CLI + ["slo", "--bench", "BENCH_r13.json"],
                         capture_output=True, text=True, cwd=REPO,
                         timeout=120, env=ENV)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "burn" in out.stdout
    # every window degraded: burn 10x the budget -> exit 2
    burned = {"device": "cpu", "parsed": {"mpc_stream": {"uc": {
        "steps": 4, "degraded_steps": 4, "step_latency_p99_s": 1.0}}}}
    bp = tmp_path / "burned.json"
    bp.write_text(json.dumps(burned))
    out2 = subprocess.run(CLI + ["slo", "--bench", str(bp)],
                          capture_output=True, text=True, cwd=REPO,
                          timeout=120, env=ENV)
    assert out2.returncode == 2
    assert "VIOLATED" in out2.stdout


def test_gate_r12_r13_slo_keys_and_burn_milestone(tmp_path):
    """ISSUE 20 gate fixture: the committed r12->r13 pair gates green
    with the per-class slo.*.burn_rate keys bound by the <= 1.0
    milestone; a synthetic burn-rate rise (or budget_remaining drop)
    on a committed artifact exits 2 — burn starts at 0, so ANY
    increase trips the relative gate."""
    r12 = os.path.join(REPO, "BENCH_r12.json")
    r13 = os.path.join(REPO, "BENCH_r13.json")
    rep = regress.gate_paths(r12, r13)
    assert rep["ok"], rep["regressions"]
    ms = {r["metric"]: r for r in rep["milestones"]
          if ".burn_rate" in r["metric"]}
    assert "slo.latency.burn_rate" in ms
    assert "slo.mpc.burn_rate" in ms
    assert all(r["status"] == "met" and r["milestone"] == 1.0
               for r in ms.values())

    slip = json.load(open(r13))
    slip["parsed"]["slo"]["latency"]["burn_rate"] = 0.5
    slip["parsed"]["slo"]["latency"]["budget_remaining"] = 0.5
    slip_path = tmp_path / "BENCH_burn_slip.json"
    slip_path.write_text(json.dumps(slip))
    rep2 = regress.gate_paths(r13, str(slip_path))
    assert not rep2["ok"]
    failed = {r["metric"] for r in rep2["regressions"]}
    assert "slo.latency.burn_rate" in failed
    assert "slo.latency.budget_remaining" in failed
    from mpisppy_tpu.telemetry.__main__ import main as tel_main
    assert tel_main(["gate", r12, r13]) == 0
    assert tel_main(["gate", r13, str(slip_path)]) == 2


def test_histogram_quantiles_and_prom_exposition():
    from mpisppy_tpu.telemetry import metrics as m

    h = m.Histogram()
    assert h.quantile(0.5) is None
    for v in (0.02, 0.03, 0.04, 0.2, 0.3, 0.4, 8.0, 9.0):
        h.observe(v)
    assert h.count == 8 and h.sum == pytest.approx(17.99)
    p50, p99 = h.quantile(0.5), h.quantile(0.99)
    assert 0.025 < p50 <= 0.5
    assert p99 >= 5.0                      # lands in the 5..10 bucket
    assert h.quantile(0.0) <= p50 <= p99
    # registry-held histograms render the Prometheus histogram model
    reg = m.MetricsRegistry()
    reg.observe("mpc_step_latency_hist_s", 0.3, stream="uc")
    reg.observe("mpc_step_latency_hist_s", 2.0, stream="uc")
    text = reg.render_prom()
    assert "# TYPE mpc_step_latency_hist_s histogram" in text
    assert 'mpc_step_latency_hist_s_bucket{stream="uc",le="+Inf"} 2' \
        in text
    assert 'mpc_step_latency_hist_s_count{stream="uc"} 2' in text
    assert "mpc_step_latency_hist_s_sum" in text
    snap = reg.to_snapshot()
    assert snap["histograms"]['mpc_step_latency_hist_s{stream="uc"}'][
        "count"] == 2


def test_analyze_trace_dir_joins_segments_on_trace_id(tmp_path):
    """Satellite (a): a migrated session's segments carry DIFFERENT run
    ids on different replicas — the (run, sid) heuristic cannot join
    them, the causal trace id does; the report disclosed the join."""
    from mpisppy_tpu.telemetry.tracecontext import TraceContext

    root = TraceContext.mint()
    td = tmp_path / "traces"
    (td / "r0").mkdir(parents=True)
    (td / "r1").mkdir()

    def row(t, kind, run, **data):
        return {"kind": kind, "run": run, "t_wall": t, "t_mono": t,
                "trace_id": root.trace_id, "span_id": root.span_id,
                "data": data}

    _jl(td / "r0" / "session-s01.jsonl", [
        row(100.0, "run-start", "run-a", hub_class="PHHub",
            num_spokes=2),
        row(100.5, "session-state", "run-a", session="s01",
            state="RUNNING", replica="r0"),
        row(101.0, "session-migrated", "run-a", session="s01",
            from_replica="r0"),
    ])
    _jl(td / "r1" / "session-s01.jsonl", [
        row(102.0, "run-start", "run-b", hub_class="PHHub",
            num_spokes=2),
        row(102.5, "session-state", "run-b", session="s01",
            state="RUNNING", replica="r1"),
        row(103.0, "run-end", "run-b", reason="converged",
            rel_gap=0.004),
    ])
    rep = an.analyze_path(str(td))
    assert rep["run"]["migrated_segments"] == 1
    assert sorted(rep["run"]["segment_files"]) == [
        os.path.join("r0", "session-s01.jsonl"),
        os.path.join("r1", "session-s01.jsonl")]
    assert rep["run"]["exit"]["reason"] == "converged"
    assert "migrated segments 1" in an.render_report(rep)


def test_watch_joins_segments_on_trace_id_and_burn_footer(tmp_path):
    """Satellite (b): watch joins migrated segments on the causal trace
    id even across run-id changes, folds EVERY step latency into the
    histogram-backed p50 (bounded retention), and renders the live SLO
    burn-rate footer from slo-observation rows."""
    from mpisppy_tpu.telemetry import watch as w
    from mpisppy_tpu.telemetry.tracecontext import TraceContext

    root = TraceContext.mint()
    td = tmp_path / "traces"
    (td / "r0").mkdir(parents=True)
    (td / "r1").mkdir()

    def row(t, kind, run, **data):
        return {"kind": kind, "run": run, "t_wall": t, "t_mono": t,
                "trace_id": root.trace_id, "span_id": root.span_id,
                "data": data}

    steps_r0 = [row(100.5 + k / 10, "mpc-step", "run-a", step=k,
                    warm=k > 0, latency_s=0.1)
                for k in range(100)]
    _jl(td / "r0" / "session-s01.jsonl", [
        row(100.0, "session-state", "run-a", session="s01",
            tenant="acme", sla="latency", state="RUNNING",
            replica="r0"),
        *steps_r0,
        row(111.0, "session-migrated", "run-a", session="s01",
            from_replica="r0", migrations=1),
    ])
    steps_r1 = [row(111.5 + k / 10, "mpc-step", "run-b", step=100 + k,
                    warm=True, latency_s=0.1) for k in range(4)]
    _jl(td / "r1" / "session-s01.jsonl", [
        row(111.4, "session-state", "run-b", session="s01",
            tenant="acme", sla="latency", state="RUNNING",
            replica="r1"),
        *steps_r1,
        row(112.0, "session-state", "run-b", session="s01",
            state="DONE", replica="r1"),
        row(112.1, "slo-observation", "run-b", outcome="done",
            sla="latency", session="s01", total_s=12.1),
    ])
    states: dict = {}
    for name in ("r0/session-s01.jsonl", "r1/session-s01.jsonl"):
        st = states.setdefault(name, w.WatchState())
        w._follow(str(td / name), st, 0)
    rows = w.merge_session_rows(states)
    assert len(rows) == 1                   # trace id beat the run ids
    assert rows[0]["chain"] == ["r0", "r1"]
    assert rows[0]["state"] == "DONE"
    assert rows[0]["mpc_steps"] == 104
    # histogram p50 covers ALL 100 windows while the raw display tail
    # retains only the last 64 — bounded memory, unbounded coverage
    st0 = states["r0/session-s01.jsonl"]
    assert st0.mpc_hist.count == 100
    assert len(st0.mpc_latencies) == 64
    assert rows[0]["step_p50"] == pytest.approx(0.1, rel=0.5)
    table = w.render_tenant_table(states)
    assert "r0>r1" in table
    assert "slo latency: burn 0.00" in table
    # slo-observation retention is capped too
    st1 = states["r1/session-s01.jsonl"]
    for _ in range(300):
        st1.feed({"kind": "slo-observation", "run": "run-b",
                     "data": {"outcome": "done", "sla": "latency",
                              "total_s": 1.0}})
    assert len(st1.slo_obs) == 256
