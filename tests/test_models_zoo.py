# Model zoo: netdes / sizes / uc / aircond generators — EF oracle
# checks vs scipy.linprog plus PH end-to-end convergence.
import numpy as np
import pytest

from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import aircond, netdes, sizes, uc
from mpisppy_tpu.ops import pdhg
from mpisppy_tpu.ops.sparse import EllMatrix

from test_farmer_ef_ph import scipy_ef_solve
from test_hydro import scipy_ef_solve_tree


def _ph(b, rho=1.0, iters=120, conv=5e-2, windows=8, tol=1e-7):
    opts = ph_mod.PHOptions(
        default_rho=rho, max_iterations=iters, conv_thresh=conv,
        subproblem_windows=windows,
        pdhg=pdhg.PDHGOptions(tol=tol, restart_period=40))
    algo = ph_mod.PH(opts, b)
    return algo, algo.ph_main()


# ---------------- netdes ----------------

def _netdes_specs(num=4):
    inst = netdes.synthetic_instance(n_nodes=8, num_scens=num, seed=3)
    names = netdes.scenario_names_creator(num)
    return [netdes.scenario_creator(nm, instance=inst, lp_relax=True)
            for nm in names]


def test_netdes_ef_matches_scipy():
    specs = _netdes_specs(4)
    sobj, _ = scipy_ef_solve(specs)
    b = batch_mod.from_specs(specs)
    assert isinstance(b.qp.A, EllMatrix)   # sparse path engaged
    st = pdhg.solve(b.qp, pdhg.PDHGOptions(tol=1e-6, max_iters=200_000,
                                           restart_period=40))
    assert bool(st.done.all())
    # per-scenario independent solves lower-bound the EF (no nonant ties)
    ws = float(b.expectation(b.objective(st.x)))
    assert ws <= sobj + abs(sobj) * 1e-3


def test_netdes_ph_converges():
    specs = _netdes_specs(4)
    sobj, _ = scipy_ef_solve(specs)
    b = batch_mod.from_specs(specs)
    algo, (conv, eobj, tb) = _ph(b, rho=300.0, iters=200, conv=1e-2)
    assert tb <= sobj + abs(sobj) * 1e-3
    assert conv <= 1e-2
    assert eobj >= tb - abs(tb) * 1e-3


def test_netdes_dat_parser_roundtrip(tmp_path):
    # synthesize a tiny .dat in the reference format and parse it back
    content = """/ header comment
An instance of the stochastic network flow problem.
/ more header
+
3
0.5
100
0,1,1;0,0,1;1,0,0
0,10,20;0,0,30;40,0,0
2
0.5,0.5
--Scenarios--
0,1,2;0,0,3;4,0,0
0,5,6;0,0,7;8,0,0
-2,2,0
------------- End of Scenario k = 0 -------
0,2,3;0,0,4;5,0,0
0,6,7;0,0,8;9,0,0
-3,3,0
"""
    f = tmp_path / "net.dat"
    f.write_text(content)
    data = netdes.parse_dat(str(f))
    assert data["n"] == 3 and len(data["scens"]) == 2
    assert data["scens"][0]["b"][0] == -2.0
    assert data["scens"][1]["u"][2, 0] == 9.0
    specs = [netdes.scenario_creator(f"Scenario{k}", instance=data,
                                     lp_relax=True) for k in range(2)]
    b = batch_mod.from_specs(specs)
    assert b.num_nonants == 4   # 4 arcs in the toy adjacency


# ---------------- sizes ----------------

def test_sizes_demand_multipliers_match_reference_data():
    # SIZES3 scenario files: D2 = {0.7, 1.0, 1.3} * D1
    assert sizes.demand_multiplier(1, 3) == pytest.approx(0.7)
    assert sizes.demand_multiplier(2, 3) == pytest.approx(1.0)
    assert sizes.demand_multiplier(3, 3) == pytest.approx(1.3)


def test_sizes_ef_and_ph():
    names = sizes.scenario_names_creator(3)
    specs = [sizes.scenario_creator(nm, scenario_count=3, lp_relax=True)
             for nm in names]
    sobj, _ = scipy_ef_solve(specs)
    assert sobj > 0  # production cost, minimization
    b = batch_mod.from_specs(specs)
    algo, (conv, eobj, tb) = _ph(b, rho=0.5, iters=200, conv=1e-2)
    assert tb <= sobj * (1 + 1e-3)
    assert conv <= 1e-2
    # PH expected objective near the EF optimum
    assert eobj == pytest.approx(sobj, rel=2e-2)


# ---------------- uc ----------------

def test_uc_shared_sparse_structure():
    inst = uc.synthetic_instance(4, 12, seed=1)
    names = uc.scenario_names_creator(3)
    specs = [uc.scenario_creator(nm, instance=inst, num_scens=3)
             for nm in names]
    b = batch_mod.from_specs(specs)
    # deterministic A: ONE shared ELL block (no scenario axis on vals)
    assert isinstance(b.qp.A, EllMatrix)
    assert b.qp.A.vals.ndim == 2
    assert b.num_nonants == 4 * 12


def test_uc_ef_and_ph():
    inst = uc.synthetic_instance(4, 12, seed=1)
    names = uc.scenario_names_creator(3)
    specs = [uc.scenario_creator(nm, instance=inst, num_scens=3)
             for nm in names]
    sobj, xref = scipy_ef_solve(specs)
    b = batch_mod.from_specs(specs)
    # rho ~ startup-cost scale: the min-up/down + startup structure added
    # in round 3 stiffens the commitment consensus (rho=50 stalls ~2e-2)
    algo, (conv, eobj, tb) = _ph(b, rho=200.0, iters=300, conv=1e-2,
                                 windows=10)
    assert tb <= sobj * (1 + 1e-3)
    assert conv <= 1e-2
    assert eobj == pytest.approx(sobj, rel=2e-2)


def test_uc_demand_seeded_and_distinct():
    inst = uc.synthetic_instance(4, 12, seed=1)
    d0 = uc.scenario_demand(inst, 0)
    d0b = uc.scenario_demand(inst, 0)
    d1 = uc.scenario_demand(inst, 1)
    np.testing.assert_array_equal(d0, d0b)
    assert not np.array_equal(d0, d1)
    assert (d0 > 0).all()


# ---------------- aircond ----------------

def test_aircond_demand_walk_shares_nodes():
    bfs = (2, 2)
    # scenarios 0 and 1 share the stage-2 node (same first branch)
    d0 = aircond.demands_for_scenario(0, bfs)
    d1 = aircond.demands_for_scenario(1, bfs)
    d2 = aircond.demands_for_scenario(2, bfs)
    assert d0[0] == d1[0] == d2[0] == 200.0
    assert d0[1] == d1[1]          # same stage-2 node
    assert d0[1] != d2[1]          # different branch
    assert d0[2] != d1[2]          # different leaves
    assert ((d0 >= 0.0) & (d0 <= 400.0)).all()


def test_aircond_ef_and_multistage_ph():
    bfs = (2, 2)
    names = aircond.scenario_names_creator(4)
    specs = [aircond.scenario_creator(nm, branching_factors=bfs)
             for nm in names]
    tree = aircond.make_tree(bfs)
    sobj, _ = scipy_ef_solve_tree(specs, tree)
    b = batch_mod.from_specs(specs, tree=tree)
    assert b.tree.num_nodes == 3   # ROOT + 2 stage-2 nodes
    algo, (conv, eobj, tb) = _ph(b, rho=1.0, iters=200, conv=1e-2)
    assert tb <= sobj + 1.0
    assert conv <= 1e-2
    assert eobj == pytest.approx(sobj, rel=2e-2)
    # nonant structure: 2 slots per non-leaf stage
    assert b.num_nonants == 4


def test_aircond_honest_inner_multistage_wheel():
    """VERDICT r5 #8 straggler (ISSUE 7 satellite): the hydro-style
    honest-inner validity check on an aircond multistage wheel.  The
    all-stages-fixed x-bar recourse trap was only proven fatal on hydro
    (uncompensated infeasibility published BELOW the EF optimum); this
    pins the published aircond inner bound to being a TRUE attainable
    upper bound against an independent scipy ground truth."""
    from mpisppy_tpu.algos import fused_wheel as fw
    from mpisppy_tpu.algos.ef import build_ef
    from mpisppy_tpu.cylinders import PHHub
    from mpisppy_tpu.cylinders.spoke import EFOuterBound, EFXhatInnerBound
    from mpisppy_tpu.spin_the_wheel import WheelSpinner

    bfs = (2, 2)
    names = aircond.scenario_names_creator(4)
    specs = [aircond.scenario_creator(nm, branching_factors=bfs)
             for nm in names]
    tree = aircond.make_tree(bfs)
    # oracle: exact EF optimum from scipy.linprog (independent of
    # every code path under test)
    opt, _ = scipy_ef_solve_tree(specs, tree)

    batch = batch_mod.from_specs(specs, tree=tree)
    efp = build_ef(specs, tree=tree)
    opts = ph_mod.PHOptions(default_rho=1.0, max_iterations=60,
                            conv_thresh=0.0, subproblem_windows=8,
                            pdhg=pdhg.PDHGOptions(tol=1e-6))
    hub = {"hub_class": PHHub, "opt_class": fw.FusedPH,
           "opt_kwargs": {"options": opts, "batch": batch},
           "hub_kwargs": {"options": {"rel_gap": 1e-2}}}
    spokes = [
        {"spoke_class": EFOuterBound,
         "opt_kwargs": {"options": {"ef_problem": efp, "n_windows": 30}}},
        {"spoke_class": EFXhatInnerBound,
         "opt_kwargs": {"options": {"ef_problem": efp, "n_windows": 30}}},
    ]
    ws = WheelSpinner(hub, spokes).spin()
    inner, outer = ws.BestInnerBound, ws.BestOuterBound
    assert np.isfinite(inner) and np.isfinite(outer)
    # the published inner bound must be ATTAINABLE: >= the true
    # optimum (up to first-order compensation slack), never below it
    slack = 5e-3 * max(1.0, abs(opt))
    assert inner >= opt - slack
    assert outer <= opt + slack
    # and the pair still certifies a tight bracket
    assert (inner - outer) / abs(inner) <= 1e-2 + 1e-6
