# Elastic mesh fault domain (ISSUE 17; mpisppy_tpu/parallel/elastic.py,
# docs/resilience.md): host membership ladder (UP -> SUSPECT -> sticky
# DEAD with epochs), the MeshFault chaos seams, the bounded hub
# harvest (typed MeshDegraded, never a hang), checkpoint re-shard
# adaptation, survivor re-partitioning with zero-probability pad
# lanes, the watchdog shrink rung, and checkpoint-directory
# durability (fsync after rename).  The end-to-end reshard round trip
# lives in tests/test_mesh_chaos.py; the multi-process gloo version in
# tests/test_multihost.py.
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpisppy_tpu.parallel import elastic, mesh as mesh_mod
from mpisppy_tpu.resilience import FaultPlan, MeshFault, PreemptionError
from mpisppy_tpu.telemetry import EventBus
from mpisppy_tpu.telemetry import metrics as _metrics

pytestmark = pytest.mark.chaos


class _Cap:
    def __init__(self):
        self.events = []

    def handle(self, event):
        self.events.append(event)

    def kinds(self):
        return [e.kind for e in self.events]


# ---------------------------------------------------------------------------
# membership: the fleet health ladder applied to mesh hosts
# ---------------------------------------------------------------------------
def test_membership_ladder_suspect_then_dead_sticky():
    cap = _Cap()
    bus = EventBus()
    bus.subscribe(cap)
    mm = elastic.MeshMembership(3, dead_after=2, bus=bus, run="t")
    assert mm.state(1) == elastic.UP and mm.epoch == 0
    assert mm.observe(1, fresh=False) == elastic.SUSPECT
    assert mm.live_hosts() == [0, 1, 2]  # suspicion alone never reshards
    assert mm.observe(1, fresh=False) == elastic.DEAD
    assert mm.dead_hosts() == [1] and mm.live_hosts() == [0, 2]
    # sticky: a zombie's late beat must NOT resurrect it (fencing)
    assert mm.observe(1, fresh=True) is None
    assert mm.state(1) == elastic.DEAD
    assert mm.epoch == 2
    states = [e.data["state"] for e in cap.events
              if e.kind == "mesh-state"]
    assert states == ["SUSPECT", "DEAD"]


def test_membership_partition_heals_without_reshard():
    cap = _Cap()
    bus = EventBus()
    bus.subscribe(cap)
    mm = elastic.MeshMembership(2, dead_after=3, bus=bus, run="t")
    mm.observe(1, fresh=False)
    assert mm.state(1) == elastic.SUSPECT
    assert mm.observe(1, fresh=True) == elastic.UP
    healed = [e for e in cap.events if e.kind == "mesh-state"
              and e.data["reason"] == "partition-healed"]
    assert len(healed) == 1
    # epoch moved (two transitions) but nobody died: no reshard signal
    assert mm.epoch == 2 and mm.dead_hosts() == []


def test_membership_beacon_files(tmp_path):
    d = str(tmp_path)
    writer = elastic.MeshMembership(2, dead_after=2, self_host=1,
                                    beacon_dir=d)
    poller = elastic.MeshMembership(2, dead_after=2, self_host=0,
                                    beacon_dir=d)
    writer.beat(1)
    assert os.path.exists(os.path.join(d, "host1.beat"))
    assert poller.poll() == [] and poller.state(1) == elastic.UP
    # no new beat: the same counter is stale on the next two sweeps
    assert poller.poll() == []
    assert poller.state(1) == elastic.SUSPECT
    assert poller.poll() == [1]
    assert poller.state(1) == elastic.DEAD
    # gauges track the poller's view
    assert _metrics.REGISTRY.get("mesh_hosts_up") == 1.0


def test_partition_seam_suppresses_beacon(tmp_path):
    d = str(tmp_path)
    plan = FaultPlan(seed=0, meshes=(
        MeshFault("partition", host=1, at_beats=(1, 2)),))
    mm = elastic.MeshMembership(2, dead_after=5, self_host=1,
                                beacon_dir=d)
    assert mm.beat(1, plan=plan)           # beat 0: delivered
    assert not mm.beat(1, plan=plan)       # beats 1, 2: suppressed
    assert not mm.beat(1, plan=plan)
    assert mm.beat(1, plan=plan)           # beat 3: window over
    with open(os.path.join(d, "host1.beat")) as f:
        assert int(f.read()) == 3
    assert ("mesh", "partition host1@beat1") in plan.fired


# ---------------------------------------------------------------------------
# MeshFault seams on the plan
# ---------------------------------------------------------------------------
def test_mesh_fault_validates_kind():
    with pytest.raises(ValueError):
        MeshFault("meteor")


def test_host_lost_seam_fires_once():
    plan = FaultPlan(seed=1, meshes=(
        MeshFault("host_lost", host=1, at_iters=(3,)),))
    assert plan.armed
    assert plan.mesh_lost_host(2) is None
    assert plan.mesh_lost_host(3) == 1
    assert plan.mesh_lost_host(3) is None   # fired once
    assert plan.mesh_lost_host(4) is None
    assert ("mesh", "host_lost host1 iter3") in plan.fired


def test_straggler_seam_fires_once_per_iteration():
    plan = FaultPlan(seed=1, meshes=(
        MeshFault("straggler", at_iters=(5,), delay_s=0.25),))
    assert plan.mesh_harvest_delay(4) == 0.0
    assert plan.mesh_harvest_delay(5) == 0.25
    # a resumed run re-executing iter 5 must not re-straggle (the
    # injected collective was transiently slow — a re-trip would
    # livelock the elastic runner into its max_reshards budget)
    assert plan.mesh_harvest_delay(5) == 0.0


def test_torn_harvest_seam_fires_once_per_iteration():
    plan = FaultPlan(seed=1, meshes=(
        MeshFault("torn_harvest", at_iters=(2,)),))
    assert not plan.mesh_torn_harvest(1)
    assert plan.mesh_torn_harvest(2)
    assert not plan.mesh_torn_harvest(2)


# ---------------------------------------------------------------------------
# the bounded harvest: result, typed error, or re-fetch — never a hang
# ---------------------------------------------------------------------------
def test_harvest_deadline_trips_typed_mesh_degraded():
    cap = _Cap()
    bus = EventBus()
    bus.subscribe(cap)
    before = _metrics.REGISTRY.get("mesh_stragglers_total")
    rt = elastic.MeshRuntime(deadline_s=0.05, bus=bus, run="t")
    with pytest.raises(elastic.MeshDegraded) as ei:
        rt.harvest(lambda: (time.sleep(5.0), np.ones(3))[1], hub_iter=7)
    assert ei.value.reason == "straggler-deadline"
    assert ei.value.hub_iter == 7
    assert isinstance(ei.value, PreemptionError)  # the unwind contract
    assert _metrics.REGISTRY.get("mesh_stragglers_total") == before + 1
    ev = [e for e in cap.events if e.kind == "mesh-straggler"]
    assert ev and ev[0].data["mode"] == "deadline"


def test_harvest_straggler_under_deadline_survives():
    plan = FaultPlan(seed=2, meshes=(
        MeshFault("straggler", at_iters=(1,), delay_s=0.02),))
    rt = elastic.MeshRuntime(plan=plan, deadline_s=5.0)
    vals = rt.harvest(lambda: np.arange(3.0), hub_iter=1)
    np.testing.assert_array_equal(vals, np.arange(3.0))
    assert ("mesh", "straggler +0.02s iter1") in plan.fired


def test_harvest_torn_transfer_refetches_intact_value():
    cap = _Cap()
    bus = EventBus()
    bus.subscribe(cap)
    before = _metrics.REGISTRY.get("mesh_torn_harvests_total")
    plan = FaultPlan(seed=2, meshes=(
        MeshFault("torn_harvest", at_iters=(4,)),))
    rt = elastic.MeshRuntime(plan=plan, bus=bus, run="t")
    vals = rt.harvest(lambda: np.arange(4.0), hub_iter=4)
    # the tear NaN'd the transfer; the device value was intact and the
    # synchronous re-fetch recovered it
    np.testing.assert_array_equal(vals, np.arange(4.0))
    assert _metrics.REGISTRY.get("mesh_torn_harvests_total") == before + 1
    ev = [e for e in cap.events if e.kind == "mesh-straggler"]
    assert ev and ev[0].data["mode"] == "torn"


def test_harvest_genuinely_nonfinite_passes_through():
    # both fetches non-finite: NOT a tear — the hub's own bound guards
    # own this case, the mesh must not mask it
    before = _metrics.REGISTRY.get("mesh_torn_harvests_total")
    rt = elastic.MeshRuntime()
    vals = rt.harvest(lambda: np.array([np.nan, 1.0]), hub_iter=0)
    assert np.isnan(vals[0])
    assert _metrics.REGISTRY.get("mesh_torn_harvests_total") == before


def test_harvest_host_lost_raises_and_fences():
    cap = _Cap()
    bus = EventBus()
    bus.subscribe(cap)
    plan = FaultPlan(seed=3, meshes=(
        MeshFault("host_lost", host=1, at_iters=(6,)),))
    mm = elastic.MeshMembership(2, bus=bus, run="t")
    rt = elastic.MeshRuntime(mm, plan=plan, bus=bus, run="t")
    assert rt.harvest(lambda: np.zeros(2), hub_iter=5).shape == (2,)
    with pytest.raises(elastic.MeshDegraded) as ei:
        rt.harvest(lambda: np.zeros(2), hub_iter=6)
    assert ei.value.reason == "host-lost" and ei.value.host == 1
    assert mm.state(1) == elastic.DEAD
    lost = [e for e in cap.events if e.kind == "mesh-host-lost"]
    assert lost and lost[0].data["survivors"] == [0]


# ---------------------------------------------------------------------------
# survivor device sets + checkpoint re-shard adaptation
# ---------------------------------------------------------------------------
def test_device_groups_and_survivors():
    devs = jax.devices()
    groups = elastic.device_groups(devs, 4)
    assert [len(g) for g in groups] == [2, 2, 2, 2]
    surv = elastic.survivor_devices(devs, 4, dead_hosts=[1])
    assert len(surv) == 6
    assert surv == groups[0] + groups[2] + groups[3]


def test_adapt_checkpoint_arrays_repads_scenario_leaves():
    arrays = {
        "leaf0": np.arange(8 * 2, dtype=np.float32).reshape(8, 2),
        "leaf1": np.arange(8.0),              # scenario vector
        "leaf2": np.arange(4.0),              # not scenario-major
        "bounds": np.array([1.0, 2.0]),       # meta: untouched
    }
    out = elastic.adapt_checkpoint_arrays(arrays, num_real=5,
                                          s_old=8, s_new=6)
    assert out["leaf0"].shape == (6, 2)
    # rows 0..4 are the real prefix; row 5 clones the LAST REAL row
    np.testing.assert_array_equal(out["leaf0"][:5], arrays["leaf0"][:5])
    np.testing.assert_array_equal(out["leaf0"][5], arrays["leaf0"][4])
    assert out["leaf1"].shape == (6,)
    np.testing.assert_array_equal(out["leaf2"], arrays["leaf2"])
    np.testing.assert_array_equal(out["bounds"], arrays["bounds"])
    # identity when the axis is unchanged
    assert elastic.adapt_checkpoint_arrays(arrays, 5, 8, 8) is arrays


# ---------------------------------------------------------------------------
# re-partitioning: pad lanes carry ZERO probability mass (satellite of
# ISSUE 17; docs/scengen.md reshard-invariance contract)
# ---------------------------------------------------------------------------
def test_repartition_zero_probability_pads():
    from mpisppy_tpu import scengen
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.scengen.virtual import repartition

    prog = farmer.scenario_program(13, seed=0)
    vb = scengen.virtual_batch(prog)           # S = 13, no pad
    rp = repartition(vb, 6)                    # survivor count: 6 -> S=18
    assert rp.num_scenarios == 18 and rp.num_real == 13
    p = np.asarray(rp.p)
    np.testing.assert_allclose(p[:13], np.asarray(vb.p)[:13])
    np.testing.assert_array_equal(p[13:], np.zeros(5))
    assert float(p.sum()) == pytest.approx(float(np.asarray(vb.p).sum()))


def test_shard_batch_pad_true_uneven_survivors_value_identical():
    """S=13 real scenarios on a shrunk 6-device survivor mesh: pad=True
    re-pads to 18 with zero-probability lanes, and every p-weighted
    reduction matches the 8-device layout up to f32 reduction-order
    noise (the tolerances of tests/test_sharding.py's layout-parity
    test) — the pad lanes contribute nothing."""
    from mpisppy_tpu import scengen
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.models import farmer

    prog = farmer.scenario_program(13, seed=0)
    opts = ph_mod.PHOptions(subproblem_windows=2, iter0_windows=20)
    rho = jnp.ones(3, jnp.float32)

    b8 = mesh_mod.shard_batch(scengen.virtual_batch(prog),
                              mesh_mod.make_mesh(8), pad=True)
    assert b8.num_scenarios == 16
    b6 = mesh_mod.shard_batch(scengen.virtual_batch(prog),
                              mesh_mod.make_mesh(6), pad=True)
    assert b6.num_scenarios == 18

    st8, tb8, _ = ph_mod.ph_iter0(b8, rho, opts)
    st6, tb6, _ = ph_mod.ph_iter0(b6, rho, opts)
    # the certified trivial bound and the consensus xbar are p-weighted
    # reductions: layout-invariant up to f32 reduction order
    assert float(tb6) == pytest.approx(float(tb8), rel=1e-4)
    np.testing.assert_allclose(np.asarray(st6.xbar[0]),
                               np.asarray(st8.xbar[0]),
                               rtol=5e-3, atol=1e-2)


def test_shard_batch_pad_true_materialized_batch():
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.models import farmer

    specs = [farmer.scenario_creator(nm, num_scens=3)
             for nm in farmer.scenario_names_creator(3)]
    b = batch_mod.from_specs(specs)
    b6 = mesh_mod.shard_batch(b, mesh_mod.make_mesh(6), pad=True)
    assert b6.num_scenarios == 6
    p = np.asarray(b6.p)
    np.testing.assert_array_equal(p[3:], np.zeros(3))
    assert float(p.sum()) == pytest.approx(1.0)
    # pad=False keeps the strict contract
    with pytest.raises(ValueError):
        mesh_mod.shard_batch(b, mesh_mod.make_mesh(6))


# ---------------------------------------------------------------------------
# watchdog shrink rung: degrade -> shrink -> abort, never wedged
# ---------------------------------------------------------------------------
class _HubStub:
    telemetry = None
    run_id = "t"
    options: dict = {}


def _trip_n(wd, n):
    for _ in range(n):
        wd._trip(999.0)


def test_watchdog_shrink_ladder():
    from mpisppy_tpu.resilience.watchdog import HubWatchdog
    calls, aborts = [], []
    wd = HubWatchdog(_HubStub(), budget_s=1e9, action="shrink",
                     abort_fn=aborts.append,
                     shrink_fn=lambda stalled: calls.append(stalled) or True)
    _trip_n(wd, 1)
    assert wd.degraded and not wd.shrunk and not aborts
    _trip_n(wd, 1)
    assert wd.shrunk and len(calls) == 1 and not aborts
    _trip_n(wd, 1)
    assert aborts == [75]           # third rung: abort (EX_TEMPFAIL)


def test_watchdog_failed_shrink_escalates_to_abort():
    from mpisppy_tpu.resilience.watchdog import HubWatchdog
    aborts = []

    def bad_shrink(stalled):
        raise RuntimeError("no survivors")

    wd = HubWatchdog(_HubStub(), budget_s=1e9, action="shrink",
                     abort_fn=aborts.append, shrink_fn=bad_shrink)
    _trip_n(wd, 3)
    # a failing shrink is attempted ONCE, then the ladder aborts —
    # it never retries shrink forever
    assert not wd.shrunk and aborts == [75]


def test_watchdog_shrink_without_fn_degrades_then_aborts():
    from mpisppy_tpu.resilience.watchdog import HubWatchdog
    aborts = []
    wd = HubWatchdog(_HubStub(), budget_s=1e9, action="shrink",
                     abort_fn=aborts.append)
    _trip_n(wd, 2)
    assert wd.degraded and aborts == [75]


# ---------------------------------------------------------------------------
# checkpoint durability: the spool directory is fsynced after the
# rename (satellite of ISSUE 17) — a crash right after save cannot
# roll the directory entry back
# ---------------------------------------------------------------------------
def test_fsync_dir_smoke(tmp_path):
    from mpisppy_tpu.utils import atomic_io
    p = tmp_path / "f.txt"
    p.write_text("x")
    atomic_io.fsync_dir(str(p))            # file path: fsyncs parent
    atomic_io.fsync_dir(str(tmp_path))     # dir path: fsyncs itself
    atomic_io.fsync_dir(str(tmp_path / "missing" / "f"))  # silent no-op


def test_checkpoint_rename_then_dir_fsync_ordering(tmp_path, monkeypatch):
    """Crash-ordering regression: the spool directory fsync must happen
    AFTER the final rename lands, and the renamed file must already be
    visible when it does — otherwise a host crash between rename and
    fsync could resurrect the old directory entry while the loader
    already trusted the new one."""
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.cylinders import PHHub
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.spin_the_wheel import WheelSpinner
    from mpisppy_tpu.utils import atomic_io

    specs = [farmer.scenario_creator(nm, num_scens=3)
             for nm in farmer.scenario_names_creator(3)]
    batch = batch_mod.from_specs(specs)
    ckpt = str(tmp_path / "wheel.npz")
    ws = WheelSpinner({
        "hub_class": PHHub,
        "hub_kwargs": {"options": {"rel_gap": 5e-3,
                                   "checkpoint_path": ckpt,
                                   "checkpoint_every_s": 1e9}},
        "opt_class": ph_mod.PH,
        "opt_kwargs": {"options": ph_mod.PHOptions(
            default_rho=1.0, max_iterations=3, conv_thresh=0.0,
            subproblem_windows=4), "batch": batch},
    }).build()
    ws.spcomm.main()

    synced = []

    def spy(path):
        # the rename must already be visible at fsync time
        synced.append((path, os.path.exists(ckpt)))

    monkeypatch.setattr(atomic_io, "fsync_dir", spy)
    # hub._write_checkpoint resolves fsync_dir at call time, so the spy
    # observes the real call site ordering
    import mpisppy_tpu.cylinders.hub as hub_mod
    monkeypatch.setattr(hub_mod, "fsync_dir", spy, raising=False)
    assert ws.spcomm.save_checkpoint(ckpt)
    assert synced, "no directory fsync after checkpoint rename"
    path, visible = synced[-1]
    assert visible, "directory fsync ran before the rename landed"
    assert os.path.dirname(os.path.abspath(path)) == str(tmp_path)


# ---------------------------------------------------------------------------
# load_checkpoint transform hook (the reshard adaptation seam)
# ---------------------------------------------------------------------------
def test_load_checkpoint_transform_applied_after_integrity(tmp_path):
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.cylinders import PHHub
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.spin_the_wheel import WheelSpinner

    specs = [farmer.scenario_creator(nm, num_scens=3)
             for nm in farmer.scenario_names_creator(3)]
    batch = batch_mod.from_specs(specs)

    def spinner():
        return WheelSpinner({
            "hub_class": PHHub,
            "hub_kwargs": {"options": {"rel_gap": 5e-3}},
            "opt_class": ph_mod.PH,
            "opt_kwargs": {"options": ph_mod.PHOptions(
                default_rho=1.0, max_iterations=3, conv_thresh=0.0,
                subproblem_windows=4), "batch": batch},
        }).build()

    ws = spinner()
    ws.spcomm.main()
    ckpt = str(tmp_path / "w.npz")
    assert ws.spcomm.save_checkpoint(ckpt)

    seen = {}

    def transform(arrays):
        seen["n_leaves"] = sum(1 for k in arrays if k.startswith("leaf"))
        seen["has_crc"] = "crc" in arrays
        return arrays

    ws2 = spinner()
    ws2.spcomm.load_checkpoint(ckpt, transform=transform)
    assert seen["n_leaves"] > 0
    assert ws2.spcomm._iter == ws.spcomm._iter
