# Chaos harness: deterministic fault injection (resilience/faults) +
# the graceful-degradation guards it exercises — per-lane PDHG
# divergence quarantine (ops/pdhg), hub bound validation with spoke
# strike/disable policy (cylinders/hub), and preemption-tolerant
# rotated/checksummed checkpoints (hub + spin_the_wheel).  The
# reference's analog is per-scenario solve retries
# (ref:mpisppy/spopt.py:931-960); the TPU wheel's fault model is
# documented in docs/resilience.md.
import dataclasses
import math
import os
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.cylinders import (
    ConvergerSpokeType, PHHub, LagrangianOuterBound, XhatXbarInnerBound,
)
from mpisppy_tpu.models import farmer
from mpisppy_tpu.ops import pdhg
from mpisppy_tpu.resilience import (
    CheckpointFault, FaultPlan, LaneFault, SimulatedPreemption,
    SpokeBoundFault,
)
from mpisppy_tpu.spin_the_wheel import WheelSpinner

pytestmark = pytest.mark.chaos

FARMER_EF_OBJ = -108390.0


def farmer_batch(num_scens=3):
    names = farmer.scenario_names_creator(num_scens)
    specs = [farmer.scenario_creator(nm, num_scens=num_scens)
             for nm in names]
    return batch_mod.from_specs(specs)


def ph_options(max_iterations=150, lane_guard=True):
    return ph_mod.PHOptions(
        default_rho=1.0, max_iterations=max_iterations, conv_thresh=0.0,
        subproblem_windows=10,
        pdhg=pdhg.PDHGOptions(tol=1e-7, lane_guard=lane_guard))


def hub_dict(batch, hub_extra=None, max_iterations=150, rel_gap=5e-3,
             lane_guard=True):
    hub_opts = {"rel_gap": rel_gap}
    hub_opts.update(hub_extra or {})
    return {
        "hub_class": PHHub,
        "hub_kwargs": {"options": hub_opts},
        "opt_class": ph_mod.PH,
        "opt_kwargs": {"options": ph_options(max_iterations, lane_guard),
                       "batch": batch},
    }


BOTH_SPOKES = [
    {"spoke_class": LagrangianOuterBound, "opt_kwargs": {"options": {}}},
    {"spoke_class": XhatXbarInnerBound, "opt_kwargs": {"options": {}}},
]


# ---------------------------------------------------------------------------
# The acceptance round trip: NaN + wrong-sense + stale bounds, forced
# lane divergence, and a simulated preemption + restore — the final
# certified bounds must match the fault-free run.
# ---------------------------------------------------------------------------
def test_chaos_round_trip(tmp_path):
    batch = farmer_batch(3)

    # fault-free reference run
    ws0 = WheelSpinner(hub_dict(batch), [dict(d) for d in BOTH_SPOKES])
    ws0.spin()
    assert np.isfinite(ws0.BestInnerBound) and np.isfinite(ws0.BestOuterBound)

    # chaos run: same wheel under a seeded FaultPlan
    ckpt = str(tmp_path / "wheel.npz")
    plan = FaultPlan(
        seed=42,
        spoke_bounds=(
            SpokeBoundFault("nan", spoke_index=0, at_iters=(3, 4)),
            SpokeBoundFault("wrong_sense", spoke_index=1, at_iters=(4,),
                            magnitude=1e8),
            SpokeBoundFault("stale", spoke_index=1, at_iters=(5,)),
        ),
        lanes=(LaneFault(at_iter=3, lanes=(1,), mode="scale", scale=1e25),
               LaneFault(at_iter=5, lanes=(0,), mode="nan")),
        preempt_at_iter=7,
    )
    assert plan.armed
    hub_extra = {"fault_plan": plan, "checkpoint_path": ckpt,
                 "checkpoint_every_s": 1e9,  # emergency save only
                 "spoke_max_strikes": 10}
    ws1 = WheelSpinner(hub_dict(batch, hub_extra),
                       [dict(d) for d in BOTH_SPOKES])
    with pytest.raises(SimulatedPreemption):
        ws1.spin()
    assert ws1.preempted
    assert os.path.exists(ckpt)
    seams = {s for s, _ in plan.fired}
    assert seams == {"spoke_bound", "lanes", "preemption"}
    # the NaN harvests struck (unambiguous garbage) but stayed below
    # the disable threshold; the wrong-sense harvest was rejected as an
    # ambiguous contradiction — no strike
    assert ws1.spcomm.spokes[0].strikes == 2   # two NaN harvests
    assert ws1.spcomm.spokes[1].strikes == 0
    assert not any(sp.disabled for sp in ws1.spcomm.spokes)
    # mid-chaos bookkeeping is still finite and sense-correct
    ob1, ib1 = ws1.BestOuterBound, ws1.BestInnerBound
    assert np.isfinite(ob1) and np.isfinite(ib1)
    assert ob1 <= ib1 + 5e-3 * abs(ib1)

    # restore into a fresh wheel (no plan) and resume to termination
    ws2 = WheelSpinner(hub_dict(batch, {"checkpoint_path": ckpt}),
                       [dict(d) for d in BOTH_SPOKES]).build()
    ws2.spcomm.load_checkpoint(ckpt)
    assert ws2.spcomm._iter == 7  # the emergency save's sync point
    # the lane guard fired on the corrupted lanes and its counters
    # rode along in the checkpoint
    resets = np.asarray(ws2.opt.state.solver.guard_resets)
    assert resets.max() >= 1
    assert np.all(np.isfinite(np.asarray(ws2.opt.state.solver.x)))
    ws2.spin()

    # certified termination, and bounds match the fault-free run
    inner0, outer0 = ws0.BestInnerBound, ws0.BestOuterBound
    inner2, outer2 = ws2.BestInnerBound, ws2.BestOuterBound
    assert np.isfinite(inner2) and np.isfinite(outer2)
    assert outer2 <= inner2 + 2e-3 * abs(inner2)          # sense-correct
    _, rel_gap = ws2.spcomm.compute_gaps()
    assert rel_gap <= 5e-3 + 1e-6                         # certified
    slack = 2e-3 * abs(FARMER_EF_OBJ)
    assert outer2 <= FARMER_EF_OBJ + slack                # valid bracket
    assert inner2 >= FARMER_EF_OBJ - slack
    assert inner2 == pytest.approx(inner0, rel=1e-2)      # matches
    assert outer2 == pytest.approx(outer0, rel=1e-2)


# ---------------------------------------------------------------------------
# No-overhead contract: a disarmed FaultPlan leaves the jitted hub step
# byte-identical to a build that never touched the resilience layer.
# ---------------------------------------------------------------------------
def test_disarmed_plan_hlo_identical():
    batch = farmer_batch(3)
    opts = ph_mod.kernel_opts(ph_mod.PHOptions(
        default_rho=1.0, conv_thresh=0.0, subproblem_windows=10,
        pdhg=pdhg.PDHGOptions(tol=1e-7)))
    rho = jnp.ones((batch.num_nonants,), batch.qp.c.dtype)
    # baseline: direct driver, no resilience objects anywhere
    st, _, _ = ph_mod.ph_iter0(batch, rho, opts)
    text_base = ph_mod.ph_iterk.lower(batch, st, opts).as_text()

    # the same step lowered from a wheel carrying a DISARMED plan
    plan = FaultPlan(seed=7)
    assert not plan.armed
    ws = WheelSpinner(
        hub_dict(batch, {"fault_plan": plan}, max_iterations=3,
                 rel_gap=5e-3, lane_guard=False),
        [dict(d) for d in BOTH_SPOKES]).spin()
    text_plan = ph_mod.ph_iterk.lower(
        batch, ws.opt.state, ph_mod.kernel_opts(ws.opt.options)).as_text()
    assert text_plan == text_base
    assert plan.fired == []


# ---------------------------------------------------------------------------
# Lane guard unit behavior
# ---------------------------------------------------------------------------
def test_lane_guard_quarantines_nan_lane():
    batch = farmer_batch(3)
    opts = pdhg.PDHGOptions(tol=1e-6, lane_guard=True, max_iters=40_000)
    st = pdhg.solve_fixed(batch.qp, 3, opts,
                          pdhg.init_state(batch.qp, opts))
    nan = jnp.asarray(np.nan, st.x.dtype)
    st = dataclasses.replace(st, x=st.x.at[1].set(nan),
                             y=st.y.at[1].set(nan))
    out = pdhg.solve(batch.qp, opts, st)
    resets = np.asarray(out.guard_resets)
    assert np.all(np.asarray(out.done))
    assert np.all(np.asarray(out.status) == pdhg.OPTIMAL)
    assert resets[1] >= 1 and resets[0] == 0 and resets[2] == 0
    # the quarantined lane re-converged to the clean solution
    clean = pdhg.solve(batch.qp,
                       pdhg.PDHGOptions(tol=1e-6, max_iters=40_000))
    np.testing.assert_allclose(np.asarray(out.x[1]),
                               np.asarray(clean.x[1]),
                               rtol=1e-3, atol=1e-2)


def test_lane_guard_bounded_retries_freeze_lane():
    """A lane past guard_max_resets is frozen done with status RUNNING
    (never certified) instead of burning max_iters forever."""
    batch = farmer_batch(3)
    opts = pdhg.PDHGOptions(tol=1e-6, lane_guard=True, max_iters=4_000,
                            guard_max_resets=2)
    st = pdhg.init_state(batch.qp, opts)
    st = dataclasses.replace(
        st, guard_resets=st.guard_resets.at[2].set(99),
        x=st.x.at[2].set(jnp.asarray(np.nan, st.x.dtype)))
    out = pdhg.solve(batch.qp, opts, st)
    assert bool(out.done[2])
    assert int(out.status[2]) == pdhg.RUNNING  # unconverged, uncertified
    # the frozen lane holds CLEAN iterates — downstream consumers (PH's
    # unmasked xbar/W node averages) must never see the poisoned ones
    assert np.all(np.isfinite(np.asarray(out.x[2])))
    assert np.all(np.isfinite(np.asarray(out.y[2])))
    # healthy lanes unaffected
    assert int(out.status[0]) == pdhg.OPTIMAL
    assert int(out.status[1]) == pdhg.OPTIMAL


def test_lane_guard_off_is_default_and_nan_sticks():
    """Without the guard a NaN lane can never converge — the behavior
    the guard exists to fix (and proof the default program is
    unchanged: guard fields ride along but no guard ops run)."""
    batch = farmer_batch(3)
    opts = pdhg.PDHGOptions(tol=1e-6, max_iters=2_000)
    assert opts.lane_guard is False
    st = pdhg.init_state(batch.qp, opts)
    st = dataclasses.replace(
        st, y=st.y.at[0].set(jnp.asarray(np.nan, st.y.dtype)))
    out = pdhg.solve(batch.qp, opts, st)
    assert not bool(out.done[0])
    assert int(np.asarray(out.guard_resets).max()) == 0


# ---------------------------------------------------------------------------
# Hub bound validation + strike/disable policy
# ---------------------------------------------------------------------------
class ScriptedSpoke:
    """Harvest a scripted sequence of bounds (then None)."""

    converger_spoke_char = "Z"

    def __init__(self, values, sense="outer"):
        self.converger_spoke_types = (
            (ConvergerSpokeType.OUTER_BOUND,) if sense == "outer"
            else (ConvergerSpokeType.INNER_BOUND,))
        self.values = list(values)
        self.bound = None
        self.best_xhat = None
        self.trace = []
        self.strikes = 0
        self.disabled = False
        self.harvest_calls = 0

    def harvest(self):
        self.harvest_calls += 1
        return self.values.pop(0) if self.values else None

    def update(self, payload):
        pass


def _bare_hub(options, spokes):
    hub = PHHub(opt=None, options=options, spokes=spokes)
    return hub


def test_hub_rejects_nonfinite_and_sense_violations():
    good = ScriptedSpoke([-110.0, -109.0], sense="outer")
    hub = _bare_hub({"spoke_max_strikes": 3}, [good])
    hub.BestInnerBound = -100.0
    hub._harvest_all()
    assert hub.BestOuterBound == -110.0

    # non-finite updates can never move the bookkeeping
    assert hub.OuterBoundUpdate(math.nan) == -110.0
    assert hub.OuterBoundUpdate(math.inf) == -110.0
    assert hub.InnerBoundUpdate(-math.inf) == -100.0

    # a sense-violating outer bound (crossing the incumbent) is
    # rejected — no fold, no trace entry, no strike (the evidence is
    # ambiguous): it is recorded as a contradiction of the incumbent
    bad = ScriptedSpoke([-50.0], sense="outer")
    hub.spokes = [bad]
    hub._harvest_all()
    assert hub.BestOuterBound == -110.0
    assert bad.strikes == 0
    assert bad.trace == []
    assert hub._contra["inner"] == [bad]


def test_hub_strikes_disable_spoke_and_wheel_continues():
    bad = ScriptedSpoke([math.nan] * 10, sense="outer")
    good = ScriptedSpoke([-120.0, -115.0, -112.0, -111.0], sense="outer")
    hub = _bare_hub({"spoke_max_strikes": 2}, [bad, good])
    hub.BestInnerBound = -100.0
    for _ in range(4):
        hub._harvest_all()
    assert bad.disabled
    assert bad.strikes == 2
    # harvest stopped being called once disabled
    assert bad.harvest_calls == 2
    # the healthy spoke kept feeding the hub throughout
    assert good.harvest_calls == 4
    assert hub.BestOuterBound == -111.0


def test_poisoned_early_incumbent_is_evicted_by_distinct_contradictors():
    """A wrong-sense outer bound accepted BEFORE any inner exists (so
    sense validation could not catch it) must not poison the monotone
    BestOuterBound forever: contradictions from enough DISTINCT spokes
    evict it — without blaming anyone, since the evidence stays
    ambiguous — and the healthy bounds land on the next sweep."""
    rogue = ScriptedSpoke([1e7], sense="outer")   # garbage, accepted at
    goods = [ScriptedSpoke([-100.0] * 2, sense="inner")  # an empty hub
             for _ in range(3)]
    hub = _bare_hub({}, [rogue] + goods)
    hub._harvest_all()
    # three distinct contradictors -> incumbent evicted mid-sweep
    assert hub.BestOuterBound == -math.inf
    hub._harvest_all()
    assert hub.BestInnerBound == -100.0
    assert all(g.strikes == 0 and not g.disabled for g in goods)
    assert rogue.strikes == 0   # ambiguous evidence never strikes


def test_lone_contradictor_cannot_evict_a_confirmed_incumbent():
    """One persistently rogue spoke must never out-vote the standing
    incumbent: its garbage is rejected every sync (and scrubbed, so a
    cached spike cannot re-offer itself), but the incumbent stands and
    nobody is struck or disabled."""
    class CachingSpoke(ScriptedSpoke):
        def harvest(self):  # the monotone-cache shape of real spokes
            self.harvest_calls += 1
            if self.values:
                b = self.values.pop(0)
                if self.bound is None or b > self.bound:
                    self.bound = b
            return self.bound

    sp = CachingSpoke([-50.0], sense="outer")  # one spike, then cache
    hub = _bare_hub({"spoke_max_strikes": 3}, [sp])
    hub.BestInnerBound = -100.0
    for _ in range(6):
        hub._harvest_all()
    assert sp.strikes == 0
    assert not sp.disabled
    assert hub.BestInnerBound == -100.0       # incumbent untouched
    assert hub._contra["inner"] == [sp]       # dissent logged ONCE
    sp.values = [-110.0]                      # the spoke recovers
    hub._harvest_all()
    assert hub.BestOuterBound == -110.0
    assert hub._contra["inner"] == []         # consistency clears it


def test_best_nonants_ignores_nan_incumbent():
    nan_sp = ScriptedSpoke([], sense="inner")
    nan_sp.bound = math.nan
    nan_sp.best_xhat = np.full((1, 2), 77.0)
    good = ScriptedSpoke([], sense="inner")
    good.bound = -105.0
    good.best_xhat = np.full((1, 2), 5.0)
    hub = _bare_hub({}, [nan_sp, good])
    np.testing.assert_array_equal(hub.best_nonants(),
                                  np.full((1, 2), 5.0))


def test_best_nonants_survives_disabled_incumbent_producer():
    """BestInnerBound keeps previously accepted values even after the
    producing spoke goes rogue and is disabled — the hub-side incumbent
    cache must keep backing the reported bound with its solution."""
    sp = ScriptedSpoke([-105.0, math.nan, math.nan], sense="inner")
    sp.best_xhat = np.full((1, 2), 7.0)
    hub = _bare_hub({"spoke_max_strikes": 2}, [sp])
    for _ in range(3):
        hub._harvest_all()
    assert hub.BestInnerBound == -105.0   # accepted value retained
    assert sp.disabled                    # then the producer died
    np.testing.assert_array_equal(hub.best_nonants(),
                                  np.full((1, 2), 7.0))


def test_lane_guard_reaches_fused_planes():
    """--lane-guard must guard the fused bound planes' PDHG options,
    not only the hub's subproblem solves."""
    from mpisppy_tpu import generic_cylinders as gc
    from mpisppy_tpu.cylinders import spoke as spoke_mod
    from mpisppy_tpu.utils.config import Config
    cfg = Config()
    cfg.resilience_args()
    cfg.lane_guard = True
    spokes = [{"spoke_class": spoke_mod.LagrangianOuterBound,
               "opt_kwargs": {"options": {}}},
              {"spoke_class": spoke_mod.XhatXbarInnerBound,
               "opt_kwargs": {"options": {}}}]
    hub2, _ = gc._fuse_wheel(cfg, {"opt_kwargs": {}}, spokes)
    wopts = hub2["opt_kwargs"]["wheel_options"]
    assert wopts.lag_pdhg.lane_guard
    assert wopts.xhat_pdhg.lane_guard


# ---------------------------------------------------------------------------
# Checkpoint rotation, checksum, fallback, cadence
# ---------------------------------------------------------------------------
def _spun_wheel_with_ckpt_opts(tmp_path, plan=None, keep=3):
    batch = farmer_batch(3)
    ckpt = str(tmp_path / "w.npz")
    hub_extra = {"checkpoint_path": ckpt, "checkpoint_every_s": 1e9,
                 "checkpoint_keep": keep}
    if plan is not None:
        hub_extra["fault_plan"] = plan
    ws = WheelSpinner(hub_dict(batch, hub_extra, max_iterations=4),
                      [dict(d) for d in BOTH_SPOKES]).spin()
    return ws, ckpt, batch


def test_torn_checkpoint_falls_back_to_rotated(tmp_path):
    # tear the SECOND write (the newest file) mid-stream — the kill-mid-
    # write case on a non-atomic filesystem
    plan = FaultPlan(seed=3, checkpoints=(
        CheckpointFault("torn", at_write=1),))
    ws, ckpt, batch = _spun_wheel_with_ckpt_opts(tmp_path, plan)
    hub = ws.spcomm
    assert hub.save_checkpoint(ckpt)          # write 0: clean
    it_saved = hub._iter
    hub._iter += 1                            # pretend progress
    assert hub.save_checkpoint(ckpt)          # write 1: torn by the plan
    assert ("checkpoint", f"torn write1 {ckpt}") in plan.fired
    assert os.path.exists(ckpt + ".1")

    ws2 = WheelSpinner(
        hub_dict(batch, {"checkpoint_path": ckpt}, max_iterations=4),
        [dict(d) for d in BOTH_SPOKES]).build()
    ws2.spcomm.load_checkpoint(ckpt)
    # the torn newest file was skipped; the last-good rotated snapshot
    # (write 0, at it_saved) restored
    assert ws2.spcomm._iter == it_saved
    assert np.isfinite(ws2.spcomm.BestOuterBound)


def test_corrupt_checkpoint_falls_back_to_rotated(tmp_path):
    plan = FaultPlan(seed=4, checkpoints=(
        CheckpointFault("corrupt", at_write=1),))
    ws, ckpt, batch = _spun_wheel_with_ckpt_opts(tmp_path, plan)
    hub = ws.spcomm
    assert hub.save_checkpoint(ckpt)
    it_saved = hub._iter
    hub._iter += 1
    assert hub.save_checkpoint(ckpt)          # bit-flipped by the plan
    ws2 = WheelSpinner(
        hub_dict(batch, {"checkpoint_path": ckpt}, max_iterations=4),
        [dict(d) for d in BOTH_SPOKES]).build()
    ws2.spcomm.load_checkpoint(ckpt)
    assert ws2.spcomm._iter == it_saved


def test_checksum_rejects_silently_tampered_arrays(tmp_path):
    """Bit rot that survives the zip layer must be caught by the crc in
    the meta (the zip member crc only covers what np.load re-reads)."""
    ws, ckpt, _ = _spun_wheel_with_ckpt_opts(tmp_path)
    hub = ws.spcomm
    assert hub.save_checkpoint(ckpt)
    with np.load(ckpt) as data:
        arrays = {k: np.asarray(data[k]) for k in data.files}
    # tamper the bounds but keep the stale crc: re-written zip is
    # perfectly valid, only OUR checksum can notice
    arrays["bounds"] = arrays["bounds"] + 1.0
    np.savez(ckpt, **arrays)
    with pytest.raises(ValueError, match="checksum mismatch"):
        hub._read_checkpoint_arrays(ckpt)
    # all candidates bad -> load_checkpoint raises, not crashes weirdly
    for cand in hub._checkpoint_candidates(ckpt)[1:]:
        os.remove(cand)
    with pytest.raises(FileNotFoundError, match="no valid checkpoint"):
        hub.load_checkpoint(ckpt)


class _DummyOpt:
    state = [jnp.zeros(2)]
    wstate = None
    trivial_bound = None
    trivial_bound_certified = False
    _iter = 0


def test_maybe_checkpoint_cadence_not_consumed_by_skipped_save(tmp_path):
    """Satellite regression: a save skipped because the previous write
    thread is still alive must NOT advance _last_ckpt_t (the slip that
    silently halved checkpoint frequency under slow writes)."""
    ckpt = str(tmp_path / "c.npz")
    hub = PHHub(opt=_DummyOpt(), options={"checkpoint_path": ckpt,
                                          "checkpoint_every_s": 0.0})
    hub._last_ckpt_t = 1.0  # long overdue
    gate = threading.Event()
    blocker = threading.Thread(target=gate.wait)
    blocker.start()
    hub._ckpt_thread = blocker
    try:
        hub._maybe_checkpoint()
        assert hub._last_ckpt_t == 1.0  # slot NOT consumed: will retry
        assert not os.path.exists(ckpt)
    finally:
        gate.set()
        blocker.join()
    hub._maybe_checkpoint()
    assert hub._last_ckpt_t != 1.0      # the real save consumed it
    hub._ckpt_thread.join()
    assert os.path.exists(ckpt)


def test_preemption_handlers_installed_and_restored(tmp_path):
    import signal
    prev_int = signal.getsignal(signal.SIGINT)
    prev_term = signal.getsignal(signal.SIGTERM)
    batch = farmer_batch(3)
    ckpt = str(tmp_path / "w.npz")
    WheelSpinner(hub_dict(batch, {"checkpoint_path": ckpt,
                                  "checkpoint_every_s": 1e9},
                          max_iterations=2),
                 [dict(d) for d in BOTH_SPOKES]).spin()
    assert signal.getsignal(signal.SIGINT) is prev_int
    assert signal.getsignal(signal.SIGTERM) is prev_term


# ---------------------------------------------------------------------------
# Graceful degradation end to end: a persistently poisoned spoke is
# disabled and the wheel still terminates on the survivors.
# ---------------------------------------------------------------------------
def test_spoke_auto_disable_wheel_continues():
    batch = farmer_batch(3)
    plan = FaultPlan(seed=5, spoke_bounds=(
        SpokeBoundFault("nan", spoke_index=0),))  # EVERY harvest
    ws = WheelSpinner(
        hub_dict(batch, {"fault_plan": plan, "spoke_max_strikes": 2},
                 max_iterations=40, rel_gap=1e-2),
        [dict(d) for d in BOTH_SPOKES]).spin()
    lag = ws.spcomm.spokes[0]
    assert lag.disabled and lag.strikes == 2
    # outer bound came from the certified trivial bound ("T"), inner
    # from the surviving xhat spoke — still a finite, sense-correct,
    # certified bracket
    assert np.isfinite(ws.BestOuterBound) and np.isfinite(ws.BestInnerBound)
    assert ws.BestOuterBound <= ws.BestInnerBound + 2e-3 * abs(
        ws.BestInnerBound)
    assert ws.spcomm.latest_ob_char == "T"


# ---------------------------------------------------------------------------
# Hub progress watchdog (resilience/watchdog.py; ISSUE 9): stalls trip a
# flight dump + the configured action — checkpoint-and-abort exit 75, or
# dispatch degradation with escalation on a second stalled budget.
# ---------------------------------------------------------------------------
class _WatchdogHub:
    """Duck-typed hub for watchdog unit tests."""

    def __init__(self, bus=None, ckpt_path=None):
        from mpisppy_tpu import telemetry
        self.telemetry = bus or telemetry.EventBus()
        self.run_id = "wdtest"
        self.options = {"checkpoint_path": ckpt_path}
        self.saved = []

    def emergency_checkpoint(self, path):
        self.saved.append(path)
        return True


def test_watchdog_trips_abort_with_checkpoint_and_exit75(tmp_path):
    from mpisppy_tpu import telemetry
    from mpisppy_tpu.resilience import HubWatchdog

    seen = []

    class _Probe:
        def handle(self, ev):
            seen.append(ev)

    bus = telemetry.EventBus()
    bus.subscribe(_Probe())
    rec = telemetry.FlightRecorder(capacity=16, dump_dir=str(tmp_path))
    bus.subscribe(rec)
    hub = _WatchdogHub(bus, ckpt_path=str(tmp_path / "w.npz"))
    codes = []
    wd = HubWatchdog(hub, budget_s=0.15, action="abort",
                     interval_s=0.02, abort_fn=codes.append).start()
    wd.beat(1, -100.0, -90.0)
    deadline = time.perf_counter() + 5.0
    while not codes and time.perf_counter() < deadline:
        time.sleep(0.01)
    wd.stop()
    assert codes == [75], "watchdog never aborted (or wrong exit code)"
    assert hub.saved == [str(tmp_path / "w.npz")]  # last-gasp save ran
    events = [e for e in seen if e.kind == "watchdog"]
    assert events and events[0].data["action"] == "abort"
    assert events[0].data["stalled_s"] >= 0.15
    assert rec.dumped_to, "no flight-recorder black box on the trip"
    from mpisppy_tpu.telemetry import metrics as metrics_mod
    assert metrics_mod.REGISTRY.get("watchdog_trips_total") >= 1


def test_watchdog_beats_hold_off_the_trip():
    from mpisppy_tpu.resilience import HubWatchdog
    hub = _WatchdogHub()
    codes = []
    wd = HubWatchdog(hub, budget_s=0.2, action="abort",
                     interval_s=0.02, abort_fn=codes.append).start()
    t_end = time.perf_counter() + 0.6
    it = 0
    while time.perf_counter() < t_end:   # steady progress: 3x budget
        it += 1
        wd.beat(it, -100.0 - it, -90.0)
        time.sleep(0.02)
    wd.stop()
    assert codes == [] and wd.trips == 0


def test_watchdog_degrade_then_escalate(tmp_path):
    from mpisppy_tpu import dispatch
    from mpisppy_tpu.resilience import HubWatchdog

    sched = dispatch.configure()
    try:
        assert sched.options.coalesce
        hub = _WatchdogHub()
        codes = []
        wd = HubWatchdog(hub, budget_s=0.1, action="degrade",
                         interval_s=0.02, abort_fn=codes.append)
        wd.start()
        deadline = time.perf_counter() + 5.0
        while not sched.stats()["degraded"] \
                and time.perf_counter() < deadline:
            time.sleep(0.01)
        assert sched.stats()["degraded"], "degrade action never reached " \
            "the process-default scheduler"
        assert not sched.options.coalesce
        # a SECOND stalled budget escalates to the abort action
        deadline = time.perf_counter() + 5.0
        while not codes and time.perf_counter() < deadline:
            time.sleep(0.01)
        wd.stop()
        assert codes == [75] and wd.trips >= 2
    finally:
        dispatch.configure()


def test_watchdog_wired_from_hub_options_and_stopped_at_finalize():
    """--watchdog-budget-s reaches the hub: the wheel arms a watchdog,
    beats it every sync, and finalize stops it — a healthy short run
    never trips."""
    batch = farmer_batch(3)
    ws = WheelSpinner(
        hub_dict(batch, {"watchdog_budget_s": 300.0,
                         "watchdog_action": "degrade"},
                 max_iterations=3),
        [dict(d) for d in BOTH_SPOKES]).spin()
    wd = ws.spcomm._watchdog
    assert wd is not None
    assert wd.trips == 0 and not wd.degraded
    assert wd._stop.is_set(), "finalize did not stop the watchdog"


def test_watchdog_cli_knobs_reach_hub_options():
    from mpisppy_tpu.utils import cfg_vanilla as vanilla
    from mpisppy_tpu.utils.config import Config
    cfg = Config()
    cfg.popular_args()
    cfg.resilience_args()
    cfg.parse_command_line("t", [
        "--watchdog-budget-s", "120", "--watchdog-action", "degrade",
        "--watchdog-interval-s", "5"])
    opts = vanilla._hub_opts(cfg)
    assert opts["watchdog_budget_s"] == 120.0
    assert opts["watchdog_action"] == "degrade"
    assert opts["watchdog_interval_s"] == 5.0


@pytest.mark.slow
def test_chaos_soak_many_faults(tmp_path):
    """Long soak: repeated lane corruption + bound poisoning + two
    preemption/restore cycles; the wheel must end with a certified
    bracket matching the fault-free run."""
    batch = farmer_batch(6)
    ws0 = WheelSpinner(hub_dict(batch, max_iterations=120),
                       [dict(d) for d in BOTH_SPOKES]).spin()
    ckpt = str(tmp_path / "soak.npz")
    plans = [
        FaultPlan(seed=11,
                  spoke_bounds=(SpokeBoundFault("nan", at_iters=(3, 5)),),
                  lanes=(LaneFault(at_iter=4, lanes=(0, 3), mode="scale",
                                   scale=1e25),),
                  preempt_at_iter=6),
        FaultPlan(seed=12,
                  lanes=(LaneFault(at_iter=8, lanes=(2,), mode="nan"),),
                  preempt_at_iter=10),
    ]
    hub_extra = {"checkpoint_path": ckpt, "checkpoint_every_s": 1e9,
                 "spoke_max_strikes": 20}
    ws = WheelSpinner(hub_dict(batch, {**hub_extra,
                                       "fault_plan": plans[0]},
                               max_iterations=120),
                      [dict(d) for d in BOTH_SPOKES])
    with pytest.raises(SimulatedPreemption):
        ws.spin()
    for plan in plans[1:]:
        ws = WheelSpinner(hub_dict(batch, {**hub_extra,
                                           "fault_plan": plan},
                                   max_iterations=120),
                          [dict(d) for d in BOTH_SPOKES]).build()
        ws.spcomm.load_checkpoint(ckpt)
        with pytest.raises(SimulatedPreemption):
            ws.spin()
    ws = WheelSpinner(hub_dict(batch, hub_extra, max_iterations=120),
                      [dict(d) for d in BOTH_SPOKES]).build()
    ws.spcomm.load_checkpoint(ckpt)
    ws.spin()
    _, rel_gap = ws.spcomm.compute_gaps()
    assert rel_gap <= 5e-3 + 1e-6
    assert ws.BestInnerBound == pytest.approx(ws0.BestInnerBound, rel=1e-2)
    assert ws.BestOuterBound == pytest.approx(ws0.BestOuterBound, rel=1e-2)


# ---------------------------------------------------------------------------
# Flight recorder: every crash leaves a black box, tracing on or off
# (ISSUE 5; the simulated-preemption path)
# ---------------------------------------------------------------------------
def test_flight_recorder_black_box_on_preemption(tmp_path):
    import json
    from mpisppy_tpu import telemetry
    from mpisppy_tpu.telemetry import analyze as an

    batch = farmer_batch(3)
    # tracing OFF: the recorder is the bus's only sink — the crash
    # must still leave a valid flight-<runid>.jsonl
    bus = telemetry.EventBus()
    rec = telemetry.FlightRecorder(capacity=64, dump_dir=str(tmp_path))
    bus.subscribe(rec)
    ckpt = str(tmp_path / "wheel.npz")
    plan = FaultPlan(seed=3, preempt_at_iter=4)
    ws = WheelSpinner(
        hub_dict(batch, {"telemetry_bus": bus, "fault_plan": plan,
                         "checkpoint_path": ckpt,
                         "checkpoint_every_s": 1e9}),
        [dict(d) for d in BOTH_SPOKES])
    with pytest.raises(SimulatedPreemption):
        ws.spin()

    path = tmp_path / f"flight-{ws.spcomm.run_id}.jsonl"
    assert path.exists(), "crash left no black box"
    assert rec.dumped_to == str(path)
    rows = [json.loads(line) for line in open(path)]
    # header first, then ordinary trace lines (oldest first)
    assert rows[0]["kind"] == "flight-recorder"
    assert "SimulatedPreemption" in rows[0]["reason"]
    seqs = [r["seq"] for r in rows[1:]]
    assert seqs == sorted(seqs)
    kinds = {r["kind"] for r in rows[1:]}
    assert {"hub-iteration", "fault-injected", "run-end"} <= kinds
    # the run-end record carries the exit reason (ISSUE 5 satellite)
    end = [r for r in rows if r["kind"] == "run-end"][0]
    assert end["data"]["reason"] == "preemption"
    assert "SimulatedPreemption" in end["data"]["error"]
    # fault events are iteration-stamped, no seq-window heuristics
    fault = [r for r in rows if r["kind"] == "fault-injected"][0]
    assert fault["iter"] == 4 and fault["data"]["seam"] == "preemption"
    # the black box is a first-class analyzer input
    rep = an.analyze_path(str(path))
    assert rep["run"]["exit"]["reason"] == "preemption"
    assert rep["resilience"]["faults_injected"]["preemption"] == 1
