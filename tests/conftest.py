# Test harness: force an 8-device virtual CPU platform BEFORE jax imports,
# mirroring the reference's "mock MPI" seam that lets distributed code run
# in one process (ref:mpisppy/MPI.py:27-90 and the no-mpi4py CI job,
# ref:.github/workflows/test_pr_and_main.yml:27-48).  Every sharded code
# path is exercised on this virtual mesh; real-TPU behavior only differs
# in performance.
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The env var alone is not enough when a TPU plugin (e.g. the axon
# tunnel) registered itself with higher priority — pin the platform via
# the config API too.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
