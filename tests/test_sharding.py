# Multi-device sharding: scenario-axis parity sharded-vs-unsharded, and
# proof that cross-device collectives actually appear in the compiled
# program (the analog of the reference's Allreduce seam,
# ref:mpisppy/phbase.py:88-92).  Runs on the virtual 8-device CPU mesh
# from conftest.py.
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import farmer
from mpisppy_tpu.parallel import mesh as mesh_mod


def build_batch(num_scens):
    names = farmer.scenario_names_creator(num_scens)
    specs = [farmer.scenario_creator(nm, num_scens=num_scens)
             for nm in names]
    return batch_mod.from_specs(specs)


def test_devices_available():
    assert len(jax.devices()) >= 8, "conftest must force 8 virtual devices"


def test_sharded_ph_matches_unsharded():
    b = build_batch(16)
    opts = ph_mod.PHOptions(default_rho=1.0, max_iterations=10,
                            conv_thresh=0.0, subproblem_windows=4)

    # unsharded (1-device mesh = the serial/mock path)
    m1 = mesh_mod.make_mesh(1)
    b1 = mesh_mod.shard_batch(b, m1)
    algo1 = ph_mod.PH(opts, b1)
    algo1.Iter0()
    for _ in range(5):
        algo1.state = ph_mod.ph_iterk(b1, algo1.state, opts)

    # sharded over all 8 devices
    m8 = mesh_mod.make_mesh(8)
    b8 = mesh_mod.shard_batch(b, m8)
    algo8 = ph_mod.PH(opts, b8)
    algo8.Iter0()
    for _ in range(5):
        algo8.state = ph_mod.ph_iterk(b8, algo8.state, opts)

    # same math, different partitioning -> near-identical trajectories
    # (tolerances account for f32 reduction-order differences compounding
    # over 6 iterations; the kernel's adaptive per-scenario restart
    # decisions can flip on such differences, which amplifies late-iter
    # W divergence slightly — hence the looser W tolerance)
    np.testing.assert_allclose(np.asarray(algo1.state.xbar[0]),
                               np.asarray(algo8.state.xbar[0]),
                               rtol=1e-3, atol=1e-2)
    np.testing.assert_allclose(float(algo1.state.conv),
                               float(algo8.state.conv),
                               rtol=1e-2, atol=1e-4)
    np.testing.assert_allclose(np.asarray(algo1.state.W),
                               np.asarray(algo8.state.W),
                               rtol=0.1, atol=2.0)


def test_sharded_step_emits_collectives():
    """The compiled PH step over a sharded batch must contain cross-device
    reduction collectives — this test fails if the xbar reduction stops
    being a psum (VERDICT r1 item 4)."""
    b = build_batch(16)
    m8 = mesh_mod.make_mesh(8)
    b8 = mesh_mod.shard_batch(b, m8)
    opts = ph_mod.PHOptions(subproblem_windows=2)
    st, _, _ = ph_mod.ph_iter0(b8, jnp.ones(b8.num_nonants, b8.qp.c.dtype),
                            opts)
    lowered = ph_mod.ph_iterk.lower(b8, st, opts)
    hlo = lowered.compile().as_text()
    assert "all-reduce" in hlo or "all-gather" in hlo, \
        "no cross-device collective in compiled PH step"


def test_pad_then_shard():
    b = build_batch(6)  # not divisible by 8
    with pytest.raises(ValueError):
        mesh_mod.shard_batch(b, mesh_mod.make_mesh(8))
    pb = batch_mod.pad_to_multiple(b, 8)
    b8 = mesh_mod.shard_batch(pb, mesh_mod.make_mesh(8))
    opts = ph_mod.PHOptions(max_iterations=3, conv_thresh=0.0,
                            subproblem_windows=3)
    algo = ph_mod.PH(opts, b8)
    algo.Iter0()
    algo.state = ph_mod.ph_iterk(b8, algo.state, opts)
    assert np.isfinite(float(algo.state.conv))
    # padded scenarios must not influence xbar: recompute from real rows
    x_non = np.asarray(pb.nonants(algo.state.solver.x))[:6]
    manual = x_non.mean(axis=0)
    np.testing.assert_allclose(np.asarray(algo.state.xbar[0]), manual,
                               rtol=1e-4, atol=1e-4)
