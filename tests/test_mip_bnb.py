# Exact-MIP path: batched branch-and-bound (ops/bnb.py, algos/mip.py)
# oracle-tested against scipy.optimize.milp (HiGHS) — the same
# independent-oracle strategy the LP tests use with scipy.linprog, in
# the role Gurobi plays for the reference's tests
# (ref:mpisppy/tests/utils.py:14-34 solver-adaptive fixtures).
import numpy as np
import pytest
import jax.numpy as jnp

from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.models import sslp
from mpisppy_tpu.ops import bnb, boxqp, pdhg
from mpisppy_tpu.ops.bnb import BnBOptions


@pytest.fixture(autouse=True, scope="module")
def _fresh_caches():
    """The branch-and-bound tests compile many large programs; run with
    a fresh XLA cache so cumulative compile-cache pressure from the rest
    of the suite cannot push the CPU client into native OOM (a segfault
    in this module reproduced only in full-suite runs)."""
    import jax
    jax.clear_caches()
    yield
    jax.clear_caches()


def milp_oracle(c, A, bl, bu, l, u, integer):  # noqa: E741
    from scipy.optimize import Bounds, LinearConstraint, milp
    res = milp(c, constraints=LinearConstraint(A, bl, bu),
               bounds=Bounds(l, u), integrality=integer.astype(int))
    return res


def random_mips(S=4, n=8, m=5, seed=3):
    """Batch of random feasible bounded MIPs + their oracle optima."""
    rng = np.random.RandomState(seed)
    c = rng.randn(S, n)
    A = rng.randn(S, m, n) * (rng.rand(S, m, n) < 0.6)
    x0 = rng.randint(0, 3, size=(S, n)).astype(float)
    bu = np.einsum("smn,sn->sm", A, x0) + rng.rand(S, m) * 2.0
    bl = np.full((S, m), -np.inf)
    l = np.zeros((S, n))  # noqa: E741
    u = np.full((S, n), 4.0)
    integer = np.ones(n, bool)
    opts = [milp_oracle(c[s], A[s], bl[s], bu[s], l[s], u[s], integer)
            for s in range(S)]
    assert all(r.success for r in opts)
    qp = boxqp.BoxQP(
        c=jnp.asarray(c, jnp.float32), q=jnp.zeros((S, n), jnp.float32),
        A=jnp.asarray(A, jnp.float32), bl=jnp.asarray(bl, jnp.float32),
        bu=jnp.asarray(bu, jnp.float32), l=jnp.asarray(l, jnp.float32),
        u=jnp.asarray(u, jnp.float32))
    return qp, integer, np.array([r.fun for r in opts])


def test_bnb_matches_milp_oracle():
    qp, integer, ref = random_mips()
    res = bnb.solve_mip(qp, jnp.ones(qp.c.shape[-1], jnp.float32),
                        np.nonzero(integer)[0].astype(np.int32),
                        BnBOptions(pool_size=32, max_rounds=300))
    inner = np.asarray(res.inner)
    outer = np.asarray(res.outer)
    scale = 1.0 + np.abs(ref)
    # the certified bracket must contain the oracle optimum
    assert np.all(outer <= ref + 1e-3 * scale), (outer, ref)
    assert np.all(inner >= ref - 1e-3 * scale), (inner, ref)
    # and close it
    assert np.all(np.abs(inner - ref) <= 2e-3 * scale), (inner, ref)


def test_certified_dual_bound_is_valid_anywhere():
    """certified_dual_bound must lower-bound the LP optimum from ANY
    iterates — including garbage ones (that is what pruning relies on)."""
    from scipy.optimize import linprog
    rng = np.random.RandomState(0)
    n, m = 6, 4
    c = rng.randn(n)
    A = rng.randn(m, n)
    x0 = rng.rand(n) * 2
    bu = A @ x0 + 0.5
    l = np.zeros(n)  # noqa: E741
    u = np.full(n, 3.0)
    ref = linprog(c, A_ub=A, b_ub=bu, bounds=list(zip(l, u)), method="highs")
    assert ref.success
    qp = boxqp.BoxQP(
        c=jnp.asarray(c[None], jnp.float32),
        q=jnp.zeros((1, n), jnp.float32),
        A=jnp.asarray(A, jnp.float32),
        bl=jnp.asarray(np.full(m, -np.inf)[None], jnp.float32),
        bu=jnp.asarray(bu[None], jnp.float32),
        l=jnp.asarray(l[None], jnp.float32),
        u=jnp.asarray(u[None], jnp.float32))
    for seed in range(5):
        r2 = np.random.RandomState(seed)
        x = jnp.asarray(r2.randn(1, n), jnp.float32)
        y = jnp.asarray(r2.randn(1, m), jnp.float32)
        b = float(boxqp.certified_dual_bound(qp, x, y)[0])
        assert b <= ref.fun + 1e-4 * (1 + abs(ref.fun)), (b, ref.fun)
    # at the PDHG solution the bound is tight
    st = pdhg.solve(qp, pdhg.PDHGOptions(tol=1e-7))
    b = float(boxqp.certified_dual_bound(qp, st.x, st.y)[0])
    assert abs(b - ref.fun) <= 1e-3 * (1 + abs(ref.fun))


@pytest.fixture(scope="module")
def small_sslp_batch():
    """Synthetic sslp small enough for oracle MIP solves."""
    inst = sslp.synthetic_instance(4, 8, seed=2)
    names = sslp.scenario_names_creator(4)
    specs = [sslp.scenario_creator(nm, instance=inst, num_scens=4)
             for nm in names]
    return specs, batch_mod.from_specs(specs)


def _sslp_ef_oracle(specs):
    from mpisppy_tpu.algos import ef as ef_mod
    efp = ef_mod.build_ef(specs, scale=False, sparse=False)
    integer = np.zeros(efp.qp.c.shape[-1], bool)
    n = efp.n_per_scen
    for s, sp in enumerate(specs):
        integer[s * n:(s + 1) * n] = sp.integer
    r = milp_oracle(np.asarray(efp.qp.c, float), np.asarray(efp.qp.A, float),
                    np.asarray(efp.qp.bl, float), np.asarray(efp.qp.bu, float),
                    np.asarray(efp.qp.l, float), np.asarray(efp.qp.u, float),
                    integer)
    assert r.success
    return r.fun


def test_ef_mip_matches_oracle(small_sslp_batch):
    from mpisppy_tpu.algos import ef as ef_mod, mip
    specs, _ = small_sslp_batch
    ref = _sslp_ef_oracle(specs)
    efp = ef_mod.build_ef(specs)
    r = mip.ef_mip(efp, specs,
                   BnBOptions(gap_tol=1e-3, pool_size=64, max_rounds=300))
    scale = 1.0 + abs(ref)
    assert r["outer"] <= ref + 2e-3 * scale, (r, ref)
    assert r["inner"] >= ref - 2e-3 * scale, (r, ref)
    assert abs(r["inner"] - ref) <= 5e-3 * scale, (r, ref)


def test_certified_mip_gap_brackets_oracle(small_sslp_batch):
    from mpisppy_tpu.algos import mip, ph as ph_mod
    specs, batch = small_sslp_batch
    ref = _sslp_ef_oracle(specs)
    res = mip.certified_mip_gap(
        batch, ph_mod.PHOptions(max_iterations=40, default_rho=10.0),
        BnBOptions(gap_tol=1e-3, pool_size=32, max_rounds=200))
    scale = 1.0 + abs(ref)
    assert res.outer <= ref + 2e-3 * scale, (res.outer, ref)
    assert res.inner >= ref - 2e-3 * scale, (res.inner, ref)
    assert res.gap <= 0.02, res


def test_evaluate_mip_integer_recourse(small_sslp_batch):
    """Integer-recourse xhat evaluation >= LP-recourse evaluation, and
    matches per-scenario oracle MIPs with the first stage fixed."""
    from mpisppy_tpu.algos import mip, xhat as xhat_mod
    specs, batch = small_sslp_batch
    nsrv = int(np.asarray(batch.integer_slot).shape[0])
    xhat = np.ones(nsrv)  # open all servers: recourse surely feasible
    ev = mip.evaluate_mip(batch, jnp.asarray(xhat, jnp.float32),
                          BnBOptions(gap_tol=1e-3, pool_size=32,
                                     max_rounds=200))
    assert ev["feasible"]
    lp = float(xhat_mod.evaluate(batch, jnp.asarray(xhat, jnp.float32)).value)
    assert ev["value"] >= lp - 1e-3 * (1 + abs(lp))
    # oracle per scenario: fix x = 1 and MIP the recourse
    vals = []
    for sp in specs:
        l = sp.l.copy()  # noqa: E741
        u = sp.u.copy()
        l[sp.nonant_idx] = xhat
        u[sp.nonant_idx] = xhat
        r = milp_oracle(sp.c, sp.A, sp.bl, sp.bu, l, u, sp.integer)
        assert r.success
        vals.append(r.fun)
    ref = float(np.mean(vals))
    assert abs(ev["value"] - ref) <= 2e-3 * (1 + abs(ref)), (ev["value"], ref)


REF_1545 = "/root/reference/examples/sslp/data/sslp_15_45_5/scenariodata"
_SLOW = __import__("os").environ.get("RUN_SLOW_MIP") == "1"


@pytest.mark.skipif(not __import__("os").path.isdir(REF_1545),
                    reason="reference sslp data not mounted")
def test_sslp_15_45_5_certified_bracket():
    """Real SIPLIB sslp_15_45_5 data: the certified (inner, outer)
    bracket must contain SIPLIB's published optimum -262.400, and the
    inner bound must be a true integer-feasible value within 1% of it.
    The full <0.5%-gap certification (dd-bnb to closure) is minutes of
    batched B&B — run by bench.py on the TPU and under RUN_SLOW_MIP=1
    here (test_sslp_15_45_5_certified_gap_slow)."""
    from mpisppy_tpu.algos import mip, ph as ph_mod
    from mpisppy_tpu.algos import xhat as xhat_mod
    import jax.numpy as jnp

    names = sslp.scenario_names_creator(5)
    specs = [sslp.scenario_creator(nm, data_dir=REF_1545, num_scens=5)
             for nm in names]
    batch = batch_mod.from_specs(specs)
    drv = ph_mod.PH(ph_mod.PHOptions(max_iterations=60, default_rho=5.0),
                    batch)
    drv.ph_main()
    # inner: MIP-evaluate the best scenario-x candidate
    x_non = batch.nonants(drv.state.solver.x)
    cands = [xhat_mod.round_integers(batch, x_non[s]) for s in range(5)]
    lp_vals = [float(xhat_mod.evaluate(batch, c).value) for c in cands]
    best = cands[int(np.argmin(lp_vals))]
    opts = BnBOptions(gap_tol=2e-3, pool_size=64, max_rounds=80,
                      pump_rounds=10)
    ev = mip.evaluate_mip(batch, jnp.asarray(best), opts)
    assert ev["feasible"]
    inner = ev["value"]
    # outer: Lagrangian MIP bound at PH's W (certified)
    outer = mip.lagrangian_mip_bound(batch, drv.state.W, opts)["bound"]
    # SIPLIB's published optimum for sslp_15_45_5 is -262.400: the
    # certified bracket must contain it
    assert outer <= -262.4 + 0.5, (outer, inner)
    assert inner >= -262.4 - 0.5, (outer, inner)
    # the recourse B&B's own lower bracket at this candidate must come
    # out near the optimum (the per-scenario bounds are the certificate;
    # full inner-side closure to <0.5% is the gated slow test / bench)
    assert ev["value_lower"] <= -255.0, ev["value_lower"]


@pytest.mark.skipif(not (_SLOW and __import__("os").path.isdir(REF_1545)),
                    reason="set RUN_SLOW_MIP=1 (minutes of batched B&B "
                           "on CPU; bench.py runs this on the TPU)")
def test_sslp_15_45_5_certified_gap_slow():
    """The round-2 review's Done criterion: real SIPLIB sslp_15_45_5
    to a certified MIP gap under 0.5% (first-stage dd-bnb closes the
    duality gap the root Lagrangian bound leaves)."""
    from mpisppy_tpu.algos import mip, ph as ph_mod
    names = sslp.scenario_names_creator(5)
    specs = [sslp.scenario_creator(nm, data_dir=REF_1545, num_scens=5)
             for nm in names]
    batch = batch_mod.from_specs(specs)
    res = mip.certified_mip_gap(
        batch, ph_mod.PHOptions(max_iterations=200, default_rho=5.0,
                                subproblem_windows=16),
        BnBOptions(gap_tol=2e-3, pool_size=64, max_rounds=200),
        ascent_steps=2, target_gap=4e-3, dd_nodes=60)
    assert np.isfinite(res.inner)
    assert res.outer <= -262.4 + 0.5 and res.inner >= -262.4 - 0.5, res
    assert res.gap <= 0.005, res


def test_polish_pipeline_improves_and_stays_valid(small_sslp_batch):
    """evaluate_mip_polished (multistart dives + LNS merge) must never
    regress below evaluate_mip and must stay a valid upper bound: every
    per-scenario value >= the per-scenario oracle MIP optimum."""
    from mpisppy_tpu.algos import mip
    specs, batch = small_sslp_batch
    xhat = np.ones(len(np.asarray(batch.nonant_idx)))
    opts = BnBOptions(max_rounds=60, pool_size=32)
    base = mip.evaluate_mip(batch, jnp.asarray(xhat), opts)
    pol = mip.evaluate_mip_polished(batch, jnp.asarray(xhat), opts,
                                    multistart=6, lns_rounds=6)
    assert pol["feasible"]
    assert pol["value"] <= base["value"] + 1e-6
    # per-scenario oracle with the first stage fixed
    for s, sp in enumerate(specs):
        l = np.asarray(sp.l, float).copy()
        u = np.asarray(sp.u, float).copy()
        ni = np.asarray(sp.nonant_idx)
        l[ni] = xhat
        u[ni] = xhat
        integer = np.zeros(len(sp.c), bool)
        integer[np.asarray(sp.integer)] = True
        ref = milp_oracle(np.asarray(sp.c, float), np.asarray(sp.A, float),
                          np.asarray(sp.bl, float),
                          np.asarray(sp.bu, float), l, u, integer)
        assert pol["per_scenario"][s] >= ref.fun - 1e-3 * (1 + abs(ref.fun))


def test_dive_multistart_and_lns_shapes(small_sslp_batch):
    from mpisppy_tpu.ops import bnb as bnb_mod
    specs, batch = small_sslp_batch
    xhat = jnp.ones(len(np.asarray(batch.nonant_idx)))
    qp = batch.with_fixed_nonants(xhat)
    int_cols = jnp.asarray(
        np.nonzero(np.asarray(batch.integer_full))[0].astype(np.int32))
    opts = BnBOptions(max_rounds=10)
    val, x, feas = bnb_mod.dive_multistart(qp, batch.d_col, int_cols,
                                           opts, K=4)
    S, n = qp.c.shape
    assert val.shape == (S,) and x.shape == (S, n)
    rep = bnb_mod.lns_repair(qp, batch.d_col, int_cols, x, val, feas,
                             opts, rounds=3)
    if rep is not None:
        rv, rx, rf = rep
        # never a regression
        assert bool(jnp.all(jnp.where(feas, rv <= val + 1e-6, True)))


def test_swap_rounds_default_off_and_polish_enables():
    """ADVICE r5: the dual-guided SOS1 swap repair defaults OFF (the
    hot Lagrangian-oracle loops were paying ~50 warm re-solves per
    solve_mip) and the polish entry points enable it explicitly."""
    from mpisppy_tpu.algos import mip

    assert BnBOptions().swap_rounds == 0
    assert bnb.POLISH_SWAP_ROUNDS == 24
    # the polish resolution rule: 0 = auto promotes to the polish
    # budget; explicit caller values (tuned-down positive, force-off
    # negative) are honored verbatim
    assert mip._polish_swap(BnBOptions()).swap_rounds \
        == bnb.POLISH_SWAP_ROUNDS
    assert mip._polish_swap(BnBOptions(swap_rounds=8)).swap_rounds == 8
    assert mip._polish_swap(BnBOptions(swap_rounds=-1)).swap_rounds == -1
    # at the default budget the repair is a guaranteed no-op (the hot
    # path pays nothing before the early return)
    assert bnb.sos1_swap_repair(None, None, None, None, None,
                                BnBOptions()) is None
    assert bnb.sos1_swap_repair(None, None, None, None, None,
                                BnBOptions(swap_rounds=-1)) is None
