# Extension plane + convergers (the TPU analogs of
# ref:mpisppy/extensions/ and ref:mpisppy/convergers/).
import functools
import os

import numpy as np
import pytest

from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.convergers import (
    FractionalConverger, NormRhoConverger, PrimalDualConverger,
)
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.extensions import MultiExtension
from mpisppy_tpu.extensions.extension import Extension
from mpisppy_tpu.extensions.fixer import Fixer
from mpisppy_tpu.extensions.mipgapper import Gapper
from mpisppy_tpu.extensions.phtracker import PHTracker
from mpisppy_tpu.extensions.rho_setters import (
    CoeffRho, NormRhoUpdater, SepRho,
)
from mpisppy_tpu.models import farmer, sslp
from mpisppy_tpu.ops import pdhg
from mpisppy_tpu.utils.wtracker import WTracker, WTrackerExtension

OPTS = ph_mod.PHOptions(default_rho=1.0, max_iterations=30,
                        conv_thresh=1e-3, subproblem_windows=8,
                        pdhg=pdhg.PDHGOptions(tol=1e-7))


def farmer_batch(n=3):
    specs = [farmer.scenario_creator(nm, num_scens=n)
             for nm in farmer.scenario_names_creator(n)]
    return batch_mod.from_specs(specs)


def test_hook_call_order():
    calls = []

    class Probe(Extension):
        def pre_iter0(self):
            calls.append("pre_iter0")

        def post_iter0(self):
            calls.append("post_iter0")

        def miditer(self):
            calls.append("miditer")

        def enditer(self):
            calls.append("enditer")

        def post_everything(self):
            calls.append("post_everything")

    algo = ph_mod.PH(OPTS, farmer_batch(), extensions=Probe)
    algo.ph_main()
    assert calls[0] == "pre_iter0"
    assert calls[1] == "post_iter0"
    assert calls[-1] == "post_everything"
    assert "miditer" in calls and "enditer" in calls
    # miditer precedes enditer within an iteration
    assert calls.index("miditer") < calls.index("enditer")


def test_multi_extension_fans_out():
    seen = []

    class A(Extension):
        def enditer(self):
            seen.append("A")

    class B(Extension):
        def enditer(self):
            seen.append("B")

    ext = functools.partial(MultiExtension, ext_classes=[A, B])
    algo = ph_mod.PH(OPTS, farmer_batch(), extensions=ext)
    algo.ph_main()
    assert seen[:2] == ["A", "B"]


def test_fixer_fixes_converged_integers():
    # integer sslp: after PH converges the binary x slots should get
    # fixed; subsequent solves keep them constant.
    inst = sslp.synthetic_instance(5, 10, 0)
    specs = [sslp.scenario_creator(nm, instance=inst, num_scens=4)
             for nm in sslp.scenario_names_creator(4)]
    b = batch_mod.from_specs(specs)
    opts = ph_mod.PHOptions(default_rho=20.0, max_iterations=40,
                            conv_thresh=0.0, subproblem_windows=10,
                            pdhg=pdhg.PDHGOptions(tol=1e-7))
    fixer_holder = {}

    def make_fixer(ph):
        f = Fixer(ph)
        f.lag = 3
        f.tol = 5e-2
        fixer_holder["f"] = f
        return f

    algo = ph_mod.PH(opts, b, extensions=make_fixer)
    algo.ph_main()
    f = fixer_holder["f"]
    assert f.nfixed() > 0
    # fixed slots have collapsed boxes in the live batch
    cols = np.asarray(algo.batch.nonant_idx)[f.fixed_mask]
    l = np.asarray(algo.batch.qp.l)[..., cols]
    u = np.asarray(algo.batch.qp.u)[..., cols]
    np.testing.assert_allclose(l, u, atol=1e-6)


def test_gapper_schedule():
    sched = {2: 4, 5: 12}
    algo = ph_mod.PH(OPTS, farmer_batch(),
                     extensions=functools.partial(Gapper, schedule=sched))
    algo.ph_main()
    assert algo.options.subproblem_windows == 12


def test_sep_rho_and_coeff_rho():
    for cls in (SepRho, CoeffRho):
        algo = ph_mod.PH(OPTS, farmer_batch(), extensions=cls)
        algo.ph_main()
        rho = np.asarray(algo.state.rho)
        assert rho.shape == (algo.batch.num_nonants,)
        assert (rho > 0).all()
        # per-variable: costs differ across crops, so rho must too
        assert rho.std() > 0


def test_norm_rho_updater_runs():
    algo = ph_mod.PH(OPTS, farmer_batch(), extensions=NormRhoUpdater)
    conv, eobj, _ = algo.ph_main()
    assert np.isfinite(eobj)


def test_wtracker(tmp_path):
    holder = {}

    def make(ph):
        e = WTrackerExtension(ph, window=5)
        holder["e"] = e
        return e

    algo = ph_mod.PH(OPTS, farmer_batch(), extensions=make)
    algo.ph_main()
    tr: WTracker = holder["e"].tracker
    mean, std = tr.compute_moving_stats()
    assert mean.shape == (3, algo.batch.num_nonants)
    fn = tmp_path / "w.csv"
    tr.write_csv(str(fn))
    assert fn.exists()


def test_phtracker(tmp_path):
    folder = str(tmp_path / "trk")
    algo = ph_mod.PH(OPTS, farmer_batch(),
                     extensions=functools.partial(
                         PHTracker, folder=folder, track_nonants=True,
                         track_duals=True, track_xbars=True,
                         track_scen_gaps=True, plots=True))
    algo.ph_main()
    cyl = os.path.join(folder, "hub")
    # per-quantity csvs (ref:phtracker.py per-cylinder folder layout)
    for t in ("convergence", "gaps", "bounds", "nonants", "duals",
              "xbars", "scen_gaps"):
        fn = os.path.join(cyl, f"{t}.csv")
        assert os.path.exists(fn), t
        lines = open(fn).read().strip().splitlines()
        assert len(lines) >= 2, t  # header + >=1 iteration
    # xbars track one value per nonant slot + the iteration column
    hdr = open(os.path.join(cyl, "xbars.csv")).readline().strip()
    assert len(hdr.split(",")) == 1 + algo.batch.num_nonants
    # plots render when matplotlib is present
    assert os.path.exists(os.path.join(cyl, "convergence.png"))


def test_primal_dual_converger():
    algo = ph_mod.PH(OPTS, farmer_batch(),
                     converger=functools.partial(PrimalDualConverger,
                                                 tol=50.0))
    algo.ph_main()
    conv_obj = algo.converger_object
    assert conv_obj.conv_value is not None
    assert len(conv_obj.trace) >= 1


def test_fractional_converger_continuous_is_trivial():
    algo = ph_mod.PH(OPTS, farmer_batch(), converger=FractionalConverger)
    algo.ph_main()
    # farmer has no integer nonants -> converged immediately at iter 1
    assert algo._iter == 1


def test_norm_rho_converger():
    algo = ph_mod.PH(OPTS, farmer_batch(), converger=NormRhoConverger)
    algo.ph_main()
    assert algo.converger_object.conv_value is not None


def test_xhat_closest(tmp_path):
    from mpisppy_tpu.extensions import XhatClosest

    algo = ph_mod.PH(OPTS, farmer_batch(),
                     extensions=functools.partial(
                         XhatClosest, options={"keep_solution": True}))
    algo.ph_main()
    obj = algo._final_xhat_closest_obj
    # farmer with a feasible closest-scenario candidate: finite objective
    # at most trivially below the EF optimum's magnitude scale
    assert obj is not None and np.isfinite(obj)
    assert hasattr(algo, "_xhat_closest_xhat")
    assert algo._xhat_closest_xhat.shape == (algo.batch.num_nonants,)
    # the incumbent from a feasible candidate upper-bounds the optimum
    assert obj >= -108390.0 - 1.0


def test_diagnoser_writes_files(tmp_path):
    from mpisppy_tpu.extensions import Diagnoser

    outdir = str(tmp_path / "diag")
    opts = ph_mod.PHOptions(default_rho=1.0, max_iterations=3,
                            conv_thresh=0.0, subproblem_windows=4)
    algo = ph_mod.PH(opts, farmer_batch(),
                     extensions=functools.partial(
                         Diagnoser, options={"diagnoser_outdir": outdir}))
    algo.ph_main()
    files = sorted(os.listdir(outdir))
    assert len(files) == 3  # one .dag per scenario
    lines = open(os.path.join(outdir, files[0])).read().strip().split("\n")
    assert len(lines) >= 3  # post_iter0 + each enditer
    it, obj = lines[0].split(",")
    assert int(it) == 0 and np.isfinite(float(obj))
    # refuses to clobber an existing directory (ref quits; we raise)
    with pytest.raises(RuntimeError):
        Diagnoser(algo, options={"diagnoser_outdir": outdir})


def test_minmaxavg(capsys):
    from mpisppy_tpu.extensions import MinMaxAvg

    opts = ph_mod.PHOptions(default_rho=1.0, max_iterations=3,
                            conv_thresh=0.0, subproblem_windows=4)
    algo = ph_mod.PH(opts, farmer_batch(),
                     extensions=functools.partial(
                         MinMaxAvg, compstr="objective"))
    algo.ph_main()
    out = capsys.readouterr().out
    assert "###  objective: avg, min, max, max-min" in out
    ext = algo.extobject
    avgv, minv, maxv = ext.avg_min_max()
    assert minv <= avgv <= maxv
