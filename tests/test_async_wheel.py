# Async wheel (ISSUE 11): the double-buffered stale exchange plane
# (algos/async_wheel.AsyncFusedPH + cylinders/hub.AsyncPHHub) overlaps
# host exchange with device iterations.  Contracts tested here:
#
#   * staleness 0 is the synchronous degrade — BIT-IDENTICAL wheel
#     trajectories (bounds, trace rows, checkpoint bytes) on farmer and
#     hydro;
#   * staleness >= 1 still CERTIFIES: the published outer/inner bounds
#     match the synchronous wheel's within restart-recheck tolerance on
#     farmer, hydro, and uc (stale planes delay bounds, never
#     invalidate them — L(W) is certified at ANY W, every candidate
#     keeps its feasibility gate);
#   * the async-exchange fault seams (dropped plane write, torn swap,
#     slow harvest) never break the certified bracket, and a genuinely
#     wedged exchange still trips the PR-8 hub watchdog;
#   * the pipelined kernel-counter harvest (begin now / complete next
#     sync, flushed at finalize) never undercounts exported totals;
#   * plane staleness + host/device overlap are observable in
#     `telemetry analyze`, and PlaneTicket keeps the dispatch layer's
#     result-or-typed-failure contract.
import os
import time

import numpy as np
import pytest

import jax.numpy as jnp

from mpisppy_tpu.algos import async_wheel as aw
from mpisppy_tpu.algos import fused_wheel as fw
from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.cylinders import AsyncPHHub, PHHub
from mpisppy_tpu.cylinders.spoke import (
    EFOuterBound, EFXhatInnerBound, FusedLagrangianOuterBound,
    FusedSlamHeuristic, FusedXhatShuffleInnerBound, FusedXhatXbarInnerBound,
)
from mpisppy_tpu.models import farmer, hydro, uc
from mpisppy_tpu.ops import pdhg
from mpisppy_tpu.resilience.faults import AsyncExchangeFault, FaultPlan
from mpisppy_tpu.spin_the_wheel import WheelSpinner

FARMER_EF_OBJ = -108390.0


def farmer_batch(num_scens=3):
    specs = [farmer.scenario_creator(nm, num_scens=num_scens)
             for nm in farmer.scenario_names_creator(num_scens)]
    return batch_mod.from_specs(specs)


def farmer_ph_opts(max_iterations=120):
    return ph_mod.PHOptions(
        default_rho=1.0, max_iterations=max_iterations, conv_thresh=0.0,
        subproblem_windows=10, pdhg=pdhg.PDHGOptions(tol=1e-7))


FARMER_WOPTS = fw.FusedWheelOptions(
    slam_windows=2, shuffle_windows=4,
    slam_sense_max=False,  # farmer: acreage minimization
    lag_pdhg=pdhg.PDHGOptions(tol=1e-7),
    xhat_pdhg=pdhg.PDHGOptions(tol=1e-7, omega0=0.1, restart_period=80))

ALL_FUSED_SPOKES = [
    {"spoke_class": FusedLagrangianOuterBound, "opt_kwargs": {"options": {}}},
    {"spoke_class": FusedXhatXbarInnerBound, "opt_kwargs": {"options": {}}},
    {"spoke_class": FusedXhatShuffleInnerBound,
     "opt_kwargs": {"options": {}}},
    {"spoke_class": FusedSlamHeuristic, "opt_kwargs": {"options": {}}},
]


def wheel_dict(batch, staleness=None, rel_gap=1e-2, max_iterations=120,
               ph_opts=None, wheel_options=None, hub_extra=None):
    """Hub dict for the synchronous pair (staleness None) or the async
    pair at the given staleness bound (0 = synchronous degrade)."""
    hub_opts = {"rel_gap": rel_gap}
    hub_opts.update(hub_extra or {})
    opts = ph_opts or farmer_ph_opts(max_iterations)
    d = {
        "hub_class": PHHub,
        "hub_kwargs": {"options": hub_opts},
        "opt_class": fw.FusedPH,
        "opt_kwargs": {"options": opts, "batch": batch,
                       "wheel_options": wheel_options or FARMER_WOPTS},
    }
    if staleness is not None:
        d["hub_class"] = AsyncPHHub
        d["opt_class"] = aw.AsyncFusedPH
        d["opt_kwargs"]["async_options"] = aw.AsyncWheelOptions(
            staleness=staleness)
        hub_opts["async_staleness"] = staleness
    return d


def spokes():
    return [dict(s) for s in ALL_FUSED_SPOKES]


def trace_rows(ws):
    """Hub trace rows with the wall-clock stamp stripped (the only
    nondeterministic field in a trajectory row)."""
    return [{k: v for k, v in row.items() if k != "t"}
            for row in ws.spcomm.trace]


def assert_ckpt_bytes_equal(path_a, path_b):
    with np.load(path_a) as a, np.load(path_b) as b:
        assert sorted(a.files) == sorted(b.files)
        for k in a.files:
            assert a[k].tobytes() == b[k].tobytes(), \
                f"checkpoint member {k!r} differs"


# ---------------------------------------------------------------------------
# shared runs (module scope: the farmer wheels are reused across tests)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def sync_farmer(tmp_path_factory):
    batch = farmer_batch(3)
    ws = WheelSpinner(wheel_dict(batch), spokes()).spin()
    ckpt = str(tmp_path_factory.mktemp("sync") / "sync.npz")
    ws.spcomm.save_checkpoint(ckpt, background=False)
    return ws, ckpt


@pytest.fixture(scope="module")
def async1_farmer(tmp_path_factory):
    from mpisppy_tpu import telemetry
    path = str(tmp_path_factory.mktemp("async1") / "trace.jsonl")
    bus = telemetry.EventBus()
    bus.subscribe(telemetry.JsonlSink(path))
    batch = farmer_batch(3)
    ws = WheelSpinner(
        wheel_dict(batch, staleness=1,
                   hub_extra={"telemetry_bus": bus}),
        spokes()).spin()
    bus.close()
    return ws, path


# ---------------------------------------------------------------------------
# staleness 0: the synchronous degrade is bit-identical
# ---------------------------------------------------------------------------
def test_staleness0_bit_identical_farmer(sync_farmer, tmp_path):
    ws_sync, ckpt_sync = sync_farmer
    batch = farmer_batch(3)
    ws0 = WheelSpinner(wheel_dict(batch, staleness=0), spokes()).spin()
    # bounds and the full per-iteration trajectory rows are EXACTLY
    # equal — same jitted programs, same host loop
    assert ws0.BestOuterBound == ws_sync.BestOuterBound
    assert ws0.BestInnerBound == ws_sync.BestInnerBound
    assert trace_rows(ws0) == trace_rows(ws_sync)
    # and the persisted wheel state is byte-identical
    ckpt0 = str(tmp_path / "async0.npz")
    ws0.spcomm.save_checkpoint(ckpt0, background=False)
    assert_ckpt_bytes_equal(ckpt0, ckpt_sync)


def hydro_wheel(staleness, rel_gap=1e-2, max_iterations=60):
    num = 9
    specs = [hydro.scenario_creator(nm, branching_factors=(3, 3))
             for nm in hydro.scenario_names_creator(num)]
    tree = hydro.make_tree((3, 3))
    batch = batch_mod.from_specs(specs, tree=tree)
    from mpisppy_tpu.algos import ef as ef_mod
    efp = ef_mod.build_ef(specs, tree=tree)
    opts = ph_mod.PHOptions(default_rho=2.0, max_iterations=max_iterations,
                            conv_thresh=0.0, subproblem_windows=8,
                            pdhg=pdhg.PDHGOptions(tol=1e-6))
    # multistage: x̄-fixing recourse planes are structurally infeasible
    # on hydro (see generic_cylinders._fuse_wheel), so the bracket comes
    # from the classic EF spokes — which exercises the async hub's
    # classic-spoke exchange path too
    sp = [
        {"spoke_class": EFOuterBound,
         "opt_kwargs": {"options": {"ef_problem": efp, "n_windows": 30}}},
        {"spoke_class": EFXhatInnerBound,
         "opt_kwargs": {"options": {"ef_problem": efp, "n_windows": 30}}},
    ]
    hub = wheel_dict(batch, staleness=staleness, rel_gap=rel_gap,
                     ph_opts=opts, wheel_options=fw.FusedWheelOptions())
    return WheelSpinner(hub, sp).spin()


def test_staleness0_bit_identical_hydro(tmp_path):
    ws_sync = hydro_wheel(staleness=None)
    ws0 = hydro_wheel(staleness=0)
    assert ws0.BestOuterBound == ws_sync.BestOuterBound
    assert ws0.BestInnerBound == ws_sync.BestInnerBound
    assert trace_rows(ws0) == trace_rows(ws_sync)
    a, b = str(tmp_path / "sync.npz"), str(tmp_path / "async0.npz")
    ws_sync.spcomm.save_checkpoint(a, background=False)
    ws0.spcomm.save_checkpoint(b, background=False)
    assert_ckpt_bytes_equal(a, b)


# ---------------------------------------------------------------------------
# staleness >= 1: the stale-plane wheel still certifies, and its bounds
# match the synchronous wheel's within restart-recheck tolerance
# ---------------------------------------------------------------------------
def certified(ws, rel_gap=1e-2):
    inner, outer = ws.BestInnerBound, ws.BestOuterBound
    assert np.isfinite(inner) and np.isfinite(outer)
    # same consistency slack as the synchronous wheel tests: the two
    # sides are evaluated by different (comp-compensated) programs
    assert outer <= inner + 2e-3 * abs(inner)
    assert (inner - outer) / abs(inner) <= rel_gap + 1e-6
    return outer, inner


def test_staleness_certifies_and_matches_sync_farmer(sync_farmer,
                                                     async1_farmer):
    out_s, in_s = certified(sync_farmer[0])
    runs = {1: async1_farmer[0]}
    batch = farmer_batch(3)
    runs[2] = WheelSpinner(wheel_dict(batch, staleness=2),
                           spokes()).spin()
    for s, ws in runs.items():
        out_a, in_a = certified(ws)
        # both brackets certify <= 1% around the same optimum, so the
        # published bounds can differ at most at that order
        tol = 1.5e-2 * abs(in_s)
        assert abs(out_a - out_s) <= tol, f"staleness {s} outer drifted"
        assert abs(in_a - in_s) <= tol, f"staleness {s} inner drifted"
        slack = 1.5e-2 * abs(FARMER_EF_OBJ)
        assert out_a <= FARMER_EF_OBJ + slack
        assert in_a >= FARMER_EF_OBJ - slack
        # the theta damping actually engaged (pipelined host read)
        assert ws.opt.last_theta is not None
        assert 0.0 <= ws.opt.last_theta <= 1.0


def test_staleness_certifies_and_matches_sync_hydro():
    ws_sync = hydro_wheel(staleness=None)
    ws1 = hydro_wheel(staleness=1)
    out_s, in_s = certified(ws_sync)
    out_a, in_a = certified(ws1)
    tol = 1.5e-2 * abs(in_s)
    assert abs(out_a - out_s) <= tol
    assert abs(in_a - in_s) <= tol


def test_staleness_matches_sync_uc():
    inst = uc.synthetic_instance(4, 12, seed=1)
    specs = [uc.scenario_creator(nm, instance=inst, num_scens=3)
             for nm in uc.scenario_names_creator(3)]
    batch = batch_mod.from_specs(specs)
    opts = ph_mod.PHOptions(
        default_rho=200.0, max_iterations=40, conv_thresh=0.0,
        subproblem_windows=10, pdhg=pdhg.PDHGOptions(tol=1e-7))
    wopts = fw.FusedWheelOptions()
    sp = [dict(s) for s in ALL_FUSED_SPOKES[:2]]

    def run(staleness):
        return WheelSpinner(
            wheel_dict(batch, staleness=staleness, rel_gap=0.0,
                       ph_opts=opts, wheel_options=wopts),
            [dict(s) for s in sp]).spin()

    ws_sync, ws1 = run(None), run(1)
    # fixed-length runs (uc consensus is stiff — certifying 1% takes
    # hundreds of iterations): the certified bounds published at the
    # same cadence must agree within restart-recheck tolerance, and
    # each bracket must stay internally consistent
    for ws in (ws_sync, ws1):
        assert np.isfinite(ws.BestOuterBound)
        assert np.isfinite(ws.BestInnerBound)
        assert ws.BestOuterBound <= ws.BestInnerBound + 2e-3 * abs(
            ws.BestInnerBound)
    tol = 5e-2 * max(1.0, abs(ws_sync.BestInnerBound))
    assert abs(ws1.BestOuterBound - ws_sync.BestOuterBound) <= tol
    assert abs(ws1.BestInnerBound - ws_sync.BestInnerBound) <= tol


# ---------------------------------------------------------------------------
# chaos: async-exchange faults never break the certified bracket, and a
# wedged exchange still trips the hub watchdog
# ---------------------------------------------------------------------------
def test_async_exchange_faults_keep_certified_bounds():
    from mpisppy_tpu import telemetry

    plan = FaultPlan(seed=11, exchanges=(
        AsyncExchangeFault("drop_plane_write", at_iters=(3, 9)),
        AsyncExchangeFault("torn_swap", at_iters=(5, 12)),
        AsyncExchangeFault("slow_harvest", at_iters=(4,), delay_s=0.02),
    ))
    seen = []

    class _Probe:
        def handle(self, e):
            seen.append(e)

    bus = telemetry.EventBus()
    bus.subscribe(_Probe())
    batch = farmer_batch(3)
    ws = WheelSpinner(
        wheel_dict(batch, staleness=1,
                   hub_extra={"fault_plan": plan, "telemetry_bus": bus}),
        spokes()).spin()
    fired = {d for seam, d in plan.fired if seam == "exchange"}
    assert any("drop_plane_write" in d for d in fired)
    assert any("torn_swap" in d for d in fired)
    assert any("slow_harvest" in d for d in fired)
    # the dropped/torn writes must be OBSERVABLE: the plane-write
    # events report the generation the slot actually holds, so the
    # recorded staleness exceeds the configured bound at the faults
    stals = [e.data["staleness"] for e in seen
             if e.kind == "plane-write"]
    assert stals and max(stals) > 1
    # a dropped/torn plane perturbs the trajectory but can never
    # invalidate a published bound: the faulted wheel still certifies
    # the fault-free bracket
    out_a, in_a = certified(ws)
    slack = 1.5e-2 * abs(FARMER_EF_OBJ)
    assert out_a <= FARMER_EF_OBJ + slack
    assert in_a >= FARMER_EF_OBJ - slack


def test_watchdog_trips_on_wedged_exchange(async1_farmer, tmp_path):
    """A genuinely wedged exchange (slow_harvest >> watchdog budget)
    must still trip the PR-8 hub watchdog under the async hub — the
    pipelined halves may not hide a stalled host."""
    del async1_farmer  # ordering only: jit caches warm, no compile stall
    plan = FaultPlan(seed=12, exchanges=(
        AsyncExchangeFault("slow_harvest", at_iters=(4,), delay_s=2.5),))
    batch = farmer_batch(3)
    codes = []
    ws = WheelSpinner(
        wheel_dict(batch, staleness=1, max_iterations=8, rel_gap=0.0,
                   hub_extra={
                       "fault_plan": plan,
                       "checkpoint_path": str(tmp_path / "wd.npz"),
                       "watchdog_budget_s": 1.0,
                       "watchdog_interval_s": 0.05,
                       "watchdog_action": "abort"}),
        spokes()).build()
    ws.spcomm._watchdog.abort_fn = codes.append
    ws.spin()
    assert codes == [75], "watchdog never tripped on the wedged exchange"
    assert ws.spcomm._watchdog.trips >= 1


# ---------------------------------------------------------------------------
# checkpoint restore: the resumed async wheel re-seeds its plane slots
# ---------------------------------------------------------------------------
def test_async_checkpoint_resume(tmp_path):
    """load_checkpoint skips _iter0_impl (which seeds the exchange
    plane), so the async driver must lazily re-seed its slots from the
    restored state — a preempted --async-staleness run has to RESUME,
    not crash on its first iteration (the PR-2 preemption contract)."""
    batch = farmer_batch(3)
    ckpt = str(tmp_path / "aw.ckpt.npz")
    hub_extra = {"checkpoint_path": ckpt, "checkpoint_every_s": 0.0}
    ws1 = WheelSpinner(
        wheel_dict(batch, staleness=1, rel_gap=1e-4, max_iterations=12,
                   hub_extra=hub_extra), spokes()).spin()
    assert os.path.exists(ckpt)
    it1 = ws1.spcomm._iter

    ws2 = WheelSpinner(
        wheel_dict(batch, staleness=1, rel_gap=1e-4, max_iterations=30,
                   hub_extra=hub_extra), spokes()).build()
    ws2.spcomm.load_checkpoint(ckpt)
    assert 0 < ws2.spcomm._iter <= it1
    ws2.spin()
    assert ws2.spcomm._iter > it1
    assert np.isfinite(ws2.BestOuterBound)
    assert np.isfinite(ws2.BestInnerBound)
    assert ws2.BestOuterBound <= ws2.BestInnerBound + 2e-3 * abs(
        ws2.BestInnerBound)


# ---------------------------------------------------------------------------
# pipelined kernel-counter harvest: exported totals never undercount
# ---------------------------------------------------------------------------
def test_pipelined_counter_harvest_never_undercounts():
    from mpisppy_tpu import telemetry
    from mpisppy_tpu.telemetry import counters as kcounters
    from mpisppy_tpu.telemetry import metrics as metrics_mod
    seen = []

    class _Probe:
        def handle(self, e):
            seen.append(e)

    bus = telemetry.EventBus()
    bus.subscribe(_Probe())
    batch = farmer_batch(3)
    opts = ph_mod.PHOptions(
        default_rho=1.0, max_iterations=6, conv_thresh=0.0,
        subproblem_windows=10,
        pdhg=pdhg.PDHGOptions(tol=1e-7, telemetry=True))
    ws = WheelSpinner(
        wheel_dict(batch, staleness=1, rel_gap=0.0, ph_opts=opts,
                   hub_extra={"telemetry_bus": bus}),
        spokes()).spin()
    # finalize flushed the pending begin_harvest AND took one final
    # synchronous harvest: the registry mirror must equal a direct
    # harvest of the final device state exactly (no lag, no undercount)
    direct = kcounters.harvest_state(ws.opt.state.solver,
                                     include_ring=False)
    for name in ("pdhg_iterations_total", "pdhg_restarts_total",
                 "pdhg_windows_total"):
        assert metrics_mod.REGISTRY.get(name, cyl="hub") == direct[name]
    assert direct["pdhg_iterations_total"] > 0
    # the flush path discards the pending one-sync-stale snapshot
    # (superseded by the fresh synchronous harvest) instead of folding
    # it alongside: every sync stamps ONE kernel-counters row, and the
    # final hub_iter carries at most one extra — the flush's exact
    # catch-up row, not a stale duplicate
    from collections import Counter
    counts = Counter(e.hub_iter for e in seen
                     if e.kind == "kernel-counters" and e.cyl == "hub")
    assert counts
    final = max(counts)
    assert all(c == 1 for it, c in counts.items() if it != final)
    assert counts[final] <= 2


# ---------------------------------------------------------------------------
# observability: staleness + overlap in telemetry analyze
# ---------------------------------------------------------------------------
def test_analyze_reports_staleness_and_overlap(async1_farmer):
    from mpisppy_tpu.telemetry import analyze as an
    ws, path = async1_farmer
    rows = an.load_trace(path)
    rep = an.analyze(an.build_run_model(rows))
    sec = rep["async_wheel"]
    assert sec is not None
    n_iters = ws.spcomm._iter
    # one plane write per iterk; the iter0 sync has none
    assert sec["plane_writes"] == n_iters - 1
    # staleness bound 1 and no faults: every write lands exactly 1 stale
    assert sec["staleness_mean"] == 1.0
    assert sec["staleness_max"] == 1
    assert sec["syncs"] == n_iters
    assert 0.0 < sec["overlapped_host_frac"] <= 1.0
    assert 0.0 <= sec["theta_min"] <= sec["theta_last"] <= 1.0
    assert "async wheel" in an.render_report(rep)
    # raw event schema: plane-write + exchange-overlap rows are present
    kinds = {r["kind"] for r in rows}
    assert {"plane-write", "exchange-overlap"} <= kinds
    from mpisppy_tpu.telemetry import metrics as metrics_mod
    assert metrics_mod.REGISTRY.get("async_plane_writes_total") \
        >= n_iters - 1


# ---------------------------------------------------------------------------
# dispatch: PlaneTicket keeps result-or-typed-failure semantics
# ---------------------------------------------------------------------------
def test_plane_ticket_deadline_and_fast_path():
    from mpisppy_tpu.dispatch.scheduler import (
        DispatchOptions, SolveFailed, SolveScheduler,
    )
    sched = SolveScheduler(DispatchOptions())

    # fast path: the dispatch is async XLA work, value is usable
    # immediately and result() settles it
    t = sched.submit_plane(lambda a: a * 2, jnp.ones((4,)), label="ok")
    np.testing.assert_allclose(np.asarray(t.result()), 2.0)
    assert t.done()

    class Wedged:
        def block_until_ready(self):
            time.sleep(30)

        def is_ready(self):
            return False

    t0 = time.perf_counter()
    tk = sched.submit_plane(lambda: Wedged(), label="wedged",
                            deadline_s=0.1)
    with pytest.raises(SolveFailed) as ei:
        tk.result()
    assert ei.value.reason == "deadline"
    assert time.perf_counter() - t0 < 5.0, "wait was not bounded"

    # an expired deadline on a result that already LANDED is not a
    # miss: the readiness re-check must return the value (the
    # SolveTicket expired-deadline recovery semantics)
    late = sched.submit_plane(lambda a: a + 1, jnp.ones(()),
                              label="late", deadline_s=0.05)
    np.asarray(late.value)          # force the result to land
    time.sleep(0.1)                 # ... and the deadline to pass
    np.testing.assert_allclose(np.asarray(late.result()), 2.0)

    # ... and past the deadline an EXPLICIT timeout grants a fresh
    # recovery wait (the dispatch may still land late)
    class Slow:
        def __init__(self):
            self.t0 = time.perf_counter()

        def is_ready(self):
            return time.perf_counter() - self.t0 > 0.3

        def block_until_ready(self):
            while not self.is_ready():
                time.sleep(0.01)

    rec = sched.submit_plane(Slow, label="recover", deadline_s=0.05)
    time.sleep(0.1)                 # deadline expired, not yet ready
    assert rec.result(timeout=5.0) is rec.value   # recovery succeeds
    with pytest.raises(SolveFailed):
        sched.submit_plane(Slow, label="bare", deadline_s=-1.0).result()

    st = sched.stats()
    assert st["plane_tickets"] == 5
    assert st["plane_deadline_misses"] == 2


def test_projective_theta_rejects_adverse_plane():
    """APH's Step-16 rejection must be REACHABLE: a plane whose era
    duals point against the current iterate drives phi <= 0 and theta
    to exactly 0 (pre-floor).  Forming y from the current W instead of
    the plane-era W_plane degenerates phi to rho*E||x - z||^2 >= 0 and
    makes rejection impossible — the regression this test pins."""
    from mpisppy_tpu.algos import aph as aph_mod
    batch = farmer_batch(3)
    rng = np.random.default_rng(7)
    S, N = batch.num_scenarios, batch.num_nonants
    x = jnp.asarray(rng.normal(size=(S, N)))
    z = jnp.asarray(rng.normal(size=(S, N)))
    W = jnp.asarray(rng.normal(size=(S, N)))
    xbar, _ = batch.node_average(x)
    rho = jnp.ones((N,))
    # aligned plane (duals unchanged): phi = rho*E||x-z||^2 > 0
    th_aligned = aph_mod.projective_theta(batch, x, xbar, W, z, W, rho)
    assert float(th_aligned) > 0.0
    # adverse plane: W - W_plane = 2*rho*(x - z) makes
    # phi = -rho*E||x-z||^2 < 0 -> Step-16 rejection, theta == 0
    W_plane = W - 2.0 * rho * (x - z)
    th_adverse = aph_mod.projective_theta(batch, x, xbar, W, z,
                                          W_plane, rho)
    assert float(th_adverse) == 0.0


def test_plane_ticket_failed_dispatch_is_typed():
    """A plane dispatch whose async computation ERRORED surfaces at
    result() as SolveFailed('exception') — never as poisoned arrays
    returned as success (the result-or-typed-failure contract), on
    every wait path: unbounded, ready fast path, and the bounded
    waiter thread."""
    from mpisppy_tpu.dispatch.scheduler import (
        DispatchOptions, SolveFailed, SolveScheduler,
    )
    sched = SolveScheduler(DispatchOptions())

    class Failed:
        def is_ready(self):
            return True

        def block_until_ready(self):
            raise RuntimeError("XLA computation failed")

    class FailedUnready(Failed):
        def is_ready(self):
            return False

    # unbounded wait
    with pytest.raises(SolveFailed) as ei:
        sched.submit_plane(Failed, label="boom").result()
    assert ei.value.reason == "exception"
    # ready fast path under a live deadline
    with pytest.raises(SolveFailed) as ei2:
        sched.submit_plane(Failed, label="boom-fast",
                           deadline_s=30.0).result()
    assert ei2.value.reason == "exception"
    # bounded waiter-thread path
    with pytest.raises(SolveFailed) as ei3:
        sched.submit_plane(FailedUnready, label="boom-wait",
                           deadline_s=30.0).result(timeout=30.0)
    assert ei3.value.reason == "exception"
    # a failed dispatch is not a deadline miss
    assert sched.stats()["plane_deadline_misses"] == 0


# ---------------------------------------------------------------------------
# regress gates: the committed smoke artifact witnesses the milestone
# ---------------------------------------------------------------------------
def test_bench_r07_witnesses_overhead_milestone():
    """BENCH_r07.json is the committed witness for the ISSUE-11
    `wheel_overhead_async.overhead_factor <= 1.3` MILESTONE key
    (graftlint's schema-drift pass requires every MILESTONE pattern to
    match a committed artifact); its smoke value meets the bound, so a
    gate anchored on it BINDS the ratchet."""
    import os

    from mpisppy_tpu.telemetry import regress

    r07 = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_r07.json")
    rep = regress.gate_paths(r07, r07)
    assert rep["ok"], rep["regressions"]
    ms = {r["metric"]: r for r in rep["milestones"]}
    row = ms["wheel_overhead_async.overhead_factor"]
    assert row["status"] == "met" and row["binding"]
    assert row["milestone"] == 1.3

    # and a later artifact slipping past the acceptance line fails the
    # plain (ratchet) gate — no --milestones flag needed
    import json as _json
    import tempfile
    slipped = _json.load(open(r07))
    slipped["parsed"]["wheel_overhead_async"]["overhead_factor"] = 1.31
    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        _json.dump(slipped, f)
    rep2 = regress.gate_paths(r07, f.name)
    assert not rep2["ok"]
    assert any(r["metric"] == "wheel_overhead_async.overhead_factor"
               for r in rep2["regressions"])


# ---------------------------------------------------------------------------
# CLI wiring: --async-staleness swaps in the async pair
# ---------------------------------------------------------------------------
def test_fuse_wheel_swaps_async_classes():
    from mpisppy_tpu import generic_cylinders as gc
    from mpisppy_tpu.utils.config import Config

    def fused_cfg(extra):
        cfg = Config()
        cfg.popular_args()
        cfg.fused_wheel_args()
        cfg.parse_command_line("t", ["--fused-wheel"] + extra)
        return cfg

    base_hub = {"hub_class": PHHub, "hub_kwargs": {"options": {}},
                "opt_kwargs": {"options": farmer_ph_opts()}}
    sp = [{"spoke_class": __import__(
        "mpisppy_tpu.cylinders.spoke", fromlist=["x"]
    ).LagrangianOuterBound, "opt_kwargs": {"options": {}}}]

    hub, _ = gc._fuse_wheel(fused_cfg(["--async-staleness", "2",
                                       "--async-exchange-deadline-s",
                                       "2.5"]),
                            dict(base_hub), sp)
    assert hub["hub_class"] is AsyncPHHub
    assert hub["opt_class"] is aw.AsyncFusedPH
    assert hub["opt_kwargs"]["async_options"].staleness == 2
    assert hub["opt_kwargs"]["async_options"].exchange_deadline_s == 2.5
    assert hub["hub_kwargs"]["options"]["async_staleness"] == 2

    hub0, _ = gc._fuse_wheel(fused_cfg([]), dict(base_hub), sp)
    assert hub0["hub_class"] is PHHub
    assert hub0["opt_class"] is fw.FusedPH
    assert "async_options" not in hub0["opt_kwargs"]
