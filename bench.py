"""North-star benchmark (BASELINE.md): wall-clock to 1% CERTIFIED gap
and PH throughput on sslp + uc, on real hardware.

Prints ONE JSON line with the headline metric:
    {"metric", "value", "unit", "vs_baseline", "detail": {...}}
and writes the full suite (scenario sweep, uc FWPH config, MFU/HBM
estimates) to BENCH_DETAIL.json.  Methodology: BENCH_METHODOLOGY.md.

Headline: seconds to drive the certified relative gap (best certified
outer bound from trivial + Lagrangian bounds vs best feasible incumbent
from the xhat plane) under 1% on LP-relaxed sslp_15_45 at 10k scenarios
— the BASELINE.md item-2 configuration run the way the reference runs
it (PH hub + Lagrangian spoke + xhat spoke,
ref:paperruns + generic_cylinders decomp path), except every "cylinder"
is a batched device computation.

`vs_baseline` = estimated wall-clock of the reference's execution model
on the same run divided by ours.  The reference model is one sequential
CPU LP solve per scenario per PH iteration per cylinder rank
(ref:mpisppy/spopt.py:250-341); we time scipy/HiGHS on a sample of the
same LPs and charge the reference (iterations x scenarios x LPs/iter)
at that rate on 64 ranks (the BASELINE.md comparison cluster).  This is
an ESTIMATE, not a measured mpi-sppy run — Gurobi/MPI are not in this
image; see BENCH_METHODOLOGY.md for exactly what is and is not charged.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

QUICK = bool(int(os.environ.get("BENCH_QUICK", "0")))
SMOKE = bool(int(os.environ.get("BENCH_SMOKE", "0")))  # CI code-path check


def _enable_compile_cache():
    """Persistent XLA compilation cache: each bench phase runs in its
    own subprocess (worker-crash isolation), and without the cache every
    child pays the full remote compile (~8s/program through the axon
    tunnel) again."""
    import jax
    jax.config.update("jax_compilation_cache_dir", os.path.join(
        os.path.dirname(os.path.abspath(__file__)) or ".", ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

def metrics_schema_probe() -> str:
    """The metrics-snapshot schema this bench embeds in BENCH_*.json —
    imported from the telemetry exporter, never duplicated, so artifact
    entries and live --metrics-snapshot files stay comparable by
    construction (tests/test_telemetry.py asserts it)."""
    from mpisppy_tpu.telemetry import metrics as metrics_mod
    return metrics_mod.SNAPSHOT_SCHEMA


#: Iteration precision for every wheel/sweep/mfu bench phase (ISSUE 8):
#: bf16x3 halves HBM bytes and MXU passes per iteration matvec — the
#: only lever left on a bandwidth-bound iteration (809 of 819 GB/s at
#: S=10k).  Certificates are unaffected by construction: restart
#: candidate scoring, convergence tests, and every published bound
#: re-check at full precision (ops/pdhg.py PDHGOptions.iter_precision;
#: accuracy contract in docs/precision.md).  Artifacts disclose the
#: mode next to every phase (iter_precision field).
ITER_PRECISION = os.environ.get("BENCH_ITER_PRECISION", "bf16x3") or None

SSLP_SERVERS, SSLP_CLIENTS = 15, 45
SSLP_SCENS = 16 if SMOKE else (1_000 if QUICK else 10_000)
SWEEP = [16] if SMOKE else ([1_000, 10_000] if QUICK
                            else [1_000, 10_000, 100_000])
UC_SCENS = 3 if SMOKE else (20 if QUICK else 100)
MAX_WHEEL_ITERS = 5 if SMOKE else 300
GAP_TARGET = 0.01
BASELINE_RANKS = 64


def _dist(times):
    """Distribution summary (VERDICT r4 #7: report the distribution of
    measured solve times, not just the mean)."""
    t = np.asarray(times)
    return {"n": int(t.size), "mean": float(t.mean()),
            "p10": float(np.percentile(t, 10)),
            "p50": float(np.percentile(t, 50)),
            "p90": float(np.percentile(t, 90)),
            "max": float(t.max())}


def _split_rows(sp):
    """ScenarioSpec constraint rows -> (A_ub, b_ub, A_eq, b_eq)."""
    A = sp.A.toarray() if hasattr(sp.A, "toarray") else np.asarray(sp.A)
    A_ub, b_ub, A_eq, b_eq = [], [], [], []
    for i in range(A.shape[0]):
        if sp.bl[i] == sp.bu[i]:
            A_eq.append(A[i]); b_eq.append(sp.bu[i])
            continue
        if np.isfinite(sp.bu[i]):
            A_ub.append(A[i]); b_ub.append(sp.bu[i])
        if np.isfinite(sp.bl[i]):
            A_ub.append(-A[i]); b_ub.append(-sp.bl[i])
    return A_ub, b_ub, A_eq, b_eq


def time_scipy_baseline(specs, sample=32):
    """Seconds per scenario LP via scipy/HiGHS (the reference's
    sequential per-rank solve model), MEASURED on the same LP instances
    the benchmarked batch solves.  Returns a distribution dict."""
    from scipy.optimize import linprog

    times = []
    for sp in specs[:sample]:
        A_ub, b_ub, A_eq, b_eq = _split_rows(sp)
        t0 = time.perf_counter()
        res = linprog(sp.c, A_ub=np.array(A_ub), b_ub=np.array(b_ub),
                      A_eq=np.array(A_eq) if A_eq else None,
                      b_eq=np.array(b_eq) if b_eq else None,
                      bounds=list(zip(sp.l, sp.u)), method="highs")
        times.append(time.perf_counter() - t0)
        assert res.status == 0
    return _dist(times)


def time_scipy_milp_baseline(specs, sample=16, time_limit=60.0):
    """Seconds per scenario MIP via scipy/HiGHS MILP — the anchor for
    what the reference's EXACT integer subproblem solves cost (its PH on
    sslp dispatches one MIQP per scenario per iteration to Gurobi,
    ref:mpisppy/spopt.py:99-247; HiGHS-without-prox is a lower bound on
    that cost).  Returns (distribution, objectives)."""
    from scipy.optimize import Bounds, LinearConstraint, milp

    times, objs = [], []
    for sp in specs[:sample]:
        A = sp.A.toarray() if hasattr(sp.A, "toarray") else np.asarray(sp.A)
        integrality = (np.asarray(sp.integer, float)
                       if sp.integer is not None
                       else np.zeros(sp.c.shape[0]))
        t0 = time.perf_counter()
        res = milp(c=sp.c,
                   constraints=LinearConstraint(A, sp.bl, sp.bu),
                   bounds=Bounds(sp.l, sp.u),
                   integrality=integrality,
                   options={"time_limit": time_limit})
        dt = time.perf_counter() - t0
        if res.status != 0:
            # censored sample (hit time_limit on a loaded host): record
            # the truncated time, flag it, keep the phase alive
            times.append(dt)
            objs.append(float("nan"))
            continue
        times.append(dt)
        objs.append(float(res.fun))
    return _dist(times), objs


def _sslp_batch(num_scens):
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.models import sslp

    inst = sslp.synthetic_instance(SSLP_SERVERS, SSLP_CLIENTS, seed=0)
    names = sslp.scenario_names_creator(num_scens)
    specs = [sslp.scenario_creator(nm, instance=inst, num_scens=num_scens,
                                   lp_relax=True)
             for nm in names]
    from mpisppy_tpu.core.batch import from_specs
    return from_specs(specs), specs


def _flops_per_ph_iter(batch, ph_opts):
    """FLOPs model for one PH iteration: dominated by PDHG matvec pairs.

    Shared dense A (m, n): matvec + rmatvec = 4*m*n flops per scenario
    per PDHG iteration (2 flops per multiply-add).  ELL A: 4*m*k.
    PDHG iterations per PH iter = subproblem_windows * restart_period
    (+ the restart-candidate KKT evaluations, ~2 extra matvec pairs per
    window, counted below)."""
    S = batch.num_scenarios
    A = batch.qp.A
    if hasattr(A, "k"):
        per_mv = 4.0 * A.m * A.k
    else:
        per_mv = 4.0 * A.shape[-2] * A.shape[-1]
    iters = ph_opts.subproblem_windows * (ph_opts.pdhg.restart_period + 4)
    return S * per_mv * iters


def bench_wheel_to_gap(batch, label, spokes_cfg, ph_opts, wheel_opts=None,
                       extra_hub_opts=None, extra_opt_kwargs=None):
    """Wall-clock from wheel start to certified rel_gap <= GAP_TARGET.

    Crash-resilient: the wheel checkpoints its full state every ~30s
    (hub.save_checkpoint); if the TPU worker dies mid-phase, the parent
    retries the phase once and this function RESUMES from the
    checkpoint, with elapsed time carried across the crash so the
    reported seconds stay honest.  Returns dict with seconds,
    iterations, bounds, and a `metrics_snapshot` in the telemetry
    exporter's schema (telemetry/metrics.py SNAPSHOT_SCHEMA) with the
    on-device counter totals — BENCH_*.json entries and live
    --metrics-snapshot files are directly comparable."""
    import dataclasses
    import jax

    from mpisppy_tpu.algos import fused_wheel as fw
    from mpisppy_tpu.cylinders import hub as hub_mod
    from mpisppy_tpu.spin_the_wheel import WheelSpinner
    from mpisppy_tpu.telemetry import metrics as metrics_mod

    # on-device kernel counters for the hub's subproblem solves AND the
    # fused bound planes: a few elementwise ops per 40-iteration restart
    # window (docs/telemetry.md overhead contract) buys pdhg
    # iteration/restart/guard totals in the artifact, labeled per
    # cylinder (cyl="hub"/"lag"/"xhat"/...)
    ph_opts = dataclasses.replace(
        ph_opts, pdhg=dataclasses.replace(ph_opts.pdhg, telemetry=True))
    wheel_opts = wheel_opts or fw.FusedWheelOptions()
    wheel_opts = dataclasses.replace(
        wheel_opts,
        lag_pdhg=dataclasses.replace(wheel_opts.lag_pdhg, telemetry=True),
        xhat_pdhg=dataclasses.replace(wheel_opts.xhat_pdhg,
                                      telemetry=True))

    ckpt = os.path.abspath(f".bench_ckpt_{label}.npz")
    # checkpoint cadence trades crash-replay time against steady-state
    # overhead: a full-wheel snapshot at 10k scenarios is ~460 MB
    # (several seconds through the device tunnel), so save sparsely
    hub_opts = {"rel_gap": GAP_TARGET,
                "checkpoint_path": ckpt,
                "checkpoint_every_s": 120.0}
    hub_opts.update(extra_hub_opts or {})
    opt_kwargs = {"options": ph_opts, "batch": batch,
                  "wheel_options": wheel_opts}
    opt_kwargs.update(extra_opt_kwargs or {})
    hub = {
        "hub_class": hub_mod.PHHub,
        "opt_class": fw.FusedPH,
        "opt_kwargs": opt_kwargs,
        "hub_kwargs": {"options": hub_opts},
    }
    wheel = WheelSpinner(hub, spokes_cfg)
    wheel.build()
    elapsed_prior, resumed = 0.0, False
    if os.path.exists(ckpt):
        extras = wheel.spcomm.load_checkpoint(ckpt)
        elapsed_prior = float(extras.get("elapsed", 0.0))
        resumed = True
    t0 = time.perf_counter()
    hub_opts["checkpoint_extra"] = lambda: {
        "elapsed": elapsed_prior + time.perf_counter() - t0}
    wheel.spin()
    jax.block_until_ready(wheel.opt.state.conv)
    elapsed = elapsed_prior + time.perf_counter() - t0
    if os.path.exists(ckpt):
        os.remove(ckpt)
    abs_gap, rel_gap = wheel.spcomm.compute_gaps()
    iters = wheel.spcomm._iter
    # the hub mirrored the cumulative device counters into the global
    # registry every sync (hub._harvest_kernel_counters); snapshot it
    # in the exporter's schema (each bench phase is its own process,
    # so the registry holds exactly this wheel's totals)
    # dispatch occupancy/recompile stats ride the artifact next to the
    # kernel counters (docs/dispatch.md): None when the wheel never
    # touched the MIP-oracle scheduler, a stats dict (batches, lanes,
    # occupancy, buckets, backend_compiles, unexpected_recompiles,
    # inflight_max) otherwise — the dispatch_* counters/gauges inside
    # metrics_snapshot are the same numbers, mirrored live
    from mpisppy_tpu import dispatch as dispatch_mod
    return {
        "label": label,
        # precision disclosure (ISSUE 8): the mode the ITERATION
        # matvecs ran at; certificates always re-check at full precision
        "iter_precision": ph_opts.pdhg.iter_precision or "bf16x6",
        "seconds_to_gap": round(elapsed, 3),
        "iterations": iters,
        # directly gateable steady-state proxy (telemetry/regress.py
        # GATES keys on sec_per_iter): to-gap wall over iterations —
        # includes compile+iter0 amortization, so compare like vs like
        "sec_per_iter": round(elapsed / max(1, iters), 6),
        "rel_gap": float(rel_gap),
        "certified": bool(rel_gap <= GAP_TARGET),
        "outer": float(wheel.BestOuterBound),
        "inner": float(wheel.BestInnerBound),
        "resumed_from_checkpoint": resumed,
        "metrics_snapshot": metrics_mod.REGISTRY.to_snapshot(),
        "dispatch": dispatch_mod.scheduler_stats(),
    }


def bench_sslp_gap():
    """Headline: sslp 15_45 at SSLP_SCENS scenarios, PH hub + FUSED
    Lagrangian outer + FUSED xhat-xbar inner (algos.fused_wheel: the
    spoke solves ride inside the hub's jitted step as fixed warm
    budgets), to 1% certified gap."""
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.cylinders import spoke as spoke_mod
    from mpisppy_tpu.ops import pdhg

    batch, specs = _sslp_batch(SSLP_SCENS)
    ph_opts = ph_mod.PHOptions(
        default_rho=20.0, max_iterations=MAX_WHEEL_ITERS, conv_thresh=0.0,
        subproblem_windows=8,
        pdhg=pdhg.PDHGOptions(tol=1e-6, restart_period=40,
                              iter_precision=ITER_PRECISION))
    spokes = [
        {"spoke_class": spoke_mod.FusedLagrangianOuterBound,
         "opt_kwargs": {"options": {}}},
        {"spoke_class": spoke_mod.FusedXhatXbarInnerBound,
         "opt_kwargs": {"options": {}}},
    ]
    out = bench_wheel_to_gap(batch, f"sslp_15_45_{SSLP_SCENS}scen",
                             spokes, ph_opts)

    # reference-model baseline: per-iteration the reference solves S LPs
    # on the hub + S on the Lagrangian spoke + S on the xhat spoke,
    # charged at the MEASURED HiGHS rate on these same LP instances
    lp_dist = time_scipy_baseline(specs)
    sec_per_lp = lp_dist["mean"]
    lps = out["iterations"] * batch.num_real * 3
    out["baseline_1rank_sec"] = round(sec_per_lp * lps, 1)
    out["baseline_64rank_sec"] = round(sec_per_lp * lps / BASELINE_RANKS, 1)
    # p90 variant: how the baseline moves if the tail rate governs
    out["baseline_64rank_sec_p90"] = round(
        lp_dist["p90"] * lps / BASELINE_RANKS, 1)
    out["sec_per_baseline_lp"] = sec_per_lp
    out["baseline_lp_dist"] = lp_dist
    return out


def bench_baseline_anchor():
    """Measured anchor for the reference execution model (VERDICT r4
    #7): HiGHS solve-time DISTRIBUTIONS on the real workload units —
    (a) the headline's own scenario LP relaxations, (b) the REAL SIPLIB
    sslp_15_45 scenario MIPs (exact integer recourse, the solves that
    give the reference its certified-gap quality), (c) the SIPLIB LP
    relaxations.  Everything here is a measurement on THIS host; no
    Gurobi/MPI modeling involved."""
    from mpisppy_tpu.models import sslp

    out = {}
    # (a) headline synthetic LPs (same generator + seed as the bench) —
    # host-side specs only: building the device batch would pay full
    # accelerator-backend init in a pure-scipy measurement phase
    inst = sslp.synthetic_instance(SSLP_SERVERS, SSLP_CLIENTS, seed=0)
    specs = [sslp.scenario_creator(nm, instance=inst, num_scens=64,
                                   lp_relax=True)
             for nm in sslp.scenario_names_creator(64)]
    out["headline_lp_sec"] = time_scipy_baseline(specs, sample=32)

    # (b)+(c) the real SIPLIB instance the certification pipeline runs
    dd = ("/root/reference/examples/sslp/data/"
          "sslp_15_45_10/scenariodata")
    if os.path.isdir(dd):
        names = sslp.scenario_names_creator(10)
        mips = [sslp.scenario_creator(nm, data_dir=dd, num_scens=10)
                for nm in names]
        lps = [sslp.scenario_creator(nm, data_dir=dd, num_scens=10,
                                     lp_relax=True) for nm in names]
        mip_dist, mip_objs = time_scipy_milp_baseline(mips, sample=10)
        out["siplib_15_45_10_mip_sec"] = mip_dist
        out["siplib_15_45_10_mip_objs"] = [round(v, 2) for v in mip_objs]
        out["siplib_15_45_10_lp_sec"] = time_scipy_baseline(lps, sample=10)
        # wait-and-see bound cross-check: E[per-scenario MIP optimum]
        # must lower-bound the published optimum -260.5 (sanity that the
        # MILP anchor solves the true SIPLIB scenarios); nan-mean in
        # case any sample was censored at time_limit
        out["siplib_15_45_10_ws_bound"] = round(
            float(np.nanmean(mip_objs)), 3)
        if any(np.isnan(v) for v in mip_objs):
            out["siplib_censored_samples"] = int(
                np.isnan(mip_objs).sum() if hasattr(mip_objs, "sum")
                else sum(np.isnan(v) for v in mip_objs))
    else:
        # make the missing key MEASUREMENT visible in the artifact —
        # the methodology doc's MIP-floor argument depends on it
        out["siplib_skipped_missing_dir"] = dd
    return out


def bench_sweep_one(S):
    """PH iters/sec at one scenario count (continuity with the round-2
    headline metric); each scale runs as its OWN subprocess phase so a
    worker crash at 100k cannot cost the smaller scales their numbers."""
    import jax
    import jax.numpy as jnp

    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.ops import pdhg

    try:
        batch, _ = _sslp_batch(S)
        # keep every dispatch SHORT at 100k scale: a single 400-window
        # iter0 (~17.6k PDHG iterations in one while_loop) can outlive
        # the TPU worker's patience
        opts = ph_mod.PHOptions(
            default_rho=20.0, subproblem_windows=8,
            iter0_windows=80 if S >= 100_000 else 400,
            pdhg=pdhg.PDHGOptions(tol=1e-6, restart_period=40,
                              iter_precision=ITER_PRECISION))
        rho = jnp.full((batch.num_nonants,), opts.default_rho)
        state, _, _ = ph_mod.ph_iter0(batch, rho, opts)
        state = ph_mod.ph_iterk(batch, state, opts)   # compile
        jax.block_until_ready(state.conv)
        n_iters = 5 if S >= 100_000 else 20
        t0 = time.perf_counter()
        for _ in range(n_iters):
            state = ph_mod.ph_iterk(batch, state, opts)
        jax.block_until_ready(state.conv)
        dt = time.perf_counter() - t0
        ips = n_iters / dt
        flops = _flops_per_ph_iter(batch, opts) * ips
        return {
            "scenarios": S,
            "iter_precision": ITER_PRECISION or "bf16x6",
            "iters_per_sec": round(ips, 3),
            "achieved_tflops_est": round(flops / 1e12, 3),
        }
    except Exception as e:
        return {"scenarios": S, "error": repr(e)}


def _overhead_ph_opts(n_iters):
    """The PH config BOTH wheel_overhead phases run — one builder (with
    _overhead_wheel_options/_overhead_spokes/_bare_ph_sec_per_iter) so
    the async phase stays an apples-to-apples A/B against the
    synchronous baseline its gated overhead_factor is compared to."""
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.ops import pdhg
    return ph_mod.PHOptions(
        default_rho=20.0, max_iterations=n_iters, conv_thresh=0.0,
        subproblem_windows=8,
        pdhg=pdhg.PDHGOptions(tol=1e-6, restart_period=40,
                              iter_precision=ITER_PRECISION))


def _overhead_wheel_options():
    from mpisppy_tpu.algos import fused_wheel as fw
    return fw.FusedWheelOptions(slam_windows=2, shuffle_windows=4,
                                spoke_period=3)


def _overhead_spokes():
    from mpisppy_tpu.cylinders import spoke as spoke_mod
    return [
        {"spoke_class": spoke_mod.FusedLagrangianOuterBound,
         "opt_kwargs": {"options": {}}},
        {"spoke_class": spoke_mod.FusedXhatXbarInnerBound,
         "opt_kwargs": {"options": {}}},
        {"spoke_class": spoke_mod.FusedXhatShuffleInnerBound,
         "opt_kwargs": {"options": {}}},
        {"spoke_class": spoke_mod.FusedSlamHeuristic,
         "opt_kwargs": {"options": {}}},
    ]


def _bare_ph_sec_per_iter(batch, ph_opts, n_iters):
    """Bare-PH per-iteration wall clock (compile + iter0 excluded) —
    the shared denominator of both overhead factors."""
    import jax
    import jax.numpy as jnp

    from mpisppy_tpu.algos import ph as ph_mod

    rho = jnp.full((batch.num_nonants,), ph_opts.default_rho)
    state, _, _ = ph_mod.ph_iter0(batch, rho, ph_opts)
    state = ph_mod.ph_iterk(batch, state, ph_opts)
    jax.block_until_ready(state.conv)
    t0 = time.perf_counter()
    for _ in range(n_iters):
        state = ph_mod.ph_iterk(batch, state, ph_opts)
    jax.block_until_ready(state.conv)
    return (time.perf_counter() - t0) / n_iters


def bench_wheel_overhead():
    """Wheel overhead: per-iteration wall-clock of a full hub + 4-bound
    wheel vs bare PH on the same batch.  Round 3 measured 642x with
    every spoke a separate to-convergence device dispatch; the fused
    wheel (algos.fused_wheel — Lagrangian + xhat-xbar + slam + shuffle
    planes INSIDE the hub's jitted step, fixed warm budgets) is the
    round-4 answer.  Target: overhead factor <= 5x."""
    import jax

    from mpisppy_tpu.algos import fused_wheel as fw
    from mpisppy_tpu.cylinders import hub as hub_mod
    from mpisppy_tpu.spin_the_wheel import WheelSpinner

    batch, _ = _sslp_batch(SSLP_SCENS)
    n_iters = 3 if SMOKE else 10
    ph_opts = _overhead_ph_opts(n_iters)
    bare = _bare_ph_sec_per_iter(batch, ph_opts, n_iters)

    # full fused wheel: hub + Lagrangian + xhat-xbar + slam + shuffle
    hub = {
        "hub_class": hub_mod.PHHub,
        "opt_class": fw.FusedPH,
        "opt_kwargs": {"options": ph_opts, "batch": batch,
                       "wheel_options": _overhead_wheel_options()},
        "hub_kwargs": {"options": {"rel_gap": 0.0}},
    }
    wheel = WheelSpinner(hub, _overhead_spokes())
    wheel.spin()
    jax.block_until_ready(wheel.opt.state.conv)
    # steady-state per-iteration cost from the hub trace timestamps,
    # excluding iter0 + the first iterk (compile)
    ts = [row["t"] for row in wheel.spcomm.trace]
    steady = np.diff(ts[2:]) if len(ts) > 3 else np.diff(ts)
    per_iter = float(np.median(steady)) if len(steady) else float("nan")
    return {
        "bare_ph_sec_per_iter": round(bare, 4),
        "wheel_sec_per_iter": round(per_iter, 4),
        "overhead_factor": round(per_iter / bare, 3),
        "round3_classic_overhead_factor": 635.2,  # BENCH_r03 measured
        "note": f"median over {len(steady)} steady-state iterations "
                "(compile + iter0 excluded); fused wheel carries 4 bound "
                "planes inside the hub step at spoke_period=3 (the same "
                "exchange cadence round 3's classic wheel used)",
    }


def bench_wheel_overhead_async():
    """Async-wheel overhead (ISSUE 11; ROADMAP item 4): per-iteration
    wall-clock of the async hub at staleness 0/1/2 vs bare PH on the
    same batch.  staleness 0 is the synchronous degrade (must match the
    wheel_overhead phase's structure); staleness >= 1 overlaps the host
    exchange with device iterations on the double-buffered plane.  The
    headline `overhead_factor` (staleness 1) carries the <= 1.3 ratchet
    MILESTONE (telemetry/regress.py)."""
    import jax

    from mpisppy_tpu.algos import async_wheel as aw
    from mpisppy_tpu.cylinders import hub as hub_mod
    from mpisppy_tpu.spin_the_wheel import WheelSpinner

    batch, _ = _sslp_batch(SSLP_SCENS)
    n_iters = 8 if SMOKE else 12
    ph_opts = _overhead_ph_opts(n_iters)
    bare = _bare_ph_sec_per_iter(batch, ph_opts, n_iters)

    out = {"bare_ph_sec_per_iter": round(bare, 4),
           "iter_precision": ITER_PRECISION or "bf16x6"}
    for s in (0, 1, 2):
        hub = {
            "hub_class": hub_mod.AsyncPHHub,
            "opt_class": aw.AsyncFusedPH,
            "opt_kwargs": {
                "options": ph_opts, "batch": batch,
                "wheel_options": _overhead_wheel_options(),
                "async_options": aw.AsyncWheelOptions(staleness=s)},
            "hub_kwargs": {"options": {"rel_gap": 0.0,
                                       "async_staleness": s}},
        }
        wheel = WheelSpinner(hub, _overhead_spokes())
        wheel.spin()
        jax.block_until_ready(wheel.opt.state.conv)
        ts = [row["t"] for row in wheel.spcomm.trace]
        # drop iter0 + the first TWO iterk rows: the stale-prox step
        # and the plane programs compile across the first two syncs
        drop = 3 if len(ts) > 5 else (2 if len(ts) > 3 else 1)
        steady = np.diff(ts[drop:]) if len(ts) > drop + 1 \
            else np.diff(ts)
        per_iter = float(np.median(steady)) if len(steady) \
            else float("nan")
        out[f"s{s}"] = {
            "staleness": s,
            "wheel_sec_per_iter": round(per_iter, 4),
            "overhead_factor": round(per_iter / bare, 3),
        }
    # the MILESTONE headline: staleness 1 at the spoke_period=3
    # exchange cadence (the same cadence wheel_overhead measures)
    out["overhead_factor"] = out["s1"]["overhead_factor"]
    out["note"] = ("async wheel (double-buffered exchange plane, "
                   "theta-damped stale-prox hub step) at staleness "
                   "0/1/2 vs bare PH; median steady-state sec/iter "
                   "(compile + iter0 excluded); headline "
                   "overhead_factor is staleness 1")
    return out


def bench_uc_fwph():
    """BASELINE.md item 5: uc, PH hub + FWPH outer + xhat-xbar inner
    (the paper-run cylinder mix, ref:paperruns/larger_uc/uc_cylinders.py)."""
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.cylinders import spoke as spoke_mod
    from mpisppy_tpu.models import uc
    from mpisppy_tpu.ops import pdhg

    inst = uc.synthetic_instance(10, 24, seed=0)
    names = uc.scenario_names_creator(UC_SCENS)
    specs = [uc.scenario_creator(nm, instance=inst, num_scens=UC_SCENS)
             for nm in names]
    batch = batch_mod.from_specs(specs)
    from mpisppy_tpu.algos import fused_wheel as fw
    from functools import partial as _partial

    from mpisppy_tpu.extensions.rho_setters import SepRho
    # NO hand-tuned rho (round-4 needed rho=1000): SepRho (the
    # Watson-Woodruff cost/spread rule, multiplier 2 — the same
    # model-agnostic setting hydro uses) certifies from default_rho in
    # FEWER iterations than the hand-set constant (427 vs 564 measured)
    ph_opts = ph_mod.PHOptions(
        default_rho=1.0, max_iterations=2 * MAX_WHEEL_ITERS,
        conv_thresh=0.0,
        subproblem_windows=10,
        pdhg=pdhg.PDHGOptions(tol=1e-6, restart_period=40,
                              iter_precision=ITER_PRECISION))
    # full precision ON PURPOSE: this is a standalone run-to-tolerance
    # solve at tol=1e-6, which bf16x3 iterates cannot certify (they
    # stall ~7e-6..1e-5 and would burn the whole max_iters budget —
    # docs/precision.md "When to opt out").  Only the inexact-by-design
    # PH/FWPH hub windows run bf16x3.
    spoke_pdhg = pdhg.PDHGOptions(tol=1e-6, max_iters=4_000)
    # slam-max commits every unit any scenario wants: the conservative
    # feasible commitment (rounded-xbar undercommits against the
    # reserve rows and pays shortfall penalties).  Lagrangian + xhat +
    # slam ride fused in the hub step; FWPH stays a classic spoke
    # advancing one outer iteration per sync period.
    spokes = [
        {"spoke_class": spoke_mod.FWPHOuterBound,
         "opt_kwargs": {"options": {"rho": 200.0,
                                    "pdhg_opts": spoke_pdhg}}},
        {"spoke_class": spoke_mod.FusedLagrangianOuterBound,
         "opt_kwargs": {"options": {}}},
        {"spoke_class": spoke_mod.FusedXhatXbarInnerBound,
         "opt_kwargs": {"options": {}}},
        {"spoke_class": spoke_mod.FusedSlamHeuristic,
         "opt_kwargs": {"options": {}}},
    ]
    return bench_wheel_to_gap(
        batch, f"uc_10g24h_{UC_SCENS}scen", spokes, ph_opts,
        wheel_opts=fw.FusedWheelOptions(slam_windows=2),
        extra_hub_opts={"spoke_sync_period": 5},
        extra_opt_kwargs={"extensions": _partial(SepRho,
                                                 multiplier=2.0)})


def bench_uc_fwph_hub():
    """VERDICT r5 #5 straggler / ISSUE 8: uc the reference's way — FWPH
    as the DRIVING algorithm (BASELINE.md item 5; the reference's
    larger_uc paper runs are FWPH cylinders, ref:paperruns/larger_uc/
    uc_cylinders.py).  Round 3 measured 545 s UNCERTIFIED because the
    FWPH run published no inner bound; here FWPH's inner-iteration-0
    oracle supplies the certified dual (outer) bound and the incumbent
    side re-evaluates the rounded x̄ (nearest + ceil — ceil mirrors the
    slam-max over-commitment that is recourse-feasible against uc's
    reserve rows) through the honest xhat recourse evaluator with the
    comp_tight publication gate.  Recorded even if it loses to the
    PH+SepRho wheel (uc_fwph_to_1pct_gap, 193.9 s) — whichever
    certifies faster is the headline uc number."""
    from mpisppy_tpu.algos import fwph as fwph_mod
    from mpisppy_tpu.algos import xhat as xhat_mod
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.models import uc
    from mpisppy_tpu.ops import pdhg

    inst = uc.synthetic_instance(10, 24, seed=0)
    specs = [uc.scenario_creator(nm, instance=inst, num_scens=UC_SCENS)
             for nm in uc.scenario_names_creator(UC_SCENS)]
    batch = batch_mod.from_specs(specs)
    # This phase runs ENTIRELY at full precision: FWPH's dual-bound
    # certificate reads the oracle's own dual residuals (rd <= 10*tol
    # = 1e-5) with no full-precision restart-recheck layer between
    # iterates and published bound, and bf16x3 iterates stall right at
    # that band (docs/precision.md) — engaging it could cost the
    # certification this phase exists to produce.
    opts = fwph_mod.FWPHOptions(
        fw_iter_limit=2, max_columns=16,
        max_iterations=3 if SMOKE else 2 * MAX_WHEEL_ITERS,
        conv_thresh=0.0,
        default_rho=200.0,   # the rho the FWPH spoke certifies with on uc
        oracle_windows=10,
        pdhg=pdhg.PDHGOptions(tol=1e-6, restart_period=40))
    # full precision ON PURPOSE (docs/precision.md "When to opt out"):
    # a standalone tol=1e-6 recourse evaluation stalls below tolerance
    # at bf16x3 and would burn max_iters + the rescue pass every eval
    xhat_opts = pdhg.PDHGOptions(tol=1e-6, max_iters=4_000)
    drv = fwph_mod.FWPH(opts, batch)
    eval_every = 1 if SMOKE else 5   # xhat evals per FWPH outer iters
    t0 = time.perf_counter()
    drv.fw_prep()
    best_outer = drv.best_bound      # -inf while uncertified
    best_inner = float("inf")
    rel_gap, iters = float("inf"), 0
    for itr in range(1, opts.max_iterations + 1):
        iters = itr
        drv.state = fwph_mod.fwph_iter(batch, drv.state, opts)
        best_outer = max(best_outer, float(drv.state.best_bound))
        if itr % eval_every == 0:
            for mode in ("nearest", "ceil"):
                cand = xhat_mod.round_integers(
                    batch, drv.state.xbar_nodes, mode)
                res = xhat_mod.evaluate(batch, cand, xhat_opts)
                if bool(res.feasible) and xhat_mod.comp_tight(batch,
                                                              res):
                    best_inner = min(best_inner, float(res.value))
        # gap check EVERY iteration: the dual bound improves between
        # xhat evals, and the recorded rel_gap must never go stale
        # against the artifact's own outer/inner fields
        if np.isfinite(best_inner) and np.isfinite(best_outer):
            rel_gap = (best_inner - best_outer) / max(
                abs(best_inner), abs(best_outer), 1e-12)
            if rel_gap <= GAP_TARGET:
                break
    elapsed = time.perf_counter() - t0

    def _fin(v):
        """strict-JSON artifacts: non-finite (no bound yet) -> None"""
        return float(v) if np.isfinite(v) else None

    return {
        "label": f"uc_10g24h_{UC_SCENS}scen_fwph_hub",
        "iter_precision": "bf16x6",   # see the opts comment above
        "seconds_to_gap": round(elapsed, 3),
        "iterations": iters,
        "sec_per_iter": round(elapsed / max(1, iters), 6),
        "rel_gap": _fin(rel_gap),
        "certified": bool(rel_gap <= GAP_TARGET),
        "outer": _fin(best_outer),
        "inner": _fin(best_inner),
        "note": "FWPH as the hub algorithm (reference uc recipe); "
                "outer = certified SDM inner-iteration-0 dual bound, "
                "inner = comp_tight-gated recourse evaluation of "
                "rounded xbar; compare against uc_fwph_to_1pct_gap "
                "(PH hub + FWPH spoke)",
    }


def bench_hydro():
    """BASELINE.md item 4: hydro 3-stage wheel (multistage path —
    node-segmented reductions) to 1% certified gap.  Scales the (3, 3)
    SIPLIB-style tree by widening the stage-2/3 branching."""
    from mpisppy_tpu.algos import fused_wheel as fw
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.cylinders import spoke as spoke_mod
    from mpisppy_tpu.models import hydro
    from mpisppy_tpu.ops import pdhg

    bfs = (3, 3) if SMOKE else ((10, 10) if QUICK else (30, 30))
    num = bfs[0] * bfs[1]
    specs = [hydro.scenario_creator(nm, branching_factors=bfs)
             for nm in hydro.scenario_names_creator(num)]
    tree = hydro.make_tree(bfs)
    batch = batch_mod.from_specs(specs, tree=tree)
    from functools import partial as _partial

    from mpisppy_tpu.extensions.rho_setters import SepRho
    # NO hand-tuned rho (round-4 needed rho=2): the same SepRho
    # adapter as uc — certifies 0.36% in 95 iterations (round-5
    # measured; the flat-rho round-4 run needed 380)
    ph_opts = ph_mod.PHOptions(
        default_rho=1.0, max_iterations=2 * MAX_WHEEL_ITERS,
        conv_thresh=0.0, subproblem_windows=8,
        pdhg=pdhg.PDHGOptions(tol=1e-6, restart_period=40,
                              iter_precision=ITER_PRECISION))
    # the fused Lagrangian plateaus ~3.5% below the LP optimum on hydro
    # (PH's dual converges slowly on this tree); the EF-bound spoke's
    # warm dual solve provides the certified outer that closes the gap.
    # Inner: EFXhatInnerBound (root-fixed EF) — fixing ALL stages'
    # nonants is structurally infeasible on hydro (stage-2 reservoir
    # balance couples fixed nonants with stochastic inflow; duals ~1e6),
    # so the fused x-bar recourse plane is disabled (round 4's 184.25
    # "inner" at such points was an uncompensated-infeasibility artifact
    # sitting BELOW the EF optimum ~186.2 — not a valid bound).
    from mpisppy_tpu.algos.ef import build_ef
    efp = build_ef(specs, tree=tree)
    spokes = [
        {"spoke_class": spoke_mod.EFOuterBound,
         "opt_kwargs": {"options": {"ef_problem": efp,
                                    "n_windows": 20}}},
        {"spoke_class": spoke_mod.FusedLagrangianOuterBound,
         "opt_kwargs": {"options": {}}},
        {"spoke_class": spoke_mod.EFXhatInnerBound,
         "opt_kwargs": {"options": {"ef_problem": efp,
                                    "n_windows": 20}}},
    ]
    return bench_wheel_to_gap(
        batch, f"hydro_3stage_{num}scen", spokes, ph_opts,
        wheel_opts=fw.FusedWheelOptions(xhat_windows=0),
        extra_hub_opts={"spoke_sync_period": 5},
        extra_opt_kwargs={"extensions": _partial(SepRho,
                                                 multiplier=2.0)})


def bench_measured_mfu():
    """VERDICT r3 weak #6 / ISSUE 7: measured (not modeled) FLOP/s and
    HBM bandwidth for the PH step.  Uses XLA's compiled cost analysis
    (flops + bytes accessed of the EXACT program run) divided by
    measured wall-clock, PLUS the trace-derived device profile
    (telemetry/deviceprof.py + roofline.py) computed from the
    jax.profiler capture of one steady-state iteration: achieved HBM
    GB/s against the device's own peak, sustained stream bandwidth of
    the HBM-dominated movement ops, DMA/compute overlap fraction, and
    device sec/iter.  The round-5 hand-rolled two-op (matvec + saxpy)
    microbenchmarks are retired: the capture that was already saved as
    an artifact IS the measurement now, and the same numbers gate in
    CI (`telemetry gate`, docs/telemetry.md)."""
    import jax
    import jax.numpy as jnp

    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.ops import pdhg

    out = {}
    scales = [16] if SMOKE else ([1_000] if QUICK else [10_000, 100_000])
    for S in scales:
        batch, _ = _sslp_batch(S)
        opts = ph_mod.PHOptions(
            default_rho=20.0, subproblem_windows=8,
            iter0_windows=80 if S >= 100_000 else 400,
            pdhg=pdhg.PDHGOptions(tol=1e-6, restart_period=40,
                              iter_precision=ITER_PRECISION))
        ko = ph_mod.kernel_opts(opts)
        rho = jnp.full((batch.num_nonants,), opts.default_rho)
        state, _, _ = ph_mod.ph_iter0(batch, rho, ko)
        compiled = ph_mod.ph_iterk.lower(batch, state, ko).compile()
        try:
            ca = compiled.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            flops = float(ca.get("flops", float("nan")))
            bytes_acc = float(ca.get("bytes accessed", float("nan")))
        except Exception as e:  # pragma: no cover - backend-specific
            flops, bytes_acc = float("nan"), float("nan")
            out.setdefault("cost_analysis_error", repr(e))
        state = ph_mod.ph_iterk(batch, state, ko)
        jax.block_until_ready(state.conv)
        n = 3 if S >= 100_000 else 10
        # device trace artifact for one iteration
        trace_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)) or ".",
            f"profile_trace_S{S}")
        try:
            with jax.profiler.trace(trace_dir):
                st2 = ph_mod.ph_iterk(batch, state, ko)
                jax.block_until_ready(st2.conv)
        except Exception as e:  # pragma: no cover
            out.setdefault("trace_error", repr(e))
        t0 = time.perf_counter()
        for _ in range(n):
            state = ph_mod.ph_iterk(batch, state, ko)
        jax.block_until_ready(state.conv)
        dt = (time.perf_counter() - t0) / n
        model_flops = _flops_per_ph_iter(batch, opts)

        entry = {
            "sec_per_iter": round(dt, 4),
            "iter_precision": ITER_PRECISION or "bf16x6",
            "xla_flops_per_iter_body_once": flops,
            "xla_bytes_per_iter_body_once": bytes_acc,
            "model_tflops": round(model_flops / dt / 1e12, 3),
            "trace_dir": trace_dir,
        }
        # trace-derived device profile: parse the capture just written
        # (stdlib-only, no TF/protobuf; telemetry/roofline.py defines
        # every metric).  measured_stream_gbps is hoisted to the entry
        # top level so r0N-over-r0N gates keep comparing the same key
        # the two-op estimate used to fill.
        try:
            from mpisppy_tpu.telemetry import roofline
            dev = roofline.roofline_path(trace_dir)
            entry["device_profile"] = dev
            entry["measured_stream_gbps"] = dev.get(
                "measured_stream_gbps")
        except (OSError, ValueError) as e:
            entry.setdefault("device_profile_error", repr(e))
        out[f"S{S}"] = entry
    out["note"] = ("xla_*_body_once are compiled cost-analysis figures "
                   "that count loop bodies once (no trip-count fold); "
                   "measured_stream_gbps and the device_profile "
                   "section are derived from the committed "
                   "jax.profiler capture by telemetry/roofline.py "
                   "(stream = HBM-dominated data-movement ops; "
                   "overlap_frac = DMA in-flight time hidden behind "
                   "compute)")
    # v5e single-chip peaks for context (public spec)
    out["v5e_peak_bf16_tflops"] = 197.0
    out["v5e_peak_hbm_gbps"] = 819.0
    return out


def bench_wheel_scengen():
    """ISSUE 14 acceptance: seeded on-device scenario synthesis takes
    the wheel to S >= 1M (docs/scengen.md).  Three parts:

      * synthesized-vs-materialized A/B at the max COMMON scale both
        paths hold resident: PH iters/s on the same farmer batch as a
        concrete ScenarioBatch vs a VirtualBatch synthesizing inside
        the step — the ratio carries the >= 0.9 MILESTONE
        (telemetry/regress.py): recompute-instead-of-store must cost
        <= 10% throughput where both fit;
      * a synthesized S sweep up to >= 1M: iters/s, resident-bytes
        high-water estimate (program pytree + solver state) vs what
        host materialization would keep resident, and scaling
        efficiency (lane-throughput relative to the smallest scale);
      * the CERTIFIED run: the fused wheel (hub + Lagrangian outer +
        x̂ = x̄ recourse inner, one monolithic jitted step) at the top
        scale to rel_gap <= 1% — its presence at S1000000 is itself a
        MILESTONE (ratchet: the phase can never silently drop).

    CPU-smoke scale on this container; the ratchet milestones bind the
    numbers for the next hardware round (the PR-7 pattern)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from mpisppy_tpu import scengen
    from mpisppy_tpu.algos import fused_wheel as fw
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.ops import pdhg
    from mpisppy_tpu.telemetry import metrics as metrics_mod

    if SMOKE:
        sweep, common_s, big_s = [64, 256], 64, 256
    elif QUICK:
        sweep, common_s, big_s = [4_096, 65_536], 4_096, 65_536
    else:
        sweep, common_s, big_s = [10_000, 100_000, 1_000_000], \
            100_000, 1_000_000

    # throughput measurements run the SWEEP-standard PH config
    # (subproblem_windows=8, the same step every sweep_* phase times) so
    # the A/B ratio compares synthesis against the step the rest of the
    # bench reports; the certified 1M run below trades step weight for
    # exchange frequency (subproblem_windows=2 certifies in fewer
    # device-seconds on farmer)
    sweep_opts = ph_mod.PHOptions(
        default_rho=1.0, subproblem_windows=8, iter0_windows=20,
        pdhg=pdhg.PDHGOptions(tol=1e-6, restart_period=40,
                              iter_precision=ITER_PRECISION))
    ks = ph_mod.kernel_opts(sweep_opts)
    ph_opts = ph_mod.PHOptions(
        default_rho=1.0, subproblem_windows=2, iter0_windows=20,
        pdhg=pdhg.PDHGOptions(tol=1e-6, restart_period=40,
                              iter_precision=ITER_PRECISION))
    ko = ph_mod.kernel_opts(ph_opts)

    def state_bytes(st):
        return sum(int(getattr(a, "nbytes", 0) or 0)
                   for a in jax.tree_util.tree_leaves(st))

    def measure_ips(batch, n_iters):
        rho = jnp.ones(batch.num_nonants, jnp.float32)
        quick = dataclasses.replace(ks, iter0_windows=8)
        st, _, _ = ph_mod.ph_iter0(batch, rho, quick)
        st = ph_mod.ph_iterk(batch, st, ks)   # compile
        jax.block_until_ready(st.conv)
        t0 = time.perf_counter()
        for _ in range(n_iters):
            st = ph_mod.ph_iterk(batch, st, ks)
        jax.block_until_ready(st.conv)
        return n_iters / (time.perf_counter() - t0), st

    out = {"iter_precision": ITER_PRECISION or "bf16x6",
           "model": "farmer", "common_scenarios": common_s}

    # -- A/B at the common scale -----------------------------------------
    n_meas = 2 if SMOKE else 3
    prog_c = farmer.scenario_program(common_s, seed=0)
    vb_c = scengen.virtual_batch(prog_c)
    bm_c = scengen.materialize(prog_c)   # same bits, resident data
    ips_mat, st_m = measure_ips(bm_c, n_meas)
    ips_syn, _ = measure_ips(vb_c, n_meas)
    out["materialized"] = {
        "iters_per_sec": round(ips_mat, 4),
        "resident_data_bytes": vb_c.materialized_bytes(),
    }
    out["synthesized"] = {
        "iters_per_sec": round(ips_syn, 4),
        "resident_data_bytes": vb_c.persistent_bytes(),
    }
    out["synth_vs_materialized_ratio"] = round(ips_syn / ips_mat, 4)
    del bm_c, st_m

    # -- synthesized sweep to >= 1M --------------------------------------
    rows = []
    base_lanes = None
    for S in sweep:
        prog = farmer.scenario_program(S, seed=0)
        vb = scengen.virtual_batch(prog)
        ips, st = measure_ips(vb, n_meas if S < 1_000_000 else 2)
        lanes = ips * S
        if base_lanes is None:
            base_lanes = lanes
        rows.append({
            "scenarios": S,
            "iters_per_sec": round(ips, 4),
            "lane_iters_per_sec": round(lanes, 1),
            "scaling_efficiency": round(lanes / base_lanes, 4),
            "resident_bytes_synth": vb.persistent_bytes()
            + state_bytes(st),
            "resident_bytes_materialized_est": vb.materialized_bytes()
            + state_bytes(st),
        })
        del vb, st
    out["sweep"] = rows

    # -- the certified wheel at the top scale ----------------------------
    wopts = fw.FusedWheelOptions(lag_windows=4, xhat_windows=2,
                                 slam_windows=0, shuffle_windows=0,
                                 split_dispatch=False)
    prog_b = farmer.scenario_program(big_s, seed=0)
    vb_b = scengen.virtual_batch(prog_b)
    rho = jnp.ones(vb_b.num_nonants, jnp.float32)
    max_iters = 5 if SMOKE else 40
    t0 = time.perf_counter()
    wst, tb, cert = fw.fused_iter0(vb_b, rho, ko, wopts)
    outer = float(tb) if bool(cert) else float("-inf")
    inner, rel_gap, iters = float("inf"), float("inf"), 0
    for k in range(1, max_iters + 1):
        iters = k
        wst = fw.fused_iterk(vb_b, wst, ko, wopts)
        sc = dict(zip(fw.SCALAR_KEYS, np.asarray(wst.scalars)))
        if sc["lag_certified"] > 0.5 and np.isfinite(sc["lag_bound"]):
            outer = max(outer, float(sc["lag_bound"]))
        if sc["xhat_feasible"] > 0.5 and np.isfinite(sc["xhat_value"]):
            inner = min(inner, float(sc["xhat_value"]))
        if np.isfinite(inner) and np.isfinite(outer):
            rel_gap = (inner - outer) / max(abs(inner), abs(outer),
                                            1e-12)
            if rel_gap <= GAP_TARGET:
                break
    elapsed = time.perf_counter() - t0

    def _fin(v):
        return float(v) if np.isfinite(v) else None

    out["certified_run"] = {
        "scenarios": big_s,
        "seconds_to_gap": round(elapsed, 3),
        "iterations": iters,
        "sec_per_iter": round(elapsed / max(1, iters), 6),
        "rel_gap": _fin(rel_gap),
        "certified": bool(rel_gap <= GAP_TARGET),
        "outer": _fin(outer),
        "inner": _fin(inner),
        "resident_bytes_synth": vb_b.persistent_bytes()
        + state_bytes(wst),
        "resident_bytes_materialized_est": vb_b.materialized_bytes()
        + state_bytes(wst),
    }
    out["metrics_snapshot"] = metrics_mod.REGISTRY.to_snapshot()
    out["note"] = (
        "farmer scenarios synthesized on-device from counter-based "
        "keys (mpisppy_tpu/scengen): the A/B ratio compares PH "
        "iters/s on the SAME bits held resident vs synthesized "
        "in-step; the certified run is the fused wheel (hub + "
        "Lagrangian + x-bar recourse planes) at the top scale to "
        "rel_gap <= 1% with only the program pytree + solver state "
        "resident")
    return out


def bench_serve_load():
    """ISSUE 12 acceptance: the multi-tenant wheel server under load
    (docs/serving.md).  An in-process WheelServer (unix socket) serves
    N concurrent synthetic clients across tenants running the mixed
    farmer/sslp/uc workload; the phase reports p50/p99 client-observed
    time-to-1%-gap, then repeats the run with ONE adversarial tenant
    (flood through the ServeFault seam + hang + disconnect) and reports
    the healthy tenants' p99 against the clean baseline — the
    tenant-isolation ratio carries a <= 1.25 MILESTONE
    (telemetry/regress.py) and the latency keys gate at +-25%."""
    import tempfile

    from mpisppy_tpu.resilience.faults import FaultPlan, ServeFault
    from mpisppy_tpu.serve import loadgen
    from mpisppy_tpu.serve.server import ServeOptions, WheelServer

    n_clients = 4 if SMOKE else 8
    sessions_each = 1 if SMOKE else 2
    tenants = ("acme", "zeta")
    mix = loadgen.DEFAULT_MIX
    deadline_s = 600.0

    def run_round(fault_plan=None, adversary=None):
        td = tempfile.mkdtemp(prefix="serve_load_")
        # the isolation mechanism under test: per-tenant quota 1 over
        # max_running 3 means no tenant — adversarial or not — can
        # hold more than a third of the worker pool, and the WFQ pop
        # keeps the freed slots rotating fairly (docs/serving.md)
        srv = WheelServer(ServeOptions(
            unix_path=os.path.join(td, "wheel.sock"),
            trace_dir=os.path.join(td, "traces"),
            spool_dir=os.path.join(td, "spool"),
            max_running=3, tenant_quota=1,
            max_queued=24, max_queued_per_tenant=8,
            fault_plan=fault_plan, multiplex=True)).start()
        try:
            recs = loadgen.run_load(
                srv.address, n_clients=n_clients,
                sessions_each=sessions_each, tenants=tenants,
                mix=mix, gap_target=GAP_TARGET, max_iterations=300,
                deadline_s=deadline_s, adversary=adversary,
                adversary_sessions=6, fault_plan=fault_plan)
            stats = srv.stats()
        finally:
            srv.stop()
        return recs, stats

    t0 = time.perf_counter()
    # warm-up round (uncounted): every model in the mix compiles once
    # per process, so the baseline/adversarial A/B below compares
    # serving latency, not who paid the jit compiles
    run_round()
    base_recs, base_stats = run_round()
    base = loadgen.summarize(base_recs, healthy_tenants=tenants)

    plan = FaultPlan(seed=12, serves=(
        ServeFault("flood", tenant="mallory", flood_factor=3),
        ServeFault("hang", tenant="mallory", at_sessions=(0,),
                   hang_s=30.0),
        ServeFault("disconnect", tenant="mallory", at_sessions=(1,)),
    ))
    adv_recs, adv_stats = run_round(fault_plan=plan,
                                    adversary="mallory")
    healthy = loadgen.summarize(adv_recs, healthy_tenants=tenants)
    adversary = loadgen.summarize(adv_recs,
                                  healthy_tenants=("mallory",))
    ratio = None
    if base["time_to_gap_p99_s"] and healthy["time_to_gap_p99_s"]:
        ratio = round(healthy["time_to_gap_p99_s"]
                      / base["time_to_gap_p99_s"], 4)
    out = {
        "clients": n_clients,
        "tenants": len(tenants),
        "sessions": base["sessions"],
        "iter_precision": ITER_PRECISION or "bf16x6",
        "gap_target": GAP_TARGET,
        "reached_gap": base["reached_gap"],
        "time_to_gap_p50_s": base["time_to_gap_p50_s"],
        "time_to_gap_p99_s": base["time_to_gap_p99_s"],
        "outcomes": base["outcomes"],
        "dispatch": base_stats.get("dispatch"),
        "exchange_ring": base_stats.get("exchange_ring"),
        "isolation": {
            "adversary": "mallory",
            "healthy_sessions": healthy["sessions"],
            "healthy_reached_gap": healthy["reached_gap"],
            "baseline_p99_s": base["time_to_gap_p99_s"],
            "adversarial_healthy_p99_s": healthy["time_to_gap_p99_s"],
            "adversarial_healthy_p50_s": healthy["time_to_gap_p50_s"],
            "isolation_ratio": ratio,
            "milestone_isolation_ratio": 1.25,
            "adversary_outcomes": adversary["outcomes"],
            "admission_rejects": adv_stats["admission"]["rejected"],
        },
        "bench_serve_total_sec": round(time.perf_counter() - t0, 1),
        "note": "multi-tenant wheel server under load: mixed "
                "farmer/sslp/uc sessions over one shared device "
                "wheel stack; time_to_gap = client-observed wall "
                "from submit to the first streamed rel_gap <= 1%; "
                "isolation_ratio = healthy-tenant p99 with one "
                "adversarial tenant (flood+hang+disconnect "
                "ServeFaults) over the no-adversary baseline p99 "
                "(acceptance <= 1.25)",
    }
    # ISSUE 20: commit the per-class SLO burn rates alongside the raw
    # latencies so regress.py's slo.* gates bind on this artifact
    from mpisppy_tpu.telemetry import slo as _slo
    out["slo"] = _slo.bench_slo_section({"serve_load": out})
    return out


def bench_mesh_chaos():
    """ISSUE 17 acceptance: kill one host mid-wheel and prove the
    elastic re-shard (parallel/elastic.run_elastic) resumes on the
    survivors and certifies the SAME <= 1% gap as a fault-free
    baseline.  A/B on the 8-virtual-device mesh split as 4 hosts x 2
    devices: the A side spins a sharded fused wheel on a synthesized
    farmer batch to the certified gap; the B side runs the identical
    program under a FaultPlan that kills host 1 mid-wheel — membership
    fences it, the MeshDegraded unwind lands the emergency checkpoint,
    and run_elastic rebuilds at 6 devices (the batch re-pads with
    zero-probability lanes) and resumes holding the bracket.  Gates:
    mesh_reshards_lost_total carries an any-increase gate (0 resharded
    runs lost) and reshard_reached_gap_frac a 1.0 ratchet MILESTONE
    (telemetry/regress.py)."""
    import tempfile

    from mpisppy_tpu import scengen
    from mpisppy_tpu.algos import fused_wheel as fw
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.cylinders import PHHub
    from mpisppy_tpu.cylinders.spoke import (
        FusedLagrangianOuterBound, FusedXhatXbarInnerBound,
    )
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.ops import pdhg
    from mpisppy_tpu.parallel import mesh as mesh_mod
    from mpisppy_tpu.parallel.elastic import run_elastic
    from mpisppy_tpu.resilience import FaultPlan, MeshFault
    from mpisppy_tpu.spin_the_wheel import WheelSpinner
    from mpisppy_tpu.telemetry import EventBus
    from mpisppy_tpu.telemetry import metrics as _metrics

    S = 256 if SMOKE else (10_000 if QUICK else 100_000)
    num_hosts = 4
    kill_iter = 2 if SMOKE else 6
    max_iters = 5 if SMOKE else MAX_WHEEL_ITERS
    prog = farmer.scenario_program(S, seed=0)
    wopts = fw.FusedWheelOptions(lag_windows=4, xhat_windows=2,
                                 slam_windows=0, shuffle_windows=0,
                                 split_dispatch=False,
                                 lag_pdhg=pdhg.PDHGOptions(tol=1e-7),
                                 xhat_pdhg=pdhg.PDHGOptions(
                                     tol=1e-7, omega0=0.1,
                                     restart_period=80))
    spokes = [
        {"spoke_class": FusedLagrangianOuterBound,
         "opt_kwargs": {"options": {}}},
        {"spoke_class": FusedXhatXbarInnerBound,
         "opt_kwargs": {"options": {}}},
    ]

    def build_fn(ckpt):
        def build(mesh):
            b = mesh_mod.shard_batch(scengen.virtual_batch(prog), mesh,
                                     pad=True)
            opts = ph_mod.PHOptions(
                default_rho=1.0, max_iterations=max_iters,
                conv_thresh=0.0, subproblem_windows=10,
                pdhg=pdhg.PDHGOptions(tol=1e-7,
                                      iter_precision=ITER_PRECISION))
            hub = {"hub_class": PHHub,
                   "hub_kwargs": {"options": {
                       "rel_gap": GAP_TARGET,
                       "checkpoint_path": ckpt,
                       "checkpoint_every_s": 1e9}},
                   "opt_class": fw.FusedPH,
                   "opt_kwargs": {"options": opts, "batch": b,
                                  "wheel_options": wopts}}
            return WheelSpinner(hub, spokes)
        return build

    def bracket(ws):
        inner = float(ws.BestInnerBound)
        outer = float(ws.BestOuterBound)
        gap = (inner - outer) / max(abs(inner), abs(outer), 1e-12)
        return inner, outer, gap

    td = tempfile.mkdtemp(prefix="mesh_chaos_")

    # A side: fault-free wheel at the full topology
    t0 = time.perf_counter()
    base = build_fn(os.path.join(td, "base.npz"))(mesh_mod.make_mesh())
    base.spin()
    base_s = round(time.perf_counter() - t0, 2)
    ib, ob, gb = bracket(base)

    # B side: identical program, host 1 dies at kill_iter
    bus = EventBus()
    lost0 = _metrics.REGISTRY.get("mesh_reshards_lost_total")
    resh0 = _metrics.REGISTRY.get("mesh_reshards_total")
    plan = FaultPlan(seed=11, meshes=(
        MeshFault("host_lost", host=1, at_iters=(kill_iter,)),))
    t1 = time.perf_counter()
    ws, info = run_elastic(
        build_fn(os.path.join(td, "chaos.npz")), num_hosts=num_hosts,
        checkpoint_path=os.path.join(td, "chaos.npz"), plan=plan,
        bus=bus, run_id="bench-mesh-chaos")
    chaos_s = round(time.perf_counter() - t1, 2)
    ic, oc, gc = bracket(ws)

    lost = _metrics.REGISTRY.get("mesh_reshards_lost_total") - lost0
    reshards = _metrics.REGISTRY.get("mesh_reshards_total") - resh0
    certified = bool(gc <= GAP_TARGET)
    return {
        "scenarios": S,
        "num_hosts": num_hosts,
        "iter_precision": ITER_PRECISION or "bf16x6",
        "gap_target": GAP_TARGET,
        "baseline": {
            "devices": 8, "inner": ib, "outer": ob,
            "rel_gap": round(gb, 6), "iters": base.spcomm._iter,
            "wall_s": base_s, "certified": bool(gb <= GAP_TARGET),
        },
        "chaos": {
            "chaos": f"kill host 1 at hub iter {kill_iter}",
            "final_devices": info["final_devices"],
            "epoch": info["epoch"],
            "inner": ic, "outer": oc, "rel_gap": round(gc, 6),
            "iters": ws.spcomm._iter, "wall_s": chaos_s,
            "certified": certified,
            "reshard_transitions": info["reshards"],
        },
        "reshard": {
            "mesh_reshards_total": reshards,
            "mesh_reshards_lost_total": lost,
            "reshard_reached_gap_frac": 1.0 if certified else 0.0,
        },
        "bench_mesh_chaos_total_sec": round(time.perf_counter() - t0, 1),
        "note": "elastic mesh A/B: fault-free sharded fused wheel vs "
                "the same wheel with host 1 killed mid-run; the "
                "MeshDegraded unwind lands the emergency checkpoint, "
                "run_elastic re-shards across the 6 survivor devices "
                "(zero-probability pad lanes keep the bracket "
                "layout-invariant) and the resumed run must certify "
                "the same <= 1% gap; reshard_reached_gap_frac "
                "ratchets at 1.0 and mesh_reshards_lost_total must "
                "stay 0",
    }


def bench_fleet_serve_load():
    """ISSUE 16 acceptance: the replicated serve fleet under load with
    a replica killed mid-traffic (docs/serving.md fleet section).  A
    3-replica FleetRouter — each replica a full wheel server with its
    own engine and structure interner over one shared checkpoint spool
    — serves the mixed farmer/sslp/uc workload twice: a fault-free
    round (p50/p99 client-observed time-to-gap) and a chaos round
    where r0 dies mid-traffic and its running sessions LIVE-MIGRATE
    (emergency checkpoint -> requeue -> restore on a surviving
    replica).  Gates: the latency keys at +-25% and isolation_ratio
    (chaos p99 over fault-free p99) ride the serve_load patterns;
    fleet_migrations_lost_total carries an any-increase gate and
    migrated_reached_gap_frac a 1.0 ratchet MILESTONE
    (telemetry/regress.py)."""
    import json as _json
    import tempfile

    from mpisppy_tpu.fleet import FleetOptions, FleetRouter
    from mpisppy_tpu.resilience.faults import FaultPlan, ReplicaFault
    from mpisppy_tpu.serve import loadgen
    from mpisppy_tpu.telemetry import metrics as _metrics

    n_replicas = 3
    n_clients = 4 if SMOKE else 8
    sessions_each = 1 if SMOKE else 2
    tenants = ("acme", "zeta")
    deadline_s = 600.0
    heartbeat_s = 0.5

    def run_round(fault_plan=None):
        td = tempfile.mkdtemp(prefix="fleet_load_")
        router = FleetRouter(FleetOptions(
            unix_path=os.path.join(td, "fleet.sock"),
            n_replicas=n_replicas, max_running_per_replica=1,
            max_queued=24, max_queued_per_tenant=8, tenant_quota=2,
            trace_dir=os.path.join(td, "traces"),
            spool_dir=os.path.join(td, "spool"),
            heartbeat_s=heartbeat_s, drain_grace_s=60.0,
            fault_plan=fault_plan)).start()
        try:
            recs = loadgen.run_load(
                router.address, n_clients=n_clients,
                sessions_each=sessions_each, tenants=tenants,
                mix=loadgen.DEFAULT_MIX, gap_target=GAP_TARGET,
                max_iterations=300, deadline_s=deadline_s,
                fault_plan=fault_plan)
            stats = router.stats()
        finally:
            router.stop()
        # evidence scan: terminal session-state transitions and
        # migrations in the router stream (one file, every replica)
        terminals: dict = {}
        migrated: set = set()
        fleet_log = os.path.join(td, "traces", "fleet.jsonl")
        if os.path.exists(fleet_log):
            with open(fleet_log) as f:
                for line in f:
                    try:
                        row = _json.loads(line)
                    except ValueError:
                        continue
                    d = row.get("data", {})
                    if row.get("kind") == "session-state" \
                            and d.get("state") in ("DONE", "FAILED",
                                                   "REJECTED"):
                        sid = d.get("session")
                        terminals[sid] = terminals.get(sid, 0) + 1
                    elif row.get("kind") == "session-migrated" \
                            and not d.get("queued"):
                        migrated.add(d.get("session"))
        return recs, stats, terminals, migrated

    t0 = time.perf_counter()
    lost0 = _metrics.REGISTRY.get("fleet_migrations_lost_total")
    # warm-up round (uncounted): every model in the mix compiles once
    # per process, so the A/B below compares serving, not jit
    run_round()
    base_recs, base_stats, base_terms, _ = run_round()
    base = loadgen.summarize(base_recs, healthy_tenants=tenants)

    # chaos round: r0 stops heartbeating a few beats in — the router
    # fences it, drains it, and its sessions migrate mid-solve
    kill_beat = 2 if SMOKE else 8
    plan = FaultPlan(seed=12, replicas=(
        ReplicaFault("kill", replica="r0", at_beats=(kill_beat,)),))
    chaos_recs, chaos_stats, chaos_terms, migrated = run_round(plan)
    chaos = loadgen.summarize(chaos_recs, healthy_tenants=tenants)

    mig_recs = [r for r in chaos_recs if r.get("session") in migrated]
    mig_hit = sum(1 for r in mig_recs
                  if r["time_to_gap_s"] is not None)
    mig_frac = round(mig_hit / len(mig_recs), 4) if mig_recs else None
    ratio = None
    if base["time_to_gap_p99_s"] and chaos["time_to_gap_p99_s"]:
        ratio = round(chaos["time_to_gap_p99_s"]
                      / base["time_to_gap_p99_s"], 4)
    multi = {sid: n for sid, n in {**base_terms, **chaos_terms}.items()
             if n > 1}
    lost = _metrics.REGISTRY.get("fleet_migrations_lost_total") - lost0
    out = {
        "replicas": n_replicas,
        "clients": n_clients,
        "sessions": base["sessions"],
        "iter_precision": ITER_PRECISION or "bf16x6",
        "gap_target": GAP_TARGET,
        "reached_gap": base["reached_gap"],
        "time_to_gap_p50_s": base["time_to_gap_p50_s"],
        "time_to_gap_p99_s": base["time_to_gap_p99_s"],
        "outcomes": base["outcomes"],
        "placement": {
            # process-cumulative across the three rounds
            "affinity": _metrics.REGISTRY.get(
                "fleet_placement_affinity_total"),
            "spill": _metrics.REGISTRY.get(
                "fleet_placement_spill_total"),
        },
        "isolation": {
            "chaos": "kill r0 mid-traffic",
            "baseline_p99_s": base["time_to_gap_p99_s"],
            "chaos_p50_s": chaos["time_to_gap_p50_s"],
            "chaos_p99_s": chaos["time_to_gap_p99_s"],
            "chaos_reached_gap": chaos["reached_gap"],
            "chaos_outcomes": chaos["outcomes"],
            "isolation_ratio": ratio,
        },
        "migration": {
            "replica_deaths": 1,
            "migrated_sessions": len(migrated),
            "migration_counters": chaos_stats["migration"],
            "migrated_reached_gap_frac": mig_frac,
            "fleet_migrations_lost_total": lost,
            "sessions_multi_terminal": len(multi),
        },
        "single_replica_ref": {
            # BENCH_r09 serve_load on the same workload shape (one
            # 3-slot server vs this 3x1-slot fleet)
            "time_to_gap_p50_s": 1.9227,
            "time_to_gap_p99_s": 5.9392,
            "isolation_ratio": 0.9732,
        },
        "bench_fleet_total_sec": round(time.perf_counter() - t0, 1),
        "note": "replicated serve fleet under load: 3 replicas x 1 "
                "slot, each a full wheel server with its own engine/"
                "interner over one shared checkpoint spool; fault-free "
                "round vs chaos round with r0 killed mid-traffic; "
                "running sessions on r0 live-migrate (emergency "
                "checkpoint -> requeue -> restore elsewhere); "
                "isolation_ratio = chaos p99 / fault-free p99; every "
                "session must observe exactly one terminal outcome "
                "and fleet_migrations_lost_total must stay 0",
    }
    # ISSUE 20: per-class SLO burn rates over the fault-free round
    from mpisppy_tpu.telemetry import slo as _slo
    out["slo"] = _slo.bench_slo_section({"fleet_serve_load": out})
    return out


def bench_mpc_stream():
    """ISSUE 19 acceptance: rolling-horizon MPC streams as a latency
    class (docs/mpc.md).  Two parts:

    LATENCY A/B — for each committed horizon (uc 2g/4h stride 1 and
    ccopf --soc) a RollingDriver solves the same windows twice: WARM
    (the previous step's PH plane shifted by the horizon's ShiftPlan)
    and COLD (no plane, jit compiles already paid), at the same
    per-step iteration budget.  Per-model step-latency p50/p99 gate at
    +-25% (telemetry/regress.py); the pooled warm-over-cold mean
    ratio carries the <= 0.6 MILESTONE.

    CHAOS — one uc stream runs fault-free through the serve engine
    (WheelEngine -> mpc.stream), then a second identical stream is
    PREEMPTED mid-flight (preempt_event at a step boundary, the
    live-migration drain seam) and resumed from its stream checkpoint.
    Every per-step bound of the resumed stream must match the
    fault-free stream bit-for-bit (resumed_matched_frac ratchets at
    1.0) and the session must observe exactly one terminal verdict."""
    import tempfile

    from mpisppy_tpu.mpc.driver import RollingDriver
    from mpisppy_tpu.mpc.horizon import ccopf_horizon, uc_horizon
    from mpisppy_tpu.serve.engine import WheelEngine
    from mpisppy_tpu.serve.protocol import SubmitRequest
    from mpisppy_tpu.serve.session import Session

    steps = 2 if SMOKE else 4
    gap = 0.05
    budget = 300
    t0 = time.perf_counter()

    def latency_ab(horizon):
        drv = RollingDriver(horizon)
        tc = time.perf_counter()
        res = drv.run_step(0)
        cold0_s = time.perf_counter() - tc     # pays the jit compiles
        plane = drv.next_plane(res)
        warm, cold, degraded, warm_hit, cold_hit = [], [], 0, 0, 0
        for k in range(1, steps + 1):
            tw = time.perf_counter()
            r = drv.run_step(k, warm_plane=plane)
            warm.append(time.perf_counter() - tw)
            plane = drv.next_plane(r)
            degraded += 1 if r.degraded else 0
            warm_hit += 0 if r.degraded else 1
        for k in range(1, steps + 1):
            tw = time.perf_counter()
            r = drv.run_step(k)
            cold.append(time.perf_counter() - tw)
            cold_hit += 0 if r.degraded else 1
        wl, cl = np.asarray(warm), np.asarray(cold)
        return {
            "steps": steps,
            "cold_step0_s": round(cold0_s, 4),
            "warm_mean_s": round(float(wl.mean()), 4),
            "cold_mean_s": round(float(cl.mean()), 4),
            "step_latency_p50_s": round(float(np.percentile(wl, 50)), 4),
            "step_latency_p99_s": round(float(np.percentile(wl, 99)), 4),
            "model_warm_cold_ratio": round(
                float(wl.mean() / cl.mean()), 4),
            "warm_reached_gap_frac": round(warm_hit / steps, 4),
            "cold_reached_gap_frac": round(cold_hit / steps, 4),
            "degraded_steps": degraded,
        }, warm, cold

    uc_args = ("--uc-n-gens", "2", "--uc-n-hours", "4")
    uc_stats, uc_warm, uc_cold = latency_ab(uc_horizon(
        n_gens=2, n_hours=4, num_scens=3, gap_target=gap,
        max_step_iterations=budget))
    cc_stats, cc_warm, cc_cold = latency_ab(ccopf_horizon(
        soc=True, gap_target=gap, max_step_iterations=budget))
    pooled_warm = np.asarray(uc_warm + cc_warm)
    pooled_cold = np.asarray(uc_cold + cc_cold)
    ratio = round(float(pooled_warm.mean() / pooled_cold.mean()), 4)

    # -- chaos: preempt one uc stream mid-flight and resume it ----------
    td = tempfile.mkdtemp(prefix="mpc_stream_")
    engine = WheelEngine(multiplexed=False)

    def make_session(lines):
        s = Session(SubmitRequest(
            tenant="acme", sla="latency", model="uc", num_scens=3,
            gap_target=gap, max_iterations=budget, args=uc_args,
            mpc_steps=steps, step_deadline_s=600.0),
            outbox=lines.append)
        s.checkpoint_path = os.path.join(td, f"stream-{s.sid}.npz")
        return s

    def step_lines(lines):
        return {l["step"]: l for l in lines if l.get("event") == "step"}

    base_lines: list = []
    verdict, _ = engine.run(make_session(base_lines))
    base_steps = step_lines(base_lines)

    preempt_at = max(1, steps // 2)
    chaos_lines: list = []
    s2 = make_session(chaos_lines)
    s2.on_step = (lambda sess: sess.preempt_event.set()
                  if sess.mpc_step == preempt_at else None)
    v1, p1 = engine.run(s2)
    terminal = 1 if v1 == "done" else 0
    s2.preempt_event.clear()
    s2.restore = True
    s2.preemptions += 1
    v2, p2 = engine.run(s2)
    terminal += 1 if v2 == "done" else 0
    chaos_steps = step_lines(chaos_lines)

    def close(a, b):
        return abs(a - b) <= 1e-9 * max(1.0, abs(a), abs(b))

    matched = sum(
        1 for k, row in base_steps.items()
        if k in chaos_steps
        and close(row["outer"], chaos_steps[k]["outer"])
        and close(row["inner"], chaos_steps[k]["inner"])
        and close(row["rel_gap"], chaos_steps[k]["rel_gap"]))
    out = {
        "steps_per_stream": steps,
        "gap_target": gap,
        "iter_budget_per_step": budget,
        "warm_over_cold_ratio": ratio,
        "milestone_warm_over_cold_ratio": 0.6,
        "uc": uc_stats,
        "ccopf_soc": cc_stats,
        "chaos": {
            "chaos": f"preempt the stream entering step {preempt_at}, "
                     "resume from the stream checkpoint",
            "preempted_verdict": v1,
            "preempted_at_step": p1.get("step"),
            "resumed_verdict": v2,
            "steps_matched": matched,
            "steps_total": len(base_steps),
            "resumed_matched_frac": round(
                matched / max(1, len(base_steps)), 4),
            "terminal_outcomes": terminal,
            "resumed_step_latency_p99_s": p2.get("step_latency_p99_s"),
        },
        "bench_mpc_total_sec": round(time.perf_counter() - t0, 1),
        "note": "rolling-horizon MPC streams: per-model warm (shifted "
                "PH plane) vs cold (no plane, compiles paid) per-step "
                "latency at the same iteration budget; "
                "warm_over_cold_ratio pools both horizons' steps "
                "(acceptance <= 0.6) — uc is where the warm start "
                "pays (cold re-solves miss certification inside the "
                "budget), ccopf --soc certifies in 2 iterations either "
                "way (warm parity); the chaos round preempts a uc "
                "stream at a step boundary and the resumed stream "
                "must reproduce the fault-free per-step bounds "
                "bit-for-bit with exactly one terminal outcome",
    }
    # ISSUE 20: the mpc stream product's step-deadline SLO burn rate
    from mpisppy_tpu.telemetry import slo as _slo
    out["slo"] = _slo.bench_slo_section({"mpc_stream": out})
    return out


_PHASES = {
    "sslp_to_1pct_gap": lambda: bench_sslp_gap(),
    "uc_fwph_to_1pct_gap": lambda: bench_uc_fwph(),
    "uc_fwph_hub_to_1pct_gap": lambda: bench_uc_fwph_hub(),
    "hydro_to_1pct_gap": lambda: bench_hydro(),
    "wheel_overhead": lambda: bench_wheel_overhead(),
    "wheel_overhead_async": lambda: bench_wheel_overhead_async(),
    "measured_mfu": lambda: bench_measured_mfu(),
    "wheel_scengen": lambda: bench_wheel_scengen(),
    "serve_load": lambda: bench_serve_load(),
    "mpc_stream": lambda: bench_mpc_stream(),
    "fleet_serve_load": lambda: bench_fleet_serve_load(),
    "mesh_chaos": lambda: bench_mesh_chaos(),
    "baseline_anchor": lambda: bench_baseline_anchor(),
}

#: per-phase subprocess timeout overrides (seconds): the scengen phase
#: runs a certified S=1M wheel on whatever host it lands on — CPU smoke
#: needs ~30 min of honest device work, not a larger problem
_PHASE_TIMEOUTS = {"wheel_scengen": 5400}
for _S in SWEEP:
    _PHASES[f"sweep_{_S}"] = (lambda S=_S: bench_sweep_one(S))


def _run_phase_once(phase: str, timeout: int):
    import subprocess
    import sys
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--phase", phase],
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)) or ".")
        for line in reversed(out.stdout.strip().splitlines()):
            line = line.strip()
            # global_toc trace lines also start with '[' — parse
            # leniently and keep scanning on failure
            if line.startswith("{") or line.startswith("[{"):
                try:
                    return json.loads(line)
                except json.JSONDecodeError:
                    continue
        return {"error": f"no JSON from phase (rc={out.returncode}): "
                         f"{out.stderr.strip()[-300:]}"}
    except subprocess.TimeoutExpired:
        return {"error": f"phase timed out after {timeout}s"}


def _run_phase_subprocess(phase: str, timeout: int = 2400, retries: int = 1):
    """Each phase runs in its own process with a fresh TPU client: the
    worker occasionally dies after sustained heavy use (observed
    'kernel fault' after ~10-15 min of back-to-back wheels), and one
    phase's crash must not cost the others their numbers.  A crashed
    phase is retried once; wheel phases resume from their periodic
    checkpoint, so the retry continues (not restarts) the run —
    VERDICT r3 #2's 'the official artifact must not record -1.0'."""
    import glob
    # a fresh phase must not resume some older run's leftover state;
    # checkpoints land in the CHILD's cwd (= this file's directory, set
    # below), but scan the parent cwd too in case of older runs
    dirs = {os.path.dirname(os.path.abspath(__file__)) or ".",
            os.getcwd()}
    for d in dirs:
        for stale in glob.glob(os.path.join(d, ".bench_ckpt_*.npz")):
            os.remove(stale)
    result = _run_phase_once(phase, timeout)
    for attempt in range(retries):
        if "error" not in result:
            break
        print(f"# phase {phase} attempt {attempt + 1} failed "
              f"({result['error'][:120]}); retrying from checkpoint",
              flush=True)
        result = _run_phase_once(phase, timeout)
    return result


def main():
    import sys
    if len(sys.argv) >= 3 and sys.argv[1] == "--phase" \
            and sys.argv[2] == "mesh_chaos":
        # the elastic A/B needs a multi-host-shaped mesh: force 8
        # virtual devices on the CPU backend (the flag only affects
        # the host platform — harmless when a real accelerator runs)
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = \
                (flags + " --xla_force_host_platform_device_count=8").strip()
    _enable_compile_cache()
    if len(sys.argv) >= 3 and sys.argv[1] == "--phase":
        # child: run one phase, emit its JSON as the last stdout line
        result = _PHASES[sys.argv[2]]()
        print(json.dumps(result))
        return

    t_start = time.time()
    detail = {}
    for phase in _PHASES:
        detail[phase] = _run_phase_subprocess(
            phase, timeout=_PHASE_TIMEOUTS.get(phase, 2400))
    detail["sweep_iters_per_sec"] = [
        detail.pop(f"sweep_{S}") for S in SWEEP]
    detail["bench_total_sec"] = round(time.time() - t_start, 1)
    import jax
    detail["device"] = str(jax.devices()[0].device_kind)

    # never clobber the full-scale hardware artifact with reduced-scale
    # runs: quick mode writes its own file (ADVICE r3 low #2)
    if not SMOKE:
        fname = "BENCH_DETAIL.quick.json" if QUICK else "BENCH_DETAIL.json"
        with open(fname, "w") as f:
            json.dump(detail, f, indent=1)

    headline = detail["sslp_to_1pct_gap"]
    if "seconds_to_gap" in headline:
        vs = headline["baseline_64rank_sec"] / max(
            headline["seconds_to_gap"], 1e-9)
        value = headline["seconds_to_gap"]
    else:
        vs, value = 0.0, -1.0
    print(json.dumps({
        "metric": f"wallclock_to_1pct_certified_gap_sslp_15_45_"
                  f"{SSLP_SCENS}scen",
        "value": value,
        "unit": "s",
        "vs_baseline": round(vs, 2),
        "detail": detail,
    }))


if __name__ == "__main__":
    main()
