"""Benchmark: PH iterations/sec on the BASELINE.md north-star config
(sslp, LP-relaxed, scenario batch at scale), on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The measured quantity is PH iterations per second over the full scenario
batch.  `vs_baseline` is the speedup over the reference's execution
model — one sequential CPU LP solve per scenario per PH iteration (what
each mpi-sppy rank does in solve_loop, ref:mpisppy/spopt.py:250-341) —
estimated by timing scipy.linprog (HiGHS) on a sample of the same
subproblems and scaling to the full scenario count.  That is the
single-rank baseline; divide by the rank count to compare against an
MPI job (e.g. vs_baseline 6400 ≈ 100x faster than a 64-rank cluster).
"""
from __future__ import annotations

import json
import time

import numpy as np

NUM_SCENS = 10_000
N_SERVERS = 15
N_CLIENTS = 45


def time_scipy_baseline(specs, sample=8):
    """Mean seconds per scenario LP via scipy/HiGHS (sequential-CPU model)."""
    from scipy.optimize import linprog

    times = []
    for sp in specs[:sample]:
        A_ub, b_ub, A_eq, b_eq = [], [], [], []
        for i in range(sp.A.shape[0]):
            if sp.bl[i] == sp.bu[i]:
                A_eq.append(sp.A[i]); b_eq.append(sp.bu[i])
                continue
            if np.isfinite(sp.bu[i]):
                A_ub.append(sp.A[i]); b_ub.append(sp.bu[i])
            if np.isfinite(sp.bl[i]):
                A_ub.append(-sp.A[i]); b_ub.append(-sp.bl[i])
        t0 = time.perf_counter()
        res = linprog(sp.c, A_ub=np.array(A_ub), b_ub=np.array(b_ub),
                      A_eq=np.array(A_eq) if A_eq else None,
                      b_eq=np.array(b_eq) if b_eq else None,
                      bounds=list(zip(sp.l, sp.u)), method="highs")
        times.append(time.perf_counter() - t0)
        assert res.status == 0
    return float(np.mean(times))


def main():
    import jax
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.models import sslp
    from mpisppy_tpu.ops import pdhg

    inst = sslp.synthetic_instance(N_SERVERS, N_CLIENTS, seed=0)
    names = sslp.scenario_names_creator(NUM_SCENS)
    specs = [sslp.scenario_creator(nm, instance=inst, num_scens=NUM_SCENS,
                                   lp_relax=True)
             for nm in names]
    batch = batch_mod.from_specs(specs)

    opts = ph_mod.PHOptions(
        default_rho=20.0, subproblem_windows=8,
        pdhg=pdhg.PDHGOptions(tol=1e-6, restart_period=40),
    )
    rho = np.full(batch.num_nonants, opts.default_rho, np.float32)
    state, _, _ = ph_mod.ph_iter0(batch, jax.numpy.asarray(rho), opts)

    # warmup/compile
    state = ph_mod.ph_iterk(batch, state, opts)
    jax.block_until_ready(state.conv)

    n_iters = 20
    t0 = time.perf_counter()
    for _ in range(n_iters):
        state = ph_mod.ph_iterk(batch, state, opts)
    jax.block_until_ready(state.conv)
    elapsed = time.perf_counter() - t0
    iters_per_sec = n_iters / elapsed

    # baseline: sequential CPU LP solves, one per scenario per iteration
    sec_per_lp = time_scipy_baseline(specs)
    baseline_iters_per_sec = 1.0 / (sec_per_lp * NUM_SCENS)

    print(json.dumps({
        "metric": f"ph_iters_per_sec_sslp_{N_SERVERS}_{N_CLIENTS}_"
                  f"{NUM_SCENS}scen",
        "value": round(iters_per_sec, 3),
        "unit": "iter/s",
        "vs_baseline": round(iters_per_sec / baseline_iters_per_sec, 2),
    }))


if __name__ == "__main__":
    main()
