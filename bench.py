"""Benchmark: PH iterations/sec on the scenario batch, on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The measured quantity is the north-star metric from BASELINE.md: PH
iterations per second at scale.  `vs_baseline` is the speedup over the
reference's execution model — one sequential CPU LP solve per scenario
per PH iteration (what each mpi-sppy rank does in solve_loop,
ref:mpisppy/spopt.py:250-341) — estimated by timing scipy.linprog
(HiGHS) on a sample of the same subproblems and scaling to the full
scenario count.
"""
from __future__ import annotations

import json
import time

import numpy as np


def time_scipy_baseline(specs, sample=8):
    """Mean seconds per scenario LP via scipy/HiGHS (sequential-CPU model)."""
    from scipy.optimize import linprog

    times = []
    for sp in specs[:sample]:
        A_ub, b_ub = [], []
        for i in range(sp.A.shape[0]):
            if np.isfinite(sp.bu[i]):
                A_ub.append(sp.A[i]); b_ub.append(sp.bu[i])
            if np.isfinite(sp.bl[i]):
                A_ub.append(-sp.A[i]); b_ub.append(-sp.bl[i])
        t0 = time.perf_counter()
        res = linprog(sp.c, A_ub=np.array(A_ub), b_ub=np.array(b_ub),
                      bounds=list(zip(sp.l, sp.u)), method="highs")
        times.append(time.perf_counter() - t0)
        assert res.status == 0
    return float(np.mean(times))


def main():
    import jax
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.ops import pdhg

    num_scens = 5000
    crops_multiplier = 4
    names = farmer.scenario_names_creator(num_scens)
    specs = [farmer.scenario_creator(nm, num_scens=num_scens,
                                     crops_multiplier=crops_multiplier)
             for nm in names]
    batch = batch_mod.from_specs(specs)

    opts = ph_mod.PHOptions(
        default_rho=1.0, subproblem_windows=8,
        pdhg=pdhg.PDHGOptions(tol=1e-6, restart_period=40),
    )
    rho = np.ones(batch.num_nonants, np.float32)
    state, _ = ph_mod.ph_iter0(batch, jax.numpy.asarray(rho), opts)

    # warmup/compile
    state = ph_mod.ph_iterk(batch, state, opts)
    jax.block_until_ready(state.conv)

    n_iters = 20
    t0 = time.perf_counter()
    for _ in range(n_iters):
        state = ph_mod.ph_iterk(batch, state, opts)
    jax.block_until_ready(state.conv)
    elapsed = time.perf_counter() - t0
    iters_per_sec = n_iters / elapsed

    # baseline: sequential CPU LP solves, one per scenario per iteration
    sec_per_lp = time_scipy_baseline(specs)
    baseline_iters_per_sec = 1.0 / (sec_per_lp * num_scens)

    print(json.dumps({
        "metric": f"ph_iters_per_sec_farmer_{num_scens}scen_"
                  f"{batch.qp.c.shape[-1]}var",
        "value": round(iters_per_sec, 3),
        "unit": "iter/s",
        "vs_baseline": round(iters_per_sec / baseline_iters_per_sec, 2),
    }))


if __name__ == "__main__":
    main()
