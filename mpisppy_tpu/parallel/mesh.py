###############################################################################
# The device mesh: this framework's entire "MPI".
#
# The reference's communication layer is mpi4py plus a numpy mock
# (ref:mpisppy/MPI.py:10-90), with scenarios block-partitioned over a
# cylinder communicator (ref:mpisppy/spbase.py:188-220) and every
# reduction an explicit Allreduce (ref:mpisppy/phbase.py:88-92,
# ref:mpisppy/spopt.py:344-556).  The TPU design needs none of that
# machinery: scenario arrays are sharded over a 1-D mesh axis 'scen'
# (ICI/DCN underneath), every jitted step takes sharded inputs, and XLA's
# SPMD partitioner turns the p-weighted reductions into all-reduce
# collectives automatically.  One seam — `shard_batch` — replaces the
# whole of MPI.py: called with a 1-device mesh it is the "mock" serial
# backend; with a TPU pod mesh it is the production backend.  Tests run
# the same code on a virtual 8-device CPU mesh
# (ref:.github/workflows/test_pr_and_main.yml:27-48 analog).
###############################################################################
from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SCEN_AXIS = "scen"


def init_multihost(coordinator_address: str,
                   num_processes: int,
                   process_id: int,
                   cpu_devices_per_process: int | None = None) -> None:
    """Initialize the multi-host (DCN) runtime — the analog of the
    reference's `mpiexec` + COMM_WORLD bootstrap
    (ref:mpisppy/spin_the_wheel.py:224-242): after this, jax.devices()
    is the GLOBAL device list, make_mesh() spans all hosts, and the
    scenario-axis reductions inside jitted steps ride ICI within a host
    and DCN across hosts via the same collectives.

    cpu_devices_per_process: when set (tests / dry runs), forces a
    virtual CPU topology — N devices per process with gloo collectives
    — so a 2-process x 4-device mesh runs on one machine with no TPU,
    the multi-host analog of the conftest virtual mesh.  Must be called
    before any other jax API touches the backend."""
    import jax as _jax

    if cpu_devices_per_process is not None:
        _jax.config.update("jax_platforms", "cpu")
        try:
            _jax.config.update("jax_num_cpu_devices",
                               int(cpu_devices_per_process))
        except AttributeError:
            # older JAX spells the knob as an XLA flag, read when the
            # backend initializes (distributed.initialize below
            # triggers that, so setting the env var here still works)
            import os
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count="
                    f"{int(cpu_devices_per_process)}").strip()
        _jax.config.update("jax_cpu_collectives_implementation", "gloo")
    _jax.distributed.initialize(coordinator_address=coordinator_address,
                                num_processes=num_processes,
                                process_id=process_id)


def process_local_slice(S: int) -> slice:
    """This process's contiguous scenario block under the canonical
    process-major layout (the analog of the reference's
    _calculate_scenario_ranks block partitioning,
    ref:mpisppy/spbase.py:188-220)."""
    import jax as _jax

    P_ = _jax.process_count()
    if S % P_ != 0:
        raise ValueError(f"{S} scenarios not divisible by "
                         f"{P_} processes; pad first")
    per = S // P_
    i = _jax.process_index()
    return slice(i * per, (i + 1) * per)


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over the scenario axis.  n_devices=None uses all
    available devices; n_devices=1 is the serial/mock path."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (SCEN_AXIS,))


def scen_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(SCEN_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh, pad: bool = False):
    """Place a ScenarioBatch on the mesh: scenario-major arrays sharded on
    their leading axis, shared arrays replicated.  Scenario-carrying
    fields are recognized by leading-axis length == num_scenarios with the
    field's batched rank (mirrors pad_to_multiple's ndim logic).

    pad=True re-pads the scenario axis to the mesh's multiple first —
    the elastic-reshard path onto a SURVIVOR set whose device count
    does not divide S (docs/resilience.md).  Padding lanes carry ZERO
    probability mass (never a replicated real lane's probability), so
    every p-weighted reduction — eobjective, conv, the certified
    bounds — is value-identical to the pre-loss layout."""
    S = batch.num_scenarios
    if S % mesh.size != 0:
        if pad:
            if getattr(batch, "is_virtual", False):
                from mpisppy_tpu.scengen.virtual import repartition
                batch = repartition(batch, mesh.size)
            else:
                from mpisppy_tpu.core.batch import pad_to_multiple
                batch = pad_to_multiple(batch, mesh.size)
            S = batch.num_scenarios
        else:
            raise ValueError(
                f"{S} scenarios not divisible by mesh size {mesh.size}; "
                "use core.batch.pad_to_multiple first"
                + (" (scengen: virtual_batch(pad_to=mesh.size))"
                   if getattr(batch, "is_virtual", False) else ""))
    shard = scen_sharding(mesh)
    repl = replicated(mesh)

    if getattr(batch, "is_virtual", False):
        # scengen VirtualBatch (docs/scengen.md sharded synthesis):
        # only the probabilities (and the multistage node map) carry
        # the scenario axis — shard those, replicate the key + shared
        # template.  Inside a jitted step, realize()'s fold_in/sampler
        # chain partitions along the same axis via SPMD propagation, so
        # each device synthesizes only its shard's scenarios from the
        # same base key (the counter scheme makes the draws
        # layout-invariant).
        repl_tree = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, repl), batch.shared)
        return dataclasses.replace(
            batch,
            base_key=jax.device_put(batch.base_key, repl),
            p=jax.device_put(batch.p, shard),
            d_col=jax.device_put(batch.d_col, repl),
            d_row=jax.device_put(batch.d_row, repl),
            d_non=jax.device_put(batch.d_non, repl),
            nonant_idx=jax.device_put(batch.nonant_idx, repl),
            node_of_slot=(None if batch.node_of_slot is None
                          else jax.device_put(batch.node_of_slot,
                                              shard)),
            integer_slot=jax.device_put(batch.integer_slot, repl),
            integer_full=jax.device_put(batch.integer_full, repl),
            shared=repl_tree,
        )

    def put(x, batched_ndim):
        if hasattr(x, "vals"):  # ops.sparse.EllMatrix: shard the values
            return dataclasses.replace(
                x, vals=put(x.vals, batched_ndim),
                cols=jax.device_put(x.cols, repl))
        return jax.device_put(x, shard if x.ndim == batched_ndim else repl)

    qp = batch.qp
    qp = dataclasses.replace(
        qp,
        c=put(qp.c, 2), q=put(qp.q, 2), A=put(qp.A, 3),
        bl=put(qp.bl, 2), bu=put(qp.bu, 2), l=put(qp.l, 2), u=put(qp.u, 2),
    )
    return dataclasses.replace(
        batch,
        qp=qp,
        d_col=put(batch.d_col, 2),
        d_row=put(batch.d_row, 2),
        d_non=put(batch.d_non, 2),
        p=jax.device_put(batch.p, shard),
        nonant_idx=jax.device_put(batch.nonant_idx, repl),
        node_of_slot=put(batch.node_of_slot, 2),
        integer_slot=jax.device_put(batch.integer_slot, repl),
        integer_full=jax.device_put(batch.integer_full, repl),
        var_prob=None if batch.var_prob is None
        else jax.device_put(batch.var_prob, shard),
    )
