###############################################################################
# The device mesh: this framework's entire "MPI".
#
# The reference's communication layer is mpi4py plus a numpy mock
# (ref:mpisppy/MPI.py:10-90), with scenarios block-partitioned over a
# cylinder communicator (ref:mpisppy/spbase.py:188-220) and every
# reduction an explicit Allreduce (ref:mpisppy/phbase.py:88-92,
# ref:mpisppy/spopt.py:344-556).  The TPU design needs none of that
# machinery: scenario arrays are sharded over a 1-D mesh axis 'scen'
# (ICI/DCN underneath), every jitted step takes sharded inputs, and XLA's
# SPMD partitioner turns the p-weighted reductions into all-reduce
# collectives automatically.  One seam — `shard_batch` — replaces the
# whole of MPI.py: called with a 1-device mesh it is the "mock" serial
# backend; with a TPU pod mesh it is the production backend.  Tests run
# the same code on a virtual 8-device CPU mesh
# (ref:.github/workflows/test_pr_and_main.yml:27-48 analog).
###############################################################################
from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SCEN_AXIS = "scen"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over the scenario axis.  n_devices=None uses all
    available devices; n_devices=1 is the serial/mock path."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (SCEN_AXIS,))


def scen_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(SCEN_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh):
    """Place a ScenarioBatch on the mesh: scenario-major arrays sharded on
    their leading axis, shared arrays replicated.  Scenario-carrying
    fields are recognized by leading-axis length == num_scenarios with the
    field's batched rank (mirrors pad_to_multiple's ndim logic)."""
    S = batch.num_scenarios
    if S % mesh.size != 0:
        raise ValueError(
            f"{S} scenarios not divisible by mesh size {mesh.size}; "
            "use core.batch.pad_to_multiple first")
    shard = scen_sharding(mesh)
    repl = replicated(mesh)

    def put(x, batched_ndim):
        if hasattr(x, "vals"):  # ops.sparse.EllMatrix: shard the values
            return dataclasses.replace(
                x, vals=put(x.vals, batched_ndim),
                cols=jax.device_put(x.cols, repl))
        return jax.device_put(x, shard if x.ndim == batched_ndim else repl)

    qp = batch.qp
    qp = dataclasses.replace(
        qp,
        c=put(qp.c, 2), q=put(qp.q, 2), A=put(qp.A, 3),
        bl=put(qp.bl, 2), bu=put(qp.bu, 2), l=put(qp.l, 2), u=put(qp.u, 2),
    )
    return dataclasses.replace(
        batch,
        qp=qp,
        d_col=put(batch.d_col, 2),
        d_row=put(batch.d_row, 2),
        d_non=put(batch.d_non, 2),
        p=jax.device_put(batch.p, shard),
        nonant_idx=jax.device_put(batch.nonant_idx, repl),
        node_of_slot=put(batch.node_of_slot, 2),
        integer_slot=jax.device_put(batch.integer_slot, repl),
        var_prob=None if batch.var_prob is None
        else jax.device_put(batch.var_prob, shard),
    )
