###############################################################################
# Multi-host dry run worker: one PROCESS of a multi-process mesh.
#
#   python -m mpisppy_tpu.parallel._multihost_dryrun \
#       <coordinator> <num_processes> <process_id> <devices_per_process>
#
# Builds the farmer batch, shards it over the GLOBAL (cross-process)
# mesh, runs PH iter0 + one iterk, and prints "CONV <value>" — every
# process must print the same value (the reductions are global).  This
# is the process-count-agnostic analog of __graft_entry__'s single-host
# dryrun_multichip, exercised by tests/test_multihost.py under a
# 2-process x 4-device virtual CPU topology (gloo collectives), the way
# the reference validates its MPI layer with `mpiexec -np 2` smoke
# tests (ref:mpisppy/tests/straight_tests.py:36-44,
# mpi_one_sided_test.py).
###############################################################################
import sys


def main():
    coord, n_proc, pid, dev_per = sys.argv[1:5]
    from mpisppy_tpu.parallel import mesh as mesh_mod
    mesh_mod.init_multihost(coord, int(n_proc), int(pid),
                            cpu_devices_per_process=int(dev_per))

    import jax
    import jax.numpy as jnp

    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.models import farmer

    n_devices = jax.device_count()
    specs = [farmer.scenario_creator(nm, num_scens=3)
             for nm in farmer.scenario_names_creator(3)]
    batch = batch_mod.from_specs(specs)
    batch = batch_mod.pad_to_multiple(batch, n_devices)
    mesh = mesh_mod.make_mesh()
    batch = mesh_mod.shard_batch(batch, mesh)

    opts = ph_mod.PHOptions(default_rho=1.0, subproblem_windows=4,
                            iter0_windows=100)
    rho = jnp.full((batch.num_nonants,), opts.default_rho)
    state, tb, _ = ph_mod.ph_iter0(batch, rho, opts)
    state = ph_mod.ph_iterk(batch, state, opts)
    conv = float(state.conv)
    print(f"CONV {conv:.6e} TB {float(tb):.6e} "
          f"procs {jax.process_count()} devices {n_devices}", flush=True)


if __name__ == "__main__":
    main()
