###############################################################################
# Elastic mesh dry run worker: one PROCESS of a multi-process gloo mesh
# under the ISSUE 17 fault domain.
#
#   python -m mpisppy_tpu.parallel._elastic_dryrun kill \
#       <coordinator> <num_processes> <process_id> <devices_per_process> \
#       <workdir>
#   python -m mpisppy_tpu.parallel._elastic_dryrun partition \
#       <coordinator> <num_processes> <process_id> <devices_per_process> \
#       <workdir>
#   python -m mpisppy_tpu.parallel._elastic_dryrun resume   <workdir>
#   python -m mpisppy_tpu.parallel._elastic_dryrun baseline <workdir>
#
# kill: every process spins the SAME sharded fused wheel (SPMD) with a
# synchronized periodic checkpoint every 4 hub iterations.  The victim
# (last process) stops beaconing and dies at iter 5; the survivor's
# beacon sweep goes SUSPECT, its bounded harvest trips MeshDegraded,
# the emergency gather cannot complete without the dead peer (bounded
# by checkpoint_gather_timeout_s, falls back to the iter-4 snapshot)
# and the process exits 75 (EX_TEMPFAIL: restartable) printing
# HOSTLOST.  gloo meshes cannot shrink live, so the elastic loop for
# the multi-process fault domain is a RELAUNCH at the survivor
# topology: `resume` runs single-process on 6 virtual devices (set
# XLA_FLAGS in the environment), re-shards the S=13 program 16 -> 18
# via elastic.adapt_checkpoint_arrays, and spins to the certified gap.
# `baseline` is the fault-free A side at the full 8-device topology.
#
# partition: the victim's beacon delivery is suppressed for beats 1-2
# (a network partition, not a death).  dead_after=3 means the survivor
# only reaches SUSPECT; the first post-partition beat heals the host
# and the wheel completes with NO reshard — suspicion alone never
# re-shards (tests/test_multihost.py).
###############################################################################
import os
import sys

S = 13
KILL_ITER = 5
CKPT_EVERY = 4
REL_GAP = 5e-3
PARTITION_REL_GAP = 1e-3   # tighter target -> enough iters to heal


def _build(mesh, ckpt, rel_gap, extra=None):
    from mpisppy_tpu import scengen
    from mpisppy_tpu.algos import fused_wheel as fw
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.cylinders import PHHub
    from mpisppy_tpu.cylinders.spoke import (
        FusedLagrangianOuterBound, FusedXhatXbarInnerBound,
    )
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.ops import pdhg
    from mpisppy_tpu.parallel import mesh as mesh_mod
    from mpisppy_tpu.spin_the_wheel import WheelSpinner

    prog = farmer.scenario_program(S, seed=0)
    b = mesh_mod.shard_batch(scengen.virtual_batch(prog), mesh, pad=True)
    opts = ph_mod.PHOptions(default_rho=1.0, max_iterations=80,
                            conv_thresh=0.0, subproblem_windows=10,
                            pdhg=pdhg.PDHGOptions(tol=1e-7))
    wopts = fw.FusedWheelOptions(lag_windows=4, xhat_windows=2,
                                 slam_windows=0, shuffle_windows=0,
                                 split_dispatch=False,
                                 lag_pdhg=pdhg.PDHGOptions(tol=1e-7),
                                 xhat_pdhg=pdhg.PDHGOptions(
                                     tol=1e-7, omega0=0.1,
                                     restart_period=80))
    hub_opts = {"rel_gap": rel_gap, "checkpoint_path": ckpt,
                "checkpoint_every_s": 1e9}
    hub_opts.update(extra or {})
    hub = {"hub_class": PHHub, "hub_kwargs": {"options": hub_opts},
           "opt_class": fw.FusedPH,
           "opt_kwargs": {"options": opts, "batch": b,
                          "wheel_options": wopts}}
    spokes = [
        {"spoke_class": FusedLagrangianOuterBound,
         "opt_kwargs": {"options": {}}},
        {"spoke_class": FusedXhatXbarInnerBound,
         "opt_kwargs": {"options": {}}},
    ]
    return WheelSpinner(hub, spokes)


def _bracket(ws):
    inner, outer = float(ws.BestInnerBound), float(ws.BestOuterBound)
    gap = (inner - outer) / max(abs(inner), abs(outer), 1e-12)
    return inner, outer, gap


class _ArmedRuntime:
    """MeshRuntime whose harvest deadline only arms once the compiled
    programs are warm (iters 0-1 pay XLA compile inside the fetch, so
    a fixed deadline would trip a false straggler on a cold cache)."""

    def __init__(self, rt, arm_after=2, deadline_s=20.0):
        self._rt, self._arm, self._dl = rt, arm_after, deadline_s

    def harvest(self, fetch, hub_iter):
        self._rt.deadline_s = self._dl if hub_iter >= self._arm else None
        return self._rt.harvest(fetch, hub_iter)


class _Victim:
    """The doomed host's harvest seam: beacons while healthy, falls
    silent one iteration before dying so the survivor's sweep sees the
    staleness, then exits without warning (a real host loss)."""

    def __init__(self, membership, self_host):
        self._mm, self._host = membership, self_host

    def harvest(self, fetch, hub_iter):
        import numpy as np
        if hub_iter >= KILL_ITER:
            sys.stdout.flush()
            os._exit(0)
        if hub_iter < KILL_ITER - 1:
            self._mm.beat(self._host)
        return np.asarray(fetch())


def _run_kill(coord, n_proc, pid, dev_per, workdir):
    from mpisppy_tpu.parallel import mesh as mesh_mod
    mesh_mod.init_multihost(coord, n_proc, pid,
                            cpu_devices_per_process=dev_per)
    from mpisppy_tpu.parallel import elastic

    beacons = os.path.join(workdir, "beacons")
    os.makedirs(beacons, exist_ok=True)
    ckpt = os.path.join(workdir, f"ckpt_p{pid}.npz")
    ws = _build(mesh_mod.make_mesh(), ckpt, REL_GAP,
                extra={"checkpoint_every_iters": CKPT_EVERY,
                       "checkpoint_gather_timeout_s": 5.0})
    ws.build()
    mm = elastic.MeshMembership(n_proc, dead_after=2, self_host=pid,
                                beacon_dir=beacons)
    victim = pid == n_proc - 1
    if victim:
        ws.spcomm.options["mesh_runtime"] = _Victim(mm, pid)
    else:
        rt = elastic.MeshRuntime(mm)
        ws.spcomm.options["mesh_runtime"] = _ArmedRuntime(rt)
    try:
        ws.spin()
    except elastic.MeshDegraded as e:
        # confirm the death on the beacon ladder (the bounded harvest
        # tripped first; the sweep is what names the lost host)
        for _ in range(3):
            mm.poll()
        print(f"HOSTLOST reason={e.reason} "  # telemetry: allow-print
              f"iter={e.hub_iter} "
              f"dead={mm.dead_hosts()} "
              f"ckpt={int(os.path.exists(ckpt))}", flush=True)
        os._exit(75)
    print(f"UNEXPECTED_COMPLETE "  # telemetry: allow-print
          f"iter={ws.spcomm._iter}", flush=True)
    os._exit(1)


def _run_partition(coord, n_proc, pid, dev_per, workdir):
    from mpisppy_tpu.parallel import mesh as mesh_mod
    mesh_mod.init_multihost(coord, n_proc, pid,
                            cpu_devices_per_process=dev_per)
    from mpisppy_tpu.parallel import elastic
    from mpisppy_tpu.resilience import FaultPlan, MeshFault
    from mpisppy_tpu.telemetry import EventBus

    beacons = os.path.join(workdir, "beacons")
    os.makedirs(beacons, exist_ok=True)
    ckpt = os.path.join(workdir, f"ckpt_p{pid}.npz")
    ws = _build(mesh_mod.make_mesh(), ckpt, PARTITION_REL_GAP)
    ws.build()

    moves: list[str] = []   # membership transition history, in order

    class _Sink:
        def handle(self, event):
            if event.kind == "mesh-state":
                moves.append(f"{event.data['host']}:"
                             f"{event.data['state']}")

    bus = EventBus()
    bus.subscribe(_Sink())
    mm = elastic.MeshMembership(n_proc, dead_after=3, self_host=pid,
                                beacon_dir=beacons, bus=bus,
                                run=f"p{pid}")
    victim = pid == n_proc - 1
    plan = FaultPlan(meshes=(
        MeshFault("partition", host=pid, at_beats=(1, 2)),)) \
        if victim else None
    rt = elastic.MeshRuntime(mm, plan=plan)
    ws.spcomm.options["mesh_runtime"] = rt
    ws.spin()
    inner, outer, gap = _bracket(ws)
    print(f"PARTITION_OK "  # telemetry: allow-print
          f"inner={inner:.6e} outer={outer:.6e} "
          f"gap={gap:.3e} iter={ws.spcomm._iter} "
          f"moves={','.join(moves) or 'none'} "
          f"dead={mm.dead_hosts()} epoch={mm.epoch}", flush=True)


def _run_single(workdir, resume):
    import jax

    from mpisppy_tpu.parallel import elastic, mesh as mesh_mod

    n_dev = jax.device_count()
    tag = "RESUME" if resume else "BASE"
    ckpt = os.path.join(
        workdir, "ckpt_p0.npz" if resume else "ckpt_base.npz")
    ws = _build(mesh_mod.make_mesh(), ckpt, REL_GAP)
    ws.build()
    start = 0
    if resume:
        s_old = 16              # S=13 padded on the full 8-device mesh
        s_new = ws.spcomm.opt.batch.num_scenarios
        ws.spcomm.load_checkpoint(
            ckpt, transform=lambda arrays: elastic.adapt_checkpoint_arrays(
                arrays, S, s_old, s_new))
        start = ws.spcomm._iter
    ws.spin()
    inner, outer, gap = _bracket(ws)
    print(f"{tag} "  # telemetry: allow-print
          f"inner={inner:.6e} outer={outer:.6e} gap={gap:.3e} "
          f"start={start} iter={ws.spcomm._iter} devices={n_dev}",
          flush=True)


def main():
    mode = sys.argv[1]
    if mode in ("kill", "partition"):
        coord, n_proc, pid, dev_per, workdir = sys.argv[2:7]
        fn = _run_kill if mode == "kill" else _run_partition
        fn(coord, int(n_proc), int(pid), int(dev_per), workdir)
    elif mode == "resume":
        _run_single(sys.argv[2], resume=True)
    elif mode == "baseline":
        _run_single(sys.argv[2], resume=False)
    else:
        raise SystemExit(f"unknown mode {mode!r}")


if __name__ == "__main__":
    main()
