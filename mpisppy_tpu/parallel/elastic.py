###############################################################################
# Elastic mesh: the fault domain of the sharded wheel (ISSUE 17;
# docs/resilience.md).
#
# parallel/mesh.py gives the wheel its "MPI"; this module gives it the
# property the reference gets from hub-and-spoke tolerance of slow
# cylinders (ref:mpisppy/cylinders/hub.py stale-window reads) at the
# layer the reference never has: the MESH.  Three guarantees:
#
#   1. membership  — a heartbeat/epoch service over the hosts of the
#      mesh (UP -> SUSPECT -> sticky DEAD, the fleet health ladder of
#      fleet/health.py applied to mesh hosts).  A SUSPECT host whose
#      beats return rejoins UP at the next epoch WITHOUT a reshard (a
#      healed DCN partition); a DEAD host never comes back (fencing —
#      no split brain between a zombie host and its re-sharded range).
#   2. bounded harvest — the ONE place the hub loop blocks on the mesh
#      (the packed-scalar fetch in FusedPH._cache_scalars, which
#      completes the cross-host psum of the wheel collectives) gets a
#      wall-clock deadline: a straggler or wedged collective trips a
#      typed MeshDegraded instead of hanging the hub, and the watchdog
#      ladder (resilience/watchdog.py) escalates degrade -> shrink ->
#      abort.  A torn transfer (non-finite scalars off an intact
#      device value) is detected and synchronously re-fetched.
#   3. elastic re-shard — on host loss the wheel emergency-checkpoints
#      the hub plane (the PR-2 spool machinery, MeshDegraded IS a
#      PreemptionError), deterministically re-partitions the
#      VirtualBatch fold_in ranges across the survivors
#      (scengen/virtual.repartition — zero scenario bytes move), maps
#      the checkpointed scenario-major state leaves onto the new
#      padded axis (adapt_checkpoint_arrays), recompiles through the
#      shape-bucketed jit cache, and resumes — the certified
#      outer/inner bracket holds across the reshard because pad lanes
#      carry zero probability mass in every reduction.
#
# Everything here is host-side: nothing enters the jitted graph, and a
# wheel without a MeshRuntime in its options pays one dict lookup.
###############################################################################
from __future__ import annotations

import os
import threading
import time

import numpy as np

from mpisppy_tpu.resilience.faults import PreemptionError
from mpisppy_tpu.utils.atomic_io import atomic_write_text

UP = "UP"
SUSPECT = "SUSPECT"
DEAD = "DEAD"


class MeshDegraded(PreemptionError):
    """The mesh can no longer complete collectives at the current
    topology — a host was lost, a harvest missed its deadline, or a
    partition outlived the miss budget.  Subclasses PreemptionError ON
    PURPOSE: WheelSpinner.spin converts it into one synchronous
    emergency checkpoint before re-raising, which is exactly the state
    hand-off the elastic re-shard resumes from."""

    def __init__(self, reason: str, host: int | None = None,
                 hub_iter: int = -1, detail: str = ""):
        self.reason = reason      # 'host-lost' | 'straggler-deadline'
        self.host = host          # the lost host, when known
        self.hub_iter = hub_iter
        super().__init__(
            f"mesh degraded ({reason}"
            + (f", host {host}" if host is not None else "")
            + (f", hub iter {hub_iter}" if hub_iter >= 0 else "")
            + (f": {detail}" if detail else "") + ")")


class MeshMembership:
    """Host membership over the mesh: the fleet health ladder
    (fleet/health.py UP -> SUSPECT -> sticky DEAD) keyed by host index,
    plus an EPOCH counter that increments on every transition — the
    version number a reshard is keyed by, and the proof a healed
    partition rejoined without one (epoch moves, device count does
    not).

    Beats arrive either in-process (`beat(host)` / `observe`) or as
    file beacons under `beacon_dir` (the multi-process gloo harness:
    gloo gives the processes no side channel, so liveness rides a
    shared filesystem the same way the checkpoint spool does).  A host
    whose beat is stale turns SUSPECT; `dead_after` consecutive stale
    polls turns it DEAD — sticky, the fencing guarantee."""

    def __init__(self, num_hosts: int, dead_after: int = 3,
                 self_host: int = 0, beacon_dir: str | None = None,
                 bus=None, run: str = ""):
        self.num_hosts = int(num_hosts)
        self.dead_after = max(1, int(dead_after))
        self.self_host = int(self_host)
        self.beacon_dir = beacon_dir
        self.bus = bus
        self.run = run
        self.epoch = 0
        self._lock = threading.Lock()
        self._state = {h: UP for h in range(self.num_hosts)}
        self._missed = {h: 0 for h in range(self.num_hosts)}
        self._last_beat = {h: -1 for h in range(self.num_hosts)}
        self._gauges()

    # -- beats ------------------------------------------------------------
    def beat(self, host: int, counter: int | None = None,
             plan=None) -> bool:
        """Record (or beacon) one liveness beat from `host`.  With a
        beacon_dir the beat is WRITTEN for other processes to poll;
        a plan's partition seam may suppress it (returns False)."""
        with self._lock:
            n = self._last_beat[host] + 1 if counter is None else counter
            # the beat was PRODUCED either way — a partition drops its
            # delivery, not the host's clock (the next beat after the
            # window must carry a fresh counter, or healing is
            # indistinguishable from the stale pre-partition beat)
            self._last_beat[host] = n
        if plan is not None and plan.mesh_partitioned(host, n):
            return False
        if self.beacon_dir is not None:
            atomic_write_text(
                os.path.join(self.beacon_dir, f"host{host}.beat"), str(n))
        self.observe(host, fresh=True, counter=n)
        return True

    def poll(self) -> list[int]:
        """One membership sweep: read every host's beacon (when
        beacon_dir is set) and run the ladder on freshness.  Returns
        hosts that transitioned to DEAD this sweep."""
        died = []
        for h in range(self.num_hosts):
            if h == self.self_host:
                continue
            fresh, counter = True, None
            if self.beacon_dir is not None:
                counter = self._read_beacon(h)
                with self._lock:
                    fresh = counter is not None \
                        and counter != self._last_beat[h]
            if self.observe(h, fresh=fresh, counter=counter) == DEAD:
                died.append(h)
        return died

    def _read_beacon(self, host: int) -> int | None:
        try:
            with open(os.path.join(self.beacon_dir,
                                   f"host{host}.beat")) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    # -- the ladder -------------------------------------------------------
    def observe(self, host: int, fresh: bool,
                counter: int | None = None, reason: str = "") -> str | None:
        """Apply one freshness observation; returns the NEW state when
        a transition happened, else None.  DEAD is sticky."""
        with self._lock:
            old = self._state[host]
            if counter is not None:
                self._last_beat[host] = counter
            if fresh:
                self._missed[host] = 0
                new = UP
                reason = reason or (
                    "partition-healed" if old == SUSPECT else "beat")
            else:
                self._missed[host] += 1
                new = DEAD if self._missed[host] >= self.dead_after \
                    else SUSPECT
                reason = reason or ("missed-beats"
                                    if new == DEAD else "stale-beat")
            return self._move(host, new, reason)

    def force(self, host: int, state: str, reason: str) -> str | None:
        """Out-of-band transition (a fault plan's host_lost, a test)."""
        with self._lock:
            return self._move(host, state, reason)

    def _move(self, host: int, new: str, reason: str) -> str | None:
        # guarded-by: _lock (both callers hold it)
        old = self._state[host]
        if old == new or old == DEAD:   # sticky DEAD: fencing
            return None
        self._state[host] = new
        self.epoch += 1
        epoch = self.epoch
        self._gauges()
        if self.bus is not None:
            from mpisppy_tpu import telemetry as tel
            self.bus.emit(tel.MESH_STATE, run=self.run, cyl="mesh",
                          host=host, state=new, prev=old, epoch=epoch,
                          reason=reason)
        return new

    def _gauges(self) -> None:
        from mpisppy_tpu.telemetry import metrics as _metrics
        up = sum(1 for s in self._state.values() if s != DEAD)
        _metrics.REGISTRY.set_gauge("mesh_hosts_up", float(up))
        _metrics.REGISTRY.set_gauge("mesh_epoch", float(self.epoch))

    # -- views ------------------------------------------------------------
    def state(self, host: int) -> str:
        with self._lock:
            return self._state[host]

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._state)

    def dead_hosts(self) -> list[int]:
        with self._lock:
            return sorted(h for h, s in self._state.items() if s == DEAD)

    def live_hosts(self) -> list[int]:
        """UP + SUSPECT: a suspect host keeps its shard until the
        ladder declares it DEAD — suspicion alone never reshards."""
        with self._lock:
            return sorted(h for h, s in self._state.items() if s != DEAD)


def device_groups(devices, num_hosts: int) -> list[list]:
    """Partition the device list into per-host groups.  Real multihost
    devices carry process_index; the virtual single-process mesh (tests,
    the CPU chaos storm) is split into `num_hosts` contiguous groups —
    the same process-major layout process_local_slice uses."""
    by_proc: dict[int, list] = {}
    procs = {getattr(d, "process_index", 0) for d in devices}
    if len(procs) >= num_hosts > 1:
        for d in devices:
            by_proc.setdefault(int(d.process_index), []).append(d)
        return [by_proc[p] for p in sorted(by_proc)]
    per = max(1, len(devices) // num_hosts)
    return [list(devices[i * per:(i + 1) * per])
            for i in range(num_hosts)]


def survivor_devices(devices, num_hosts: int, dead_hosts) -> list:
    """The flat device list after dropping every dead host's group."""
    dead = set(dead_hosts)
    out = []
    for h, group in enumerate(device_groups(devices, num_hosts)):
        if h not in dead:
            out.extend(group)
    return out


def adapt_checkpoint_arrays(arrays: dict, num_real: int, s_old: int,
                            s_new: int) -> dict:
    """Map a checkpoint's scenario-major state leaves from the old
    padded scenario axis (s_old) onto the new one (s_new) — the
    `transform` hook of hub.load_checkpoint on the re-shard path.

    Leaves whose leading axis is s_old are sliced to the real prefix
    and re-padded by cloning the last real row — exactly the
    pad_to_multiple / VirtualBatch.realize() pad contract, so a pad
    lane resumes iterating on the last real scenario's data and its
    zero probability keeps it out of every reduction.  Everything else
    (bounds, spoke bests, xbar nodes, scalars) passes through
    untouched."""
    if s_old == s_new:
        return arrays
    out = dict(arrays)
    for k, v in arrays.items():
        if not k.startswith("leaf") or v.ndim < 1 or v.shape[0] != s_old:
            continue
        real = v[:min(num_real, s_old)]
        if s_new > real.shape[0]:
            pad = np.repeat(real[-1:], s_new - real.shape[0], axis=0)
            out[k] = np.concatenate([real, pad], axis=0)
        else:
            out[k] = real[:s_new]
    return out


class MeshRuntime:
    """The hub-side handle of the mesh fault domain: FusedPH routes its
    per-iteration packed-scalar fetch (the collective-completing
    device->host transfer) through `harvest`, which layers on the
    deadline, the chaos seams, and the membership sweep.  Installed as
    opt options['mesh_runtime']; absent, the wheel runs the
    zero-overhead default path."""

    def __init__(self, membership: MeshMembership | None = None,
                 plan=None, deadline_s: float | None = None,
                 bus=None, run: str = ""):
        self.membership = membership
        self.plan = plan
        self.deadline_s = deadline_s
        self.bus = bus
        self.run = run

    # -- the bounded, chaos-seamed harvest --------------------------------
    def harvest(self, fetch, hub_iter: int) -> np.ndarray:
        """Run `fetch` (the blocking np.asarray of the packed scalar
        vector) under the mesh fault domain.  Every caller observes a
        result, a typed MeshDegraded, or the watchdog's abort — never
        a hang (docs/resilience.md failure-semantics table)."""
        if self.membership is not None \
                and self.membership.beacon_dir is not None:
            # beacon mode (multi-process gloo): liveness rides the hub
            # loop cadence — one self-beat per harvest, suppressed by
            # the plan's partition seam when this host is partitioned
            self.membership.beat(self.membership.self_host,
                                 plan=self.plan)
        self._check_hosts(hub_iter)
        delay = self.plan.mesh_harvest_delay(hub_iter) \
            if self.plan is not None else 0.0
        t0 = time.perf_counter()
        vals = self._bounded(fetch, delay, hub_iter)
        if self.plan is not None and self.plan.mesh_torn_harvest(hub_iter):
            vals = np.full_like(np.asarray(vals), np.nan)
        if not np.all(np.isfinite(vals)):
            # a torn transfer leaves the DEVICE value intact: one
            # synchronous re-fetch separates a tear from a genuinely
            # non-finite state (which passes through to the hub's own
            # bound guards)
            refetched = np.asarray(fetch())
            if np.all(np.isfinite(refetched)):
                self._straggle_event("torn", hub_iter,
                                     time.perf_counter() - t0)
                from mpisppy_tpu.telemetry import metrics as _metrics
                _metrics.REGISTRY.inc("mesh_torn_harvests_total")
            vals = refetched
        return vals

    def _bounded(self, fetch, delay: float, hub_iter: int):
        def run():
            if delay > 0.0:
                time.sleep(delay)   # the injected slow collective
            return np.asarray(fetch())

        if self.deadline_s is None:
            return run()
        box: list = []
        t = threading.Thread(
            target=lambda: box.append(run()), daemon=True,
            name="mpisppy-tpu-mesh-harvest")
        t0 = time.perf_counter()
        t.start()
        t.join(self.deadline_s)
        if t.is_alive():
            waited = time.perf_counter() - t0
            self._straggle_event("deadline", hub_iter, waited)
            from mpisppy_tpu.telemetry import metrics as _metrics
            _metrics.REGISTRY.inc("mesh_stragglers_total")
            # the worker is abandoned (daemon): the run is unwinding to
            # an emergency checkpoint and a rebuilt wheel anyway
            raise MeshDegraded(
                "straggler-deadline", hub_iter=hub_iter,
                detail=f"harvest exceeded {self.deadline_s}s "
                       f"(waited {waited:.2f}s)")
        return box[0]

    def _check_hosts(self, hub_iter: int) -> None:
        """Membership sweep + the host_lost chaos seam: any host newly
        DEAD orphans its shard and degrades the mesh NOW."""
        lost: list[int] = []
        if self.plan is not None:
            h = self.plan.mesh_lost_host(hub_iter)
            if h is not None:
                lost.append(h)
        if self.membership is not None:
            if self.membership.beacon_dir is not None:
                lost.extend(self.membership.poll())
            for h in lost:
                self.membership.force(h, DEAD, "lost")
        if not lost:
            return
        from mpisppy_tpu.telemetry import metrics as _metrics
        for h in lost:
            _metrics.REGISTRY.inc("mesh_hosts_lost_total")
            if self.bus is not None:
                from mpisppy_tpu import telemetry as tel
                survivors = self.membership.live_hosts() \
                    if self.membership is not None else []
                self.bus.emit(tel.MESH_HOST_LOST, run=self.run,
                              cyl="mesh", host=h, hub_iter=hub_iter,
                              epoch=getattr(self.membership, "epoch", 0),
                              survivors=survivors)
        raise MeshDegraded("host-lost", host=lost[0], hub_iter=hub_iter)

    def _straggle_event(self, kind: str, hub_iter: int,
                        waited: float) -> None:
        if self.bus is None:
            return
        from mpisppy_tpu import telemetry as tel
        # payload field is `mode` (not `kind` — that's the event kind)
        self.bus.emit(tel.MESH_STRAGGLER, run=self.run, cyl="mesh",
                      hub_iter=hub_iter, mode=kind,
                      waited_s=round(waited, 4),
                      budget_s=self.deadline_s)


def run_elastic(build_fn, *, num_hosts: int, checkpoint_path: str,
                plan=None, bus=None, run_id: str = "",
                harvest_deadline_s: float | None = None,
                membership: MeshMembership | None = None,
                devices=None, max_reshards: int | None = None):
    """Spin a wheel elastically: build at the current topology, run,
    and on MeshDegraded re-shard across the survivors and resume from
    the emergency checkpoint — the keyed-re-sharding loop of ISSUE 17.

    build_fn(mesh) -> WheelSpinner for that mesh.  The caller shards
    its batch with `mesh_mod.shard_batch(batch, mesh, pad=True)` (pad
    lanes carry zero probability, so the certified bracket is
    layout-invariant) and must set options['checkpoint_path'] to
    `checkpoint_path` so the MeshDegraded -> PreemptionError unwind
    lands the emergency snapshot this loop resumes from.

    Returns (spinner, info): info['reshards'] records every
    (hub_iter, old_devices, new_devices, epoch) transition,
    info['resumed'] whether any re-shard happened.  A resumed run that
    still cannot finish counts into mesh_reshards_lost_total before
    the error propagates."""
    import jax

    from mpisppy_tpu.parallel import mesh as mesh_mod
    from mpisppy_tpu.telemetry import metrics as _metrics

    all_devices = list(devices) if devices is not None else jax.devices()
    if membership is None:
        membership = MeshMembership(num_hosts, bus=bus, run=run_id)
    if max_reshards is None:
        max_reshards = num_hosts - 1
    # causal trace (ISSUE 20): the elastic run is ONE trace; every
    # build-at-a-topology attempt is a child segment span of the same
    # root, so a reshard renders as sibling segments with the
    # MESH_HOST_LOST/MESH_RESHARD rows between them — the reshard gap
    # on the critical path
    root = None
    if bus is not None and hasattr(bus, "set_trace"):
        from mpisppy_tpu import telemetry as tel
        root = bus.trace
        if root is None:
            root = tel.TraceContext.mint()
            bus.set_trace(root)
            bus.emit(tel.SPAN_START, run=run_id, cyl="mesh",
                     name="mesh-run", num_hosts=num_hosts)
    reshards: list[dict] = []
    prev_s = prev_nreal = None
    while True:
        devs = survivor_devices(all_devices, num_hosts,
                                membership.dead_hosts())
        if not devs:
            raise MeshDegraded("host-lost", detail="no survivors")
        if root is not None:
            from mpisppy_tpu import telemetry as tel
            seg = root.child()
            bus.set_trace(seg)
            bus.emit(tel.SPAN_START, run=run_id, cyl="mesh",
                     name="mesh-segment", devices=len(devs),
                     epoch=membership.epoch, resumed=bool(reshards))
        mesh = mesh_mod.make_mesh(devices=devs)
        ws = build_fn(mesh)
        ws.build()
        rt = MeshRuntime(membership, plan=plan,
                         deadline_s=harvest_deadline_s, bus=bus,
                         run=run_id)
        ws.spcomm.options["mesh_runtime"] = rt
        batch = ws.spcomm.opt.batch
        s_new = batch.num_scenarios
        n_real = getattr(batch, "num_real", s_new)
        if reshards or (prev_s is not None and prev_s != s_new):
            transform = (lambda arrays: adapt_checkpoint_arrays(
                arrays, prev_nreal, prev_s, s_new))
            ws.spcomm.load_checkpoint(checkpoint_path,
                                      transform=transform)
        prev_s, prev_nreal = s_new, n_real
        try:
            ws.spin()
            return ws, {"reshards": reshards, "resumed": bool(reshards),
                        "final_devices": len(devs),
                        "epoch": membership.epoch}
        except MeshDegraded as e:
            # spin() already wrote the emergency checkpoint (the
            # PreemptionError contract); account the transition and go
            # around — same topology for a straggler trip, fewer
            # devices after a host loss
            new_devs = survivor_devices(all_devices, num_hosts,
                                        membership.dead_hosts())
            if len(reshards) >= max_reshards:
                _metrics.REGISTRY.inc("mesh_reshards_lost_total")
                raise
            reshards.append({
                "hub_iter": e.hub_iter, "reason": e.reason,
                "old_devices": len(devs), "new_devices": len(new_devs),
                "epoch": membership.epoch})
            _metrics.REGISTRY.inc("mesh_reshards_total")
            if bus is not None:
                from mpisppy_tpu import telemetry as tel
                # dedicated reshard child span (like a fleet
                # migration): its start to the next segment's start is
                # the reshard gap on the critical path
                rs = root.child() if root is not None else None
                if rs is not None:
                    bus.emit(tel.SPAN_START, run=run_id, cyl="mesh",
                             trace=rs, name="reshard",
                             epoch=membership.epoch)
                bus.emit(tel.MESH_RESHARD, run=run_id, cyl="mesh",
                         trace=rs,
                         old_devices=len(devs),
                         new_devices=len(new_devs),
                         epoch=membership.epoch, hub_iter=e.hub_iter,
                         scenarios=n_real,
                         pad=(-n_real) % max(1, len(new_devs)))
        except Exception:
            if reshards:
                _metrics.REGISTRY.inc("mesh_reshards_lost_total")
            raise
