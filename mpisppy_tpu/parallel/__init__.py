# parallel subpackage of mpisppy_tpu
