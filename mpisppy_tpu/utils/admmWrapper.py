###############################################################################
# admmWrapper: consensus ADMM as a PH problem
# (ref:mpisppy/utils/admmWrapper.py:37-167).
#
# A generic consensus problem  min sum_r f_r(x_r, y_r)
#                              s.t. x_r[v] equal across the regions r
#                                   that share consensus variable v
# becomes "stochastic": each admm subproblem (region) is a SCENARIO,
# the consensus variables are the nonants, and nonanticipativity is
# enforced with VARIABLE probabilities 1/(#regions sharing v)
# (ref:admmWrapper.py:111-120) — a var absent from a region is added as
# a dummy fixed-at-0 column with weight 0 (ref:admmWrapper.py:129-141).
# Objectives are multiplied by the region count so the uniform-p PH
# expectation reproduces the plain sum (ref:admmWrapper.py:157-166).
#
# TPU shape discipline: regions may have heterogeneous column/row
# counts; the wrapper re-lays every region spec out as
#   [consensus block (K, shared order)] ++ [padded local columns]
# and pads rows, so the whole consensus problem is ONE ScenarioBatch.
#
# The user's scenario_creator returns a ScenarioSpec plus `var_names`
# (the label of every column) — the analog of Pyomo component names the
# reference resolves with find_component.
###############################################################################
from __future__ import annotations


import numpy as np


from mpisppy_tpu.core.batch import ScenarioSpec


def _consensus_vars_number_creator(consensus_vars: dict) -> dict:
    """label -> number of subproblems sharing it
    (ref:admmWrapper.py:24-34)."""
    count: dict = {}
    for sub, labels in consensus_vars.items():
        for v in labels:
            count[v] = count.get(v, 0) + 1
    return count


class AdmmWrapper:
    """ref:mpisppy/utils/admmWrapper.py:37.

    Args:
        all_scenario_names: admm subproblem names.
        scenario_creator(name, **kwargs) -> (ScenarioSpec, var_names).
        consensus_vars: {subproblem_name: [labels]}.
    """

    def __init__(self, options, all_scenario_names, scenario_creator,
                 consensus_vars, n_cylinders: int = 1, mpicomm=None,
                 scenario_creator_kwargs=None, verbose=False):
        assert len(options) == 0, "no options supported by AdmmWrapper"
        self.all_scenario_names = list(all_scenario_names)
        self.consensus_vars = consensus_vars
        self.consensus_vars_number = _consensus_vars_number_creator(
            consensus_vars)
        self.number_of_scenario = len(self.all_scenario_names)
        kw = scenario_creator_kwargs or {}

        labels = sorted(self.consensus_vars_number)
        self._labels = labels
        K = len(labels)
        raw = {}
        for nm in self.all_scenario_names:
            spec, var_names = scenario_creator(nm, **kw)
            missing = [v for v in consensus_vars[nm]
                       if v not in var_names]
            if missing:
                raise RuntimeError(
                    f"for {nm}, consensus vars not in the model: "
                    f"{missing} (ref:admmWrapper.py:143-147)")
            raw[nm] = (spec, list(var_names))

        n_loc = {nm: len(vn) - len(consensus_vars[nm])
                 for nm, (sp, vn) in raw.items()}
        n_local_max = max(n_loc.values())
        m_max = max(sp.A.shape[0] for sp, _ in raw.values())
        n_new = K + n_local_max

        from mpisppy_tpu.utils.sputils import remap_spec_arrays
        label_ix = {v: i for i, v in enumerate(labels)}
        self.local_scenarios = {}
        self.varprob_dict = {}
        for nm, (spec, var_names) in raw.items():
            mine = set(consensus_vars[nm])
            colmap = np.empty(len(var_names), np.int64)
            loc = 0
            for j, v in enumerate(var_names):
                if v in mine:
                    colmap[j] = label_ix[v]
                else:
                    colmap[j] = K + loc
                    loc += 1

            # the objective carries the region-count factor so uniform-p
            # PH expectation = the plain admm sum; absent consensus +
            # unused local pad columns come back fixed at 0
            parts = remap_spec_arrays(spec, colmap, n_new, m_max,
                                      scale=self.number_of_scenario)

            var_prob = np.zeros(K)
            for v in mine:
                var_prob[label_ix[v]] = \
                    1.0 / self.consensus_vars_number[v]
            self.varprob_dict[nm] = var_prob

            self.local_scenarios[nm] = ScenarioSpec(
                name=nm, nonant_idx=np.arange(K, dtype=np.int32),
                var_prob=var_prob, **parts)

    def var_prob_list(self, sname: str):
        """(slot, weight) pairs (ref:admmWrapper.py:97-103)."""
        return list(enumerate(self.varprob_dict[sname]))

    def admmWrapper_scenario_creator(self, sname: str) -> ScenarioSpec:
        """The scenario_creator handed to PH/cylinders
        (ref:admmWrapper.py:157-166)."""
        return self.local_scenarios[sname]

    def make_batch(self):
        from mpisppy_tpu.core import batch as batch_mod
        specs = [self.local_scenarios[nm]
                 for nm in self.all_scenario_names]
        return batch_mod.from_specs(specs)
