###############################################################################
# Proper bundles (ref:mpisppy/utils/proper_bundler.py:29-120).
#
# A "proper bundle" replaces k scenarios by ONE subproblem — their
# extensive form with the within-bundle nonanticipativity built in.
# The reference forms a Pyomo EF per bundle (sputils.create_EF) whose
# reference variables become the bundle's nonants; here the bundle spec
# shares ONE set of nonant columns across members and block-concatenates
# the second-stage columns/rows:
#
#   columns: [x_non (N, shared)] ++ [member i's other columns]_i
#   rows:    member i's rows with its nonant columns remapped to the
#            shared block (sparse; bundles of one model family share a
#            sparsity pattern, so the batch compiler lowers a bundle
#            batch to one ELL block)
#   c, q:    weighted by the member's conditional probability p_i/p_bun
#            (so p_bun * f_bun = sum_i p_i f_i, the EF identity)
#   prob:    p_bun = sum_i p_i
#
# PH over bundles is then IDENTICAL machinery with S/k "scenarios" —
# the reference's microbatching analog (SURVEY §2.3 parallelism #4).
# Two-stage only, like the reference (ref:proper_bundler.py:22).
###############################################################################
from __future__ import annotations

import numpy as np
import scipy.sparse as sps

from mpisppy_tpu.core.batch import ScenarioSpec
from mpisppy_tpu.utils import pickle_bundle
from mpisppy_tpu.utils.sputils import extract_num


def form_bundle_spec(members: list[ScenarioSpec],
                     name: str) -> ScenarioSpec:
    """EF of the member scenarios with shared nonant columns."""
    first = members[0]
    nonant_idx = np.asarray(first.nonant_idx, np.int64)
    N = len(nonant_idx)
    n = first.c.shape[0]
    oth = np.setdiff1d(np.arange(n), nonant_idx)
    n_oth = len(oth)
    k = len(members)

    nones = [m.probability is None for m in members]
    if any(nones) and not all(nones):
        raise ValueError(
            "form_bundle_spec: members mix explicit and None (uniform) "
            "probabilities; make them consistent before bundling")
    p_i = np.ones(k) if all(nones) else \
        np.array([m.probability for m in members])
    p_bun = p_i.sum()
    w = p_i / p_bun

    n_new = N + k * n_oth
    # column map per member: full column j -> bundle column
    colmap = np.empty((k, n), np.int64)
    for i in range(k):
        colmap[i, nonant_idx] = np.arange(N)
        colmap[i, oth] = N + i * n_oth + np.arange(n_oth)

    c = np.zeros(n_new)
    q = np.zeros(n_new)
    l = np.empty(n_new)  # noqa: E741
    u = np.empty(n_new)
    integer = np.zeros(n_new, bool)
    l[:N] = -np.inf
    u[:N] = np.inf
    rows_l, rows_u, blocks = [], [], []
    for i, m in enumerate(members):
        cm = colmap[i]
        c[cm] += w[i] * np.asarray(m.c, np.float64)
        if m.q is not None:
            q[cm] += w[i] * np.asarray(m.q, np.float64)
        # nonant box: intersection across members; others: per member
        l[:N] = np.maximum(l[:N], np.asarray(m.l)[nonant_idx]) \
            if i else np.asarray(m.l)[nonant_idx]
        u[:N] = np.minimum(u[:N], np.asarray(m.u)[nonant_idx]) \
            if i else np.asarray(m.u)[nonant_idx]
        l[N + i * n_oth:N + (i + 1) * n_oth] = np.asarray(m.l)[oth]
        u[N + i * n_oth:N + (i + 1) * n_oth] = np.asarray(m.u)[oth]
        if m.integer is not None:
            integer[cm] |= np.asarray(m.integer, bool)
        A = m.A if sps.issparse(m.A) else sps.csr_matrix(np.asarray(m.A))
        A = A.tocoo()
        blocks.append(sps.coo_matrix(
            (A.data, (A.row, cm[A.col])), shape=(A.shape[0], n_new)))
        rows_l.append(np.asarray(m.bl, np.float64))
        rows_u.append(np.asarray(m.bu, np.float64))

    A_bun = sps.vstack(blocks).tocsr()
    return ScenarioSpec(
        name=name, c=c, A=A_bun,
        bl=np.concatenate(rows_l), bu=np.concatenate(rows_u),
        l=l, u=u, nonant_idx=np.arange(N, dtype=np.int32),
        q=q if q.any() else None,
        probability=None if all(m.probability is None for m in members)
        else float(p_bun),
        integer=integer if integer.any() else None,
    )


class ProperBundler:
    """Module wrapper with the reference's API shape
    (ref:proper_bundler.py:29-120): bundle names Bundle_<lo>_<hi>,
    scenario_creator dispatching on the name, optional pickle dirs."""

    def __init__(self, module):
        self.module = module

    def inparser_adder(self, cfg):
        self.module.inparser_adder(cfg)

    def scenario_names_creator(self, num_scens, start=None):
        return self.module.scenario_names_creator(num_scens, start=start)

    def bundle_names_creator(self, num_buns, start=None, cfg=None):
        assert cfg is not None, "ProperBundler needs cfg for bundle names"
        if cfg.get("num_scens") is None \
                or cfg.get("scenarios_per_bundle") is None:
            raise ValueError("ProperBundler needs num_scens and "
                             "scenarios_per_bundle in the config")
        bsize = int(cfg["scenarios_per_bundle"])
        num_scens = int(cfg["num_scens"])
        assert num_scens % bsize == 0, \
            "num_scens must be a multiple of scenarios_per_bundle"
        start = 0 if start is None else start
        inum = extract_num(self.module.scenario_names_creator(1)[0])
        return [f"Bundle_{bn * bsize + inum}_{(bn + 1) * bsize - 1 + inum}"
                for bn in range(start, start + num_buns)]

    def kw_creator(self, cfg):
        kw = self.module.kw_creator(cfg)
        self.original_kwargs = dict(kw)
        kw["cfg"] = cfg
        return kw

    def scenario_creator(self, sname, cfg=None, **kwargs):
        if "Bundle" not in sname:
            return self.module.scenario_creator(
                sname, **{**getattr(self, "original_kwargs", {}),
                          **kwargs})
        if cfg is not None and cfg.get("unpickle_bundles_dir"):
            return pickle_bundle.read_spec(cfg["unpickle_bundles_dir"],
                                           sname)
        lo = int(sname.split("_")[1])
        hi = int(sname.split("_")[2])
        snames = self.module.scenario_names_creator(hi - lo + 1, lo)
        kw = getattr(self, "original_kwargs", kwargs)
        members = [self.module.scenario_creator(nm, **kw)
                   for nm in snames]
        bundle = form_bundle_spec(members, sname)
        if cfg is not None and cfg.get("pickle_bundles_dir"):
            pickle_bundle.write_spec(bundle, cfg["pickle_bundles_dir"])
        return bundle

    def scenario_denouement(self, rank, sname, spec, x=None):
        if hasattr(self.module, "scenario_denouement"):
            self.module.scenario_denouement(rank, sname, spec, x)
