###############################################################################
# Atomic text/bytes file writes — the one helper every host-side
# artifact writer shares (phtracker CSVs, wtracker CSVs, the telemetry
# metrics snapshot).  Write-to-tmp + os.replace: a reader (or a scraper
# tailing the metrics file) can never observe a torn half-written file,
# and a crash mid-write leaves the previous complete version in place.
# The checkpoint writer in cylinders/hub.py keeps its own rotated
# variant (it additionally needs multi-slot rotation under a lock).
###############################################################################
from __future__ import annotations

import os


def atomic_write_bytes(path: str, payload: bytes) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, path)


def atomic_write_text(path: str, text: str) -> None:
    atomic_write_bytes(path, text.encode())


def fsync_dir(path: str) -> None:
    """fsync the DIRECTORY holding `path` (or the directory itself).

    os.replace makes a rename atomic but not durable: until the
    directory inode is flushed, a crash can roll the directory entry
    back to the pre-rename state — for the checkpoint spool that means
    losing the newest-snapshot pointer even though its bytes fully
    landed.  Callers invoke this after the rename(s) that must survive
    a host loss (cylinders/hub._write_checkpoint rotation).  Platforms
    whose directory handles refuse fsync (some network filesystems,
    Windows) degrade to the old non-durable behavior rather than
    failing the write."""
    d = path if os.path.isdir(path) else (os.path.dirname(path) or ".")
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def append_text(path: str, text: str) -> None:
    """Append one block in a single os.write on an O_APPEND descriptor:
    concurrent appenders never interleave mid-block, and a crash can
    tear at most the final block's tail — the file stays parseable up
    to it.  The incremental companion to atomic_write_text for growing
    artifacts (CSV row batches) where full rewrites would cost
    O(rows^2) I/O over a run."""
    fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
    try:
        os.write(fd, text.encode())
    finally:
        os.close(fd)
