###############################################################################
# ProxApproxManager: outer-approximation cuts for the quadratic prox
# term (ref:mpisppy/utils/prox_approx.py:24-216).
#
# The reference needs this because its subproblem solvers may be
# LP-only: the PH prox (rho/2)(x - xbar)^2 is replaced by epigraph
# variables with tangent cuts  t >= x_pt^2 + 2 x_pt (x - x_pt), placed
# on demand with a Newton step toward the violating point
# (ref:prox_approx.py:24-60).  The TPU kernel solves diagonal QPs
# NATIVELY, so the framework never needs these cuts on its main path —
# this module exists for API parity and for LP-only backends
# (ops/simplex_qp-style), and its math is tested directly.
###############################################################################
from __future__ import annotations

import numpy as np


def tangent_cut(x_pt: np.ndarray):
    """Underestimator of x^2 at x_pt:  t >= 2 x_pt x - x_pt^2.
    Returns (slope, intercept) with t >= slope*x + intercept."""
    x_pt = np.asarray(x_pt, np.float64)
    return 2.0 * x_pt, -(x_pt * x_pt)


class ProxApproxManager:
    """Per-slot cut collection with the reference's on-demand Newton
    placement (ref:prox_approx.py:24-60): when the epigraph value t
    underestimates x^2 by more than tol, add cuts at the midpointish
    Newton iterates between the violating x and the current support."""

    def __init__(self, num_slots: int, tol: float = 1e-2,
                 max_cuts_per_slot: int = 32):
        self.tol = tol
        self.max_cuts = max_cuts_per_slot
        self.cuts: list[list[tuple[float, float]]] = [
            [] for _ in range(num_slots)]
        # seed with the tangent at 0 (t >= 0 for x^2)
        for cl in self.cuts:
            cl.append((0.0, 0.0))

    def evaluate(self, i: int, x: float) -> float:
        """Current epigraph value max over cuts at x."""
        return max(s * x + b for (s, b) in self.cuts[i])

    def add_cut(self, i: int, x: float) -> int:
        """ref:prox_approx.py add_cut: 0 if no violation, else the
        number of cuts added (Newton placement halves the gap)."""
        t = self.evaluate(i, x)
        viol = x * x - t
        if viol <= self.tol or len(self.cuts[i]) >= self.max_cuts:
            return 0
        # Newton step for g(y) = y^2 + t - 2*y*x (the gap function)
        # lands midway; the reference adds the tangent there AND at the
        # reflected point for symmetry
        y = 0.5 * (x + t / x) if abs(x) > 1e-12 else 0.0
        added = 0
        for pt in (y, 2.0 * x - y):
            s, b = tangent_cut(np.asarray(pt))
            self.cuts[i].append((float(s), float(b)))
            added += 1
        return added

    def check_and_add(self, x_vec: np.ndarray) -> int:
        """Vector interface: one pass over all slots, returns total cuts
        added (0 means the approximation is tol-tight at x_vec)."""
        return sum(self.add_cut(i, float(x))
                   for i, x in enumerate(np.asarray(x_vec)))
