###############################################################################
# WTracker: W-oscillation diagnostics over a moving window
# (ref:mpisppy/utils/wtracker.py:15-253).  Collects the (S, N) W tensor
# once per PH iteration (one host transfer) and reports per-(scenario,
# slot) mean/stdev over the last `window` iterations — the reference's
# wlen/reportlen semantics.
###############################################################################
from __future__ import annotations

import collections

import numpy as np


class WTracker:
    """ref:mpisppy/utils/wtracker.py:15."""

    def __init__(self, ph, window: int = 10):
        self.ph = ph
        self.window = int(window)
        self._hist: collections.deque = collections.deque(maxlen=window)

    def grab_local_Ws(self):
        """Record this iteration's W (ref:wtracker.py grab_local_Ws)."""
        self._hist.append(np.asarray(self.ph.state.W))

    def compute_moving_stats(self):
        """(mean, stdev) arrays of shape (S, N) over the window."""
        if not self._hist:
            raise RuntimeError("no W history recorded")
        stack = np.stack(self._hist)
        return stack.mean(axis=0), stack.std(axis=0)

    def report_by_moving_stats(self, stdevthresh: float | None = None):
        """Rows (scenario, slot, mean, stdev) whose stdev exceeds the
        threshold (ref:wtracker.py report_by_moving_stats)."""
        mean, std = self.compute_moving_stats()
        thresh = 0.0 if stdevthresh is None else stdevthresh
        rows = []
        for s, i in zip(*np.nonzero(std > thresh)):
            rows.append((int(s), int(i), float(mean[s, i]),
                         float(std[s, i])))
        return rows

    def write_csv(self, fname: str):
        from mpisppy_tpu.utils.atomic_io import atomic_write_text
        mean, std = self.compute_moving_stats()
        lines = ["scenario,slot,mean,stdev"]
        S, N = mean.shape
        for s in range(S):
            for i in range(N):
                lines.append(f"{s},{i},{mean[s, i]},{std[s, i]}")
        atomic_write_text(fname, "\n".join(lines) + "\n")


class WTrackerExtension:
    """Extension wrapper (ref:mpisppy/extensions/wtracker_extension.py:15).
    Build via functools.partial(WTrackerExtension, window=…) or rely on
    defaults."""

    def __init__(self, ph, window: int = 10, report_thresh: float = 0.0):
        self.opt = ph
        self.tracker = WTracker(ph, window)
        self.report_thresh = report_thresh

    def pre_iter0(self):
        pass

    def post_iter0(self):
        pass

    def miditer(self):
        pass

    def enditer(self):
        self.tracker.grab_local_Ws()

    def post_everything(self):
        from mpisppy_tpu.telemetry import console
        rows = self.tracker.report_by_moving_stats(self.report_thresh)
        # DEBUG level: visible at --telemetry-verbosity 2 (the old code
        # built the report and then never showed it at all)
        console.log(f"WTracker: {len(rows)} (scenario, slot) pairs above "
                    f"stdev {self.report_thresh}", level=console.DEBUG)
