###############################################################################
# Per-nonant sensitivities (ref:mpisppy/utils/nonant_sensitivities.py,
# backed by a vendored interior-point KKT interface,
# ref:mpisppy/utils/kkt/interface.py:20+).
#
# The reference solves each scenario's relaxation and extracts
# d(objective)/d(nonant) sensitivities from the KKT system.  The
# batched PDHG solve already produces exactly that object: the
# ORIGINAL-space reduced cost  rc = (c + q x + A'y) / d_col  at an
# (approximately) optimal primal-dual pair IS the objective sensitivity
# to moving the nonant off its current value (zero for strictly
# interior basic variables).  One batched solve replaces the per-rank
# interior-point factorizations.
###############################################################################
from __future__ import annotations

import jax
import numpy as np

from mpisppy_tpu.core.batch import ScenarioBatch
from mpisppy_tpu.ops import pdhg

Array = jax.Array


def nonant_sensitivities(batch: ScenarioBatch,
                         solver: pdhg.PDHGState) -> np.ndarray:
    """(S, N) objective sensitivities of the nonants at a solve —
    exactly the W=0 reduced costs (one shared implementation of the
    scaling/sign convention: algos.lagrangian.nonant_reduced_costs)."""
    import jax.numpy as jnp
    from mpisppy_tpu.algos.lagrangian import nonant_reduced_costs
    W0 = jnp.zeros((batch.num_scenarios, batch.num_nonants),
                   batch.qp.c.dtype)
    return np.asarray(nonant_reduced_costs(batch, W0, solver),
                      np.float64)
