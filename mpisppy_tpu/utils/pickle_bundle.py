###############################################################################
# Scenario/bundle (de)serialization
# (ref:mpisppy/utils/pickle_bundle.py:21-59).
#
# The reference dill-pickles Pyomo bundle models so expensive scenario
# construction amortizes across runs.  Our scenarios are plain
# numpy/scipy specs, so standard pickle suffices; helpers keep the
# reference's API names.  `check_args`/`have_proper_bundles` mirror the
# reference's Config cross-checks.
###############################################################################
from __future__ import annotations

import os
import pickle


def dill_pickle(obj, fname: str):
    """ref:pickle_bundle.py:21-27 (dill there; specs need only pickle)."""
    with open(fname, "wb") as f:
        pickle.dump(obj, f, protocol=pickle.HIGHEST_PROTOCOL)


def dill_unpickle(fname: str):
    """ref:pickle_bundle.py:29-35."""
    with open(fname, "rb") as f:
        return pickle.load(f)


def check_args(cfg):
    """ref:pickle_bundle.py:39-52 cross-option validation."""
    assert cfg.get("pickle_bundles_dir") is None \
        or cfg.get("unpickle_bundles_dir") is None, \
        "can't pickle and unpickle bundles in the same run"
    if cfg.get("pickle_bundles_dir") is not None \
            or cfg.get("unpickle_bundles_dir") is not None:
        assert cfg.get("scenarios_per_bundle") is not None, \
            "bundle pickling needs scenarios_per_bundle"


def have_proper_bundles(cfg) -> bool:
    """ref:pickle_bundle.py:54-59."""
    return (cfg.get("pickle_bundles_dir") is not None
            or cfg.get("unpickle_bundles_dir") is not None
            or cfg.get("scenarios_per_bundle") is not None)


def write_spec(spec, dirname: str):
    os.makedirs(dirname, exist_ok=True)
    dill_pickle(spec, os.path.join(dirname, f"{spec.name}.pkl"))


def read_spec(dirname: str, name: str):
    return dill_unpickle(os.path.join(dirname, f"{name}.pkl"))
