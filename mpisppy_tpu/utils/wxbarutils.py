###############################################################################
# W / x̄ persistence (ref:mpisppy/utils/wxbarutils.py:47-391).
#
# The reference writes one csv row per (scenario, variable) for W and
# per variable for x̄, and reloads them into Pyomo Params to warm-start
# PH.  Here the natural unit is the device array: W is (S, N), xbar is
# (num_nodes, N); both csv (reference-compatible shape: name-keyed rows)
# and npz (fast path, exact) forms are supported, plus full PHState
# checkpointing so a PH run can resume exactly (the reference has no
# general checkpointing — SURVEY §5 gap we close).
###############################################################################
from __future__ import annotations

import dataclasses

import numpy as np


# ---- W ------------------------------------------------------------------
def write_W_to_file(ph, fname: str, sep_files: bool = False):
    """ref:wxbarutils.py:47-90.  csv rows: scenario_name,slot,value."""
    W = np.asarray(ph.state.W)
    names = ph.scenario_names
    with open(fname, "w") as f:
        for s, nm in enumerate(names):
            for i in range(W.shape[1]):
                f.write(f"{nm},{i},{float(W[s, i])!r}\n")


def set_W_from_file(fname: str, ph, disable_check: bool = False):
    """ref:wxbarutils.py:92-134.  Loads W and installs it into the PH
    state; checks the p-weighted node mean is ~0 (the PH invariant,
    ref:wxbarutils.py:224-275 _check_W) unless disabled."""
    import jax.numpy as jnp
    W = np.array(np.asarray(ph.state.W))
    index = {nm: s for s, nm in enumerate(ph.scenario_names)}
    with open(fname) as f:
        for line in f:
            nm, i, v = line.rsplit(",", 2)
            if nm not in index:
                raise ValueError(f"unknown scenario {nm!r} in {fname}")
            W[index[nm], int(i)] = float(v)
    if not disable_check:
        Wj = jnp.asarray(W, ph.batch.qp.c.dtype)
        wbar, _ = ph.batch.node_average(Wj)
        if float(jnp.max(jnp.abs(wbar))) > 1e-4 * (1.0 + np.abs(W).max()):
            raise ValueError(
                "loaded W has nonzero probability-weighted node mean "
                "(invalid PH duals; pass disable_check to force)")
    ph.state = dataclasses.replace(
        ph.state, W=jnp.asarray(W, ph.batch.qp.c.dtype))


# ---- xbar ---------------------------------------------------------------
def write_xbar_to_file(ph, fname: str):
    """ref:wxbarutils.py:276-296.  csv rows: node,slot,value."""
    xb = np.asarray(ph.state.xbar_nodes)
    with open(fname, "w") as f:
        for nd in range(xb.shape[0]):
            for i in range(xb.shape[1]):
                f.write(f"{nd},{i},{float(xb[nd, i])!r}\n")


def set_xbar_from_file(fname: str, ph):
    """ref:wxbarutils.py:298-356."""
    import jax.numpy as jnp
    xb = np.array(np.asarray(ph.state.xbar_nodes))
    with open(fname) as f:
        for line in f:
            nd, i, v = line.split(",")
            xb[int(nd), int(i)] = float(v)
    batch = ph.batch
    xbj = jnp.asarray(xb, batch.qp.c.dtype)
    xbar_scen = jnp.take_along_axis(xbj, batch.node_of_slot, axis=0) \
        if batch.tree.num_nodes > 1 \
        else jnp.broadcast_to(xbj[0], ph.state.xbar.shape)
    ph.state = dataclasses.replace(ph.state, xbar_nodes=xbj,
                                   xbar=xbar_scen)


def ROOT_xbar_npy_serializer(ph, fname: str):
    """ref:wxbarutils.py:378-388: flat npy of the root-node xbar."""
    np.save(fname, np.asarray(ph.state.xbar_nodes)[0])


# ---- full-state checkpointing (SURVEY §5: reference gap) ----------------
def validate_state_leaves(arrays: dict, leaves) -> None:
    """Checkpoint-compatibility gate shared by every state restore path
    (hub.load_checkpoint and load_ph_state): each flattened leaf{i} must
    be present with the exact expected shape AND dtype — a float64 leaf
    silently upcasting a float32 state would poison every downstream
    jit cache.  Raises ValueError on the first incompatibility."""
    n = len(leaves)
    missing = [i for i in range(n) if f"leaf{i}" not in arrays]
    if missing:
        raise ValueError(f"checkpoint missing leaves {missing} "
                         f"(different problem/options?)")
    for i in range(n):
        a, b = arrays[f"leaf{i}"], leaves[i]
        if tuple(a.shape) != tuple(b.shape):
            raise ValueError(
                f"checkpoint leaf {i} shape {tuple(a.shape)} != expected "
                f"{tuple(b.shape)} (different problem/options?)")
        if np.dtype(a.dtype) != np.dtype(b.dtype):
            raise ValueError(
                f"checkpoint leaf {i} dtype {a.dtype} != expected "
                f"{np.dtype(b.dtype)} (different problem/options?)")


def save_ph_state(fname: str, ph):
    """npz snapshot of every PHState leaf + iteration counter; exact
    resume (same shapes) via load_ph_state."""
    import jax
    leaves, treedef = jax.tree.flatten(ph.state)
    np.savez(fname, _iter=ph._iter,
             **{f"leaf{i}": np.asarray(x) for i, x in enumerate(leaves)})


def load_ph_state(fname: str, ph):
    import jax
    import jax.numpy as jnp
    # NpzFile holds an open zip handle — close it (context manager)
    # instead of leaking it
    with np.load(fname) as data:
        arrays = {k: np.asarray(data[k]) for k in data.files}
    leaves, treedef = jax.tree.flatten(ph.state)
    validate_state_leaves(arrays, leaves)
    new = [jnp.asarray(arrays[f"leaf{i}"], leaves[i].dtype)
           for i in range(len(leaves))]
    ph.state = jax.tree.unflatten(treedef, new)
    ph._iter = int(arrays["_iter"])
