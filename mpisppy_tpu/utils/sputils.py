###############################################################################
# Small shared scenario utilities (the analog of the reference's
# ref:mpisppy/utils/sputils.py grab-bag; most of that file's roles —
# EF building, tree parsing, writers — live in core/tree.py, algos/ef.py
# and the drivers here, so only the genuinely shared helpers remain).
###############################################################################
from __future__ import annotations

import re

_TRAILING_DIGITS = re.compile(r"(\d+)$")


def extract_num(name: str) -> int:
    """Digits scraped off the right of a scenario name
    (ref:mpisppy/utils/sputils.py:632-689 scenario-number parsing)."""
    m = _TRAILING_DIGITS.search(name)
    if m is None:
        raise ValueError(f"scenario name {name!r} has no trailing number")
    return int(m.group(1))


def remap_spec_arrays(spec, colmap, n_new: int, m_max: int,
                      scale: float = 1.0) -> dict:
    """Re-lay a ScenarioSpec's arrays into a wider shared layout.

    colmap[j] = new column of old column j.  Unused new columns are
    fixed at 0 (dummy vars, ref:mpisppy/utils/admmWrapper.py:129-141);
    rows are padded inactive up to m_max; c and q are multiplied by
    `scale` (the admm region-count factor).  Shared by the admm
    wrappers (utils/admmWrapper.py, utils/stoch_admmWrapper.py)."""
    import numpy as np
    import scipy.sparse as sps

    c = np.zeros(n_new)
    q = np.zeros(n_new)
    l = np.zeros(n_new)  # noqa: E741
    u = np.zeros(n_new)
    integer = np.zeros(n_new, bool)
    c[colmap] = scale * np.asarray(spec.c)
    if spec.q is not None:
        q[colmap] = scale * np.asarray(spec.q)
    l[colmap] = np.asarray(spec.l)
    u[colmap] = np.asarray(spec.u)
    if spec.integer is not None:
        integer[colmap] = np.asarray(spec.integer, bool)
    used = np.zeros(n_new, bool)
    used[colmap] = True
    l[~used] = 0.0
    u[~used] = 0.0

    A = spec.A if sps.issparse(spec.A) \
        else sps.csr_matrix(np.asarray(spec.A))
    A = A.tocoo()
    m_old = A.shape[0]
    A_new = sps.coo_matrix((A.data, (A.row, colmap[A.col])),
                           shape=(m_max, n_new)).tocsr()
    bl = np.concatenate([np.asarray(spec.bl),
                         np.full(m_max - m_old, -np.inf)])
    bu = np.concatenate([np.asarray(spec.bu),
                         np.full(m_max - m_old, np.inf)])
    return dict(c=c, q=q if q.any() else None, A=A_new, bl=bl, bu=bu,
                l=l, u=u, integer=integer if integer.any() else None)
