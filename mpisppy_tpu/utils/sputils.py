###############################################################################
# Small shared scenario utilities (the analog of the reference's
# ref:mpisppy/utils/sputils.py grab-bag; most of that file's roles —
# EF building, tree parsing, writers — live in core/tree.py, algos/ef.py
# and the drivers here, so only the genuinely shared helpers remain).
###############################################################################
from __future__ import annotations

import re

_TRAILING_DIGITS = re.compile(r"(\d+)$")


def extract_num(name: str) -> int:
    """Digits scraped off the right of a scenario name
    (ref:mpisppy/utils/sputils.py:632-689 scenario-number parsing)."""
    m = _TRAILING_DIGITS.search(name)
    if m is None:
        raise ValueError(f"scenario name {name!r} has no trailing number")
    return int(m.group(1))
