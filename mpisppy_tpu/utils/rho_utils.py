###############################################################################
# rho csv helpers (ref:mpisppy/utils/rho_utils.py:1-44): rows of
# "slot,value" (the reference keys by variable name; slots are the
# TPU-native variable identity).
###############################################################################
from __future__ import annotations

import numpy as np


def rhos_to_csv(rho: np.ndarray, fname: str):
    with open(fname, "w") as f:
        f.write("ID,rho\n")
        for i, v in enumerate(np.asarray(rho)):
            f.write(f"{i},{float(v)!r}\n")


def rhos_from_csv(fname: str, num_nonants: int) -> np.ndarray:
    rho = np.ones(num_nonants)
    with open(fname) as f:
        header = f.readline()
        if "rho" not in header:
            raise ValueError(f"{fname}: missing 'ID,rho' header")
        for line in f:
            i, v = line.split(",")
            rho[int(i)] = float(v)
    return rho
