###############################################################################
# rho csv helpers (ref:mpisppy/utils/rho_utils.py:1-44): rows of
# "slot,value" (the reference keys by variable name; slots are the
# TPU-native variable identity).
###############################################################################
from __future__ import annotations

import numpy as np


def rhos_to_csv(rho: np.ndarray, fname: str):
    with open(fname, "w") as f:
        f.write("ID,rho\n")
        for i, v in enumerate(np.asarray(rho)):
            f.write(f"{i},{float(v)!r}\n")


def rhos_from_csv(fname: str, num_nonants: int) -> np.ndarray:
    rho = np.ones(num_nonants)
    with open(fname) as f:
        header = f.readline()
        if "rho" not in header:
            raise ValueError(f"{fname}: missing 'ID,rho' header")
        for lineno, line in enumerate(f, start=2):
            line = line.strip()
            if not line:
                continue
            try:
                i_str, v_str = line.split(",")
                i, v = int(i_str), float(v_str)
            except ValueError as e:
                raise ValueError(
                    f"{fname}:{lineno}: expected 'ID,rho', got "
                    f"{line!r}") from e
            if not 0 <= i < num_nonants:
                raise ValueError(
                    f"{fname}:{lineno}: slot {i} out of range "
                    f"[0, {num_nonants})")
            rho[i] = v
    return rho
