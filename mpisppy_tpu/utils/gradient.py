###############################################################################
# Gradient-based cost and rho (ref:mpisppy/utils/gradient.py:34-267,
# ref:mpisppy/utils/find_rho.py:38-357).
#
# Find_Grad: the reference fixes nonants at x̂, solves every scenario,
# and evaluates the objective gradient via PyomoNLP (pynumero AD).  Our
# objectives are explicit quadratics, so the gradient at the solve IS
# c + q x — one batched fixed-nonant solve, no AD plumbing.  Stored as
# the NEGATED gradient ("gradient cost", ref:gradient.py:85-90).
#
# Find_Rho: the WW-heuristic rho from first-order conditions
# (ref:find_rho.py:152-225):  rho[s,i] = |cost[s,i] - W[s,i]| / denom,
# with denom either per-scenario |x - xbar| (clipped to its max /
# tolerance, ref:find_rho.py:73-95) or the scenario-independent
# E[max(|x - xbar|, 1)] (ref:find_rho.py:117-150), then aggregated
# across scenarios with the grad_order_stat triangular interpolation
# (0 = min, 0.5 = p-mean, 1 = max).
###############################################################################
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from mpisppy_tpu.core.batch import ScenarioBatch
from mpisppy_tpu.ops import pdhg

Array = jax.Array
E1_TOLERANCE = 1e-5  # ref:spbase E1_tolerance default


@jax.jit
def _grad_costs(batch: ScenarioBatch, solver_x: Array) -> Array:
    """(S, N) negated objective gradients at the nonant columns, in
    ORIGINAL space (ref:gradient.py:55-90 compute_grad)."""
    qp = batch.qp
    grad = qp.c + qp.q * solver_x
    return -(grad[..., batch.nonant_idx] / batch.d_non)


def find_grad_cost(batch: ScenarioBatch, xhat: Array,
                   opts: pdhg.PDHGOptions | None = None) -> np.ndarray:
    """Batched analog of Find_Grad.find_grad_cost
    (ref:gradient.py:95-130): fix nonants at x̂, solve, grab gradients."""
    opts = opts or pdhg.PDHGOptions(tol=1e-6, max_iters=100_000)
    qp = batch.with_fixed_nonants(jnp.asarray(xhat, batch.qp.c.dtype))
    st = pdhg.solve(qp, opts, pdhg.init_state(qp, opts))
    fixed_batch = dataclasses.replace(batch, qp=qp)
    return np.asarray(_grad_costs(fixed_batch, st.x), np.float64)


def w_denom(x_non: np.ndarray, xbar: np.ndarray) -> np.ndarray:
    """(S, N) per-scenario denominator |x - xbar|, zeros replaced by the
    row max (ref:find_rho.py:73-95)."""
    d = np.abs(np.asarray(x_non) - np.asarray(xbar))
    dmax = np.maximum(d.max(axis=-1, keepdims=True), E1_TOLERANCE)
    return np.where(d <= E1_TOLERANCE, dmax, d)


def prox_denom(x_non: np.ndarray, xbar: np.ndarray) -> np.ndarray:
    """2 (x - xbar)^2, floored like w_denom (ref:find_rho.py:97-115)."""
    d = np.asarray(x_non) - np.asarray(xbar)
    d = 2.0 * d * d
    dmax = np.maximum(d.max(axis=-1, keepdims=True), E1_TOLERANCE)
    return np.where(d <= E1_TOLERANCE, dmax, d)


def grad_denom(batch: ScenarioBatch, x_non: np.ndarray,
               xbar: np.ndarray,
               grad_rho_relative_bound: float = 1e3) -> np.ndarray:  # noqa: D401
    """(N,) scenario-independent denominator E[max(|x - xbar|, 1)]
    (ref:find_rho.py:117-150)."""
    p = np.asarray(batch.p, np.float64)
    d = np.maximum(np.abs(np.asarray(x_non) - np.asarray(xbar)), 1.0)
    g = (p[:, None] * d).sum(0)
    return np.maximum(g, 1.0 / grad_rho_relative_bound)


def order_stat_aggregate(rho_scen: np.ndarray, p: np.ndarray,
                         alpha: float) -> np.ndarray:
    """Aggregate per-scenario rhos to one per slot with the triangular
    order statistic (ref:find_rho.py:186-224)."""
    if not 0.0 <= alpha <= 1.0:
        raise ValueError(
            f"grad_order_stat must be in [0,1] (0=min, 0.5=mean, "
            f"1=max); got {alpha}")
    rmin = rho_scen.min(axis=0)
    rmax = rho_scen.max(axis=0)
    rmean = (p[:, None] * rho_scen).sum(0) / max(p.sum(), 1e-30)
    if alpha == 0.5:
        return rmean
    if alpha == 0.0:
        return rmin
    if alpha == 1.0:
        return rmax
    if alpha < 0.5:
        return rmin + alpha * 2.0 * (rmean - rmin)
    return (2.0 * rmean - rmax) + alpha * 2.0 * (rmax - rmean)


class Find_Rho:
    """ref:mpisppy/utils/find_rho.py:38.  Needs a PH driver with a
    state (post Iter0 at least) and per-(scenario, slot) gradient costs
    (from find_grad_cost, or the driver's own iterates)."""

    def __init__(self, ph, cfg=None):
        self.ph = ph
        self.cfg = cfg or {}
        self.c: np.ndarray | None = None  # (S, N) gradient costs

    def _get(self, name, default):
        try:
            v = self.cfg.get(name, default)
        except AttributeError:
            v = getattr(self.cfg, name, default)
        return default if v is None else v

    def compute_rho(self, indep_denom: bool = False,
                    denom_kind: str = "w") -> np.ndarray:
        """(N,) rho from the WW heuristic (ref:find_rho.py:152-225).
        denom_kind: 'w' (|x - xbar|) or 'prox' (2(x - xbar)^2);
        indep_denom selects the scenario-independent grad denominator."""
        ph = self.ph
        batch = ph.batch
        st = ph.state
        x_non = np.asarray(batch.nonants(st.solver.x), np.float64)
        xbar = np.asarray(st.xbar, np.float64)
        if self.c is None:
            # costs at the current iterates (the xhat-file path of the
            # reference is find_grad_cost)
            self.c = np.asarray(
                _grad_costs(batch, st.solver.x), np.float64)
        W = np.asarray(st.W, np.float64)
        if indep_denom:
            denom = grad_denom(
                batch, x_non, xbar,
                self._get("grad_rho_relative_bound", 1e3))[None, :]
        elif denom_kind == "prox":
            denom = prox_denom(x_non, xbar)
        else:
            denom = w_denom(x_non, xbar)
        rho_scen = np.abs((self.c - W) / denom)
        p = np.asarray(batch.p, np.float64)
        return order_stat_aggregate(rho_scen, p,
                                    float(self._get("grad_order_stat",
                                                    0.5)))


class Set_Rho:
    """rho_setter plumbing from a saved rho file
    (ref:find_rho.py:246-288)."""

    def __init__(self, cfg):
        self.cfg = cfg

    def rho_setter(self, batch) -> np.ndarray:
        from mpisppy_tpu.utils.rho_utils import rhos_from_csv
        fname = self.cfg.get("rho_file_in") \
            if hasattr(self.cfg, "get") else self.cfg["rho_file_in"]
        return rhos_from_csv(fname, batch.num_nonants)
