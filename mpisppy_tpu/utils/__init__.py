# Utility plane: config, vanilla hub/spoke factories, W/xbar I-O.
