###############################################################################
# Vanilla hub/spoke dict factories — the one-stop shop consumed by
# WheelSpinner, keyed off a Config (ref:mpisppy/utils/cfg_vanilla.py:
# ph_hub:93, lagrangian_spoke:436, subgradient_spoke:526,
# xhatxbar_spoke:589, xhatshuffle_spoke:622, slammax/min_spoke:701/722).
#
# The reference factories package (opt_class, comm_class, options) per
# MPI cylinder; here they package the same dicts for the single-program
# wheel: the hub owns the PH driver on the scenario batch, each spoke is
# a batched device computation.
###############################################################################
from __future__ import annotations

import jax.numpy as jnp

from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.cylinders.hub import LShapedHub, PHHub
from mpisppy_tpu.cylinders import spoke as spoke_mod
from mpisppy_tpu.ops import pdhg


def _pdhg_opts(cfg) -> pdhg.PDHGOptions:
    from mpisppy_tpu.ops import boxqp
    prec = cfg.get("iter_precision")
    # validate HERE (config time): a typo'd --iter-precision must fail
    # before any jit trace, with the full alias list in the message
    boxqp.as_precision(prec)
    return pdhg.PDHGOptions(
        tol=cfg.get("pdhg_tol", 1e-6),
        iter_precision=prec,
        pallas_pipeline=bool(cfg.get("pallas_pipeline", True)),
        lane_guard=bool(cfg.get("lane_guard", False)),
        guard_max_resets=cfg.get("guard_max_resets", 3),
        telemetry=bool(cfg.get("kernel_counters", False)))


def _hub_opts(cfg) -> dict:
    """Shared hub termination options (ref:hub.py:82-166 inputs) plus
    the resilience knobs (checkpointing / strike policy,
    docs/resilience.md) and the telemetry knobs (profiler session,
    docs/telemetry.md; the event bus itself is wired by the driver —
    generic_cylinders builds it once per run via telemetry.from_cfg)."""
    hub_opts = {"rel_gap": cfg.get("rel_gap", 0.01),
                "display_progress": cfg.get("display_progress", False)}
    if cfg.get("abs_gap") is not None:
        hub_opts["abs_gap"] = cfg["abs_gap"]
    if cfg.get("max_stalled_iters") is not None:
        hub_opts["max_stalled_iters"] = cfg["max_stalled_iters"]
    for key in ("checkpoint_path", "checkpoint_every_s",
                "checkpoint_keep", "spoke_max_strikes", "bound_slack",
                "bound_evict_contras", "profile_dir", "profile_iters",
                "watchdog_budget_s", "watchdog_action",
                "watchdog_interval_s"):
        if cfg.get(key) is not None:
            hub_opts[key] = cfg[key]
    return hub_opts


def ph_options(cfg) -> ph_mod.PHOptions:
    return ph_mod.PHOptions(
        default_rho=cfg.get("default_rho", 1.0),
        max_iterations=cfg.get("max_iterations", 100),
        conv_thresh=cfg.get("convthresh", 1e-4),
        subproblem_windows=cfg.get("subproblem_windows", 8),
        iter0_windows=cfg.get("iter0_windows", 400),
        pdhg=_pdhg_opts(cfg),
        smoothed=cfg.get("smoothed", False),
        smooth_beta=cfg.get("defaultPHbeta", 0.2),
        smooth_p=cfg.get("defaultPHp", 0.0),
        display_progress=cfg.get("display_progress", False),
        time_limit=cfg.get("time_limit"),
    )


def ph_hub(cfg, batch, scenario_names=None, rho_setter=None,
           extensions=None, converger=None) -> dict:
    """ref:cfg_vanilla.py:93-141."""
    hub_opts = _hub_opts(cfg)
    return {
        "hub_class": PHHub,
        "hub_kwargs": {"options": hub_opts},
        "opt_class": ph_mod.PH,
        "opt_kwargs": {
            "options": ph_options(cfg),
            "batch": batch,
            "scenario_names": scenario_names,
            "rho_setter": rho_setter,
            "extensions": extensions,
            "converger": converger,
        },
    }


def aph_hub(cfg, batch, scenario_names=None, rho_setter=None,
            extensions=None, converger=None) -> dict:
    """ref:cfg_vanilla.py:142-210 (aph_hub)."""
    from mpisppy_tpu.algos import aph as aph_mod
    from mpisppy_tpu.cylinders.hub import APHHub
    hub_opts = _hub_opts(cfg)
    aph_opts = aph_mod.APHOptions(
        default_rho=cfg.get("default_rho", 1.0),
        max_iterations=cfg.get("max_iterations", 100),
        conv_thresh=cfg.get("convthresh", 1e-4),
        gamma=cfg.get("aph_gamma", 1.0),
        nu=cfg.get("aph_nu", 1.0),
        dispatch_frac=cfg.get("aph_dispatch_frac", 1.0),
        use_dynamic_gamma=cfg.get("aph_use_dynamic_gamma", False),
        subproblem_windows=cfg.get("subproblem_windows", 8),
        iter0_windows=cfg.get("iter0_windows", 400),
        pdhg=_pdhg_opts(cfg),
        display_progress=cfg.get("display_progress", False),
        time_limit=cfg.get("time_limit"),
    )
    return {
        "hub_class": APHHub,
        "hub_kwargs": {"options": hub_opts},
        "opt_class": aph_mod.APH,
        "opt_kwargs": {
            "options": aph_opts,
            "batch": batch,
            "scenario_names": scenario_names,
            "rho_setter": rho_setter,
            "extensions": extensions,
            "converger": converger,
        },
    }


def lshaped_hub(cfg, batch, scenario_names=None) -> dict:
    """L-shaped (Benders) as the hub (ref:cfg_vanilla.py lshaped_hub
    analog; reference wires it via dedicated drivers)."""
    from mpisppy_tpu.algos import lshaped as ls_mod
    hub_opts = _hub_opts(cfg)
    tol = cfg.get("pdhg_tol", 1e-7)
    guard = bool(cfg.get("lane_guard", False))
    guard_resets = cfg.get("guard_max_resets", 3)
    ls_opts = ls_mod.LShapedOptions(
        max_iter=cfg.get("lshaped_max_iter", 50),
        tol=cfg.get("rel_gap", 1e-4),
        multicut=cfg.get("lshaped_multicut", False),
        sub_pdhg=pdhg.PDHGOptions(tol=tol, max_iters=100_000,
                                  detect_infeas=True, lane_guard=guard,
                                  guard_max_resets=guard_resets),
        master_pdhg=pdhg.PDHGOptions(tol=tol, max_iters=200_000,
                                     lane_guard=guard,
                                     guard_max_resets=guard_resets),
        display_progress=cfg.get("display_progress", False),
    )
    return {
        "hub_class": LShapedHub,
        "hub_kwargs": {"options": hub_opts},
        "opt_class": ls_mod.LShapedMethod,
        "opt_kwargs": {"options": ls_opts, "batch": batch,
                       "scenario_names": scenario_names},
    }


def _spoke(cls, options=None) -> dict:
    return {"spoke_class": cls, "opt_kwargs": {"options": options or {}}}


def lagrangian_spoke(cfg) -> dict:
    """ref:cfg_vanilla.py:436-465."""
    return _spoke(spoke_mod.LagrangianOuterBound,
                  {"pdhg_opts": _pdhg_opts(cfg)})


def lagranger_spoke(cfg) -> dict:
    """ref:cfg_vanilla.py:493-525."""
    import json
    rescale = {}
    fname = cfg.get("lagranger_rho_rescale_factors_json")
    if fname:
        with open(fname) as f:
            rescale = {int(k): float(v) for k, v in json.load(f).items()}
    return _spoke(spoke_mod.LagrangerOuterBound,
                  {"pdhg_opts": _pdhg_opts(cfg),
                   "rho": cfg.get("default_rho", 1.0),
                   "rho_rescale_factors": rescale})


def subgradient_spoke(cfg) -> dict:
    """ref:cfg_vanilla.py:526-558."""
    return _spoke(spoke_mod.SubgradientOuterBound,
                  {"pdhg_opts": _pdhg_opts(cfg),
                   "rho": cfg.get("subgradient_rho",
                                  cfg.get("default_rho", 1.0))})


def fwph_spoke(cfg) -> dict:
    """ref:cfg_vanilla.py:328-435."""
    from mpisppy_tpu.algos import fwph as fwph_mod
    fw_opts = fwph_mod.FWPHOptions(
        fw_iter_limit=cfg.get("fwph_iter_limit", 2),
        fw_weight=cfg.get("fwph_weight", 0.0),
        fw_conv_thresh=cfg.get("fwph_conv_thresh", 1e-4),
        max_columns=cfg.get("fwph_max_columns", 16),
        default_rho=cfg.get("default_rho", 1.0),
        pdhg=_pdhg_opts(cfg),
    )
    return _spoke(spoke_mod.FWPHOuterBound,
                  {"pdhg_opts": _pdhg_opts(cfg), "fw_opts": fw_opts,
                   "rho": cfg.get("default_rho", 1.0)})


def reduced_costs_spoke(cfg) -> dict:
    """ref:cfg_vanilla.py:466-492."""
    return _spoke(spoke_mod.ReducedCostsSpoke,
                  {"pdhg_opts": _pdhg_opts(cfg),
                   "rc_bound_tol": cfg.get("rc_bound_tol", 1e-6)})


def reduced_costs_fixer(cfg):
    """Factory for the hub-side fixer extension."""
    import functools
    from mpisppy_tpu.extensions.reduced_costs_fixer import (
        ReducedCostsFixer,
    )
    return functools.partial(
        ReducedCostsFixer,
        fix_fraction_target_iter0=cfg.get("rc_fix_fraction_iter0", 0.0),
        fix_fraction_target_iterK=cfg.get("rc_fix_fraction_iterk", 0.0),
        zero_rc_tol=cfg.get("rc_zero_rc_tol", 1e-4),
        bound_tol=cfg.get("rc_bound_tol", 1e-6),
        use_rc_bt=cfg.get("rc_bound_tightening", False),
    )


def ph_ob_spoke(cfg) -> dict:
    """ref:cfg_vanilla.py:781-820."""
    return _spoke(spoke_mod.PhOuterBound,
                  {"pdhg_opts": _pdhg_opts(cfg),
                   "rho": cfg.get("default_rho", 1.0),
                   "ph_ob_rho_rescale":
                       cfg.get("ph_ob_rho_rescale_factor", 0.1)})


def cross_scenario_cuts_spoke(cfg) -> dict:
    """ref:cfg_vanilla.py:743-780."""
    from mpisppy_tpu.cylinders.spoke import CrossScenarioCutSpoke
    return _spoke(CrossScenarioCutSpoke,
                  {"pdhg_opts": _pdhg_opts(cfg)})


def cross_scenario_extension(cfg):
    """Factory for the hub-side extension (pass as ph_hub
    extensions=...)."""
    import functools
    from mpisppy_tpu.extensions.cross_scen_extension import (
        CrossScenarioExtension,
    )
    return functools.partial(
        CrossScenarioExtension,
        check_bound_improve_iterations=cfg.get("cross_scenario_iter_cnt",
                                               4),
        max_rounds=cfg.get("cross_scenario_max_rounds", 8),
        pdhg_opts=pdhg.PDHGOptions(tol=cfg.get("pdhg_tol", 1e-6),
                                   max_iters=100_000),
    )


def xhatxbar_spoke(cfg) -> dict:
    """ref:cfg_vanilla.py:589-621."""
    return _spoke(spoke_mod.XhatXbarInnerBound,
                  {"pdhg_opts": _pdhg_opts(cfg)})


def xhatshuffle_spoke(cfg) -> dict:
    """ref:cfg_vanilla.py:622-655."""
    return _spoke(spoke_mod.XhatShuffleInnerBound,
                  {"pdhg_opts": _pdhg_opts(cfg),
                   "k": cfg.get("xhatshuffle_iter_step", 4),
                   "add_reversed": cfg.get("add_reversed_shuffle", False)})


def xhatlshaped_spoke(cfg) -> dict:
    """ref:cfg_vanilla.py:679-700."""
    return _spoke(spoke_mod.XhatLShapedInnerBound,
                  {"pdhg_opts": _pdhg_opts(cfg)})


def slammax_spoke(cfg) -> dict:
    """ref:cfg_vanilla.py:701-721."""
    return _spoke(spoke_mod.SlamMaxHeuristic,
                  {"pdhg_opts": _pdhg_opts(cfg)})


def slammin_spoke(cfg) -> dict:
    """ref:cfg_vanilla.py:722-742."""
    return _spoke(spoke_mod.SlamMinHeuristic,
                  {"pdhg_opts": _pdhg_opts(cfg)})
