###############################################################################
# Amalgamator: the one-call programmatic driver.
#
# The reference's Amalgamator (ref:mpisppy/utils/amalgamator.py:143-257)
# is what library users and the CI-sampling code call instead of the
# generic_cylinders CLI: give it a Config + a model module (or the
# module's five functions) and it runs either the EF or a hub-and-spokes
# wheel, then exposes the results as attributes.  Same surface here,
# driving the same code paths as mpisppy_tpu.generic_cylinders so the
# CLI and the library entry stay behaviourally identical.
#
#   ama = amalgamator.from_module("mpisppy_tpu.models.farmer", cfg)
#   ama.run()
#   ama.best_outer_bound / ama.best_inner_bound / ama.EF_Obj
#   ama.first_stage_solution   # (n_root_nonants,)
#
# The confidence-interval subsystem uses this as its solver entry the
# way the reference's ciutils/seqsampling call Amalgamator
# (ref:mpisppy/confidence_intervals/ciutils.py:214+).
###############################################################################
from __future__ import annotations

import importlib
import types

import numpy as np

from mpisppy_tpu import global_toc
from mpisppy_tpu.utils.config import Config


_MODULE_API = ("scenario_creator", "scenario_names_creator", "kw_creator",
               "scenario_denouement", "inparser_adder")


def _as_module(thing) -> types.ModuleType | types.SimpleNamespace:
    if isinstance(thing, str):
        return importlib.import_module(thing)
    return thing


def check_module_ama(module) -> None:
    """Verify the five-function model API
    (ref:mpisppy/utils/amalgamator.py:106-140 check for modules)."""
    missing = [f for f in _MODULE_API if not hasattr(module, f)]
    if missing:
        raise RuntimeError(
            f"model module lacks required function(s): {missing} "
            "(ref:generic_cylinders.py:43-52 five-function API)")


class Amalgamator:
    """Programmatic equivalent of the generic_cylinders CLI
    (ref:mpisppy/utils/amalgamator.py:257+).

    cfg: a Config that already carries the run options (use
    Config groups or from_module() to parse an option list).  The run
    mode is cfg['EF'] (direct extensive form) vs hub/spokes flags
    (lagrangian, xhatshuffle, fwph, ...).
    """

    def __init__(self, cfg: Config, module,
                 scenario_creator=None, kw_creator=None, verbose=True):
        self.cfg = cfg
        self.module = _as_module(module)
        check_module_ama(self.module)
        # explicit overrides, matching the reference's ability to pass
        # creators directly (ref:amalgamator.py:257 ctor args)
        if scenario_creator is not None or kw_creator is not None:
            ns = types.SimpleNamespace(**{
                f: getattr(self.module, f) for f in _MODULE_API})
            if scenario_creator is not None:
                ns.scenario_creator = scenario_creator
            if kw_creator is not None:
                ns.kw_creator = kw_creator
            self.module = ns
        self.verbose = verbose
        self.is_EF = bool(cfg.get("EF"))
        # results (populated by run)
        self.EF_Obj: float | None = None
        self.best_outer_bound: float | None = None
        self.best_inner_bound: float | None = None
        self.first_stage_solution: np.ndarray | None = None
        self.wheel = None
        self.ef = None

    def run(self):
        """ref:mpisppy/utils/amalgamator.py:257+ Amalgamator.run."""
        from mpisppy_tpu import generic_cylinders as gc
        if self.is_EF:
            self.ef = gc._do_EF(self.cfg, self.module)
            self.EF_Obj = self.ef.get_objective_value()
            self.best_outer_bound = self.EF_Obj
            self.best_inner_bound = self.EF_Obj
            self.first_stage_solution = np.asarray(
                list(self.ef.get_root_solution().values()))
        else:
            self.wheel = gc._do_decomp(self.cfg, self.module)
            self.best_outer_bound = self.wheel.BestOuterBound
            self.best_inner_bound = self.wheel.BestInnerBound
            opt = self.wheel.opt
            if getattr(opt, "state", None) is not None \
                    and hasattr(opt, "first_stage_solution"):
                self.first_stage_solution = opt.first_stage_solution()
        global_toc("Amalgamator run done", self.verbose)
        return self


def from_module(mname, cfg: Config, scenario_creator=None,
                kw_creator=None, use_command_line: bool = False,
                args=None, verbose=True) -> Amalgamator:
    """Build an Amalgamator from a model module name/object
    (ref:mpisppy/utils/amalgamator.py:143 from_module).

    use_command_line: parse `args` (or sys.argv) through the full
    generic_cylinders flag set; otherwise `cfg` must already contain the
    options (num_scens etc.)."""
    module = _as_module(mname)
    check_module_ama(module)
    if use_command_line:
        from mpisppy_tpu import generic_cylinders as gc
        cfg = gc._parse_args(module, args)
    else:
        # ensure the module's own flags exist with their defaults even
        # when cfg was built programmatically
        module.inparser_adder(cfg)
    return Amalgamator(cfg, module, scenario_creator=scenario_creator,
                       kw_creator=kw_creator, verbose=verbose)
