###############################################################################
# Config: the framework's option system with an argparse bridge.
#
# The reference wraps pyomo's ConfigDict and auto-generates argparse
# flags from declared options, with ~45 canned groups
# (ref:mpisppy/utils/config.py:54-157 and the *_args group functions at
# :174-976).  Here Config is a small self-contained dict-of-entries with
# the same surface: add_to_config(), attribute/dict access, quick_assign,
# canned groups (popular_args, ph_args, ...), and parse_command_line()
# building an argparse parser from the declared entries (dashes in flag
# names, underscores in attribute names — same convention).
###############################################################################
from __future__ import annotations

import argparse
import dataclasses
from typing import Any


@dataclasses.dataclass
class _Entry:
    name: str
    description: str
    domain: type | None
    default: Any
    value: Any
    argparse: bool = True
    complain: bool = False


def _boolify(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).lower() in ("1", "true", "yes", "on")


class Config:
    """ref:mpisppy/utils/config.py:54 — declare options, then parse."""

    def __init__(self):
        object.__setattr__(self, "_entries", {})

    # -- core declaration/access (ref:config.py:64-140) -------------------
    def add_to_config(self, name: str, description: str, domain=str,
                      default=None, argparse: bool = True,
                      complain: bool = False):
        if name in self._entries:
            if complain:
                raise RuntimeError(f"option {name} already declared")
            return
        self._entries[name] = _Entry(name, description, domain, default,
                                     default, argparse)

    def quick_assign(self, name: str, domain=str, value=None):
        """declare-and-set (ref:config.py:118)."""
        self.add_to_config(name, name, domain, value, argparse=False)
        self._entries[name].value = value

    def add_and_assign(self, name: str, description: str, domain, default,
                       value):
        self.add_to_config(name, description, domain, default,
                           argparse=False)
        self._entries[name].value = value

    def __getattr__(self, name):
        entries = object.__getattribute__(self, "_entries")
        if name in entries:
            return entries[name].value
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if name in self._entries:
            self._entries[name].value = value
        else:
            self.quick_assign(name, type(value), value)

    def __contains__(self, name):
        return name in self._entries

    def __getitem__(self, name):
        return self._entries[name].value

    def get(self, name, default=None):
        e = self._entries.get(name)
        return default if e is None or e.value is None else e.value

    def keys(self):
        return self._entries.keys()

    def items(self):
        return {k: e.value for k, e in self._entries.items()}.items()

    # -- canned groups (ref:config.py:174-976) ----------------------------
    def num_scens_required(self):
        self.add_to_config("num_scens", "number of scenarios", int, None)

    def num_scens_optional(self):
        self.add_to_config("num_scens", "number of scenarios", int, None)

    def popular_args(self):
        """ref:config.py:174-249 (solver options dropped: the kernel is
        in-repo; PDHG knobs take their place)."""
        self.add_to_config("max_iterations", "PH max iterations", int, 100)
        self.add_to_config("time_limit", "wall clock limit (sec)", float,
                           None)
        self.add_to_config("default_rho", "PH rho", float, 1.0)
        self.add_to_config("rel_gap", "relative termination gap", float,
                           0.01)
        self.add_to_config("abs_gap", "absolute termination gap", float,
                           None)
        self.add_to_config("max_stalled_iters", "stall termination", int,
                           None)
        self.add_to_config("display_progress", "per-iter trace", bool,
                           False)
        self.add_to_config("pdhg_tol", "subproblem KKT tolerance", float,
                           1e-6)
        self.add_to_config("subproblem_windows",
                           "PDHG restart windows per PH iteration", int, 8)
        self.add_to_config("iter_precision",
                           "PDHG iteration matvec precision alias: "
                           "bf16x3 (3-pass bf16 — half the HBM bytes "
                           "and MXU passes per matvec, ~4e-6 relative "
                           "error, docs/precision.md) or bf16x6 (full "
                           "f32, the default when unset).  Restart "
                           "scoring, convergence tests and certificates "
                           "always run at full precision regardless",
                           str, None)
        self.add_to_config("pallas_pipeline",
                           "double-buffer the Pallas window kernel's "
                           "scenario-tile DMA (prefetch next tile while "
                           "the current one computes); disable to force "
                           "the single-buffer grid kernel", bool, True)

    def two_sided_args(self):
        self.add_to_config("rel_gap", "relative termination gap", float,
                           0.01)
        self.add_to_config("abs_gap", "absolute termination gap", float,
                           None)

    def presolve_args(self):
        """Batched FBBT presolve (ref:mpisppy/opt/presolve.py via the
        reference's 'presolve' option; here ops/fbbt.py)."""
        self.add_to_config("presolve",
                           "run FBBT bound tightening on the batch",
                           bool, False)
        self.add_to_config("presolve_sweeps",
                           "FBBT interval-tightening sweeps", int, 3)

    def ph_args(self):
        """ref:config.py:250-315."""
        self.popular_args()
        self.add_to_config("convthresh", "PH convergence threshold", float,
                           1e-4)
        self.add_to_config("smoothed", "use smoothing", bool, False)
        self.add_to_config("defaultPHbeta", "smoothing beta", float, 0.2)
        self.add_to_config("defaultPHp", "smoothing p coefficient", float,
                           0.0)

    def aph_args(self):
        """ref:config.py:396-430."""
        self.add_to_config("aph_hub", "use APH as the hub algorithm",
                           bool, False)
        self.add_to_config("aph_gamma", "APH gamma parameter", float, 1.0)
        self.add_to_config("aph_nu", "APH step scaling nu", float, 1.0)
        self.add_to_config("aph_dispatch_frac",
                           "fraction of subproblems dispatched per "
                           "iteration", float, 1.0)
        self.add_to_config("aph_use_dynamic_gamma",
                           "adapt gamma from the u/v norm decrease ratio",
                           bool, False)
        # legacy alias (the listener-consensus fraction has no analog in
        # the single-program design; kept so reference scripts parse —
        # an INTENTIONAL parse-only no-op, not a dead knob)
        # graftlint: allow-config-knob
        self.add_to_config("aph_frac_needed",
                           "legacy parse-only no-op (listener consensus "
                           "fraction; use --aph-dispatch-frac)", float, 1.0)

    def fwph_args(self):
        """ref:config.py:487-520."""
        self.add_to_config("fwph", "use an FWPH outer-bound spoke", bool,
                           False)
        self.add_to_config("fwph_iter_limit", "FWPH inner iterations", int,
                           2)
        self.add_to_config("fwph_max_columns", "FWPH column-buffer size",
                           int, 16)
        self.add_to_config("fwph_weight", "FWPH weight", float, 0.0)
        self.add_to_config("fwph_conv_thresh", "FWPH convergence", float,
                           1e-4)

    def lagrangian_args(self):
        """ref:config.py:521-538."""
        self.add_to_config("lagrangian", "use a Lagrangian bound spoke",
                           bool, False)

    def lagranger_args(self):
        self.add_to_config("lagranger", "use a Lagranger bound spoke",
                           bool, False)
        self.add_to_config("lagranger_rho_rescale_factors_json",
                           "json {iter: factor}", str, None)

    def subgradient_args(self):
        self.add_to_config("subgradient", "use a subgradient bound spoke",
                           bool, False)
        self.add_to_config("subgradient_rho", "subgradient step rho",
                           float, 1.0)

    def xhatxbar_args(self):
        self.add_to_config("xhatxbar", "use an xhat-xbar inner spoke",
                           bool, False)

    def fused_wheel_args(self):
        """TPU-native: run the requested lagrangian/xhatxbar/slam/
        xhatshuffle planes INSIDE the hub's jitted step
        (algos/fused_wheel — measured <=5x bare PH vs 642x for
        separate-dispatch spokes on one chip)."""
        self.add_to_config("fused_wheel",
                           "fuse the bound spokes into the hub step",
                           bool, False)
        self.add_to_config("fused_spoke_period",
                           "run fused planes every k-th iteration",
                           int, 1)
        self.add_to_config("async_staleness",
                           "async wheel: exchange-plane staleness bound "
                           "(0 = synchronous hub; docs/async_wheel.md)",
                           int, 0)
        self.add_to_config("async_exchange_deadline_s",
                           "async wheel: bound (seconds) on settling an "
                           "exchange plane ticket — expiry surfaces a "
                           "typed SolveFailed instead of a hang "
                           "(0 = unbounded; the hub watchdog is then "
                           "the wedged-exchange backstop)",
                           float, 0.0)

    def xhatshuffle_args(self):
        """ref:config.py:676-699."""
        self.add_to_config("xhatshuffle", "use an xhat shuffle spoke",
                           bool, False)
        self.add_to_config("add_reversed_shuffle", "also reversed order",
                           bool, False)
        self.add_to_config("xhatshuffle_iter_step",
                           "candidates per sync", int, 4)

    def gradient_args(self):
        """ref:config.py:821-872."""
        self.add_to_config("grad_rho", "use gradient-based dynamic rho",
                           bool, False)
        self.add_to_config("grad_order_stat",
                           "rho order statistic (0=min,0.5=mean,1=max)",
                           float, 0.5)
        self.add_to_config("grad_rho_update_interval",
                           "iterations between rho recomputation", int, 5)
        self.add_to_config("grad_rho_relative_bound",
                           "denominator floor bound", float, 1e3)
        self.add_to_config("grad_rho_indep_denom",
                           "use the scenario-independent denominator",
                           bool, False)
        self.add_to_config("rho_file_in",
                           "csv of per-slot rhos (ID,rho header)", str,
                           None)
        self.add_to_config("rho_file_out", "write computed rhos here",
                           str, None)

    def dynamic_rho_args(self):
        """ref:config.py:873-910."""
        self.add_to_config("sensi_rho",
                           "rho from iter0 KKT sensitivities", bool,
                           False)
        self.add_to_config("sensi_rho_multiplier",
                           "sensitivity rho multiplier", float, 1.0)
        self.add_to_config("mult_rho", "multiplicative rho schedule",
                           bool, False)
        self.add_to_config("mult_rho_update_factor", "rho factor",
                           float, 2.0)
        self.add_to_config("mult_rho_update_interval",
                           "iterations between rho multiplications",
                           int, 2)

    def reduced_costs_args(self):
        """ref:config.py:539-600."""
        self.add_to_config("reduced_costs",
                           "use a reduced-costs spoke + fixer", bool,
                           False)
        self.add_to_config("rc_bound_tol", "at-bound tolerance for rc "
                           "extraction", float, 1e-6)
        self.add_to_config("rc_zero_rc_tol", "zero reduced-cost "
                           "tolerance", float, 1e-4)
        self.add_to_config("rc_fix_fraction_iter0",
                           "fraction of nonants to fix after iter0",
                           float, 0.0)
        self.add_to_config("rc_fix_fraction_iterk",
                           "fraction of nonants to fix at iter k",
                           float, 0.0)
        self.add_to_config("rc_bound_tightening",
                           "tighten nonant bounds from reduced costs",
                           bool, False)

    def ph_ob_args(self):
        """ref:config.py ph_ob group."""
        self.add_to_config("ph_ob", "use a PH outer-bound spoke", bool,
                           False)
        self.add_to_config("ph_ob_rho_rescale_factor",
                           "rho rescale for the ph_ob spoke", float, 0.1)

    def cross_scenario_cuts_args(self):
        """ref:config.py cross_scenario_cuts group."""
        self.add_to_config("cross_scenario_cuts",
                           "use a cross-scenario cut spoke + hub "
                           "extension", bool, False)
        self.add_to_config("cross_scenario_iter_cnt",
                           "hub iterations between EF bound checks",
                           int, 4)
        self.add_to_config("cross_scenario_max_rounds",
                           "capacity of the preallocated cut buffer "
                           "(rounds of S cuts)", int, 8)

    def slama_args(self):
        self.add_to_config("slammax", "use slam-max heuristic spoke", bool,
                           False)
        self.add_to_config("slammin", "use slam-min heuristic spoke", bool,
                           False)

    def lshaped_args(self):
        """L-shaped (Benders) hub options (ref:mpisppy/opt/lshaped.py
        options dict: max_iter/tol/root_solver)."""
        self.add_to_config("lshaped_hub", "use L-shaped (Benders) as the "
                           "hub algorithm instead of PH", bool, False)
        self.add_to_config("lshaped_max_iter", "Benders iterations", int,
                           50)
        self.add_to_config("lshaped_multicut", "per-scenario cuts", bool,
                           False)
        self.add_to_config("xhatlshaped", "use an xhat-lshaped inner "
                           "spoke", bool, False)

    def converger_args(self):
        """ref:config.py:897-910."""
        self.add_to_config("use_primal_dual_converger",
                           "primal-dual converger", bool, False)
        self.add_to_config("primal_dual_converger_tol",
                           "pd converger tolerance", float, 1e-2)

    def wxbar_read_write_args(self):
        """ref:config.py:950-975."""
        self.add_to_config("init_W_fname", "warm-start W file", str, None)
        self.add_to_config("init_Xbar_fname", "warm-start xbar file", str,
                           None)
        self.add_to_config("W_fname", "output W file", str, None)
        self.add_to_config("Xbar_fname", "output xbar file", str, None)

    def proper_bundle_config(self):
        """ref:config.py:976-1010."""
        self.add_to_config("scenarios_per_bundle",
                           "proper-bundle size (scenarios per bundle)",
                           int, None)
        self.add_to_config("pickle_bundles_dir",
                           "write pickled bundles here", str, None)
        self.add_to_config("unpickle_bundles_dir",
                           "read pickled bundles from here", str, None)

    def multistage(self):
        """ref:config.py:315-330."""
        self.add_to_config("branching_factors",
                           "branching factors per stage", list, None)

    def mip_options(self):
        self.add_to_config("iter0_windows",
                           "PDHG restart windows for iter0", int, 400)

    def resilience_args(self):
        """Chaos/graceful-degradation knobs (docs/resilience.md):
        preemption-tolerant checkpointing, spoke strike policy, and the
        PDHG per-lane divergence guard.  No reference analog — the
        reference leans on exact-solver retries (ref:spopt.py:931-960)."""
        self.add_to_config("checkpoint_path",
                           "rotated wheel checkpoint file; also enables "
                           "the SIGTERM/SIGINT emergency save",
                           str, None)
        self.add_to_config("checkpoint_every_s",
                           "seconds between background checkpoints",
                           float, 60.0)
        self.add_to_config("checkpoint_keep",
                           "rotated snapshots kept (path, path.1, ...; "
                           "minimum 2)", int, 2)
        self.add_to_config("checkpoint_restore",
                           "resume from the newest valid snapshot when "
                           "one exists at checkpoint-path",
                           bool, False)
        self.add_to_config("spoke_max_strikes",
                           "auto-disable a spoke after this many "
                           "rejected (non-finite/sense-violating) bounds",
                           int, 3)
        self.add_to_config("bound_slack",
                           "relative slack for sense-violation bound "
                           "rejection", float, 5e-3)
        self.add_to_config("bound_evict_contras",
                           "distinct contradicting spokes that evict a "
                           "standing incumbent bound", int, 3)
        self.add_to_config("lane_guard",
                           "quarantine-reset diverged PDHG scenario "
                           "lanes at restart boundaries", bool, False)
        self.add_to_config("guard_max_resets",
                           "bounded quarantine retries per PDHG lane",
                           int, 3)
        self.add_to_config("watchdog_budget_s",
                           "hub progress watchdog: trip when no hub "
                           "iteration or certified-bound movement for "
                           "this many wall seconds (off when unset)",
                           float, None)
        self.add_to_config("watchdog_action",
                           "watchdog trip action: 'abort' (flight dump "
                           "+ emergency checkpoint + exit 75) or "
                           "'degrade' (un-coalesced direct dispatch; "
                           "a second stalled budget escalates to "
                           "abort)", str, "abort")
        self.add_to_config("watchdog_interval_s",
                           "watchdog poll interval (default: a quarter "
                           "of the budget)", float, None)

    def telemetry_args(self):
        """Telemetry subsystem knobs (docs/telemetry.md): structured
        wheel tracing, the metrics exporter, on-device kernel counters,
        and the profiler session.  No reference analog — the reference
        observes its wheel through per-rank stdout."""
        self.add_to_config("trace_jsonl",
                           "write structured wheel events to this JSONL "
                           "trace file", str, None)
        self.add_to_config("metrics_snapshot",
                           "Prometheus-style text metrics file, "
                           "rewritten atomically during the run", str,
                           None)
        self.add_to_config("metrics_every_s",
                           "seconds between metrics snapshot rewrites",
                           float, 30.0)
        self.add_to_config("telemetry_verbosity",
                           "console verbosity: 0 quiet, 1 progress, "
                           "2 debug", int, 1)
        self.add_to_config("kernel_counters",
                           "accumulate on-device PDHG counters "
                           "(iterations/restarts/omega adaptations + a "
                           "score ring) inside the jit graph", bool,
                           False)
        self.add_to_config("profile_dir",
                           "bracket wheel iterations with a "
                           "jax.profiler trace written here", str, None)
        self.add_to_config("profile_iters",
                           "wheel iterations the profiler trace covers",
                           int, 5)
        self.add_to_config("flight_recorder",
                           "always-on crash black box: ring of the last "
                           "events, dumped to flight-<runid>.jsonl when "
                           "the wheel dies (disable: "
                           "--flight-recorder false)", bool, True)
        self.add_to_config("flight_capacity",
                           "events held by the flight-recorder ring",
                           int, 512)
        self.add_to_config("flight_dir",
                           "directory flight-<runid>.jsonl dumps land "
                           "in", str, ".")

    def dispatch_args(self):
        """Dispatch-scheduler knobs (docs/dispatch.md): the coalescing
        queue, the bounded in-flight pipeline, and the shape-bucket /
        compile-cache discipline every host-driven MIP solve rides
        through.  No reference analog — each reference subproblem is
        one opaque Gurobi call on its own rank (ref:mpisppy/
        spopt.py:884); batching/queueing is the TPU wheel's problem."""
        self.add_to_config("dispatch_coalesce",
                           "aggregate concurrent same-shape solves "
                           "into megabatch dispatches", bool, True)
        self.add_to_config("dispatch_max_batch",
                           "lane cap per coalesced megabatch dispatch",
                           int, 4096)
        self.add_to_config("dispatch_max_wait_ms",
                           "admission window (ms) a queued solve may "
                           "wait for coalescence", float, 2.0)
        self.add_to_config("dispatch_max_inflight",
                           "outstanding device dispatches before "
                           "submitters block (2 = double buffer)",
                           int, 2)
        self.add_to_config("dispatch_pad",
                           "pad megabatches up the geometric bucket "
                           "ladder (bounded jit cache)", bool, True)
        self.add_to_config("dispatch_bucket_growth",
                           "geometric growth factor of the batch "
                           "bucket ladder", float, 2.0)
        self.add_to_config("dispatch_compile_guard",
                           "raise on a backend compile against an "
                           "already-warm shape bucket", bool, False)
        self.add_to_config("dispatch_timeout_s",
                           "per-attempt megabatch dispatch timeout: a "
                           "hung dispatch is abandoned and retried "
                           "after this many seconds (off when unset)",
                           float, None)
        self.add_to_config("dispatch_retry_max",
                           "retries (with exponential backoff) before "
                           "a failing megabatch is bisected to isolate "
                           "and quarantine the poison request(s)",
                           int, 2)
        self.add_to_config("dispatch_retry_backoff_s",
                           "base retry backoff, doubled per retry",
                           float, 0.05)
        self.add_to_config("dispatch_deadline_s",
                           "default per-ticket deadline: result() can "
                           "never block longer; expiry raises a typed "
                           "SolveFailed (off when unset)", float, None)

    def checker(self):
        """Cross-option validation (ref:config.py:143-157)."""
        if self.get("smoothed") and self.get("defaultPHp", 0.0) < 0:
            raise ValueError("smoothing needs defaultPHp >= 0")

    # -- argparse bridge (ref:config.py:1014-1048) ------------------------
    def create_parser(self, progname: str | None = None):
        parser = argparse.ArgumentParser(prog=progname)
        for e in self._entries.values():
            if not e.argparse:
                continue
            flag = "--" + e.name.replace("_", "-")
            if e.domain is bool:
                parser.add_argument(flag, dest=e.name, nargs="?",
                                    const=True, default=e.default,
                                    type=_boolify, help=e.description)
            elif e.domain is list:
                parser.add_argument(flag, dest=e.name, nargs="+",
                                    default=e.default, type=int,
                                    help=e.description)
            else:
                parser.add_argument(flag, dest=e.name, default=e.default,
                                    type=e.domain or str,
                                    help=e.description)
        return parser

    def parse_command_line(self, progname: str | None = None, args=None):
        parser = self.create_parser(progname)
        ns = parser.parse_args(args)
        for k, v in vars(ns).items():
            if k in self._entries:
                self._entries[k].value = v
        return ns
