###############################################################################
# stoch_admmWrapper: scenario x region consensus ADMM as multistage PH
# (ref:mpisppy/utils/stoch_admmWrapper.py:36-237).
#
# Each (stochastic scenario s, admm region r) pair becomes one
# "scenario" of a 3-stage tree ROOT -> scenario nodes -> region leaves
# (ref:stoch_admmWrapper.py:104-116 create_node_names):
#   * stage-1 slots: the ORIGINAL first-stage variables — shared across
#     everything, reduced at ROOT;
#   * stage-2 slots: the consensus variables — shared across the
#     regions of ONE scenario, reduced at that scenario's node with
#     variable probabilities p_s / count(v)
#     (ref:stoch_admmWrapper.py:118-180 assign_variable_probs).
# Pair probability is p_s / R and each pair objective carries the
# region count R, so the PH expectation reproduces
# sum_s p_s sum_r f_{s,r} exactly.
#
# The user's scenario_creator(stoch_name, region_name, **kw) returns
# (ScenarioSpec, var_names) with spec.nonant_idx marking the ORIGINAL
# first-stage columns.  Originally-multistage problems (the reference's
# BFs path) are not supported here.
###############################################################################
from __future__ import annotations

import numpy as np


from mpisppy_tpu.core.batch import ScenarioSpec
from mpisppy_tpu.core.tree import ScenarioTree
from mpisppy_tpu.utils.admmWrapper import _consensus_vars_number_creator


class Stoch_AdmmWrapper:
    """ref:mpisppy/utils/stoch_admmWrapper.py:36."""

    def __init__(self, options, admm_subproblem_names,
                 stoch_scenario_names, scenario_creator, consensus_vars,
                 stoch_probabilities=None,
                 scenario_creator_kwargs=None, BFs=None, verbose=False):
        assert len(options) == 0, \
            "no options supported by stoch_admmWrapper"
        if BFs is not None:
            raise NotImplementedError(
                "originally-multistage problems (BFs) are not supported")
        self.admm_subproblem_names = list(admm_subproblem_names)
        self.stoch_scenario_names = list(stoch_scenario_names)
        R = len(self.admm_subproblem_names)
        Sst = len(self.stoch_scenario_names)
        self.number_admm_subproblems = R
        self.consensus_vars = consensus_vars
        self.consensus_vars_number = _consensus_vars_number_creator(
            consensus_vars)
        p_s = np.full(Sst, 1.0 / Sst) if stoch_probabilities is None \
            else np.asarray(stoch_probabilities, np.float64)
        kw = scenario_creator_kwargs or {}

        labels = sorted(self.consensus_vars_number)
        K = len(labels)

        # probe one pair per region for layout
        raw = {}
        for snm in self.stoch_scenario_names:
            for rnm in self.admm_subproblem_names:
                spec, var_names = scenario_creator(snm, rnm, **kw)
                missing = [v for v in consensus_vars[rnm]
                           if v not in var_names]
                if missing:
                    raise RuntimeError(
                        f"for ({snm}, {rnm}), consensus vars not in "
                        f"the model: {missing} "
                        "(ref:stoch_admmWrapper.py assign_variable_"
                        "probs error lists)")
                raw[snm, rnm] = (spec, list(var_names))

        n1 = len(raw[self.stoch_scenario_names[0],
                     self.admm_subproblem_names[0]][0].nonant_idx)
        n_loc = {}
        for (snm, rnm), (spec, vn) in raw.items():
            n_loc[snm, rnm] = (len(vn) - n1
                               - len(consensus_vars[rnm]))
        n_local_max = max(n_loc.values())
        m_max = max(sp.A.shape[0] for sp, _ in raw.values())
        n_new = n1 + K + n_local_max
        scale = float(R)

        from mpisppy_tpu.utils.sputils import remap_spec_arrays
        label_ix = {v: i for i, v in enumerate(labels)}
        self.local_admm_stoch_subproblem_scenarios = {}
        self.all_pair_names = []
        for si, snm in enumerate(self.stoch_scenario_names):
            for rnm in self.admm_subproblem_names:
                spec, var_names = raw[snm, rnm]
                first_slot = {int(j): k for k, j in
                              enumerate(np.asarray(spec.nonant_idx))}
                mine = set(consensus_vars[rnm])
                colmap = np.empty(len(var_names), np.int64)
                loc = 0
                for j, v in enumerate(var_names):
                    if j in first_slot:
                        colmap[j] = first_slot[j]
                    elif v in mine:
                        colmap[j] = n1 + label_ix[v]
                    else:
                        colmap[j] = n1 + K + loc
                        loc += 1

                parts = remap_spec_arrays(spec, colmap, n_new, m_max,
                                          scale=scale)

                # nonant slots: stage-1 block then consensus block
                var_prob = np.zeros(n1 + K)
                var_prob[:n1] = p_s[si] / R
                for v in mine:
                    var_prob[n1 + label_ix[v]] = \
                        p_s[si] / self.consensus_vars_number[v]

                pname = f"ADMM_STOCH_{snm}_{rnm}"
                self.all_pair_names.append(pname)
                self.local_admm_stoch_subproblem_scenarios[pname] = \
                    ScenarioSpec(
                        name=pname,
                        nonant_idx=np.arange(n1 + K, dtype=np.int32),
                        probability=float(p_s[si] / R),
                        var_prob=var_prob, **parts)
        self._n1, self._K = n1, K

    def split_admm_stoch_subproblem_scenario_name(self, pname: str):
        """ref:stoch_admmWrapper.py split function (inverse of the pair
        naming)."""
        body = pname[len("ADMM_STOCH_"):]
        for rnm in self.admm_subproblem_names:
            if body.endswith("_" + rnm):
                return body[:-(len(rnm) + 1)], rnm
        raise ValueError(f"cannot split pair name {pname!r}")

    def admmWrapper_scenario_creator(self, pname: str) -> ScenarioSpec:
        return self.local_admm_stoch_subproblem_scenarios[pname]

    def make_tree(self) -> ScenarioTree:
        return ScenarioTree(
            branching_factors=(len(self.stoch_scenario_names),
                               self.number_admm_subproblems),
            nonants_per_stage=(self._n1, self._K))

    def make_batch(self):
        from mpisppy_tpu.core import batch as batch_mod
        specs = [self.local_admm_stoch_subproblem_scenarios[nm]
                 for nm in self.all_pair_names]
        return batch_mod.from_specs(specs, tree=self.make_tree())
