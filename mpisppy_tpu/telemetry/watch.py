###############################################################################
# `telemetry watch` — live wheel monitoring (ISSUE 7 tentpole, part 4;
# docs/telemetry.md).
#
# Tails a RUNNING wheel's --trace-jsonl stream (and, optionally, its
# --metrics-snapshot Prometheus file) and renders a refreshing status
# block: bound/gap, steady-state sec/iter, dispatch occupancy and
# queue pressure, quarantine/strike counts, checkpoint age.  Built for
# the long S=100k runs where the console scrolls too fast to read and
# the analyzer only answers post-mortem.
#
# Pure stdlib, incremental: the file is read FROM THE LAST OFFSET each
# tick (a 10-hour trace is parsed once, not per refresh), torn final
# lines are retried next tick, and a log-rotated/truncated file is
# detected by shrinkage and re-read from the top.  `--once` renders a
# single snapshot and exits — the mode CI smoke-tests.
###############################################################################
from __future__ import annotations

import json
import os
import sys
import time

from mpisppy_tpu.telemetry import slo as _slo
from mpisppy_tpu.telemetry.metrics import Histogram


class WatchState:
    """Rolling view over one run's event stream (newest run wins —
    a restarted wheel appending to the same path takes over the
    display, matching the analyzer's default run selection)."""

    def __init__(self):
        self.run = None
        self.hub_class = None
        self.tenant = None          # serve layer: session-state rows
        self.sla = None
        self.session = None
        self.session_state = None
        self.replica = None         # fleet: replica this trace segment
                                    # rode on (session-state payloads)
        self.migrations = 0         # fleet: session-migrated events
                                    # seen in THIS segment
        self.events = 0
        self.last_iter = None
        self.outer = self.inner = self.rel_gap = None
        self.iter_monos: list = []      # (iter, t_mono) tail
        self.megabatch_lanes = 0
        self.megabatch_padded = 0
        self.megabatches = 0
        self.dispatch_last: dict = {}
        self.quarantine_resets = 0
        self.strikes = 0
        self.disables = 0
        self.faults = 0
        self.dispatch_retries = 0
        self.dispatch_quarantined = 0
        self.watchdog_trips = 0
        self.mesh_epoch = 0         # elastic mesh (ISSUE 17)
        self.mesh_hosts_lost = 0
        self.mesh_reshards = 0
        self.mesh_devices = None    # old->new of the latest reshard
        self.mesh_stragglers = 0
        self.mpc_steps = 0          # MPC streams (ISSUE 19): mpc-step
        self.mpc_last_step = None   # events on the session trace
        self.mpc_warm = 0
        self.mpc_degraded = 0
        self.mpc_latencies: list = []   # recent step latency_s tail
                                        # (display only; capped)
        # ALL step latencies fold into a histogram so the p50 covers
        # the stream's whole life in O(1) memory — the old tail-only
        # median silently forgot everything before the last 64 windows
        # (ISSUE 20 satellite)
        self.mpc_hist = Histogram()
        self.trace_id = None        # causal trace id (ISSUE 20) — the
                                    # migrated-segment join key
        self.slo_obs: list = []     # slo-observation payloads
        self.ckpt_writes = 0
        self.last_ckpt_wall = None
        self.last_event_wall = None
        self.end: dict | None = None
        self.profile_dir = None

    def feed(self, row: dict) -> None:
        kind = row.get("kind")
        run = row.get("run")
        if run and run != self.run:
            if kind == "run-start" or self.run is None:
                # new segment: reset to follow the newest run
                self.__init__()
                self.run = run
            else:
                return                 # stale cross-run stragglers
        self.events += 1
        self.last_event_wall = row.get("t_wall", self.last_event_wall)
        if row.get("trace_id") and self.trace_id is None:
            self.trace_id = row["trace_id"]
        data = row.get("data", {})
        it = row.get("iter")
        if kind == "run-start":
            self.hub_class = data.get("hub_class")
        elif kind == "hub-iteration":
            self.last_iter = data.get("iter", it)
            self.outer = data.get("outer", self.outer)
            self.inner = data.get("inner", self.inner)
            self.rel_gap = data.get("rel_gap", self.rel_gap)
            if row.get("t_mono") is not None:
                self.iter_monos.append((self.last_iter, row["t_mono"]))
                del self.iter_monos[:-32]
        elif kind == "dispatch":
            if row.get("cyl") == "dispatch":
                self.megabatches += 1
                self.megabatch_lanes += data.get("lanes", 0)
                self.megabatch_padded += data.get("padded_to", 0)
            else:
                self.dispatch_last = data
        elif kind == "lane-quarantine":
            self.quarantine_resets += data.get("resets", 0)
        elif kind == "dispatch-retry":
            self.dispatch_retries += 1
        elif kind == "dispatch-quarantine":
            self.dispatch_quarantined += data.get("lanes", 0)
        elif kind == "watchdog":
            # count hub-watchdog TRIPS only, mirroring the analyzer's
            # resilience summary — a dispatcher fail-fast event shares
            # the kind but is not a progress-watchdog trip
            if data.get("action") in ("abort", "degrade"):
                self.watchdog_trips += 1
        elif kind == "spoke-strike":
            self.strikes += 1
        elif kind == "spoke-disable":
            self.disables += 1
        elif kind == "fault-injected":
            self.faults += 1
        elif kind == "mpc-step":
            # rolling-horizon stream (docs/mpc.md): one row per solved
            # window; degraded windows carry degraded=True here too, so
            # the paired mpc-degraded event needs no extra counting
            self.mpc_steps += 1
            self.mpc_last_step = data.get("step", self.mpc_last_step)
            self.mpc_warm += 1 if data.get("warm") else 0
            self.mpc_degraded += 1 if data.get("degraded") else 0
            if data.get("latency_s") is not None:
                self.mpc_hist.observe(data["latency_s"])
                self.mpc_latencies.append(data["latency_s"])
                del self.mpc_latencies[:-64]
        elif kind == "checkpoint-write":
            self.ckpt_writes += 1
            self.last_ckpt_wall = row.get("t_wall")
        elif kind == "run-end":
            self.end = data
        elif kind == "session-state":
            # serve layer (docs/serving.md): the per-session lifecycle
            # rides the same trace; the newest state wins the display
            self.tenant = data.get("tenant", self.tenant)
            self.sla = data.get("sla", self.sla)
            self.session = data.get("session", self.session)
            self.session_state = data.get("state", self.session_state)
            self.replica = data.get("replica", self.replica)
        elif kind == "session-migrated":
            # fleet: this segment ends here; the destination replica's
            # segment continues the same (run, session)
            self.session = data.get("session", self.session)
            self.tenant = data.get("tenant", self.tenant)
            self.migrations = max(self.migrations,
                                  data.get("migrations", 0) or 0)
        elif kind == "mesh-state":
            self.mesh_epoch = max(self.mesh_epoch,
                                  data.get("epoch", 0) or 0)
        elif kind == "mesh-host-lost":
            self.mesh_hosts_lost += 1
        elif kind == "mesh-reshard":
            self.mesh_reshards += 1
            self.mesh_devices = (f"{data.get('old_devices')}->"
                                 f"{data.get('new_devices')}")
        elif kind == "mesh-straggler":
            self.mesh_stragglers += 1
        elif kind == "slo-observation":
            # one terminal SLO sample per session (ISSUE 20): folded
            # into the live burn-rate rows below the session table
            if "outcome" in data:
                self.slo_obs.append(data)
                del self.slo_obs[:-256]
        elif kind == "profile":
            self.profile_dir = data.get("profile_dir", self.profile_dir)

    @property
    def sec_per_iter(self) -> float | None:
        ms = [m for _, m in self.iter_monos]
        deltas = sorted(b - a for a, b in zip(ms, ms[1:]) if b > a)
        return deltas[len(deltas) // 2] if deltas else None

    @property
    def mpc_step_latency_p50(self) -> float | None:
        """p50 over EVERY retained window (the histogram), not just
        the recent display tail."""
        if not self.mpc_hist.count:
            return None
        return self.mpc_hist.quantile(0.5)


def _follow(path: str, state: WatchState, pos: int) -> int:
    """Feed appended complete lines; returns the new offset."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return pos
    if size < pos:      # truncated/rotated: start over
        state.__init__()
        pos = 0
    if size == pos:
        return pos
    with open(path, "rb") as f:
        f.seek(pos)
        chunk = f.read()
    # keep a torn final line for the next tick
    last_nl = chunk.rfind(b"\n")
    if last_nl < 0:
        return pos
    for line in chunk[:last_nl].split(b"\n"):
        line = line.strip()
        if not line:
            continue
        try:
            state.feed(json.loads(line))
        except ValueError:
            continue
    return pos + last_nl + 1


def read_metrics_snapshot(path: str) -> dict[str, float]:
    """Prometheus text exposition -> {metric_name: value} (labels are
    folded into the name verbatim; last sample wins)."""
    out: dict[str, float] = {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.rsplit(" ", 1)
                if len(parts) != 2:
                    continue
                try:
                    out[parts[0]] = float(parts[1])
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def _fmt(v, spec=".6g"):
    return "-" if v is None else format(v, spec)


def render_status(state: WatchState,
                  metrics: dict[str, float] | None = None) -> str:
    L: list[str] = []
    age = (time.time() - state.last_event_wall
           if state.last_event_wall else None)
    L.append(f"run {state.run or '?'}  hub={state.hub_class or '?'}  "
             f"events {state.events}"
             + (f"  last event {age:.1f}s ago" if age is not None
                else ""))
    gap = state.rel_gap
    L.append(f"iter {_fmt(state.last_iter)}  outer {_fmt(state.outer)}  "
             f"inner {_fmt(state.inner)}  rel_gap {_fmt(gap, '.3e')}"
             f"  sec/iter {_fmt(state.sec_per_iter, '.4g')}")
    occ = (state.megabatch_lanes / state.megabatch_padded
           if state.megabatch_padded else None)
    d = state.dispatch_last
    L.append(f"dispatch: megabatches {state.megabatches}"
             f"  occupancy {_fmt(occ, '.3f')}"
             f"  inflight_max {_fmt(d.get('inflight_max'))}"
             f"  compiles {_fmt(d.get('backend_compiles'))}"
             f"  unexpected {_fmt(d.get('unexpected_recompiles'))}")
    ck_age = (time.time() - state.last_ckpt_wall
              if state.last_ckpt_wall else None)
    L.append(f"resilience: quarantine resets {state.quarantine_resets}"
             f"  strikes {state.strikes}  disabled {state.disables}"
             f"  faults {state.faults}"
             f"  retries {state.dispatch_retries}"
             f"  quarantined lanes {state.dispatch_quarantined}"
             f"  watchdog {state.watchdog_trips}"
             f"  ckpt writes {state.ckpt_writes}"
             + (f" (last {ck_age:.0f}s ago)" if ck_age is not None
                else ""))
    if (state.mesh_epoch or state.mesh_hosts_lost
            or state.mesh_reshards or state.mesh_stragglers):
        L.append(f"mesh: epoch {state.mesh_epoch}"
                 f"  hosts lost {state.mesh_hosts_lost}"
                 f"  reshards {state.mesh_reshards}"
                 + (f" ({state.mesh_devices} devices)"
                    if state.mesh_devices else "")
                 + (f"  stragglers/tears {state.mesh_stragglers}"
                    if state.mesh_stragglers else ""))
    if state.mpc_steps:
        L.append(f"mpc: steps {state.mpc_steps}"
                 f" (last {_fmt(state.mpc_last_step, 'd')})"
                 f"  warm {state.mpc_warm}"
                 f"  degraded {state.mpc_degraded}"
                 f"  step p50 "
                 f"{_fmt(state.mpc_step_latency_p50, '.3g')}s")
    if metrics:
        keys = sorted(k for k in metrics
                      if k.startswith(("dispatch_", "wheel_", "pdhg_")))
        if keys:
            L.append("metrics: " + "  ".join(
                f"{k}={metrics[k]:g}" for k in keys[:6]))
    if state.end is not None:
        L.append(f"RUN ENDED: {state.end.get('reason')}  rel_gap "
                 f"{_fmt(state.end.get('rel_gap'), '.3e')}")
    if state.profile_dir:
        L.append(f"profiler captures under {state.profile_dir} "
                 f"(analyze --profile-dir to inspect)")
    return "\n".join(L)


# ---------------------------------------------------------------------------
# directory mode (`telemetry watch --trace-dir`; ISSUE 12 satellite)
# ---------------------------------------------------------------------------
def _fmt_cell(v, spec=".3g", width=0):
    s = "-" if v is None else format(v, spec)
    return s.rjust(width) if width else s


def merge_session_rows(states: dict[str, "WatchState"]) -> list[dict]:
    """Fold per-FILE states into per-SESSION rows.  A fleet-migrated
    session leaves one trace segment per replica it ran on (the same
    sid file name under each replica's subdirectory); the segments
    join on the CAUSAL TRACE ID (ISSUE 20) so the session counts ONCE,
    with the newest segment supplying its current state and the
    replica chain recording the journey; the (run id, session id)
    heuristic remains only as the fallback for pre-trace segments."""
    groups: dict = {}
    for name in sorted(states):
        st = states[name]
        if st.trace_id:
            key = st.trace_id
        elif st.run and st.session:
            key = (st.run, st.session)
        else:
            key = name
        groups.setdefault(key, []).append((name, st))
    rows: list[dict] = []
    for key in groups:
        segs = groups[key]
        # segment order = event recency (the destination segment is
        # the live one; ties keep listing order)
        segs = sorted(segs, key=lambda p: p[1].last_event_wall or 0.0)
        chain = []
        for name, s in segs:
            rep = s.replica or os.path.dirname(name) or None
            if rep and rep not in chain:
                chain.append(rep)
        name, prim = segs[-1]
        iters = [s.last_iter for _, s in segs
                 if isinstance(s.last_iter, int)]
        rows.append({
            "session": prim.session or os.path.basename(name)
            .replace("session-", "").replace(".jsonl", ""),
            "tenant": next((s.tenant for _, s in reversed(segs)
                            if s.tenant), "?"),
            "sla": next((s.sla for _, s in reversed(segs) if s.sla),
                        None),
            "state": prim.session_state,
            "iter": max(iters) if iters else None,
            "rel_gap": prim.rel_gap,
            "sec_per_iter": prim.sec_per_iter,
            "events": sum(s.events for _, s in segs),
            "chain": chain,
            "replica": chain[-1] if chain else None,
            "migrations": max((s.migrations for _, s in segs),
                              default=0),
            "mpc_steps": sum(s.mpc_steps for _, s in segs),
            "step_p50": prim.mpc_step_latency_p50,
        })
    return rows


def render_tenant_table(states: dict[str, "WatchState"]) -> str:
    """Per-session table over a directory of per-session traces (the
    serve layer writes one per session; docs/serving.md), grouped by
    tenant with a per-tenant rollup line.  Fleet layouts (per-replica
    subdirectories) get a replica column — `r0>r1` marks a migrated
    session — and a per-replica summary block."""
    L: list[str] = []
    rows = merge_session_rows(states)
    fleet = any(r["replica"] for r in rows)
    mpc = any(r["mpc_steps"] for r in rows)
    rep_w = 9 if fleet else 0
    head = (f"{'session':<10} {'tenant':<10} {'sla':<10} {'state':<9} "
            f"{'iter':>5} {'rel_gap':>9} {'s/iter':>8} {'events':>7}")
    if mpc:
        # MPC streams (docs/mpc.md): windows solved + step-latency p50
        head += f" {'steps':>6} {'step p50':>9}"
    if fleet:
        head += f" {'replica':<9}"
    L.append(head)
    by_tenant: dict[str, list] = {}
    for r in rows:
        by_tenant.setdefault(r["tenant"], []).append(r)
    for tenant in sorted(by_tenant):
        rows_t = by_tenant[tenant]
        done = sum(1 for r in rows_t
                   if r["state"] in ("DONE", "FAILED", "REJECTED"))
        gaps = [r["rel_gap"] for r in rows_t
                if r["rel_gap"] is not None]
        L.append(f"tenant {tenant}: {len(rows_t)} session(s), "
                 f"{done} terminal"
                 + (f", best rel_gap {min(gaps):.3e}" if gaps else ""))
        for r in sorted(rows_t, key=lambda r: r["session"]):
            line = (
                f"  {r['session']:<8} {tenant:<10} "
                f"{r['sla'] or '-':<10} {r['state'] or '-':<9} "
                f"{_fmt_cell(r['iter'], 'd'):>5} "
                f"{_fmt_cell(r['rel_gap'], '.3e'):>9} "
                f"{_fmt_cell(r['sec_per_iter'], '.3g'):>8} "
                f"{r['events']:>7}")
            if mpc:
                line += (f" {_fmt_cell(r['mpc_steps'], 'd'):>6} "
                         f"{_fmt_cell(r['step_p50'], '.3g'):>9}")
            if fleet:
                line += f" {'>'.join(r['chain']) or '-':<{rep_w}}"
            L.append(line)
    if fleet:
        reps = sorted({rep for r in rows for rep in r["chain"]})
        for rid in reps:
            here = [r for r in rows if r["replica"] == rid]
            touched = [r for r in rows if rid in r["chain"]]
            done = sum(1 for r in here
                       if r["state"] in ("DONE", "FAILED", "REJECTED"))
            moved = sum(1 for r in touched if len(r["chain"]) > 1)
            L.append(f"replica {rid}: {len(here)} session(s) "
                     f"resident, {done} terminal, {moved} migrated")
    # live SLO burn rates (ISSUE 20): fold every settled session's
    # slo-observation sample into the per-class error budgets
    obs = [{"kind": "slo-observation", "data": d}
           for st in states.values() for d in st.slo_obs]
    if obs:
        rep = _slo.evaluate_observations(obs)
        for name, row in rep["slo"].items():
            if not row["samples"]:
                continue
            verdict = "ok" if row["ok"] else "BUDGET EXHAUSTED"
            L.append(f"slo {name}: burn {row['burn_rate']:.2f}  "
                     f"budget left {row['budget_remaining']:.2f}  "
                     f"({row['bad']}/{row['samples']} bad)  {verdict}")
    if not by_tenant:
        L.append("(no session traces yet)")
    return "\n".join(L)


def watch_dir(trace_dir: str, interval: float = 2.0,
              once: bool = False, out=None) -> int:
    """Tail a DIRECTORY of per-session JSONL traces (the serve layer
    writes one per session) and render the per-tenant table.  New
    files are picked up between ticks; each file keeps its own
    incremental offset.  A fleet layout — per-replica SUBDIRECTORIES
    each holding that replica's session traces — is walked one level
    deep; aggregate streams (fleet.jsonl) are skipped, and a migrated
    session's segments merge on (run, sid) so it never double-counts."""
    out = out or sys.stdout
    if not os.path.isdir(trace_dir):
        print(f"watch: no trace directory at {trace_dir!r}",
              file=sys.stderr)
        return 1
    states: dict[str, WatchState] = {}
    offsets: dict[str, int] = {}

    def _scan() -> list[str]:
        try:
            entries = sorted(os.listdir(trace_dir))
        except OSError:
            return []
        found: list[str] = []
        for e in entries:
            p = os.path.join(trace_dir, e)
            if e.endswith(".jsonl") and os.path.isfile(p):
                found.append(e)
            elif os.path.isdir(p):
                try:
                    subs = sorted(os.listdir(p))
                except OSError:
                    continue
                found.extend(os.path.join(e, s) for s in subs
                             if s.endswith(".jsonl"))
        session_only = [n for n in found
                        if os.path.basename(n).startswith("session-")]
        return session_only or found

    try:
        while True:
            names = _scan()
            for n in names:
                st = states.setdefault(n, WatchState())
                offsets[n] = _follow(os.path.join(trace_dir, n), st,
                                     offsets.get(n, 0))
            block = render_tenant_table(states)
            if once:
                print(block, file=out, flush=True)
                return 0
            print("\x1b[2J\x1b[H" + block, file=out, flush=True)
            time.sleep(max(0.2, interval))
    except KeyboardInterrupt:
        return 0


def watch(trace_path: str, metrics_path: str | None = None,
          interval: float = 2.0, once: bool = False,
          out=None) -> int:
    """The `telemetry watch` loop.  Returns the process exit code."""
    out = out or sys.stdout
    if not os.path.exists(trace_path):
        print(f"watch: no trace at {trace_path!r}", file=sys.stderr)
        return 1
    state = WatchState()
    pos = 0
    try:
        while True:
            pos = _follow(trace_path, state, pos)
            metrics = (read_metrics_snapshot(metrics_path)
                       if metrics_path else None)
            block = render_status(state, metrics)
            if once:
                print(block, file=out, flush=True)
                return 0
            # clear + repaint (plain ANSI home; scrollback stays sane
            # on dumb terminals because the block is short)
            print("\x1b[2J\x1b[H" + block, file=out, flush=True)
            if state.end is not None:
                return 0
            time.sleep(max(0.2, interval))
    except KeyboardInterrupt:
        return 0
