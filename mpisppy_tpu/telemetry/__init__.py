###############################################################################
# mpisppy_tpu.telemetry — the wheel's observability spine
# (docs/telemetry.md; ISSUE 3).
#
#   events    — typed event taxonomy (hub iteration, harvest, bound
#               accept/reject/strike, checkpoint, fault, quarantine, ...)
#   bus       — EventBus: thread-safe, failure-isolated fan-out
#   sinks     — JsonlSink / ConsoleSink / MetricsSnapshotSink
#   views     — back-compat Hub.trace / Spoke.trace list views
#   metrics   — MetricsRegistry + the shared snapshot schema (bench.py
#               embeds the same object in BENCH_*.json)
#   console   — log(): the replacement for library print(...)
#   counters  — on-device PDHG kernel counters (imports jax; import the
#               submodule directly)
#   profiler  — jax.profiler spans + the --profile-dir session (ditto)
#   flightrec — the always-on crash black box (last ~512 events,
#               dumped to flight-<runid>.jsonl when the wheel dies)
#   analyze   — trace -> typed run model -> phase/bound/stall/dispatch
#               report (`python -m mpisppy_tpu.telemetry analyze`)
#   regress   — perf compare/gate over analyzer reports and
#               BENCH_*.json artifacts (`... compare|gate`)
#
# This package (minus counters/profiler) imports only the stdlib, so a
# host-only consumer can read traces without a jax install.
###############################################################################
from __future__ import annotations

from mpisppy_tpu.telemetry import console, metrics
from mpisppy_tpu.telemetry.bus import EventBus
from mpisppy_tpu.telemetry.events import (  # noqa: F401 (re-exports)
    ADMISSION_REJECTED, BOUND_ACCEPT, BOUND_EVICT, BOUND_REJECT,
    CHECKPOINT_RESTORE, CHECKPOINT_WRITE, CONSOLE, DISPATCH,
    DISPATCH_QUARANTINE, DISPATCH_RETRY, EXCHANGE_OVERLAP,
    FAULT_INJECTED, FLEET_PLACEMENT, HUB_ITERATION, KERNEL_COUNTERS,
    LANE_QUARANTINE, MESH_HOST_LOST, MESH_RESHARD, MESH_STATE,
    MESH_STRAGGLER, MPC_DEGRADED, MPC_STEP, PLANE_WRITE, PROFILE,
    REPLICA_STATE, RUN_END,
    RUN_START, SESSION_MIGRATED, SESSION_STATE, SLO_OBSERVATION, SPAN,
    SPAN_START, SPOKE_DISABLE, SPOKE_HARVEST, SPOKE_STRIKE, WATCHDOG,
    Event, new_run_id,
)
from mpisppy_tpu.telemetry.flightrec import FlightRecorder  # noqa: F401
from mpisppy_tpu.telemetry.tracecontext import TraceContext  # noqa: F401
from mpisppy_tpu.telemetry.sinks import (  # noqa: F401
    ConsoleSink, JsonlSink, MetricsSnapshotSink, Sink,
)
from mpisppy_tpu.telemetry.views import WheelTraceView  # noqa: F401


def from_cfg(cfg, registry=None):
    """Build the run's EventBus from the telemetry_args Config group
    (utils/config.py).  Returns None when no telemetry output is
    requested — callers then skip all wiring and the wheel runs the
    zero-overhead default path.  Always applies --telemetry-verbosity
    to the console."""
    verbosity = int(cfg.get("telemetry_verbosity", console.INFO))
    console.set_verbosity(verbosity)
    trace_path = cfg.get("trace_jsonl")
    snap_path = cfg.get("metrics_snapshot")
    if not trace_path and not snap_path:
        return None
    bus = EventBus()
    if trace_path:
        bus.subscribe(JsonlSink(trace_path))
    if snap_path:
        bus.subscribe(MetricsSnapshotSink(
            snap_path, registry=registry,
            every_s=float(cfg.get("metrics_every_s", 30.0))))
    # the human stream moves onto the bus so stdout and the JSONL trace
    # can never diverge (telemetry/console.py suppresses its direct
    # print while a ConsoleSink-bearing bus is attached)
    bus.subscribe(ConsoleSink(verbosity))
    console.attach(bus)
    return bus


def close_bus(bus) -> None:
    """Flush + detach a from_cfg bus (final metrics snapshot, JSONL
    close).  Safe on None."""
    if bus is None:
        return
    console.detach(bus)
    bus.close()
