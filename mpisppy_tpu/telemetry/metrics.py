###############################################################################
# Metrics registry + the shared snapshot schema.
#
# A MetricsRegistry is a flat map of named counters (monotone within a
# run) and gauges (point-in-time values), with optional Prometheus-style
# labels.  Two render paths share ONE schema:
#
#   * render_prom()  — Prometheus text exposition, written atomically to
#     the --metrics-snapshot file so a node-exporter-style scraper (or a
#     human with `cat`) can watch a long-running wheel;
#   * to_snapshot()  — the JSON snapshot dict.  bench.py embeds exactly
#     this object in BENCH_*.json entries, so offline benchmark
#     artifacts and live-run snapshots are directly comparable
#     (ISSUE 3 satellite; see docs/telemetry.md).
#
# There is a process-global default registry (REGISTRY) in the style of
# prometheus_client: deep library code (ops/bnb.py, the hub's kernel
# harvest) records into it without threading a handle through every
# call, and sinks snapshot it.  Values mirrored from on-device cumulative
# counters are SET (absolute), not inc'd — the device is the source of
# truth and re-folding would double count.
###############################################################################
from __future__ import annotations

import threading
import time

SNAPSHOT_SCHEMA = "mpisppy-tpu-metrics/1"

#: The declared metric vocabulary (ISSUE 10 schema-drift pass): every
#: literal metric name recorded anywhere in the library must appear
#: here, so a typo'd or ad-hoc name is a lint failure instead of a
#: silently forked time series (`python -m tools.graftlint`).  Names
#: are grouped by producer; labels (cyl=, kind=) are orthogonal to the
#: base name and not part of the schema.
ALL_METRICS = frozenset({
    # telemetry spine (sinks.py, hub checkpoint path)
    "events_total",
    "checkpoint_writes_total",
    # on-device PDHG kernel counters (counters.py harvest)
    "pdhg_iterations_total",
    "pdhg_restarts_total",
    "pdhg_omega_adaptations_total",
    "pdhg_guard_resets_total",
    "pdhg_windows_total",
    "pdhg_last_score_median",
    # host-driven B&B (ops/bnb.py)
    "bnb_nodes_solved_total",
    "bnb_lanes_closed_total",
    # dispatch scheduler (dispatch/scheduler.py; docs/dispatch.md)
    "dispatch_batches_total",
    "dispatch_lanes_total",
    "dispatch_pad_lanes_total",
    "dispatch_batch_occupancy",
    "dispatch_queue_depth",
    "dispatch_buckets_active",
    "dispatch_inflight",
    "dispatch_backend_compiles_total",
    "dispatch_unexpected_recompiles_total",
    "dispatch_retries_total",
    "dispatch_quarantined_lanes_total",
    "dispatch_quarantined_requests_total",
    "dispatch_dispatcher_deaths_total",
    "dispatch_plane_tickets_total",
    "dispatch_plane_deadline_misses_total",
    # async wheel exchange plane (cylinders/hub.AsyncPHHub; ISSUE 11)
    "async_plane_writes_total",
    "async_plane_staleness",
    # seeded scenario synthesis (mpisppy_tpu/scengen; docs/scengen.md)
    "scengen_virtual_batches_total",
    "scengen_scenarios",
    "scengen_data_bytes_saved",
    # supervisors (resilience/watchdog.py)
    "watchdog_trips_total",
    # multi-tenant wheel server (mpisppy_tpu/serve; ISSUE 12)
    "serve_sessions_total",
    "serve_sessions_active",
    "serve_queue_depth",
    "serve_admission_rejects_total",
    "serve_preemptions_total",
    "serve_disconnects_total",
    "serve_failures_total",
    # replicated serve fleet (mpisppy_tpu/fleet; ISSUE 16)
    "fleet_replicas_up",
    "fleet_replica_deaths_total",
    "fleet_sessions_migrated_total",
    "fleet_migrations_lost_total",
    "fleet_placement_affinity_total",
    "fleet_placement_spill_total",
    # rolling-horizon MPC streams (mpisppy_tpu/mpc; ISSUE 19)
    "mpc_streams_total",
    "mpc_steps_total",
    "mpc_warm_steps_total",
    "mpc_cold_fallbacks_total",
    "mpc_degraded_steps_total",
    "mpc_stream_resumes_total",
    "mpc_step_latency_s",
    # elastic mesh fault domain (parallel/elastic.py; ISSUE 17)
    "mesh_hosts_up",
    "mesh_epoch",
    "mesh_hosts_lost_total",
    "mesh_reshards_total",
    "mesh_reshards_lost_total",
    "mesh_stragglers_total",
    "mesh_torn_harvests_total",
    # SLO plane (telemetry/slo.py, serve/session.py; ISSUE 20) —
    # *_latency_* names are HISTOGRAMS (observe()), the rest gauges
    "slo_session_latency_s",
    "slo_burn_rate",
    "slo_error_budget_remaining",
    "mpc_step_latency_hist_s",
})

#: default histogram bucket upper bounds (seconds — the latency scale
#: every slo_*/mpc latency histogram shares); +Inf is implicit
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)


class Histogram:
    """One bucketed distribution: cumulative-style bucket counts plus
    sum/count, the Prometheus histogram data model.  Standalone (no
    registry required) so stream-following consumers — `telemetry
    watch`'s per-stream MPC step latencies (ISSUE 20 satellite) — can
    fold unbounded row streams into O(buckets) state instead of
    retaining every raw row."""

    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets=None):
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self.counts = [0] * (len(self.buckets) + 1)   # +1: the +Inf tail
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        i = len(self.buckets)
        for j, b in enumerate(self.buckets):
            if v <= b:
                i = j
                break
        self.counts[i] += 1
        self.sum += v
        self.count += 1

    def quantile(self, q: float) -> float | None:
        """Bucket-resolution quantile estimate (linear interpolation
        inside the landing bucket; the +Inf tail reports its lower
        bound).  None while empty."""
        if self.count == 0:
            return None
        target = max(0.0, min(1.0, float(q))) * self.count
        cum = 0
        lo = 0.0
        for j, b in enumerate(self.buckets):
            nxt = cum + self.counts[j]
            if nxt >= target and self.counts[j] > 0:
                frac = (target - cum) / self.counts[j]
                return lo + frac * (b - lo)
            cum = nxt
            lo = b
        return lo

    def to_dict(self) -> dict:
        return {"buckets": list(self.buckets),
                "counts": list(self.counts),
                "sum": self.sum, "count": self.count}


def _key(name: str, labels: dict | None) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


class MetricsRegistry:
    """Thread-safe counter/gauge map (checkpoint writes record from a
    daemon thread)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}      # guarded-by: _lock
        self._gauges: dict[str, float] = {}        # guarded-by: _lock
        self._histograms: dict[str, Histogram] = {}  # guarded-by: _lock

    # -- recording --------------------------------------------------------
    def inc(self, name: str, value: float = 1.0, **labels):
        k = _key(name, labels)
        with self._lock:
            self._counters[k] = self._counters.get(k, 0.0) + value

    def set_counter(self, name: str, value: float, **labels):
        """Mirror an absolute cumulative value (e.g. an on-device
        counter total) into the registry."""
        with self._lock:
            self._counters[_key(name, labels)] = float(value)

    def set_gauge(self, name: str, value: float, **labels):
        with self._lock:
            self._gauges[_key(name, labels)] = float(value)

    def observe(self, name: str, value: float, buckets=None, **labels):
        """Record one sample into a histogram series (first-class
        histogram type, ISSUE 20 — p50/p99 stop being recomputed from
        retained raw rows)."""
        k = _key(name, labels)
        with self._lock:
            h = self._histograms.get(k)
            if h is None:
                h = self._histograms[k] = Histogram(buckets)
            h.observe(value)

    def get(self, name: str, default: float = 0.0, **labels) -> float:
        k = _key(name, labels)
        with self._lock:
            if k in self._counters:
                return self._counters[k]
            return self._gauges.get(k, default)

    def get_histogram(self, name: str, **labels) -> Histogram | None:
        with self._lock:
            return self._histograms.get(_key(name, labels))

    def quantile(self, name: str, q: float, **labels) -> float | None:
        h = self.get_histogram(name, **labels)
        return None if h is None else h.quantile(q)

    def reset(self):
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()

    # -- rendering (the one shared schema) --------------------------------
    def to_snapshot(self) -> dict:
        """JSON snapshot — the schema bench.py embeds in BENCH_*.json.
        `histograms` is additive (absent pre-ISSUE-20 artifacts parse
        identically)."""
        with self._lock:
            snap = {
                "schema": SNAPSHOT_SCHEMA,
                "t_wall": time.time(),
                "counters": dict(sorted(self._counters.items())),
                "gauges": dict(sorted(self._gauges.items())),
            }
            if self._histograms:
                snap["histograms"] = {
                    k: self._histograms[k].to_dict()
                    for k in sorted(self._histograms)}
            return snap

    def render_prom(self) -> str:
        """Prometheus text exposition (one sample per line)."""
        snap = self.to_snapshot()
        lines = [f"# mpisppy-tpu metrics snapshot "
                 f"(schema {SNAPSHOT_SCHEMA})"]
        for kind, samples in (("counter", snap["counters"]),
                              ("gauge", snap["gauges"])):
            seen_names = set()
            for k, v in samples.items():
                base = k.split("{", 1)[0]
                if base not in seen_names:
                    seen_names.add(base)
                    lines.append(f"# TYPE {base} {kind}")
                lines.append(f"{k} {v!r}")
        seen_names = set()
        for k, h in snap.get("histograms", {}).items():
            base, _, labels = k.partition("{")
            labels = labels[:-1] if labels else ""
            if base not in seen_names:
                seen_names.add(base)
                lines.append(f"# TYPE {base} histogram")

            def series(suffix, extra=""):
                inner = ",".join(x for x in (labels, extra) if x)
                return f"{base}{suffix}" + (f"{{{inner}}}" if inner
                                            else "")
            cum = 0
            for b, c in zip(h["buckets"], h["counts"]):
                cum += c
                le = 'le="%s"' % b
                lines.append(series("_bucket", le) + f" {cum}")
            cum += h["counts"][-1]
            lines.append(series("_bucket", 'le="+Inf"') + f" {cum}")
            lines.append(series("_sum") + " " + repr(h["sum"]))
            lines.append(series("_count") + " %d" % h["count"])
        return "\n".join(lines) + "\n"


#: process-global default registry (prometheus_client convention)
REGISTRY = MetricsRegistry()
