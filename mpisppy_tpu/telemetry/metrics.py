###############################################################################
# Metrics registry + the shared snapshot schema.
#
# A MetricsRegistry is a flat map of named counters (monotone within a
# run) and gauges (point-in-time values), with optional Prometheus-style
# labels.  Two render paths share ONE schema:
#
#   * render_prom()  — Prometheus text exposition, written atomically to
#     the --metrics-snapshot file so a node-exporter-style scraper (or a
#     human with `cat`) can watch a long-running wheel;
#   * to_snapshot()  — the JSON snapshot dict.  bench.py embeds exactly
#     this object in BENCH_*.json entries, so offline benchmark
#     artifacts and live-run snapshots are directly comparable
#     (ISSUE 3 satellite; see docs/telemetry.md).
#
# There is a process-global default registry (REGISTRY) in the style of
# prometheus_client: deep library code (ops/bnb.py, the hub's kernel
# harvest) records into it without threading a handle through every
# call, and sinks snapshot it.  Values mirrored from on-device cumulative
# counters are SET (absolute), not inc'd — the device is the source of
# truth and re-folding would double count.
###############################################################################
from __future__ import annotations

import threading
import time

SNAPSHOT_SCHEMA = "mpisppy-tpu-metrics/1"

#: The declared metric vocabulary (ISSUE 10 schema-drift pass): every
#: literal metric name recorded anywhere in the library must appear
#: here, so a typo'd or ad-hoc name is a lint failure instead of a
#: silently forked time series (`python -m tools.graftlint`).  Names
#: are grouped by producer; labels (cyl=, kind=) are orthogonal to the
#: base name and not part of the schema.
ALL_METRICS = frozenset({
    # telemetry spine (sinks.py, hub checkpoint path)
    "events_total",
    "checkpoint_writes_total",
    # on-device PDHG kernel counters (counters.py harvest)
    "pdhg_iterations_total",
    "pdhg_restarts_total",
    "pdhg_omega_adaptations_total",
    "pdhg_guard_resets_total",
    "pdhg_windows_total",
    "pdhg_last_score_median",
    # host-driven B&B (ops/bnb.py)
    "bnb_nodes_solved_total",
    "bnb_lanes_closed_total",
    # dispatch scheduler (dispatch/scheduler.py; docs/dispatch.md)
    "dispatch_batches_total",
    "dispatch_lanes_total",
    "dispatch_pad_lanes_total",
    "dispatch_batch_occupancy",
    "dispatch_queue_depth",
    "dispatch_buckets_active",
    "dispatch_inflight",
    "dispatch_backend_compiles_total",
    "dispatch_unexpected_recompiles_total",
    "dispatch_retries_total",
    "dispatch_quarantined_lanes_total",
    "dispatch_quarantined_requests_total",
    "dispatch_dispatcher_deaths_total",
    "dispatch_plane_tickets_total",
    "dispatch_plane_deadline_misses_total",
    # async wheel exchange plane (cylinders/hub.AsyncPHHub; ISSUE 11)
    "async_plane_writes_total",
    "async_plane_staleness",
    # seeded scenario synthesis (mpisppy_tpu/scengen; docs/scengen.md)
    "scengen_virtual_batches_total",
    "scengen_scenarios",
    "scengen_data_bytes_saved",
    # supervisors (resilience/watchdog.py)
    "watchdog_trips_total",
    # multi-tenant wheel server (mpisppy_tpu/serve; ISSUE 12)
    "serve_sessions_total",
    "serve_sessions_active",
    "serve_queue_depth",
    "serve_admission_rejects_total",
    "serve_preemptions_total",
    "serve_disconnects_total",
    "serve_failures_total",
    # replicated serve fleet (mpisppy_tpu/fleet; ISSUE 16)
    "fleet_replicas_up",
    "fleet_replica_deaths_total",
    "fleet_sessions_migrated_total",
    "fleet_migrations_lost_total",
    "fleet_placement_affinity_total",
    "fleet_placement_spill_total",
    # rolling-horizon MPC streams (mpisppy_tpu/mpc; ISSUE 19)
    "mpc_streams_total",
    "mpc_steps_total",
    "mpc_warm_steps_total",
    "mpc_cold_fallbacks_total",
    "mpc_degraded_steps_total",
    "mpc_stream_resumes_total",
    "mpc_step_latency_s",
    # elastic mesh fault domain (parallel/elastic.py; ISSUE 17)
    "mesh_hosts_up",
    "mesh_epoch",
    "mesh_hosts_lost_total",
    "mesh_reshards_total",
    "mesh_reshards_lost_total",
    "mesh_stragglers_total",
    "mesh_torn_harvests_total",
})


def _key(name: str, labels: dict | None) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


class MetricsRegistry:
    """Thread-safe counter/gauge map (checkpoint writes record from a
    daemon thread)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}  # guarded-by: _lock
        self._gauges: dict[str, float] = {}    # guarded-by: _lock

    # -- recording --------------------------------------------------------
    def inc(self, name: str, value: float = 1.0, **labels):
        k = _key(name, labels)
        with self._lock:
            self._counters[k] = self._counters.get(k, 0.0) + value

    def set_counter(self, name: str, value: float, **labels):
        """Mirror an absolute cumulative value (e.g. an on-device
        counter total) into the registry."""
        with self._lock:
            self._counters[_key(name, labels)] = float(value)

    def set_gauge(self, name: str, value: float, **labels):
        with self._lock:
            self._gauges[_key(name, labels)] = float(value)

    def get(self, name: str, default: float = 0.0, **labels) -> float:
        k = _key(name, labels)
        with self._lock:
            if k in self._counters:
                return self._counters[k]
            return self._gauges.get(k, default)

    def reset(self):
        with self._lock:
            self._counters.clear()
            self._gauges.clear()

    # -- rendering (the one shared schema) --------------------------------
    def to_snapshot(self) -> dict:
        """JSON snapshot — the schema bench.py embeds in BENCH_*.json."""
        with self._lock:
            return {
                "schema": SNAPSHOT_SCHEMA,
                "t_wall": time.time(),
                "counters": dict(sorted(self._counters.items())),
                "gauges": dict(sorted(self._gauges.items())),
            }

    def render_prom(self) -> str:
        """Prometheus text exposition (one sample per line)."""
        snap = self.to_snapshot()
        lines = [f"# mpisppy-tpu metrics snapshot "
                 f"(schema {SNAPSHOT_SCHEMA})"]
        for kind, samples in (("counter", snap["counters"]),
                              ("gauge", snap["gauges"])):
            seen_names = set()
            for k, v in samples.items():
                base = k.split("{", 1)[0]
                if base not in seen_names:
                    seen_names.add(base)
                    lines.append(f"# TYPE {base} {kind}")
                lines.append(f"{k} {v!r}")
        return "\n".join(lines) + "\n"


#: process-global default registry (prometheus_client convention)
REGISTRY = MetricsRegistry()
