###############################################################################
# Declarative SLOs + error budgets (ISSUE 20 tentpole, piece 3;
# docs/telemetry.md).
#
# One SLOSpec per serve class and per MPC stream product:
#
#   latency     time-to-1%-gap p99 within target_s, and at most
#               `budget` of sessions missing their per-session target;
#   throughput  certified-within-deadline rate >= 1 - budget;
#   mpc         per-step deadline miss (degraded-window) rate <= budget.
#
# Evaluation folds either `slo-observation` rows (the terminal sample
# Session.settle stamps on every request's root span) or a committed
# BENCH artifact's parsed sections into the same row shape:
#
#   bad_frac          the violating fraction of samples
#   burn_rate         bad_frac / budget   (1.0 = the budget is exactly
#                     spent; > 1.0 = the SLO is violated)
#   budget_remaining  max(0, 1 - burn_rate)
#
# burn_rate is THE scalar the machinery binds on: `telemetry slo`
# renders it, watch shows it live, metrics.py exports it as the
# slo_burn_rate gauge, and regress.py gates any committed
# `*.slo.*.burn_rate` key (relative growth AND the absolute <= 1.0
# milestone), so a burn-rate regression on a committed serve/fleet/MPC
# artifact exits 2.
#
# Pure stdlib: regress-adjacent tooling loads these modules on machines
# without jax.
###############################################################################
from __future__ import annotations

import dataclasses

SLO_SCHEMA = "mpisppy-tpu-slo/1"


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One service-level objective."""

    name: str        # row key ("latency", "throughput", "mpc")
    sla: str         # the SLA class / product the spec applies to
    objective: str   # the human sentence
    target_s: float  # per-sample latency target (p99 line)
    budget: float    # allowed violating fraction (error budget)


#: the shipped objectives (docs/telemetry.md SLO table)
DEFAULT_SLOS: tuple[SLOSpec, ...] = (
    SLOSpec("latency", "latency",
            "time-to-1%-gap p99 <= 15s; <= 5% of latency-class "
            "sessions miss gap or deadline",
            target_s=15.0, budget=0.05),
    SLOSpec("throughput", "throughput",
            "<= 5% of throughput-class sessions fail to certify "
            "within their deadline",
            target_s=60.0, budget=0.05),
    SLOSpec("mpc", "mpc",
            "step-deadline miss (degraded-window) rate <= 10% per "
            "stream; step p99 <= 5s",
            target_s=5.0, budget=0.10),
)


def _row(spec: SLOSpec, samples: int, bad: int,
         detail: dict | None = None) -> dict:
    """One evaluated SLO row.  With zero samples the row reports
    burn 0 and samples 0 — absence of traffic is not a violation."""
    bad_frac = (bad / samples) if samples else 0.0
    burn = bad_frac / spec.budget if spec.budget else 0.0
    out = {
        "sla": spec.sla,
        "objective": spec.objective,
        "target_s": spec.target_s,
        "budget": spec.budget,
        "samples": samples,
        "bad": bad,
        "bad_frac": round(bad_frac, 6),
        "burn_rate": round(burn, 4),
        "budget_remaining": round(max(0.0, 1.0 - burn), 4),
        "ok": burn <= 1.0,
    }
    if detail:
        out.update(detail)
    return out


# -- evaluation from slo-observation rows ------------------------------------
def observations(rows: list[dict]) -> list[dict]:
    """The slo-observation payloads in a row stream (trace files, an
    assembled trace, or a raw JSONL list)."""
    out = []
    for r in rows:
        if r.get("kind") == "slo-observation":
            d = r.get("data") or {}
            if "outcome" in d:
                out.append(d)
    return out


def _p99(xs: list[float]) -> float | None:
    """Nearest-rank p99 (stdlib; no numpy on the tooling path)."""
    if not xs:
        return None
    xs = sorted(xs)
    i = min(len(xs) - 1, max(0, int(0.99 * len(xs) + 0.5) - 1))
    return xs[i]


def evaluate_observations(rows: list[dict],
                          specs=DEFAULT_SLOS) -> dict:
    """Fold slo-observation rows (one per settled session) into the
    per-SLO burn-rate report."""
    obs = observations(rows)
    slos: dict = {}
    for spec in specs:
        if spec.name == "mpc":
            mine = [o for o in obs
                    if (o.get("steps_expected") or 0) > 0]
            # a stream's violating unit is the WINDOW: count expected
            # windows as samples, missed windows (a stream that died
            # at step k misses the rest) as bad
            samples = sum(int(o.get("steps_expected") or 0)
                          for o in mine)
            bad = sum(max(0, int(o.get("steps_expected") or 0)
                          - int(o.get("steps") or 0))
                      for o in mine)
            bad += sum(1 for o in mine if o.get("outcome") != "done"
                       and int(o.get("steps") or 0)
                       >= int(o.get("steps_expected") or 0))
            lat = [o["total_s"] for o in mine
                   if o.get("total_s") is not None]
            slos[spec.name] = _row(
                spec, samples, bad,
                {"streams": len(mine), "p99_s": _p99(lat)})
            continue
        mine = [o for o in obs
                if o.get("sla") == spec.sla
                and not (o.get("steps_expected") or 0)]
        lat = [o["total_s"] for o in mine
               if o.get("total_s") is not None]
        bad = 0
        for o in mine:
            failed = o.get("outcome") != "done"
            over = (o.get("total_s") is not None
                    and o["total_s"] > spec.target_s)
            if failed or over:
                bad += 1
        slos[spec.name] = _row(spec, len(mine), bad,
                               {"p99_s": _p99(lat)})
    return {"schema": SLO_SCHEMA, "source": "observations",
            "slo": slos}


def evaluate_path(path: str, specs=DEFAULT_SLOS) -> dict:
    """Evaluate a trace file or directory (spans.load_rows)."""
    from mpisppy_tpu.telemetry import spans
    return evaluate_observations(spans.load_rows(path), specs)


# -- evaluation from a committed BENCH artifact ------------------------------
def _frac_bad(section: dict, reached_key: str = "reached_gap") -> float:
    """1 - reached/sessions from a loadgen summary section."""
    n = section.get("sessions") or 0
    if not n:
        return 0.0
    return max(0.0, 1.0 - (section.get(reached_key) or 0) / n)


def evaluate_bench(parsed: dict, specs=DEFAULT_SLOS) -> dict:
    """The same burn-rate rows from a BENCH artifact's parsed sections
    (serve_load / fleet_serve_load / mpc_stream).  Aggregates stand in
    for per-session samples: a p99 over target charges at least the
    1% the percentile proves; the reached-gap shortfall charges the
    rest."""
    by_name = {s.name: s for s in specs}
    slos: dict = {}
    serve = parsed.get("serve_load") or {}
    fleet = parsed.get("fleet_serve_load") or {}
    mpc = parsed.get("mpc_stream") or {}
    if serve or fleet:
        spec = by_name["latency"]
        n = int((serve.get("sessions") or 0)
                + (fleet.get("sessions") or 0))
        bad_frac = 0.0
        p99s = []
        for sec in (serve, fleet):
            if not sec:
                continue
            w = (sec.get("sessions") or 0) / max(1, n)
            bad = _frac_bad(sec)
            p99 = sec.get("time_to_gap_p99_s")
            if p99 is not None:
                p99s.append(p99)
                if p99 > spec.target_s:
                    bad = max(bad, 0.01)
            bad_frac += w * bad
        slos["latency"] = _row(
            spec, n, round(bad_frac * n),
            {"p99_s": max(p99s) if p99s else None})
        spec = by_name["throughput"]
        done = sum((sec.get("outcomes") or {}).get("done", 0)
                   for sec in (serve, fleet) if sec)
        slos["throughput"] = _row(spec, n, max(0, n - done))
    if mpc:
        spec = by_name["mpc"]
        steps = bad = 0
        p99s = []
        for key, sec in mpc.items():
            if not isinstance(sec, dict) or "degraded_steps" not in sec:
                continue
            steps += int(sec.get("steps") or 0)
            bad += int(sec.get("degraded_steps") or 0)
            p99 = sec.get("step_latency_p99_s")
            if p99 is not None:
                p99s.append(p99)
                if p99 > spec.target_s:
                    bad = max(bad, 1)
        slos["mpc"] = _row(spec, steps, bad,
                           {"p99_s": max(p99s) if p99s else None})
    return {"schema": SLO_SCHEMA, "source": "bench", "slo": slos}


def bench_slo_section(parsed: dict, specs=DEFAULT_SLOS) -> dict:
    """The `slo` section a BENCH artifact commits: just the rows (the
    schema/source envelope stays on the CLI report)."""
    return evaluate_bench(parsed, specs)["slo"]


# -- metrics export ----------------------------------------------------------
def export_metrics(report: dict) -> None:
    """Publish the evaluated burn rates as slo_* gauges (labels key the
    SLO name).  Import is local so the module stays loadable standalone
    on tooling machines."""
    try:
        from mpisppy_tpu.telemetry import metrics as _metrics
    except ImportError:
        return
    for name, row in (report.get("slo") or {}).items():
        _metrics.REGISTRY.set_gauge("slo_burn_rate",
                                    row["burn_rate"], slo=name)
        _metrics.REGISTRY.set_gauge("slo_error_budget_remaining",
                                    row["budget_remaining"], slo=name)


# -- rendering ---------------------------------------------------------------
def render_slo(report: dict) -> str:
    lines = [f"SLO report ({report.get('source', '?')})"]
    lines.append(f"{'slo':<12} {'samples':>7} {'bad':>5} "
                 f"{'burn':>7} {'budget left':>11}  verdict")
    for name, row in (report.get("slo") or {}).items():
        verdict = "ok" if row["ok"] else "VIOLATED"
        lines.append(
            f"{name:<12} {row['samples']:>7} {row['bad']:>5} "
            f"{row['burn_rate']:>7.2f} "
            f"{row['budget_remaining']:>11.2f}  {verdict}")
        lines.append(f"    {row['objective']}")
    if not report.get("slo"):
        lines.append("  (no samples)")
    return "\n".join(lines)
