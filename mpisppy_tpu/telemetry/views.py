###############################################################################
# Back-compat views: the pre-telemetry in-memory trace surfaces
# (`Hub.trace` list of per-iteration dict rows, `Spoke.trace` list of
# (hub_iter, bound) tuples) are now SUBSCRIBERS of the event bus — one
# spine, with the legacy lists as a derived view (ISSUE 3 satellite).
# bench.py and the cylinder tests keep reading the lists unchanged.
###############################################################################
from __future__ import annotations

from mpisppy_tpu.telemetry import events as ev
from mpisppy_tpu.telemetry.sinks import Sink


class WheelTraceView(Sink):
    """Maintains one hub's legacy trace lists from its event stream.

    Run-scoped: events carry the emitting hub's run id, so several
    wheels sharing one bus (or one configured global bus) can never
    cross-pollinate each other's lists."""

    def __init__(self, hub):
        self._hub = hub

    def handle(self, event: ev.Event) -> None:
        hub = self._hub
        if event.run != hub.run_id:
            return
        if event.kind == ev.HUB_ITERATION:
            row = dict(event.data)
            row["t"] = event.t_mono
            hub.trace.append(row)
        elif event.kind == ev.BOUND_ACCEPT:
            j = event.data.get("spoke")
            if j is not None and 0 <= j < len(hub.spokes):
                hub.spokes[j].trace.append(
                    (event.hub_iter, event.data.get("bound")))
