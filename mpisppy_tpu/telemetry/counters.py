###############################################################################
# On-device kernel counters (ISSUE 3 tentpole, part 2; docs/telemetry.md).
#
# The PDHG wheel kernel is a lax.while_loop of restart windows — the
# natural observation point MPAX (PAPERS.md) identifies: restart
# boundaries are where the solver already touches every lane's
# bookkeeping, so accumulating a handful of int32 counters and one
# small score ring there costs a few elementwise ops per ~40-iteration
# window (<0.1% of the window's matvec work) and NO extra host
# round-trips.  The whole KernelCounters pytree rides inside PDHGState
# and is harvested in one small device-to-host transfer
# (telemetry.counters.harvest_state) whenever the host wants totals —
# the hub does it once per sync, leaving the per-lane ring in HBM.
#
# Overhead contract (asserted by tests/test_telemetry.py): with
# PDHGOptions.telemetry=False the counters field is None, zero extra
# leaves enter the jit graph, and the lowered HLO of the PH wheel step
# is byte-identical to a build that never imported this module.
###############################################################################
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

Array = jax.Array

#: score-sample ring slots per lane (one sample per restart window)
RING_SIZE = 8


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["iters", "restarts", "omega_adapt", "ring", "ring_pos"],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class KernelCounters:
    """Per-lane cumulative counters + a residual-curve sample ring.

    All counters survive warm restarts across PH iterations (solve()'s
    bookkeeping reset leaves them alone), so totals are per-run."""

    iters: Array        # (...,) int32 PDHG iterations run while active
    restarts: Array     # (...,) int32 adaptive restarts fired
    omega_adapt: Array  # (...,) int32 primal-weight adaptations applied
    ring: Array         # (..., RING) last KKT scores at window boundaries
    ring_pos: Array     # () int32 total windows observed (write cursor)


def init_counters(batch_shape: tuple, dtype,
                  ring_size: int = RING_SIZE) -> KernelCounters:
    return KernelCounters(
        iters=jnp.zeros(batch_shape, jnp.int32),
        restarts=jnp.zeros(batch_shape, jnp.int32),
        omega_adapt=jnp.zeros(batch_shape, jnp.int32),
        ring=jnp.full(batch_shape + (ring_size,), jnp.nan, dtype),
        ring_pos=jnp.zeros((), jnp.int32),
    )


def record_window(kc: KernelCounters, *, active: Array, restarted: Array,
                  omega_moved: Array, score: Array,
                  period: int) -> KernelCounters:
    """Fold one restart window's observations into the counters
    (traced; called from ops.pdhg._window only when telemetry is on)."""
    slot = kc.ring_pos % kc.ring.shape[-1]
    ring = jax.lax.dynamic_update_slice_in_dim(
        kc.ring, score[..., None].astype(kc.ring.dtype), slot, axis=-1)
    return KernelCounters(
        iters=kc.iters + jnp.where(active, period, 0).astype(jnp.int32),
        restarts=kc.restarts + (restarted & active).astype(jnp.int32),
        omega_adapt=kc.omega_adapt + (omega_moved & active).astype(
            jnp.int32),
        ring=ring,
        ring_pos=kc.ring_pos + 1,
    )


# -- host-side harvest -------------------------------------------------------
def begin_harvest(solver_state, include_ring: bool = True):
    """Non-blocking half of a counter harvest: slice what must be
    sliced on device and ENQUEUE the device-to-host copies without
    waiting for them (jax.Array.copy_to_host_async).  Returns an opaque
    handle for complete_harvest, or None when the state carries no
    counters (telemetry off).

    This is the async hub's stale-side pipeline seam (ISSUE 11
    satellite): the hub begins a harvest right after dispatching the
    next step and completes the PREVIOUS one, so the blocking
    device_get in complete_harvest lands on copies that already
    arrived instead of gating the in-flight iteration."""
    kc = getattr(solver_state, "counters", None)
    if kc is None:
        return None
    ring_size = kc.ring.shape[-1]
    parts = [kc.iters, kc.restarts, kc.omega_adapt,
             solver_state.guard_resets, kc.ring_pos]
    if include_ring:
        parts.append(kc.ring)
    else:
        # slice the newest slot ON DEVICE with the device-resident
        # cursor; before any window has written, the slot holds the
        # NaN ring fill and drops out of the median in complete_harvest
        slot = (kc.ring_pos - 1) % ring_size
        parts.append(jnp.take(kc.ring, slot, axis=-1))
    for p in parts:
        start = getattr(p, "copy_to_host_async", None)
        if start is not None:
            start()
    return parts, include_ring, ring_size


def complete_harvest(handle) -> dict | None:
    """Blocking half: turn a begin_harvest handle into the totals dict.
    Cheap when the enqueued copies already landed."""
    if handle is None:
        return None
    import numpy as np
    parts, include_ring, ring_size = handle
    vals = jax.device_get(parts)  # the one blocking transfer
    iters, restarts, omega, guard = vals[:4]
    pos = int(vals[4])
    ring = None
    if include_ring:
        ring = np.asarray(vals[5])
        last = ring[..., (pos - 1) % ring_size] if pos > 0 \
            else np.full(ring.shape[:-1], np.nan)
    else:
        last = np.asarray(vals[5])
    finite = np.asarray(last)[np.isfinite(np.asarray(last))]
    out = {
        "pdhg_iterations_total": int(np.sum(iters)),
        "pdhg_restarts_total": int(np.sum(restarts)),
        "pdhg_omega_adaptations_total": int(np.sum(omega)),
        "pdhg_guard_resets_total": int(np.sum(guard)),
        "pdhg_windows_total": pos,
        "pdhg_last_score_median": float(np.median(finite))
        if finite.size else float("nan"),
    }
    if include_ring:
        out["residual_ring"] = ring
    return out


def harvest_state(solver_state, include_ring: bool = True) -> dict | None:
    """Synchronous harvest of a PDHGState's counters (plus the
    lane-guard totals that already live in the state) — begin_harvest
    immediately completed.  Returns None when the state carries no
    counters (telemetry off).

    include_ring=False is the per-sync hot path: only the LAST ring
    slot is sliced on device and transferred (the hub needs one score
    sample for the median gauge) — the full lanes x ring curve stays
    in HBM until something actually asks for it."""
    return complete_harvest(begin_harvest(solver_state, include_ring))


def fold_into_registry(registry, harvested: dict, cyl: str = "hub"):
    """Mirror harvested ABSOLUTE totals into a MetricsRegistry (set, not
    inc — the device counters are cumulative and the source of truth)."""
    for name in ("pdhg_iterations_total", "pdhg_restarts_total",
                 "pdhg_omega_adaptations_total",
                 "pdhg_guard_resets_total", "pdhg_windows_total"):
        registry.set_counter(name, harvested[name], cyl=cyl)
    med = harvested["pdhg_last_score_median"]
    if med == med:  # not NaN
        registry.set_gauge("pdhg_last_score_median", med, cyl=cyl)
