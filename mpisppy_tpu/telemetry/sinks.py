###############################################################################
# Pluggable event sinks (docs/telemetry.md).
#
#   JsonlSink            — one JSON object per line; the machine trace.
#   ConsoleSink          — renders CONSOLE events for humans (verbosity-
#                          filtered); the replacement for library
#                          print(...) output (telemetry/console.py
#                          routes through it when one is attached).
#   MetricsSnapshotSink  — periodically (and on close) rewrites a
#                          Prometheus text-exposition file ATOMICALLY
#                          from a MetricsRegistry, for long-running runs
#                          where tailing a JSONL stream is the wrong
#                          tool.  Also folds per-event counts
#                          (events_total{kind=...}) into the registry.
#
# A sink must never raise into the wheel: EventBus.emit guards every
# handle() call and detaches a sink after repeated failures.
###############################################################################
from __future__ import annotations

import sys
import time

from mpisppy_tpu.telemetry import events as ev
from mpisppy_tpu.telemetry import metrics as metrics_mod
from mpisppy_tpu.utils.atomic_io import atomic_write_text


class Sink:
    """Subscriber interface: handle(event) per event, close() once."""

    def handle(self, event: ev.Event) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class JsonlSink(Sink):
    """Append events to a JSONL trace file (wall + monotonic timestamps,
    run/cylinder ids — see Event.to_dict for the line schema).  The file
    is opened lazily in APPEND mode — a preempted run restarted with
    --checkpoint-restore and the same --trace-jsonl path continues the
    stream instead of truncating the pre-preemption history (run ids
    delimit the segments) — and flushed per line, so a crashed run's
    trace is complete up to the crash."""

    def __init__(self, path: str):
        self.path = path
        self._fh = None

    def handle(self, event: ev.Event) -> None:
        if self._fh is None:
            self._fh = open(self.path, "a")
        self._fh.write(event.to_json() + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


# console verbosity levels (CONSOLE event `level` field)
QUIET, INFO, DEBUG = 0, 1, 2


class ConsoleSink(Sink):
    """Human console: prints CONSOLE events whose level clears the
    verbosity bar, in the classic `[elapsed] msg` global_toc format."""

    def __init__(self, verbosity: int = INFO, stream=None, t0=None):
        self.verbosity = int(verbosity)
        self.stream = stream
        if t0 is None:
            # anchor at process start like global_toc, not at sink
            # construction — the [elapsed] column must not reset when
            # telemetry attaches mid-process
            try:
                import mpisppy_tpu
                t0 = mpisppy_tpu._T0
            except Exception:
                t0 = time.time()
        self._t0 = t0

    def handle(self, event: ev.Event) -> None:
        if event.kind != ev.CONSOLE:
            return
        level = INFO if event.level is None else event.level
        if level > self.verbosity:
            return
        stream = self.stream or sys.stdout
        msg = event.data.get("msg", "")
        print(f"[{event.t_wall - self._t0:9.2f}] {msg}", file=stream,
              flush=True)


class MetricsSnapshotSink(Sink):
    """Atomic Prometheus-style text snapshot of a MetricsRegistry.

    Rewrites `path` at most every `every_s` seconds (piggybacked on the
    event stream — no extra thread) and always on close(), via the
    shared atomic-write helper so a scraper never reads a torn file.
    Each event also bumps events_total{kind} so the snapshot reflects
    stream activity even before any kernel counters land."""

    def __init__(self, path: str, registry=None, every_s: float = 30.0):
        self.path = path
        self.registry = registry if registry is not None \
            else metrics_mod.REGISTRY
        self.every_s = float(every_s)
        self._last_write = 0.0

    def handle(self, event: ev.Event) -> None:
        self.registry.inc("events_total", kind=event.kind)
        now = time.perf_counter()
        if now - self._last_write >= self.every_s:
            self._last_write = now
            self.write_snapshot()

    def write_snapshot(self) -> None:
        atomic_write_text(self.path, self.registry.render_prom())

    def close(self) -> None:
        self.write_snapshot()
