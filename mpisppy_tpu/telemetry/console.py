###############################################################################
# The library console: every human-readable line mpisppy_tpu produces
# goes through log() — bare print(...) in library code is a lint error
# (tools/lint_no_print.py, enforced by a tier-1 test).
#
# Behavior:
#   * With no telemetry configured (the default), log() prints directly
#     in the classic `[elapsed] msg` global_toc format — byte-for-byte
#     the pre-telemetry output, so nothing changes for existing users.
#   * When a bus with a ConsoleSink is attached (telemetry.from_cfg),
#     the sink renders instead (same format, verbosity-filtered) and
#     every line ALSO lands in the JSONL trace as a CONSOLE event —
#     the stdout story and the machine trace can never diverge.
#
# Verbosity levels: QUIET(0) errors/final results only, INFO(1) the
# default progress stream (including verbose-gated milestone lines),
# DEBUG(2) chatty per-round/per-step diagnostics — the old
# `verbose=True` round loops in ops/bnb.py and algos/mip.py log at
# DEBUG, so they need BOTH their verbose flag and verbosity >= 2.
###############################################################################
from __future__ import annotations

import sys
import time

from mpisppy_tpu.telemetry import events as ev
from mpisppy_tpu.telemetry.sinks import ConsoleSink, DEBUG, INFO, QUIET

__all__ = ["log", "attach", "detach", "set_verbosity",
           "QUIET", "INFO", "DEBUG"]

_verbosity = INFO
_attached: list = []  # EventBus instances receiving CONSOLE events


def set_verbosity(level: int) -> None:
    global _verbosity
    _verbosity = int(level)


def attach(bus) -> None:
    if bus not in _attached:
        _attached.append(bus)


def detach(bus) -> None:
    if bus in _attached:
        _attached.remove(bus)


def _t0() -> float:
    import mpisppy_tpu
    return mpisppy_tpu._T0


def log(msg: str, level: int = INFO, cyl: str = "",
        cond: bool = True) -> None:
    """Emit one console line (and a CONSOLE event to attached buses)."""
    if not cond:
        return
    rendered = False
    for bus in list(_attached):
        out = bus.emit(ev.CONSOLE, cyl=cyl, level=level, msg=msg)
        if out is not None and any(isinstance(s, ConsoleSink)
                                   for s in bus.sinks):
            rendered = True
    if not rendered and level <= _verbosity:
        # the sink of last resort: identical to the historical
        # global_toc print format
        print(f"[{time.time() - _t0():9.2f}] {msg}", file=sys.stdout,
              flush=True)
