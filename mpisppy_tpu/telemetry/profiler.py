###############################################################################
# Profiler hooks (ISSUE 3 tentpole, part 3; docs/telemetry.md).
#
# Two layers:
#   * annotate(name) / step(name, n) — thin wrappers over
#     jax.profiler.TraceAnnotation / StepTraceAnnotation that NEVER
#     raise (and degrade to no-ops without jax).  The wheel brackets
#     its phases — hub sync, spoke update, harvest, checkpoint,
#     subproblem solve — so any externally-started device trace (e.g.
#     bench.py's jax.profiler.trace) shows named spans instead of an
#     undifferentiated dispatch soup.  An annotation outside an active
#     trace is a few ns of host work; nothing enters the jit graph.
#   * ProfilerSession — the --profile-dir CLI flag: brackets N wheel
#     iterations with jax.profiler.start_trace/stop_trace, skipping the
#     compile-heavy first iterations so the trace shows steady state.
###############################################################################
from __future__ import annotations

import contextlib


def annotate(name: str):
    """Named host-span context manager (shows as a range in the device
    trace's host timeline)."""
    try:
        import jax.profiler
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()


def step(name: str, step_num: int):
    """StepTraceAnnotation: marks one wheel iteration as a training-
    style 'step' so trace viewers compute per-step statistics."""
    try:
        import jax.profiler
        return jax.profiler.StepTraceAnnotation(name, step_num=step_num)
    except Exception:
        return contextlib.nullcontext()


class ProfilerSession:
    """Bracket wheel iterations [start_iter, start_iter + num_iters)
    with a jax.profiler trace written to `profile_dir`.

    Driven by the hub: on_sync(hub_iter) every sync, close() at
    finalize (stops a still-open trace when the wheel terminates before
    the window completes).  start_iter defaults past Iter0 + the first
    compiled iterk so steady-state iterations dominate the trace."""

    def __init__(self, profile_dir: str, num_iters: int = 5,
                 start_iter: int = 3, bus=None, run: str = ""):
        self.profile_dir = profile_dir
        self.num_iters = max(1, int(num_iters))
        self.start_iter = int(start_iter)
        self.active = False
        self.failed = False
        self._bus = bus
        self._run = run

    def _emit(self, action: str, hub_iter: int | None):
        if self._bus is not None:
            from mpisppy_tpu.telemetry import events as ev
            self._bus.emit(ev.PROFILE, run=self._run, cyl="hub",
                           hub_iter=hub_iter, action=action,
                           profile_dir=self.profile_dir)

    def on_sync(self, hub_iter: int) -> None:
        if self.failed:
            return
        try:
            import jax.profiler
            if not self.active and hub_iter >= self.start_iter:
                jax.profiler.start_trace(self.profile_dir)
                self.active = True
                self._emit("start", hub_iter)
            elif self.active \
                    and hub_iter >= self.start_iter + self.num_iters:
                jax.profiler.stop_trace()
                self.active = False
                self._emit("stop", hub_iter)
        except Exception:
            # a broken profiler backend must never kill the run
            self.failed = True
            self.active = False

    def close(self) -> None:
        if self.active:
            try:
                import jax.profiler
                jax.profiler.stop_trace()
                self._emit("stop", None)
            except Exception:
                pass
            self.active = False
