###############################################################################
# Profiler hooks (ISSUE 3 tentpole, part 3; ISSUE 7 hardening;
# docs/telemetry.md).
#
# Two layers:
#   * annotate(name) / step(name, n) — thin wrappers over
#     jax.profiler.TraceAnnotation / StepTraceAnnotation that NEVER
#     raise (and degrade to no-ops without jax).  The wheel brackets
#     its phases — hub sync, spoke update, harvest, checkpoint,
#     subproblem solve — so any externally-started device trace (e.g.
#     bench.py's jax.profiler.trace) shows named spans instead of an
#     undifferentiated dispatch soup.  An annotation outside an active
#     trace is a few ns of host work; nothing enters the jit graph.
#   * ProfilerSession — the --profile-dir CLI flag: brackets N wheel
#     iterations with jax.profiler.start_trace/stop_trace, skipping the
#     compile-heavy first iterations so the trace shows steady state.
#
# Hardening contract (ISSUE 7): a missing or unwritable profile_dir —
# a read-only pod filesystem, a typo'd path — degrades to a console
# warning, never an unhandled exception; and the `profile` event that
# advertises a capture (action "captured", carrying the capture dir
# for `telemetry analyze` auto-discovery) is emitted ONLY after the
# trace files are verified on disk, so a trace row never points at a
# capture that silently failed to materialize.
###############################################################################
from __future__ import annotations

import contextlib
import os


def annotate(name: str):
    """Named host-span context manager (shows as a range in the device
    trace's host timeline)."""
    try:
        import jax.profiler
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()


def step(name: str, step_num: int):
    """StepTraceAnnotation: marks one wheel iteration as a training-
    style 'step' so trace viewers (and telemetry/deviceprof.py) compute
    per-step device statistics keyed by hub_iter."""
    try:
        import jax.profiler
        return jax.profiler.StepTraceAnnotation(name, step_num=step_num)
    except Exception:
        return contextlib.nullcontext()


class ProfilerSession:
    """Bracket wheel iterations [start_iter, start_iter + num_iters)
    with a jax.profiler trace written to `profile_dir`.

    Driven by the hub: on_sync(hub_iter) every sync, close() at
    finalize (stops a still-open trace when the wheel terminates before
    the window completes).  start_iter defaults past Iter0 + the first
    compiled iterk so steady-state iterations dominate the trace."""

    def __init__(self, profile_dir: str, num_iters: int = 5,
                 start_iter: int = 3, bus=None, run: str = ""):
        self.profile_dir = profile_dir
        self.num_iters = max(1, int(num_iters))
        self.start_iter = int(start_iter)
        self.active = False
        self.failed = False
        self.done = False      # window completed: never re-arm
        self._bus = bus
        self._run = run
        self._known_captures: set = set()

    def _emit(self, action: str, hub_iter: int | None, **extra):
        if self._bus is not None:
            from mpisppy_tpu.telemetry import events as ev
            self._bus.emit(ev.PROFILE, run=self._run, cyl="hub",
                           hub_iter=hub_iter, action=action,
                           profile_dir=self.profile_dir, **extra)

    def _warn(self, msg: str) -> None:
        from mpisppy_tpu.telemetry import console
        console.log(f"WARNING: profiler: {msg}", cyl="hub")

    def _capture_dirs(self) -> set:
        try:
            from mpisppy_tpu.telemetry import deviceprof
            return {c["dir"]
                    for c in deviceprof.discover_captures(
                        self.profile_dir)}
        except (OSError, ValueError):
            return set()

    def _fail(self, msg: str) -> None:
        # a broken profiler backend / filesystem must never kill the
        # run: warn once, then stand down for the rest of the wheel
        self._warn(f"{msg} — device profiling disabled for this run")
        self.failed = True
        self.active = False

    def on_sync(self, hub_iter: int) -> None:
        if self.failed or self.done:
            return
        if not self.active and hub_iter >= self.start_iter:
            try:
                os.makedirs(self.profile_dir, exist_ok=True)
            except OSError as e:
                return self._fail(
                    f"cannot create --profile-dir "
                    f"{self.profile_dir!r} ({e})")
            if not os.access(self.profile_dir, os.W_OK):
                return self._fail(f"--profile-dir {self.profile_dir!r} "
                                  "is not writable")
            self._known_captures = self._capture_dirs()
            try:
                import jax.profiler
                jax.profiler.start_trace(self.profile_dir)
            except Exception as e:
                return self._fail(f"start_trace failed ({e})")
            self.active = True
            self._emit("start", hub_iter)
        elif self.active \
                and hub_iter >= self.start_iter + self.num_iters:
            self._stop(hub_iter)

    def _stop(self, hub_iter: int | None) -> None:
        self.done = True       # one window per session: never re-arm
        try:
            import jax.profiler
            jax.profiler.stop_trace()
        except Exception as e:
            self.active = False
            return self._fail(f"stop_trace failed ({e})")
        self.active = False
        # the `profile` "captured" event is a claim that analyzable
        # trace files EXIST — verify before advertising (ISSUE 7)
        new = self._capture_dirs() - self._known_captures
        if new:
            self._emit("captured", hub_iter,
                       trace_dir=sorted(new)[-1])
        else:
            self._warn(f"trace stopped but no capture landed under "
                       f"{self.profile_dir!r} (backend wrote nothing)")

    def close(self) -> None:
        if self.active and not self.failed:
            self._stop(None)
