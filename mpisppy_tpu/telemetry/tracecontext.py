###############################################################################
# Causal trace context — the W3C-traceparent-shaped identity every event
# carries from client submit to device kernel (ISSUE 20;
# docs/telemetry.md "Causal tracing").
#
# A TraceContext is the (trace_id, span_id, parent_span_id) triple:
#
#   * trace_id   — 32 hex chars, minted ONCE at client submit (loadgen,
#     an external client's `traceparent` field) or, for traffic that
#     arrives without one, by the first Session that sees the request.
#     Every event of every hop of that request — router placement,
#     replica run segments, hub sync, dispatch megabatch attribution,
#     mesh reshard rebuilds, MPC windows — carries the SAME trace_id.
#   * span_id    — 16 hex chars naming the current causal span.  Spans
#     are implicit intervals: an event *belongs to* the span whose id it
#     carries, and the span's extent is the [min, max] wall-clock of its
#     events (torn-tail safe — no close record is required, so a crashed
#     segment still renders).  `span-start` events add names/attributes.
#   * parent_span_id — the causal edge.  A migration hand-off detaches
#     the source segment span; the restore on the destination parents a
#     NEW segment under the same root, so the gap between the two
#     segments IS the migration gap on the critical path.
#
# The wire form is the W3C traceparent header shape
# (`00-<trace>-<span>-01`), carried as a first-class SubmitRequest
# field; the event-row form is three top-level JSONL keys
# (`trace_id`/`span_id`/`parent_span_id`, omitted when absent so
# pre-trace rows are valid rows of the same schema).  Stdlib only.
###############################################################################
from __future__ import annotations

import dataclasses
import uuid

_VERSION = "00"


def _hex(n: int) -> str:
    h = uuid.uuid4().hex
    while len(h) < n:
        h += uuid.uuid4().hex
    return h[:n]


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One causal position: the trace, the current span, and its
    parent edge.  Immutable — every hop derives a child instead of
    mutating, so two threads sharing a context can never race it."""

    trace_id: str
    span_id: str
    parent_span_id: str = ""

    @staticmethod
    def mint() -> "TraceContext":
        """A fresh root: new trace, new root span, no parent."""
        return TraceContext(trace_id=_hex(32), span_id=_hex(16))

    def child(self) -> "TraceContext":
        """A new span under this one (same trace)."""
        return TraceContext(trace_id=self.trace_id, span_id=_hex(16),
                            parent_span_id=self.span_id)

    # -- wire form (SubmitRequest.traceparent) ----------------------------
    def to_traceparent(self) -> str:
        return f"{_VERSION}-{self.trace_id}-{self.span_id}-01"

    @staticmethod
    def from_traceparent(s) -> "TraceContext | None":
        """Parse the wire form; None on anything malformed — a client
        sending garbage gets a freshly minted trace, never an error."""
        if not isinstance(s, str):
            return None
        parts = s.strip().split("-")
        if len(parts) != 4:
            return None
        _ver, trace_id, span_id, _flags = parts
        if len(trace_id) != 32 or len(span_id) != 16:
            return None
        try:
            int(trace_id, 16), int(span_id, 16)
        except ValueError:
            return None
        if set(trace_id) == {"0"} or set(span_id) == {"0"}:
            return None    # all-zero ids are invalid per W3C
        return TraceContext(trace_id=trace_id, span_id=span_id)
