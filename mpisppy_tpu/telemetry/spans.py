###############################################################################
# Causal span assembly (ISSUE 20 tentpole, piece 2; docs/telemetry.md).
#
# `telemetry trace <trace_id>` turns the per-replica / per-session /
# fleet JSONL segments back into ONE causal tree per trace.  The model
# is deliberately record-free: a span is the set of rows carrying its
# span_id, its extent the [min, max] wall clock of those rows, its name
# and attributes the `span-start` row that opened it.  No close record
# exists, so a torn tail (a replica killed mid-write) shortens a span's
# extent but can never corrupt the tree — and every row self-describes
# its span's parent (the bus stamps trace_id/span_id/parent_span_id
# together), so parentage survives files being read in any order.
#
# Zero-orphan is the structural invariant the chaos tests pin: on a
# clean run — including a live migration and a mesh reshard — every
# parent_span_id referenced by any span resolves to a span that has
# rows of its own.  An orphan means a propagation hop dropped the
# context (the bug class this plane exists to catch).
#
# CRITICAL PATH: client-observed latency is attributed by partitioning
# the [first-row, last-row] wall timeline at every event and charging
# each inter-event gap to the bucket of the event that CLOSES it
# (queue-wait / admission / iter0 / hub-sync / exchange-overlap /
# dispatch-queue / solve / migration-gap / step-shift).  Because the
# buckets partition the timeline, they sum to the client-observed
# latency by construction (the acceptance criterion's 5% headroom
# covers only wall-vs-monotonic clock skew).
#
# Pure stdlib on purpose: this module is imported by regress.py, which
# tools (graftlint, CI gates) load standalone by path on machines
# without jax.
###############################################################################
from __future__ import annotations

import json
import os

#: the machine-report schema tag (graftlint schema-drift pins the key
#: set below against docs/telemetry.md)
TRACE_SCHEMA = "mpisppy-tpu-trace/1"

#: the critical-path buckets, in render order (docs/telemetry.md)
BUCKETS = ("queue-wait", "admission", "iter0", "hub-sync",
           "exchange-overlap", "dispatch-queue", "solve",
           "migration-gap", "step-shift")


# -- row loading (torn-tail safe) -------------------------------------------
def iter_rows(path: str):
    """Yield parsed JSONL rows; a torn/garbage line (the killed-replica
    tail) is skipped, never raised."""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if isinstance(row, dict):
                yield row


def load_rows(path: str) -> list[dict]:
    """All rows from a JSONL file, or from every *.jsonl under a
    directory tree (a fleet trace dir holds one subdir per replica plus
    the router stream) — each row annotated with its source file."""
    files: list[str] = []
    if os.path.isdir(path):
        for dirpath, _dirs, names in os.walk(path):
            for name in sorted(names):
                if name.endswith(".jsonl"):
                    files.append(os.path.join(dirpath, name))
    else:
        files.append(path)
    rows: list[dict] = []
    for fp in sorted(files):
        rel = os.path.relpath(fp, path) if os.path.isdir(path) else fp
        for row in iter_rows(fp):
            row["_file"] = rel
            rows.append(row)
    return rows


def trace_ids(rows: list[dict]) -> list[str]:
    """Distinct trace ids in first-appearance order."""
    seen: dict[str, None] = {}
    for r in rows:
        tid = r.get("trace_id")
        if tid and tid not in seen:
            seen[tid] = None
    return list(seen)


def resolve_trace_id(rows: list[dict], prefix: str | None) -> str:
    """Match a full id or unique prefix; None/'' picks the only trace
    present (or raises listing the candidates)."""
    ids = trace_ids(rows)
    if not ids:
        raise ValueError("no trace-stamped rows found")
    if not prefix or prefix == "last":
        if prefix != "last" and len(ids) > 1:
            raise ValueError(
                "multiple traces present; pass one of: "
                + ", ".join(i[:12] for i in ids))
        return ids[-1] if prefix == "last" else ids[0]
    hits = [i for i in ids if i.startswith(prefix)]
    if len(hits) != 1:
        raise ValueError(
            f"trace id {prefix!r} matches {len(hits)} of: "
            + ", ".join(i[:12] for i in ids))
    return hits[0]


# -- span-tree assembly ------------------------------------------------------
def assemble(rows: list[dict], trace_id: str) -> dict:
    """One causal span tree for `trace_id` (the machine report,
    schema TRACE_SCHEMA).  Spans carry extent, row/kind accounting,
    the files their rows landed in, and the span-start attributes;
    `orphans` lists spans whose parent has no rows of its own."""
    mine = [r for r in rows if r.get("trace_id") == trace_id]
    if not mine:
        raise ValueError(f"no rows for trace {trace_id!r}")
    mine.sort(key=lambda r: (r.get("t_wall") or 0.0, r.get("seq") or 0))
    spans: dict[str, dict] = {}
    for r in mine:
        sid = r.get("span_id") or ""
        if not sid:
            continue
        sp = spans.get(sid)
        if sp is None:
            sp = spans[sid] = {
                "span_id": sid, "parent_span_id": "", "name": "",
                "t_start": r["t_wall"], "t_end": r["t_wall"],
                "events": 0, "kinds": {}, "files": [], "attrs": {},
            }
        sp["t_start"] = min(sp["t_start"], r["t_wall"])
        sp["t_end"] = max(sp["t_end"], r["t_wall"])
        sp["events"] += 1
        kind = r.get("kind", "?")
        sp["kinds"][kind] = sp["kinds"].get(kind, 0) + 1
        f = r.get("_file")
        if f and f not in sp["files"]:
            sp["files"].append(f)
        parent = r.get("parent_span_id") or ""
        if parent and not sp["parent_span_id"]:
            sp["parent_span_id"] = parent
        if kind == "span-start" and not sp["name"]:
            data = r.get("data") or {}
            sp["name"] = str(data.get("name") or "")
            sp["attrs"] = {k: v for k, v in data.items()
                           if k != "name" and v is not None}
    # rows stamped with a span we never saw a span-start for still name
    # it by its dominant kind — e.g. the request root is named by its
    # own span-start, but a bare hub trace roots at an anonymous span
    for sp in spans.values():
        if not sp["name"]:
            top = max(sp["kinds"].items(), key=lambda kv: kv[1])[0]
            sp["name"] = f"({top})"
    orphans = sorted(
        sp["span_id"] for sp in spans.values()
        if sp["parent_span_id"] and sp["parent_span_id"] not in spans)
    roots = sorted(
        (sp for sp in spans.values()
         if not sp["parent_span_id"]
         or sp["parent_span_id"] not in spans),
        key=lambda sp: sp["t_start"])
    children: dict[str, list] = {}
    for sp in spans.values():
        if sp["parent_span_id"] in spans:
            children.setdefault(sp["parent_span_id"], []).append(sp)
    for kids in children.values():
        kids.sort(key=lambda sp: sp["t_start"])
    # a request that never moved has ONE segment span; every extra
    # segment is a resume after a preemption/migration hand-off
    n_segments = sum(1 for sp in spans.values()
                     if sp["name"] in ("segment", "mesh-segment"))
    migrated = n_segments - 1
    cp = critical_path(mine)
    span_rows = []

    def _emit(sp, depth):
        span_rows.append(dict(sp, depth=depth,
                              duration_s=round(
                                  sp["t_end"] - sp["t_start"], 6)))
        for kid in children.get(sp["span_id"], []):
            _emit(kid, depth + 1)

    for root in roots:
        _emit(root, 0)
    return {
        "schema": TRACE_SCHEMA,
        "trace_id": trace_id,
        "spans": span_rows,
        "orphans": orphans,
        "critical_path": cp,
        "migrated_segments": max(0, migrated),
        "files": sorted({f for sp in spans.values()
                         for f in sp["files"]}),
        "events": len(mine),
    }


# -- critical path -----------------------------------------------------------
def _bucket_of(row: dict, st: dict) -> str:
    """The bucket charged for the gap THIS row closes.  `st` is the
    walker's state (admitted / segment-open / first-sync-seen /
    draining), mutated here as the row is consumed."""
    kind = row.get("kind")
    data = row.get("data") or {}
    if kind == "session-state":
        state = data.get("state")
        if state == "ADMITTED":
            st["admitted"] = True
            return "queue-wait"
        if state == "RUNNING":
            st["admitted"] = True
            return ("migration-gap" if data.get("prev") == "DEGRADED"
                    else "admission")
        if state == "DEGRADED":
            st["draining"] = True
            return "solve" if st.get("in_seg") else "migration-gap"
        return "solve" if st.get("in_seg") else "admission"
    if kind == "span-start":
        name = data.get("name")
        if name in ("segment", "mesh-segment"):
            b = ("migration-gap" if st.get("draining")
                 else "admission" if st.get("admitted")
                 else "queue-wait")
            st.update(in_seg=True, seg_synced=False, draining=False)
            return b
        if name in ("migration", "reshard"):
            st.update(in_seg=False, draining=True)
            return "migration-gap"
        if name == "mpc-step":
            # the shift/checkpoint wall between window k's last event
            # and window k+1's open
            return "step-shift" if st.get("seg_synced") else "iter0"
        if name in ("request", "mesh-run"):
            return "queue-wait"
        return "solve" if st.get("in_seg") else "admission"
    if kind == "hub-iteration":
        if not st.get("seg_synced"):
            st["seg_synced"] = True
            return "iter0"
        return "hub-sync"
    if kind == "exchange-overlap":
        return "exchange-overlap"
    if kind in ("dispatch", "dispatch-retry"):
        return "dispatch-queue"
    if kind in ("session-migrated", "mesh-reshard", "mesh-host-lost",
                "checkpoint-restore"):
        st.update(in_seg=False, draining=True)
        return "migration-gap"
    if kind == "mpc-step":
        st["seg_synced"] = True
        return "solve"
    if kind == "run-start":
        return "iter0"
    # anything else: compute time inside a segment, queue time before
    # admission, drain time while migrating
    if st.get("in_seg"):
        return "solve"
    if st.get("draining"):
        return "migration-gap"
    return "solve" if st.get("admitted") else "queue-wait"


def critical_path(rows: list[dict]) -> dict:
    """Partition the trace's wall timeline into the BUCKETS; the sums
    equal last-row minus first-row wall clock exactly.  When the trace
    carries an slo-observation row, its client-observed total_s is
    reported alongside with the coverage ratio (the 5% acceptance
    line)."""
    rows = sorted(rows,
                  key=lambda r: (r.get("t_wall") or 0.0,
                                 r.get("seq") or 0))
    buckets = {b: 0.0 for b in BUCKETS}
    st: dict = {}
    prev_t = rows[0]["t_wall"] if rows else 0.0
    client_total = None
    for row in rows:
        t = row.get("t_wall")
        if t is None:
            continue
        dt = max(0.0, t - prev_t)
        buckets[_bucket_of(row, st)] += dt
        prev_t = t
        if row.get("kind") == "slo-observation":
            tot = (row.get("data") or {}).get("total_s")
            if tot is not None:
                client_total = float(tot)
    total = sum(buckets.values())
    out = {
        "buckets": {b: round(v, 6) for b, v in buckets.items()},
        "total_s": round(total, 6),
        "client_total_s": client_total,
    }
    if client_total:
        out["coverage"] = round(total / client_total, 4)
    return out


# -- entry points ------------------------------------------------------------
def assemble_path(path: str, trace: str | None = None) -> dict:
    """Load a JSONL file or trace directory and assemble one trace
    (`trace` is a full id, unique prefix, 'last', or None when only
    one trace is present)."""
    rows = load_rows(path)
    return assemble(rows, resolve_trace_id(rows, trace))


def render_trace(rep: dict) -> str:
    """The human rendering of an assemble() report."""
    lines = [f"trace {rep['trace_id']}  "
             f"({rep['events']} events, "
             f"{len(rep['files'])} file(s), "
             f"{rep['migrated_segments']} migrated segment(s))"]
    t0 = min((sp["t_start"] for sp in rep["spans"]), default=0.0)
    for sp in rep["spans"]:
        pad = "  " * sp["depth"]
        attrs = ""
        keep = {k: v for k, v in sp["attrs"].items()
                if k in ("session", "tenant", "sla", "replica", "step",
                         "epoch", "devices", "from_replica",
                         "resume_iter", "restore")}
        if keep:
            attrs = "  " + " ".join(f"{k}={v}"
                                    for k, v in sorted(keep.items()))
        lines.append(
            f"{pad}{sp['name']:<14s} "
            f"+{sp['t_start'] - t0:8.3f}s "
            f"{sp['duration_s']:8.3f}s "
            f"{sp['events']:4d} ev{attrs}")
    if rep["orphans"]:
        lines.append(f"ORPHAN SPANS: {len(rep['orphans'])} "
                     f"({', '.join(o[:8] for o in rep['orphans'])})")
    cp = rep["critical_path"]
    lines.append("critical path:")
    total = cp["total_s"] or 1.0
    for b in BUCKETS:
        v = cp["buckets"].get(b, 0.0)
        if v <= 0.0:
            continue
        lines.append(f"  {b:<18s} {v:8.3f}s  {100.0 * v / total:5.1f}%")
    tail = f"  {'total':<18s} {cp['total_s']:8.3f}s"
    if cp.get("client_total_s") is not None:
        tail += (f"  (client observed {cp['client_total_s']:.3f}s, "
                 f"coverage {cp.get('coverage', 0.0):.2%})")
    lines.append(tail)
    return "\n".join(lines)
